"""Plan cardinality estimates + expansion-join capacity hints.

Reference role: ``core/trino-main/.../cost/`` (StatsCalculator,
FilterStatsCalculator, JoinStatsRule) in miniature. Estimates flow from
connector row counts (``Connector.table_row_count``) through simple
selectivity heuristics. They are NOT trusted for correctness — an expansion
join or hash exchange whose true size exceeds its estimated static capacity
raises a deferred ``CAPACITY_EXCEEDED:<hint-key>`` flag, and the compiled
paths double that bucket and recompile (the bucketed-recompile loop of
SURVEY.md §7.3; the spill-FSM analog of HashBuilderOperator.java:162-177).

Also home to the broadcast-vs-repartition distribution choice (reference:
DetermineJoinDistributionType + AddExchanges.java:138): both the build-time
hint estimation and SpmdExecutor's trace-time dispatch consult the same
predicates, so hints always exist for the exchanges the trace creates.
"""
from __future__ import annotations

from typing import Dict

from trino_tpu.sql.planner import plan as P

# Heuristic fudge factors, biased high — capacity hints should over- rather
# than under-estimate to avoid recompiles. Filters don't discount (the
# reference's FilterStatsCalculator discounts by 0.9 per unknown conjunct;
# a capacity hint must survive the filter being non-selective).
JOIN_FANOUT = 1.25  # M:N fudge over the FK-join output (= probe rows)
MIN_CAPACITY = 1024


def estimate_rows(session, node: P.PlanNode) -> int:
    """Rough output-row estimate per plan node (upper-bound biased)."""
    if isinstance(node, P.TableScanNode):
        if node.runtime_rows is not None:
            return max(int(node.runtime_rows), 1)
        conn = session.catalogs.get(node.catalog)
        n = conn.table_row_count(node.schema, node.table) if conn else None
        return int(n) if n else MIN_CAPACITY
    if isinstance(node, P.ValuesNode):
        return max(1, len(node.rows or ()))
    if isinstance(node, (P.LimitNode, P.TopNNode)):
        return min(node.count, estimate_rows(session, node.source))
    if isinstance(node, P.JoinNode):
        left = estimate_rows(session, node.left)
        right = estimate_rows(session, node.right)
        if node.join_type in ("semi", "anti"):
            return left
        if node.singleton:
            return left
        if node.right_unique:
            return left  # N:1 lookup join: output == probe rows
        if not node.left_keys:  # cross join
            return left * right
        return int(max(left, right) * JOIN_FANOUT)
    if isinstance(node, P.AggregationNode):
        src = estimate_rows(session, node.source)
        if not node.group_channels:
            return src  # global agg: the sort-based kernel's capacity is
            # the input row count anyway
        # group count <= min(input rows, product of group-key NDVs): the
        # NDV cap keeps compiled group-by capacity hints (and every hint
        # derived above an aggregation) from over-allocating to the full
        # input row count (reference: AggregationStatsRule)
        ndv = key_ndv(session, node.source, node.group_channels)
        return max(1, min(src, ndv)) if ndv else src
    if isinstance(node, P.UnionNode):
        # UNION ALL output = SUM of branches (the generic max fallback
        # would under-allocate capacity hints by the branch count)
        return sum(estimate_rows(session, s) for s in node.sources_)
    srcs = node.sources
    if not srcs:
        # exchange sources (RemoteSourceNode) stamped with actual upstream
        # stage output rows by the adaptive re-planner start from truth —
        # the TableScanNode.runtime_rows analog on fragment boundaries
        rr = getattr(node, "runtime_rows", None)
        if rr is not None:
            return max(int(rr), 1)
        return MIN_CAPACITY
    return max(estimate_rows(session, s) for s in srcs)


def _expansion_capacity(session, node: P.JoinNode) -> int:
    left = estimate_rows(session, node.left)
    right = estimate_rows(session, node.right)
    if not node.left_keys:  # true cross join: exact
        est = left * right
    elif node.join_type in ("semi", "anti"):
        # filtered-semi expansion materializes all key matches
        est = int(max(left, right) * JOIN_FANOUT)
    else:
        est = int(max(left, right) * JOIN_FANOUT)
        if node.join_type == "left":
            est = max(est, left)  # outer emits >= one slot per probe row
    return _pow2(max(est, MIN_CAPACITY))


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length()


def estimate_capacity_hints(session, root: P.PlanNode) -> Dict[str, int]:
    """Static output capacities for every expansion-join node in the plan,
    from stats alone (no eager pre-run)."""
    hints: Dict[str, int] = {}
    for n in P.walk_plan(root):
        if isinstance(n, P.JoinNode) and P.uses_expansion_kernel(n):
            hints[f"join:{n.id}"] = _expansion_capacity(session, n)
        elif isinstance(n, P.CompactNode):
            hints[f"cmp:{n.id}"] = compact_capacity(session, n)
    return hints


# ---------------------------------------------------------------- exchanges

# Build sides larger than this repartition instead of broadcasting
# (join_max_broadcast_table_size analog, in rows).
BROADCAST_BUILD_MAX = 1 << 17
# Aggregations whose per-device input exceeds this repartition raw rows by
# group-key hash instead of gathering partial states.
GATHER_AGG_MAX_ROWS_PER_DEVICE = 1 << 16
MIN_EXCHANGE_CAPACITY = 256


def _keys_low_cardinality(node: P.AggregationNode) -> bool:
    """Group keys whose domain is small enough for the gather exchange no
    matter the row count (dictionary codes / booleans — the direct-layout
    grouping fast path)."""
    src_types = node.source.output_types
    for c in node.group_channels:
        t = src_types[c]
        if not (t.is_varchar or t.name == "boolean"):
            return False
    return True


def agg_repartitions(session, node: P.AggregationNode, n_devices: int) -> bool:
    """True when a distributed single-step aggregation should hash-repartition
    raw rows by group key (FIXED_HASH_DISTRIBUTION) instead of gathering
    partial states (the low-cardinality path)."""
    if not node.group_channels:
        return False  # global aggregate: partial states are one row
    if not P.can_split_aggs(node.aggregates):
        return False  # distinct/percentile fallback gathers raw rows (for now)
    if _keys_low_cardinality(node):
        return False
    rows = estimate_rows(session, node.source)
    return rows // max(n_devices, 1) > GATHER_AGG_MAX_ROWS_PER_DEVICE


def resolved_broadcast_limit(properties) -> int:
    """The effective join_max_broadcast_rows threshold: the session
    property when explicitly set, else the module constant (sessions
    materialize every default, so an untouched property defers to
    BROADCAST_BUILD_MAX — which tests tune directly). The ONE resolution
    both the static rule and the adaptive re-planner consult."""
    from trino_tpu.client.properties import SYSTEM_SESSION_PROPERTIES

    declared = SYSTEM_SESSION_PROPERTIES["join_max_broadcast_rows"].default
    limit = int((properties or {}).get("join_max_broadcast_rows", declared))
    return BROADCAST_BUILD_MAX if limit == declared else limit


def join_repartitions(session, node: P.JoinNode, n_devices: int) -> bool:
    """True when a distributed join should co-partition both sides by key
    hash instead of broadcasting the build side (session property
    join_max_broadcast_rows; reference: join_max_broadcast_table_size)."""
    if not node.left_keys:
        return False  # cross join: broadcast is the only option
    limit = resolved_broadcast_limit(getattr(session, "properties", None))
    build = estimate_rows(session, node.right)
    return build > limit


def _gather_max_rows(session) -> int:
    """Per-device row threshold above which windows/set-ops/sorts
    repartition instead of gathering everything to every device
    (session property gather_max_rows_per_device)."""
    from trino_tpu.client.properties import SYSTEM_SESSION_PROPERTIES

    default = SYSTEM_SESSION_PROPERTIES["gather_max_rows_per_device"].default
    props = getattr(session, "properties", None) or {}
    return int(props.get("gather_max_rows_per_device", default))


def window_repartitions(session, node: P.WindowNode, n_devices: int) -> bool:
    """True when a distributed window should hash-repartition rows by its
    PARTITION BY keys (whole partitions co-locate) instead of gathering."""
    if not node.partition_channels:
        return False  # global window frame: every row is one partition
    rows = estimate_rows(session, node.source)
    return rows // max(n_devices, 1) > _gather_max_rows(session)


def setop_repartitions(session, node: P.SetOpNode, n_devices: int) -> bool:
    """True when INTERSECT/EXCEPT should co-partition both sides by whole-
    row hash (equal rows co-locate) instead of gathering."""
    rows = estimate_rows(session, node.left) + estimate_rows(session, node.right)
    return rows // max(n_devices, 1) > _gather_max_rows(session)


def sort_repartitions(session, source: P.PlanNode, n_devices: int) -> bool:
    """True when a full ORDER BY (no limit) should range-partition by
    sampled splitters and sort shards locally — the sharded distributed
    sort (reference role: range exchange + ordered-merge consumer) —
    instead of gathering the whole input to every device."""
    rows = estimate_rows(session, source)
    return rows // max(n_devices, 1) > _gather_max_rows(session)


def exchange_capacity(session, source: P.PlanNode, n_devices: int) -> int:
    """Static per-(source device, destination device) block size for a hash
    exchange of ``source``'s rows: ~2x the uniform share, doubled on
    overflow by the recompile loop (skewed keys land here)."""
    rows = estimate_rows(session, source)
    per_block = (2 * rows) // max(n_devices * n_devices, 1)
    return _pow2(max(per_block, MIN_EXCHANGE_CAPACITY))


def estimate_exchange_hints(session, root: P.PlanNode, n_devices: int) -> Dict[str, int]:
    """Capacity hints for every hash exchange the SPMD trace will create —
    consults the same predicates as SpmdExecutor's dispatch."""
    hints: Dict[str, int] = {}
    for n in P.walk_plan(root):
        if isinstance(n, P.AggregationNode) and n.step == "single":
            if agg_repartitions(session, n, n_devices):
                hints[f"xchg:{n.id}"] = exchange_capacity(session, n.source, n_devices)
        elif isinstance(n, P.JoinNode):
            if join_repartitions(session, n, n_devices):
                hints[f"xchgl:{n.id}"] = exchange_capacity(session, n.left, n_devices)
                hints[f"xchgr:{n.id}"] = exchange_capacity(session, n.right, n_devices)
        elif isinstance(n, P.WindowNode):
            if window_repartitions(session, n, n_devices):
                hints[f"xchgw:{n.id}"] = exchange_capacity(session, n.source, n_devices)
        elif isinstance(n, P.SetOpNode):
            if setop_repartitions(session, n, n_devices):
                cap_l = exchange_capacity(session, n.left, n_devices)
                cap_r = exchange_capacity(session, n.right, n_devices)
                hints[f"xchgs:{n.id}"] = _pow2(cap_l + cap_r)
        elif isinstance(n, P.SortNode):
            if sort_repartitions(session, n.source, n_devices):
                hints[f"xchgo:{n.id}"] = exchange_capacity(session, n.source, n_devices)
    return hints


CAPACITY_ERROR_PREFIX = "CAPACITY_EXCEEDED:"


def grow_overflowed_hints(hints: Dict[str, int], codes, flags) -> Dict[str, int]:
    """Scan deferred-error (code, flag) pairs; double the bucket of every
    expansion join / exchange whose capacity flag fired (flags may be
    per-device stacks). Returns a new dict, or None when nothing overflowed
    — the shared half of the bucketed-recompile loop (CompiledQuery.run /
    DistributedQuery.run)."""
    import numpy as np

    out = None
    for code, flag in zip(codes, flags):
        if code.startswith(CAPACITY_ERROR_PREFIX) and bool(np.asarray(flag).any()):
            key = code[len(CAPACITY_ERROR_PREFIX):]
            out = dict(hints) if out is None else out
            out[key] = out.get(key, MIN_CAPACITY) * 2
    return out


# ------------------------------------------------- selectivity / live rows

# Reference: FilterStatsCalculator.UNKNOWN_FILTER_COEFFICIENT — predicates
# we can't estimate keep 90% of rows (biased high: capacities must survive
# a non-selective filter without a recompile).
UNKNOWN_FILTER_COEFFICIENT = 0.9


def resolve_column_stats(session, node: P.PlanNode, channel: int):
    """ColumnStats of the base-table column a channel traces to (through
    pass-through projections, filters, joins, and group keys), or None."""
    from trino_tpu.sql import ir

    if isinstance(node, P.TableScanNode):
        conn = session.catalogs.get(node.catalog)
        if conn is None:
            return None
        return conn.column_stats(node.schema, node.table, node.column_names[channel])
    if isinstance(node, P.ProjectNode):
        e = node.expressions[channel]
        if isinstance(e, ir.ColumnRef):
            return resolve_column_stats(session, node.source, e.index)
        return None
    if isinstance(node, (P.FilterNode, P.CompactNode, P.LimitNode, P.SortNode,
                         P.TopNNode, P.WindowNode)):
        if isinstance(node, P.WindowNode) and channel >= len(node.source.output_types):
            return None
        return resolve_column_stats(session, node.source, channel)
    if isinstance(node, P.JoinNode):
        nl = len(node.left.output_types)
        if node.join_type in ("semi", "anti") or channel < nl:
            if channel < nl:
                return resolve_column_stats(session, node.left, channel)
            return None
        return resolve_column_stats(session, node.right, channel - nl)
    if isinstance(node, P.AggregationNode):
        if channel < len(node.group_channels):
            return resolve_column_stats(
                session, node.source, node.group_channels[channel])
        return None
    return None


def _scale_of_type(t) -> int:
    return t.scale if getattr(t, "scale", None) is not None and t.is_decimal else 0


def _cmp_selectivity(session, fn: str, col_expr, const_expr, source) -> float:
    """Range-interpolated selectivity of ``col <op> const`` from column
    min/max stats (reference: FilterStatsCalculator range arithmetic)."""
    cs = resolve_column_stats(session, source, col_expr.index)
    if cs is None or const_expr.value is None:
        return UNKNOWN_FILTER_COEFFICIENT
    if cs.low is None or cs.high is None:
        # no range (e.g. varchar vocab) — NDV still prices equality
        if cs.ndv and fn == "eq":
            return 1.0 / cs.ndv
        if cs.ndv and fn == "ne":
            return 1.0 - 1.0 / cs.ndv
        return UNKNOWN_FILTER_COEFFICIENT
    lo, hi = cs.low, cs.high
    try:
        c = int(const_expr.value)
    except (TypeError, ValueError):
        return UNKNOWN_FILTER_COEFFICIENT
    # align literal scale to the column's storage scale
    ds = _scale_of_type(col_expr.type) - _scale_of_type(const_expr.type)
    if ds > 0:
        c *= 10 ** ds
    elif ds < 0:
        c //= 10 ** (-ds)
    span = hi - lo + 1
    if fn == "eq":
        return 1.0 / max(cs.ndv or span, 1) if lo <= c <= hi else 0.0
    if fn == "ne":
        return 1.0 - (1.0 / max(cs.ndv or span, 1)) if lo <= c <= hi else 1.0
    if fn in ("lt", "le"):
        kept = c - lo + (1 if fn == "le" else 0)
    elif fn in ("gt", "ge"):
        kept = hi - c + (1 if fn == "ge" else 0)
    else:
        return UNKNOWN_FILTER_COEFFICIENT
    return min(max(kept / span, 0.0), 1.0)


def predicate_selectivity(session, pred, source) -> float:
    """Estimated fraction of rows a predicate keeps."""
    from trino_tpu.sql import ir

    if isinstance(pred, ir.Call):
        if pred.name == "and":
            return predicate_selectivity(session, pred.args[0], source) * \
                predicate_selectivity(session, pred.args[1], source)
        if pred.name == "or":
            a = predicate_selectivity(session, pred.args[0], source)
            b = predicate_selectivity(session, pred.args[1], source)
            return min(1.0, a + b - a * b)
        if pred.name in ("eq", "ne", "lt", "le", "gt", "ge"):
            a, b = pred.args
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}
            if isinstance(a, ir.ColumnRef) and isinstance(b, ir.Constant):
                return _cmp_selectivity(session, pred.name, a, b, source)
            if isinstance(b, ir.ColumnRef) and isinstance(a, ir.Constant):
                return _cmp_selectivity(
                    session, flip.get(pred.name, pred.name), b, a, source)
        if pred.name == "between":
            v, lo_e, hi_e = pred.args
            if isinstance(v, ir.ColumnRef) and isinstance(lo_e, ir.Constant) \
                    and isinstance(hi_e, ir.Constant):
                return max(
                    0.0,
                    _cmp_selectivity(session, "ge", v, lo_e, source)
                    + _cmp_selectivity(session, "le", v, hi_e, source) - 1.0,
                )
        if pred.name == "in_list":
            v = pred.args[0]
            if isinstance(v, ir.ColumnRef):
                cs = resolve_column_stats(session, source, v.index)
                if cs is not None and cs.ndv:
                    return min(1.0, (len(pred.args) - 1) / cs.ndv)
    return UNKNOWN_FILTER_COEFFICIENT


def key_ndv(session, node: P.PlanNode, channels) -> int:
    """Product of per-key NDVs (capped), or 0 when unknown."""
    total = 1
    for c in channels:
        cs = resolve_column_stats(session, node, c)
        if cs is None or not cs.ndv:
            return 0
        total *= cs.ndv
        if total > 1 << 62:
            break
    return total


def estimate_live_rows(session, node: P.PlanNode) -> int:
    """Estimated LIVE output rows (as opposed to estimate_rows, which is
    capacity-biased): drives compaction placement and capacities.
    Reference role: StatsCalculator's outputRowCount."""
    if isinstance(node, P.TableScanNode):
        # NO constraint discount here: scan constraints are advisory and the
        # enforcing FilterNode is always kept (optimizer.derive_scan_
        # constraints), so the filter's predicate_selectivity already counts
        # them — discounting both would square the selectivity.
        if node.runtime_rows is not None:
            return max(int(node.runtime_rows), 1)  # phase-1 staged truth
        conn = session.catalogs.get(node.catalog)
        n = conn.table_row_count(node.schema, node.table) if conn else None
        return int(n) if n else MIN_CAPACITY
    if isinstance(node, P.FilterNode):
        src = estimate_live_rows(session, node.source)
        return max(1, int(src * predicate_selectivity(
            session, node.predicate, node.source)))
    if isinstance(node, (P.ProjectNode, P.CompactNode, P.WindowNode, P.SortNode)):
        return estimate_live_rows(session, node.source)
    if isinstance(node, (P.LimitNode, P.TopNNode)):
        return min(node.count, estimate_live_rows(session, node.source))
    if isinstance(node, P.ValuesNode):
        return max(1, len(node.rows or ()))
    if isinstance(node, P.UnionNode):
        return sum(estimate_live_rows(session, s) for s in node.sources_)
    if isinstance(node, P.JoinNode):
        left = estimate_live_rows(session, node.left)
        right = estimate_live_rows(session, node.right)
        if node.singleton:
            return left
        if not node.left_keys:
            return left * right
        ndv = key_ndv(session, node.left, node.left_keys)
        match = min(1.0, right / ndv) if ndv else 1.0
        if node.df_exact:
            # probe scans were narrowed by this join's exact in-set domain:
            # every surviving probe row matches (two-phase dynamic filtering)
            match = 1.0
        if node.join_type == "semi":
            return max(1, int(left * match))
        if node.join_type == "anti":
            return max(1, int(left * (1.0 - match)) if ndv else left)
        if node.right_unique:
            out = int(left * match)
        else:
            ndv_r = key_ndv(session, node.right, node.right_keys)
            fanout = max(right / ndv_r, 1.0) if ndv_r else JOIN_FANOUT
            out = int(left * match * fanout)
        if node.join_type == "left":
            out = max(out, left)
        return max(1, out)
    if isinstance(node, P.AggregationNode):
        src = estimate_live_rows(session, node.source)
        if not node.group_channels:
            return 1
        ndv = key_ndv(session, node.source, node.group_channels)
        return max(1, min(src, ndv) if ndv else src)
    if isinstance(node, P.SetOpNode):
        return estimate_live_rows(session, node.left)
    srcs = node.sources
    if not srcs:
        rr = getattr(node, "runtime_rows", None)  # stamped exchange source
        if rr is not None:
            return max(int(rr), 1)
        return MIN_CAPACITY
    return max(estimate_live_rows(session, s) for s in srcs)


def compact_capacity(session, node: P.CompactNode) -> int:
    """Static capacity for a CompactNode: estimated live rows + 30% slack,
    next power of two (the recompile loop doubles on overflow)."""
    est = node.estimated_rows or estimate_live_rows(session, node.source)
    return _pow2(max(int(est * 1.3), MIN_CAPACITY))
