"""Plan cardinality estimates + expansion-join capacity hints.

Reference role: ``core/trino-main/.../cost/`` (StatsCalculator,
FilterStatsCalculator, JoinStatsRule) in miniature. Estimates flow from
connector row counts (``Connector.table_row_count``) through simple
selectivity heuristics. They are NOT trusted for correctness — an expansion
join whose true output exceeds its estimated static capacity raises the
deferred ``JOIN_OUTPUT_CAPACITY_EXCEEDED:<node-id>`` flag, and the compiled
paths double that node's bucket and recompile (the bucketed-recompile loop of
SURVEY.md §7.3; the spill-FSM analog of HashBuilderOperator.java:162-177).
"""
from __future__ import annotations

from typing import Dict

from trino_tpu.sql.planner import plan as P

# Heuristic fudge factors, biased high — capacity hints should over- rather
# than under-estimate to avoid recompiles. Filters don't discount (the
# reference's FilterStatsCalculator discounts by 0.9 per unknown conjunct;
# a capacity hint must survive the filter being non-selective).
JOIN_FANOUT = 1.25  # M:N fudge over the FK-join output (= probe rows)
MIN_CAPACITY = 1024


def estimate_rows(session, node: P.PlanNode) -> int:
    """Rough output-row estimate per plan node (upper-bound biased)."""
    if isinstance(node, P.TableScanNode):
        conn = session.catalogs.get(node.catalog)
        n = conn.table_row_count(node.schema, node.table) if conn else None
        return int(n) if n else MIN_CAPACITY
    if isinstance(node, P.ValuesNode):
        return max(1, len(node.rows or ()))
    if isinstance(node, (P.LimitNode, P.TopNNode)):
        return min(node.count, estimate_rows(session, node.source))
    if isinstance(node, P.JoinNode):
        left = estimate_rows(session, node.left)
        right = estimate_rows(session, node.right)
        if node.join_type in ("semi", "anti"):
            return left
        if node.singleton:
            return left
        if node.right_unique:
            return left  # N:1 lookup join: output == probe rows
        if not node.left_keys:  # cross join
            return left * right
        return int(max(left, right) * JOIN_FANOUT)
    if isinstance(node, P.AggregationNode):
        # group count <= input rows; the sort-based kernel's capacity is the
        # input row count anyway
        return estimate_rows(session, node.source)
    srcs = node.sources
    if not srcs:
        return MIN_CAPACITY
    return max(estimate_rows(session, s) for s in srcs)


def _expansion_capacity(session, node: P.JoinNode) -> int:
    left = estimate_rows(session, node.left)
    right = estimate_rows(session, node.right)
    if not node.left_keys:  # true cross join: exact
        est = left * right
    elif node.join_type in ("semi", "anti"):
        # filtered-semi expansion materializes all key matches
        est = int(max(left, right) * JOIN_FANOUT)
    else:
        est = int(max(left, right) * JOIN_FANOUT)
        if node.join_type == "left":
            est = max(est, left)  # outer emits >= one slot per probe row
    return _pow2(max(est, MIN_CAPACITY))


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length()


def estimate_capacity_hints(session, root: P.PlanNode) -> Dict[int, int]:
    """Static output capacities for every expansion-join node in the plan,
    from stats alone (no eager pre-run)."""
    hints: Dict[int, int] = {}
    for n in P.walk_plan(root):
        if isinstance(n, P.JoinNode) and P.uses_expansion_kernel(n):
            hints[n.id] = _expansion_capacity(session, n)
    return hints


CAPACITY_ERROR_PREFIX = "JOIN_OUTPUT_CAPACITY_EXCEEDED:"


def grow_overflowed_hints(hints: Dict[int, int], codes, flags) -> Dict[int, int]:
    """Scan deferred-error (code, flag) pairs; double the bucket of every
    expansion join whose capacity flag fired (flags may be per-device
    stacks). Returns a new dict, or None when nothing overflowed — the
    shared half of the bucketed-recompile loop (CompiledQuery.run /
    DistributedQuery.run)."""
    import numpy as np

    out = None
    for code, flag in zip(codes, flags):
        if code.startswith(CAPACITY_ERROR_PREFIX) and bool(np.asarray(flag).any()):
            nid = int(code[len(CAPACITY_ERROR_PREFIX):])
            out = dict(hints) if out is None else out
            out[nid] = out.get(nid, MIN_CAPACITY) * 2
    return out
