"""Logical plan optimizer.

Reference: ``core/trino-main/.../sql/planner/PlanOptimizers.java`` sequences
227 iterative rules + big-bang passes. Round-1 passes (the load-bearing
subset):

- ``push_predicates``: PredicatePushDown analog — moves filter conjuncts to
  their lowest legal position, turning cross joins (from implicit-join SQL)
  into equi-keyed hash joins along the way (EqualityInference role).
- ``prune_channels``: PruneUnreferencedOutputs/projection-pushdown analog —
  trims every node to the channels actually consumed; at scans this becomes
  connector projection pushdown (the TPC-H generator then only generates the
  projected columns).
- ``order_joins``: greedy size-based join ordering (ReorderJoins stand-in)
  + distribution choice (AddExchanges' broadcast-vs-partitioned decision)
  happens in the fragmenter for now.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from trino_tpu import types as T
from trino_tpu.sql import ir
from trino_tpu.sql.planner import plan as P
from trino_tpu.sql.planner.planner import combine_conjuncts, ir_conjuncts


def optimize(root: P.OutputNode, session=None) -> P.OutputNode:
    # plan-IR sanity checking between passes (reference: PlanSanityChecker
    # interposed on every PlanOptimizer): a pass that breaks a channel
    # invariant is named by the failing phase instead of corrupting rows
    from trino_tpu.sql.planner.sanity import checker

    check = checker(session)
    check(root, "initial-plan")
    node = push_predicates(root.source, [])
    check(node, "optimizer:push_predicates")
    node = orient_joins(node, session)
    check(node, "optimizer:orient_joins")
    node, _ = prune_channels(node, set(range(len(node.output_types))))
    check(node, "optimizer:prune_channels")
    node = merge_identity_projects(node)
    check(node, "optimizer:merge_identity_projects")
    # local rewrites run as memo-resident rules to fixpoint (reference:
    # IterativeOptimizer + rule/ — the scaling path for new rewrites;
    # the passes above stay whole-tree, as PredicatePushDown does there)
    from trino_tpu.sql.planner.iterative import IterativeOptimizer
    from trino_tpu.sql.planner.rules import DEFAULT_RULES

    node = IterativeOptimizer(DEFAULT_RULES).optimize(node, session)
    check(node, "optimizer:iterative_rules")
    derive_scan_constraints(node)
    plan_dynamic_filters(node)
    check(node, "optimizer:dynamic_filters")
    if session is not None:
        node = insert_compactions(node, session)
        check(node, "optimizer:insert_compactions")
    out = P.OutputNode(node, root.column_names)
    check(out, "optimizer:output")
    return out


# ------------------------------------------------------- compaction pass

# only consider squeezing inputs this large (the payload sort that performs
# the compaction has to pay for itself downstream)
COMPACT_MIN_SLOTS = 1 << 17
COMPACT_MIN_RATIO = 2.0  # slots / estimated live rows


def _slot_count(session, node: P.PlanNode) -> int:
    """Physical row-slot count a node's output page carries (the static
    shape downstream operators process, live or dead)."""
    from trino_tpu.sql.planner import stats

    if isinstance(node, P.TableScanNode):
        conn = session.catalogs.get(node.catalog)
        n = conn.table_row_count(node.schema, node.table) if conn else None
        return int(n) if n else 1024
    if isinstance(node, P.CompactNode):
        from trino_tpu.sql.planner.stats import compact_capacity

        return compact_capacity(session, node)
    if isinstance(node, P.JoinNode):
        if P.uses_expansion_kernel(node):
            return stats._expansion_capacity(session, node)
        left = _slot_count(session, node.left)
        if node.join_type == "left" and node.filter is not None:
            return 2 * left  # head + null-tail concat (expand_join)
        return left
    if isinstance(node, P.AggregationNode):
        return _slot_count(session, node.source)  # sorted-path capacity == n
    if isinstance(node, P.UnionNode):
        return sum(_slot_count(session, s) for s in node.sources_)
    if isinstance(node, P.SetOpNode):
        return _slot_count(session, node.left) + _slot_count(session, node.right)
    if isinstance(node, P.ValuesNode):
        return max(1, len(node.rows or ()))
    srcs = node.sources
    if not srcs:
        return 1024
    return max(_slot_count(session, s) for s in srcs)


def insert_compactions(node: P.PlanNode, session) -> P.PlanNode:
    """Insert CompactNodes where cardinality estimates say the live rows
    are a small fraction of the page's slots AND a downstream operator
    (join / aggregation / window / set-op) would pay per-slot costs for the
    dead ones. Sorts/TopN don't qualify: the compaction itself is one
    payload sort, so compact-then-sort saves nothing over sorting.
    Capacities are estimates; underestimates raise CAPACITY_EXCEEDED and
    the bucketed recompile loop doubles them (CompiledQuery.run)."""
    from trino_tpu.sql.planner import stats

    def maybe_compact(child: P.PlanNode) -> P.PlanNode:
        if isinstance(child, (P.CompactNode, P.ValuesNode, P.TableScanNode)):
            return child
        slots = _slot_count(session, child)
        if slots < COMPACT_MIN_SLOTS:
            return child
        live = stats.estimate_live_rows(session, child)
        if slots < COMPACT_MIN_RATIO * live * 1.3:
            return child
        return P.CompactNode(child, estimated_rows=live)

    def walk(n: P.PlanNode) -> P.PlanNode:
        srcs = [walk(s) for s in n.sources]
        n = _replace_sources(n, srcs)
        if isinstance(n, P.JoinNode):
            n.left = maybe_compact(n.left)
            n.right = maybe_compact(n.right)
        elif isinstance(n, (P.AggregationNode, P.WindowNode)):
            n.source = maybe_compact(n.source)
        elif isinstance(n, P.SetOpNode):
            n.left = maybe_compact(n.left)
            n.right = maybe_compact(n.right)
        return n

    return walk(node)


# ------------------------------------------- scan constraint pushdown


def derive_scan_constraints(node: P.PlanNode) -> None:
    """Attach a TupleDomain to every scan under a filter (reference:
    PushPredicateIntoTableScan + ConnectorMetadata.applyFilter). The
    constraint is advisory: the enforcing FilterNode is KEPT, so connectors
    may ignore or over-approximate it."""
    from trino_tpu.connector.predicate import TupleDomain

    for child in node.sources:
        derive_scan_constraints(child)
    if isinstance(node, P.FilterNode) and isinstance(node.source, P.TableScanNode):
        scan = node.source
        td = TupleDomain.all()
        for conj in ir_conjuncts(node.predicate):
            d = _conjunct_domain(conj, scan)
            if d is not None:
                td = td.intersect(d)
        if not td.is_all():
            scan.constraint = td if scan.constraint is None else scan.constraint.intersect(td)


def _conjunct_domain(e: ir.Expr, scan: P.TableScanNode):
    """Single-column comparison conjunct -> TupleDomain, else None."""
    from trino_tpu.connector.predicate import Domain, TupleDomain

    if not isinstance(e, ir.Call):
        return None

    def col_const(args):
        a, b = args
        if isinstance(a, ir.ColumnRef) and isinstance(b, ir.Constant) and b.value is not None:
            return a, b.value, False
        if isinstance(b, ir.ColumnRef) and isinstance(a, ir.Constant) and a.value is not None:
            return b, a.value, True
        return None, None, False

    name = e.name
    if name in ("eq", "lt", "le", "gt", "ge") and len(e.args) == 2:
        col, v, flipped = col_const(e.args)
        if col is None:
            return None
        if flipped:  # const OP col  ==  col FLIP(OP) const
            name = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}[name]
        dom = {
            "eq": lambda: Domain.from_values([v]),
            "lt": lambda: Domain.range(high=v, high_inclusive=False),
            "le": lambda: Domain.range(high=v),
            "gt": lambda: Domain.range(low=v, low_inclusive=False),
            "ge": lambda: Domain.range(low=v),
        }[name]()
        return TupleDomain({scan.column_names[col.index]: dom})
    if name == "between" and len(e.args) == 3:
        col, lo, hi = e.args
        if (isinstance(col, ir.ColumnRef) and isinstance(lo, ir.Constant)
                and isinstance(hi, ir.Constant)
                and lo.value is not None and hi.value is not None):
            return TupleDomain(
                {scan.column_names[col.index]: Domain.range(low=lo.value, high=hi.value)})
        return None
    if name == "in_list":
        col = e.args[0]
        rest = e.args[1:]
        if isinstance(col, ir.ColumnRef) and all(
                isinstance(a, ir.Constant) and a.value is not None for a in rest):
            return TupleDomain(
                {scan.column_names[col.index]: Domain.from_values([a.value for a in rest])})
    return None


# ------------------------------------------------- dynamic filter planning


def plan_dynamic_filters(node: P.PlanNode) -> None:
    """Annotate probe-side scans of inner/semi joins with the joins whose
    build-side key domains can narrow them at runtime (reference:
    DynamicFilterService.java:105 + LocalDynamicFilterConsumer): the
    executor runs build sides first, extracts key min/max (or small
    in-sets), and hands the domain to the scan's connector."""
    for child in node.sources:
        plan_dynamic_filters(child)
    if not isinstance(node, P.JoinNode):
        return
    if node.join_type not in ("inner", "semi") or node.singleton:
        return
    for i, probe_ch in enumerate(node.left_keys or []):
        target = _trace_to_scan(node.left, probe_ch)
        if target is None:
            continue
        scan, column = target
        if scan.dynamic_filters is None:
            scan.dynamic_filters = []
        scan.dynamic_filters.append((node.id, i, column))
        if node.dyn_filter_keys is None:
            node.dyn_filter_keys = []
        node.dyn_filter_keys.append(i)


def reoptimize_distribution(session, join: P.JoinNode, n_workers: int) -> str:
    """Adaptive re-optimization entry point (reference: AdaptivePlanner
    re-firing DetermineJoinDistributionType on runtime stats): the SAME
    static distribution predicate, evaluated after the adaptive re-planner
    stamped ``runtime_rows`` on the join's exchange sources — so the
    runtime decision and the plan-time decision can never use different
    rules, only different cardinalities. Returns 'partitioned' or
    'broadcast'."""
    from trino_tpu.sql.planner import stats

    if not join.left_keys:
        return "broadcast"  # cross join: broadcast is the only option
    return ("partitioned"
            if stats.join_repartitions(session, join, n_workers)
            else "broadcast")


def _trace_to_scan(node: P.PlanNode, channel: int):
    """Follow ``channel`` down through row-preserving/identity mappings to
    the originating scan column, or None."""
    if isinstance(node, P.TableScanNode):
        return node, node.column_names[channel]
    if isinstance(node, P.FilterNode):
        # row-preserving in the required direction: pruned scan rows could
        # only be rows the join drops anyway. LIMIT is NOT traceable — which
        # rows a limit admits depends on what the scan materialized, so
        # pruning would change results.
        return _trace_to_scan(node.source, channel)
    if isinstance(node, P.ProjectNode):
        e = node.expressions[channel]
        if isinstance(e, ir.ColumnRef):
            return _trace_to_scan(node.source, e.index)
        return None
    if isinstance(node, P.JoinNode):
        if node.join_type in ("semi", "anti") or channel < len(node.left.output_types):
            return _trace_to_scan(node.left, channel)
        return _trace_to_scan(node.right, channel - len(node.left.output_types))
    return None


def merge_identity_projects(node: P.PlanNode) -> P.PlanNode:
    """Drop Projects that are pure identity over their source (reference:
    iterative rule RemoveRedundantIdentityProjections)."""
    new_sources = [merge_identity_projects(s) for s in node.sources]
    _replace_sources(node, new_sources)
    if isinstance(node, P.ProjectNode):
        src = node.source
        if len(node.expressions) == len(src.output_types) and all(
            isinstance(e, ir.ColumnRef) and e.index == i for i, e in enumerate(node.expressions)
        ):
            return src
    return node


# ----------------------------------------------------- join orientation


def unique_key_sets(node: P.PlanNode, session) -> List[frozenset]:
    """Channel sets whose values are unique in node's output.

    Reference analog: uniqueness/cardinality reasoning the CBO does via
    stats; here structural (primary keys, group-by outputs) and used to pick
    the lookup-join build side (executor requires a unique build)."""
    if isinstance(node, P.TableScanNode):
        conn = session.catalogs.get(node.catalog) if session else None
        pk = conn.primary_key(node.schema, node.table) if conn else None
        if pk and all(c in node.column_names for c in pk):
            return [frozenset(node.column_names.index(c) for c in pk)]
        return []
    if isinstance(node, (P.FilterNode, P.SortNode, P.TopNNode, P.LimitNode, P.ExchangeNode)):
        return unique_key_sets(node.source, session)
    if isinstance(node, P.ProjectNode):
        mapping = {}
        for out_ch, e in enumerate(node.expressions):
            if isinstance(e, ir.ColumnRef):
                mapping.setdefault(e.index, out_ch)
        out = []
        for s in unique_key_sets(node.source, session):
            if all(c in mapping for c in s):
                out.append(frozenset(mapping[c] for c in s))
        return out
    if isinstance(node, P.AggregationNode):
        k = len(node.group_channels)
        return [frozenset(range(k))] if k else []
    if isinstance(node, P.JoinNode):
        if node.join_type in ("semi", "anti"):
            return unique_key_sets(node.left, session)
        if node.right_unique and node.join_type in ("inner", "left"):
            # N:1 join preserves left-side uniqueness; left channels keep indices
            return unique_key_sets(node.left, session)
        return []
    return []


def orient_joins(node: P.PlanNode, session) -> P.PlanNode:
    """Bottom-up: make the unique-keyed side the build (right) side of each
    lookup join, flipping sides (and restoring channel order with a Project)
    when only the left side is unique."""
    if isinstance(node, P.JoinNode):
        node.left = orient_joins(node.left, session)
        node.right = orient_joins(node.right, session)
    else:
        new_sources = [orient_joins(s, session) for s in node.sources]
        _replace_sources(node, new_sources)
    if not isinstance(node, P.JoinNode) or node.join_type in ("semi", "anti"):
        return node
    if not node.left_keys:
        return node  # scalar-subquery singleton or true cross join
    if _covered(node.right_keys, unique_key_sets(node.right, session)):
        node.right_unique = True
        return node
    if node.join_type == "inner" and _covered(
        node.left_keys, unique_key_sets(node.left, session)
    ):
        nleft = len(node.left.output_types)
        nright = len(node.right.output_types)
        flipped = P.JoinNode(
            join_type="inner", left=node.right, right=node.left,
            left_keys=list(node.right_keys), right_keys=list(node.left_keys),
            filter=(
                ir.remap_channels(
                    node.filter,
                    {
                        **{c: nright + c for c in range(nleft)},
                        **{nleft + c: c for c in range(nright)},
                    },
                )
                if node.filter is not None
                else None
            ),
            distribution=node.distribution,
            right_unique=True,
        )
        # restore original channel order: left channels then right channels
        tys = node.left.output_types + node.right.output_types
        nms = node.left.output_names + node.right.output_names
        order = list(range(nright, nright + nleft)) + list(range(nright))
        return P.ProjectNode(
            flipped,
            [ir.ColumnRef(tys[i], order[i], nms[i]) for i in range(len(order))],
            nms,
        )
    return node  # M:N join: executor uses the two-pass expansion kernel


def _covered(keys: List[int], unique_sets: List[frozenset]) -> bool:
    ks = set(keys)
    return any(s <= ks for s in unique_sets)


# --------------------------------------------------------------- pushdown


def substitute(e: ir.Expr, mapping: Dict[int, ir.Expr]) -> ir.Expr:
    if isinstance(e, ir.ColumnRef):
        return mapping[e.index]
    if isinstance(e, ir.Call):
        return ir.Call(e.type, e.name, tuple(substitute(a, mapping) for a in e.args))
    if isinstance(e, ir.Case):
        return ir.Case(
            e.type,
            tuple((substitute(c, mapping), substitute(v, mapping)) for c, v in e.whens),
            substitute(e.default, mapping) if e.default is not None else None,
        )
    if isinstance(e, ir.Cast):
        return ir.Cast(e.type, substitute(e.value, mapping))
    return e


def or_disjuncts(e: ir.Expr) -> List[ir.Expr]:
    if isinstance(e, ir.Call) and e.name == "or":
        return or_disjuncts(e.args[0]) + or_disjuncts(e.args[1])
    return [e]


def combine_disjuncts(parts: List[ir.Expr]) -> ir.Expr:
    out = parts[0]
    for p in parts[1:]:
        out = ir.Call(T.BOOLEAN, "or", (out, p))
    return out


def extract_common_or_conjuncts(c: ir.Expr) -> List[ir.Expr]:
    """or(and(a,b), and(a,c)) -> [a, or(b, c)] — factoring common conjuncts
    out of a disjunction (reference: ExtractCommonPredicatesExpressionRewrite)
    so e.g. TPC-H Q19's repeated `p_partkey = l_partkey` becomes a join key."""
    branches = or_disjuncts(c)
    if len(branches) < 2:
        return [c]
    branch_conjs = [ir_conjuncts(b) for b in branches]
    common = [
        x for x in branch_conjs[0] if all(x in bc for bc in branch_conjs[1:])
    ]
    if not common:
        return [c]
    rest = [
        combine_conjuncts([x for x in bc if x not in common]) for bc in branch_conjs
    ]
    if any(r is None for r in rest):  # a branch reduced to TRUE
        return common
    return common + [combine_disjuncts(rest)]


def push_predicates(node: P.PlanNode, conjuncts: List[ir.Expr]) -> P.PlanNode:
    """Push ``conjuncts`` (over node's output channels) down through ``node``."""
    conjuncts = [x for c in conjuncts for x in extract_common_or_conjuncts(c)]
    if isinstance(node, P.FilterNode):
        return push_predicates(node.source, conjuncts + ir_conjuncts(node.predicate))
    if isinstance(node, P.ProjectNode):
        mapping = dict(enumerate(node.expressions))
        inlined = [substitute(c, mapping) for c in conjuncts]
        src = push_predicates(node.source, inlined)
        return P.ProjectNode(src, node.expressions, node.names)
    if isinstance(node, P.JoinNode):
        return _push_into_join(node, conjuncts)
    if isinstance(node, P.UnionNode):
        # predicates distribute over UNION ALL branches (channel-aligned)
        new_sources = [push_predicates(s, list(conjuncts)) for s in node.sources]
        return _replace_sources(node, new_sources)
    if isinstance(node, P.UnnestNode):
        # predicates touching only replicated (source) channels push below
        # the expansion — each survives iff its parent row survives; element
        # predicates stay above (reference: unnest pushdown in
        # PredicatePushDown is similarly source-channel-only)
        rep = node.replicate_channels
        down, up = [], []
        for c in conjuncts:
            if all(ch < len(rep) for ch in ir.referenced_channels(c)):
                down.append(ir.remap_channels(c, {i: r for i, r in enumerate(rep)}))
            else:
                up.append(c)
        node.source = push_predicates(node.source, down)
        return _wrap_filter(node, up)
    if isinstance(
        node,
        (P.LimitNode, P.TopNNode, P.SortNode, P.AggregationNode, P.ExchangeNode,
         P.WindowNode, P.SetOpNode),
    ):
        # not safe/supported to push through — recurse with nothing
        # (predicates over window outputs change which rows a window sees;
        # set-op membership is over whole rows)
        new_sources = [push_predicates(s, []) for s in node.sources]
        node = _replace_sources(node, new_sources)
        return _wrap_filter(node, conjuncts)
    # leaves (scan, values)
    return _wrap_filter(node, conjuncts)


def _wrap_filter(node: P.PlanNode, conjuncts: List[ir.Expr]) -> P.PlanNode:
    pred = combine_conjuncts(conjuncts)
    return P.FilterNode(node, pred) if pred is not None else node


def _replace_sources(node: P.PlanNode, sources: List[P.PlanNode]) -> P.PlanNode:
    if isinstance(node, P.JoinNode):
        node.left, node.right = sources
    elif isinstance(node, P.SetOpNode):
        node.left, node.right = sources
    elif isinstance(node, P.UnionNode):
        node.sources_ = list(sources)
    elif sources:
        node.source = sources[0]
    return node


def _push_into_join(node: P.JoinNode, conjuncts: List[ir.Expr]) -> P.PlanNode:
    nleft = len(node.left.output_types)
    nright = len(node.right.output_types)
    left_conj: List[ir.Expr] = []
    right_conj: List[ir.Expr] = []
    new_left_keys = list(node.left_keys)
    new_right_keys = list(node.right_keys)
    residual: List[ir.Expr] = []
    above: List[ir.Expr] = []
    semi = node.join_type in ("semi", "anti")
    outer = node.join_type == "left"

    pending = list(conjuncts)
    if node.filter is not None and node.join_type == "inner":
        pending += ir_conjuncts(node.filter)
        node.filter = None
    kept_filter: List[ir.Expr] = []
    if node.filter is not None and outer:
        # ON-clause conjuncts of a left join: right-only ones can be pushed
        # into the build side (they only restrict match candidates); all
        # others must stay in the join filter
        for c in ir_conjuncts(node.filter):
            chans = set(ir.referenced_channels(c))
            if chans and min(chans) >= nleft:
                right_conj.append(ir.remap_channels(c, {i: i - nleft for i in chans}))
            else:
                kept_filter.append(c)
        node.filter = combine_conjuncts(kept_filter)

    for c in pending:
        chans = set(ir.referenced_channels(c))
        if semi:
            # output channels == left channels: pushing into left is always legal
            left_conj.append(c)
            continue
        if chans and max(chans, default=-1) < nleft:
            left_conj.append(c)
            continue
        if chans and min(chans, default=nleft) >= nleft:
            rc = ir.remap_channels(c, {i: i - nleft for i in chans})
            if outer:
                above.append(c)  # can't push to right of a left join
            else:
                right_conj.append(rc)
            continue
        # mixed: equi-join key? (not into singleton joins — the scalar
        # subquery's 0/multi-row error semantics live in the cross kernel)
        if (
            node.join_type == "inner"
            and not node.singleton
            and isinstance(c, ir.Call)
            and c.name == "eq"
            and isinstance(c.args[0], ir.ColumnRef)
            and isinstance(c.args[1], ir.ColumnRef)
        ):
            a, b = c.args[0].index, c.args[1].index
            if a < nleft <= b:
                new_left_keys.append(a)
                new_right_keys.append(b - nleft)
                continue
            if b < nleft <= a:
                new_left_keys.append(b)
                new_right_keys.append(a - nleft)
                continue
        if node.join_type == "inner":
            residual.append(c)
        else:
            above.append(c)

    node.left = push_predicates(node.left, left_conj)
    node.right = push_predicates(node.right, right_conj)
    node.left_keys = new_left_keys
    node.right_keys = new_right_keys
    existing_filter = ir_conjuncts(node.filter)
    node.filter = combine_conjuncts(existing_filter + residual)
    return _wrap_filter(node, above)


def prune_output(node: P.PlanNode) -> P.PlanNode:
    return node


# ----------------------------------------------------------------- pruning


def prune_channels(node: P.PlanNode, needed: Set[int]) -> Tuple[P.PlanNode, Dict[int, int]]:
    """Rewrite the subtree to produce only ``needed`` output channels.

    Returns (new_node, mapping old_channel -> new_channel).

    Invariant: no node is ever pruned to zero channels — a Page's row count
    lives in its columns, so count(*)-style consumers that need no values
    still need one channel."""
    if not needed and node.output_types:
        needed = {0}
    if isinstance(node, P.TableScanNode):
        keep = sorted(needed)
        mapping = {old: i for i, old in enumerate(keep)}
        new = P.TableScanNode(
            catalog=node.catalog, schema=node.schema, table=node.table,
            column_names=[node.column_names[i] for i in keep],
            column_types=[node.column_types[i] for i in keep],
            table_handle=node.table_handle,
        )
        return new, mapping
    if isinstance(node, P.ValuesNode):
        keep = sorted(needed)
        mapping = {old: i for i, old in enumerate(keep)}
        new = P.ValuesNode(
            [node.types[i] for i in keep],
            [node.names[i] for i in keep],
            [tuple(r[i] for i in keep) for r in node.rows],
        )
        return new, mapping
    if isinstance(node, P.ProjectNode):
        keep = sorted(needed)
        kept_exprs = [node.expressions[i] for i in keep]
        src_needed = set()
        for e in kept_exprs:
            src_needed.update(ir.referenced_channels(e))
        src, src_map = prune_channels(node.source, src_needed)
        new_exprs = [ir.remap_channels(e, src_map) for e in kept_exprs]
        new = P.ProjectNode(src, new_exprs, [node.names[i] for i in keep])
        return new, {old: i for i, old in enumerate(keep)}
    if isinstance(node, P.UnnestNode):
        rep = node.replicate_channels
        keep_pos = [i for i in range(len(rep)) if i in needed]
        src_needed = {rep[i] for i in keep_pos}
        for e in node.unnest_exprs:
            src_needed.update(ir.referenced_channels(e))
        src, src_map = prune_channels(node.source, src_needed)
        new_exprs = [ir.remap_channels(e, src_map) for e in node.unnest_exprs]
        new = P.UnnestNode(
            source=src,
            unnest_exprs=new_exprs,
            ordinality=node.ordinality,
            replicate_channels=[src_map[rep[i]] for i in keep_pos],
        )
        mapping = {pos: i for i, pos in enumerate(keep_pos)}
        for j in range(len(node.output_types) - len(rep)):
            mapping[len(rep) + j] = len(keep_pos) + j
        return new, mapping
    if isinstance(node, P.FilterNode):
        src_needed = set(needed) | set(ir.referenced_channels(node.predicate))
        src, src_map = prune_channels(node.source, src_needed)
        pred = ir.remap_channels(node.predicate, src_map)
        filt = P.FilterNode(src, pred)
        if src_needed == needed:
            return filt, src_map
        keep = sorted(needed)
        proj = P.ProjectNode(
            filt,
            [
                ir.ColumnRef(node.source.output_types[i], src_map[i],
                             node.source.output_names[i])
                for i in keep
            ],
            [node.source.output_names[i] for i in keep],
        )
        return proj, {old: i for i, old in enumerate(keep)}
    if isinstance(node, P.AggregationNode):
        k = len(node.group_channels)
        kept_aggs = [
            (i, a) for i, a in enumerate(node.aggregates) if (k + i) in needed or not needed
        ]
        src_needed = set(node.group_channels)
        for _, a in kept_aggs:
            if a.arg_channel is not None:
                src_needed.add(a.arg_channel)
            if a.arg2_channel is not None:
                src_needed.add(a.arg2_channel)
        src, src_map = prune_channels(node.source, src_needed)
        new_aggs = [
            P.AggregateCall(
                a.function,
                src_map[a.arg_channel] if a.arg_channel is not None else None,
                a.output_type,
                a.distinct,
                a.param,
                arg2_channel=(
                    src_map[a.arg2_channel] if a.arg2_channel is not None else None
                ),
            )
            for _, a in kept_aggs
        ]
        new_groups = [src_map[c] for c in node.group_channels]
        names = [node.names[c] for c in range(k)] + [
            node.names[k + i] for i, _ in kept_aggs
        ]
        new_node = P.AggregationNode(src, new_groups, new_aggs, node.step, names)
        mapping = {c: c for c in range(k)}
        for newi, (oldi, _) in enumerate(kept_aggs):
            mapping[k + oldi] = k + newi
        return new_node, mapping
    if isinstance(node, P.JoinNode):
        nleft = len(node.left.output_types)
        semi = node.join_type in ("semi", "anti")
        filter_chans = set(ir.referenced_channels(node.filter)) if node.filter is not None else set()
        left_needed = {c for c in needed if c < nleft} | set(node.left_keys) | {
            c for c in filter_chans if c < nleft
        }
        right_needed = (
            set(node.right_keys) | {c - nleft for c in filter_chans if c >= nleft}
        )
        if not semi:
            right_needed |= {c - nleft for c in needed if c >= nleft}
        new_left, lmap = prune_channels(node.left, left_needed)
        new_right, rmap = prune_channels(node.right, right_needed)
        node_filter = node.filter
        if node_filter is not None:
            fmap = {c: lmap[c] for c in filter_chans if c < nleft}
            nl = len(new_left.output_types)
            fmap.update({c: nl + rmap[c - nleft] for c in filter_chans if c >= nleft})
            node_filter = ir.remap_channels(node_filter, fmap)
        new_node = P.JoinNode(
            join_type=node.join_type, left=new_left, right=new_right,
            left_keys=[lmap[c] for c in node.left_keys],
            right_keys=[rmap[c] for c in node.right_keys],
            filter=node_filter, distribution=node.distribution,
            right_unique=node.right_unique, singleton=node.singleton,
        )
        if semi:
            return new_node, lmap
        nl = len(new_left.output_types)
        mapping = dict(lmap)
        mapping.update({nleft + c: nl + rc for c, rc in rmap.items()})
        # the join output may contain channels not in `needed` (keys kept for
        # the join itself); project them away if any extra survive
        produced = set(mapping[c] for c in needed)
        total = nl + len(new_right.output_types)
        if len(produced) != total:
            keep = sorted(mapping[c] for c in needed)
            tys = new_node.output_types
            nms = new_node.output_names
            proj = P.ProjectNode(
                new_node,
                [ir.ColumnRef(tys[c], c, nms[c]) for c in keep],
                [nms[c] for c in keep],
            )
            inv = {c: i for i, c in enumerate(keep)}
            return proj, {c: inv[mapping[c]] for c in needed}
        return new_node, mapping
    if isinstance(node, (P.SortNode, P.TopNNode)):
        src_needed = set(needed) | {c for c, _, _ in node.sort_channels}
        src, src_map = prune_channels(node.source, src_needed)
        node.source = src
        node.sort_channels = [(src_map[c], a, nf) for c, a, nf in node.sort_channels]
        return node, src_map
    if isinstance(node, P.LimitNode):
        src, src_map = prune_channels(node.source, needed)
        node.source = src
        return node, src_map
    if isinstance(node, P.ExchangeNode):
        src_needed = set(needed) | set(node.partition_channels or [])
        src, src_map = prune_channels(node.source, src_needed)
        node.source = src
        if node.partition_channels:
            node.partition_channels = [src_map[c] for c in node.partition_channels]
        return node, src_map
    if isinstance(node, P.WindowNode):
        w = len(node.source.output_types)
        keep_calls = [i for i in range(len(node.calls)) if (w + i) in needed]
        src_needed = {c for c in needed if c < w}
        src_needed |= set(node.partition_channels)
        src_needed |= {c for c, _, _ in node.order_channels}
        for i in keep_calls:
            if node.calls[i].arg_channel is not None:
                src_needed.add(node.calls[i].arg_channel)
        src, src_map = prune_channels(node.source, src_needed)
        if not keep_calls:  # window outputs unused: drop the node entirely
            return src, {c: src_map[c] for c in needed if c < w}
        node.source = src
        node.partition_channels = [src_map[c] for c in node.partition_channels]
        node.order_channels = [(src_map[c], a, nf) for c, a, nf in node.order_channels]
        node.calls = [
            dataclasses.replace(
                node.calls[i],
                arg_channel=(
                    src_map[node.calls[i].arg_channel]
                    if node.calls[i].arg_channel is not None
                    else None
                ),
            )
            for i in keep_calls
        ]
        node.names = [node.names[i] for i in keep_calls]
        new_w = len(src.output_types)
        mapping = {c: src_map[c] for c in needed if c < w}
        for j, i in enumerate(keep_calls):
            mapping[w + i] = new_w + j
        return node, mapping
    if isinstance(node, P.UnionNode):
        keep = sorted(needed)
        mapping = {old: i for i, old in enumerate(keep)}
        new_sources = []
        for s in node.sources_:
            src, src_map = prune_channels(s, set(keep))
            # branches must stay channel-aligned: re-project when a source
            # pruned differently than requested
            if [src_map.get(c) for c in keep] != list(range(len(keep))):
                tys = src.output_types
                src = P.ProjectNode(
                    src,
                    [ir.ColumnRef(tys[src_map[c]], src_map[c]) for c in keep],
                    [node.names[c] for c in keep],
                )
            new_sources.append(src)
        return P.UnionNode(sources_=new_sources, names=[node.names[c] for c in keep]), mapping
    if isinstance(node, P.SetOpNode):
        # set membership is whole-row: every channel stays
        width = len(node.output_types)
        keep = list(range(width))
        names = node.output_names
        for attr in ("left", "right"):
            src, src_map = prune_channels(getattr(node, attr), set(keep))
            if [src_map.get(c) for c in keep] != keep:
                tys = src.output_types
                src = P.ProjectNode(
                    src,
                    [ir.ColumnRef(tys[src_map[c]], src_map[c]) for c in keep],
                    list(names),
                )
            setattr(node, attr, src)
        return node, {i: i for i in keep}
    if isinstance(node, P.MatchRecognizeNode):
        # DEFINE/MEASURES reference input columns by NAME (host matcher):
        # every source channel stays; MR outputs are not pruned through
        width = len(node.source.output_types)
        src, src_map = prune_channels(node.source, set(range(width)))
        assert all(src_map.get(c) == c for c in range(width))
        node.source = src
        return node, {i: i for i in range(len(node.output_types))}
    raise NotImplementedError(f"prune_channels: {type(node).__name__}")
