"""The rule set for the iterative optimizer.

Reference: ``sql/planner/iterative/rule/`` (227 rules). This is the
load-bearing starter set, each a faithful analog of the named reference
rule, re-targeted at the channel-positional plan IR. Rules fire through
``iterative.IterativeOptimizer``; whole-tree passes in optimizer.py remain
for global rewrites (predicate pushdown, channel pruning) — the reference
keeps the same split (PredicatePushDown is not an iterative rule there
either).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from trino_tpu.sql import ir
from trino_tpu.sql.planner import plan as P
from trino_tpu.sql.planner.iterative import Context, Rule
from trino_tpu.sql.planner.planner import combine_conjuncts, ir_conjuncts


def _is_true(e: ir.Expr) -> bool:
    return isinstance(e, ir.Constant) and e.value is True


class MergeFilters(Rule):
    """Filter(Filter(x)) -> Filter(x, a AND b)
    (reference: rule/MergeFilters.java)."""

    pattern = P.FilterNode

    def apply(self, node: P.FilterNode, ctx: Context):
        child = ctx.resolve(node.source)
        if not isinstance(child, P.FilterNode):
            return None
        pred = combine_conjuncts(
            ir_conjuncts(node.predicate) + ir_conjuncts(child.predicate))
        return P.FilterNode(source=child.source, predicate=pred)


class RemoveTrivialFilter(Rule):
    """Filter(x, TRUE) -> x (reference: rule/RemoveTrivialFilters.java)."""

    pattern = P.FilterNode

    def apply(self, node: P.FilterNode, ctx: Context):
        if _is_true(node.predicate):
            return ctx.resolve(node.source)
        return None


class MergeLimits(Rule):
    """Limit(Limit(x, a), b) -> Limit(x, min(a, b))
    (reference: rule/MergeLimits.java)."""

    pattern = P.LimitNode

    def apply(self, node: P.LimitNode, ctx: Context):
        child = ctx.resolve(node.source)
        if not isinstance(child, P.LimitNode) or child.step != node.step:
            return None
        return P.LimitNode(source=child.source,
                           count=min(node.count, child.count), step=node.step)


class PushLimitThroughProject(Rule):
    """Limit(Project(x)) -> Project(Limit(x)) — the limit moves toward the
    data (reference: rule/PushLimitThroughProject.java)."""

    pattern = P.LimitNode

    def apply(self, node: P.LimitNode, ctx: Context):
        child = ctx.resolve(node.source)
        if not isinstance(child, P.ProjectNode):
            return None
        inner = P.LimitNode(source=child.source, count=node.count,
                            step=node.step)
        return P.ProjectNode(source=inner,
                             expressions=list(child.expressions),
                             names=list(child.names))


class LimitOverSortToTopN(Rule):
    """Limit(Sort(x)) -> TopN(x) — one bounded device kernel instead of a
    full sort then a cut (reference: rule/CreateTopN ...
    LimitOverProjectWithSort family)."""

    pattern = P.LimitNode

    def apply(self, node: P.LimitNode, ctx: Context):
        child = ctx.resolve(node.source)
        if not isinstance(child, P.SortNode):
            return None
        return P.TopNNode(source=child.source, count=node.count,
                          sort_channels=list(child.sort_channels))


class RemoveIdentityProject(Rule):
    """Project that passes every input channel through unchanged -> source
    (reference: rule/RemoveRedundantIdentityProjections.java)."""

    pattern = P.ProjectNode

    def apply(self, node: P.ProjectNode, ctx: Context):
        child = ctx.resolve(node.source)
        width = len(child.output_types)
        if len(node.expressions) != width:
            return None
        for i, e in enumerate(node.expressions):
            if not (isinstance(e, ir.ColumnRef) and e.index == i):
                return None
        return ctx.resolve(node.source)


def _substitute(e: ir.Expr, inner: List[ir.Expr]):
    """Replace every ColumnRef with the inner project's expression. Covers
    the WHOLE expression grammar; an unknown composite kind returns None
    (caller declines the rewrite) rather than risking stale channel refs.
    Lambda bodies index lambda PARAMETERS, not input channels — a project
    expression containing one declines (conservative)."""
    if isinstance(e, ir.ColumnRef):
        return inner[e.index]
    if isinstance(e, (ir.Constant, ir.OuterRef)):
        return e
    if isinstance(e, ir.Lambda):
        return None
    if isinstance(e, ir.Call):
        args = [_substitute(a, inner) for a in e.args]
        if any(a is None for a in args):
            return None
        return dataclasses.replace(e, args=tuple(args))
    if isinstance(e, ir.Cast):
        v = _substitute(e.value, inner)
        return None if v is None else dataclasses.replace(e, value=v)
    if isinstance(e, ir.Case):
        whens = []
        for c, v in e.whens:
            c2, v2 = _substitute(c, inner), _substitute(v, inner)
            if c2 is None or v2 is None:
                return None
            whens.append((c2, v2))
        d = None
        if e.default is not None:
            d = _substitute(e.default, inner)
            if d is None:
                return None
        return dataclasses.replace(e, whens=tuple(whens), default=d)
    return None  # unknown composite: decline


def _ref_counts(e: ir.Expr, counts: dict) -> None:
    if isinstance(e, ir.ColumnRef):
        counts[e.index] = counts.get(e.index, 0) + 1
        return
    for c in (e.children() if hasattr(e, "children") else ()):
        _ref_counts(c, counts)


class MergeProjects(Rule):
    """Project(Project(x)) -> Project(x) with inner expressions inlined
    (reference: rule/InlineProjections.java). Guard: an inner expression
    referenced more than once must be trivial (column/constant), else
    inlining would duplicate computation."""

    pattern = P.ProjectNode

    def apply(self, node: P.ProjectNode, ctx: Context):
        child = ctx.resolve(node.source)
        if not isinstance(child, P.ProjectNode):
            return None
        counts: dict = {}
        for e in node.expressions:
            _ref_counts(e, counts)
        for idx, n in counts.items():
            inner_e = child.expressions[idx]
            if n > 1 and not isinstance(inner_e, (ir.ColumnRef, ir.Constant)):
                return None
        exprs = [_substitute(e, child.expressions) for e in node.expressions]
        if any(e is None for e in exprs):
            return None  # grammar kind the substituter cannot renumber
        return P.ProjectNode(source=child.source, expressions=exprs,
                             names=list(node.names))


class PushLimitThroughUnion(Rule):
    """Limit(Union(a, b)) -> Limit(Union(Limit(a), Limit(b))) — each branch
    need produce at most ``count`` rows (reference:
    rule/PushLimitThroughUnion.java). Fires once per shape (branches that
    are already limits to the same count are left alone)."""

    pattern = P.LimitNode

    def apply(self, node: P.LimitNode, ctx: Context):
        child = ctx.resolve(node.source)
        if not isinstance(child, P.UnionNode) or node.step != "single":
            return None
        branches = [ctx.resolve(s) for s in child.sources_]
        if all(isinstance(b, P.LimitNode) and b.count <= node.count
               for b in branches):
            return None
        limited = [
            s if (isinstance(b, P.LimitNode) and b.count <= node.count)
            else P.LimitNode(source=s, count=node.count, step="single")
            for s, b in zip(child.sources_, branches)
        ]
        new_union = P.UnionNode(sources_=limited, names=list(child.names))
        return P.LimitNode(source=new_union, count=node.count, step="single")


class PruneUnpayingCompact(Rule):
    """Remove a CompactNode whose cost gate says the payload sort cannot
    pay for itself: estimated live rows are NOT far below the input's slot
    count (the inverse of optimizer.insert_compactions' insertion gate —
    a stats-driven COST decision, reference: the iterative rules'
    isExpensive()/cost-comparison gates)."""

    pattern = P.CompactNode

    def apply(self, node: P.CompactNode, ctx: Context):
        if ctx.session is None:
            return None
        from trino_tpu.sql.planner import optimizer as O
        from trino_tpu.sql.planner import stats

        source = ctx.resolve(node.source)
        try:
            slots = O._slot_count(ctx.session, self._resolved(source, ctx))
            live = stats.estimate_live_rows(
                ctx.session, self._resolved(source, ctx))
        except Exception:  # noqa: BLE001 — stats unavailable: keep the node
            return None
        if slots >= O.COMPACT_MIN_SLOTS and slots >= O.COMPACT_MIN_RATIO * live * 1.3:
            return None  # still worth it
        return source

    @staticmethod
    def _resolved(node: P.PlanNode, ctx: Context) -> P.PlanNode:
        """Stats walk a plain tree: materialize this subtree out of the
        memo (cheap — subtrees under a compact candidate are small)."""
        from trino_tpu.sql.planner.iterative import GroupReference

        if isinstance(node, GroupReference):
            return ctx.memo.extract(node.group)
        children = [PruneUnpayingCompact._resolved(c, ctx) for c in node.sources]
        if not children:
            return node
        from trino_tpu.sql.planner.iterative import replace_children

        return replace_children(node, children)


def _catalog(ctx: Context, scan: P.TableScanNode):
    if ctx.session is None:
        return None
    return ctx.session.catalogs.get(scan.catalog)


def _scan_with_handle(scan: P.TableScanNode, handle) -> P.TableScanNode:
    new = dataclasses.replace(scan)
    new.id = scan.id
    new.table_handle = handle
    return new


class PushLimitIntoTableScan(Rule):
    """Limit(TableScan) -> Limit(TableScan[handle+limit]) — the connector
    caps rows remotely; the engine's Limit stays (split-level guarantee
    only), as the reference does unless the handle is guaranteed
    (reference: rule/PushLimitIntoTableScan.java +
    ConnectorMetadata.applyLimit)."""

    pattern = P.LimitNode

    def apply(self, node: P.LimitNode, ctx: Context):
        child = ctx.resolve(node.source)
        if not isinstance(child, P.TableScanNode) or node.step != "single":
            return None
        conn = _catalog(ctx, child)
        if conn is None:
            return None
        h = conn.apply_limit(child.schema, child.table, child.table_handle,
                             node.count)
        if h is None:
            return None
        return P.LimitNode(source=_scan_with_handle(child, h),
                           count=node.count, step=node.step)


class PushTopNIntoTableScan(Rule):
    """TopN(TableScan) -> TopN(TableScan[handle+topN]) (reference:
    rule/PushTopNIntoTableScan.java + ConnectorMetadata.applyTopN). The
    engine's TopN stays: the remote order guarantees the top set per
    split, the engine re-establishes total order."""

    pattern = P.TopNNode

    def apply(self, node: P.TopNNode, ctx: Context):
        child = ctx.resolve(node.source)
        if not isinstance(child, P.TableScanNode) or node.step != "single":
            return None
        conn = _catalog(ctx, child)
        if conn is None:
            return None
        from trino_tpu.connector.spi import SortItem

        order = []
        for ch, asc, nulls_first in node.sort_channels:
            nf = nulls_first if nulls_first is not None else (not asc)
            order.append(SortItem(child.column_names[ch], asc, nf))
        h = conn.apply_topn(child.schema, child.table, child.table_handle,
                            node.count, order)
        if h is None:
            return None
        return P.TopNNode(source=_scan_with_handle(child, h),
                          count=node.count,
                          sort_channels=list(node.sort_channels),
                          step=node.step)


class PushAggregationIntoTableScan(Rule):
    """Aggregation(TableScan) -> TableScan[handle+aggregate] — the WHOLE
    aggregation moves to the connector when it can evaluate it with the
    engine's exact semantics; the scan's output schema becomes the
    aggregation's (reference: rule/PushAggregationIntoTableScan.java +
    ConnectorMetadata.applyAggregation)."""

    pattern = P.AggregationNode

    def apply(self, node: P.AggregationNode, ctx: Context):
        if node.step != "single":
            return None
        child = ctx.resolve(node.source)
        # see through the planner's argument-mapping Project when it is
        # pure column references (channel -> scan column renumbering)
        chan_map = None
        if isinstance(child, P.ProjectNode):
            if not all(isinstance(e, ir.ColumnRef) for e in child.expressions):
                return None
            chan_map = [e.index for e in child.expressions]
            child = ctx.resolve(child.source)
        if not isinstance(child, P.TableScanNode):
            return None
        conn = _catalog(ctx, child)
        if conn is None or getattr(child, "table_handle", None) is not None:
            return None
        from trino_tpu.connector.spi import AggregateSpec

        def col(ch: int) -> str:
            return child.column_names[chan_map[ch] if chan_map else ch]

        group_cols = [col(c) for c in node.group_channels]
        specs = []
        for call in node.aggregates:
            if call.distinct or call.arg2_channel is not None:
                return None
            fn = call.function
            if fn == "count_star" or (fn == "count" and call.arg_channel is None):
                specs.append(AggregateSpec("count", None, call.output_type))
                continue
            if fn not in ("count", "sum", "min", "max"):
                return None
            specs.append(AggregateSpec(
                fn, col(call.arg_channel), call.output_type))
        got = conn.apply_aggregation(
            child.schema, child.table, child.table_handle, group_cols, specs)
        if got is None:
            return None
        handle, out_cols = got
        return P.TableScanNode(
            catalog=child.catalog, schema=child.schema, table=child.table,
            column_names=[c.name for c in out_cols],
            column_types=[c.type for c in out_cols],
            table_handle=handle)


DEFAULT_RULES = [
    MergeFilters(),
    RemoveTrivialFilter(),
    MergeLimits(),
    PushLimitThroughUnion(),
    PushLimitThroughProject(),
    LimitOverSortToTopN(),
    RemoveIdentityProject(),
    MergeProjects(),
    PushAggregationIntoTableScan(),
    PushTopNIntoTableScan(),
    PushLimitIntoTableScan(),
]
