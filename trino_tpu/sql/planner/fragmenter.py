"""Plan fragmenter: cut the plan into exchange-separated fragments.

Reference: ``core/trino-main/.../sql/planner/PlanFragmenter.java:94`` cuts at
remote ExchangeNodes into PlanFragments with PartitioningHandles
(SystemPartitioningHandle.java:48-57). Here the same cuts describe how the
SPMD executor maps the query onto the mesh (parallel/spmd.py):

- SOURCE fragments: sharded scans + local work, one shard per device;
- partial->final aggregations cut at a GATHER_STATES exchange (all_gather of
  partial-state pages);
- lookup/semi join build sides cut at BROADCAST exchanges (all_gather of the
  build page);
- the root fragment is SINGLE (sort/topN/limit/output over the gathered,
  replicated result).

Unlike the reference, a fragment boundary is not a process/wire boundary on
the intra-slice path — every exchange compiles to a collective inside one
program. The fragment tree IS the scheduling unit for the multi-host DCN
tier (trino_tpu/server: coordinator schedules source fragments onto
workers, pages stream over HTTP) and drives EXPLAIN (TYPE DISTRIBUTED).
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import List, Optional, Tuple

from trino_tpu.sql.planner import plan as P

_frag_ids = itertools.count()


@dataclasses.dataclass
class RemoteSourceNode(P.PlanNode):
    """Leaf standing for another fragment's output (reference:
    plan/RemoteSourceNode.java)."""

    fragment_id: int = 0
    types: List = None
    names: List[str] = None
    exchange_type: str = "gather"  # gather | broadcast | gather_states
    # ACTUAL output rows of the producing stage, stamped at the stage
    # boundary by the adaptive re-planner (trino_tpu/adaptive/): downstream
    # cardinality estimation then starts from truth — the
    # TableScanNode.runtime_rows analog on fragment boundaries.
    runtime_rows: Optional[int] = None

    @property
    def output_types(self):
        return list(self.types)

    @property
    def output_names(self):
        return list(self.names)


@dataclasses.dataclass
class PlanFragment:
    # 'source' (sharded over splits) | 'hash' (one task per key partition)
    # | 'single' (replicated/coordinator)
    id: int
    partitioning: str
    root: P.PlanNode
    # producer-side hash partitioning of this fragment's OUTPUT: the task
    # splits its result by hash of these channels into one stream per
    # consumer (FIXED_HASH_DISTRIBUTION's PartitionedOutputOperator role)
    output_partition_channels: Optional[List[int]] = None
    # adaptive skew mitigation (trino_tpu/adaptive/replanner.py): rows of
    # these HOT partitions spread round-robin across all partitions
    # (probe side) / replicate into every partition (build side) — set
    # only on salted re-run fragments the re-planner creates
    skew_spread_partitions: Optional[List[int]] = None
    skew_replicate_partitions: Optional[List[int]] = None


def _hash_distributed_final(session, node: P.AggregationNode) -> bool:
    """Hash-distribute the FINAL aggregation stage when the group space is
    too big to gather into one process (threshold: the same
    gather_max_rows_per_device session property the SPMD tier uses).
    Partitioned outputs spool per partition (server/task.py), so the FTE
    retry policy no longer forces the gather path."""
    if session is None or not node.group_channels:
        return False
    from trino_tpu.sql.planner import stats

    rows = stats.estimate_rows(session, node.source)
    return rows > stats._gather_max_rows(session)


def _colocated_join(session, node: P.JoinNode, left, right) -> bool:
    """True when both join sides trace to scans whose connector-declared
    partitionings share a family on exactly the join keys, and neither
    scan's static constraint narrows the partitioning column (which could
    desynchronize the two sides' split boundaries). Split alignment then
    holds by the connector contract: same family => same key->split map."""
    if not node.left_keys or len(node.left_keys) != 1:
        return False
    if node.join_type not in ("inner", "semi", "anti", "left"):
        return False
    from trino_tpu.sql.planner.optimizer import _trace_to_scan

    lt = _trace_to_scan(left, node.left_keys[0])
    rt = _trace_to_scan(right, node.right_keys[0])
    if lt is None or rt is None:
        return False
    (lscan, lcol), (rscan, rcol) = lt, rt
    if lscan.catalog != rscan.catalog:
        return False
    conn = session.catalogs.get(lscan.catalog)
    if conn is None:
        return False
    lp = conn.table_partitioning(lscan.schema, lscan.table)
    rp = conn.table_partitioning(rscan.schema, rscan.table)
    if lp is None or rp is None or lp.family != rp.family:
        return False
    if lp.columns != (lcol,) or rp.columns != (rcol,):
        return False
    for scan, col in ((lscan, lcol), (rscan, rcol)):
        td = scan.constraint
        if td is not None and not td.domain(col).is_all():
            return False  # key-narrowed splits could misalign
    return True


def fragment_plan(root: P.OutputNode, session=None) -> List[PlanFragment]:
    """Cut the optimized plan into fragments mirroring the SPMD execution."""
    global _frag_ids
    _frag_ids = itertools.count()
    fragments: List[PlanFragment] = []

    def cut(node: P.PlanNode, fragments: List[PlanFragment]) -> Tuple[P.PlanNode, bool]:
        """Returns (node-in-current-fragment, is_replicated)."""
        if isinstance(node, P.TableScanNode):
            return node, False
        if isinstance(node, (P.FilterNode, P.ProjectNode, P.LimitNode, P.CompactNode)):
            src, rep = cut(node.source, fragments)
            node.source = src
            return node, rep
        if isinstance(node, P.AggregationNode):
            src, rep = cut(node.source, fragments)
            if rep:
                node.source = src
                return node, True
            if not P.can_split_aggs(node.aggregates):
                # DISTINCT aggregates can't be split partial/final: gather the
                # raw rows, aggregate single-step above the exchange
                fid = next(_frag_ids)
                fragments.append(PlanFragment(fid, "source", src))
                node.source = RemoteSourceNode(
                    fragment_id=fid,
                    types=src.output_types,
                    names=src.output_names,
                    exchange_type="gather",
                )
                return node, True
            # partial in a source fragment, final above a state exchange
            partial = P.AggregationNode(
                src, node.group_channels, node.aggregates, step="partial",
                names=node.names,
            )
            k = len(node.group_channels)
            if _hash_distributed_final(session, node):
                # FIXED_HASH_DISTRIBUTION: partial tasks partition their
                # state pages by group-key hash; one FINAL task per
                # partition aggregates disjoint key sets in parallel —
                # no process ever holds all groups (reference:
                # PagePartitioner producer + hash-distributed final stage)
                fid = next(_frag_ids)
                fragments.append(PlanFragment(
                    fid, "source", partial,
                    output_partition_channels=list(range(k))))
                remote = RemoteSourceNode(
                    fragment_id=fid,
                    types=partial.output_types,
                    names=partial.output_names,
                    exchange_type="partitioned",
                )
                final = P.AggregationNode(
                    remote, list(range(k)), node.aggregates, step="final",
                    names=node.names,
                )
                hfid = next(_frag_ids)
                fragments.append(PlanFragment(hfid, "hash", final))
                return RemoteSourceNode(
                    fragment_id=hfid,
                    types=final.output_types,
                    names=final.output_names,
                    exchange_type="gather",
                ), True
            fid = next(_frag_ids)
            fragments.append(PlanFragment(fid, "source", partial))
            remote = RemoteSourceNode(
                fragment_id=fid,
                types=partial.output_types,
                names=partial.output_names,
                exchange_type="gather_states",
            )
            final = P.AggregationNode(
                remote, list(range(k)), node.aggregates, step="final", names=node.names
            )
            return final, True
        if isinstance(node, P.JoinNode):
            left, lrep = cut(node.left, fragments)
            right, rrep = cut(node.right, fragments)
            if (session is not None and not lrep and not rrep
                    and _colocated_join(session, node, left, right)):
                # connector-partitioned co-located join (reference:
                # ConnectorNodePartitioningProvider + bucketed-table
                # execution): both sides' scans split by the SAME key
                # boundaries, and the scheduler assigns same-index splits
                # to the same task — so the join runs INSIDE the source
                # fragment with ZERO exchange on either side.
                node.left, node.right = left, right
                node.distribution = "colocated"
                return node, False
            if (session is not None and not lrep and not rrep
                    and node.left_keys and node.join_type in ("inner", "semi",
                                                              "anti", "left")):
                from trino_tpu.sql.planner import stats

                if stats.join_repartitions(session, node, 1):
                    # co-partitioned join (FIXED_HASH_DISTRIBUTION both
                    # sides): probe and build tasks partition their output
                    # pages by key hash; hash-stage task p joins partition
                    # p of each side locally — equal keys co-locate, so the
                    # union of per-partition joins is the exact join and NO
                    # process ever materializes a whole side (reference:
                    # PagePartitioner.java:134-149 + partitioned join
                    # distribution).
                    lfid = next(_frag_ids)
                    fragments.append(PlanFragment(
                        lfid, "source", left,
                        output_partition_channels=list(node.left_keys)))
                    rfid = next(_frag_ids)
                    fragments.append(PlanFragment(
                        rfid, "source", right,
                        output_partition_channels=list(node.right_keys)))
                    node.left = RemoteSourceNode(
                        fragment_id=lfid, types=left.output_types,
                        names=left.output_names, exchange_type="partitioned")
                    node.right = RemoteSourceNode(
                        fragment_id=rfid, types=right.output_types,
                        names=right.output_names, exchange_type="partitioned")
                    node.distribution = "partitioned"
                    jfid = next(_frag_ids)
                    fragments.append(PlanFragment(jfid, "hash", node))
                    return RemoteSourceNode(
                        fragment_id=jfid, types=node.output_types,
                        names=node.output_names, exchange_type="gather",
                    ), True
            node.left = left
            if not rrep:
                # build side broadcast: its own source fragment
                fid = next(_frag_ids)
                fragments.append(PlanFragment(fid, "source", right))
                node.right = RemoteSourceNode(
                    fragment_id=fid,
                    types=right.output_types,
                    names=right.output_names,
                    exchange_type="broadcast",
                )
                node.distribution = node.distribution or "broadcast"
            else:
                node.right = right
            return node, lrep
        if isinstance(node, (P.SortNode, P.TopNNode, P.WindowNode,
                             P.MatchRecognizeNode)):
            src, rep = cut(node.source, fragments)
            if not rep:
                fid = next(_frag_ids)
                fragments.append(PlanFragment(fid, "source", src))
                src = RemoteSourceNode(
                    fragment_id=fid,
                    types=src.output_types,
                    names=src.output_names,
                    exchange_type="gather",
                )
            node.source = src
            return node, True
        if isinstance(node, (P.UnionNode, P.SetOpNode)):
            # each non-replicated operand becomes a gathered source fragment
            kids = list(node.sources)
            new_kids = []
            for kid in kids:
                src, rep = cut(kid, fragments)
                if not rep:
                    fid = next(_frag_ids)
                    fragments.append(PlanFragment(fid, "source", src))
                    src = RemoteSourceNode(
                        fragment_id=fid,
                        types=src.output_types,
                        names=src.output_names,
                        exchange_type="gather",
                    )
                new_kids.append(src)
            if isinstance(node, P.UnionNode):
                node.sources_ = new_kids
            else:
                node.left, node.right = new_kids
            return node, True
        if isinstance(node, P.ValuesNode):
            return node, True
        raise NotImplementedError(f"fragmenter: {type(node).__name__}")

    import copy

    body, rep = cut(copy.deepcopy(root.source), fragments)
    out = P.OutputNode(body, root.column_names)
    if not rep:
        fid = next(_frag_ids)
        fragments.append(PlanFragment(fid, "source", body))
        out = P.OutputNode(
            RemoteSourceNode(
                fragment_id=fid,
                types=body.output_types,
                names=body.output_names,
                exchange_type="gather",
            ),
            root.column_names,
        )
    fragments.append(PlanFragment(next(_frag_ids), "single", out))
    from trino_tpu.sql.planner.sanity import (
        validate_fragments, validation_enabled)

    if validation_enabled(session):
        validate_fragments(fragments, phase="fragmentation")
    return fragments


def fresh_fragment_ids(fragments: List[PlanFragment]):
    """Id allocator for fragments added AFTER fragmentation (the adaptive
    re-planner): continues past the query's own max id. The module-global
    ``_frag_ids`` cannot be reused — a concurrent query's fragment_plan
    resets it, and a recycled id would collide inside this query."""
    return itertools.count(max((f.id for f in fragments), default=-1) + 1)


def adapt_broadcast_to_partitioned(frag: PlanFragment, join: P.JoinNode,
                                   build_root: P.PlanNode,
                                   id_alloc) -> List[PlanFragment]:
    """Re-fragment a broadcast join into the co-partitioned shape at the
    stage boundary (the adaptive half of DetermineJoinDistributionType):
    the probe subtree moves into its own key-partitioned source fragment,
    the build re-runs as a key-partitioned source fragment (its broadcast
    output was never pulled), and ``frag`` becomes the hash join stage.
    Operators above the join stay in ``frag`` — they were already computed
    per task and merged downstream, and a hash partition is just a
    different task-partitioning of the same rows. Returns the new producer
    fragments to schedule before ``frag``."""
    probe = join.left
    pfid, bfid = next(id_alloc), next(id_alloc)
    probe_frag = PlanFragment(
        pfid, "source", probe,
        output_partition_channels=list(join.left_keys))
    build_frag = PlanFragment(
        bfid, "source", build_root,
        output_partition_channels=list(join.right_keys))
    join.left = RemoteSourceNode(
        fragment_id=pfid, types=probe.output_types,
        names=probe.output_names, exchange_type="partitioned")
    join.right = RemoteSourceNode(
        fragment_id=bfid, types=build_root.output_types,
        names=build_root.output_names, exchange_type="partitioned")
    join.distribution = "partitioned"
    frag.partitioning = "hash"
    return [probe_frag, build_frag]


def adapt_partitioned_to_broadcast(frag: PlanFragment, join: P.JoinNode,
                                   build_root: P.PlanNode,
                                   id_alloc) -> List[PlanFragment]:
    """Re-fragment a co-partitioned join's BUILD side into a broadcast at
    the stage boundary (actual build rows came in far under the threshold):
    the build re-runs as an unpartitioned source fragment whose full stream
    every join task pulls; the probe side keeps its partitioned producers,
    so each hash task joins its probe partition against the whole (tiny)
    build — build-side partition skew disappears. Returns the new build
    fragment to schedule before ``frag``."""
    bfid = next(id_alloc)
    build_frag = PlanFragment(bfid, "source", build_root)
    join.right = RemoteSourceNode(
        fragment_id=bfid, types=build_root.output_types,
        names=build_root.output_names, exchange_type="broadcast")
    join.distribution = "broadcast"
    return [build_frag]


def format_fragments(fragments: List[PlanFragment], stats=None,
                     stage_stats=None, verbose: bool = False,
                     adapted=None, kernels=None) -> str:
    """EXPLAIN (TYPE DISTRIBUTED) rendering (reference: PlanPrinter's
    fragmented text plan). With ``stats`` (plan-node id → OperatorStats,
    the coordinator's rollup of worker-reported task stats) this renders
    distributed EXPLAIN ANALYZE: per-node ``wall=``/``rows=`` annotations
    sourced from the workers that actually ran each fragment. With
    ``stage_stats`` (fragment id → stage rollup dict), each fragment header
    carries its stage totals; ``verbose`` adds a device-detail line per
    fragment (device seconds, output/peak bytes, spill count). ``adapted``
    (fragment id → change description, from the query's versioned plan
    changes) annotates fragments the runtime re-planner rewrote, e.g.
    ``[adapted: broadcast->partitioned]``."""
    lines = []
    for f in reversed(fragments):
        head = f"Fragment {f.id} [{f.partitioning}]"
        note = (adapted or {}).get(f.id)
        if note:
            head += f" [adapted: {note}]"
        si = (stage_stats or {}).get(f.id)
        if si is not None:
            head += (f" [tasks={si['tasks']},"
                     f" splits={si['completedSplits']}/{si['totalSplits']},"
                     f" wall={si['wallS'] * 1e3:.1f}ms,"
                     f" rows={si['outputRows']}]")
        lines.append(head)
        if verbose and si is not None:
            lines.append(
                f"  device: execute={si['deviceS'] * 1e3:.1f}ms,"
                f" output={si['outputBytes'] // 1024}KiB,"
                f" peak={si['peakBytes'] // 1024}KiB,"
                f" spills={si['spills']}")
        lines.append(_format(f.root, 1, stats, verbose, kernels))
        lines.append("")
    return "\n".join(lines).rstrip()


def _format(node: P.PlanNode, indent: int, stats=None,
            verbose: bool = False, kernels=None) -> str:
    if isinstance(node, RemoteSourceNode):
        pad = "  " * indent
        line = (f"{pad}- RemoteSource[{node.exchange_type}]"
                f" <- Fragment {node.fragment_id}")
        st = (stats or {}).get(node.id)
        if st is not None:
            line += f"  [wall={st.wall_s * 1e3:.1f}ms rows={st.output_rows}]"
        return line
    base = P.format_plan(node, indent, stats=stats, verbose=verbose,
                         kernels=kernels).split("\n")
    out = [base[0]]
    # re-render children so RemoteSourceNodes print specially
    kids = list(node.sources)
    if kids:
        out = [base[0]]
        for k in kids:
            out.append(_format(k, indent + 1, stats, verbose, kernels))
        return "\n".join(out)
    return base[0]