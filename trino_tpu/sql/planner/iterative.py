"""Iterative rule optimizer: memo + rules + fixpoint driver.

Reference: ``sql/planner/iterative/IterativeOptimizer.java:67`` +
``Memo.java`` + ``Rule.java`` — plans live in a memo of single-node groups
whose children are group references, so a rule rewrite swaps one group's
node without copying the rest of the tree, and the driver re-fires rules
until no pattern matches (or the transformation budget trips). This is the
scaling path past the big-bang pass pipeline in optimizer.py: new rewrites
become local rules instead of new whole-tree recursions.

The memo here is a rewrite memo (one node per group), exactly like the
reference's — not a Cascades exploration memo with alternatives.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from trino_tpu.sql.planner import plan as P


@dataclasses.dataclass
class GroupReference(P.PlanNode):
    """Placeholder child pointing at a memo group (reference:
    iterative/GroupReference.java). Output types/names delegate to the
    group's current node so parents stay type-checkable mid-rewrite."""

    memo: "Memo" = None
    group: int = 0

    @property
    def sources(self):
        return ()

    @property
    def output_types(self):
        return self.memo.node(self.group).output_types

    @property
    def output_names(self):
        return self.memo.node(self.group).output_names


def child_slots(node: P.PlanNode) -> List[Tuple[str, bool]]:
    """(attribute, is_list) slots holding this node's children — the
    channel-positional plan nodes keep children in one of three layouts."""
    if isinstance(node, (P.JoinNode, P.SetOpNode)):
        return [("left", False), ("right", False)]
    if isinstance(node, P.UnionNode):
        return [("sources_", True)]
    if hasattr(node, "source") and node.source is not None:
        return [("source", False)]
    return []


def replace_children(node: P.PlanNode, new_children: Sequence[P.PlanNode]) -> P.PlanNode:
    """Shallow-copy ``node`` with its children replaced (order matches
    ``node.sources``)."""
    clone = dataclasses.replace(node)
    clone.id = node.id  # structural re-wiring keeps identity (id is
    # init=False, so dataclasses.replace would otherwise mint a fresh one)
    it = iter(new_children)
    for attr, is_list in child_slots(node):
        if is_list:
            old = getattr(node, attr)
            setattr(clone, attr, [next(it) for _ in old])
        else:
            setattr(clone, attr, next(it))
    return clone


class Memo:
    """Single-node groups; children of memo-resident nodes are
    GroupReferences (reference: iterative/Memo.java)."""

    def __init__(self, root: P.PlanNode):
        self._groups: Dict[int, P.PlanNode] = {}
        self._next = 0
        self.root_group = self._intern(root)

    def _intern(self, node: P.PlanNode) -> int:
        gid = self._next
        self._next += 1
        self._groups[gid] = self._with_ref_children(node)
        return gid

    def _with_ref_children(self, node: P.PlanNode) -> P.PlanNode:
        children = list(node.sources)
        if not children:
            return node
        refs = [
            c if isinstance(c, GroupReference)
            else GroupReference(memo=self, group=self._intern(c))
            for c in children
        ]
        return replace_children(node, refs)

    def node(self, group: int) -> P.PlanNode:
        return self._groups[group]

    def reachable_groups(self) -> List[int]:
        """Groups reachable from the root — rewrites that drop nodes leave
        orphaned groups behind (the reference memo garbage-collects them;
        here the driver simply skips them)."""
        seen: List[int] = []
        stack = [self.root_group]
        visited = set()
        while stack:
            gid = stack.pop()
            if gid in visited:
                continue
            visited.add(gid)
            seen.append(gid)
            for c in self._groups[gid].sources:
                if isinstance(c, GroupReference):
                    stack.append(c.group)
        return seen

    def replace(self, group: int, node: P.PlanNode) -> None:
        """Install a rewritten node; its NEW (non-reference) children are
        interned as fresh groups."""
        self._groups[group] = self._with_ref_children(node)

    def resolve(self, node: P.PlanNode) -> P.PlanNode:
        """GroupReference -> its group's current node (reference: Lookup)."""
        while isinstance(node, GroupReference):
            node = self._groups[node.group]
        return node

    def extract(self, group: Optional[int] = None) -> P.PlanNode:
        """Materialize the memo back into a plain plan tree."""
        node = self._groups[self.root_group if group is None else group]
        children = [
            self.extract(c.group) if isinstance(c, GroupReference) else c
            for c in node.sources
        ]
        if not children:
            return node
        return replace_children(node, children)


@dataclasses.dataclass
class Context:
    """What a rule sees besides the matched node (reference: Rule.Context —
    lookup + session/stats access + id allocator)."""

    memo: Memo
    session: object

    def resolve(self, node: P.PlanNode) -> P.PlanNode:
        return self.memo.resolve(node)


class Rule:
    """One local rewrite (reference: iterative/Rule.java). ``pattern`` is
    the matched node class; ``apply`` returns the replacement node (whose
    children may be GroupReferences from the matched node, or plain new
    subtrees) or None when the rule decides not to fire — cost gates live
    inside ``apply`` via ``context.session`` stats."""

    pattern: type = P.PlanNode

    def apply(self, node: P.PlanNode, context: Context) -> Optional[P.PlanNode]:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__


class IterativeOptimizer:
    """Fire rules to fixpoint over the memo (reference:
    IterativeOptimizer.exploreGroup's top-down loop + re-exploration of
    parents when children change)."""

    def __init__(self, rules: Sequence[Rule], max_transforms: int = 10_000):
        self.rules = list(rules)
        self.max_transforms = max_transforms
        self.fired: List[str] = []  # rule-name log (PlanTester-style asserts)

    def optimize(self, root: P.PlanNode, session=None) -> P.PlanNode:
        memo = Memo(root)
        ctx = Context(memo, session)
        budget = self.max_transforms
        progress = True
        while progress:
            progress = False
            for gid in memo.reachable_groups():
                changed = True
                while changed and budget > 0:
                    changed = False
                    node = memo.node(gid)
                    for rule in self.rules:
                        if not isinstance(node, rule.pattern):
                            continue
                        out = rule.apply(node, ctx)
                        if out is None:
                            continue
                        memo.replace(gid, out)
                        self.fired.append(rule.name)
                        budget -= 1
                        changed = progress = True
                        break
            if budget <= 0:
                break
        return memo.extract()
