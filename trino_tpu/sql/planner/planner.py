"""AST -> logical plan.

Reference: ``core/trino-main/.../sql/planner/LogicalPlanner.java:167`` +
``QueryPlanner``/``RelationPlanner`` — plans relations, predicates,
aggregations, sorts; subqueries are decorrelated into semi/anti joins or
single-row cross joins (the role of Trino's ApplyNode + correlated-subquery
rewrite rules, done here directly at planning time).

Join planning for implicit (comma) joins builds the join from WHERE equi
conjuncts greedily in FROM order — the CBO join-reordering pass
(reference ReorderJoins) refines this in the optimizer.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from trino_tpu import types as T
from trino_tpu.sql import ir
from trino_tpu.sql.analyzer.expr_analyzer import (
    AGGREGATE_FUNCTIONS,
    AnalysisError,
    ExprAnalyzer,
    WINDOW_ONLY_FUNCTIONS,
    aggregate_result_type,
    find_aggregates,
    find_windows,
    window_result_type,
)
from trino_tpu.sql.analyzer.scope import Field, Scope
from trino_tpu.sql.parser import ast
from trino_tpu.sql.planner import plan as P


class PlanningError(ValueError):
    pass


@dataclasses.dataclass
class RelationPlan:
    node: P.PlanNode
    scope: Scope


def split_conjuncts(e: Optional[ast.Expression]) -> List[ast.Expression]:
    if e is None:
        return []
    if isinstance(e, ast.LogicalBinary) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def ir_conjuncts(e: Optional[ir.Expr]) -> List[ir.Expr]:
    if e is None:
        return []
    if isinstance(e, ir.Call) and e.name == "and":
        return ir_conjuncts(e.args[0]) + ir_conjuncts(e.args[1])
    return [e]


def combine_conjuncts(parts: Sequence[ir.Expr]) -> Optional[ir.Expr]:
    out = None
    for p in parts:
        out = p if out is None else ir.Call(T.BOOLEAN, "and", (out, p))
    return out


def decorrelate_to_joint(e: ir.Expr, nleft: int) -> ir.Expr:
    """Rewrite an expression analyzed in an inner scope (OuterRefs to the
    outer query) onto the joint channel space of a join: OuterRef(i) ->
    channel i, inner ColumnRef(j) -> channel nleft + j."""
    if isinstance(e, ir.OuterRef):
        return ir.ColumnRef(e.type, e.index, e.name)
    if isinstance(e, ir.ColumnRef):
        return ir.ColumnRef(e.type, nleft + e.index, e.name)
    if isinstance(e, ir.Call):
        return ir.Call(e.type, e.name, tuple(decorrelate_to_joint(a, nleft) for a in e.args))
    if isinstance(e, ir.Case):
        return ir.Case(
            e.type,
            tuple(
                (decorrelate_to_joint(c, nleft), decorrelate_to_joint(v, nleft))
                for c, v in e.whens
            ),
            decorrelate_to_joint(e.default, nleft) if e.default is not None else None,
        )
    if isinstance(e, ir.Cast):
        return ir.Cast(e.type, decorrelate_to_joint(e.value, nleft))
    return e


class Planner:
    def __init__(self, session):
        self.session = session
        self.catalogs = session.catalogs
        self.default_catalog = session.properties.get("catalog", "tpch")
        self.default_schema = session.properties.get("schema", "tiny")

    # ------------------------------------------------------------------ api
    def plan(self, query: ast.Query) -> P.OutputNode:
        rp = self.plan_query(query, outer_scope=None, ctes={})
        return P.OutputNode(rp.node, [f.name or f"_col{i}" for i, f in enumerate(rp.scope.fields)])

    # ------------------------------------------------------------- relations
    def plan_query(
        self, query: ast.Query, outer_scope: Optional[Scope], ctes: Dict[str, ast.WithQuery]
    ) -> RelationPlan:
        ctes = dict(ctes)
        for wq in query.with_queries:
            ctes[wq.name.lower()] = wq
        body = query.body
        if isinstance(body, ast.Values):
            vp = self._plan_values(body, outer_scope)
            node = vp.node
            if query.order_by:
                raise PlanningError("ORDER BY on VALUES: not yet supported")
            if query.limit is not None:
                node = P.LimitNode(node, query.limit)
            return RelationPlan(node, vp.scope)
        if isinstance(body, ast.SetOperation):
            sp = self._plan_set_operation(body, outer_scope, ctes)
            node = sp.node
            if query.order_by:
                node = self._plan_order_by(
                    query, node, sp.scope, replacements={}, select_asts=[],
                )
            if query.limit is not None:
                if isinstance(node, P.SortNode):
                    node = P.TopNNode(node.source, query.limit, node.sort_channels)
                else:
                    node = P.LimitNode(node, query.limit)
            return RelationPlan(node, sp.scope)
        if isinstance(body, ast.Query):
            inner = self.plan_query(body, outer_scope, ctes)
            body_plan = inner
        else:
            body_plan = self.plan_query_spec(body, outer_scope, ctes, query)
            return body_plan  # ORDER BY/LIMIT handled inside (needs agg scope)
        # parenthesized query: apply outer ORDER BY/LIMIT
        node = body_plan.node
        if query.order_by:
            raise PlanningError("ORDER BY on parenthesized query: not yet supported")
        if query.limit is not None:
            node = P.LimitNode(node, query.limit)
        return RelationPlan(node, body_plan.scope)

    def _plan_set_operation(
        self, body: ast.SetOperation, outer_scope, ctes
    ) -> RelationPlan:
        """UNION [ALL] / INTERSECT / EXCEPT (reference:
        SetOperationNodeTranslator): sides unify per-column to the common
        super type (cast projections inserted); UNION distinct = UnionNode +
        grouping aggregation; INTERSECT/EXCEPT = whole-row SetOpNode."""
        left = self._plan_body(body.left, outer_scope, ctes)
        right = self._plan_body(body.right, outer_scope, ctes)
        lf, rf = left.scope.fields, right.scope.fields
        if len(lf) != len(rf):
            raise PlanningError(
                f"set operation column counts differ: {len(lf)} vs {len(rf)}")
        types = []
        for i, (a, b) in enumerate(zip(lf, rf)):
            t = T.common_super_type(a.type, b.type)
            if t is None:
                raise PlanningError(
                    f"set operation column {i}: incompatible types {a.type} / {b.type}")
            types.append(t)
        names = [f.name or f"_col{i}" for i, f in enumerate(lf)]
        lnode = _cast_to(left.node, types, names)
        rnode = _cast_to(right.node, types, names)
        if body.op == "union":
            node: P.PlanNode = P.UnionNode(sources_=[lnode, rnode], names=names)
            if not body.all:
                node = P.AggregationNode(
                    node, list(range(len(types))), [], step="single", names=names)
        else:
            if body.all:
                raise PlanningError(f"{body.op.upper()} ALL: not yet supported")
            node = P.SetOpNode(op=body.op, left=lnode, right=rnode)
        fields = [Field(n, t, None) for n, t in zip(names, types)]
        return RelationPlan(node, Scope(fields, outer_scope))

    def _plan_body(self, body, outer_scope, ctes) -> RelationPlan:
        """Plan one side of a set operation (QuerySpec / nested set op /
        Values / parenthesized Query)."""
        if isinstance(body, ast.SetOperation):
            return self._plan_set_operation(body, outer_scope, ctes)
        if isinstance(body, ast.Values):
            return self._plan_values(body, outer_scope)
        if isinstance(body, ast.Query):
            return self.plan_query(body, outer_scope, ctes)
        if isinstance(body, ast.QuerySpec):
            return self.plan_query_spec(
                body, outer_scope, ctes,
                ast.Query(body=body, with_queries=(), order_by=(), limit=None),
            )
        raise PlanningError(f"unsupported set operation operand: {type(body).__name__}")

    def _plan_values(self, body: ast.Values, outer_scope: Optional[Scope]) -> RelationPlan:
        """VALUES rows -> ValuesNode (reference: sql/tree/Values +
        QueryPlanner.planValues). Rows are constant-folded; per-column types
        unify to the common super type."""
        from trino_tpu.data.page import _from_repr
        from trino_tpu.sql.analyzer.expr_analyzer import ExprAnalyzer

        analyzer = ExprAnalyzer(Scope([], outer_scope))
        ir_rows = []
        width = None
        for row in body.rows:
            if width is None:
                width = len(row)
            elif len(row) != width:
                raise PlanningError("VALUES rows have mismatched column counts")
            ir_rows.append([analyzer.analyze(e) for e in row])
        types = []
        for ci in range(width or 0):
            t = T.UNKNOWN
            for r in ir_rows:
                t2 = T.common_super_type(t, r[ci].type)
                if t2 is None:
                    raise PlanningError(
                        f"VALUES column {ci}: incompatible types {t} and {r[ci].type}")
                t = t2
            types.append(t if t != T.UNKNOWN else T.BIGINT)
        py_rows = []
        for r in ir_rows:
            vals = []
            for ci, e in enumerate(r):
                c = _fold_constant(e)
                if c is None:
                    raise PlanningError("VALUES expressions must be constants")
                if c.value is None:
                    vals.append(None)
                elif types[ci].is_varchar or types[ci] == T.BOOLEAN:
                    vals.append(c.value)  # repr == Python value
                else:
                    vals.append(_from_repr(types[ci], _rescale(c, types[ci])))
            py_rows.append(tuple(vals))
        names = [f"_col{i}" for i in range(width or 0)]
        node = P.ValuesNode(types, names, py_rows)
        return RelationPlan(node, Scope([Field(n, t, None) for n, t in zip(names, types)], outer_scope))

    def plan_relation(
        self, rel: ast.Relation, outer_scope: Optional[Scope], ctes: Dict[str, ast.WithQuery]
    ) -> RelationPlan:
        if isinstance(rel, ast.Table):
            name = rel.parts[-1].lower()
            if len(rel.parts) == 1 and name in ctes:
                wq = ctes[name]
                sub = self.plan_query(wq.query, outer_scope, ctes)
                names = (
                    list(wq.column_aliases)
                    if wq.column_aliases
                    else [f.name for f in sub.scope.fields]
                )
                fields = [
                    Field(n, f.type, wq.name) for n, f in zip(names, sub.scope.fields)
                ]
                return RelationPlan(sub.node, Scope(fields, outer_scope))
            mv_plan = self._plan_matview(rel, outer_scope)
            if mv_plan is not None:
                return mv_plan
            return self.plan_table_scan(rel, outer_scope)
        if isinstance(rel, ast.AliasedRelation):
            inner = self.plan_relation(rel.relation, outer_scope, ctes)
            names = (
                list(rel.column_aliases)
                if rel.column_aliases
                else [f.name for f in inner.scope.fields]
            )
            fields = [Field(n, f.type, rel.alias) for n, f in zip(names, inner.scope.fields)]
            return RelationPlan(inner.node, Scope(fields, outer_scope))
        if isinstance(rel, ast.SubqueryRelation):
            sub = self.plan_query(rel.query, outer_scope, ctes)
            fields = [Field(f.name, f.type, None) for f in sub.scope.fields]
            return RelationPlan(sub.node, Scope(fields, outer_scope))
        if isinstance(rel, ast.Join):
            return self.plan_join(rel, outer_scope, ctes)
        if isinstance(rel, ast.Unnest):
            # standalone FROM UNNEST(...): constant arguments, one dummy row
            return self.plan_unnest(
                rel, RelationPlan(P.ValuesNode([], [], [()]), Scope([], outer_scope)),
                None, None, outer_scope,
            )
        if isinstance(rel, ast.TableFunctionCall):
            return self._plan_table_function(rel, outer_scope)
        if isinstance(rel, ast.MatchRecognize):
            return self._plan_match_recognize(rel, outer_scope, ctes)
        raise PlanningError(f"unsupported relation {type(rel).__name__}")

    def _plan_match_recognize(self, rel: "ast.MatchRecognize", outer_scope,
                              ctes) -> RelationPlan:
        """MATCH_RECOGNIZE -> MatchRecognizeNode. Partition/order resolve
        to input channels; DEFINE/MEASURES stay AST for the host matcher
        but are TYPE-checked here by stripping pattern navigation
        (PREV/FIRST/... -> argument, var-qualifiers -> bare columns) and
        analyzing against the input scope — typos fail at plan time."""
        from trino_tpu.sql.routines import _rewrite_node

        inner = self.plan_relation(rel.input, outer_scope, ctes)
        analyzer = ExprAnalyzer(inner.scope)

        def channel(e: ast.Expression, what: str) -> int:
            out = analyzer.analyze(e)
            if not isinstance(out, ir.ColumnRef):
                raise PlanningError(
                    f"MATCH_RECOGNIZE {what} must be an input column")
            return out.index

        part = [channel(e, "PARTITION BY") for e in rel.partition_by]
        order = [(channel(e, "ORDER BY"), asc, None)
                 for e, asc in rel.order_by]
        pattern_vars = {v for v, _ in rel.pattern}
        for v, _ in rel.defines:
            if v not in pattern_vars:
                raise PlanningError(f"DEFINE {v} not in PATTERN")

        def strip(e: ast.Expression) -> ast.Expression:
            def fn(x):
                if isinstance(x, ast.Identifier) and len(x.parts) == 2 \
                        and x.parts[0].lower() in pattern_vars:
                    return ast.Identifier((x.parts[1],))
                if isinstance(x, ast.FunctionCall):
                    n = x.name.lower()
                    if n in ("prev", "next", "first", "last") and x.args:
                        return x.args[0]
                    if n == "classifier":
                        return ast.Literal("string", "X")
                    if n == "match_number":
                        return ast.Literal("number", "1")
                return x

            return _rewrite_node(e, fn)

        measure_types = []
        for e, _name in rel.measures:
            measure_types.append(analyzer.analyze(strip(e)).type)
        for _v, pred in rel.defines:
            analyzer.analyze(strip(pred))  # column/type validation only
        node = P.MatchRecognizeNode(
            source=inner.node, partition_channels=part, sort_channels=order,
            pattern=tuple(rel.pattern), defines=tuple(rel.defines),
            measures=tuple(rel.measures), measure_types=measure_types,
            after_match=rel.after_match,
            input_names=[f.name for f in inner.scope.fields])
        fields = [Field(n, t, None)
                  for n, t in zip(node.output_names, node.output_types)]
        return RelationPlan(node, Scope(fields, outer_scope))

    def _plan_table_function(self, rel: "ast.TableFunctionCall", outer_scope
                             ) -> RelationPlan:
        """TABLE(fn(...)) -> constant relation (reference:
        sql/tree/TableFunctionInvocation; the processor runs at plan time —
        arguments must be constants)."""
        from trino_tpu.exec.table_functions import resolve

        analyzer = ExprAnalyzer(Scope([], outer_scope))

        def const(e):
            c = _fold_constant(analyzer.analyze(e))
            if c is None:
                raise PlanningError(
                    f"table function {rel.name} arguments must be constants")
            return c.value

        args = [const(e) for e in rel.args]
        named = {k: const(v) for k, v in (rel.named_args or {}).items()}
        names, types, rows = resolve(self.session, rel.name, args, named)
        node = P.ValuesNode(list(types), list(names), rows)
        return RelationPlan(
            node, Scope([Field(n, t, rel.name) for n, t in zip(names, types)],
                        outer_scope))

    def plan_unnest(
        self, rel: ast.Unnest, left: RelationPlan, alias, col_aliases, outer_scope
    ) -> RelationPlan:
        """Lateral UNNEST: argument expressions resolve against the columns
        of the preceding FROM items (reference: RelationPlanner.visitUnnest +
        planUnnest in QueryPlanner)."""
        analyzer = ExprAnalyzer(left.scope)
        exprs = [analyzer.analyze(e) for e in rel.exprs]
        for e in exprs:
            if not (e.type.is_array or e.type.is_map):
                raise PlanningError(f"UNNEST argument must be array or map, got {e.type}")
        node = P.UnnestNode(
            source=left.node, unnest_exprs=exprs, ordinality=rel.ordinality
        )
        produced = node.output_types[len(left.node.output_types):]
        default_names = node.output_names[len(left.node.output_names):]
        names = list(col_aliases) if col_aliases else default_names
        if len(names) < len(produced):
            names = names + default_names[len(names):]
        unnest_fields = [
            Field(n, t, alias) for n, t, in zip(names, produced)
        ]
        return RelationPlan(node, Scope(left.scope.fields + unnest_fields, outer_scope))

    def _plan_matview(self, rel: ast.Table, outer_scope: Optional[Scope]
                      ) -> Optional[RelationPlan]:
        """FROM <materialized view name>: expand the registered
        definition like a view (reference: view expansion in
        StatementAnalyzer + getMaterializedView). Always correct —
        freshness is irrelevant to an inline expansion — and the
        expanded plan then flows through the transparent substitution
        pass (trino_tpu/matview/substitute.py), which rewrites it into a
        storage-table scan exactly when the view is fresh. A connector
        table of the same resolved name wins (the registry never
        shadows real tables); plan-time access control on the base
        tables fires inside the expansion for the CURRENT principal."""
        registry = getattr(self.session, "matviews", None)
        if registry is None or registry.empty():
            return None
        parts = [p.lower() for p in rel.parts]
        if len(parts) == 1:
            catalog, schema, name = (self.default_catalog,
                                     self.default_schema, parts[0])
        elif len(parts) == 2:
            catalog, schema, name = self.default_catalog, parts[0], parts[1]
        elif len(parts) == 3:
            catalog, schema, name = parts
        else:
            return None
        mv = registry.get(catalog, schema, name)
        if mv is None:
            return None
        conn = self.catalogs.get(catalog)
        try:
            if conn is not None and conn.get_table(schema, name) is not None:
                return None  # a real table always wins over the registry
        except Exception:  # noqa: BLE001 — metadata probe only
            pass
        expanding = getattr(self, "_mv_expanding", None)
        if expanding is None:
            expanding = self._mv_expanding = set()
        key = (catalog, schema, name)
        if key in expanding:
            raise PlanningError(
                f"materialized view cycle detected at {mv.qualified}")
        stmt = mv.definition
        udfs = getattr(self.session, "udfs", None)
        if udfs:
            from trino_tpu.sql.routines import expand_udfs

            stmt = expand_udfs(stmt, udfs)
        expanding.add(key)
        # the definition's unqualified names keep resolving against the
        # CREATOR's defaults, whatever session expands the view
        saved = (self.default_catalog, self.default_schema)
        self.default_catalog = mv.default_catalog
        self.default_schema = mv.default_schema
        try:
            sub = self.plan_query(stmt, outer_scope, {})
        finally:
            self.default_catalog, self.default_schema = saved
            expanding.discard(key)
        fields = [Field(f.name, f.type, name) for f in sub.scope.fields]
        return RelationPlan(sub.node, Scope(fields, outer_scope))

    def plan_table_scan(self, rel: ast.Table, outer_scope: Optional[Scope]) -> RelationPlan:
        parts = [p.lower() for p in rel.parts]
        if len(parts) == 1:
            catalog, schema, table = self.default_catalog, self.default_schema, parts[0]
        elif len(parts) == 2:
            catalog, schema, table = self.default_catalog, parts[0], parts[1]
        elif len(parts) == 3:
            catalog, schema, table = parts
        else:
            raise PlanningError(f"bad table name {'.'.join(rel.parts)}")
        conn = self.catalogs.get(catalog)
        if conn is None:
            raise PlanningError(f"catalog not found: {catalog}")
        if schema == "information_schema":
            return self._plan_information_schema(catalog, conn, table, outer_scope)
        meta = conn.get_table(schema, table)
        if meta is None and len(parts) == 2 and parts[0] in self.catalogs:
            # single-table-schema convenience: a two-part name whose head
            # is a CATALOG resolves to that catalog's schema-named-like-
            # the-table relation — so ``system.metrics`` reaches
            # system.metrics.metrics without a USE system. Gated on the
            # connector DECLARING the jmx-style one-relation-per-schema
            # convention: a typo'd schema name against an ordinary
            # multi-table catalog must keep erroring, never silently
            # resolve into a different catalog's data
            alt_conn = self.catalogs[parts[0]]
            if getattr(alt_conn, "single_table_schemas", False):
                alt_meta = alt_conn.get_table(parts[1], parts[1])
                if alt_meta is not None:
                    catalog, schema, table = parts[0], parts[1], parts[1]
                    conn, meta = alt_conn, alt_meta
        if meta is None:
            raise PlanningError(f"table not found: {catalog}.{schema}.{table}")
        # authorization seam (reference: AccessControl.checkCanSelectFromColumns
        # called from StatementAnalyzer)
        ac = getattr(self.session, "access_control", None)
        if ac is not None:
            ac.check_can_select(self.session.identity, catalog, schema, table)
        node = P.TableScanNode(
            catalog=catalog,
            schema=schema,
            table=table,
            column_names=[c.name for c in meta.columns],
            column_types=[c.type for c in meta.columns],
        )
        fields = [Field(c.name, c.type, table) for c in meta.columns]
        return RelationPlan(node, Scope(fields, outer_scope))

    def _plan_information_schema(self, catalog: str, conn, table: str,
                                 outer_scope) -> RelationPlan:
        """information_schema views synthesized from connector metadata
        (reference: ``connector/informationschema/`` — schemata, tables,
        columns per catalog). Materialized at plan time as a constant
        relation (metadata scale)."""
        from trino_tpu.server.security import AccessDeniedError

        ac = getattr(self.session, "access_control", None)
        identity = getattr(self.session, "identity", None)

        def visible(s: str, t: str) -> bool:
            """Metadata visibility follows table access (reference:
            information_schema rows are filtered through access control —
            names must not leak to identities that cannot select)."""
            if ac is None:
                return True
            try:
                ac.check_can_select(identity, catalog, s, t)
                return True
            except AccessDeniedError:
                return False

        if table == "schemata":
            cols = [("catalog_name", T.varchar()), ("schema_name", T.varchar())]
            rows = [(catalog, s) for s in conn.list_schemas()]
        elif table == "tables":
            cols = [("table_catalog", T.varchar()), ("table_schema", T.varchar()),
                    ("table_name", T.varchar()), ("table_type", T.varchar())]
            rows = [
                (catalog, s, t, "BASE TABLE")
                for s in conn.list_schemas()
                for t in conn.list_tables(s)
                if visible(s, t)
            ]
        elif table == "columns":
            cols = [("table_catalog", T.varchar()), ("table_schema", T.varchar()),
                    ("table_name", T.varchar()), ("column_name", T.varchar()),
                    ("ordinal_position", T.BIGINT), ("data_type", T.varchar())]
            rows = []
            for s in conn.list_schemas():
                for t in conn.list_tables(s):
                    if not visible(s, t):
                        continue
                    meta = conn.get_table(s, t)
                    if meta is None:
                        continue
                    for i, c in enumerate(meta.columns):
                        rows.append((catalog, s, t, c.name, i + 1, str(c.type)))
        else:
            raise PlanningError(
                f"information_schema has no table {table!r} "
                "(schemata, tables, columns)")
        node = P.ValuesNode([t for _, t in cols], [n for n, _ in cols], rows)
        fields = [Field(n, t, table) for n, t in cols]
        return RelationPlan(node, Scope(fields, outer_scope))

    # ------------------------------------------------- join-order selection
    def _reorder_implicit_joins(self, from_rel, spec, ctes):
        """Reorder a FROM comma-list (a chain of implicit/cross joins) so
        every join has an equi edge when one exists: start from the largest
        relation (the fact), repeatedly append the SMALLEST relation
        connected by a WHERE equality to the relations already joined.

        Reference role: ReorderJoins + DetermineJoinDistributionType in
        miniature — without it, a FROM list like TPC-DS q64's (18 relations
        whose equi predicates don't follow list order) plans Cartesian
        products (a date_dim cross join = 73k x fact rows before the filter
        lands). Name-based and best-effort: relations whose columns can't
        be resolved just keep list order. Skipped for SELECT * (reordering
        would change the star's column order)."""
        if not isinstance(from_rel, ast.Join) or from_rel.join_type not in (
            "cross", "implicit"
        ):
            return from_rel
        if any(isinstance(it.expr, ast.Star) for it in spec.select_items or ()):
            return from_rel

        # flatten the implicit chain
        rels: List = []

        def flatten(r):
            if isinstance(r, ast.Join) and r.join_type in ("cross", "implicit"):
                flatten(r.left)
                flatten(r.right)
            else:
                rels.append(r)

        flatten(from_rel)
        if len(rels) < 3:
            return from_rel
        if any(self._unwrap_unnest(r)[0] is not None for r in rels):
            return from_rel  # UNNEST is lateral: list order is a data dependency
        names, sizes, ndv_fns = [], [], []
        for r in rels:
            n, s, nf = self._relation_columns_and_size(r, ctes)
            names.append(n)
            sizes.append(s)
            ndv_fns.append(nf)

        def owner(ident: ast.Identifier):
            parts = [p.lower() for p in ident.parts]
            if len(parts) >= 2:
                q = parts[-2]
                for i, r in enumerate(rels):
                    if self._relation_alias(r) == q:
                        return i
                return None
            hits = [i for i, cols in enumerate(names) if parts[-1] in cols]
            return hits[0] if len(hits) == 1 else None

        edges = []  # (rel_a, rel_b, col_a, col_b)
        for conj in split_conjuncts(spec.where):
            if (isinstance(conj, ast.Comparison) and conj.op == "="
                    and isinstance(conj.left, ast.Identifier)
                    and isinstance(conj.right, ast.Identifier)):
                a, b = owner(conj.left), owner(conj.right)
                if a is not None and b is not None and a != b:
                    edges.append((a, b, conj.left.parts[-1].lower(),
                                  conj.right.parts[-1].lower()))
        if not edges:
            return from_rel

        def edge_ndv(i, col):
            ndv = ndv_fns[i](col)
            return ndv if ndv else sizes[i]

        def join_estimate(cur_rows, cand, prefix):
            """|prefix ⨝ cand| ≈ cur * |cand| / Π max(ndv_left, ndv_right)
            over the connecting equi edges — the textbook containment
            formula (reference: JoinStatsRule). Chooses the SELECTIVE edge
            (suppkey, ndv 10k) over the exploding one (nationkey, ndv 25)
            where plain smallest-relation-first cannot tell them apart."""
            denom = 1.0
            connected = False
            for a, b, ca, cb in edges:
                if a == cand and b in prefix:
                    denom *= max(edge_ndv(a, ca), edge_ndv(b, cb), 1)
                    connected = True
                elif b == cand and a in prefix:
                    denom *= max(edge_ndv(b, cb), edge_ndv(a, ca), 1)
                    connected = True
            if not connected:
                return cur_rows * sizes[cand], False
            return cur_rows * sizes[cand] / denom, True

        remaining = set(range(len(rels)))
        start = max(remaining, key=lambda i: sizes[i])
        order = [start]
        prefix = {start}
        cur_rows = float(sizes[start])
        remaining.discard(start)
        while remaining:
            scored = [
                (i,) + join_estimate(cur_rows, i, prefix) for i in remaining
            ]
            connected = [s for s in scored if s[2]]
            pool = connected or scored
            nxt, est, _ = min(pool, key=lambda s: (s[1], sizes[s[0]]))
            order.append(nxt)
            prefix.add(nxt)
            cur_rows = max(est, 1.0)
            remaining.discard(nxt)
        if order == list(range(len(rels))):
            return from_rel
        out = rels[order[0]]
        for i in order[1:]:
            out = ast.Join(join_type="implicit", left=out, right=rels[i])
        return out

    def _relation_alias(self, r) -> Optional[str]:
        if isinstance(r, ast.AliasedRelation):
            return r.alias.lower()
        if isinstance(r, ast.Table):
            return r.parts[-1].lower()
        return None

    @staticmethod
    def _no_ndv(_col):
        return None

    def _relation_columns_and_size(self, r, ctes):
        """(column-name set, row estimate, ndv-lookup) for join-order
        attribution; the ndv lookup backs the cost-based edge choice."""
        if isinstance(r, ast.AliasedRelation):
            cols, size, ndv = self._relation_columns_and_size(r.relation, ctes)
            if r.column_aliases:
                cols = {c.lower() for c in r.column_aliases}
            return cols, size, ndv
        if isinstance(r, ast.Table):
            cte = ctes.get(r.parts[-1].lower()) if len(r.parts) == 1 else None
            if cte is not None:
                body = cte.query.body if isinstance(cte.query, ast.Query) else None
                cols = set()
                if isinstance(body, ast.QuerySpec):
                    for it in body.select_items or ():
                        if it.alias:
                            cols.add(it.alias.lower())
                        elif isinstance(it.expr, ast.Identifier):
                            cols.add(it.expr.parts[-1].lower())
                if cte.column_aliases:
                    cols = {c.lower() for c in cte.column_aliases}
                return cols, 100_000, self._no_ndv
            try:
                parts = [p.lower() for p in r.parts]
                if len(parts) == 1:
                    catalog, schema, table = (
                        self.default_catalog, self.default_schema, parts[0])
                elif len(parts) == 2:
                    catalog, schema, table = self.default_catalog, parts[0], parts[1]
                else:
                    catalog, schema, table = parts[:3]
                conn = self.catalogs[catalog]
                meta = conn.get_table(schema, table)
                rows = conn.table_row_count(schema, table) or 10_000

                def ndv(col, _c=conn, _s=schema, _t=table):
                    try:
                        cs = _c.column_stats(_s, _t, col)
                    except Exception:  # noqa: BLE001
                        return None
                    return cs.ndv if cs is not None else None

                return {c.name.lower() for c in meta.columns}, rows, ndv
            except Exception:  # noqa: BLE001 — best-effort attribution
                return set(), 10_000, self._no_ndv
        return set(), 10_000

    @staticmethod
    def _unwrap_unnest(r: ast.Relation):
        """(unnest, alias, col_aliases) if ``r`` is an UNNEST relation."""
        if isinstance(r, ast.Unnest):
            return r, None, None
        if isinstance(r, ast.AliasedRelation) and isinstance(r.relation, ast.Unnest):
            return r.relation, r.alias, r.column_aliases
        return None, None, None

    def plan_join(
        self, rel: ast.Join, outer_scope: Optional[Scope], ctes: Dict[str, ast.WithQuery]
    ) -> RelationPlan:
        left = self.plan_relation(rel.left, outer_scope, ctes)
        un, un_alias, un_cols = self._unwrap_unnest(rel.right)
        if un is not None:
            if rel.join_type not in ("cross", "implicit", "inner"):
                raise PlanningError(f"{rel.join_type} JOIN UNNEST not supported")
            if rel.using:
                raise PlanningError("JOIN UNNEST ... USING not supported")
            out = self.plan_unnest(un, left, un_alias, un_cols, outer_scope)
            if rel.on is not None:
                pred = ExprAnalyzer(out.scope).analyze(rel.on)
                return RelationPlan(P.FilterNode(out.node, pred), out.scope)
            return out
        right = self.plan_relation(rel.right, outer_scope, ctes)
        joint_fields = left.scope.fields + right.scope.fields
        joint_scope = Scope(joint_fields, outer_scope)
        nleft = len(left.scope.fields)

        if rel.join_type in ("cross", "implicit"):
            node = P.JoinNode(
                join_type="inner", left=left.node, right=right.node,
                left_keys=[], right_keys=[], filter=None,
            )
            return RelationPlan(node, joint_scope)

        if rel.using:
            conj = []
            for c in rel.using:
                conj.append(
                    ast.Comparison("=", ast.Identifier((c,)), ast.Identifier((c,)))
                )
            raise PlanningError("JOIN USING: not yet supported")

        analyzer = ExprAnalyzer(joint_scope)
        predicate = analyzer.analyze(rel.on) if rel.on is not None else None
        left_keys, right_keys, residual = self._extract_equi_keys(predicate, nleft)
        if rel.join_type in ("inner", "left"):
            node = P.JoinNode(
                join_type=rel.join_type, left=left.node, right=right.node,
                left_keys=left_keys, right_keys=right_keys,
                filter=combine_conjuncts(residual),
            )
            return RelationPlan(node, joint_scope)
        raise PlanningError(f"{rel.join_type} join: not yet supported")

    @staticmethod
    def _extract_equi_keys(
        predicate: Optional[ir.Expr], nleft: int
    ) -> Tuple[List[int], List[int], List[ir.Expr]]:
        left_keys: List[int] = []
        right_keys: List[int] = []
        residual: List[ir.Expr] = []
        for c in ir_conjuncts(predicate):
            if (
                isinstance(c, ir.Call)
                and c.name == "eq"
                and isinstance(c.args[0], ir.ColumnRef)
                and isinstance(c.args[1], ir.ColumnRef)
            ):
                a, b = c.args[0].index, c.args[1].index
                if a < nleft <= b:
                    left_keys.append(a)
                    right_keys.append(b - nleft)
                    continue
                if b < nleft <= a:
                    left_keys.append(b)
                    right_keys.append(a - nleft)
                    continue
            residual.append(c)
        return left_keys, right_keys, residual

    # ---------------------------------------------------------- query spec
    def plan_query_spec(
        self,
        spec: ast.QuerySpec,
        outer_scope: Optional[Scope],
        ctes: Dict[str, ast.WithQuery],
        query: ast.Query,
    ) -> RelationPlan:
        if spec.grouping_sets is not None:
            return self._plan_grouping_sets(spec, outer_scope, ctes, query)
        # FROM (implicit-join chains reordered by connectivity + size first
        # — see _reorder_implicit_joins)
        if spec.from_ is not None:
            from_rel = self._reorder_implicit_joins(spec.from_, spec, ctes)
            rp = self.plan_relation(from_rel, outer_scope, ctes)
        else:
            rp = RelationPlan(P.ValuesNode([], [], [()]), Scope([], outer_scope))
        node, scope = rp.node, rp.scope

        # WHERE: split into plain conjuncts and subquery predicates
        plain: List[ir.Expr] = []
        for conj in split_conjuncts(spec.where):
            node, scope, handled = self._plan_predicate_subquery(conj, node, scope, ctes)
            if handled:
                continue
            analyzer = ExprAnalyzer(scope)
            e = analyzer.analyze(conj)
            if analyzer.outer_refs:
                raise PlanningError("correlated predicate in unsupported position")
            plain.append(e)
        if plain:
            node = P.FilterNode(node, combine_conjuncts(plain))

        has_aggs = (
            bool(spec.group_by)
            or bool(spec.having)
            or any(find_aggregates(si.expr) for si in spec.select_items if not isinstance(si.expr, ast.Star))
        )
        if has_aggs:
            return self._plan_aggregation(spec, query, node, scope, outer_scope, ctes)

        # plain SELECT (window functions evaluate between FROM/WHERE and the
        # final projection — reference: QueryPlanner.window())
        replacements: Dict[ast.Expression, ir.Expr] = {}
        node = self._plan_windows(spec, query, node, scope, replacements)
        select_irs, names, scope_after = self._plan_select_items(
            spec, scope, ctes, node, replacements
        )
        n_visible = len(select_irs)
        extra_ast_to_ch = self._append_order_by_windows(
            query, spec, select_irs, names, replacements
        )
        self._append_order_by_hidden(
            query, spec, select_irs, names, scope, replacements, extra_ast_to_ch
        )
        node_proj = P.ProjectNode(node, select_irs, names)
        out_fields = [
            Field(n, e.type, None)
            for n, e in zip(names[:n_visible], select_irs[:n_visible])
        ]
        out_scope = Scope(out_fields, outer_scope)
        node = node_proj
        if spec.distinct:
            if extra_ast_to_ch:
                raise PlanningError("DISTINCT with window in ORDER BY only")
            node = P.AggregationNode(
                node, list(range(len(select_irs))), [], step="single", names=names
            )
        if query.order_by:
            # select-item index -> first output channel (Star items expand)
            item_channels = []
            ch = 0
            for si in spec.select_items:
                item_channels.append(ch)
                if isinstance(si.expr, ast.Star):
                    ch += len(
                        scope.channels_of_alias(si.expr.qualifier[0])
                        if si.expr.qualifier
                        else scope.fields
                    )
                else:
                    ch += 1
            node = self._plan_order_by(
                query, node, out_scope, replacements=replacements,
                select_asts=spec.select_items, extra_ast_to_ch=extra_ast_to_ch,
                item_channels=item_channels,
            )
        if query.limit is not None:
            if query.order_by and isinstance(node, P.SortNode):
                node = P.TopNNode(node.source, query.limit, node.sort_channels)
            else:
                node = P.LimitNode(node, query.limit)
        node = self._drop_hidden(node, names, n_visible)
        return RelationPlan(node, out_scope)

    def _plan_grouping_sets(self, spec, outer_scope, ctes, query) -> RelationPlan:
        """GROUPING SETS / ROLLUP / CUBE by expansion: one aggregation per
        set, keys absent from a set become NULL in its select list, results
        concatenate (UNION ALL shape). The reference computes all sets in
        one pass over a GroupIdNode-expanded input (sql/planner/
        QueryPlanner.planGroupingSets); the expansion here re-reads the
        source per set — correct, simpler, and each branch still takes the
        engine's fast single-set path."""
        all_keys = {k for gs in spec.grouping_sets for k in gs}

        def null_missing(e, present):
            if e in all_keys and e not in present:
                return ast.Literal("null", None)
            if isinstance(e, tuple):
                return tuple(null_missing(x, present) for x in e)
            if hasattr(e, "__dataclass_fields__") and isinstance(e, (ast.Expression,)):
                import dataclasses as _dc

                changes = {}
                for f in _dc.fields(e):
                    v = getattr(e, f.name)
                    if isinstance(v, (ast.Expression, tuple)):
                        nv = null_missing(v, present)
                        if nv is not v:
                            changes[f.name] = nv
                return _dc.replace(e, **changes) if changes else e
            return e

        branches = []
        for gs in spec.grouping_sets:
            present = set(gs)
            items = tuple(
                ast.SelectItem(null_missing(it.expr, present), it.alias)
                for it in spec.select_items
            )
            branches.append(
                dataclasses.replace(
                    spec, select_items=items, group_by=tuple(gs),
                    grouping_sets=None,
                )
            )
        # branches must not apply the query's ORDER BY/LIMIT — those wrap
        # the union below
        inner_q = dataclasses.replace(query, order_by=(), limit=None)
        plan = self.plan_query_spec(branches[0], outer_scope, ctes, inner_q)
        nodes = [plan.node]
        for b in branches[1:]:
            nodes.append(self.plan_query_spec(b, outer_scope, ctes, inner_q).node)
        width = len(nodes[0].output_types)
        out_types = []
        for i in range(width):
            t = nodes[0].output_types[i]
            for n in nodes[1:]:
                t2 = T.common_super_type(t, n.output_types[i])
                if t2 is None:
                    raise PlanningError("grouping sets branches: incompatible types")
                t = t2
            out_types.append(t)
        names = [f.name or f"_col{i}" for i, f in enumerate(plan.scope.fields)]
        casted = [_cast_to(n, out_types, names) for n in nodes]
        union = casted[0]
        for n in casted[1:]:
            union = P.UnionNode(sources_=[union, n], names=names)
        fields = [
            Field(f.name, t, None)
            for f, t in zip(plan.scope.fields, out_types)
        ]
        scope = Scope(fields, outer_scope)
        node: P.PlanNode = union
        if query is not None and query.order_by:
            node = self._plan_order_by(
                query, node, scope, replacements={}, select_asts=[])
        if query is not None and query.limit is not None:
            if isinstance(node, P.SortNode):
                node = P.TopNNode(node.source, query.limit, node.sort_channels)
            else:
                node = P.LimitNode(node, query.limit)
        return RelationPlan(node, scope)

    def _plan_select_items(self, spec, scope, ctes, node, replacements=None):
        select_irs: List[ir.Expr] = []
        names: List[str] = []
        for si in spec.select_items:
            if isinstance(si.expr, ast.Star):
                chans = (
                    scope.channels_of_alias(si.expr.qualifier[0])
                    if si.expr.qualifier
                    else range(len(scope.fields))
                )
                for ch in chans:
                    f = scope.fields[ch]
                    select_irs.append(ir.ColumnRef(f.type, ch, f.name or ""))
                    names.append(f.name or f"_col{len(names)}")
                continue
            analyzer = ExprAnalyzer(scope, replacements)
            e = analyzer.analyze(si.expr)
            select_irs.append(e)
            names.append(si.alias or _derive_name(si.expr) or f"_col{len(names)}")
        return select_irs, names, scope

    # -------------------------------------------------------------- windows
    def _plan_windows(self, spec, query, node, scope, replacements):
        """Plan window functions in the SELECT list: append a WindowNode per
        distinct (PARTITION BY, ORDER BY) spec, each adding one output
        channel per call; post-window expressions see the calls through
        ``replacements`` (reference: QueryPlanner.window + WindowNode)."""
        windows: List[ast.WindowFunction] = []
        for si in spec.select_items:
            if not isinstance(si.expr, ast.Star):
                for w in find_windows(si.expr):
                    if w not in windows:
                        windows.append(w)
        for s in query.order_by:
            for w in find_windows(s.expr):
                if w not in windows:
                    windows.append(w)
        if not windows:
            return node
        if spec.where is not None and find_windows(spec.where):
            raise PlanningError("window functions are not allowed in WHERE")

        # group by identical window specification -> one WindowNode each
        def spec_key(w: ast.WindowFunction):
            return (w.partition_by, w.order_by)

        groups: Dict[tuple, List[ast.WindowFunction]] = {}
        for w in windows:
            groups.setdefault(spec_key(w), []).append(w)

        for (pby, oby), ws in groups.items():
            width = len(node.output_types)
            analyzer = ExprAnalyzer(scope, replacements)
            # inputs: identity prefix + partition keys + order keys + args
            extra: List[ir.Expr] = []
            extra_names: List[str] = []

            def add_input(e: ir.Expr, tag: str) -> int:
                if isinstance(e, ir.ColumnRef) and e.index < width:
                    return e.index
                extra.append(e)
                extra_names.append(f"${tag}{len(extra)}")
                return width + len(extra) - 1

            part_ch = [add_input(analyzer.analyze(p), "pk") for p in pby]
            order_ch = [
                (add_input(analyzer.analyze(s.expr), "ok"), s.ascending, s.nulls_first)
                for s in oby
            ]
            calls: List[P.WindowCall] = []
            call_names: List[str] = []
            for w in ws:
                calls.append(self._window_call(w, analyzer, add_input, bool(oby)))
                call_names.append(w.name)
            if extra:
                node = P.ProjectNode.identity_prefix(node, extra, extra_names)
            wnode = P.WindowNode(node, part_ch, order_ch, calls, call_names)
            base = len(node.output_types)
            for i, w in enumerate(ws):
                replacements[w] = ir.ColumnRef(calls[i].output_type, base + i, w.name)
            node = wnode
        return node

    def _window_call(self, w: ast.WindowFunction, analyzer, add_input, has_order) -> P.WindowCall:
        fn = w.name
        if fn not in WINDOW_ONLY_FUNCTIONS and fn not in AGGREGATE_FUNCTIONS:
            raise PlanningError(f"unknown window function {fn}")
        frame, flo, fhi = self._window_frame(w, has_order)

        def call(*args, **kw):
            kw.setdefault("frame", frame)
            kw.setdefault("frame_lo", flo)
            kw.setdefault("frame_hi", fhi)
            return P.WindowCall(*args, **kw)

        if fn in ("rank", "dense_rank", "row_number", "percent_rank",
                  "cume_dist"):
            if not has_order:
                raise PlanningError(f"{fn}() requires window ORDER BY")
            if w.args:
                raise PlanningError(f"{fn}() takes no arguments")
            return call(fn, None, window_result_type(fn, None))
        if fn == "ntile":
            if not has_order:
                raise PlanningError("ntile() requires window ORDER BY")
            if len(w.args) != 1 or not (
                isinstance(w.args[0], ast.Literal) and w.args[0].kind == "number"
            ):
                raise PlanningError("ntile(n) requires a literal bucket count")
            k = int(w.args[0].value)
            if k < 1:
                raise PlanningError("ntile() bucket count must be positive")
            return call(fn, None, window_result_type(fn, None), offset=k)
        if fn == "nth_value":
            if len(w.args) != 2 or not (
                isinstance(w.args[1], ast.Literal) and w.args[1].kind == "number"
            ):
                raise PlanningError("nth_value(value, n) with literal n supported")
            nth = int(w.args[1].value)
            if nth < 1:
                raise PlanningError("nth_value() offset must be positive")
            arg = analyzer.analyze(w.args[0])
            ch = add_input(arg, "a")
            return call(fn, ch, window_result_type(fn, arg.type), offset=nth)
        if fn in ("lag", "lead"):
            if not has_order:
                raise PlanningError(f"{fn}() requires window ORDER BY")
            if not 1 <= len(w.args) <= 2:
                raise PlanningError(f"{fn}(value[, offset]) supported")
            offset = 1
            if len(w.args) == 2:
                off = w.args[1]
                if not (isinstance(off, ast.Literal) and off.kind == "number"):
                    raise PlanningError(f"{fn} offset must be a literal")
                offset = int(off.value)
            arg = analyzer.analyze(w.args[0])
            ch = add_input(arg, "a")
            return call(fn, ch, window_result_type(fn, arg.type), offset=offset)
        if fn in ("first_value", "last_value"):
            if len(w.args) != 1:
                raise PlanningError(f"{fn}(value) expects 1 argument")
            arg = analyzer.analyze(w.args[0])
            ch = add_input(arg, "a")
            return call(fn, ch, window_result_type(fn, arg.type))
        # aggregates over the window
        if w.is_star or (fn == "count" and not w.args):
            return call("count", None, T.BIGINT)
        if len(w.args) != 1:
            raise PlanningError(f"{fn} window aggregate expects 1 argument")
        if fn in ("min", "max") and frame != "partition":
            raise PlanningError(
                f"{fn}() with a window ORDER BY (running frame) is not supported; "
                "omit the ORDER BY for whole-partition min/max"
            )
        arg = analyzer.analyze(w.args[0])
        ch = add_input(arg, "a")
        return call(fn, ch, window_result_type(fn, arg.type))

    def _append_order_by_windows(self, query, spec, select_irs, names, replacements):
        """Windows appearing only in ORDER BY get hidden projection channels
        (dropped again after the sort by _drop_hidden). Returns AST->channel
        for _plan_order_by."""
        extra: Dict[ast.Expression, int] = {}
        select_asts = [
            si.expr for si in spec.select_items if not isinstance(si.expr, ast.Star)
        ]
        for s in query.order_by:
            for w in find_windows(s.expr):
                if w in replacements and w not in select_asts and w not in extra:
                    extra[w] = len(select_irs)
                    select_irs.append(replacements[w])
                    names.append(f"$ob_win{len(extra)}")
        return extra

    def _append_order_by_hidden(
        self, query, spec, select_irs, names, scope, replacements, extra
    ):
        """ORDER BY over source columns/expressions that are not in the
        SELECT list (reference: QueryPlanner's pre-projection of ordering
        symbols): analyze against the PRE-projection scope and append a
        hidden channel, pruned after the sort by _drop_hidden."""
        if spec.distinct:
            # invalid SQL to order by a non-output column under DISTINCT
            # (reference error: "ORDER BY expressions must appear in select
            # list"); leave resolution to _plan_order_by's error path
            return
        select_asts = [
            si.expr for si in spec.select_items if not isinstance(si.expr, ast.Star)
        ]
        aliases = {
            si.alias.lower()
            for si in spec.select_items
            if isinstance(si, ast.SelectItem) and si.alias
        }
        for s in query.order_by:
            e = s.expr
            if e in extra or e in select_asts:
                continue
            if isinstance(e, ast.Identifier) and len(e.parts) == 1 and e.parts[0].lower() in aliases:
                continue
            if isinstance(e, ast.Literal) and e.kind == "number":
                continue  # ordinal
            # does it already name a visible output column?
            star = any(isinstance(si.expr, ast.Star) for si in spec.select_items)
            if star and isinstance(e, ast.Identifier):
                continue  # SELECT * exposes every source column
            try:
                analyzed = ExprAnalyzer(scope, replacements).analyze(e)
            except AnalysisError:
                continue  # let _plan_order_by report the failure
            extra[e] = len(select_irs)
            select_irs.append(analyzed)
            names.append(f"$ob{len(extra)}")

    @staticmethod
    def _drop_hidden(node, names, n_visible):
        if len(names) == n_visible:
            return node
        tys = node.output_types
        return P.ProjectNode(
            node,
            [ir.ColumnRef(tys[i], i, names[i]) for i in range(n_visible)],
            list(names[:n_visible]),
        )

    @staticmethod
    def _window_frame(w: ast.WindowFunction, has_order: bool):
        """-> (frame kind, rows lo offset, rows hi offset). Offsets are
        None except for 'rows_offset' (ROWS frames with numeric bounds —
        reference: window/FrameInfo; RANGE value offsets are not yet
        lowered)."""
        if w.frame is None:
            return ("running" if has_order else "partition"), None, None
        mode, lo, hi = w.frame

        def bound(s, is_lo):
            if s == "unbounded preceding":
                return None if is_lo else PlanningError
            if s == "unbounded following":
                return PlanningError if is_lo else None
            if s == "current row":
                return 0
            n, kind = s.split()
            return -int(n) if kind == "preceding" else int(n)

        if lo == "unbounded preceding" and hi == "unbounded following":
            return "partition", None, None
        if lo == "unbounded preceding" and hi == "current row":
            return ("rows_running" if mode == "rows" else "running"), None, None
        if mode == "rows":
            blo, bhi = bound(lo, True), bound(hi, False)
            if blo is not PlanningError and bhi is not PlanningError:
                if blo is not None and bhi is not None and blo > bhi:
                    raise PlanningError(f"empty window frame {w.frame}")
                return "rows_offset", blo, bhi
        raise PlanningError(f"unsupported window frame {w.frame}")

    # ---------------------------------------------------------- aggregation
    def _plan_aggregation(self, spec, query, node, scope, outer_scope, ctes) -> RelationPlan:
        # Collect aggregate calls from SELECT, HAVING, ORDER BY
        agg_asts: List[ast.FunctionCall] = []
        for si in spec.select_items:
            if not isinstance(si.expr, ast.Star):
                agg_asts.extend(find_aggregates(si.expr))
        if spec.having is not None:
            agg_asts.extend(find_aggregates(spec.having))
        for s in query.order_by:
            agg_asts.extend(find_aggregates(s.expr))
        # dedupe by structural equality
        uniq_aggs: List[ast.FunctionCall] = []
        for a in agg_asts:
            if a not in uniq_aggs:
                uniq_aggs.append(a)

        # group keys: resolve ordinals (GROUP BY 1) to select expressions
        group_asts: List[ast.Expression] = []
        for g in spec.group_by:
            if isinstance(g, ast.Literal) and g.kind == "number":
                idx = int(g.value) - 1
                if not 0 <= idx < len(spec.select_items):
                    raise PlanningError("GROUP BY ordinal out of range")
                group_asts.append(spec.select_items[idx].expr)
            else:
                group_asts.append(g)

        analyzer = ExprAnalyzer(scope, allow_aggregates=True)
        group_irs = [analyzer.analyze(g) for g in group_asts]
        agg_arg_irs: List[Optional[ir.Expr]] = []
        agg_calls: List[P.AggregateCall] = []
        pre_exprs: List[ir.Expr] = list(group_irs)
        pre_names: List[str] = [_derive_name(g) or f"gk{i}" for i, g in enumerate(group_asts)]
        for a in uniq_aggs:
            if a.is_star:
                agg_arg_irs.append(None)
                agg_calls.append(P.AggregateCall("count", None, T.BIGINT))
                continue
            param = None
            arg2 = None
            fname = "bool_and" if a.name == "every" else a.name
            if fname == "approx_percentile":
                if len(a.args) != 2:
                    raise PlanningError("approx_percentile expects 2 arguments")
                p_ir = ExprAnalyzer(scope).analyze(a.args[1])
                param = _constant_fraction(p_ir, "approx_percentile")
            elif fname in P._TWO_ARG_AGGS:
                if len(a.args) != 2:
                    raise PlanningError(f"{fname} expects 2 arguments")
                arg2 = ExprAnalyzer(scope).analyze(a.args[1])
            elif len(a.args) != 1:
                raise PlanningError(f"{a.name} expects 1 argument")
            arg = ExprAnalyzer(scope).analyze(a.args[0])
            out_t = aggregate_result_type(
                fname, arg.type, arg2.type if arg2 is not None else None)
            ch = len(pre_exprs)
            pre_exprs.append(arg)
            pre_names.append(f"aggarg{len(agg_calls)}")
            ch2 = None
            if arg2 is not None:
                ch2 = len(pre_exprs)
                pre_exprs.append(arg2)
                pre_names.append(f"aggarg{len(agg_calls)}b")
            agg_calls.append(
                P.AggregateCall(fname, ch, out_t, distinct=a.distinct,
                                param=param, arg2_channel=ch2))
            agg_arg_irs.append(arg)

        if not pre_exprs:
            # count(*)-only aggregation: carry a constant channel so the page
            # keeps its row count through projection pruning
            pre_exprs = [ir.Constant(T.BIGINT, 0)]
            pre_names = ["$zero"]
        pre_project = P.ProjectNode(node, pre_exprs, pre_names)
        k = len(group_irs)
        agg_names = [pre_names[i] for i in range(k)] + [
            f"agg{i}" for i in range(len(agg_calls))
        ]
        agg_node = P.AggregationNode(
            pre_project, list(range(k)), agg_calls, step="single", names=agg_names
        )

        # scope over aggregation output + replacement map for outer exprs
        agg_fields = [
            Field(scope.fields[g.index].name if isinstance(g, ir.ColumnRef) else None,
                  g.type,
                  scope.fields[g.index].relation_alias if isinstance(g, ir.ColumnRef) else None)
            for g in group_irs
        ] + [Field(None, c.output_type, None) for c in agg_calls]
        agg_scope = Scope(agg_fields, outer_scope)
        replacements: Dict[ast.Expression, ir.Expr] = {}
        for i, g in enumerate(group_asts):
            replacements[g] = ir.ColumnRef(group_irs[i].type, i, pre_names[i])
        for i, a in enumerate(uniq_aggs):
            replacements[a] = ir.ColumnRef(agg_calls[i].output_type, k + i, f"agg{i}")

        node = agg_node
        if spec.having is not None:
            plain_having: List[ir.Expr] = []
            for conj in split_conjuncts(spec.having):
                node, agg_scope, handled = self._plan_predicate_subquery(
                    conj, node, agg_scope, ctes, replacements
                )
                if handled:
                    continue
                plain_having.append(ExprAnalyzer(agg_scope, replacements).analyze(conj))
            if plain_having:
                node = P.FilterNode(node, combine_conjuncts(plain_having))

        # windows over the aggregation output (rank() over (order by sum(x)))
        node = self._plan_windows(spec, query, node, agg_scope, replacements)

        select_irs: List[ir.Expr] = []
        names: List[str] = []
        for si in spec.select_items:
            if isinstance(si.expr, ast.Star):
                raise PlanningError("SELECT * with GROUP BY")
            e = ExprAnalyzer(agg_scope, replacements).analyze(si.expr)
            select_irs.append(e)
            names.append(si.alias or _derive_name(si.expr) or f"_col{len(names)}")
        n_visible = len(select_irs)
        extra_ast_to_ch = self._append_order_by_windows(
            query, spec, select_irs, names, replacements
        )
        proj = P.ProjectNode(node, select_irs, names)
        out_fields = [
            Field(n, e.type, None)
            for n, e in zip(names[:n_visible], select_irs[:n_visible])
        ]
        out_scope = Scope(out_fields, outer_scope)
        node = proj

        if spec.distinct:
            if extra_ast_to_ch:
                raise PlanningError("DISTINCT with window in ORDER BY only")
            node = P.AggregationNode(
                node, list(range(len(select_irs))), [], step="single", names=names
            )
        if query.order_by:
            node = self._plan_order_by(
                query, node, out_scope,
                replacements=replacements, select_asts=spec.select_items,
                inner_scope=agg_scope, extra_ast_to_ch=extra_ast_to_ch,
            )
        if query.limit is not None:
            if isinstance(node, P.SortNode):
                node = P.TopNNode(node.source, query.limit, node.sort_channels)
            else:
                node = P.LimitNode(node, query.limit)
        node = self._drop_hidden(node, names, n_visible)
        return RelationPlan(node, out_scope)

    def _plan_order_by(
        self, query, node, out_scope, replacements, select_asts,
        inner_scope=None, extra_ast_to_ch=None, item_channels=None,
    ):
        """ORDER BY resolves against select aliases/ordinals first, then the
        select expressions themselves (by structure). ``extra_ast_to_ch``
        maps hidden projection channels (windows only in ORDER BY);
        ``item_channels`` maps select-item index -> first output channel
        (they diverge when a Star item expands to several channels)."""
        sort_channels = []
        alias_to_ch = {}
        ast_to_ch = dict(extra_ast_to_ch or {})
        for i, si in enumerate(select_asts):
            pos = item_channels[i] if item_channels is not None else i
            if isinstance(si, ast.SelectItem):
                if si.alias:
                    alias_to_ch[si.alias.lower()] = pos
                if not isinstance(si.expr, ast.Star):
                    ast_to_ch[si.expr] = pos
        for s in query.order_by:
            ch = None
            if isinstance(s.expr, ast.Identifier) and len(s.expr.parts) == 1:
                ch = alias_to_ch.get(s.expr.parts[0].lower())
            if ch is None and isinstance(s.expr, ast.Literal) and s.expr.kind == "number":
                ch = int(s.expr.value) - 1
            if ch is None and s.expr in ast_to_ch:
                ch = ast_to_ch[s.expr]
            if ch is None:
                # resolve as a plain column of the output scope
                try:
                    analyzer = ExprAnalyzer(out_scope, replacements)
                    e = analyzer.analyze(s.expr)
                    if isinstance(e, ir.ColumnRef):
                        ch = e.index
                except AnalysisError:
                    ch = None
            if ch is None:
                raise PlanningError(f"cannot resolve ORDER BY expression {s.expr}")
            sort_channels.append((ch, s.ascending, s.nulls_first))
        return P.SortNode(node, sort_channels)

    # ------------------------------------------------------- subquery preds
    def _plan_predicate_subquery(self, conj, node, scope, ctes, replacements=None):
        """Handle IN (subquery) / EXISTS / scalar-subquery comparisons.
        Returns (node, scope, handled)."""
        replacements = replacements or {}
        if isinstance(conj, ast.InSubquery):
            value_ir = ExprAnalyzer(scope).analyze(conj.value)
            sub = self.plan_query(conj.query, None, ctes)  # uncorrelated only
            if len(sub.scope.fields) != 1:
                raise PlanningError("IN subquery must return one column")
            if not isinstance(value_ir, ir.ColumnRef):
                raise PlanningError("IN subquery over expressions: not yet supported")
            jt = "anti" if conj.negated else "semi"
            new_node = P.JoinNode(
                join_type=jt, left=node, right=sub.node,
                left_keys=[value_ir.index], right_keys=[0],
            )
            return new_node, scope, True
        if isinstance(conj, ast.Exists) or (
            isinstance(conj, ast.Not) and isinstance(conj.value, ast.Exists)
        ):
            negated = isinstance(conj, ast.Not)
            ex: ast.Exists = conj.value if negated else conj
            return self._plan_exists(ex, negated, node, scope, ctes)
        if isinstance(conj, ast.Comparison) and isinstance(conj.right, ast.ScalarSubquery):
            return self._plan_scalar_comparison(conj, node, scope, ctes, replacements)
        return node, scope, False

    def _plan_exists(self, ex: ast.Exists, negated: bool, node, scope, ctes):
        """Correlated EXISTS -> semi/anti join on the equi-correlation keys;
        non-equality correlated conjuncts (e.g. TPC-H Q21's
        ``l2.l_suppkey <> l1.l_suppkey``) become the join's residual filter,
        which the executor evaluates with the expansion kernel
        (reference: TransformExistsApplyToCorrelatedJoin + decorrelation)."""
        q = ex.query
        if q.with_queries or not isinstance(q.body, ast.QuerySpec):
            raise PlanningError("complex EXISTS subquery: not yet supported")
        spec = q.body
        inner_rp = self.plan_relation(spec.from_, scope, ctes) if spec.from_ else None
        if inner_rp is None:
            raise PlanningError("EXISTS without FROM")
        inner_node, inner_scope = inner_rp.node, inner_rp.scope
        nleft = len(scope.fields)
        corr_outer: List[int] = []
        corr_inner: List[int] = []
        inner_filters: List[ir.Expr] = []
        residual: List[ir.Expr] = []  # over joint (outer ++ inner) channels
        for c in split_conjuncts(spec.where):
            analyzer = ExprAnalyzer(inner_scope)
            e = analyzer.analyze(c)
            if not analyzer.outer_refs:
                inner_filters.append(e)
                continue
            if (
                isinstance(e, ir.Call)
                and e.name == "eq"
                and {type(e.args[0]), type(e.args[1])} == {ir.OuterRef, ir.ColumnRef}
            ):
                outer_arg = e.args[0] if isinstance(e.args[0], ir.OuterRef) else e.args[1]
                inner_arg = e.args[0] if isinstance(e.args[1], ir.OuterRef) else e.args[1]
                corr_outer.append(outer_arg.index)
                corr_inner.append(inner_arg.index)
                continue
            residual.append(decorrelate_to_joint(e, nleft))
        if not corr_outer:
            raise PlanningError("uncorrelated EXISTS: not yet supported")
        if inner_filters:
            inner_node = P.FilterNode(inner_node, combine_conjuncts(inner_filters))
        jt = "anti" if negated else "semi"
        if residual:
            # keep the full inner relation: the filter references its columns
            new_node = P.JoinNode(
                join_type=jt, left=node, right=inner_node,
                left_keys=corr_outer, right_keys=corr_inner,
                filter=combine_conjuncts(residual),
            )
            return new_node, scope, True
        # project the inner correlation keys
        proj = P.ProjectNode(
            inner_node,
            [ir.ColumnRef(inner_scope.fields[ch].type, ch) for ch in corr_inner],
            [f"ck{i}" for i in range(len(corr_inner))],
        )
        new_node = P.JoinNode(
            join_type=jt, left=node, right=proj,
            left_keys=corr_outer, right_keys=list(range(len(corr_inner))),
        )
        return new_node, scope, True

    def _plan_scalar_comparison(self, conj: ast.Comparison, node, scope, ctes, replacements=None):
        """x <op> (SELECT agg(...) [FROM ... WHERE outer = inner]) —
        uncorrelated: single-row cross join; correlated equi: group the
        subquery by its correlation keys and equi-join."""
        replacements = replacements or {}
        sub_ast = conj.right.query
        # Try planning as uncorrelated first
        try:
            sub = self.plan_query(sub_ast, None, ctes)
            correlated = False
        except Exception:
            correlated = True
        if not correlated:
            if len(sub.scope.fields) != 1:
                raise PlanningError("scalar subquery must return one column")
            nleft = len(scope.fields)
            f = sub.scope.fields[0]
            join = P.JoinNode(
                join_type="inner", left=node, right=sub.node,
                left_keys=[], right_keys=[], distribution="broadcast",
                singleton=True,
            )
            new_scope = Scope(scope.fields + [Field(None, f.type, "$scalar")], scope.parent)
            left_ir = ExprAnalyzer(new_scope, replacements).analyze(conj.left)
            from trino_tpu.sql.analyzer.expr_analyzer import _COMPARISON_OPS

            pred = ir.Call(
                T.BOOLEAN,
                _COMPARISON_OPS[conj.op],
                (left_ir, ir.ColumnRef(f.type, nleft)),
            )
            filt = P.FilterNode(join, pred)
            # project away the scalar channel
            proj = P.ProjectNode(
                filt,
                [ir.ColumnRef(fl.type, i, fl.name or "") for i, fl in enumerate(scope.fields)],
                [fl.name or f"_c{i}" for i, fl in enumerate(scope.fields)],
            )
            return proj, scope, True
        return self._plan_correlated_scalar(conj, sub_ast, node, scope, ctes, replacements)

    def _plan_correlated_scalar(self, conj, sub_ast: ast.Query, node, scope, ctes, replacements=None):
        replacements = replacements or {}
        """Decorrelate agg scalar subquery: SELECT agg(e) FROM R WHERE
        outer.k = R.j AND rest  ==>  join on k with (SELECT j, agg(e) FROM R
        WHERE rest GROUP BY j)."""
        if not isinstance(sub_ast.body, ast.QuerySpec):
            raise PlanningError("complex correlated scalar subquery")
        spec = sub_ast.body
        if spec.group_by or spec.having or len(spec.select_items) != 1:
            raise PlanningError("correlated scalar subquery must be a bare aggregate")
        agg_calls = find_aggregates(spec.select_items[0].expr)
        if len(agg_calls) == 0:
            raise PlanningError("correlated scalar subquery must aggregate")
        inner_rp = self.plan_relation(spec.from_, scope, ctes)
        inner_node, inner_scope = inner_rp.node, inner_rp.scope
        corr_outer: List[int] = []
        corr_inner: List[int] = []
        inner_filters: List[ast.Expression] = []
        for c in split_conjuncts(spec.where):
            analyzer = ExprAnalyzer(inner_scope)
            e = analyzer.analyze(c)
            if not analyzer.outer_refs:
                inner_filters.append(c)
                continue
            if (
                isinstance(e, ir.Call)
                and e.name == "eq"
                and {type(e.args[0]), type(e.args[1])} == {ir.OuterRef, ir.ColumnRef}
            ):
                outer_arg = e.args[0] if isinstance(e.args[0], ir.OuterRef) else e.args[1]
                inner_arg = e.args[0] if isinstance(e.args[1], ir.OuterRef) else e.args[1]
                corr_outer.append(outer_arg.index)
                corr_inner.append(inner_arg.index)
                continue
            raise PlanningError("correlated scalar subquery predicate too complex")
        if not corr_outer:
            raise PlanningError("scalar subquery planning failed")
        # rebuild: SELECT ck..., expr-over-aggs FROM inner WHERE rest GROUP BY ck
        if inner_filters:
            fil_ir = [ExprAnalyzer(inner_scope).analyze(c) for c in inner_filters]
            inner_node = P.FilterNode(inner_node, combine_conjuncts(fil_ir))
        # pre-project: corr keys + one arg channel per aggregate
        k = len(corr_inner)
        pre_exprs = [
            ir.ColumnRef(inner_scope.fields[ch].type, ch) for ch in corr_inner
        ]
        pre_names = [f"ck{i}" for i in range(k)]
        calls: List[P.AggregateCall] = []
        for a in agg_calls:
            if a.is_star:
                calls.append(P.AggregateCall("count", None, T.BIGINT))
                continue
            if a.name in P._TWO_ARG_AGGS:
                raise PlanningError(
                    f"{a.name} in a correlated scalar subquery: not supported")
            arg_ir = ExprAnalyzer(inner_scope).analyze(a.args[0])
            param = None
            if a.name == "approx_percentile":
                if len(a.args) != 2:
                    raise PlanningError("approx_percentile expects 2 arguments")
                param = _constant_fraction(
                    ExprAnalyzer(inner_scope).analyze(a.args[1]),
                    "approx_percentile")
            calls.append(
                P.AggregateCall(
                    a.name, len(pre_exprs),
                    aggregate_result_type(a.name, arg_ir.type),
                    distinct=a.distinct, param=param,
                )
            )
            pre_exprs.append(arg_ir)
            pre_names.append(f"aggarg{len(calls) - 1}")
        pre = P.ProjectNode(inner_node, pre_exprs, pre_names)
        agg_node = P.AggregationNode(
            pre, list(range(k)), calls, step="single",
            names=pre_names[:k] + [f"aggval{i}" for i in range(len(calls))],
        )
        # the select item may be an expression over the aggregates
        # (e.g. Q17's ``0.2 * avg(l_quantity)``): substitute agg calls with
        # their output channels and project the value alongside the keys
        agg_fields = [Field(None, t, None) for t in agg_node.output_types]
        repl = {
            a: ir.ColumnRef(calls[i].output_type, k + i) for i, a in enumerate(agg_calls)
        }
        value_ir = ExprAnalyzer(Scope(agg_fields, None), repl).analyze(
            spec.select_items[0].expr
        )
        value_proj = P.ProjectNode(
            agg_node,
            [ir.ColumnRef(agg_node.output_types[i], i) for i in range(k)] + [value_ir],
            pre_names[:k] + ["value"],
        )
        nleft = len(scope.fields)
        join = P.JoinNode(
            join_type="inner", left=node, right=value_proj,
            left_keys=corr_outer, right_keys=list(range(k)),
            right_unique=True,
        )
        # predicate: left <op> value
        ext_fields = scope.fields + [Field(None, t, "$sub") for t in value_proj.output_types]
        ext_scope = Scope(ext_fields, scope.parent)
        left_ir = ExprAnalyzer(ext_scope, replacements).analyze(conj.left)
        from trino_tpu.sql.analyzer.expr_analyzer import _COMPARISON_OPS

        pred = ir.Call(
            T.BOOLEAN,
            _COMPARISON_OPS[conj.op],
            (left_ir, ir.ColumnRef(value_ir.type, nleft + k)),
        )
        filt = P.FilterNode(join, pred)
        proj = P.ProjectNode(
            filt,
            [ir.ColumnRef(fl.type, i, fl.name or "") for i, fl in enumerate(scope.fields)],
            [fl.name or f"_c{i}" for i, fl in enumerate(scope.fields)],
        )
        return proj, scope, True


def _derive_name(e: ast.Expression) -> Optional[str]:
    if isinstance(e, ast.Identifier):
        return e.parts[-1]
    if isinstance(e, ast.FunctionCall):
        return e.name
    return None


def _cast_to(node: P.PlanNode, types: List[T.Type], names: List[str]) -> P.PlanNode:
    """Project ``node`` onto exactly ``types`` (identity when it matches)."""
    src_types = node.output_types
    if list(src_types) == list(types):
        return node
    exprs = [
        ir.ColumnRef(st, i) if st == t else ir.Cast(t, ir.ColumnRef(st, i))
        for i, (st, t) in enumerate(zip(src_types, types))
    ]
    return P.ProjectNode(node, exprs, list(names))


def _fold_constant(e: ir.Expr) -> Optional[ir.Constant]:
    """Constant-fold the VALUES-expression subset: literals, unary negate,
    and casts of literals (reference: IrExpressionOptimizer, minimally)."""
    if isinstance(e, ir.Constant):
        return e
    if isinstance(e, ir.Call) and e.name == "negate" and len(e.args) == 1:
        inner = _fold_constant(e.args[0])
        if inner is not None and inner.value is not None:
            return ir.Constant(e.type, -inner.value)
        return inner
    if isinstance(e, ir.Cast):
        inner = _fold_constant(e.value)
        if inner is None or inner.value is None:
            return inner
        # apply the cast NOW (rescale to the target type's repr) so the
        # constant's type tag matches its repr — relabeling without
        # rescaling shifts values by powers of ten
        return ir.Constant(e.type, _rescale(inner, e.type))
    if isinstance(e, ir.Call) and e.name in ("add", "sub", "mul") \
            and len(e.args) == 2 and e.type.is_integer_kind:
        # integer arithmetic over constants (inlined routine bodies reach
        # constant contexts like table-function arguments)
        a = _fold_constant(e.args[0])
        b = _fold_constant(e.args[1])
        if a is None or b is None or a.value is None or b.value is None:
            return None
        op = {"add": lambda x, y: x + y, "sub": lambda x, y: x - y,
              "mul": lambda x, y: x * y}[e.name]
        return ir.Constant(e.type, op(int(a.value), int(b.value)))
    return None


def _constant_fraction(e: ir.Expr, fn: str) -> float:
    """A numeric constant in [0, 1] (e.g. the percentile argument)."""
    if not isinstance(e, ir.Constant) or e.value is None:
        raise PlanningError(f"{fn}: percentile must be a constant")
    v = float(e.value)
    if e.type.is_decimal:
        v /= 10 ** e.type.scale
    if not 0.0 <= v <= 1.0:
        raise PlanningError(f"{fn}: percentile must be between 0 and 1")
    return v


def _rescale(c: ir.Constant, target: T.Type):
    """Convert a constant's storage repr to the target column type's repr
    (int -> scaled decimal, decimal scale change, int -> float,
    timestamp precision change, date -> timestamp)."""
    v = c.value
    if v is None:
        return None
    if isinstance(target, T.TimestampType):
        # unit counts rescale like decimal scales; DATE promotes through
        # UTC midnight
        if c.type == T.DATE:
            return int(v) * 86_400 * 10**target.precision
        assert isinstance(c.type, T.TimestampType), c.type
        dp = target.precision - c.type.precision
        return int(v) * 10**dp if dp >= 0 else int(v) // 10**(-dp)
    if target.is_decimal:
        if c.type.is_floating or isinstance(v, float):
            # scale BEFORE integer conversion, half away from zero
            # (int(1.5) * 10**s would truncate the fraction entirely)
            scaled = float(v) * (10 ** target.scale)
            q = int(abs(scaled) + 0.5)
            return q if scaled >= 0 else -q
        src_scale = c.type.scale if c.type.is_decimal else 0
        if target.scale >= src_scale:
            return int(v) * (10 ** (target.scale - src_scale))
        # narrowing: round half away from zero, the reference's CAST
        # semantics (Int128Math.rescale / DecimalOperators)
        p = 10 ** (src_scale - target.scale)
        iv = int(v)
        q, r = divmod(abs(iv), p)
        q += 1 if 2 * r >= p else 0
        return q if iv >= 0 else -q
    if target.is_floating and not isinstance(v, float):
        scale = c.type.scale if c.type.is_decimal else 0
        return float(v) / (10 ** scale)
    if c.type.is_decimal and not target.is_decimal:
        # integer target: unscale with half-away-from-zero rounding
        # (reference: DecimalCasts round, not truncate)
        p = 10 ** c.type.scale
        iv = int(v)
        q, r = divmod(abs(iv), p)
        q += 1 if 2 * r >= p else 0
        return q if iv >= 0 else -q
    return v
