"""Plan-IR sanity checker: fail loudly at plan time, not wrongly at run time.

Reference: ``sql/planner/sanity/PlanSanityChecker.java`` — Trino interposes
a validator between every optimizer stage (ValidateDependenciesChecker,
NoDuplicatePlanNodeIdsChecker, TypeValidator, ...) so a bad rewrite raises
at plan time instead of corrupting results at execution time. Our plans are
*channel-positional* (sql/planner/plan.py): every expression indexes its
source's output channels by position, so a rule that misindexes a channel
silently reads the wrong column. ``validate_plan`` walks any PlanNode tree
and enforces the invariants the executor assumes:

- ``len(output_types) == len(output_names)`` on every node (``arity``);
- every ``ir.Expr`` channel reference within the source's arity
  (``channel-range``) and type-consistent with the channel it names
  (``channel-type``); no ``OuterRef`` survives planning
  (``unresolved-outer-ref``);
- join/aggregate/window/sort/exchange key channels in range
  (``key-range``), join key lists the same length (``key-arity``);
- boolean positions (filter predicates, join filters) actually typed
  BOOLEAN (``predicate-type``);
- the tree is a tree — no node object reachable twice (``tree-sharing``);
- UNION branches channel-aligned (``union-alignment``);
- fragment-level (``validate_fragments``): every ``RemoteSourceNode.types``
  matches the producing fragment's ``output_types``
  (``stale-remote-source``), producers exist (``unknown-fragment``),
  fragment ids are unique (``duplicate-fragment-id``), and the fragment
  DAG is acyclic (``fragment-cycle``).

Failures raise :class:`PlanSanityError` naming the node, the violated
invariant, and the optimizer phase that produced the plan, and increment
``trino_tpu_plan_validation_failures_total``. Wired after initial planning,
after each named pass in ``optimizer.optimize``, after ``fragment_plan``,
and after every adaptive re-plan (``adaptive/replanner.py``) — gated by the
``plan_validation`` session property, which defaults to ON under pytest.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from trino_tpu import types as T
from trino_tpu.sql import ir
from trino_tpu.sql.planner import plan as P


class PlanSanityError(ValueError):
    """A plan invariant does not hold. Names the failing node, the
    invariant, and the phase (the pass that produced the plan) so the
    offending rewrite is identified without bisection."""

    def __init__(self, node: P.PlanNode, invariant: str, phase: str,
                 detail: str):
        self.node_type = type(node).__name__
        self.node_id = node.id
        self.invariant = invariant
        self.phase = phase
        self.detail = detail
        super().__init__(
            f"plan sanity [{invariant}] at {self.node_type}#{self.node_id} "
            f"after {phase}: {detail}")


def _fail(node: P.PlanNode, invariant: str, phase: str, detail: str):
    from trino_tpu.obs import metrics as M

    M.PLAN_VALIDATION_FAILURES.inc(1, phase.split(":", 1)[0])
    raise PlanSanityError(node, invariant, phase, detail)


# ---------------------------------------------------------------- gating


def validation_enabled(session) -> bool:
    """The ``plan_validation`` session property; its None default means
    AUTO — on under pytest (every test run validates every plan), off in
    production paths unless explicitly enabled."""
    props = getattr(session, "properties", None) or {}
    val = props.get("plan_validation")
    if val is None:
        return "PYTEST_CURRENT_TEST" in os.environ
    if isinstance(val, str):  # wire-protocol header strings
        return val.lower() not in ("false", "0", "no")
    return bool(val)


def checker(session):
    """A ``check(node, phase)`` callable for pass pipelines — a no-op when
    validation is off, so call sites stay one line per pass."""
    if not validation_enabled(session):
        return lambda node, phase: None
    return lambda node, phase: validate_plan(node, phase=phase)


# ------------------------------------------------------------ tree walk


def validate_plan(root: P.PlanNode, phase: str = "unknown",
                  _seen: Optional[Dict[int, P.PlanNode]] = None) -> None:
    """Validate one plan tree. ``_seen`` (object id -> node) is threaded by
    ``validate_fragments`` so node sharing is also caught ACROSS fragment
    roots (a subtree may live in exactly one fragment)."""
    seen: Dict[int, P.PlanNode] = _seen if _seen is not None else {}
    stack = [root]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            _fail(node, "tree-sharing", phase,
                  "node reachable through more than one parent — the plan "
                  "must be a tree (rewrites must copy, not alias)")
        seen[id(node)] = node
        _validate_node(node, phase)
        stack.extend(node.sources)


def _validate_node(node: P.PlanNode, phase: str) -> None:
    out_types = node.output_types
    out_names = node.output_names
    if len(out_types) != len(out_names):
        _fail(node, "arity", phase,
              f"{len(out_types)} output_types vs {len(out_names)} "
              f"output_names")
    kind = type(node).__name__
    fn = _NODE_CHECKS.get(kind)
    if fn is not None:
        fn(node, phase)


def _check_channel(node: P.PlanNode, ch, src_types: Sequence, phase: str,
                   what: str) -> None:
    if not isinstance(ch, int) or not 0 <= ch < len(src_types):
        _fail(node, "key-range", phase,
              f"{what} channel {ch!r} out of range for source arity "
              f"{len(src_types)}")


def _check_expr(node: P.PlanNode, e: ir.Expr, src_types: Sequence,
                phase: str, what: str) -> None:
    """Every ColumnRef in range and type-consistent; Lambda bodies are
    element-scoped (their refs name lambda parameters) and are skipped,
    matching ir.referenced_channels."""
    if e is None:
        _fail(node, "missing-expr", phase, f"{what} is None")
    stack = [e]
    while stack:
        x = stack.pop()
        if isinstance(x, ir.Lambda):
            continue
        if isinstance(x, ir.OuterRef):
            _fail(node, "unresolved-outer-ref", phase,
                  f"{what} still holds {x!r} — decorrelation must rewrite "
                  "outer references into join criteria before execution")
        if isinstance(x, ir.ColumnRef):
            if not 0 <= x.index < len(src_types):
                _fail(node, "channel-range", phase,
                      f"{what} references channel {x.index} but the source "
                      f"has {len(src_types)} channels")
            if x.type != src_types[x.index]:
                _fail(node, "channel-type", phase,
                      f"{what} reads channel {x.index} as {x.type} but the "
                      f"source produces {src_types[x.index]}")
        stack.extend(x.children())


# --------------------------------------------------------- node checks


def _check_filter(node: P.FilterNode, phase: str) -> None:
    src = node.source.output_types
    _check_expr(node, node.predicate, src, phase, "predicate")
    if node.predicate.type != T.BOOLEAN:
        _fail(node, "predicate-type", phase,
              f"filter predicate typed {node.predicate.type}, not boolean")


def _check_project(node: P.ProjectNode, phase: str) -> None:
    if len(node.expressions) != len(node.names):
        _fail(node, "arity", phase,
              f"{len(node.expressions)} expressions vs {len(node.names)} "
              "names")
    src = node.source.output_types
    for i, e in enumerate(node.expressions):
        _check_expr(node, e, src, phase, f"expression {i}")


def _check_aggregation(node: P.AggregationNode, phase: str) -> None:
    src = node.source.output_types
    for c in node.group_channels:
        _check_channel(node, c, src, phase, "group")
    if node.step == "final":
        # final-step aggregates keep their ORIGINAL arg channels; the
        # executor slices the exchanged state columns positionally
        # (aggregate_final), so range checks against the remote source's
        # state layout would be both wrong and meaningless
        return
    for a in node.aggregates:
        if a.arg_channel is not None:
            _check_channel(node, a.arg_channel, src, phase,
                           f"aggregate {a.function} arg")
        if a.arg2_channel is not None:
            _check_channel(node, a.arg2_channel, src, phase,
                           f"aggregate {a.function} arg2")


def _check_join(node: P.JoinNode, phase: str) -> None:
    if len(node.left_keys or ()) != len(node.right_keys or ()):
        _fail(node, "key-arity", phase,
              f"{len(node.left_keys or ())} left keys vs "
              f"{len(node.right_keys or ())} right keys")
    for c in node.left_keys or ():
        _check_channel(node, c, node.left.output_types, phase, "left key")
    for c in node.right_keys or ():
        _check_channel(node, c, node.right.output_types, phase, "right key")
    if node.filter is not None:
        # the join filter evaluates over left ++ right channels (also for
        # semi/anti: semi_join_filtered expands matches before reducing)
        joint = node.left.output_types + node.right.output_types
        _check_expr(node, node.filter, joint, phase, "join filter")
        if node.filter.type != T.BOOLEAN:
            _fail(node, "predicate-type", phase,
                  f"join filter typed {node.filter.type}, not boolean")
    for i in node.dyn_filter_keys or ():
        if not 0 <= i < len(node.left_keys or ()):
            _fail(node, "key-range", phase,
                  f"dyn_filter_keys index {i} out of range for "
                  f"{len(node.left_keys or ())} join keys")


def _check_window(node: P.WindowNode, phase: str) -> None:
    src = node.source.output_types
    for c in node.partition_channels or ():
        _check_channel(node, c, src, phase, "partition")
    for c, _asc, _nf in node.order_channels or ():
        _check_channel(node, c, src, phase, "order")
    for call in node.calls:
        if call.arg_channel is not None:
            _check_channel(node, call.arg_channel, src, phase,
                           f"window {call.function} arg")
    if len(node.names or ()) != len(node.calls):
        _fail(node, "arity", phase,
              f"{len(node.calls)} window calls vs "
              f"{len(node.names or ())} appended names")


def _check_sorted(node, phase: str) -> None:
    src = node.source.output_types
    for c, _asc, _nf in node.sort_channels or ():
        _check_channel(node, c, src, phase, "sort")


def _check_exchange(node: P.ExchangeNode, phase: str) -> None:
    src = node.source.output_types
    for c in node.partition_channels or ():
        _check_channel(node, c, src, phase, "partition")


def _check_union(node: P.UnionNode, phase: str) -> None:
    width = len(node.sources_[0].output_types)
    for i, s in enumerate(node.sources_):
        st = s.output_types
        if len(st) != width:
            _fail(node, "union-alignment", phase,
                  f"branch {i} has {len(st)} channels, branch 0 has "
                  f"{width} — UNION ALL is positional")
        if st != node.sources_[0].output_types:
            _fail(node, "union-alignment", phase,
                  f"branch {i} types {st} differ from branch 0 "
                  f"{node.sources_[0].output_types}")


def _check_setop(node: P.SetOpNode, phase: str) -> None:
    lt, rt = node.left.output_types, node.right.output_types
    if len(lt) != len(rt):
        _fail(node, "union-alignment", phase,
              f"left has {len(lt)} channels, right has {len(rt)} — "
              "set operations are whole-row positional")


def _check_unnest(node: P.UnnestNode, phase: str) -> None:
    src = node.source.output_types
    for c in node.replicate_channels or ():
        _check_channel(node, c, src, phase, "replicate")
    for i, e in enumerate(node.unnest_exprs):
        _check_expr(node, e, src, phase, f"unnest expression {i}")
        if not isinstance(e.type, (T.ArrayType, T.MapType)):
            _fail(node, "predicate-type", phase,
                  f"unnest expression {i} typed {e.type}, not array/map")


def _check_values(node: P.ValuesNode, phase: str) -> None:
    width = len(node.types or ())
    for i, row in enumerate(node.rows or ()):
        if len(row) != width:
            _fail(node, "arity", phase,
                  f"row {i} has {len(row)} values for {width} columns")


def _check_scan(node: P.TableScanNode, phase: str) -> None:
    if len(node.column_names) != len(set(node.column_names)):
        _fail(node, "arity", phase,
              f"duplicate scan columns: {node.column_names}")


def _check_match_recognize(node: P.MatchRecognizeNode, phase: str) -> None:
    src = node.source.output_types
    for c in node.partition_channels or ():
        _check_channel(node, c, src, phase, "partition")
    for c, _asc, _nf in node.sort_channels or ():
        _check_channel(node, c, src, phase, "sort")
    if len(node.measure_types or ()) != len(node.measures or ()):
        _fail(node, "arity", phase,
              f"{len(node.measures or ())} measures vs "
              f"{len(node.measure_types or ())} measure types")


def _check_remote_source(node, phase: str) -> None:
    if node.types is None or node.names is None:
        _fail(node, "arity", phase, "RemoteSourceNode without types/names")


_NODE_CHECKS = {
    "FilterNode": _check_filter,
    "ProjectNode": _check_project,
    "AggregationNode": _check_aggregation,
    "JoinNode": _check_join,
    "WindowNode": _check_window,
    "SortNode": _check_sorted,
    "TopNNode": _check_sorted,
    "ExchangeNode": _check_exchange,
    "UnionNode": _check_union,
    "SetOpNode": _check_setop,
    "UnnestNode": _check_unnest,
    "ValuesNode": _check_values,
    "TableScanNode": _check_scan,
    "MatchRecognizeNode": _check_match_recognize,
    "RemoteSourceNode": _check_remote_source,
}


# ------------------------------------------------------------- fragments


def validate_fragments(fragments: List, phase: str = "fragmentation") -> None:
    """Fragment-level invariants over the whole fragment list: per-root
    tree validation (with sharing caught across fragments), unique ids,
    RemoteSourceNode.types consistency with the producing fragment, and
    fragment-DAG acyclicity."""
    from trino_tpu.sql.planner.fragmenter import RemoteSourceNode

    by_id: Dict[int, object] = {}
    for f in fragments:
        if f.id in by_id:
            _fail(f.root, "duplicate-fragment-id", phase,
                  f"fragment id {f.id} appears more than once")
        by_id[f.id] = f
    seen: Dict[int, P.PlanNode] = {}
    edges: Dict[int, List[int]] = {}
    for f in fragments:
        validate_plan(f.root, phase=phase, _seen=seen)
        deps = []
        for node in P.walk_plan(f.root):
            if not isinstance(node, RemoteSourceNode):
                continue
            producer = by_id.get(node.fragment_id)
            if producer is None:
                _fail(node, "unknown-fragment", phase,
                      f"consumes fragment {node.fragment_id}, which does "
                      "not exist")
            if list(node.types) != producer.root.output_types:
                _fail(node, "stale-remote-source", phase,
                      f"declares types {node.types} but fragment "
                      f"{node.fragment_id} produces "
                      f"{producer.root.output_types}")
            deps.append(node.fragment_id)
        edges[f.id] = deps
    # acyclicity: iterative DFS with a WHITE/GRAY/BLACK coloring
    color: Dict[int, int] = {}
    for start in edges:
        if color.get(start):
            continue
        stack = [(start, iter(edges.get(start, ())))]
        color[start] = 1
        while stack:
            fid, it = stack[-1]
            nxt = next(it, None)
            if nxt is None:
                color[fid] = 2
                stack.pop()
                continue
            c = color.get(nxt, 0)
            if c == 1:
                _fail(by_id[nxt].root, "fragment-cycle", phase,
                      f"fragment {nxt} reachable from itself through the "
                      "exchange graph")
            if c == 0:
                color[nxt] = 1
                stack.append((nxt, iter(edges.get(nxt, ()))))


def validate_adapted(frag, new_fragments: List, by_id: Dict[int, object],
                     phase: str) -> None:
    """Validation entry point for the adaptive re-planner: validate the
    full post-rewrite fragment graph (the adapted consumer, the new
    producers, and everything else still registered) so a bad runtime
    rewrite is caught BEFORE any task is created from it."""
    frags = dict(by_id)
    frags[frag.id] = frag
    for f in new_fragments:
        frags[f.id] = f
    validate_fragments(list(frags.values()), phase=phase)
