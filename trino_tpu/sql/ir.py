"""Post-analysis expression IR.

Reference: ``core/trino-main/.../sql/ir/`` — Trino keeps a small rowful
expression IR distinct from the parser AST (Call, Case, Cast, Comparison,
Constant, Reference, Logical, ...). Ours mirrors that scope; analysis resolves
parser AST names into ``ColumnRef`` channel indices and all operators into
``Call`` by canonical function name. The IR lowers to jax in
``trino_tpu.ops.expr_lower`` (the role played by
``sql/gen/ExpressionCompiler.java`` + ``PageFunctionCompiler.java`` in the
reference — there bytecode, here traced XLA).
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

from trino_tpu import types as T


class Expr:
    type: T.Type

    def children(self) -> Sequence["Expr"]:
        return ()


@dataclasses.dataclass(frozen=True)
class Constant(Expr):
    """A literal. ``value`` is a Python value (int/float/bool/str/None).

    Dates are epoch days (int), decimals scaled ints, varchar a Python str
    (encoded to dictionary codes at lowering time, when the input columns'
    dictionaries are known).
    """

    type: T.Type
    value: Any

    def __repr__(self):
        return f"Const({self.value!r}:{self.type})"


@dataclasses.dataclass(frozen=True)
class ColumnRef(Expr):
    """Reference to channel ``index`` of the operator's input page."""

    type: T.Type
    index: int
    name: str = ""  # debug only

    def __repr__(self):
        return f"#{self.index}:{self.name or self.type}"


@dataclasses.dataclass(frozen=True)
class Lambda(Expr):
    """A lambda argument of a higher-order array function (reference:
    sql/ir LambdaExpression). ``body`` is analyzed in an element scope where
    ColumnRef(0..n_params-1) are the lambda parameters; the lowering
    evaluates it over the FLATTENED child column(s)."""

    type: T.Type  # body's type
    body: "Expr" = None
    n_params: int = 1

    def children(self):
        return (self.body,)

    def __repr__(self):
        return f"Lambda({self.body!r})"


@dataclasses.dataclass(frozen=True)
class Parameter(Expr):
    """A prepared-statement parameter placeholder (reference: sql/ir
    Constant's role in planner/ParameterRewriter, kept SYMBOLIC here).

    Carries the type inferred from the first EXECUTE's binding so the
    whole analyzer/optimizer pipeline type-checks normally, but no
    optimizer pass treats it as a constant — the value must not bake into
    the cached plan (no constant folding, no pushdown into scan
    constraints). ``server/prepared.bind_plan_parameters`` substitutes a
    ``Constant`` per EXECUTE; an unbound parameter reaching the executor's
    lowering fails loudly (expr_lower has no case for it, by design)."""

    type: T.Type
    index: int

    def __repr__(self):
        return f"?{self.index}:{self.type}"


@dataclasses.dataclass(frozen=True)
class OuterRef(Expr):
    """Correlated reference to channel ``index`` of the OUTER query's scope.

    Appears only transiently during subquery planning; decorrelation
    (reference: sql/planner/iterative/rule/ correlated-subquery rules)
    rewrites it into join criteria before execution.
    """

    type: T.Type
    index: int
    name: str = ""

    def __repr__(self):
        return f"outer#{self.index}:{self.name or self.type}"


@dataclasses.dataclass(frozen=True)
class Call(Expr):
    """Scalar function / operator call by canonical name.

    Canonical names: add sub mul div mod negate, eq ne lt le gt ge,
    and or not, is_null, between, in_list, like, coalesce, nullif,
    extract_year extract_month extract_day, date_add_months, abs, ...
    (registry: trino_tpu.ops.functions.FUNCTIONS).
    """

    type: T.Type
    name: str
    args: Tuple[Expr, ...]

    def children(self):
        return self.args

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


@dataclasses.dataclass(frozen=True)
class Case(Expr):
    """CASE WHEN c1 THEN v1 ... ELSE d END (searched form)."""

    type: T.Type
    whens: Tuple[Tuple[Expr, Expr], ...]
    default: Optional[Expr]

    def children(self):
        out: List[Expr] = []
        for c, v in self.whens:
            out += [c, v]
        if self.default is not None:
            out.append(self.default)
        return out

    def __repr__(self):
        parts = " ".join(f"WHEN {c!r} THEN {v!r}" for c, v in self.whens)
        return f"CASE {parts} ELSE {self.default!r} END"


@dataclasses.dataclass(frozen=True)
class Cast(Expr):
    type: T.Type
    value: Expr

    def children(self):
        return (self.value,)

    def __repr__(self):
        return f"cast({self.value!r} as {self.type})"


def walk(e: Expr):
    yield e
    for c in e.children():
        yield from walk(c)


def referenced_channels(e: Expr) -> List[int]:
    """Input channels an expression reads. Lambda bodies are element-scoped
    — their ColumnRefs name lambda parameters, not input channels — so the
    walk does not descend into them."""
    out = set()

    def visit(x: Expr):
        if isinstance(x, Lambda):
            return
        if isinstance(x, ColumnRef):
            out.add(x.index)
        for c in x.children():
            visit(c)

    visit(e)
    return sorted(out)


def remap_channels(e: Expr, mapping: dict) -> Expr:
    """Rewrite ColumnRef indices through ``mapping`` (for projection
    pushdown). Lambda bodies are element-scoped and pass through unchanged."""
    if isinstance(e, Lambda):
        return e
    if isinstance(e, ColumnRef):
        return ColumnRef(e.type, mapping[e.index], e.name)
    if isinstance(e, Call):
        return Call(e.type, e.name, tuple(remap_channels(a, mapping) for a in e.args))
    if isinstance(e, Case):
        return Case(
            e.type,
            tuple((remap_channels(c, mapping), remap_channels(v, mapping)) for c, v in e.whens),
            remap_channels(e.default, mapping) if e.default is not None else None,
        )
    if isinstance(e, Cast):
        return Cast(e.type, remap_channels(e.value, mapping))
    return e
