"""Device-resident table cache (warm-HBM buffer pool).

Public surface: the process-global :data:`DEVICE_CACHE` pool, the key
constructors consulted by the three staging tiers (eager/compiled scans
in ``exec/executor.py``, worker fragment scans in ``server/task.py``,
SPMD sharded staging in ``parallel/spmd.py``), and the device-memory
capacity probe the worker announce payload ships to the coordinator's
``ClusterMemoryManager``.
"""
from trino_tpu.devcache.cache import (
    DEVICE_CACHE, CacheEntry, CacheKey, DeviceTableCache,
    device_memory_bytes, instance_token)
from trino_tpu.devcache.keys import (
    admit_budget, cache_enabled, cached_build, cached_stage, scan_cache_key,
    scan_signature, splits_shard)

__all__ = [
    "DEVICE_CACHE", "CacheEntry", "CacheKey", "DeviceTableCache",
    "admit_budget", "cache_enabled", "cached_build", "cached_stage",
    "device_memory_bytes", "instance_token", "scan_cache_key",
    "scan_signature", "splits_shard",
]
