"""Device-resident table cache (warm-HBM buffer pool) + host-RAM tier.

Public surface: the process-global :data:`DEVICE_CACHE` pool and the
:data:`HOST_CACHE` tier under it (``devcache/hostcache.py``: decoded
per-split numpy column sets, same key/flight/invalidation semantics), the
key constructors consulted by the three staging tiers (eager/compiled
scans in ``exec/executor.py``, worker fragment scans in
``server/task.py``, SPMD sharded staging in ``parallel/spmd.py``), and
the device-memory capacity probe the worker announce payload ships to the
coordinator's ``ClusterMemoryManager``.
"""
from trino_tpu.devcache.cache import (
    DEVICE_CACHE, CacheEntry, CacheKey, DeviceTableCache,
    device_memory_bytes, instance_token)
from trino_tpu.devcache.hostcache import (
    HOST_CACHE, HostColumnCache, host_admit_budget, shed_revocable,
    split_data_bytes)
from trino_tpu.devcache.keys import (
    admit_budget, cache_enabled, cached_build, cached_stage,
    host_split_keys, scan_cache_key, scan_signature, splits_shard)

__all__ = [
    "DEVICE_CACHE", "CacheEntry", "CacheKey", "DeviceTableCache",
    "HOST_CACHE", "HostColumnCache", "admit_budget", "cache_enabled",
    "cached_build", "cached_stage", "device_memory_bytes",
    "host_admit_budget", "host_split_keys", "instance_token",
    "scan_cache_key", "scan_signature", "shed_revocable",
    "split_data_bytes", "splits_shard",
]
