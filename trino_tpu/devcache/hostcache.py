"""Host-RAM columnar page cache: the staging tier UNDER the warm-HBM pool.

Reference role: the buffer-pool tier hierarchy every disk engine has
(HBM ≈ buffer pool, host RAM ≈ OS page cache), rebuilt for the staged
execution model. The unit of caching is one SPLIT's decoded numpy column
set — the output of ``connector.scan`` + host-applied domain pruning,
BEFORE dictionary-merge/narrowing/transfer — keyed by the same
``(catalog, schema, table, data_version, signature, shard)`` identity the
device cache uses (trino_tpu/devcache/keys.py), with the split's own
boundary digest as the shard component. Because the key is per split, the
host tier survives re-shardings the HBM tier cannot: an HBM eviction, a
mesh-width change, or a different worker split grouping re-stages from
host memory (concat + transfer only) instead of re-running the connector
scan and decode — the dominant cold-path cost BENCH_r05 measured
(q3_sf10: 22.7 s staging vs 1.17 s device execute).

Semantics are inherited wholesale from :class:`DeviceTableCache`:
byte-budgeted LRU, SINGLE-FLIGHT admission (concurrent stagings of the
same split run one scan), and data_version invalidation (any
INSERT/UPDATE/DELETE/DROP/CTAS moves the version; stale same-table
entries are reclaimed on the next lookup). Only the metric hooks and the
budget source differ.

Memory discipline: the host tier is the SECOND revocable tier — under
node pressure it sheds BEFORE the HBM tier does (:func:`shed_revocable`):
losing a host page costs one transfer to rebuild; losing a warm HBM page
costs the whole scan→decode→transfer path when the host tier is gone too.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from trino_tpu.devcache.cache import DeviceTableCache
from trino_tpu.obs import metrics as M

# fallback server-wide budget (env TRINO_TPU_HOST_CACHE_BYTES overrides):
# host RAM is plentiful relative to HBM, but the cache must never crowd
# out the engine's own working set
DEFAULT_HOST_CACHE_BYTES = 1 << 30


def _default_budget() -> int:
    env = os.environ.get("TRINO_TPU_HOST_CACHE_BYTES")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    return DEFAULT_HOST_CACHE_BYTES


def column_data_bytes(cd) -> int:
    """Approximate host bytes of one decoded ColumnData (arrays exact,
    dictionary vocab estimated) — the host cache's accounting unit."""
    n = int(np.asarray(cd.values).nbytes)
    if cd.nulls is not None:
        n += int(np.asarray(cd.nulls).nbytes)
    if getattr(cd, "hi", None) is not None:
        n += int(np.asarray(cd.hi).nbytes)
    d = getattr(cd, "dictionary", None)
    if d is not None:
        n += sum(len(v) + 8 for v in d.values)
    for k in getattr(cd, "children", None) or ():
        n += column_data_bytes(k)
    return n


def split_data_bytes(data: dict) -> int:
    """Host bytes of one split's decoded column set."""
    return sum(column_data_bytes(cd) for cd in data.values())


class HostColumnCache(DeviceTableCache):
    """The host-RAM tier: same machinery, host metrics, host budget.
    Entry values are ``{column name: ColumnData}`` dicts of decoded numpy
    arrays — consumers must treat them as immutable (assembly concats and
    narrows into FRESH arrays; nothing writes back)."""

    M_HITS = M.HOST_CACHE_HITS
    M_MISSES = M.HOST_CACHE_MISSES
    M_EVICTIONS = M.HOST_CACHE_EVICTIONS
    M_BYTES = M.HOST_CACHE_BYTES

    # this tier's pages are host RAM: its ledger events land in the host
    # pool under the host-cache owner (obs/memledger.py taxonomy)
    LEDGER_POOL = "host"
    LEDGER_OWNER = "host-cache"

    def _default_max_bytes(self) -> int:
        return _default_budget()


# the process-wide host tier: every staging tier in this process (eager,
# compiled phase-1, SPMD shards, worker task splits) fills and consults
# one pool, exactly like DEVICE_CACHE
HOST_CACHE = HostColumnCache()


def host_admit_budget(session) -> Optional[int]:
    """Per-entry admission cap from the ``host_cache_max_bytes`` session
    property (min-ed with the server-wide budget at admit time — mirrors
    device_cache_max_bytes semantics)."""
    props = getattr(session, "properties", None) or {}
    v = props.get("host_cache_max_bytes")
    return int(v) if v is not None else None


def shed_revocable(nbytes: int) -> int:
    """NODE-level (host-RAM) pressure shed across BOTH revocable tiers,
    host tier first: host pages are the cheapest to rebuild (one
    transfer), warm HBM pages the most valuable to keep (zero work on
    the next query) — so pressure eats the cheap tier before it touches
    the expensive one. The worker invokes this when its process RSS
    crosses ``TRINO_TPU_HOST_MEMORY_LIMIT_BYTES`` (server/worker.py
    announce loop). NOTE: callers that specifically need DEVICE bytes
    back (the device-pool overflow check, the spill path in
    exec/memory.py) must keep calling ``DEVICE_CACHE.yield_bytes``
    directly — freeing host RAM cannot satisfy an HBM reservation, and
    counting host bytes against the device pool would thrash this tier
    for nothing."""
    from trino_tpu.devcache.cache import DEVICE_CACHE

    if nbytes <= 0:
        return 0
    freed = HOST_CACHE.yield_bytes(nbytes, reason="host-pressure")
    if freed < nbytes and _device_memory_host_backed():
        # escalate into the device tier ONLY where its arrays live in
        # host RAM (CPU meshes — no discoverable HBM): there, evicting
        # warm "device" pages genuinely relieves RSS. On a real
        # accelerator they are HBM-resident: evicting them would free
        # device memory, not host RSS, so a persistent RSS overage
        # would thrash the warm tier every announce cycle for nothing.
        # Each tier's yield emits its own single shed event, so the
        # ledger shows the escalation ORDER (host first, then device
        # under the rss-escalation reason).
        freed += DEVICE_CACHE.yield_bytes(nbytes - freed,
                                          reason="rss-escalation")
    return freed


def _device_memory_host_backed() -> bool:
    """True when this process's jax device memory is host RAM (no
    discoverable accelerator HBM) — the precondition for host-RAM
    pressure to escalate into the device tier."""
    from trino_tpu.devcache.cache import device_memory_bytes

    return device_memory_bytes() is None
