"""Cache-key construction for the device table cache.

A staged artifact is reusable only when EVERYTHING that shaped it
matches: the projection (column subset), the pushdown handle (an
``apply_limit``/``apply_topn``/``apply_aggregation`` handle changes what
the connector returns), the effective scan constraint (static pushdown ∩
available dynamic-filter domains — connectors may prune splits/rows from
it, advisorily but deterministically), and the subset of dynamic domains
the engine physically applied host-side before the transfer (the
compiled tier applies only STRONG domains at staging and enforces weak
ones on device — two executors with the same constraint but different
host-applied sets stage different pages). All of that digests into
``CacheKey.signature``; ``data_version`` and the shard shape ride
alongside. Anything not provably stable — an unversioned connector, an
active transaction overlay, a handle whose repr is identity-based —
yields ``None``: bypass, never guess.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional

from trino_tpu.devcache.cache import CacheKey, instance_token

# discrete domains above this digest through their sorted numpy array
# (phase-1 dynamic filters reach millions of keys; repr would be O(n)
# python-object formatting)
_ARRAY_DIGEST_MIN = 64


def cache_enabled(session) -> bool:
    props = getattr(session, "properties", None) or {}
    return bool(props.get("device_cache_enabled", False))


def admit_budget(session) -> Optional[int]:
    """The session's per-admission byte cap (min-ed with the server-wide
    budget at admit time — mirrors result_cache_max_bytes semantics)."""
    props = getattr(session, "properties", None) or {}
    v = props.get("device_cache_max_bytes")
    return int(v) if v is not None else None


def _update_domain(h, dom) -> None:
    if dom.values is not None:
        h.update(f"set:{len(dom.values)}:{int(dom.null_allowed)}:".encode())
        if len(dom.values) >= _ARRAY_DIGEST_MIN:
            try:
                from trino_tpu.connector.predicate import sorted_values_array

                arr = sorted_values_array(dom)
                h.update(str(arr.dtype).encode())
                h.update(arr.tobytes())
                return
            except Exception:  # noqa: BLE001 — non-numeric set: repr path
                pass
        h.update(repr(sorted(dom.values, key=repr)).encode())
        return
    h.update(repr(("range", dom.low, dom.high, dom.low_inclusive,
                   dom.high_inclusive, dom.null_allowed)).encode())


def _update_tuple_domain(h, constraint) -> None:
    if constraint is None or constraint.is_all():
        h.update(b"|all|")
        return
    for col in sorted(constraint.domains):
        h.update(f"|c:{col}|".encode())
        _update_domain(h, constraint.domains[col])


def _stable_repr(obj) -> Optional[str]:
    """repr(obj) when it is content-based; None when it falls back to the
    identity form (``<... object at 0x...>``) — an unstable key component
    means bypass, not a guess."""
    r = repr(obj)
    if " at 0x" in r or " object at " in r:
        return None
    return r


def scan_signature(node, constraint, applied_domains) -> Optional[str]:
    """Projection/pruning digest for one TableScanNode staging, or None
    when any component has no stable content repr."""
    h = hashlib.blake2b(digest_size=16)
    h.update(repr(tuple(node.column_names)).encode())
    handle = getattr(node, "table_handle", None)
    if handle is not None:
        r = _stable_repr(handle)
        if r is None:
            return None
        h.update(b"|h:")
        h.update(r.encode())
    _update_tuple_domain(h, constraint)
    for col in sorted(applied_domains or {}):
        h.update(f"|applied:{col}|".encode())
        _update_domain(h, applied_domains[col])
    return h.hexdigest()


def splits_shard(splits: List) -> Optional[str]:
    """Shard component for a worker task's assigned split set (split
    boundaries and any pushdown payload riding ``Split.info``)."""
    h = hashlib.blake2b(digest_size=12)
    for s in splits:
        h.update(repr((s.schema, s.table, s.lo, s.hi)).encode())
        info = getattr(s, "info", None)
        if info is not None:
            r = _stable_repr(info)
            if r is None:
                return None
            h.update(r.encode())
    return f"splits:{len(splits)}:{h.hexdigest()}"


def host_split_keys(session, node, constraint, applied_domains, splits):
    """Host-tier cache keys for a split list's decoded column sets (None
    per bypassed split). Identity = the scan signature (projection +
    handle + constraint + host-APPLIED domain subset — the pruning baked
    into the cached arrays) + each split's own boundary digest as the
    shard, so the same split reached through ANY grouping (whole-table
    staging, a worker's assigned set, any SPMD mesh width) lands on one
    entry. The signature (which digests full dynamic-filter domains —
    megabytes at sf10) and the connector version probe are computed ONCE
    for the whole list; only the cheap per-split shard digest varies. The
    bypass rules (disabled cache, unversioned connector, transaction
    overlay, unstable handle/info repr) are scan_cache_key's, unchanged."""
    import dataclasses as _dc

    base = scan_cache_key(session, node, constraint, applied_domains,
                          shard="host")
    if base is None:
        return [None] * len(splits)
    out = []
    for split in splits:
        shard = splits_shard([split])
        out.append(None if shard is None else
                   _dc.replace(base, shard="host:" + shard))
    return out


def cached_stage(session, node, constraint, applied_domains, shard, loader):
    """The one consult-the-pool-or-stage step every staging tier runs:
    build the key, serve from :data:`DEVICE_CACHE` under a
    ``device-cache/lookup`` span, or run ``loader`` directly on bypass.
    ``loader() -> (value, rows, nbytes, splits)``; returns
    ``(CacheEntry, "hit"|"miss"|"bypass")`` — bypass wraps the loaded
    artifact in a transient (never-admitted) entry so callers read one
    shape."""
    import time

    from trino_tpu.devcache.cache import DEVICE_CACHE, CacheEntry
    from trino_tpu.obs import trace as tracing

    key = scan_cache_key(session, node, constraint, applied_domains,
                         shard=shard)
    if key is None:
        value, rows, nbytes, splits = loader()
        now = time.time()
        return CacheEntry(None, value, rows, int(nbytes), splits,
                          created_at=now, last_used_at=now), "bypass"
    with tracing.span("device-cache/lookup", table=node.table) as sp:
        ent, disposition = DEVICE_CACHE.lookup_or_stage(
            key, loader, admit_bytes=admit_budget(session))
        sp.set("result", disposition)
        sp.set("bytes", ent.nbytes)
    return ent, disposition


def cached_build(session, node, constraint, applied_domains, key_channels,
                 key_dtypes: str, loader):
    """Device-cached SORTED BUILD artifact for a join whose build side is a
    bare versioned table scan: the ops/join.py ``SortedBuild`` (sorted key
    columns + row permutation + live flags, all device arrays) keyed by
    the scan's staging signature PLUS the join-key signature (key channels
    and their post-alignment physical dtypes — the probe side's dtype
    participates in alignment, so two probes of different widths need two
    artifacts). A warm repeated join skips the build-side sort entirely.

    Same revocable-tier pool and accounting as staged scans
    (:data:`~trino_tpu.devcache.cache.DEVICE_CACHE`); build hits count
    under ``trino_tpu_device_cache_build_hits_total`` (and, like any pool
    hit, the general hit counter). Returns ``(SortedBuild, disposition)``
    — or ``(None, "bypass")`` WITHOUT running ``loader`` when the key is
    not cacheable, so callers can keep the (cheaper) fully-fused path for
    uncacheable builds instead of paying a separate build sort.

    ``loader() -> (SortedBuild, rows, nbytes, splits)``.
    """
    from trino_tpu.devcache.cache import DEVICE_CACHE
    from trino_tpu.obs import metrics as M
    from trino_tpu.obs import trace as tracing

    shard = "build:" + ",".join(str(c) for c in key_channels) \
        + ":" + key_dtypes
    key = scan_cache_key(session, node, constraint, applied_domains,
                         shard=shard)
    if key is None:
        return None, "bypass"
    with tracing.span("device-cache/lookup", table=node.table) as sp:
        ent, disposition = DEVICE_CACHE.lookup_or_stage(
            key, loader, admit_bytes=admit_budget(session))
        sp.set("result", disposition)
        sp.set("bytes", ent.nbytes)
        sp.set("artifact", "sorted-build")
    if disposition == "hit":
        M.DEVICE_CACHE_BUILD_HITS.inc()
    return ent.value, disposition


def scan_cache_key(session, node, constraint,
                   applied_domains: Optional[Dict] = None,
                   shard: Optional[str] = "table") -> Optional[CacheKey]:
    """CacheKey for staging this scan under this session, or None when
    the cache must be bypassed (disabled, unversioned connector, active
    transaction, unstable handle/split repr)."""
    if shard is None or not cache_enabled(session):
        return None
    if getattr(session, "transaction", None) is not None:
        # transaction overlays are unversioned by construction (the
        # overlay never defines data_version) — this check just makes the
        # bypass explicit and future-proof
        return None
    conn = (getattr(session, "catalogs", None) or {}).get(node.catalog)
    if conn is None:
        return None
    try:
        version = conn.data_version(node.schema, node.table)
    except Exception:  # noqa: BLE001 — a failing version probe means bypass
        return None
    if version is None:
        return None
    sig = scan_signature(node, constraint, applied_domains or {})
    if sig is None:
        return None
    return CacheKey(node.catalog, node.schema, node.table, str(version),
                    sig, shard, instance_token(conn))
