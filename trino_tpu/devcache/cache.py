"""Warm-HBM device table cache: the worker-side buffer pool.

Reference role: the classical buffer pool (and Trino's split/page caching
proposals) redesigned for the staged-execution model: the unit of caching
is a fully staged DEVICE artifact — an assembled scan ``Page`` (eager /
compiled tiers), a per-split worker page, or the stacked shard arrays of
an SPMD scan — so a warm query skips the whole host pipeline (connector
scan, dynamic-domain pruning, dictionary merge, host->device transfer),
which BENCH_r05 measured as the engine's single biggest loss (q3_sf10:
22.7 s staging vs 1.17 s device execution).

Correctness comes from the connector SPI's ``data_version()`` token
(trino_tpu/connector/spi.py): the version rides inside every cache key,
so any INSERT/UPDATE/DELETE/DROP/CTAS changes the key and the stale entry
can never be served again (lookup additionally drops same-table entries
whose version moved, reclaiming their HBM immediately). Unversioned
connectors (``data_version() is None`` — e.g. the live ``system``
catalog, or a transaction overlay) bypass the cache entirely.

Memory discipline: the cache is the cluster's REVOCABLE tier.

- byte-budgeted LRU (budget sized from real device memory when
  discoverable, see :func:`device_memory_bytes`);
- ``yield_bytes`` sheds entries under pressure — called by the spill
  decision (exec/memory.py: a query about to spill reclaims cache HBM
  first) and by the worker announce loop when the node's pool is over
  its limit, BEFORE the coordinator's low-memory killer would consider
  killing a query;
- admission is SINGLE-FLIGHT: concurrent queries staging the same table
  produce one transfer — followers park on the leader's flight and are
  served the same entry (the request-coalescing role of any serving
  cache, same shape as cache/result_cache.py).
"""
from __future__ import annotations

import dataclasses
import os
import threading
import time
import weakref
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

# the single-flight holder is shared with the result cache — ONE
# implementation of the wait/resolve protocol in the tree (its payload
# field is generic: here it carries the CacheEntry)
from trino_tpu.cache.result_cache import _Flight
from trino_tpu.obs import metrics as M

# fallback budget when device memory is not discoverable (CPU test meshes)
DEFAULT_DEVICE_CACHE_BYTES = 256 << 20
# fraction of discovered device memory the cache may hold: running
# queries own the rest (the cache yields even that share under pressure)
DEVICE_MEMORY_FRACTION = 4  # budget = HBM / 4

_device_memory_cell: List = []  # lazily computed once per process


def device_memory_bytes() -> Optional[int]:
    """This process's per-device accelerator memory capacity (HBM bytes),
    or None when not discoverable. Sources, in order: the
    ``TRINO_TPU_DEVICE_MEMORY_BYTES`` env override, then the backend's
    ``memory_stats()['bytes_limit']`` (real TPU/GPU devices report it;
    CPU test meshes do not). Computed once and cached — the worker
    announce loop reads it every heartbeat."""
    if _device_memory_cell:
        return _device_memory_cell[0]
    cap: Optional[int] = None
    env = os.environ.get("TRINO_TPU_DEVICE_MEMORY_BYTES")
    if env:
        try:
            cap = int(env)
        except ValueError:
            cap = None
    if cap is None:
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            if stats and stats.get("bytes_limit"):
                cap = int(stats["bytes_limit"])
        except Exception:  # noqa: BLE001 — no backend / no stats on CPU
            cap = None
    _device_memory_cell.append(cap)
    return cap


def _default_budget() -> int:
    env = os.environ.get("TRINO_TPU_DEVICE_CACHE_BYTES")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    cap = device_memory_bytes()
    if cap:
        return max(cap // DEVICE_MEMORY_FRACTION, 64 << 20)
    return DEFAULT_DEVICE_CACHE_BYTES


@dataclasses.dataclass(frozen=True)
class CacheKey:
    """Identity of one staged device artifact. ``signature`` digests the
    projection, pushdown handle, effective constraint, and the host-applied
    dynamic domains (trino_tpu/devcache/keys.py); ``shard`` distinguishes
    staging shapes of the same table (whole-table vs a worker task's split
    set vs an SPMD mesh width); ``conn_token`` pins process-local
    connectors (the memory connector's version counter is instance state —
    two sessions' private catalogs must never alias)."""

    catalog: str
    schema: str
    table: str
    data_version: str
    signature: str
    shard: str
    conn_token: int = 0

    def table_id(self) -> Tuple[str, str, str, int]:
        return (self.catalog, self.schema, self.table, self.conn_token)


@dataclasses.dataclass
class CacheEntry:
    """One resident entry: ``value`` is the tier-specific staged artifact
    (Page, or (arrays, spec, rows) for SPMD), ``rows`` the live staged
    rows it holds, ``nbytes`` its exact device bytes."""

    key: CacheKey
    value: object
    rows: int
    nbytes: int
    splits: int = 0
    hits: int = 0
    created_at: float = 0.0
    last_used_at: float = 0.0
    # resource group whose query staged this entry (None outside a lane):
    # drives the per-group carve-out eviction preference and the ledger
    # owner suffix (``device-cache:<group>``)
    group: Optional[str] = None


def _current_group() -> Optional[str]:
    """The resource group of the query running on THIS thread (set by the
    dispatcher lane around execution), or None outside a lane. Lazy so the
    cache stays importable without the server package."""
    try:
        from trino_tpu.server.resource_groups import current_group

        return current_group()
    except Exception:  # noqa: BLE001 — attribution never fails staging
        return None




class DeviceTableCache:
    """Byte-budgeted LRU of staged device tables with single-flight
    admission and version-based invalidation. The metric hooks are class
    attributes so the host-RAM tier (devcache/hostcache.py) reuses the
    whole LRU/flight/invalidation machinery under its own counters."""

    # followers give a slow leader this long before re-staging themselves
    # (a TPU cold compile through a tunnel can take minutes; staging alone
    # is tens of seconds at sf10)
    FLIGHT_WAIT_S = 600.0

    M_HITS = M.DEVICE_CACHE_HITS
    M_MISSES = M.DEVICE_CACHE_MISSES
    M_EVICTIONS = M.DEVICE_CACHE_EVICTIONS
    M_BYTES = M.DEVICE_CACHE_BYTES

    # memory-ledger attribution (obs/memledger.py): which pool this
    # tier's bytes live in and the owner its events carry — the host
    # tier overrides both
    LEDGER_POOL = "device"
    LEDGER_OWNER = "device-cache"

    def __init__(self, max_bytes: Optional[int] = None):
        self._max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: "OrderedDict[CacheKey, CacheEntry]" = OrderedDict()
        self._bytes = 0
        self._flights: Dict[CacheKey, _Flight] = {}
        # table_id -> resident keys: keeps the per-lookup stale-version
        # sweep O(entries-for-this-table), not O(all entries) under the
        # global lock (worker split-set shards accumulate many keys)
        self._by_table: Dict[tuple, set] = {}
        # lifetime hit count of THIS pool (the worker announce payload's
        # per-tier column — the process-global metric cannot distinguish
        # tiers once both exist)
        self._hit_count = 0
        # resident bytes per resource group (None = ungrouped): the
        # carve-out ground truth the over-share eviction preference and
        # ``system.runtime.resource_groups`` read
        self._group_bytes: Dict[Optional[str], int] = {}

    def _default_max_bytes(self) -> int:
        """Budget when the constructor did not pin one (subclass hook)."""
        return _default_budget()

    def _ledger_event(self, kind: str, nbytes: int,
                      reason: Optional[str] = None,
                      group: Optional[str] = None) -> None:
        """One memory-ledger event for this tier. Callers MUST have
        released ``self._lock`` first (the emission discipline
        ``tools/lint/lock_discipline.py`` enforces): bytes are collected
        inside the lock, the event is emitted after — which is also what
        gives pressure sheds their exactly-one-event contract. Entries
        staged under a resource group carry the group as an owner SUFFIX
        (``device-cache:<group>``) symmetric across admit/evict/shed, so
        the ledger's live bytes attribute carve-out occupancy per tenant;
        ungrouped entries keep the bare tier owner."""
        if nbytes <= 0:
            return
        from trino_tpu.obs.memledger import MEMORY_LEDGER

        owner = (f"{self.LEDGER_OWNER}:{group}" if group
                 else self.LEDGER_OWNER)
        MEMORY_LEDGER.record_event(
            kind, self.LEDGER_POOL, owner, nbytes, reason=reason)

    def _ledger_events(self, kind: str, by_group: Dict[Optional[str], int],
                       reason: Optional[str] = None) -> None:
        """Per-group ledger emission for a batch of freed entries: one
        event per owning group (lock released first, as above)."""
        for group, nbytes in by_group.items():
            self._ledger_event(kind, nbytes, reason=reason, group=group)

    # ---------------------------------------------------------- inspection
    @property
    def max_bytes(self) -> int:
        if self._max_bytes is None:
            self._max_bytes = self._default_max_bytes()
        return self._max_bytes

    def hit_count(self) -> int:
        with self._lock:
            return self._hit_count

    def cached_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def group_bytes(self) -> Dict[Optional[str], int]:
        """Resident bytes per owning resource group (None = ungrouped) —
        the carve-out occupancy snapshot."""
        with self._lock:
            return dict(self._group_bytes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> List[dict]:
        """Row-shaped entry list (system.runtime.device_cache), MRU
        first."""
        with self._lock:
            entries = list(reversed(self._entries.values()))
        return [
            {
                "catalog": e.key.catalog,
                "schema": e.key.schema,
                "table": e.key.table,
                "version": e.key.data_version,
                "shard": e.key.shard,
                "signature": e.key.signature,
                "bytes": e.nbytes,
                "rows": e.rows,
                "hits": e.hits,
                "createdAt": e.created_at,
                "lastUsedAt": e.last_used_at,
            }
            for e in entries
        ]

    # ----------------------------------------------------------- lifecycle
    def lookup_or_stage(
        self, key: CacheKey, loader: Callable[[], Tuple[object, int, int, int]],
        admit_bytes: Optional[int] = None, wait: bool = True,
    ) -> Tuple[Optional[CacheEntry], str]:
        """``(entry, "hit"|"miss")``. ``loader() -> (value, rows, nbytes,
        splits)`` runs OUTSIDE the cache lock (staging is the slow path);
        concurrent callers of the same key single-flight: exactly one
        loader runs, followers are served its entry as hits (they paid no
        transfer). A failed leader wakes followers empty-handed and they
        race again.

        ``wait=False``: when another caller is already staging this key,
        return ``(None, "inflight")`` immediately instead of parking as a
        follower. Shared-pool worker threads use this so one slow staging
        can never pin every pool slot behind its flight (the staging
        fan-out, exec/staging.py) — the caller re-resolves in-flight keys
        on its OWN thread afterwards with a blocking call."""
        while True:
            inflight = False
            with self._lock:
                stale_freed = self._drop_stale_locked(key)
                ent = self._entries.get(key)
                if ent is not None:
                    self._entries.move_to_end(key)
                    ent.hits += 1
                    ent.last_used_at = time.time()
                    self._hit_count += 1
                    self.M_HITS.inc()
                else:
                    flight = self._flights.get(key)
                    if flight is None:
                        flight = self._flights[key] = _Flight()
                        lead = True
                    else:
                        if not wait:
                            inflight = True
                        lead = False
            self._ledger_events("evict", stale_freed, reason="stale")
            if ent is not None:
                return ent, "hit"
            if inflight:
                return None, "inflight"
            if not lead:
                if not flight.wait(self.FLIGHT_WAIT_S):
                    # the leader is alive but STUCK (e.g. blocked in a
                    # connector read): bypass the pool and stage privately
                    # rather than hanging every query on that table behind
                    # one wedged staging
                    value, rows, nbytes, splits = loader()
                    now = time.time()
                    self.M_MISSES.inc()
                    return CacheEntry(key, value, rows, int(nbytes), splits,
                                      created_at=now, last_used_at=now), "miss"
                if flight.ok and flight.value is not None:
                    ent = flight.value
                    with self._lock:
                        ent.hits += 1
                        ent.last_used_at = time.time()
                        self._hit_count += 1
                    self.M_HITS.inc()
                    return ent, "hit"
                continue  # leader failed: race for leadership
            try:
                value, rows, nbytes, splits = loader()
            except BaseException:
                with self._lock:
                    flight = self._flights.pop(key, None)
                if flight is not None:
                    flight._resolve(None, ok=False)
                raise
            now = time.time()
            ent = CacheEntry(key, value, rows, int(nbytes), splits,
                             created_at=now, last_used_at=now)
            self._admit(ent, admit_bytes)
            with self._lock:
                flight = self._flights.pop(key, None)
            if flight is not None:
                flight._resolve(ent, ok=True)
            self.M_MISSES.inc()
            return ent, "miss"

    def peek(self, key: CacheKey) -> Optional[CacheEntry]:
        """Resident entry for ``key`` (counted + LRU-bumped as a hit), or
        None — WITHOUT staging on a miss and without joining a flight. The
        staging pipeline probes the host tier this way up front (under the
        ``staging/host-cache`` span) and routes only the missing splits
        into the scan fan-out; a racing ``lookup_or_stage`` on the same
        key stays correct (it re-checks residency under the lock)."""
        with self._lock:
            stale_freed = self._drop_stale_locked(key)
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
                ent.hits += 1
                ent.last_used_at = time.time()
                self._hit_count += 1
        self._ledger_events("evict", stale_freed, reason="stale")
        if ent is None:
            return None
        self.M_HITS.inc()
        return ent

    def _admit(self, ent: CacheEntry, admit_bytes: Optional[int]) -> None:
        """Admit under the budget. The session's ``admit_bytes`` is a
        PER-ENTRY size filter only — over-cap entries are returned to the
        caller but not retained; the eviction loop always targets the
        shared server-wide budget, so one tenant's tight cap can never
        flush other tenants' warm tables."""
        cap = (self.max_bytes if admit_bytes is None
               else min(self.max_bytes, int(admit_bytes)))
        if ent.nbytes > cap:
            return
        if ent.group is None:
            ent.group = _current_group()
        evicted: Dict[Optional[str], int] = {}
        with self._lock:
            replaced = self._remove_locked(ent.key)
            while self._bytes + ent.nbytes > self.max_bytes and self._entries:
                nbytes, group = self._evict_victim_locked()
                evicted[group] = evicted.get(group, 0) + nbytes
            self._entries[ent.key] = ent
            self._bytes += ent.nbytes
            self._group_bytes[ent.group] = (
                self._group_bytes.get(ent.group, 0) + ent.nbytes)
            self._by_table.setdefault(ent.key.table_id(), set()).add(ent.key)
            self.M_BYTES.set(self._bytes)
        # ledger emission happens OUTSIDE the lock: bytes collected above,
        # one aggregated evict event per victim group for however many
        # LRU/over-share victims made room
        self._ledger_events("evict", evicted, reason="lru")
        if replaced is not None:
            self._ledger_event("release", replaced.nbytes, reason="replace",
                               group=replaced.group)
        self._ledger_event("admit", ent.nbytes, group=ent.group)

    def _remove_locked(self, key: CacheKey) -> Optional[CacheEntry]:
        ent = self._entries.pop(key, None)
        if ent is None:
            return None
        self._bytes -= ent.nbytes
        remaining = self._group_bytes.get(ent.group, 0) - ent.nbytes
        if remaining > 0:
            self._group_bytes[ent.group] = remaining
        else:
            self._group_bytes.pop(ent.group, None)
        keys = self._by_table.get(key.table_id())
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._by_table[key.table_id()]
        return ent

    def _evict_victim_locked(self) -> Tuple[int, Optional[str]]:
        """Evict one entry and return ``(bytes, group)``. Carve-out
        preference: the oldest entry belonging to a group holding MORE
        than its configured cache share goes first, so one tenant's
        staging storm reclaims its own over-share bytes before touching
        another tenant's warm state; plain LRU head when nobody is over
        (or no shares are configured)."""
        victim_key = None
        try:
            from trino_tpu.server.resource_groups import CACHE_SHARES

            for k, e in self._entries.items():  # LRU order
                if CACHE_SHARES.over_share(
                        e.group, self._group_bytes.get(e.group, 0),
                        self.max_bytes):
                    victim_key = k
                    break
        except Exception:  # noqa: BLE001 — carve-outs never wedge eviction
            victim_key = None
        if victim_key is None:
            victim_key = next(iter(self._entries))
        victim = self._remove_locked(victim_key)
        self.M_EVICTIONS.inc()
        self.M_BYTES.set(self._bytes)
        return victim.nbytes, victim.group

    def _evict_lru_locked(self) -> int:
        """Back-compat shim over ``_evict_victim_locked`` (bytes only)."""
        nbytes, _ = self._evict_victim_locked()
        return nbytes

    def _drop_stale_locked(self, key: CacheKey) -> Dict[Optional[str], int]:
        """Drop every entry of the same table whose data_version differs
        from the version the caller just observed: a mutation moved the
        version, so those arrays can never be served again — reclaim
        their HBM now instead of waiting for LRU age-out. Returns bytes
        freed per owning group so the caller can emit the ledger events
        AFTER releasing the lock."""
        keys = self._by_table.get(key.table_id())
        if not keys:
            return {}
        stale = [k for k in keys if k.data_version != key.data_version]
        freed: Dict[Optional[str], int] = {}
        for k in stale:
            victim = self._remove_locked(k)
            if victim is not None:
                freed[victim.group] = (
                    freed.get(victim.group, 0) + victim.nbytes)
            self.M_EVICTIONS.inc()
        if stale:
            self.M_BYTES.set(self._bytes)
        return freed

    # ------------------------------------------------------------ pressure
    def yield_bytes(self, nbytes: int, reason: str = "yield") -> int:
        """Revocable-tier contract: shed at least ``nbytes`` of cached
        tables (LRU-first) for a running query's benefit; returns the
        bytes actually freed. Never blocks on staging flights. Each call
        that frees anything emits EXACTLY ONE ledger ``shed`` event
        carrying the reclaiming ``reason`` (``spill`` / ``pool-overflow``
        / ``host-pressure`` / ``rss-escalation`` / ...)."""
        if nbytes <= 0:
            return 0
        freed = 0
        by_group: Dict[Optional[str], int] = {}
        with self._lock:
            while freed < nbytes and self._entries:
                n, group = self._evict_victim_locked()
                freed += n
                by_group[group] = by_group.get(group, 0) + n
        self._ledger_events("shed", by_group, reason=reason)
        return freed

    def evict_to(self, target_bytes: int, reason: str = "trim") -> int:
        """Evict LRU entries until the cache holds at most
        ``target_bytes``; returns bytes freed."""
        freed = 0
        by_group: Dict[Optional[str], int] = {}
        with self._lock:
            while self._bytes > max(0, int(target_bytes)) and self._entries:
                n, group = self._evict_victim_locked()
                freed += n
                by_group[group] = by_group.get(group, 0) + n
        self._ledger_events("evict", by_group, reason=reason)
        return freed

    def invalidate_all(self) -> None:
        with self._lock:
            by_group = dict(self._group_bytes)
            self._entries.clear()
            self._by_table.clear()
            self._group_bytes.clear()
            self._bytes = 0
            self.M_BYTES.set(0)
        self._ledger_events("release", by_group, reason="invalidate")


# the process-wide pool: coordinator-local execution, the compiled tier,
# and every task on a worker share one budget (one device per process)
DEVICE_CACHE = DeviceTableCache()


# --------------------------------------------------- connector identity
# Process-local connectors (coordinator_only: the memory connector, whose
# version counter is instance state) get a per-instance token so two
# sessions' PRIVATE catalog maps never alias in the cache. Monotonic ids
# (never reused, unlike id()) via a weak map: a collected connector's
# entries become unreachable keys and age out by LRU.
_conn_tokens: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_conn_token_lock = threading.Lock()
_conn_token_next = [1]


def instance_token(conn) -> int:
    """0 for connectors whose data_version is globally meaningful (file
    state, immutable generators); a unique per-instance token for
    process-local ones."""
    if not getattr(conn, "coordinator_only", False):
        return 0
    with _conn_token_lock:
        tok = _conn_tokens.get(conn)
        if tok is None:
            tok = _conn_tokens[conn] = _conn_token_next[0]
            _conn_token_next[0] += 1
        return tok
