"""SPMD distributed execution: one `shard_map` program per query body.

Reference: the distributed data plane — splits scheduled across workers
(SourcePartitionedScheduler), hash-repartition shuffles between stages
(PartitionedOutputOperator -> HTTP -> ExchangeOperator, SURVEY.md §2.6/§3.4).
TPU-first redesign (SURVEY.md §7.1 "shuffle = collective"): the whole
multi-stage pipeline compiles into a single SPMD program over a device mesh:

- leaf scans = data-parallel splits, one shard per device (padded to a
  common shape; the pad rows carry sel=False) — SOURCE_DISTRIBUTION analog;
- low-cardinality aggregation = local partial aggregate, `all_gather` of the
  (small) partial-state pages over ICI, local final aggregate — the
  partial/FINAL split HashAggregationOperator does across an exchange;
- high-cardinality aggregation = hash-repartition raw rows by group-key
  hash (`all_to_all`, parallel/exchange.py — FIXED_HASH_DISTRIBUTION),
  aggregate locally, keep the result sharded;
- join build sides: `all_gather` (FIXED_BROADCAST_DISTRIBUTION) when small,
  else co-partition both sides by key hash and join locally (partitioned
  join) — the DetermineJoinDistributionType choice, from connector stats;
- sort/topN/limit run on the gathered (replicated) result.

Collectives ride ICI inside the compiled program — there is no serialized
page shuttle between stages on this path.
"""
from __future__ import annotations

import dataclasses
import time as _time
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as PSpec

from trino_tpu import types as T
from trino_tpu.connector import spi as spi_mod
from trino_tpu.data.page import Column, Page
from trino_tpu.data import page as page_mod
from trino_tpu.exec.executor import Executor, QueryError, _col_to_lowered
from trino_tpu.exec.page_tree import ColSpec, PageSpec, flatten_page, unflatten_page
from trino_tpu.ops import aggregate as agg_ops
from trino_tpu.ops import groupby as gb
from trino_tpu.sql.planner import plan as P

AXIS = "d"


def _shard_map(f, mesh, in_specs, out_specs):
    """Version-portable shard_map: ``jax.shard_map`` (0.5+, check_vma)
    with the ``jax.experimental.shard_map`` (0.4.x, check_rep) fallback —
    replication checking stays off either way (error flags are replicated
    by construction, the checker can't see it)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _gather_flat(x: jnp.ndarray) -> jnp.ndarray:
    """all_gather along the mesh axis and flatten device dim into rows."""
    g = jax.lax.all_gather(x, AXIS)  # [ndev, n, ...]
    return g.reshape((-1,) + g.shape[2:])


def gather_page(page: Page) -> Page:
    """Replicate a sharded page on every device (broadcast exchange).
    Idempotent: already-replicated pages pass through."""
    if page.replicated:
        return page
    cols = [
        Column(
            c.type,
            _gather_flat(c.values),
            _gather_flat(c.nulls) if c.nulls is not None else None,
            c.dictionary,
            c.vrange,
            hi=_gather_flat(c.hi) if c.hi is not None else None,
        )
        for c in page.columns
    ]
    sel = (
        _gather_flat(page.sel)
        if page.sel is not None
        else None
    )
    return Page(cols, sel, replicated=True)


class SpmdExecutor(Executor):
    """Runs the plan per-shard inside shard_map; exchanges are collectives.

    Distribution choice per exchange (reference: AddExchanges.java:138 +
    DetermineJoinDistributionType): broadcast (all_gather) for small build
    sides / low-cardinality aggregations, hash repartition (all_to_all,
    parallel/exchange.py) when stats say the data is too big to replicate —
    the same predicates (sql/planner/stats.py) drive build-time capacity
    hints, so the trace always finds its hints."""

    eager_tier = False  # runs under jax tracing: no host-side syncs
    enable_dynamic_filtering = False  # scans pre-staged before tracing
    collect_stats = False  # tracing once; per-call timing is meaningless

    def __init__(self, session, staged: Dict[int, Page], capacity_hints=None, n_devices: int = 1):
        super().__init__(session, capacity_hints)
        self.staged = staged
        self.n_devices = n_devices

    def _exec_TableScanNode(self, node: P.TableScanNode) -> Page:
        return self.staged[node.id]

    # ------------------------------------------------------ hash exchange
    def _repartition(self, page: Page, key_channels, hint_key: str) -> Page:
        from trino_tpu.parallel import exchange

        page = self._narrowed_for_exchange(page)
        capacity = self.hint_capacity(hint_key, None)
        out, overflow = exchange.repartition_page(
            page, key_channels, self.n_devices, capacity, AXIS
        )
        self.errors.append((f"CAPACITY_EXCEEDED:{hint_key}", overflow))
        return out

    def _join_repartitioned(self, node: P.JoinNode, left: Page, right: Page):
        """Co-partition both join sides by key hash when stats prefer it and
        neither side is already replicated. Returns None to fall back to the
        broadcast path."""
        from trino_tpu.sql.planner import stats

        if left.replicated or right.replicated:
            return None
        if not stats.join_repartitions(self.session, node, self.n_devices):
            return None
        left2 = self._repartition(left, node.left_keys, f"xchgl:{node.id}")
        right2 = self._repartition(right, node.right_keys, f"xchgr:{node.id}")
        return left2, right2

    # ----------------------------------------------------- distributed agg
    def aggregate_page(self, node: P.AggregationNode, page: Page) -> Page:
        """Low cardinality: partial aggregate -> all_gather partial states ->
        final combine (HashAggregationOperator PARTIAL -> exchange -> FINAL).
        High cardinality: hash-repartition RAW rows by group key, aggregate
        single-step locally, output stays sharded (the partial step would not
        reduce — the SkipAggregationBuilder insight). DISTINCT aggregates
        can't be split: gather raw rows and aggregate single-step."""
        from trino_tpu.sql.planner import stats

        if page.replicated:
            # every device already holds all rows: single-step local aggregate
            return super().aggregate_page(node, page)
        if stats.agg_repartitions(self.session, node, self.n_devices):
            page2 = self._repartition(page, node.group_channels, f"xchg:{node.id}")
            return Executor.aggregate_page(self, node, page2)  # sharded out
        if not P.can_split_aggs(node.aggregates):
            return super().aggregate_page(node, gather_page(page))
        partial = self.aggregate_partial(node, page)
        gathered = gather_page(partial)
        final = P.AggregationNode(
            None, list(range(len(node.group_channels))), node.aggregates,
            step="final", names=node.names,
        )
        out = self.aggregate_final(final, gathered)
        return Page(out.columns, out.sel, replicated=True)

    # -------------------------------------------------- distributed joins
    def _overlap_blocks(self) -> int:
        props = getattr(self.session, "properties", None) or {}
        return int(props.get("exchange_overlap_blocks", 0) or 0)

    def _narrowed_for_exchange(self, page: Page) -> Page:
        """Two-limb columns degrade to low words with the deferred
        overflow check before any device exchange (no limb lanes)."""
        if not any(c.hi is not None for c in page.columns):
            return page
        return Page(
            [self._narrowed_or_flag(c, page.sel) for c in page.columns],
            page.sel, page.replicated, live_prefix=page.live_prefix,
        )

    def _overlapped_join(self, node: P.JoinNode, left: Page, right: Page,
                         semi: bool) -> Optional[Page]:
        """Partitioned lookup/semi join with the PROBE-side exchange
        pipelined against join compute: the build side co-partitions
        first (it must be complete before any probe row can match), then
        the probe side ships in ``exchange_overlap_blocks`` double-
        buffered send blocks — the ``all_to_all`` for block k+1 issues
        before the join kernel consumes block k, so ICI transfer and
        compute overlap instead of running as exchange-then-compute
        phases. Build artifacts (the dense table or the sorted build) are
        hoisted OUT of the per-block consume, so the per-block work is
        pure probe. Bit-identical to the unoverlapped path: the consume
        is row-local and the block outputs restack to the one-shot row
        order (exchange._restack_blocks). Returns None when the pipeline
        doesn't apply (disabled, broadcast distribution, replicated
        inputs)."""
        from trino_tpu.obs import metrics as M
        from trino_tpu.obs import trace as tracing
        from trino_tpu.ops import join as join_ops
        from trino_tpu.parallel import exchange
        from trino_tpu.sql.planner import stats

        blocks = self._overlap_blocks()
        if blocks <= 1 or left.replicated or right.replicated:
            return None
        if not self._fused_join_enabled():
            # the per-block consume rides the fused module's merge tier;
            # disabling the fused tier must disable the pipeline too (the
            # kill switch covers ALL new join-kernel code paths)
            return None
        if not stats.join_repartitions(self.session, node, self.n_devices):
            return None
        right2 = self._repartition(right, node.right_keys, f"xchgr:{node.id}")
        left = self._narrowed_for_exchange(left)
        capacity = self.hint_capacity(f"xchgl:{node.id}", None)
        # ---- build artifacts, hoisted out of the per-block consume (the
        # per-block work must be pure probe: one dense table / membership
        # LUT / sorted build, shared by every block)
        dense = self._dense_join_cols(node, left, right2)
        table = lut = build = None
        if dense is not None:
            bc, pc, lo, span = dense
            if semi:
                lut = join_ops.dense_membership_table(
                    _col_to_lowered(bc), right2.sel, lo, span)
            else:
                table = join_ops.dense_unique_table(
                    _col_to_lowered(bc), right2.sel, lo, span)
            M.FUSED_JOIN_SELECTIONS.inc(1, "dense")
        else:
            bk, _pk = self._join_keys_aligned(
                left, right2, node.left_keys, node.right_keys)
            build = join_ops.build_side(
                bk, right2.sel,
                presorted=self._build_presorted(right2, node.right_keys))
        recorded = [False]  # first consume records the merge-tier selection

        def consume(lp: Page) -> Page:
            if dense is not None:
                bc, pc, lo, span = dense
                plowered = _col_to_lowered(lp.columns[node.left_keys[0]])
                if semi:
                    hit = join_ops.dense_membership_probe(lut, plowered, lo)
                else:
                    rows, matched = join_ops.dense_probe_unique(
                        table, plowered, lo)
            else:
                bkeys, pkeys = self._join_keys_aligned(
                    lp, right2, node.left_keys, node.right_keys)
                # the tier selection is counted ONCE per join (first
                # block), not once per send block
                rows, matched = self._merge_sorted_tier(
                    node, lp, right2, build, bkeys, pkeys,
                    record=not recorded[0])
                recorded[0] = True
                if semi:
                    hit = matched
            if semi:
                keep = hit if node.join_type == "semi" else ~hit
                sel = keep if lp.sel is None else lp.sel & keep
                return Page(lp.columns, sel, lp.replicated)
            return self._assemble_lookup_output(
                node, lp, right2, rows, matched)

        with tracing.span("exchange/overlap") as sp:
            sp.set("blocks", blocks)
            sp.set("join", node.id)
            out, overflow = exchange.repartition_page_overlapped(
                left, node.left_keys, self.n_devices, capacity, AXIS,
                blocks, consume)
        self.errors.append((f"CAPACITY_EXCEEDED:xchgl:{node.id}", overflow))
        M.EXCHANGE_OVERLAPPED.inc(1, str(blocks))
        return out

    def lookup_join(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        out = self._overlapped_join(node, left, right, semi=False)
        if out is not None:
            return out
        rp = self._join_repartitioned(node, left, right)
        if rp is not None:
            return Executor.lookup_join(self, node, *rp)
        # broadcast exchange: replicate the (small, unique-keyed) build side
        return super().lookup_join(node, left, gather_page(right))

    def semi_join(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        out = self._overlapped_join(node, left, right, semi=True)
        if out is not None:
            return out
        rp = self._join_repartitioned(node, left, right)
        if rp is not None:
            return Executor.semi_join(self, node, *rp)
        return super().semi_join(node, left, gather_page(right))

    def singleton_cross(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        return super().singleton_cross(node, left, gather_page(right))

    def expand_join(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        rp = self._join_repartitioned(node, left, right)
        if rp is not None:
            return Executor.expand_join(self, node, *rp)
        # M:N expansion probes stay local; the build side is broadcast.
        # Stats-estimated capacity hints upper-bound every shard's local
        # match count (probe shard ⊆ all probes).
        return super().expand_join(node, left, gather_page(right))

    def semi_join_filtered(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        rp = self._join_repartitioned(node, left, right)
        if rp is not None:
            return Executor.semi_join_filtered(self, node, *rp)
        return super().semi_join_filtered(node, left, gather_page(right))

    # ----------------------------------------------------------- set ops
    def _exec_UnionNode(self, node) -> Page:
        """UNION ALL of shards is the union of per-shard concatenations —
        unless replication statuses differ, where local concat would
        multiply the replicated side; gather everything then."""
        pages = [self.execute(s) for s in node.sources_]
        if len({p.replicated for p in pages}) > 1:
            pages = [gather_page(p) for p in pages]
        out = pages[0]
        for p in pages[1:]:
            out = Page.concat_pages(out, p)
        return out

    def set_op_pages(self, node, left: Page, right: Page) -> Page:
        """Whole-row membership needs equal rows co-located: big inputs
        co-partition by row hash over ALL columns (NULLs hash to a constant
        so set-semantics NULL equality survives the exchange); the combined
        page carries an explicit side-tag column through the shuffle. Small
        inputs gather (cheaper than an exchange)."""
        from trino_tpu.sql.planner import stats

        if (left.replicated or right.replicated
                or not stats.setop_repartitions(self.session, node, self.n_devices)):
            return super().set_op_pages(node, gather_page(left), gather_page(right))
        both = Page.concat_pages(left, right)
        n_l = left.num_rows
        side = jnp.arange(both.num_rows, dtype=jnp.int32) >= n_l
        tagged = Page(
            both.columns + [Column(T.BOOLEAN, side)], both.sel, both.replicated
        )
        recv = self._repartition(
            tagged, list(range(both.channel_count)), f"xchgs:{node.id}"
        )
        body = Page(recv.columns[:-1], recv.sel, recv.replicated)
        return self._set_op_grouped(node, body, recv.columns[-1].values)

    # --------------------------------------------------- distributed sort
    def _exec_TopNNode(self, node: P.TopNNode) -> Page:
        """Distributed top-N: per-shard top-N (the global top-N is a subset
        of the union of shard top-Ns), all_gather the N*D survivors (tiny),
        final local sort. The reference's TopNOperator-per-task + single
        merge consumer (MergeOperator), without gathering full shards."""
        page = self.execute(node.source)
        if page.replicated:
            return Executor.sorted_page(self, page, node.sort_channels, node.count)
        local = Executor.sorted_page(self, page, node.sort_channels, node.count)
        gathered = gather_page(_take_prefix(local, node.count))
        return Executor.sorted_page(self, gathered, node.sort_channels, node.count)

    def _exec_LimitNode(self, node: P.LimitNode) -> Page:
        """LIMIT without ordering: any N rows qualify — take N per shard,
        gather only those."""
        page = self.execute(node.source)
        if page.replicated:
            return Executor.sorted_page(self, page, [], node.count)
        local = Executor.sorted_page(self, page, [], node.count)
        gathered = gather_page(_take_prefix(local, node.count))
        return Executor.sorted_page(self, gathered, [], node.count)

    def _exec_SortNode(self, node: P.SortNode) -> Page:
        """Full ORDER BY: big inputs range-partition by sampled splitters
        and sort locally — the output stays SHARDED, globally ordered by
        device index (the reference's range exchange + ordered-merge
        consumer, redesigned: the 'merge' IS the mesh's device order,
        realized as concatenation order when the root gathers). Small
        inputs gather and sort locally."""
        from trino_tpu.sql.planner import stats

        page = self.execute(node.source)
        if page.replicated or not stats.sort_repartitions(
                self.session, node.source, self.n_devices):
            return Executor.sorted_page(self, gather_page(page), node.sort_channels)
        recv = self._range_exchange(page, node.sort_channels, f"xchgo:{node.id}")
        return Executor.sorted_page(self, recv, node.sort_channels)

    SORT_SAMPLES_PER_SHARD = 32

    def _range_exchange(self, page: Page, sort_channels, hint_key: str) -> Page:
        """Route rows to devices by lexicographic comparison against
        sampled splitters, so device d receives exactly the d-th key range.
        Splitters come from per-shard evenly spaced samples of the locally
        sorted keys, all_gathered and re-sampled — the classic sample-sort
        recipe; skew beyond the capacity hint doubles-and-recompiles."""
        from trino_tpu.ops import sort as sort_ops
        from trino_tpu.parallel import exchange

        n = page.num_rows
        live = page.sel if page.sel is not None else jnp.ones((n,), bool)
        keys = [
            ((page.columns[c].values,
              None if page.columns[c].nulls is None else ~page.columns[c].nulls),
             asc, nf)
            for c, asc, nf in sort_channels
        ]
        t_ops = sort_ops._sort_operands(keys, None)  # ascending-comparable
        # local live-first key sort -> evenly spaced live samples
        s_ops = jax.lax.sort(
            tuple([~live] + t_ops), num_keys=1 + len(t_ops), is_stable=True
        )[1:]
        nlive = jnp.maximum(jnp.sum(live).astype(jnp.int32), 1)
        m = self.SORT_SAMPLES_PER_SHARD
        pos = jnp.clip(
            ((jnp.arange(m, dtype=jnp.int32) * 2 + 1) * nlive) // (2 * m), 0, n - 1
        )
        samples = [o[pos] for o in s_ops]
        gath = [jax.lax.all_gather(s, AXIS).reshape(-1) for s in samples]
        gsorted = jax.lax.sort(tuple(gath), num_keys=len(gath), is_stable=True)
        total = m * self.n_devices
        sp_pos = (jnp.arange(1, self.n_devices, dtype=jnp.int32) * total) // self.n_devices
        splitters = [g[sp_pos] for g in gsorted]
        # pid = number of splitters the row is lexicographically greater
        # than (ties co-locate on the lower device)
        pid = jnp.zeros((n,), jnp.int32)
        for d in range(self.n_devices - 1):
            gt = jnp.zeros((n,), bool)
            eq = jnp.ones((n,), bool)
            for o, sp in zip(t_ops, splitters):
                gt = gt | (eq & (o > sp[d]))
                eq = eq & (o == sp[d])
            pid = pid + gt.astype(jnp.int32)
        capacity = self.hint_capacity(hint_key, None)
        out, overflow = exchange.repartition_by_pid(
            page, pid, self.n_devices, capacity, AXIS
        )
        self.errors.append((f"CAPACITY_EXCEEDED:{hint_key}", overflow))
        return out

    def sorted_page(self, page: Page, sort_channels, limit=None) -> Page:
        return super().sorted_page(gather_page(page), sort_channels, limit)

    def window_over_page(self, node, page: Page) -> Page:
        """Windows need whole partitions co-located: big partitioned inputs
        hash-repartition by the PARTITION BY keys; global frames (no
        partition keys) and small inputs gather."""
        from trino_tpu.sql.planner import stats

        if (page.replicated
                or not stats.window_repartitions(self.session, node, self.n_devices)):
            return super().window_over_page(node, gather_page(page))
        recv = self._repartition(page, node.partition_channels, f"xchgw:{node.id}")
        return Executor.window_over_page(self, node, recv)


def _take_prefix(page: Page, k: int) -> Page:
    """First k slots of a page (static slice; sorted pages carry their live
    rows as a prefix)."""
    k = min(k, page.num_rows)
    return Page(
        [
            Column(c.type, c.values[:k],
                   None if c.nulls is None else c.nulls[:k],
                   c.dictionary, c.vrange)
            for c in page.columns
        ],
        page.sel[:k] if page.sel is not None else None,
        page.replicated,
    )


def stage_sharded_scans(session, root: P.OutputNode, n_devices: int,
                        dyn_domains=None, profile=None):
    """Enumerate splits per scan, load per-device shards, pad to a common
    per-device shape, stack [ndev, rows]. This is the SOURCE_DISTRIBUTION
    split assignment done statically. ``dyn_domains`` carries phase-1
    resolved dynamic-filter domains (exec/host_eval.py) — the reference's
    split-time DynamicFilter blocking, realised as two-phase execution:
    probe splits are enumerated AND row-filtered under the build-side key
    domains before any device sees them.

    Each scan's stacked shard arrays consult the device table cache
    (trino_tpu/devcache/) first: a warm entry skips split enumeration,
    generation/IO, dynamic-domain pruning, AND the host->device transfer
    — the shard component of the key pins the mesh width, so a cache
    built for one device count never serves another."""
    from trino_tpu import devcache
    from trino_tpu.exec.executor import (
        dynamic_domain_map, scan_constraint_with)

    dyn_domains = dyn_domains or {}
    staged: Dict[int, List] = {}
    specs: Dict[int, PageSpec] = {}
    for node in P.walk_plan(root):
        if not isinstance(node, P.TableScanNode):
            continue
        constraint = scan_constraint_with(node, dyn_domains)

        def load(node=node, constraint=constraint):
            from trino_tpu.exec import staging as _staging
            from trino_tpu.obs import metrics as _M
            from trino_tpu.obs import trace as _tracing

            arrays, spec, total_rows = _stage_scan_shards(
                session, node, n_devices, constraint, dyn_domains, profile)
            # cache-resident arrays live ON DEVICE: transfer here (a
            # no-op for already-device arrays), so a warm hit hands back
            # HBM-resident shards with zero host work. The stacked
            # [ndev, rows] shard arrays move in double-buffered blocks
            # along the rows axis (exec/staging.blocked_transfer).
            t0 = _time.perf_counter()
            with _tracing.span("staging/transfer", table=node.table) as sp:
                xfer = _staging.blocked_transfer()
                arrays = [xfer(a) if isinstance(a, np.ndarray)
                          else jnp.asarray(a) for a in arrays]
                sp.set("arrays", len(arrays))
            _M.STAGING_PHASE_SECONDS.inc(_time.perf_counter() - t0,
                                         "transfer")
            nbytes = sum(int(a.size) * a.dtype.itemsize for a in arrays)
            return (arrays, spec, total_rows), total_rows, nbytes, n_devices

        ent, _disposition = devcache.cached_stage(
            session, node, constraint, dynamic_domain_map(node, dyn_domains),
            f"spmd:{n_devices}", load)
        arrays, spec, total_rows = ent.value
        staged[node.id] = arrays
        specs[node.id] = spec
        node.runtime_rows = total_rows  # staged truth for capacity estimates
    return staged, specs


def _stage_scan_shards(session, node, n_devices: int, constraint,
                       dyn_domains, profile=None):
    """Stage ONE scan's per-device shards: ``(arrays, PageSpec,
    total_rows)`` — the cold path behind the device-cache loader. Split
    reads run through the pipelined engine (exec/staging.py): the
    adaptive target fans big tables out FINER than the mesh (contiguous
    fine-split groups per device), every fine split consults the host-RAM
    tier — so a mesh-width change regroups warm host entries instead of
    re-running the connector — and scans overlap on the shared pool."""
    from trino_tpu.exec import staging
    from trino_tpu.exec.executor import (
        apply_dynamic_domains, dynamic_domain_map)

    conn = session.catalogs[node.catalog]
    target = staging.target_split_count(
        session, conn, node.schema, node.table, floor=n_devices,
        handle=node.table_handle)
    splits = conn.get_splits(
        node.schema, node.table, target, constraint=constraint,
        handle=node.table_handle)

    def prune(datas):
        return apply_dynamic_domains(node, dyn_domains, datas)

    split_datas, prof = staging.stage_splits(
        session, node, conn, splits, constraint, prune=prune,
        applied_domains=dynamic_domain_map(node, dyn_domains))
    if profile is not None:
        profile["df_apply_s"] = (
            profile.get("df_apply_s", 0.0) + prof.prune_s)
    # contiguous split groups per device: with <= n_devices splits, split
    # i stages on device i (the historical assignment — bit-compatible
    # with the pre-pipeline layout); finer adaptive split sets group into
    # n_devices contiguous covers so each shard still reads an ascending
    # key range and per-shard sortedness survives the concat
    if len(split_datas) <= n_devices:
        groups = [[split_datas[i]] if i < len(split_datas) else []
                  for i in range(n_devices)]
    else:
        bounds = [len(split_datas) * i // n_devices
                  for i in range(n_devices + 1)]
        groups = [split_datas[bounds[i]:bounds[i + 1]]
                  for i in range(n_devices)]
    total_rows = 0
    shard_pages = []
    for di in range(n_devices):
        group = [d for d in groups[di] if d]
        if group:
            data = group[0] if len(group) == 1 else {
                name: spi_mod.concat_column_data([g[name] for g in group])
                for name in node.column_names
            }
            if data:
                total_rows += len(next(iter(data.values())).values)
        else:
            # devices beyond the split count scan NOTHING. Built here
            # from the scan node's own schema — no connector round-trip:
            # a synthetic empty Split would either clobber a pushdown
            # handle riding Split.info (breaking schema resolution for
            # pushed aggregations) or, preserved, re-run a GLOBAL pushed
            # statement on every extra device (duplicating rows).
            from trino_tpu.data.page import Column as _Col

            data = {
                name: spi_mod.column_data_from_column(
                    _Col.from_python(typ, []))
                for name, typ in zip(node.column_names, node.column_types)
            }
        cols = []
        for name, typ in zip(node.column_names, node.column_types):
            cd = data[name]
            vals = np.asarray(cd.values)
            # physical narrowing, same rule as staging.page_from_host_columns:
            # table-wide ranges keep every shard dtype-uniform
            if vals.dtype == np.int64 and page_mod.fits_int32(cd.vrange):
                vals = vals.astype(np.int32)
            cols.append(
                Column(
                    typ,
                    vals,
                    np.asarray(cd.nulls) if cd.nulls is not None else None,
                    cd.dictionary,
                    cd.vrange,
                    hi=np.asarray(cd.hi) if cd.hi is not None else None,
                )
            )
        shard_pages.append(cols)
    max_rows = max((len(c[0].values) if c else 0) for c in shard_pages)
    max_rows = max(max_rows, 1)
    # unify per-shard dictionaries: codes must mean the same string on
    # every device (the "stable dictionary ids" FTE determinism concern,
    # SURVEY.md §7.3 item 8)
    for ci, typ in enumerate(node.column_types):
        if not typ.is_varchar:
            continue
        merged = shard_pages[0][ci].dictionary
        for p in shard_pages[1:]:
            if p[ci].dictionary.values != merged.values:
                merged = merged.merge(p[ci].dictionary)
        for p in shard_pages:
            d = p[ci].dictionary
            if d.values != merged.values:
                table = np.asarray(d.recode_table(merged))
                codes = np.asarray(p[ci].values)
                p[ci] = Column(
                    typ,
                    np.where(codes >= 0, table[np.clip(codes, 0, None)], -1).astype(np.int32),
                    p[ci].nulls,
                    merged,
                )
            else:
                p[ci] = Column(typ, p[ci].values, p[ci].nulls, merged)
    stacked_cols = []
    for ci in range(len(node.column_names)):
        anyhi = any(p[ci].hi is not None for p in shard_pages)
        vals = np.stack(
            [
                _pad(np.asarray(p[ci].values).astype(np.int64)
                     if anyhi else np.asarray(p[ci].values), max_rows)
                for p in shard_pages
            ]
        )
        anynull = any(p[ci].nulls is not None for p in shard_pages)
        nulls = (
            np.stack(
                [
                    _pad(
                        np.asarray(p[ci].nulls)
                        if p[ci].nulls is not None
                        else np.zeros(len(p[ci].values), bool),
                        max_rows,
                    )
                    for p in shard_pages
                ]
            )
            if anynull
            else None
        )
        # hi-limb presence must be uniform across shards (the PageSpec
        # is static): missing shards sign-extend their low words
        hi = (
            np.stack(
                [
                    _pad(
                        np.asarray(p[ci].hi)
                        if p[ci].hi is not None
                        else (np.asarray(p[ci].values).astype(np.int64) >> 63),
                        max_rows,
                    )
                    for p in shard_pages
                ]
            )
            if anyhi
            else None
        )
        stacked_cols.append((vals, nulls, hi, shard_pages[0][ci].dictionary))
    sel = np.stack(
        [
            np.arange(max_rows) < len(p[0].values) if p else np.zeros(max_rows, bool)
            for p in shard_pages
        ]
    )
    arrays = []
    col_specs = []
    vranges = [c.vrange for c in shard_pages[0]]
    for (vals, nulls, hi, d), typ, vr in zip(
            stacked_cols, node.column_types, vranges):
        arrays.append(vals)
        if nulls is not None:
            arrays.append(nulls)
        if hi is not None:
            arrays.append(hi)
        col_specs.append(ColSpec(
            typ, d, nulls is not None, vr, has_hi=hi is not None))
    arrays.append(sel)
    return arrays, PageSpec(col_specs, True), total_rows


def _pad(a: np.ndarray, n: int) -> np.ndarray:
    if len(a) == n:
        return a
    pad = np.zeros((n - len(a),) + a.shape[1:], dtype=a.dtype)
    return np.concatenate([a, pad])


@dataclasses.dataclass
class DistributedQuery:
    """A query compiled to one shard_map program over a device mesh."""

    mesh: Mesh
    fn: object
    inputs: List
    out_spec_cell: List
    error_codes_cell: List
    session: object = None
    root: P.OutputNode = None
    capacity_hints: Dict[str, int] = dataclasses.field(default_factory=dict)
    # two-phase profile (see CompiledQuery): benchmarks charge this host
    # time to every run — it is query work done off-device
    phase1_s: float = 0.0
    df_apply_s: float = 0.0
    # capacity-overflow regrowth recompiles (0 when the hints were right
    # the first time — e.g. under adaptive_capacity_reseed)
    recompiles: int = 0
    # kernel-ledger rollup (obs/devprofiler.py): one "SpmdBody" row
    # accumulating this query's shard_map-body dispatches
    kernel_stats: Dict[tuple, dict] = dataclasses.field(default_factory=dict)
    # compile-ledger identity, computed lazily once per instance
    _fingerprint: str = ""

    MAX_RECOMPILES = 16

    @classmethod
    def build(
        cls, session, root: P.OutputNode, mesh: Mesh, capacity_hints: Dict[str, int] = None
    ) -> "DistributedQuery":
        """Two-phase compile (see CompiledQuery.build): phase 1 host-resolves
        dynamic-filter domains, scans stage narrowed, and capacities estimate
        from staged truth (global totals upper-bound each shard); overflow at
        runtime doubles the bucket and recompiles (see CompiledQuery.run)."""
        from trino_tpu.exec import host_eval
        from trino_tpu.sql.planner import stats

        n_devices = mesh.devices.size
        # a ROOT-level ORDER BY over nested (array/map/row) outputs cannot
        # sort under tracing (the nested host-sort fallback needs concrete
        # arrays); peel it off the traced plan and apply it host-side after
        # the gather — semantically identical (the sort is the last step)
        post_sort = None
        if (isinstance(root.source, P.SortNode)
                and any(t.is_nested for t in root.source.output_types)):
            post_sort = list(root.source.sort_channels)
            root = P.OutputNode(root.source.source, root.column_names)
        t0 = _time.perf_counter()
        dyn = host_eval.resolve_dynamic_filters(session, root)
        phase1_s = _time.perf_counter() - t0
        prof: Dict[str, float] = {}
        staged_arrays, specs = stage_sharded_scans(
            session, root, n_devices, dyn, profile=prof)
        if capacity_hints is None:
            capacity_hints = stats.estimate_capacity_hints(session, root)
            capacity_hints.update(stats.estimate_exchange_hints(session, root, n_devices))
        from trino_tpu.adaptive.reseed import (
            apply_reseed, reseed_enabled, staged_pages_from_arrays)

        if reseed_enabled(session):
            # adaptive capacity reseeding: per-(shard, partition) key
            # histograms of the STAGED rows price expansion joins and the
            # hash-exchange send blocks exactly — skewed keys size their
            # real hot partition instead of the 2x-uniform guess, so the
            # run loop never pays a regrowth recompile
            pages = staged_pages_from_arrays(staged_arrays, specs)
            apply_reseed(session, root, pages, n_devices, capacity_hints)
        layout = [(nid, len(arrs)) for nid, arrs in staged_arrays.items()]
        flat_inputs: List = []
        for _, arrs in staged_arrays.items():
            flat_inputs.extend(jnp.asarray(a) for a in arrs)
        dq = cls(mesh, None, flat_inputs, [None], [None], session, root, dict(capacity_hints))
        dq.phase1_s = phase1_s
        dq.df_apply_s = prof.get("df_apply_s", 0.0)
        dq._layout = layout
        dq._specs = specs
        dq._post_sort = post_sort
        dq._jit()
        return dq

    def _jit(self):
        session, root = self.session, self.root
        layout, specs, hints = self._layout, self._specs, self.capacity_hints
        out_spec_cell, error_codes_cell = self.out_spec_cell, self.error_codes_cell

        def per_shard(flat):
            # flat arrays arrive with the device axis stripped by shard_map
            pages: Dict[int, Page] = {}
            i = 0
            for nid, count in layout:
                local = [a.reshape(a.shape[1:]) for a in flat[i : i + count]]
                pages[nid] = unflatten_page(specs[nid], local)
                i += count
            ex = SpmdExecutor(session, pages, dict(hints), n_devices=self.mesh.devices.size)
            out_page = ex.execute(root)
            if not out_page.replicated:
                # scan/filter/project-only plans never hit an exchange:
                # gather so run() sees the full result, not shard 0's slice
                out_page = gather_page(out_page)
            out_arrays, out_spec = flatten_page(out_page)
            out_spec_cell[0] = out_spec
            error_codes_cell[0] = [c for c, _ in ex.errors]
            # re-add a leading device axis so out_specs can shard it
            return (
                [a[None] for a in out_arrays],
                [f[None] for _, f in ex.errors],
            )

        shard_fn = _shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=(PSpec(AXIS),),
            out_specs=(PSpec(AXIS), PSpec(AXIS)),
        )
        self.fn = jax.jit(shard_fn)
        # compile-cache state (see CompiledQuery._jit): the next call on
        # this jitted callable traces + compiles (a miss); later calls
        # reuse the executable (hits) — the compile ledger records both
        self._executable_fresh = True

    def _profile_run(self, fresh: bool, dispatch_wall_s: float,
                     body_device_s: float, estimated: bool) -> None:
        """Feed the device profiler: one compile-ledger event per run + a
        ``SpmdBody`` kernel row. Best-effort — accounting never fails."""
        try:
            from trino_tpu.cache.plan_key import plan_fingerprint
            from trino_tpu.obs.devprofiler import (
                DEVICE_PROFILER, shape_signature)

            if not self._fingerprint:
                self._fingerprint = plan_fingerprint(self.root)
            DEVICE_PROFILER.record_compile(
                "spmd", self._fingerprint, shape_signature(self.inputs),
                dispatch_wall_s if fresh else 0.0,
                "miss" if fresh else "hit", started=fresh)
            wall = (body_device_s if fresh
                    else dispatch_wall_s + (0.0 if estimated
                                            else body_device_s))
            key = (str(self.root.id), "SpmdBody", "spmd")
            ks = self.kernel_stats.get(key)
            if ks is None:
                ks = self.kernel_stats[key] = {
                    "planNodeId": key[0], "operator": key[1],
                    "tier": "spmd", "launches": 0, "wallS": 0.0,
                    "deviceS": 0.0, "inputBytes": 0, "outputBytes": 0,
                    "estimated": estimated}
            ks["launches"] += 1
            ks["wallS"] += wall
            ks["deviceS"] += body_device_s
            ks["estimated"] = bool(ks["estimated"] or estimated)
            DEVICE_PROFILER.count_launch(wall, body_device_s
                                         if not estimated else 0.0)
        except Exception:  # noqa: BLE001 — accounting never fails work
            pass

    def run(self) -> Page:
        from trino_tpu.exec.executor import QueryError, raise_query_errors
        from trino_tpu.sql.planner import stats

        for _ in range(self.MAX_RECOMPILES):
            fresh = getattr(self, "_executable_fresh", False)
            if fresh:
                try:
                    from trino_tpu.obs.devprofiler import DEVICE_PROFILER

                    DEVICE_PROFILER.compile_started()
                except Exception:  # noqa: BLE001 — accounting only
                    pass
            t0 = _time.perf_counter()
            out_arrays, error_flags = self.fn(self.inputs)
            dispatch_s = _time.perf_counter() - t0
            props = getattr(self.session, "properties", None) or {}
            sync = bool(props.get("device_profiling", False))
            body_device_s = 0.0 if fresh else dispatch_s
            estimated = True
            if sync:
                t_sync = _time.perf_counter()
                try:
                    jax.block_until_ready(out_arrays)
                except Exception:  # noqa: BLE001 — profiling never fails
                    pass
                body_device_s = _time.perf_counter() - t_sync
                estimated = False
            self._profile_run(fresh, dispatch_s, body_device_s, estimated)
            self._executable_fresh = False
            codes = self.error_codes_cell[0]
            # flags are stacked per device: overflow on ANY shard grows the
            # bucket (capacity first — other flags may be truncation artifacts)
            grown = stats.grow_overflowed_hints(self.capacity_hints, codes, error_flags)
            if grown is not None:
                self.capacity_hints = grown
                self.recompiles += 1
                self._jit()
                continue
            raise_query_errors(codes, error_flags)
            # results are replicated across shards post-gather: take shard 0
            local = [np.asarray(a)[0] for a in out_arrays]
            page = unflatten_page(self.out_spec_cell[0], local)
            post_sort = getattr(self, "_post_sort", None)
            if post_sort is not None:
                from trino_tpu.exec.executor import Executor

                page = Executor(self.session).sorted_page(page, post_sort)
            return page
        raise QueryError("capacity still exceeded after recompiles (join or exchange bucket)")
