"""Hash-partitioned exchange: `all_to_all` over ICI inside the compiled program.

Reference: the FIXED_HASH_DISTRIBUTION repartition shuffle —
``SystemPartitioningHandle.java:50``, producer ``PagePartitioner.java:134-149``
(column-wise partition strategy: compute all partition assignments, then per
partition append each column's selected positions), consumer
``ExchangeOperator``/``DirectExchangeClient``. TPU redesign (SURVEY.md §7.1
"shuffle = collective"): the producer/wire/consumer trio compiles into the
query program itself —

1. partition id per row = mix64 hash of the key columns mod n_devices
   (identical on every device; NULL keys hash to a constant so equal keys —
   and all NULLs — co-locate);
2. rows sort by partition id (one fused int32 sort — the column-wise
   gather-by-partition strategy, which is exactly the sorted formulation);
3. each partition's rows gather into a static [n_devices, capacity] send
   buffer (capacity from stats; overflow raises the deferred
   ``CAPACITY_EXCEEDED:xchg*`` flag and the run loop doubles + recompiles —
   the skew story);
4. ``jax.lax.all_to_all`` swaps blocks across the mesh axis (ICI);
5. received blocks flatten into a new sharded Page (pad slots dead).

The wire format IS the device layout — no serialization, no backpressure,
no HTTP: XLA schedules the collective against compute.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.data.page import Column, Page
from trino_tpu.ops import ranks

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]

# numpy (host) scalars, NOT jnp: a jnp scalar built at first import
# INSIDE a traced region (shard_map lazily importing this module)
# becomes a tracer and leaks across traces on jax 0.4.x
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_NULL_HASH = np.uint64(0x9E3779B97F4A7C15)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer (public-domain constant mix; wraps mod 2^64)."""
    x = (x ^ (x >> 30)) * _M1
    x = (x ^ (x >> 27)) * _M2
    return x ^ (x >> 31)


def partition_ids(keys: List[Lowered], n_devices: int) -> jnp.ndarray:
    """int32[n] partition id per row: combined key hash mod n_devices.
    Deterministic and device-independent (FTE determinism: replayed
    exchanges produce identical partitions, SURVEY.md §5.4)."""
    n = keys[0][0].shape[0]
    h = jnp.zeros((n,), jnp.uint64)
    for vals, valid in keys:
        k = _mix64(vals.astype(jnp.int64).astype(jnp.uint64))
        if valid is not None:
            k = jnp.where(valid, k, _NULL_HASH)
        h = _mix64(h ^ k)
    return (h % jnp.uint64(n_devices)).astype(jnp.int32)


def spread_partition_ids(pid: np.ndarray, hot_partitions, n_parts: int,
                         start: int = 0) -> Tuple[np.ndarray, int]:
    """Salted spread of HOT partitions (host half of the adaptive skew
    mitigation, trino_tpu/adaptive/replanner.py): rows whose key hash
    landed in a hot partition are re-dealt round-robin across ALL
    partitions, deterministically by row position (FTE replay produces
    identical placement). ``start`` is the producer's rotating cursor —
    streaming producers call this once per output page, and restarting at
    partition 0 each page would pile every page's few hot rows onto the
    low-numbered partitions, re-creating the skew; the caller threads the
    returned cursor into the next call. Exactness contract: the spread
    side's rows lose key co-location, so the OTHER join side must
    replicate the same hot partitions into every partition — a spread
    probe row then finds its (hot-key) build matches wherever it lands,
    while rows of non-hot partitions cannot spuriously match replicated
    hot-key rows (their key hashes differ by construction). One hot key
    stops serializing on one task; the price is |hot build| x n_parts
    replicated bytes.

    Returns ``(new_pid, next_start)``."""
    pid = np.asarray(pid).copy()
    hot = np.asarray(sorted(hot_partitions), dtype=pid.dtype)
    idx = np.flatnonzero(np.isin(pid, hot))
    pid[idx] = ((start + np.arange(len(idx))) % n_parts).astype(pid.dtype)
    return pid, (start + len(idx)) % n_parts


def page_partition_ids(page: Page, key_channels: List[int],
                       n_devices: int) -> jnp.ndarray:
    """Partition ids for a page's key columns — hoisted out of
    :func:`repartition_page` so callers that route the SAME page more than
    once (the overlapped per-block exchange, the adaptive salting path)
    hash it exactly once and reuse the array."""
    keys = [
        (page.columns[c].values,
         None if page.columns[c].nulls is None else ~page.columns[c].nulls)
        for c in key_channels
    ]
    return partition_ids(keys, n_devices)


def repartition_page(
    page: Page,
    key_channels: List[int],
    n_devices: int,
    capacity: int,
    axis: str,
) -> Tuple[Page, jnp.ndarray]:
    """Hash-repartition a sharded page over the mesh axis.

    Returns (received_page [n_devices*capacity rows, sharded], overflow_flag).
    Dead rows (sel False) are not sent; received pad slots carry sel False.
    Callers that route the same page more than once hash it once via
    :func:`page_partition_ids` + :func:`repartition_by_pid` (the
    overlapped per-block exchange does this internally).
    """
    for c in page.columns:
        if c.hi is not None or c.type.is_nested:
            raise NotImplementedError(
                "device hash exchange over long-decimal/nested columns")
    pid = page_partition_ids(page, key_channels, n_devices)
    return repartition_by_pid(page, pid, n_devices, capacity, axis)


def _send_plan(page: Page, pid: jnp.ndarray, n_devices: int):
    """(order, starts, counts): the routing plan shared by the one-shot
    exchange and the overlapped per-block exchange — rows sorted by
    partition id (dead rows last) and each partition's [start, count)
    range in sorted space (merge ranks, no search)."""
    n = page.num_rows
    live = page.sel if page.sel is not None else jnp.ones((n,), bool)
    pid = jnp.where(live, pid, jnp.int32(n_devices))  # dead rows sort last
    order = ranks.argsort32(pid)
    pid_sorted = pid[order]
    starts, counts = ranks.sorted_ranks(
        [pid_sorted], [jnp.arange(n_devices, dtype=jnp.int32)]
    )
    return order, starts, counts


def _xchg_block(page: Page, order, starts, counts, lo: int, cap: int,
                n_devices: int, axis: str) -> Page:
    """Exchange send-slot range [lo, lo+cap) of every partition: gather
    the block's rows, ``all_to_all`` them across the mesh axis, and
    assemble the received page (pad slots dead)."""
    n = page.num_rows
    j = lo + jnp.arange(cap, dtype=jnp.int32)
    slot_idx = jnp.clip(starts[:, None] + j[None, :], 0, n - 1)  # [ndev, cap]
    send_live = j[None, :] < counts[:, None]
    rows = order[slot_idx]  # original row index per send slot

    def xchg(a: jnp.ndarray) -> jnp.ndarray:
        recv = jax.lax.all_to_all(a, axis, split_axis=0, concat_axis=0, tiled=False)
        return recv.reshape((n_devices * cap,) + recv.shape[2:])

    out_cols = []
    for c in page.columns:
        vals = xchg(c.values[rows])
        nulls = xchg(c.nulls[rows]) if c.nulls is not None else None
        out_cols.append(Column(c.type, vals, nulls, c.dictionary, c.vrange))
    sel = xchg(send_live)
    return Page(out_cols, sel, replicated=False)


def repartition_by_pid(
    page: Page,
    pid: jnp.ndarray,
    n_devices: int,
    capacity: int,
    axis: str,
) -> Tuple[Page, jnp.ndarray]:
    """Repartition by a PRECOMPUTED per-row partition id (int32 in
    [0, n_devices)): the shared producer half of both the hash exchange
    (FIXED_HASH_DISTRIBUTION) and the range exchange used by the sharded
    distributed sort (the reference's range-partitioned MergeOperator
    pipeline, redesigned as splitter-routed all_to_all)."""
    order, starts, counts = _send_plan(page, pid, n_devices)
    overflow = jnp.any(counts > capacity)
    out = _xchg_block(page, order, starts, counts, 0, capacity,
                      n_devices, axis)
    return out, overflow


def repartition_page_overlapped(
    page: Page,
    key_channels: List[int],
    n_devices: int,
    capacity: int,
    axis: str,
    n_blocks: int,
    consume,
) -> Tuple[Page, jnp.ndarray]:
    """Hash-repartition with the send buffer split into ``n_blocks``
    double-buffered blocks, each consumed as it lands: the ``all_to_all``
    for block k+1 is ISSUED (in program order) before ``consume`` runs on
    block k, so XLA's async collective scheduler overlaps the ICI
    transfer with compute — the exchange-then-compute barrier of the
    one-shot path becomes a pipeline.

    ``consume(received_block_page) -> Page`` must be ROW-LOCAL (each
    output row a function of its input row plus replicated state — the
    probe side of a lookup/semi join against an already-exchanged build).
    Under that contract the assembled result is BIT-IDENTICAL to
    ``consume(repartition_page(...))``: per-block outputs restack from
    block-major to the one-shot path's device-major row order before
    concatenation (a static transpose, no data-dependent movement).

    The effective capacity rounds up to a whole number of blocks;
    returns (assembled_page, overflow_flag).
    """
    for c in page.columns:
        if c.hi is not None or c.type.is_nested:
            raise NotImplementedError(
                "device hash exchange over long-decimal/nested columns")
    n_blocks = max(int(n_blocks), 1)
    bcap = -(-capacity // n_blocks)
    pid = page_partition_ids(page, key_channels, n_devices)
    order, starts, counts = _send_plan(page, pid, n_devices)
    overflow = jnp.any(counts > bcap * n_blocks)
    out_pages: List[Page] = []
    prev = _xchg_block(page, order, starts, counts, 0, bcap, n_devices, axis)
    for b in range(1, n_blocks):
        # issue block b's collectives BEFORE consuming block b-1: the
        # program-order gap is what the latency-hiding scheduler fills
        nxt = _xchg_block(page, order, starts, counts, b * bcap, bcap,
                          n_devices, axis)
        out_pages.append(consume(prev))
        prev = nxt
    out_pages.append(consume(prev))
    return _restack_blocks(out_pages, n_devices, bcap), overflow


def _restack_blocks(pages: List[Page], n_devices: int, bcap: int) -> Page:
    """Reorder per-block consume outputs (block-major) into the one-shot
    exchange's row order (device-major): rows [b][dev][slot] transpose to
    [dev][b][slot] and flatten — device d's region is then its blocks in
    order, exactly the unoverlapped layout."""
    n_blocks = len(pages)
    if n_blocks == 1:
        return pages[0]

    def restack(arrays: List[jnp.ndarray]) -> jnp.ndarray:
        stacked = jnp.stack([
            a.reshape((n_devices, bcap) + a.shape[1:]) for a in arrays
        ])  # [blocks, ndev, bcap, ...]
        moved = jnp.moveaxis(stacked, 0, 1)  # [ndev, blocks, bcap, ...]
        return moved.reshape((n_devices * n_blocks * bcap,) + moved.shape[3:])

    first = pages[0]
    out_cols = []
    for ci, c in enumerate(first.columns):
        vals = restack([p.columns[ci].values for p in pages])
        nulls = (restack([p.columns[ci].nulls for p in pages])
                 if c.nulls is not None else None)
        hi = (restack([p.columns[ci].hi for p in pages])
              if c.hi is not None else None)
        out_cols.append(Column(c.type, vals, nulls, c.dictionary, c.vrange,
                               hi=hi))
    sel = (restack([p.sel for p in pages])
           if first.sel is not None else None)
    return Page(out_cols, sel, replicated=first.replicated)
