"""In-memory tables connector.

Reference: ``plugin/trino-memory`` (3.7k LoC in-memory tables used heavily by
tests). Tables are registered programmatically (round 1; CREATE TABLE AS in a
later round) and served as single- or multi-split scans.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector import spi
from trino_tpu.data.dictionary import Dictionary


class MemoryConnector(spi.Connector):
    name = "memory"
    coordinator_only = True  # tables live in this process only
    supports_transactions = True  # overlay protocol (exec/transaction.py)

    def __init__(self):
        self._tables: Dict[Tuple[str, str], Tuple[spi.TableMetadata, Dict[str, spi.ColumnData]]] = {}
        # monotonic per-table mutation counter (the cache-invalidation
        # token): survives DROP so a re-created table keeps advancing
        self._versions: Dict[Tuple[str, str], int] = {}

    def _bump(self, schema: str, table: str) -> None:
        key = (schema, table)
        self._versions[key] = self._versions.get(key, 0) + 1

    def data_version(self, schema: str, table: str) -> str:
        return f"v{self._versions.get((schema, table), 0)}"

    def create_table(self, schema: str, name: str, schema_def: Sequence[Tuple[str, T.Type]], rows: List[tuple]):
        """Register a table from Python rows (None = NULL)."""
        from trino_tpu.data.page import Column

        cols: Dict[str, spi.ColumnData] = {}
        for i, (cname, ctype) in enumerate(schema_def):
            pycol = [r[i] for r in rows]
            col = Column.from_python(ctype, pycol)
            cols[cname] = spi.column_data_from_column(col)
        meta = spi.TableMetadata(
            schema, name, [spi.ColumnMetadata(n, t) for n, t in schema_def]
        )
        self._tables[(schema, name)] = (meta, cols)
        self._bump(schema, name)

    def overwrite_rows(self, schema: str, table: str, rows) -> None:
        """Replace contents (engine-computed DELETE/UPDATE rewrite)."""
        entry = self._tables.get((schema, table))
        if entry is None:
            raise KeyError(f"memory.{schema}.{table} does not exist")
        meta, _cols = entry
        from trino_tpu.data.page import Column

        new_cols = {
            cm.name: spi.column_data_from_column(
                Column.from_python(cm.type, [r[i] for r in rows]))
            for i, cm in enumerate(meta.columns)
        }
        self._tables[(schema, table)] = (meta, new_cols)
        self._bump(schema, table)

    def insert_rows(self, schema: str, table: str, rows: List[tuple]) -> int:
        """Append rows (reference: memory connector's page sink). New data
        is columnized independently and concatenated with dictionary merge."""
        entry = self._tables.get((schema, table))
        if entry is None:
            raise KeyError(f"memory.{schema}.{table} does not exist")
        meta, cols = entry
        if not rows:
            return 0
        from trino_tpu.data.page import Column

        # build ALL new columns before publishing: a mid-loop failure must
        # not leave the table with some columns longer than others
        # (auto-commit atomicity; reference: page sinks buffer then finish)
        new_cols = {}
        for i, cm in enumerate(meta.columns):
            pycol = [r[i] for r in rows]
            col = Column.from_python(cm.type, pycol)
            new = spi.column_data_from_column(col)
            new_cols[cm.name] = spi.concat_column_data([cols[cm.name], new])
        self._tables[(schema, table)] = (meta, {**cols, **new_cols})
        self._bump(schema, table)
        return len(rows)

    def drop_table(self, schema: str, table: str) -> None:
        self._tables.pop((schema, table), None)
        self._bump(schema, table)

    def list_schemas(self) -> List[str]:
        return sorted({s for s, _ in self._tables} | {"default"})

    def list_tables(self, schema: str) -> List[str]:
        return sorted(n for s, n in self._tables if s == schema)

    def get_table(self, schema: str, table: str) -> Optional[spi.TableMetadata]:
        entry = self._tables.get((schema, table))
        return entry[0] if entry else None

    def table_row_count(self, schema: str, table: str) -> Optional[int]:
        entry = self._tables.get((schema, table))
        if not entry:
            return None
        _, cols = entry
        first = next(iter(cols.values()), None)
        return 0 if first is None else len(first.values)

    def get_splits(self, schema: str, table: str, target_splits: int, constraint=None,
                   handle=None) -> List[spi.Split]:
        n = self.table_row_count(schema, table) or 0
        target_splits = max(1, min(target_splits, max(n, 1)))
        bounds = [n * i // target_splits for i in range(target_splits + 1)]
        return [
            spi.Split(table, schema, bounds[i], bounds[i + 1])
            for i in range(target_splits)
            if bounds[i] < bounds[i + 1] or n == 0
        ] or [spi.Split(table, schema, 0, 0)]

    def scan(self, split: spi.Split, columns: List[str], constraint=None) -> Dict[str, spi.ColumnData]:
        _, cols = self._tables[(split.schema, split.table)]
        out = {}
        for c in columns:
            out[c] = spi.column_data_slice(cols[c], split.lo, split.hi)
        return out
