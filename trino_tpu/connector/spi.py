"""Connector SPI.

Reference: ``core/trino-spi/src/main/java/io/trino/spi/connector/`` —
``ConnectorMetadata.java:80``, ``ConnectorSplitManager.java:19``,
``ConnectorPageSource.java:24``. Round-1 surface: metadata (schemas, tables,
columns, row-count stats), split enumeration (for distributed scans), and a
page source that materializes a projected column subset of a split as numpy
arrays (the engine moves them to device). Pushdown: the planner prunes
projections (``columns`` argument) and passes advisory TupleDomain
constraints (connector/predicate.py) to ``get_splits``/``scan``.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from trino_tpu import types as T
from trino_tpu.data.dictionary import Dictionary


@dataclasses.dataclass(frozen=True)
class ColumnMetadata:
    name: str
    type: T.Type


@dataclasses.dataclass(frozen=True)
class TableMetadata:
    schema: str
    name: str
    columns: Sequence[ColumnMetadata]

    def column_index(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(name)


@dataclasses.dataclass(frozen=True)
class ColumnStats:
    """CBO column statistics (reference: spi/statistics/ColumnStatistics).
    ``low``/``high`` are storage-repr bounds (scaled ints for decimals,
    epoch days for dates); ``ndv`` estimates distinct values."""

    low: Optional[int] = None
    high: Optional[int] = None
    ndv: Optional[int] = None
    null_fraction: float = 0.0

    @property
    def vrange(self) -> Optional[tuple]:
        if self.low is None or self.high is None:
            return None
        return (self.low, self.high)


@dataclasses.dataclass(frozen=True)
class Split:
    """A unit of scan parallelism (reference: spi/connector/ConnectorSplit).
    ``lo``/``hi`` are connector-interpreted bounds (e.g. row or key range)."""

    table: str
    schema: str
    lo: int
    hi: int
    info: object = None


@dataclasses.dataclass(frozen=True)
class SortItem:
    """One ORDER BY term for TopN pushdown (reference:
    spi/connector/SortItem)."""

    column: str
    ascending: bool = True
    nulls_first: bool = False


@dataclasses.dataclass(frozen=True)
class AggregateSpec:
    """One aggregate for aggregation pushdown (reference:
    spi/connector/AggregateFunction): ``column`` None = count(*)."""

    function: str  # count | sum | min | max
    column: Optional[str]
    output_type: T.Type


@dataclasses.dataclass(frozen=True)
class TablePartitioning:
    """Connector-declared physical partitioning (reference:
    ConnectorTablePartitioning + ConnectorNodePartitioningProvider): two
    tables whose partitionings share ``family`` split their rows by the
    SAME key boundaries — split i of one co-locates with split i of the
    other, so a join on the partitioning columns needs no exchange."""

    columns: tuple  # partitioning column names, in key order
    family: str  # co-location domain (same family => aligned splits)


@dataclasses.dataclass
class ColumnData:
    """One scanned column: numpy values (+nulls) host-side; the executor
    transfers to device. Varchar carries the dictionary.

    ``vrange`` is an optional TABLE-WIDE static (min, max) bound on the
    column's storage values (reference: spi/statistics ColumnStatistics
    min/max). Table-wide — not per-split — so every split of a table
    narrows to the same physical dtype (data/page.py Column.vrange) and
    pages stay dtype-compatible across workers."""

    type: T.Type
    values: np.ndarray
    nulls: Optional[np.ndarray] = None
    dictionary: Optional[Dictionary] = None
    vrange: Optional[tuple] = None
    # values are non-decreasing within this part (reference: the sort
    # properties of LocalProperties/ConnectorTableProperties) — monotone
    # generator keys and sorted file layouts declare it; the engine's
    # sorted-input fast paths (group/join without lax.sort) consume it
    sorted: bool = False
    # nested (array/map/row) columns: values = per-row int32 lengths,
    # children = flattened child columns (data/page.py Column.children)
    children: Optional[List["ColumnData"]] = None
    # long-decimal high limb (data/page.py Column.hi)
    hi: Optional[np.ndarray] = None


def concat_column_data(cols: Sequence[ColumnData]) -> ColumnData:
    """Host-side row-wise concat of scanned column parts, merging varchar
    dictionaries when parts disagree (range-dependent vocabularies). The
    single shared implementation for engine scan assembly and connectors."""
    assert cols
    if len(cols) == 1:
        return cols[0]
    from trino_tpu.data.page import merge_vrange

    if cols[0].children is not None:
        # nested: lengths concatenate; flat children concatenate recursively
        vals = np.concatenate([np.asarray(cd.values) for cd in cols])
        nulls = (
            np.concatenate([
                np.asarray(cd.nulls) if cd.nulls is not None
                else np.zeros(len(cd.values), bool)
                for cd in cols
            ])
            if any(cd.nulls is not None for cd in cols)
            else None
        )
        kids = [
            concat_column_data([cd.children[i] for cd in cols])
            for i in range(len(cols[0].children))
        ]
        return ColumnData(cols[0].type, vals, nulls, children=kids)

    vrange = cols[0].vrange
    for cd in cols[1:]:
        vrange = merge_vrange(vrange, cd.vrange)
    d = cols[0].dictionary
    if d is not None:
        for cd in cols[1:]:
            if cd.dictionary.values != d.values:
                d = d.merge(cd.dictionary)
        vals = np.concatenate([
            np.where(
                np.asarray(cd.values) >= 0,
                np.asarray(cd.dictionary.recode_table(d))[
                    np.clip(np.asarray(cd.values), 0, None)],
                -1,
            ).astype(np.int32)
            if cd.dictionary.values != d.values
            else np.asarray(cd.values)
            for cd in cols
        ])
    else:
        vals = np.concatenate([np.asarray(cd.values) for cd in cols])
    nulls = (
        np.concatenate([
            np.asarray(cd.nulls) if cd.nulls is not None
            else np.zeros(len(cd.values), bool)
            for cd in cols
        ])
        if any(cd.nulls is not None for cd in cols)
        else None
    )
    if any(cd.hi is not None for cd in cols):
        hi = np.concatenate([
            np.asarray(cd.hi) if cd.hi is not None
            else (np.asarray(cd.values).astype(np.int64) >> 63)
            for cd in cols
        ])
        return ColumnData(cols[0].type, vals.astype(np.int64), nulls, hi=hi)
    # sortedness survives concat when every part is sorted AND callers pass
    # parts in ascending key order (connector scans enumerate ranges
    # ascending); last-of-prev <= first-of-next is verified cheaply
    srt = all(cd.sorted for cd in cols)
    if srt:
        for a, b in zip(cols, cols[1:]):
            va, vb = np.asarray(a.values), np.asarray(b.values)
            if len(va) and len(vb) and va[-1] > vb[0]:
                srt = False
                break
    return ColumnData(cols[0].type, vals, nulls, d, vrange, srt)


def column_data_from_column(col) -> ColumnData:
    """data/page.py Column -> ColumnData (numpy views; recursive)."""
    return ColumnData(
        col.type,
        np.asarray(col.values),
        np.asarray(col.nulls) if col.nulls is not None else None,
        col.dictionary,
        col.vrange,
        children=(
            [column_data_from_column(k) for k in col.children]
            if col.children is not None
            else None
        ),
        hi=np.asarray(col.hi) if col.hi is not None else None,
    )


def column_data_slice(cd: ColumnData, lo: int, hi: int) -> ColumnData:
    """Row-range slice [lo, hi) — offset-aware for nested columns (child
    flats are sliced by the parent lengths' prefix sums)."""
    nulls = cd.nulls[lo:hi] if cd.nulls is not None else None
    if cd.children is None:
        return ColumnData(cd.type, cd.values[lo:hi], nulls, cd.dictionary,
                          cd.vrange, cd.sorted,
                          hi=cd.hi[lo:hi] if cd.hi is not None else None)
    if cd.type.is_row:
        kids = [column_data_slice(k, lo, hi) for k in cd.children]
        return ColumnData(cd.type, cd.values[lo:hi], nulls, children=kids)
    off = np.concatenate(
        [np.zeros(1, np.int64), np.cumsum(np.asarray(cd.values, dtype=np.int64))]
    )
    clo, chi = int(off[lo]), int(off[hi])
    kids = [column_data_slice(k, clo, chi) for k in cd.children]
    return ColumnData(cd.type, cd.values[lo:hi], nulls, children=kids)


def column_data_take(cd: ColumnData, idx: np.ndarray) -> ColumnData:
    """Row gather (indices or bool mask) — limb- and nested-aware (the
    ColumnData analog of data/page.py host_take)."""
    if idx.dtype == np.bool_:
        idx = np.nonzero(idx)[0]
    nulls = np.asarray(cd.nulls)[idx] if cd.nulls is not None else None
    if cd.children is not None and not cd.type.is_row:
        lens = np.asarray(cd.values, dtype=np.int64)
        off = np.concatenate([np.zeros(1, np.int64), np.cumsum(lens)])
        child_idx = (
            np.concatenate([np.arange(off[i], off[i + 1], dtype=np.int64) for i in idx])
            if len(idx)
            else np.zeros(0, np.int64)
        )
        kids = [column_data_take(k, child_idx) for k in cd.children]
        return ColumnData(cd.type, lens[idx].astype(np.int32), nulls, children=kids)
    kids = (
        [column_data_take(k, idx) for k in cd.children]
        if cd.children is not None
        else None
    )
    # idx from a mask (or any ascending index list) preserves row order, so
    # the sorted-input flag survives; arbitrary permutations must clear it
    order_preserving = len(idx) < 2 or bool(np.all(np.diff(idx) >= 0))
    return ColumnData(
        cd.type,
        np.asarray(cd.values)[idx],
        nulls,
        cd.dictionary,
        cd.vrange,
        cd.sorted and order_preserving,
        children=kids,
        hi=np.asarray(cd.hi)[idx] if cd.hi is not None else None,
    )


class LiveTableProvider:
    """Live-row source for a connector whose tables materialize at SCAN
    time from running-process state instead of stored pages (reference:
    the coordinator-state feeds behind ``connector/system/``'s
    ``QuerySystemTable``/``NodeSystemTable``). The provider contract:

    - ``snapshot_rows`` returns a CONSISTENT point-in-time row list and
      must never hold engine-wide locks while building it (snapshot the
      registry under its lock, compute rows outside), so a query scanning
      the live table that describes itself neither deadlocks nor observes
      a torn state;
    - ``procedure`` resolves a named procedure to a callable
      ``fn(session, *args) -> message`` or None (the CALL surface).
    """

    def snapshot_rows(self, schema: str, table: str) -> List[tuple]:
        raise NotImplementedError

    def procedure(self, schema: str, name: str):
        return None


class Connector:
    """Reference: spi/Plugin.java -> ConnectorFactory -> Connector."""

    # connectors whose schemas each hold exactly one relation named like
    # the schema (the jmx-connector shape) declare this so the planner's
    # two-part-name fallback (``system.metrics`` -> system.metrics.metrics)
    # applies ONLY to them — never silently rerouting a typo'd schema name
    # against ordinary multi-table catalogs
    single_table_schemas = False

    name: str = "connector"
    # True when table state lives only in the creating process (e.g. the
    # in-memory connector): the coordinator must not distribute scans to
    # workers, whose catalog instances would be empty.
    coordinator_only: bool = False
    # True when the connector supports explicit transactions via the
    # copy-on-write overlay protocol (exec/transaction.py; reference:
    # Connector.beginTransaction / isSingleStatementWritesOnly)
    supports_transactions: bool = False

    # --- metadata (ConnectorMetadata) ---
    def list_schemas(self) -> List[str]:
        raise NotImplementedError

    def list_tables(self, schema: str) -> List[str]:
        raise NotImplementedError

    def get_table(self, schema: str, table: str) -> Optional[TableMetadata]:
        raise NotImplementedError

    def table_row_count(self, schema: str, table: str) -> Optional[int]:
        """Stats for the cost-based optimizer (reference: spi/statistics/)."""
        return None

    def column_stats(self, schema: str, table: str, column: str) -> Optional["ColumnStats"]:
        """Per-column statistics for the cost-based optimizer: storage-repr
        (min, max) and distinct-value estimate (reference:
        spi/statistics/ColumnStatistics — low/high value + NDV)."""
        return None

    def primary_key(self, schema: str, table: str) -> Optional[List[str]]:
        """Unique key columns, if any — drives join build-side selection
        (reference: uniqueness constraints via
        spi/connector/ConnectorMetadata getTableProperties)."""
        return None

    def data_version(self, schema: str, table: str) -> Optional[str]:
        """Cheap opaque token that changes whenever the table's DATA (or
        existence/shape) changes — the query cache's invalidation handle
        (trino_tpu/cache/): versions are captured into cache keys at plan
        time, so a mutation makes the next identical query fingerprint
        differently and stale entries miss naturally. Immutable catalogs
        (tpch/tpcds generators) return a constant; stateful ones bump a
        counter (memory) or derive from storage state (filesystem file
        mtime+size). None (the default) means "unversioned": the engine
        cannot invalidate, so queries over this table bypass the cache."""
        return None

    # --- pushdown negotiation (ConnectorMetadata.apply*) ---
    # Each apply_* returns a NEW opaque table handle when the connector can
    # serve the narrowed request, or None to decline; the engine stores the
    # handle on the scan node and keeps its own enforcing operator (split
    # semantics make connector guarantees per-split, not global), exactly
    # like the reference keeps the plan node unless the handle is
    # guaranteed (ConnectorMetadata.java:80 applyLimit/applyTopN/
    # applyAggregation contracts).
    def apply_limit(self, schema: str, table: str, handle, count: int):
        return None

    def apply_topn(self, schema: str, table: str, handle, count: int,
                   order: List["SortItem"]):
        return None

    def apply_aggregation(self, schema: str, table: str, handle,
                          group_columns: List[str],
                          aggregates: List["AggregateSpec"]):
        """-> (handle, output ColumnMetadata list) or None. Output columns
        must be [group columns..., one per aggregate...], with values the
        ENGINE's exact semantics — a connector whose arithmetic differs
        (e.g. float sums for decimals) must decline."""
        return None

    def table_partitioning(self, schema: str, table: str) -> Optional["TablePartitioning"]:
        """Physical partitioning for co-located joins, if any."""
        return None

    def table_function(self, name: str):
        """Connector-provided table function, or None (reference:
        spi/function/table/ConnectorTableFunction). The returned callable
        takes (positional_args, named_args) and returns (column names,
        column types, rows)."""
        return None

    def procedure(self, schema: str, name: str):
        """Connector-provided procedure for ``CALL catalog.schema.name(...)``
        or None (reference: spi/procedure/Procedure + CallTask). The
        returned callable takes ``(session, *constant_args)`` and returns
        an optional result message."""
        return None

    def attach_live_provider(self, provider: "LiveTableProvider") -> None:
        """Bind a LiveTableProvider to this connector (the server that owns
        the live state injects itself after constructing its catalog map).
        Only live-table connectors accept one."""
        raise NotImplementedError(
            f"{self.name}: connector does not accept a live table provider")

    # --- splits (ConnectorSplitManager) ---
    def get_splits(
        self, schema: str, table: str, target_splits: int, constraint=None,
        handle=None,
    ) -> List[Split]:
        """``constraint`` is an ADVISORY TupleDomain (connector/predicate.py;
        reference: ConnectorMetadata.applyFilter + the DynamicFilter the
        split manager receives): a connector may use it to skip splits but
        the engine keeps the enforcing filter, so ignoring it is correct.
        ``handle`` is the pushdown handle minted by apply_* (if any); a
        connector embeds it in Split.info so scan() sees it."""
        raise NotImplementedError

    # --- data (ConnectorPageSource) ---
    def scan(self, split: Split, columns: List[str], constraint=None) -> Dict[str, ColumnData]:
        """``constraint`` as in get_splits — advisory row-reduction only."""
        raise NotImplementedError

    # --- writes (ConnectorMetadata DDL + ConnectorPageSink) ---
    def create_table(self, schema: str, name: str, schema_def, rows) -> None:
        """CREATE TABLE [AS]: register a table with the given columns and
        initial rows (reference: ConnectorMetadata.createTable /
        beginCreateTable + ConnectorPageSink)."""
        raise NotImplementedError(f"{self.name}: connector does not support CREATE TABLE")

    def insert_rows(self, schema: str, table: str, rows) -> int:
        """INSERT: append Python-value rows in table column order; returns
        the row count (reference: beginInsert/finishInsert + page sink)."""
        raise NotImplementedError(f"{self.name}: connector does not support INSERT")

    def drop_table(self, schema: str, table: str) -> None:
        raise NotImplementedError(f"{self.name}: connector does not support DROP TABLE")

    def overwrite_rows(self, schema: str, table: str, rows) -> None:
        """Replace the table's contents with ``rows`` (engine-computed
        DELETE/UPDATE rewrite: the engine evaluates the surviving/modified
        row set with its full expression machinery and hands the result
        back — the whole-table analog of the reference's row-change
        machinery, ConnectorMetadata.beginMerge/MergeSink)."""
        raise NotImplementedError(
            f"{self.name}: connector does not support DELETE/UPDATE")
