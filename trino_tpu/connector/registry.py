"""Catalog registry.

Reference: ``core/trino-main/.../metadata/CatalogManager`` + connector
creation from ``etc/catalog/*.properties``. Round 1: built-in catalogs
(tpch, memory); plugin-style registration hook for more.
"""
from __future__ import annotations

from typing import Dict

from trino_tpu.connector.spi import Connector


def default_catalogs() -> Dict[str, Connector]:
    import os

    from trino_tpu.connector.blackhole.connector import BlackHoleConnector
    from trino_tpu.connector.filesystem.connector import FileSystemConnector
    from trino_tpu.connector.memory.connector import MemoryConnector
    from trino_tpu.connector.system.connector import SystemConnector
    from trino_tpu.connector.tpcds import TpcdsConnector
    from trino_tpu.connector.tpch import TpchConnector

    cats = {
        "tpch": TpchConnector(),
        "tpcds": TpcdsConnector(),
        "memory": MemoryConnector(),
        "blackhole": BlackHoleConnector(),
        # parquet-on-disk catalog; root via env (etc/catalog/*.properties role)
        "filesystem": FileSystemConnector(os.environ.get("TRINO_TPU_FS_ROOT")),
        # runtime introspection (reference: connector/system/): tables fed
        # live by the coordinator's LiveTableProvider; provider-less
        # instances (standalone sessions, workers) serve empty runtime
        # tables and this process's own metrics registry
        "system": SystemConnector(),
    }
    # RDBMS catalog (the JDBC plugin family's analog); db file via env
    sqlite_path = os.environ.get("TRINO_TPU_SQLITE_DB")
    if sqlite_path:
        from trino_tpu.connector.sqlite import SqliteConnector

        cats["sqlite"] = SqliteConnector(sqlite_path)
    return cats
