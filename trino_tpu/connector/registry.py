"""Catalog registry.

Reference: ``core/trino-main/.../metadata/CatalogManager`` + connector
creation from ``etc/catalog/*.properties``. Round 1: built-in catalogs
(tpch, memory); plugin-style registration hook for more.
"""
from __future__ import annotations

from typing import Dict

from trino_tpu.connector.spi import Connector


def default_catalogs() -> Dict[str, Connector]:
    from trino_tpu.connector.blackhole.connector import BlackHoleConnector
    from trino_tpu.connector.memory.connector import MemoryConnector
    from trino_tpu.connector.tpch import TpchConnector

    return {
        "tpch": TpchConnector(),
        "memory": MemoryConnector(),
        "blackhole": BlackHoleConnector(),
    }
