"""Catalog registry.

Reference: ``core/trino-main/.../metadata/CatalogManager`` + connector
creation from ``etc/catalog/*.properties``. Round 1: built-in catalogs
(tpch, memory); plugin-style registration hook for more.
"""
from __future__ import annotations

from typing import Dict

from trino_tpu.connector.spi import Connector


def default_catalogs() -> Dict[str, Connector]:
    import os

    from trino_tpu.connector.blackhole.connector import BlackHoleConnector
    from trino_tpu.connector.filesystem.connector import FileSystemConnector
    from trino_tpu.connector.memory.connector import MemoryConnector
    from trino_tpu.connector.tpcds import TpcdsConnector
    from trino_tpu.connector.tpch import TpchConnector

    return {
        "tpch": TpchConnector(),
        "tpcds": TpcdsConnector(),
        "memory": MemoryConnector(),
        "blackhole": BlackHoleConnector(),
        # parquet-on-disk catalog; root via env (etc/catalog/*.properties role)
        "filesystem": FileSystemConnector(os.environ.get("TRINO_TPU_FS_ROOT")),
    }
