"""Connector predicate model: Domain / TupleDomain.

Reference: ``core/trino-spi/.../spi/predicate/`` — ``TupleDomain.java``
(column→Domain map), ``Domain.java`` (ValueSet + null-allowed), ``Range``.
Simplified to the shapes the engine produces today: one contiguous range
(optionally unbounded on either side) OR a discrete in-set, per column.
Constraints are ADVISORY to connectors: the engine always keeps the
enforcing filter (the reference drops it only when the connector promises
full enforcement via applyFilter's result), so a connector that ignores or
over-approximates a constraint is still correct — pushdown only reduces
rows materialized.

Values are storage representations (ints for bigint/date-as-epoch-days/
scaled decimals, floats, Python str for varchar).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, Optional


@dataclasses.dataclass(frozen=True)
class Domain:
    """Allowed values of one column: either ``values`` (discrete set) or a
    [low, high] range with optional open bounds; plus NULL admissibility."""

    low: Optional[object] = None  # None = unbounded below
    high: Optional[object] = None  # None = unbounded above
    low_inclusive: bool = True
    high_inclusive: bool = True
    values: Optional[FrozenSet] = None  # discrete set; overrides range
    null_allowed: bool = False
    # lazily-cached sorted numpy array of ``values`` (phase-1 dynamic
    # filters reach millions of keys; per-use frozenset iteration is the
    # cost that matters, not storage). Excluded from equality/repr.
    values_sorted: Optional[object] = dataclasses.field(
        default=None, compare=False, repr=False)

    @staticmethod
    def all() -> "Domain":
        return Domain(null_allowed=True)

    @staticmethod
    def from_values(vals, null_allowed: bool = False) -> "Domain":
        import numpy as np

        arr = None
        if isinstance(vals, np.ndarray):
            arr = np.sort(vals)
            vals = arr.tolist()
        return Domain(values=frozenset(vals), null_allowed=null_allowed,
                      values_sorted=arr)

    @staticmethod
    def range(low=None, high=None, low_inclusive=True, high_inclusive=True) -> "Domain":
        return Domain(low, high, low_inclusive, high_inclusive)

    @staticmethod
    def only_null() -> "Domain":
        return Domain(values=frozenset(), null_allowed=True)

    def is_all(self) -> bool:
        return self.values is None and self.low is None and self.high is None and self.null_allowed

    def is_none(self) -> bool:
        """Provably empty (no value and no NULL admitted)."""
        if self.null_allowed:
            return False
        if self.values is not None:
            return len(self.values) == 0
        if self.low is not None and self.high is not None:
            if self.low > self.high:
                return True
            if self.low == self.high and not (self.low_inclusive and self.high_inclusive):
                return True
        return False

    def value_bounds(self):
        """(low, high) closed bounds, or None on that side if unbounded.
        In-set domains report their min/max."""
        if self.values is not None:
            if not self.values:
                return None, None
            return min(self.values), max(self.values)
        return self.low, self.high

    def contains(self, v) -> bool:
        if v is None:
            return self.null_allowed
        if self.values is not None:
            return v in self.values
        if self.low is not None and (v < self.low or (v == self.low and not self.low_inclusive)):
            return False
        if self.high is not None and (v > self.high or (v == self.high and not self.high_inclusive)):
            return False
        return True

    def intersect(self, other: "Domain") -> "Domain":
        null_ok = self.null_allowed and other.null_allowed
        if self.values is not None or other.values is not None:
            if self.values is not None and other.values is not None:
                vals = self.values & other.values
            elif self.values is not None:
                vals = frozenset(v for v in self.values if other.contains(v))
            else:
                vals = frozenset(v for v in other.values if self.contains(v))
            return Domain(values=vals, null_allowed=null_ok)
        low, low_inc = self.low, self.low_inclusive
        if other.low is not None and (low is None or other.low > low
                                      or (other.low == low and not other.low_inclusive)):
            low, low_inc = other.low, other.low_inclusive
        high, high_inc = self.high, self.high_inclusive
        if other.high is not None and (high is None or other.high < high
                                       or (other.high == high and not other.high_inclusive)):
            high, high_inc = other.high, other.high_inclusive
        return Domain(low, high, low_inc, high_inc, None, null_ok)


def sorted_values_array(dom: Domain):
    """Sorted numpy array of an in-set Domain's values, cached on the
    instance (frozen dataclass: installed via object.__setattr__)."""
    import numpy as np

    if dom.values_sorted is not None:
        return dom.values_sorted
    if not dom.values:
        arr = np.empty(0, dtype=np.int64)
    else:
        # dtype-aware: an int64 fromiter would silently truncate float
        # domain values (double join keys) and drop every matching row
        dt = np.int64 if all(
            isinstance(v, (int, np.integer)) for v in dom.values) else np.float64
        arr = np.sort(np.fromiter(dom.values, dtype=dt, count=len(dom.values)))
    object.__setattr__(dom, "values_sorted", arr)
    return arr


@dataclasses.dataclass(frozen=True)
class TupleDomain:
    """Conjunction of per-column Domains (absent column = unconstrained)."""

    domains: Dict[str, Domain] = dataclasses.field(default_factory=dict)

    @staticmethod
    def all() -> "TupleDomain":
        return TupleDomain({})

    def is_all(self) -> bool:
        return not self.domains

    def is_none(self) -> bool:
        return any(d.is_none() for d in self.domains.values())

    def domain(self, column: str) -> Domain:
        return self.domains.get(column, Domain.all())

    def intersect(self, other: Optional["TupleDomain"]) -> "TupleDomain":
        if other is None:
            return self
        merged = dict(self.domains)
        for col, dom in other.domains.items():
            merged[col] = merged[col].intersect(dom) if col in merged else dom
        return TupleDomain(merged)

    def __repr__(self):
        if not self.domains:
            return "TupleDomain.all()"
        parts = []
        for col, d in sorted(self.domains.items()):
            if d.values is not None:
                vs = sorted(d.values)
                shown = vs if len(vs) <= 4 else vs[:4] + ["…"]
                parts.append(f"{col} IN {shown}")
            else:
                lo = "-inf" if d.low is None else repr(d.low)
                hi = "+inf" if d.high is None else repr(d.high)
                lb = "[" if d.low_inclusive else "("
                rb = "]" if d.high_inclusive else ")"
                parts.append(f"{col} {lb}{lo}, {hi}{rb}")
        return "TupleDomain(" + ", ".join(parts) + ")"
