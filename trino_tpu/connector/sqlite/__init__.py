from trino_tpu.connector.sqlite.connector import SqliteConnector  # noqa: F401
