"""SQLite connector: the walking skeleton of the reference's JDBC plugin
family.

Reference: ``plugin/trino-base-jdbc`` (JdbcMetadata / JdbcSplitManager /
JdbcRecordSetProvider, predicate pushdown via ``QueryBuilder`` compiling a
TupleDomain into a WHERE clause) and its concrete plugins (trino-postgresql,
trino-mysql, ...). SQLite via the stdlib driver stands in for the remote
RDBMS: metadata comes from ``sqlite_master``/``PRAGMA table_info``, splits
are rowid ranges, scans SELECT only the requested columns with the
constraint compiled to SQL (pushdown happens IN the remote engine — the
whole point of the JDBC family), and writes go through CREATE TABLE/INSERT.

Type mapping (reference: each JDBC plugin's StandardColumnMappings):
INTEGER->bigint, REAL/FLOAT/DOUBLE->double, TEXT/CHAR->varchar,
DATE->date, BOOLEAN->boolean, NUMERIC/DECIMAL(p,s)->decimal.
"""
from __future__ import annotations

import dataclasses
import re
import sqlite3
import threading
from typing import Dict, List, Optional

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector import spi
from trino_tpu.connector.predicate import Domain, TupleDomain
from trino_tpu.data.dictionary import Dictionary
from trino_tpu.data.page import Column

_SPLIT_ROWS = 250_000  # rowid range per split (JdbcSplitManager's analog)


@dataclasses.dataclass(frozen=True)
class SqlitePushdown:
    """Opaque table handle carrying negotiated pushdown (reference: the
    JdbcTableHandle's limit/sortOrder/groupingSets state that QueryBuilder
    compiles into the remote SELECT)."""

    limit: Optional[int] = None
    order: tuple = ()  # ((column, ascending, nulls_first), ...)
    group_by: Optional[tuple] = None  # grouping column names
    aggs: tuple = ()  # ((function, column|None, output_name, output_type), ...)

    def __repr__(self):
        parts = []
        if self.aggs:
            gb = ", ".join(self.group_by or ())
            parts.append(f"aggregate[{', '.join(f'{f}({c or chr(42)})' for f, c, _, _ in self.aggs)}"
                         + (f" group by {gb}" if gb else "") + "]")
        if self.order:
            parts.append("sort[" + ", ".join(
                f"{c} {'asc' if a else 'desc'}" for c, a, _ in self.order) + "]")
        if self.limit is not None:
            parts.append(f"limit[{self.limit}]")
        return " ".join(parts) or "none"


def _type_from_sqlite(decl: str) -> T.Type:
    d = (decl or "").strip().lower()
    m = re.match(r"(?:numeric|decimal)\s*\((\d+)\s*,\s*(\d+)\)", d)
    if m:
        return T.decimal(int(m.group(1)), int(m.group(2)))
    if "int" in d:
        return T.BIGINT
    if any(k in d for k in ("real", "floa", "doub")):
        return T.DOUBLE
    if "bool" in d:
        return T.BOOLEAN
    if "date" in d:
        return T.DATE
    # TEXT affinity catch-all (sqlite is dynamically typed)
    return T.varchar()


def _sqlite_decl(t: T.Type) -> str:
    if t.is_integer_kind:
        return "INTEGER"
    if t.is_floating:
        return "DOUBLE"
    if t == T.BOOLEAN:
        return "BOOLEAN"
    if t == T.DATE:
        return "DATE"
    if t.is_decimal:
        assert isinstance(t, T.DecimalType)
        return f"DECIMAL({t.precision},{t.scale})"
    return "TEXT"


class SqliteConnector(spi.Connector):
    name = "sqlite"
    coordinator_only = False  # a shared db file is reachable from workers

    def __init__(self, path: str):
        self._path = path
        self._local = threading.local()

    def _conn(self) -> sqlite3.Connection:
        # sqlite connections are not thread-safe; one per engine thread
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self._path)
            self._local.conn = conn
        return conn

    def data_version(self, schema: str, table: str):
        """Database-file mtime+size, including the WAL sidecar: coarser
        than per-table (any write invalidates every table's cached
        results) but safe — in journal_mode=WAL a commit lands in the
        ``-wal`` file and may leave the main db file untouched until
        checkpoint, so the sidecars participate in the token."""
        import os

        parts = []
        for suffix in ("", "-wal", "-shm"):
            try:
                st = os.stat(self._path + suffix)
                parts.append(f"{st.st_mtime_ns}:{st.st_size}")
            except OSError:
                parts.append("absent")
        if parts[0] == "absent":
            return None  # no database file: unversioned
        return "|".join(parts)

    # ------------------------------------------------------------ metadata
    def list_schemas(self) -> List[str]:
        return ["main"]

    def list_tables(self, schema: str) -> List[str]:
        cur = self._conn().execute(
            "select name from sqlite_master where type = 'table' order by name"
        )
        return [r[0] for r in cur.fetchall()]

    def get_table(self, schema: str, table: str) -> Optional[spi.TableMetadata]:
        if not re.fullmatch(r"\w+", table):
            return None
        cur = self._conn().execute(f"PRAGMA table_info({table})")
        cols = cur.fetchall()
        if not cols:
            return None
        return spi.TableMetadata(
            schema, table,
            [spi.ColumnMetadata(c[1], _type_from_sqlite(c[2])) for c in cols],
        )

    def table_row_count(self, schema: str, table: str) -> Optional[int]:
        if self.get_table(schema, table) is None:
            return None
        (n,) = self._conn().execute(f"select count(*) from {table}").fetchone()
        return int(n)

    def column_stats(self, schema: str, table: str, column: str):
        meta = self.get_table(schema, table)
        if meta is None:
            return None
        try:
            t = meta.columns[meta.column_index(column)].type
        except KeyError:
            return None
        if not (t.is_integer_kind or t == T.DATE or t.is_decimal):
            return None
        _check_ident(column)
        lo, hi, ndv = self._conn().execute(
            f'select min("{column}"), max("{column}"), count(distinct "{column}")'
            f" from {table}"
        ).fetchone()
        if lo is None or hi is None:
            return None
        conv = _to_repr_fn(t)
        return spi.ColumnStats(low=conv(lo), high=conv(hi), ndv=int(ndv))

    # ----------------------------------------------------------- pushdown
    def apply_limit(self, schema, table, handle, count: int):
        h = handle or SqlitePushdown()
        if h.limit is not None and h.limit <= count:
            return None  # already at least as narrow — fixpoint
        return dataclasses.replace(h, limit=count)

    def apply_topn(self, schema, table, handle, count: int, order):
        h = handle or SqlitePushdown()
        want = tuple((o.column, o.ascending, o.nulls_first) for o in order)
        for c, _a, _n in want:
            if not re.fullmatch(r"\w+", c):
                return None
        if h.order == want and h.limit is not None and h.limit <= count:
            return None
        if h.aggs:
            return None  # ordering over pushed aggregates: not composed yet
        return dataclasses.replace(h, order=want, limit=count)

    def apply_aggregation(self, schema, table, handle, group_columns, aggregates):
        h = handle or SqlitePushdown()
        if h.aggs or h.limit is not None or h.order:
            return None  # aggregation must be innermost
        meta = self.get_table(schema, table)
        if meta is None:
            return None
        col_types = {c.name: c.type for c in meta.columns}
        for c in group_columns:
            if c not in col_types or not re.fullmatch(r"\w+", c):
                return None
        out_cols = [spi.ColumnMetadata(c, col_types[c]) for c in group_columns]
        specs = []
        for i, a in enumerate(aggregates):
            # exactness gate: sqlite sums of INTEGER-affinity columns are
            # exact int64; float/fractional-decimal arithmetic differs from
            # the engine's, so decline (the reference's JDBC plugins gate
            # applyAggregate the same way via type mappings)
            if a.function == "count" and a.column is None:
                specs.append(("count", None, f"agg{i}", a.output_type))
            elif a.function in ("count", "min", "max", "sum"):
                t = col_types.get(a.column)
                if t is None or not re.fullmatch(r"\w+", a.column or ""):
                    return None
                exact = (t.is_integer_kind or t == T.DATE
                         or (t.is_decimal and isinstance(t, T.DecimalType)
                             and t.scale == 0))
                if a.function != "count" and not exact:
                    return None
                specs.append((a.function, a.column, f"agg{i}", a.output_type))
            else:
                return None
            out_cols.append(spi.ColumnMetadata(f"agg{i}", a.output_type))
        new_handle = dataclasses.replace(
            h, group_by=tuple(group_columns), aggs=tuple(specs))
        return new_handle, out_cols

    # -------------------------------------------------------------- splits
    def get_splits(self, schema, table, target_splits, constraint=None,
                   handle=None) -> List[spi.Split]:
        _check_ident(table)
        if handle is not None and (
                handle.aggs or handle.limit is not None or handle.order):
            # pushed aggregation/topN/limit is a GLOBAL statement: one split
            # (the remote engine does the work; splitting would make the
            # guarantee per-range)
            return [spi.Split(table, schema, 0, 1 << 62, info=handle)]
        row = self._conn().execute(
            f"select min(rowid), max(rowid) from {table}"
        ).fetchone()
        lo, hi = (row or (None, None))
        if lo is None:
            return [spi.Split(table, schema, 0, -1, info=handle)]
        lo, hi = int(lo), int(hi)
        n = hi - lo + 1
        parts = max(1, min(target_splits, (n + _SPLIT_ROWS - 1) // _SPLIT_ROWS))
        bounds = [lo + n * i // parts for i in range(parts)] + [hi + 1]
        return [
            spi.Split(table, schema, bounds[i], bounds[i + 1] - 1, info=handle)
            for i in range(parts)
        ]

    # ---------------------------------------------------------------- scan
    def scan(self, split: spi.Split, columns: List[str], constraint=None):
        meta = self.get_table(split.schema, split.table)
        assert meta is not None
        h: Optional[SqlitePushdown] = split.info if isinstance(
            split.info, SqlitePushdown) else None
        col_types = {c.name: c.type for c in meta.columns}
        where, params = [], []
        if h is None or not (h.aggs or h.limit is not None or h.order):
            where, params = ["rowid between ? and ?"], [split.lo, split.hi]
        if constraint is not None:
            w, p = _compile_constraint(constraint, col_types)
            where += w
            params += p
        where_sql = f' where {" and ".join(where)}' if where else ""
        if h is not None and h.aggs:
            # the handle defines output names: group columns + aggN aliases
            sel_parts = [f'"{c}"' for c in h.group_by]
            for fn, col, alias, _t in h.aggs:
                expr = "count(*)" if col is None else f'{fn}("{col}")'
                sel_parts.append(f"{expr} as {alias}")
            gb = (" group by " + ", ".join(f'"{c}"' for c in h.group_by)
                  if h.group_by else "")
            sql = (f"select {', '.join(sel_parts)} from {split.table}"
                   f"{where_sql}{gb}")
        else:
            for c in columns:
                _check_ident(c)
            sel = ", ".join(f'"{c}"' for c in columns)
            order_sql = ""
            if h is not None and h.order:
                terms = []
                for c, asc, nf in h.order:
                    nulls = "nulls first" if nf else "nulls last"
                    terms.append(f'"{c}" {"asc" if asc else "desc"} {nulls}')
                order_sql = " order by " + ", ".join(terms)
            limit_sql = (f" limit {int(h.limit)}"
                         if h is not None and h.limit is not None else "")
            sql = (f"select {sel} from {split.table}{where_sql}"
                   f"{order_sql}{limit_sql}")
        rows = self._conn().execute(sql, params).fetchall()
        out: Dict[str, spi.ColumnData] = {}
        if h is not None and h.aggs:
            names = list(h.group_by) + [alias for _, _, alias, _ in h.aggs]
            types = [col_types[c] for c in h.group_by] + [t for _, _, _, t in h.aggs]
            assert list(columns) == names, (columns, names)
            for i, (cname, t) in enumerate(zip(names, types)):
                pycol = [_from_sql_value(t, r[i]) for r in rows]
                out[cname] = spi.column_data_from_column(Column.from_python(t, pycol))
            return out
        for i, cname in enumerate(columns):
            t = col_types[cname]
            pycol = [_from_sql_value(t, r[i]) for r in rows]
            out[cname] = spi.column_data_from_column(Column.from_python(t, pycol))
        return out

    # --------------------------------------------------------------- write
    def create_table(self, schema: str, name: str, schema_def, rows) -> None:
        _check_ident(name)
        for c, _ in schema_def:
            _check_ident(c)
        if self.get_table(schema, name) is not None:
            raise ValueError(f"table already exists: {schema}.{name}")
        cols = ", ".join(f'"{c}" {_sqlite_decl(t)}' for c, t in schema_def)
        conn = self._conn()
        conn.execute(f'create table {name} ({cols})')
        if rows:
            ph = ", ".join("?" * len(schema_def))
            conn.executemany(
                f"insert into {name} values ({ph})",
                [tuple(_to_sql_value(t, v) for (_, t), v in zip(schema_def, r))
                 for r in rows],
            )
        conn.commit()

    def insert_rows(self, schema: str, table: str, rows) -> int:
        meta = self.get_table(schema, table)
        if meta is None:
            raise KeyError(f"sqlite.{schema}.{table} does not exist")
        if rows:
            ph = ", ".join("?" * len(meta.columns))
            conn = self._conn()
            conn.executemany(
                f"insert into {table} values ({ph})",
                [tuple(_to_sql_value(c.type, v) for c, v in zip(meta.columns, r))
                 for r in rows],
            )
            conn.commit()
        return len(rows)

    def overwrite_rows(self, schema: str, table: str, rows) -> None:
        """DELETE-all + re-insert inside one sqlite transaction (the
        engine hands back the surviving/modified row set)."""
        meta = self.get_table(schema, table)
        if meta is None:
            raise KeyError(f"sqlite.{schema}.{table} does not exist")
        _check_ident(table)
        conn = self._conn()
        conn.execute(f"delete from {table}")
        if rows:
            ph = ", ".join("?" * len(meta.columns))
            conn.executemany(
                f"insert into {table} values ({ph})",
                [tuple(_to_sql_value(c.type, v) for c, v in zip(meta.columns, r))
                 for r in rows],
            )
        conn.commit()

    def drop_table(self, schema: str, table: str) -> None:
        _check_ident(table)
        conn = self._conn()
        conn.execute(f"drop table if exists {table}")
        conn.commit()


def _check_ident(name: str) -> None:
    """Identifiers are interpolated into remote SQL: restrict to word
    characters (the reference's QueryBuilder quotes through the JDBC
    driver; sqlite3 has no identifier binding)."""
    if not re.fullmatch(r"\w+", name):
        raise ValueError(f"invalid sqlite identifier: {name!r}")


def _to_repr_fn(t: T.Type):
    """SQL value -> engine storage repr (for stats)."""
    if t == T.DATE:
        import datetime

        def conv(v):
            if isinstance(v, str):
                return (datetime.date.fromisoformat(v) - datetime.date(1970, 1, 1)).days
            return int(v)

        return conv
    if t.is_decimal:
        scale = t.scale if isinstance(t, T.DecimalType) else 0
        return lambda v: int(round(float(v) * 10**scale))
    return lambda v: int(v)


def _from_sql_value(t: T.Type, v):
    """sqlite driver value -> Python value in the engine's expected kind."""
    if v is None:
        return None
    if t == T.DATE:
        return v  # ISO string or days; Column.from_python handles both
    if t == T.BOOLEAN:
        return bool(v)
    if t.is_decimal:
        from decimal import Decimal

        return Decimal(str(v))
    return v


def _to_sql_value(t: T.Type, v):
    if v is None:
        return None
    if t == T.DATE:
        return str(v)
    if t.is_decimal:
        return str(v)
    if t == T.BOOLEAN:
        return int(bool(v))
    if t.is_floating:
        return float(v)  # engine literals may arrive as Decimal
    if t.is_integer_kind:
        return int(v)
    return v


def _compile_constraint(td: TupleDomain, col_types) -> tuple:
    """TupleDomain -> (WHERE conjuncts, bind params): the reference's
    QueryBuilder.toPredicate — pushdown evaluated by the remote engine."""
    where, params = [], []
    for column, dom in (td.domains or {}).items():
        if column not in col_types or dom.is_all():
            continue
        if not re.fullmatch(r"\w+", column):
            continue  # advisory constraint: skip rather than interpolate
        t = col_types[column]
        if t.is_decimal and (not isinstance(t, T.DecimalType) or t.scale != 0):
            # fractional decimals bind as floats, whose rounding past 2^53
            # could DROP matching rows remotely (the constraint is advisory
            # — over-approximation only) — skip the pushdown
            continue
        conv = _param_fn(t)
        q = f'"{column}"'
        parts = []
        if dom.values is not None:
            vals = sorted(dom.values, key=str)
            if not vals:
                parts.append("1 = 0")
            elif len(vals) <= 500:
                ph = ", ".join("?" * len(vals))
                parts.append(f"{q} in ({ph})")
                params.extend(conv(v) for v in vals)
            # else: too many keys — skip (advisory constraint)
        else:
            if dom.low is not None:
                parts.append(f"{q} >{'=' if dom.low_inclusive else ''} ?")
                params.append(conv(dom.low))
            if dom.high is not None:
                parts.append(f"{q} <{'=' if dom.high_inclusive else ''} ?")
                params.append(conv(dom.high))
        if not parts:
            if not dom.null_allowed:
                where.append(f"({q} is not null)")
            continue
        pred = " and ".join(parts)
        if dom.null_allowed:
            pred = f"({pred} or {q} is null)"
        where.append(f"({pred})")
    return where, params


def _param_fn(t: T.Type):
    """Engine storage repr -> SQL bind value."""
    if t == T.DATE:
        import datetime

        return lambda v: (
            (datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v))).isoformat()
            if not isinstance(v, str)
            else v
        )
    if t.is_decimal:
        # scale-0 decimals bind as exact ints (sqlite INTEGER affinity
        # compares exactly); fractional decimals never push down (see
        # _compile_constraint) so floats here can't drop rows
        scale = t.scale if isinstance(t, T.DecimalType) else 0
        if scale == 0:
            return lambda v: int(v)
        return lambda v: float(v) / 10**scale
    return lambda v: v
