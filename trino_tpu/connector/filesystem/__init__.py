from trino_tpu.connector.filesystem.connector import FileSystemConnector

__all__ = ["FileSystemConnector"]
