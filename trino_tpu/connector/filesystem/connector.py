"""Filesystem connector: Parquet/ORC (+ read-only CSV/JSON) tables on
local disk.

Reference roles collapsed into one connector: ``lib/trino-parquet``
(``ParquetReader.java:85`` — column readers, row-group pruning by min/max
statistics), ``lib/trino-orc`` (``OrcReader`` — stripes as the scan
granule), the lakehouse connectors' table layout (``plugin/trino-hive``:
a table is a directory of files), and the write path
(``ConnectorPageSink`` → parquet/orc files). Format follows the file
extension; writes use the connector's default_format.

TPU-first notes: columns decode straight to the engine's storage reprs —
strings dictionary-encode (pyarrow dictionary arrays pass through without
materializing Python strings when possible), dates to epoch-day int32,
decimals to scaled int64 — so a scanned page is device-transfer-ready.
Splits are row groups; a TupleDomain constraint prunes row groups whose
min/max statistics can't match (the Parquet predicate-pushdown behavior of
``applyFilter`` + row-group filtering).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector import spi
from trino_tpu.data.dictionary import Dictionary


def _pa():
    import pyarrow  # noqa: PLC0415 — optional heavy dep, loaded on use

    return pyarrow


def _pq():
    import pyarrow.parquet  # noqa: PLC0415

    return pyarrow.parquet


def _porc():
    import pyarrow.orc  # noqa: PLC0415

    return pyarrow.orc


_EXTS = ("parquet", "orc", "csv", "json")  # csv/json are read-only tables


def _type_from_arrow(at) -> T.Type:
    pa = _pa()
    if pa.types.is_boolean(at):
        return T.BOOLEAN
    if pa.types.is_int8(at) or pa.types.is_int16(at) or pa.types.is_int32(at):
        return T.INTEGER
    if pa.types.is_integer(at):
        return T.BIGINT
    if pa.types.is_floating(at):
        return T.DOUBLE
    if pa.types.is_date(at):
        return T.DATE
    if pa.types.is_decimal(at):
        return T.decimal(at.precision, at.scale)
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return T.varchar()
    if pa.types.is_dictionary(at):
        return _type_from_arrow(at.value_type)
    raise NotImplementedError(f"unsupported parquet/arrow type: {at}")


def _arrow_from_type(t: T.Type):
    pa = _pa()
    if t == T.BOOLEAN:
        return pa.bool_()
    if t == T.INTEGER:
        return pa.int32()
    if t == T.BIGINT:
        return pa.int64()
    if t == T.DOUBLE:
        return pa.float64()
    if t == T.DATE:
        return pa.date32()
    if t.is_decimal:
        return pa.decimal128(t.precision, t.scale)
    if t.is_varchar:
        return pa.string()
    raise NotImplementedError(f"unsupported type for parquet write: {t}")


class FileSystemConnector(spi.Connector):
    name = "filesystem"

    # rows per row group on write: the scan-parallelism granule (a split =
    # a run of row groups), like the reference's parquet writer block size
    ROW_GROUP_SIZE = 4096

    def __init__(self, root: Optional[str] = None,
                 default_format: str = "parquet"):
        # schema = subdirectory of root, table = <name>.<format> inside it
        self.root = root or os.path.join(os.getcwd(), "fs_catalog")
        assert default_format in ("parquet", "orc")
        self.default_format = default_format

    # ------------------------------------------------------------- layout
    def _table_path(self, schema: str, table: str) -> str:
        """Existing table file (any supported format), else the
        default-format path for writes."""
        for ext in _EXTS:
            p = os.path.join(self.root, schema, f"{table}.{ext}")
            if os.path.exists(p):
                return p
        return os.path.join(self.root, schema, f"{table}.{self.default_format}")

    @staticmethod
    def _is_orc(path: str) -> bool:
        return path.endswith(".orc")

    @staticmethod
    def _text_format(path: str):
        """'csv' / 'json' for the read-only text formats, else None
        (reference roles: the hive connector's CSV/JSON serdes)."""
        for fmt in ("csv", "json"):
            if path.endswith("." + fmt):
                return fmt
        return None

    def _read_text_table(self, path: str):
        """Whole-file arrow table for a text-format table (small reference
        / dimension data; columnar formats are the scan path at scale).
        Cached by (path, mtime): plan-time schema, stats, and the scan
        would otherwise each re-parse the file."""
        key = (path, os.path.getmtime(path))
        cache = getattr(self, "_text_cache", None)
        if cache is None:
            cache = self._text_cache = {}
        hit = cache.get(path)
        if hit is not None and hit[0] == key[1]:
            return hit[1]
        if path.endswith(".csv"):
            import pyarrow.csv as pc

            tbl = pc.read_csv(path)
        else:
            import pyarrow.json as pj

            tbl = pj.read_json(path)
        cache[path] = (key[1], tbl)
        return tbl

    def list_schemas(self) -> List[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d))
        )

    def list_tables(self, schema: str) -> List[str]:
        d = os.path.join(self.root, schema)
        if not os.path.isdir(d):
            return []
        return sorted({
            f.rsplit(".", 1)[0] for f in os.listdir(d)
            if f.rsplit(".", 1)[-1] in _EXTS
        })

    def get_table(self, schema: str, table: str) -> Optional[spi.TableMetadata]:
        path = self._table_path(schema, table)
        if not os.path.exists(path):
            return None
        if self._text_format(path):
            arrow_schema = self._read_text_table(path).schema
        elif self._is_orc(path):
            arrow_schema = _porc().ORCFile(path).schema
        else:
            arrow_schema = _pq().read_schema(path)
        cols = [
            spi.ColumnMetadata(f.name, _type_from_arrow(f.type))
            for f in arrow_schema
        ]
        return spi.TableMetadata(schema, table, cols)

    def data_version(self, schema: str, table: str) -> Optional[str]:
        """Storage-derived version: the table file's mtime+size (the cache
        layer's invalidation token — any rewrite changes it). Missing
        table -> a distinct token too, so create-after-miss invalidates."""
        path = self._table_path(schema, table)
        try:
            st = os.stat(path)
        except OSError:
            return "absent"
        return f"{st.st_mtime_ns}:{st.st_size}"

    def table_row_count(self, schema: str, table: str) -> Optional[int]:
        path = self._table_path(schema, table)
        if not os.path.exists(path):
            return None
        if self._text_format(path):
            return self._read_text_table(path).num_rows
        if self._is_orc(path):
            return _porc().ORCFile(path).nrows
        return _pq().ParquetFile(path).metadata.num_rows

    # ------------------------------------------------------------- splits
    def get_splits(
        self, schema: str, table: str, target_splits: int, constraint=None,
        handle=None,
    ) -> List[spi.Split]:
        """One split per row-group (parquet) or stripe (orc) run; parquet
        row groups whose min/max statistics contradict the constraint are
        pruned (ParquetReader's predicate evaluation on column-chunk
        statistics; pyarrow exposes no stripe statistics, so orc scans
        every stripe — correct, just unpruned)."""
        path = self._table_path(schema, table)
        if self._text_format(path):
            return [spi.Split(table, schema, 0, 0, info=None)]
        if self._is_orc(path):
            n_stripes = _porc().ORCFile(path).nstripes
            keep = list(range(n_stripes))
            if not keep:
                return [spi.Split(table, schema, 0, 0, info=())]
            per = max(1, (len(keep) + max(target_splits, 1) - 1)
                      // max(target_splits, 1))
            return [
                spi.Split(table, schema, 0, 0, info=tuple(keep[i : i + per]))
                for i in range(0, len(keep), per)
            ]
        pf = _pq().ParquetFile(path)
        md = pf.metadata
        keep = [
            rg for rg in range(md.num_row_groups)
            if constraint is None or self._row_group_matches(md, rg, constraint)
        ]
        if not keep:
            return []
        # distribute kept row groups over at most target_splits splits
        per = max(1, (len(keep) + max(target_splits, 1) - 1) // max(target_splits, 1))
        return [
            spi.Split(table, schema, 0, 0, info=tuple(keep[i : i + per]))
            for i in range(0, len(keep), per)
        ]

    def _row_group_matches(self, md, rg: int, constraint) -> bool:
        rgm = md.row_group(rg)
        name_to_idx = {rgm.column(i).path_in_schema: i for i in range(rgm.num_columns)}
        for column, dom in constraint.domains.items():
            ci = name_to_idx.get(column)
            if ci is None:
                continue
            stats = rgm.column(ci).statistics
            if stats is None or not stats.has_min_max:
                continue
            lo, hi = _stat_repr(stats.min), _stat_repr(stats.max)
            dlo, dhi = dom.value_bounds()
            try:
                if dlo is not None and hi is not None and hi < dlo:
                    return False
                if dhi is not None and lo is not None and lo > dhi:
                    return False
            except TypeError:
                continue  # incomparable statistic/domain value kinds
        return True

    # --------------------------------------------------------------- scan
    def scan(self, split: spi.Split, columns: List[str], constraint=None) -> Dict[str, spi.ColumnData]:
        path = self._table_path(split.schema, split.table)
        if self._text_format(path):
            tbl = self._read_text_table(path).select(list(columns))
            return {name: _column_data(tbl.column(name)) for name in columns}
        if self._is_orc(path):
            import pyarrow as pa

            f = _porc().ORCFile(path)
            stripes = (list(split.info) if split.info is not None
                       else list(range(f.nstripes)))
            if not stripes:
                tbl = f.schema.empty_table().select(list(columns))
            else:
                parts = [f.read_stripe(i, columns=list(columns))
                         for i in stripes]
                tbl = (pa.Table.from_batches(parts) if parts
                       else f.schema.empty_table().select(list(columns)))
            return {name: _column_data(tbl.column(name)) for name in columns}
        pf = _pq().ParquetFile(path)
        if split.info is not None:
            row_groups = list(split.info)
        else:
            row_groups = list(range(pf.metadata.num_row_groups))
        if not row_groups:  # empty pad split (SPMD over-provisioned devices)
            tbl = pf.schema_arrow.empty_table().select(list(columns))
        else:
            tbl = pf.read_row_groups(row_groups, columns=list(columns))
        out: Dict[str, spi.ColumnData] = {}
        for name in columns:
            out[name] = _column_data(tbl.column(name))
        return out

    # -------------------------------------------------------------- write
    def _write_arrow(self, path: str, tbl) -> None:
        """One write dispatch for both columnar formats (create/insert/
        overwrite all funnel here)."""
        if self._is_orc(path):
            _porc().write_table(tbl, path, stripe_size=64 * 1024)
        else:
            _pq().write_table(tbl, path, row_group_size=self.ROW_GROUP_SIZE)

    @staticmethod
    def _columnize(columns, rows):
        """[(name, type)] + python rows -> arrow table."""
        pa = _pa()
        arrays, fields = [], []
        for i, (cname, ctype) in enumerate(columns):
            at = _arrow_from_type(ctype)
            arrays.append(pa.array(
                [_coerce_py(ctype, r[i]) for r in rows], type=at))
            fields.append(pa.field(cname, at))
        return pa.table(arrays, schema=pa.schema(fields))

    def create_table(self, schema: str, name: str, schema_def, rows) -> None:
        pa = _pa()
        path = self._table_path(schema, name)
        if os.path.exists(path):
            raise ValueError(f"table already exists: {schema}.{name}")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._write_arrow(path, self._columnize(schema_def, rows))

    def insert_rows(self, schema: str, table: str, rows) -> int:
        """Append by rewrite (single-file tables; the multi-file append is
        the lakehouse upgrade)."""
        pa = _pa()
        meta = self.get_table(schema, table)
        if meta is None:
            raise KeyError(f"{self.name}.{schema}.{table} does not exist")
        path = self._table_path(schema, table)
        if self._text_format(path):
            raise NotImplementedError(
                f"{self.name}: {self._text_format(path)} tables are "
                "read-only (write to parquet/orc)")
        old = (_porc().ORCFile(path).read() if self._is_orc(path)
               else _pq().read_table(path))
        arrays = []
        for i, cm in enumerate(meta.columns):
            at = _arrow_from_type(cm.type)
            new = pa.array([_coerce_py(cm.type, r[i]) for r in rows], type=at)
            arrays.append(pa.concat_arrays([old.column(i).combine_chunks(), new]))
        self._write_arrow(
            path, pa.table(arrays, names=[c.name for c in meta.columns]))
        return len(rows)

    def overwrite_rows(self, schema: str, table: str, rows) -> None:
        """Rewrite the table file with the engine-computed row set."""
        meta = self.get_table(schema, table)
        if meta is None:
            raise KeyError(f"{self.name}.{schema}.{table} does not exist")
        path = self._table_path(schema, table)
        fmt = self._text_format(path)
        if fmt:
            raise NotImplementedError(
                f"{self.name}: {fmt} tables are read-only "
                "(write to parquet/orc)")
        self._write_arrow(path, self._columnize(
            [(c.name, c.type) for c in meta.columns], rows))

    def drop_table(self, schema: str, table: str) -> None:
        path = self._table_path(schema, table)
        if os.path.exists(path):
            os.remove(path)


def _coerce_py(t: T.Type, v):
    """Python value -> the arrow type's expected Python kind (the engine's
    implicit widening: int/Decimal into double, int into decimal, ...)."""
    import decimal

    if v is None:
        return None
    if t == T.DOUBLE:
        return float(v)
    if t.is_decimal and not isinstance(v, decimal.Decimal):
        return decimal.Decimal(v)
    if t in (T.BIGINT, T.INTEGER) and not isinstance(v, bool):
        return int(v)
    return v


def _stat_repr(v):
    """Parquet statistic value -> engine storage repr."""
    import datetime
    import decimal

    if isinstance(v, datetime.date):
        return (v - datetime.date(1970, 1, 1)).days
    if isinstance(v, decimal.Decimal):
        exp = -v.as_tuple().exponent
        return int(v.scaleb(exp))
    return v


def _column_data(chunked) -> spi.ColumnData:
    """Arrow column -> engine ColumnData (storage reprs, dictionary-first
    strings)."""
    pa = _pa()
    arr = chunked.combine_chunks() if hasattr(chunked, "combine_chunks") else chunked
    at = arr.type
    t = _type_from_arrow(at)
    n = len(arr)
    nulls = None
    if arr.null_count:
        nulls = np.asarray(arr.is_null())
    if t.is_varchar:
        # dictionary-encode through arrow (C++-side) — no per-row Python
        dict_arr = arr.dictionary_encode() if not pa.types.is_dictionary(at) else arr
        vocab = dict_arr.dictionary.to_pylist()
        codes = np.asarray(dict_arr.indices.fill_null(-1)).astype(np.int32)
        # engine dictionaries are sorted + order-preserving: recode
        d = Dictionary.build([v for v in vocab if v is not None])
        remap = np.array(
            [d.code_of(v) if v is not None else -1 for v in vocab], dtype=np.int32
        )
        if len(remap) == 0:
            # all-null column: empty vocab would make the remap gather
            # raise (np.where evaluates both branches); one -1 pad keeps
            # the shape machinery happy and every row maps to NULL
            remap = np.array([-1], dtype=np.int32)
        vals = np.where(codes >= 0, remap[np.clip(codes, 0, None)], -1).astype(np.int32)
        return spi.ColumnData(t, vals, nulls, d)
    if t == T.DATE:
        vals = np.asarray(arr.cast(pa.int32())).astype(np.int32)
        return spi.ColumnData(t, vals, nulls)
    if t.is_decimal:
        # decimal128's storage IS the scaled integer: read the 16-byte
        # little-endian values straight from the validity+data buffers
        # (casting through arrow would round to the integral VALUE).
        if arr.offset:
            arr = arr.combine_chunks() if hasattr(arr, "combine_chunks") else arr
            arr = arr.slice(0)  # normalize; buffers() below honors offset via copy
            arr = pa.concat_arrays([arr])
        data = np.frombuffer(arr.buffers()[1], dtype=np.int64)
        vals = np.ascontiguousarray(
            data[2 * arr.offset : 2 * (arr.offset + n) : 2]
        )  # low limb = full value for p <= 18
        if t.precision > 18:
            hi = np.ascontiguousarray(
                data[2 * arr.offset + 1 : 2 * (arr.offset + n) + 1 : 2]
            )
            if not np.array_equal(hi, vals >> 63):
                # genuinely-wide values: two-limb column (Column.hi)
                return spi.ColumnData(t, vals, nulls, hi=hi)
        return spi.ColumnData(t, vals, nulls)
    vals = np.asarray(arr.fill_null(0) if arr.null_count else arr)
    return spi.ColumnData(t, np.asarray(vals, dtype=t.np_dtype), nulls)
