"""Black-hole connector: swallow writes, serve empty scans.

Reference: ``plugin/trino-blackhole`` (2.2k LoC) — the null sink/source used
to benchmark write paths and exercise DDL/DML without storage. Tables keep
metadata only; INSERT counts rows and discards them; scans return zero rows.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector import spi
from trino_tpu.data.dictionary import Dictionary


class BlackHoleConnector(spi.Connector):
    name = "blackhole"

    def __init__(self):
        self._tables: Dict[Tuple[str, str], spi.TableMetadata] = {}
        self.rows_swallowed = 0

    def create_table(self, schema: str, name: str, schema_def, rows) -> None:
        self._tables[(schema, name)] = spi.TableMetadata(
            schema, name, [spi.ColumnMetadata(n, t) for n, t in schema_def]
        )
        self.rows_swallowed += len(rows)

    def insert_rows(self, schema: str, table: str, rows) -> int:
        if (schema, table) not in self._tables:
            raise KeyError(f"blackhole.{schema}.{table} does not exist")
        self.rows_swallowed += len(rows)
        return len(rows)

    def drop_table(self, schema: str, table: str) -> None:
        self._tables.pop((schema, table), None)

    def list_schemas(self) -> List[str]:
        return sorted({s for s, _ in self._tables} | {"default"})

    def list_tables(self, schema: str) -> List[str]:
        return sorted(n for s, n in self._tables if s == schema)

    def get_table(self, schema: str, table: str) -> Optional[spi.TableMetadata]:
        return self._tables.get((schema, table))

    def table_row_count(self, schema: str, table: str) -> Optional[int]:
        return 0 if (schema, table) in self._tables else None

    def data_version(self, schema: str, table: str) -> str:
        # scans always return zero rows regardless of writes swallowed
        return "immutable"

    def get_splits(self, schema: str, table: str, target_splits: int, constraint=None,
                   handle=None) -> List[spi.Split]:
        return [spi.Split(table, schema, 0, 0)]

    def scan(self, split: spi.Split, columns: List[str], constraint=None) -> Dict[str, spi.ColumnData]:
        meta = self._tables[(split.schema, split.table)]
        out = {}
        for c in columns:
            t = meta.columns[meta.column_index(c)].type
            out[c] = spi.ColumnData(
                t,
                np.empty(0, dtype=t.np_dtype or np.dtype(np.int64)),
                None,
                Dictionary([]) if t.is_varchar else None,
            )
        return out
