from trino_tpu.connector.blackhole.connector import BlackHoleConnector

__all__ = ["BlackHoleConnector"]
