from trino_tpu.connector.system.connector import (  # noqa: F401
    SYSTEM_CATALOG, SYSTEM_PROCEDURES, SYSTEM_TABLES, SystemConnector,
    metric_sample_rows)
