"""System catalog connector: SQL-queryable runtime introspection.

Reference: ``core/trino-main/.../connector/system/`` — the ``system``
catalog whose ``system.runtime.queries/tasks/nodes`` tables are fed LIVE
from coordinator state (``QuerySystemTable``, ``TaskSystemTable``,
``NodeSystemTable``), plus the ``jmx`` connector's every-metric-as-a-
relation role collapsed into ``system.metrics``. Rows materialize at SCAN
time through a :class:`~trino_tpu.connector.spi.LiveTableProvider` the
owning server injects (``server/system_tables.py``); without a provider
(standalone sessions, worker processes) the runtime tables are empty and
``system.metrics`` falls back to this process's own registry — the
metadata surface (SHOW TABLES, information_schema) works everywhere.

Cache interaction: ``data_version`` returns None (live tables are
unversioned ⇒ plan/result caches never admit them) and the determinism
machinery (``trino_tpu/cache/determinism.py``) additionally flags any
``system`` scan as uncachable, so introspection queries are provably
never served stale.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from trino_tpu import types as T
from trino_tpu.connector import spi
from trino_tpu.connector.system.schemas import (
    SYSTEM_CATALOG, SYSTEM_PROCEDURES, SYSTEM_TABLES)

__all__ = ["SystemConnector", "SYSTEM_CATALOG", "SYSTEM_TABLES",
           "SYSTEM_PROCEDURES", "device_cache_rows", "metric_sample_rows"]


def device_cache_rows() -> List[tuple]:
    """THIS process's staged-table cache entries as
    ``system.runtime.device_cache`` rows (column order:
    connector/system/schemas.py): the warm-HBM pool (tier='hbm') plus the
    host-RAM columnar tier under it (tier='host'). The pools are
    process-global, so the coordinator provider and the providerless
    fallback (a standalone session, or a worker inspecting itself) share
    this one materializer."""
    from trino_tpu.devcache import DEVICE_CACHE, HOST_CACHE

    return [
        (e["catalog"], e["schema"], e["table"], e["version"], e["shard"],
         e["signature"], int(e["bytes"]), int(e["rows"]), int(e["hits"]),
         float(e["createdAt"]), float(e["lastUsedAt"]), tier)
        for tier, pool in (("hbm", DEVICE_CACHE), ("host", HOST_CACHE))
        for e in pool.snapshot()
    ]


def metric_sample_rows() -> List[tuple]:
    """Every touched series of this process's typed registry as
    ``(name, type, labels, value, help)`` rows — histogram buckets expand
    to ``_bucket``/``_sum``/``_count`` rows exactly like the Prometheus
    exposition (obs/metrics.registry_samples)."""
    from trino_tpu.obs.metrics import registry_samples

    def render_labels(labels: Dict[str, str]) -> Optional[str]:
        if not labels:
            return None
        return ",".join(f'{k}="{v}"' for k, v in labels.items())

    return [
        (name, type_name, render_labels(labels), float(value), help_text)
        for name, type_name, labels, value, help_text in registry_samples()
    ]


class SystemConnector(spi.Connector):
    name = "system"
    # live state exists only on the process that injected the provider
    # (the coordinator): scans must never be distributed to workers
    coordinator_only = True
    # the metrics schema holds exactly one relation named like the schema,
    # so the two-part spelling ``system.metrics`` resolves through the
    # planner's single-table-schema fallback (gated on this declaration)
    single_table_schemas = True

    def __init__(self, provider: Optional[spi.LiveTableProvider] = None):
        self._provider = provider
        self._metadata: Dict[tuple, spi.TableMetadata] = {}
        for (schema, table), columns in SYSTEM_TABLES.items():
            self._metadata[(schema, table)] = spi.TableMetadata(
                schema, table,
                [spi.ColumnMetadata(n, T.parse_type(t)) for n, t in columns])

    # ----------------------------------------------------------- SPI hooks
    def attach_live_provider(self, provider: spi.LiveTableProvider) -> None:
        self._provider = provider

    def procedure(self, schema: str, name: str):
        if (schema, name) not in SYSTEM_PROCEDURES:
            return None
        if self._provider is None:
            raise ValueError(
                f"procedure system.{schema}.{name} requires a coordinator "
                "(no live provider attached in this process)")
        return self._provider.procedure(schema, name)

    # ------------------------------------------------------------ metadata
    def list_schemas(self) -> List[str]:
        return sorted({s for s, _ in SYSTEM_TABLES})

    def list_tables(self, schema: str) -> List[str]:
        return sorted(t for s, t in SYSTEM_TABLES if s == schema)

    def get_table(self, schema: str, table: str) -> Optional[spi.TableMetadata]:
        return self._metadata.get((schema, table))

    def data_version(self, schema: str, table: str) -> Optional[str]:
        # live tables are unversioned BY DESIGN: the plan and result caches
        # cannot revalidate them, so every introspection query re-snapshots
        return None

    # --------------------------------------------------------------- scan
    def get_splits(self, schema: str, table: str, target_splits: int,
                   constraint=None, handle=None) -> List[spi.Split]:
        if (schema, table) not in SYSTEM_TABLES:
            raise KeyError(f"system.{schema}.{table} does not exist")
        # ONE split always: the snapshot happens at scan time, and a table
        # this size (metadata scale) gains nothing from parallel scans
        # while a multi-split scan would stitch two different instants
        return [spi.Split(table, schema, 0, 0)]

    def _rows(self, schema: str, table: str) -> List[tuple]:
        if self._provider is not None:
            return self._provider.snapshot_rows(schema, table)
        if (schema, table) == ("metrics", "metrics"):
            return metric_sample_rows()
        if (schema, table) == ("runtime", "device_cache"):
            # the cache pool is process-global: even without a live
            # provider a session can inspect its own process's entries
            return device_cache_rows()
        if (schema, table) == ("runtime", "memory"):
            # the memory ledger is process-global too: a providerless
            # session reads its own process's owner rows
            from trino_tpu.obs.memledger import MEMORY_LEDGER

            nid = MEMORY_LEDGER.node_id or "local"
            return [(nid, r["pool"], r["owner"], int(r["bytes"]),
                     int(r["peakBytes"]), int(r["events"]))
                    for r in MEMORY_LEDGER.owner_rows()]
        return []

    def scan(self, split: spi.Split, columns: List[str],
             constraint=None) -> Dict[str, spi.ColumnData]:
        from trino_tpu.data.page import Column

        meta = self._metadata[(split.schema, split.table)]
        rows = self._rows(split.schema, split.table)
        index = {c.name: i for i, c in enumerate(meta.columns)}
        out: Dict[str, spi.ColumnData] = {}
        for c in columns:
            i = index[c]
            col = Column.from_python(meta.columns[i].type,
                                     [r[i] for r in rows])
            out[c] = spi.column_data_from_column(col)
        return out
