"""System-catalog table schemas — the single source of truth.

Reference: ``core/trino-main/.../connector/system/`` — every system table
declares its ``ConnectorTableMetadata`` statically (``QuerySystemTable``,
``TaskSystemTable``, ``NodeSystemTable``) while its ROWS materialize at
scan time from live coordinator state. Here the declarations live in a
dependency-free module (types as strings, parsed by the connector with
``T.parse_type``) so the docs drift gate (``tools/
check_system_table_docs.py``) can load them without pulling in jax, the
same standalone-file trick the metric and session-property gates use.

``SYSTEM_TABLES`` maps ``(schema, table)`` to an ordered column tuple of
``(name, type_string)``. The ``metrics`` schema follows the single-table-
schema convention (``metrics.metrics``) so the two-part spelling
``system.metrics`` resolves (sql/planner/planner.py's catalog fallback).
"""
from __future__ import annotations

SYSTEM_CATALOG = "system"

SYSTEM_TABLES = {
    # every query the coordinator tracks: live (QUEUED..RUNNING) from the
    # query registry, completed from the bounded history ring
    ("runtime", "queries"): (
        ("query_id", "varchar"),
        ("state", "varchar"),
        ("user", "varchar"),
        ("query", "varchar"),
        ("created_at", "double"),      # epoch seconds
        ("ended_at", "double"),        # epoch seconds; NULL while running
        ("elapsed_ms", "bigint"),
        ("device_seconds", "double"),
        ("total_splits", "bigint"),
        ("completed_splits", "bigint"),
        ("input_rows", "bigint"),
        ("output_bytes", "bigint"),
        ("peak_bytes", "bigint"),
        ("shed_bytes", "bigint"),      # revocable-cache bytes shed on this
                                       # query's behalf (memory ledger)
        ("yield_events", "bigint"),    # revocable-yield events (spill-path
                                       # cache yields) this query triggered
        ("result_rows", "bigint"),
        ("cache_status", "varchar"),   # HIT | MISS | BYPASS; NULL early
        ("adaptations", "bigint"),
        ("plan_versions", "bigint"),
        ("failure", "varchar"),
        ("fast_path", "varchar"),      # fast-path | distributed |
                                       # local-catalog; NULL for non-SELECT
                                       # and for SELECTs served straight
                                       # from the result cache (no
                                       # execution path was taken)
        # phase-ledger rollups (obs/timeline.py), computed at completion
        # from the merged span tree; NULL while the query still runs.
        # planning = dispatch + parse-analyze + plan-optimize +
        # prepare-bind; execution = schedule + device-staging +
        # device-execute + exchange-wait + result-serialization; the
        # full per-phase breakdown rides queryStats.timeline.
        ("queued_ms", "double"),
        ("planning_ms", "double"),
        ("execution_ms", "double"),
        ("unattributed_ms", "double"),
        ("resource_group", "varchar"),  # full dotted group path that
                                        # admitted the query; NULL under
                                        # a legacy injected flat gate
    ),
    # the resource-group admission tree (server/resource_groups.py): one
    # row per live group node — limits from the validated config, live
    # occupancy/queue depth, the ledger-backed memory rollup, and the
    # fairness knobs (weight, cache_share, queue_timeout_ms)
    ("runtime", "resource_groups"): (
        ("name", "varchar"),            # full dotted path (global.adhoc.u1)
        ("state", "varchar"),           # can-run | full | blocked-memory
        ("queued", "bigint"),
        ("running", "bigint"),          # subtree rollup
        ("served", "bigint"),           # concurrency-free serving-index hits
        ("hard_concurrency_limit", "bigint"),
        ("max_queued", "bigint"),
        ("memory_limit_bytes", "bigint"),   # NULL = unlimited
        ("memory_bytes", "bigint"),     # live ledger bytes of running queries
        ("weight", "bigint"),           # weighted-fair drain share
        ("cache_share", "double"),      # carve-out fraction; NULL = none
        ("queue_timeout_ms", "bigint"),  # aging deadline; NULL = never
    ),
    # prepared statements held by the coordinator registry
    # (server/prepared.py): one row per (user, name), live until
    # DEALLOCATE or LRU eviction
    ("runtime", "prepared_statements"): (
        ("user", "varchar"),
        ("name", "varchar"),
        ("statement", "varchar"),      # the inner (post-FROM) SQL text
        ("parameters", "bigint"),      # number of ? markers
        ("created_at", "double"),      # epoch seconds
        ("executions", "bigint"),
        ("last_executed_at", "double"),  # epoch seconds; NULL before first
    ),
    # the serving plane's shared-state ownership table (server/
    # dispatch.py): one row per shared structure of the dispatch/executor
    # split — which process owns it, in which plane mode, and how full
    # it is — so the ownership story is introspectable over SQL
    ("runtime", "serving"): (
        ("structure", "varchar"),      # dispatch_queue | executor_lanes
                                       # | serving_index | result_cache |
                                       # plan_cache | prepared_statements
                                       # | materialized_views
                                       # | query_registry | query_history
                                       # | device
        ("owner", "varchar"),          # dispatch-process |
                                       # executor-process (sticky shard)
        ("plane", "varchar"),          # thread | process
        ("entries", "bigint"),         # occupancy (NULL where not sized)
        ("bytes", "bigint"),           # byte footprint (NULL unknown)
        ("detail", "varchar"),         # capacity / ownership note
    ),
    # per-slot task records of live queries (worker-reported stats rollup)
    ("runtime", "tasks"): (
        ("query_id", "varchar"),
        ("task_id", "varchar"),
        ("stage_id", "bigint"),
        ("state", "varchar"),
        ("worker_uri", "varchar"),
        ("total_splits", "bigint"),
        ("completed_splits", "bigint"),
        ("input_rows", "bigint"),
        ("output_rows", "bigint"),
        ("output_bytes", "bigint"),
        ("peak_bytes", "bigint"),
        ("elapsed_seconds", "double"),
        ("device_seconds", "double"),
        ("operators", "bigint"),       # distinct plan nodes with stats
    ),
    # discovery registry + the workers' announce payloads
    ("runtime", "nodes"): (
        ("node_id", "varchar"),
        ("http_uri", "varchar"),
        ("state", "varchar"),          # active | dead (announce aged out)
        ("version", "varchar"),
        ("tasks", "bigint"),
        ("memory_used_bytes", "bigint"),
        ("memory_limit_bytes", "bigint"),
        ("device_memory_bytes", "bigint"),  # announced HBM capacity; NULL
                                            # when not discoverable (CPU)
        ("device_cache_bytes", "bigint"),   # warm-table bytes (revocable)
        ("heartbeat_age_ms", "bigint"),
        ("host_cache_bytes", "bigint"),     # host-RAM columnar tier bytes
                                            # (second revocable tier —
                                            # sheds before the HBM tier)
        ("host_cache_hits", "bigint"),      # lifetime host-tier hits
        ("net_bytes_sent", "bigint"),       # flow-ledger lifetime bytes
                                            # sent across every link
        ("net_bytes_received", "bigint"),   # ...and received
    ),
    # the staged-table caches (trino_tpu/devcache/): one row per resident
    # entry of THIS process's pools — the warm-HBM tier (tier='hbm') and
    # the host-RAM columnar tier under it (tier='host', per-split decoded
    # column sets) — the coordinator's when a provider is attached; any
    # process can inspect its own
    ("runtime", "device_cache"): (
        ("catalog", "varchar"),
        ("schema_name", "varchar"),
        ("table_name", "varchar"),
        ("data_version", "varchar"),
        ("shard", "varchar"),          # table | splits:N:... | spmd:N |
                                       # host:splits:1:... (host tier)
        ("signature", "varchar"),      # projection/pruning digest
        ("entry_bytes", "bigint"),
        ("rows", "bigint"),
        ("hits", "bigint"),
        ("created_at", "double"),      # epoch seconds
        ("last_used_at", "double"),
        ("tier", "varchar"),           # hbm | host
    ),
    # the cluster memory ledger (trino_tpu/obs/memledger.py): one row per
    # (node, pool, owner) — live attributed bytes, the owner's peak, and
    # how many ledger events it produced. Owners: query:<id> |
    # device-cache | host-cache | staging | mv-storage | total (the
    # per-pool watermark row, so attribution coverage = sum(named owners)
    # / total is computable from this table alone). Coordinator rows come
    # from its own process ledger; worker rows ride the announce payload.
    ("runtime", "memory"): (
        ("node_id", "varchar"),
        ("pool", "varchar"),           # device | host
        ("owner", "varchar"),
        ("bytes", "bigint"),           # live attributed bytes
        ("peak_bytes", "bigint"),      # this owner's high-water mark
        ("events", "bigint"),          # ledger events this owner produced
    ),
    # the kernel ledger (trino_tpu/obs/devprofiler.py): one row per
    # (query, plan node, operator, tier, node) — device dispatches with
    # wall vs device seconds split, so dispatch overhead is an explicit
    # per-operator number. Terminal queries read the folded profiler
    # store; RUNNING queries merge their live task rollups.
    ("runtime", "kernels"): (
        ("query_id", "varchar"),
        ("node_id", "varchar"),        # worker uri or "coordinator"
        ("plan_node_id", "varchar"),
        ("operator", "varchar"),       # TableScan | Join | CompiledBody...
        ("tier", "varchar"),           # eager | compiled | spmd
        ("launches", "bigint"),
        ("wall_seconds", "double"),
        ("device_seconds", "double"),  # measured under device_profiling,
                                       # estimated from wall otherwise
        ("dispatch_overhead_seconds", "double"),  # wall − device
        ("input_bytes", "bigint"),
        ("output_bytes", "bigint"),
        ("estimated", "boolean"),      # true = no-sync estimate
    ),
    # the compile ledger (trino_tpu/obs/devprofiler.py): one row per
    # jit/Pallas compile event cluster-wide — plan fingerprint + shape
    # signature name WHAT compiled, cache says hit or miss. Worker rows
    # ride the announce payload (compileEvents); coordinator rows come
    # from its own process ring.
    ("runtime", "compiles"): (
        ("node_id", "varchar"),
        ("query_id", "varchar"),       # empty for bench/local compiles
        ("tier", "varchar"),           # eager | compiled | spmd
        ("fingerprint", "varchar"),    # plan fingerprint (cache/plan_key)
        ("shape_signature", "varchar"),
        ("compile_seconds", "double"),
        ("cache", "varchar"),          # hit | miss
        ("created_at", "double"),      # epoch seconds
    ),
    # the data-plane flow ledger (trino_tpu/obs/flowledger.py): one row
    # per (node, link, owner) transfer rollup — bytes in motion typed by
    # link class (exchange-pull | spool-write | segment-fetch |
    # staging-transfer | client-drain | control) with derived effective
    # MB/s. Worker rows ride the announce payload (flows); coordinator
    # rows come from its own process ledger (announce rows win for a
    # shared in-process ledger).
    ("runtime", "transfers"): (
        ("node_id", "varchar"),
        ("link", "varchar"),           # link class (see above)
        ("owner", "varchar"),          # task:<id> | query:<id> |
                                       # drain:<id> | staging | control
        ("bytes", "bigint"),
        ("pages", "bigint"),
        ("transfers", "bigint"),       # records folded into this row
        ("seconds", "double"),         # transfer wall attributed here
        ("mb_per_s", "double"),        # bytes/seconds; NULL if no wall
        ("retries", "bigint"),
        ("last_status", "varchar"),    # last HTTP status / path marker
    ),
    # the straggler detector (trino_tpu/obs/flowledger.py): one row per
    # flagged task — elapsed exceeded the configurable multiple of its
    # stage median (straggler_multiple session property), attributed to
    # its dominant cause (transfer-bound | device-bound | queue-bound).
    # RUNNING queries detect live; terminal queries read frozen verdicts.
    ("runtime", "stragglers"): (
        ("query_id", "varchar"),
        ("stage_id", "bigint"),
        ("task_id", "varchar"),
        ("worker_uri", "varchar"),
        ("elapsed_seconds", "double"),
        ("stage_median_seconds", "double"),
        ("ratio", "double"),           # elapsed / stage median
        ("multiple", "double"),        # threshold multiple in force
        ("cause", "varchar"),          # dominant ledger seconds bucket
        ("completed_splits", "bigint"),
    ),
    # registered materialized views (trino_tpu/matview/): definitions,
    # storage location, and LIVE freshness (recomputed at scan time from
    # the connectors' current data versions vs the versions recorded at
    # the last REFRESH)
    ("metadata", "materialized_views"): (
        ("catalog", "varchar"),
        ("schema_name", "varchar"),
        ("name", "varchar"),
        ("owner", "varchar"),
        ("definition", "varchar"),      # the defining query's SQL text
        ("storage_table", "varchar"),   # catalog.schema.table holding rows
        ("fresh", "boolean"),           # substitutable right now?
        ("stale_reason", "varchar"),    # NULL when fresh
        ("last_refresh", "double"),     # epoch seconds; NULL never run
        ("base_versions", "varchar"),   # c.s.t@version, ... at REFRESH
        ("hit_count", "bigint"),        # plans substituted so far
        ("refresh_count", "bigint"),
    ),
    # every touched series of the typed metrics registry as rows — the jmx
    # connector's role; /v1/metrics stays the Prometheus surface
    ("metrics", "metrics"): (
        ("name", "varchar"),
        ("type", "varchar"),           # counter | gauge | histogram
        ("labels", "varchar"),         # k="v",... rendered label set
        ("value", "double"),
        ("help", "varchar"),
    ),
}

# procedures the system connector registers (CALL surface); listed here so
# the docs gate can require each to be documented alongside the tables
SYSTEM_PROCEDURES = (
    ("runtime", "kill_query"),
    ("runtime", "sync_materialized_view"),
)
