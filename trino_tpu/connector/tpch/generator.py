"""TPC-H data generator: stateless, vectorized, split-parallel.

Reference: ``plugin/trino-tpch`` (TpchMetadata.java:99, TpchRecordSetProvider)
generates TPC-H data on the fly from the dbgen algorithm. This generator
reproduces the *schema, scale rules, key relationships, and value
distributions* of the TPC-H spec with a counter-based PRNG (splitmix64 over
row indices), so ANY row range of any table can be generated independently —
that is what makes distributed scans coordination-free (a split is a row/order
range; each worker generates its own slice bit-identically).

Deviations from dbgen (documented; the correctness oracle runs on OUR data so
tests are exact regardless): text columns (comments, addresses, part names)
draw from bounded phrase pools instead of the dbgen grammar corpus, so
dictionaries stay small at scale; LIKE-pattern selectivities used by TPC-H
queries (e.g. '%special%requests%', '%green%') are preserved by construction.
"""
from __future__ import annotations

import datetime
from typing import Dict, List, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector.spi import ColumnData
from trino_tpu.data.dictionary import Dictionary

# --- counter-based PRNG (splitmix64) ---------------------------------------

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)


def _mix(x: np.ndarray) -> np.ndarray:
    # operands are already uint64 (the _stream contract): the wrapping
    # arithmetic stays uint64 end to end, so no .astype copies — the old
    # per-round astype was 3 full-array copies per draw, a measurable
    # slice of cold staging at sf>=2
    with np.errstate(over="ignore"):
        x = x + _GOLDEN
        x = (x ^ (x >> np.uint64(30))) * _M1
        x = (x ^ (x >> np.uint64(27))) * _M2
        return x ^ (x >> np.uint64(31))


def _stream(tag: int, idx: np.ndarray) -> np.ndarray:
    """Independent uniform u64 stream ``tag`` evaluated at positions ``idx``."""
    with np.errstate(over="ignore"):
        base = np.uint64(tag) * np.uint64(0xD6E8FEB86659FD93)
        return _mix(base ^ idx.astype(np.uint64))


def _randint(tag: int, idx: np.ndarray, lo: int, hi: int) -> np.ndarray:
    """Uniform int64 in [lo, hi] inclusive."""
    span = np.uint64(hi - lo + 1)
    return lo + (_stream(tag, idx) % span).astype(np.int64)


# --- epoch-day helpers ------------------------------------------------------

_EPOCH = datetime.date(1970, 1, 1)


def _d(s: str) -> int:
    return (datetime.date.fromisoformat(s) - _EPOCH).days


START_DATE = _d("1992-01-01")
CURRENT_DATE = _d("1995-06-17")
END_DATE = _d("1998-08-02")

# --- vocabularies (spec lists; see TPC-H spec 4.2.2-4.2.3) ------------------

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
# (nation, region_index) in nationkey order 0..24 (spec table)
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
MKT_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
ORDER_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIP_INSTRUCTIONS = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
SHIP_MODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
PART_COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "hotpink", "indian", "ivory", "khaki", "lace", "lavender", "lawn",
    "lemon", "light", "lime", "linen", "magenta", "maroon", "medium", "metallic",
    "midnight", "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange",
    "orchid", "pale", "papaya", "peach", "peru", "pink", "plum", "powder",
    "puff", "purple", "red", "rose", "rosy", "royal", "saddle", "salmon",
    "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring",
    "steel", "tan", "thistle", "tomato", "turquoise", "violet", "wheat", "white",
    "yellow",
]
TYPE_SYLLABLE1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_SYLLABLE2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_SYLLABLE3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_SYLLABLE1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_SYLLABLE2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]

# Comment phrase pool: bounded vocabulary with the LIKE-relevant phrases
# ("special...requests", "Customer...Complaints", colors) mixed in at
# spec-plausible rates.
_COMMENT_WORDS = [
    "carefully", "quickly", "furiously", "slyly", "blithely", "ironic",
    "regular", "express", "special", "final", "pending", "bold", "even",
    "silent", "unusual", "daring", "requests", "deposits", "packages",
    "accounts", "instructions", "foxes", "pinto", "beans", "theodolites",
    "dependencies", "platelets", "ideas", "asymptotes", "somas", "sauternes",
    "warhorses", "sheaves", "sleep", "nag", "wake", "haggle", "cajole",
    "detect", "integrate", "engage", "about", "among", "across", "against",
]


def _phrase_pool(tag: int, size: int, words_per: int = 4) -> List[str]:
    idx = np.arange(size, dtype=np.uint64)
    cols = [
        np.asarray(_COMMENT_WORDS)[
            np.asarray(_stream(tag * 7 + k, idx) % np.uint64(len(_COMMENT_WORDS)), dtype=np.int64)
        ]
        for k in range(words_per)
    ]
    return [" ".join(t) for t in zip(*cols)]


_ORDER_COMMENT_POOL: List[str] = None
_GENERIC_COMMENT_POOL: List[str] = None


def _order_comment_pool() -> List[str]:
    global _ORDER_COMMENT_POOL
    if _ORDER_COMMENT_POOL is None:
        pool = _phrase_pool(11, 1024)
        # ~1.2% of orders match '%special%requests%' (Q13's exclusion pattern)
        for i in range(0, 1024, 83):
            pool[i] = "special packages wake quickly among the requests"
        _ORDER_COMMENT_POOL = pool
    return _ORDER_COMMENT_POOL


def _generic_comment_pool() -> List[str]:
    global _GENERIC_COMMENT_POOL
    if _GENERIC_COMMENT_POOL is None:
        _GENERIC_COMMENT_POOL = _phrase_pool(13, 1024)
    return _GENERIC_COMMENT_POOL


# --- scale rules ------------------------------------------------------------


def table_row_count(table: str, sf: float) -> int:
    if table == "region":
        return 5
    if table == "nation":
        return 25
    if table == "supplier":
        return max(1, round(10_000 * sf))
    if table == "customer":
        return max(1, round(150_000 * sf))
    if table == "part":
        return max(1, round(200_000 * sf))
    if table == "partsupp":
        return table_row_count("part", sf) * 4
    if table == "orders":
        return max(1, round(1_500_000 * sf))
    if table == "lineitem":
        # variable (1..7 lines per order); exact count needs the per-order
        # draw — report the expected value as a stats estimate
        return int(table_row_count("orders", sf) * 4)
    raise KeyError(table)


SCHEMAS: Dict[str, List[Tuple[str, str]]] = {
    "region": [
        ("r_regionkey", "bigint"), ("r_name", "varchar(25)"), ("r_comment", "varchar(152)"),
    ],
    "nation": [
        ("n_nationkey", "bigint"), ("n_name", "varchar(25)"),
        ("n_regionkey", "bigint"), ("n_comment", "varchar(152)"),
    ],
    "supplier": [
        ("s_suppkey", "bigint"), ("s_name", "varchar(25)"), ("s_address", "varchar(40)"),
        ("s_nationkey", "bigint"), ("s_phone", "varchar(15)"),
        ("s_acctbal", "decimal(12,2)"), ("s_comment", "varchar(101)"),
    ],
    "customer": [
        ("c_custkey", "bigint"), ("c_name", "varchar(25)"), ("c_address", "varchar(40)"),
        ("c_nationkey", "bigint"), ("c_phone", "varchar(15)"),
        ("c_acctbal", "decimal(12,2)"), ("c_mktsegment", "varchar(10)"),
        ("c_comment", "varchar(117)"),
    ],
    "part": [
        ("p_partkey", "bigint"), ("p_name", "varchar(55)"), ("p_mfgr", "varchar(25)"),
        ("p_brand", "varchar(10)"), ("p_type", "varchar(25)"), ("p_size", "integer"),
        ("p_container", "varchar(10)"), ("p_retailprice", "decimal(12,2)"),
        ("p_comment", "varchar(23)"),
    ],
    "partsupp": [
        ("ps_partkey", "bigint"), ("ps_suppkey", "bigint"), ("ps_availqty", "integer"),
        ("ps_supplycost", "decimal(12,2)"), ("ps_comment", "varchar(199)"),
    ],
    "orders": [
        ("o_orderkey", "bigint"), ("o_custkey", "bigint"), ("o_orderstatus", "varchar(1)"),
        ("o_totalprice", "decimal(12,2)"), ("o_orderdate", "date"),
        ("o_orderpriority", "varchar(15)"), ("o_clerk", "varchar(15)"),
        ("o_shippriority", "integer"), ("o_comment", "varchar(79)"),
    ],
    "lineitem": [
        ("l_orderkey", "bigint"), ("l_partkey", "bigint"), ("l_suppkey", "bigint"),
        ("l_linenumber", "integer"), ("l_quantity", "decimal(12,2)"),
        ("l_extendedprice", "decimal(12,2)"), ("l_discount", "decimal(12,2)"),
        ("l_tax", "decimal(12,2)"), ("l_returnflag", "varchar(1)"),
        ("l_linestatus", "varchar(1)"), ("l_shipdate", "date"),
        ("l_commitdate", "date"), ("l_receiptdate", "date"),
        ("l_shipinstruct", "varchar(25)"), ("l_shipmode", "varchar(10)"),
        ("l_comment", "varchar(44)"),
    ],
}

_DEC2 = T.decimal(12, 2)


def _vocab_col(vocab: List[str], codes_into_vocab: np.ndarray) -> ColumnData:
    """Column over an unsorted vocab: re-sort vocab, remap codes."""
    order = np.argsort(np.asarray(vocab))
    sorted_vocab = [vocab[i] for i in order]
    inverse = np.empty(len(vocab), dtype=np.int32)
    inverse[order] = np.arange(len(vocab), dtype=np.int32)
    return ColumnData(
        T.varchar(), values=inverse[codes_into_vocab], dictionary=Dictionary(sorted_vocab)
    )


def _keyed_name_col(prefix: str, keys: np.ndarray, lo: int, hi: int) -> ColumnData:
    """'Customer#000000042'-style columns: zero-padded -> lexicographic order
    equals key order, so the dictionary is the key range itself."""
    vocab = [f"{prefix}#{k:09d}" for k in range(lo, hi)]
    return ColumnData(
        T.varchar(), values=(keys - lo).astype(np.int32), dictionary=Dictionary(vocab)
    )


def _pool_comment_col(pool: List[str], tag: int, idx: np.ndarray) -> ColumnData:
    codes = np.asarray(_stream(tag, idx) % np.uint64(len(pool)), dtype=np.int64)
    return _vocab_col(pool, codes.astype(np.int32))


def _dec(values_scaled: np.ndarray) -> ColumnData:
    return ColumnData(_DEC2, values=values_scaled.astype(np.int64))


def _phone(nation: np.ndarray, tag: int, idx: np.ndarray) -> ColumnData:
    cc = 10 + nation
    a = _randint(tag + 1, idx, 100, 999)
    b = _randint(tag + 2, idx, 100, 999)
    c = _randint(tag + 3, idx, 1000, 9999)
    strs = [f"{w}-{x}-{y}-{z}" for w, x, y, z in zip(cc, a, b, c)]
    d = Dictionary.build(strs)
    return ColumnData(T.varchar(), values=d.encode(strs), dictionary=d)


def _memo1(fn):
    """One-draw memo: two columns built from the SAME random draw (e.g.
    nationkey + phone) share one materialization per build call."""
    cell = []

    def get():
        if not cell:
            cell.append(fn())
        return cell[0]

    return get


def _retail_price_scaled(partkey: np.ndarray) -> np.ndarray:
    # spec 4.2.3: retailprice = (90000 + (partkey/10 mod 20001) + 100*(partkey mod 1000)) / 100
    return (90000 + (partkey // 10) % 20001 + 100 * (partkey % 1000)).astype(np.int64)


# --- per-table generators ---------------------------------------------------


def generate(table: str, sf: float, lo: int, hi: int, columns=None) -> Dict[str, ColumnData]:
    """Generate rows [lo, hi) of ``table`` (for orders/lineitem: ORDER index
    range — lineitem expands to that range's line rows). ``columns`` prunes
    generation to the requested subset (the big tables only generate what the
    scan projects — the generator-side analog of connector projection
    pushdown, reference ConnectorMetadata.applyProjection). Results ride the
    scan-range cache (connector/gencache.py): re-scans of the same range —
    Q18's double lineitem read, phase-1 host evaluation before staging —
    cost generation once."""
    need = set(columns) if columns is not None else {n for n, _ in SCHEMAS[table]}
    return _gen_cache.generate(table, sf, lo, hi, need)


def _generate_vranged(table: str, sf: float, lo: int, hi: int, need) -> Dict[str, ColumnData]:
    out = _generate(table, sf, lo, hi, need)
    for name, cd in out.items():
        if cd.vrange is None:
            cd.vrange = column_vrange(table, name, sf)
    return out


from trino_tpu.connector.gencache import GenCache  # noqa: E402

_gen_cache = GenCache(_generate_vranged)


def _generate(table: str, sf: float, lo: int, hi: int, need) -> Dict[str, ColumnData]:
    if table == "orders":
        return _generate_orders(sf, lo, hi, need)
    if table == "lineitem":
        return _generate_lineitem(sf, lo, hi, need)
    if table == "region":
        idx = np.arange(lo, hi)
        pool = _generic_comment_pool()
        return {
            "r_regionkey": ColumnData(T.BIGINT, idx.astype(np.int64)),
            "r_name": _vocab_col(REGIONS[lo:hi], np.arange(hi - lo, dtype=np.int32)),
            "r_comment": _pool_comment_col(pool, 101, idx.astype(np.uint64)),
        }
    if table == "nation":
        idx = np.arange(lo, hi)
        names = [NATIONS[i][0] for i in range(lo, hi)]
        regionkeys = np.array([NATIONS[i][1] for i in range(lo, hi)], dtype=np.int64)
        return {
            "n_nationkey": ColumnData(T.BIGINT, idx.astype(np.int64)),
            "n_name": _vocab_col(names, np.arange(hi - lo, dtype=np.int32)),
            "n_regionkey": ColumnData(T.BIGINT, regionkeys),
            "n_comment": _pool_comment_col(_generic_comment_pool(), 102, idx.astype(np.uint64)),
        }
    if table == "supplier":
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        idx = keys.astype(np.uint64)

        def _s_comment():
            pool = list(_generic_comment_pool())
            # spec: 5 suppliers per SF*10k get Customer Complaints, 5 get
            # Recommends
            pool = pool + [
                "the furiously express Customer accounts detect Complaints",
                "blithely special packages wake Customer Recommends quickly",
            ]
            codes = np.asarray(_stream(205, idx) % np.uint64(1024), dtype=np.int64)
            complaints = _stream(206, idx) % np.uint64(2000) == 0
            recommends = _stream(207, idx) % np.uint64(2000) == 1
            codes = np.where(complaints, 1024, np.where(recommends, 1025, codes))
            return _vocab_col(pool, codes.astype(np.int32))

        # shared between s_nationkey and s_phone: one draw, not two
        _nation = _memo1(lambda: _randint(201, idx, 0, 24))

        builders = {
            "s_suppkey": lambda: ColumnData(T.BIGINT, keys),
            "s_name": lambda: _keyed_name_col("Supplier", keys, lo + 1, hi + 1),
            "s_address": lambda: _pool_comment_col(_generic_comment_pool(), 202, idx),
            "s_nationkey": lambda: ColumnData(T.BIGINT, _nation()),
            "s_phone": lambda: _phone(_nation(), 210, idx),
            "s_acctbal": lambda: _dec(_randint(203, idx, -99999, 999999)),
            "s_comment": _s_comment,
        }
        return {c: b() for c, b in builders.items() if c in need}
    if table == "customer":
        # generation honors ``need`` here exactly like orders/lineitem —
        # a q3-shaped scan (c_custkey, c_mktsegment) must not pay the
        # Python-heavy phone/name/address/comment synthesis it projects
        # away (pre-scan projection: the staging pipeline's "only needed
        # columns cross" rule applied at the source)
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        idx = keys.astype(np.uint64)

        def _c_mktsegment():
            seg = np.asarray(_stream(302, idx) % np.uint64(5), dtype=np.int64)
            return _vocab_col(MKT_SEGMENTS, seg.astype(np.int32))

        # shared between c_nationkey and c_phone: one draw, not two
        _nation = _memo1(lambda: _randint(301, idx, 0, 24))

        builders = {
            "c_custkey": lambda: ColumnData(T.BIGINT, keys),
            "c_name": lambda: _keyed_name_col("Customer", keys, lo + 1, hi + 1),
            "c_address": lambda: _pool_comment_col(_generic_comment_pool(), 303, idx),
            "c_nationkey": lambda: ColumnData(T.BIGINT, _nation()),
            "c_phone": lambda: _phone(_nation(), 310, idx),
            "c_acctbal": lambda: _dec(_randint(304, idx, -99999, 999999)),
            "c_mktsegment": _c_mktsegment,
            "c_comment": lambda: _pool_comment_col(_generic_comment_pool(), 305, idx),
        }
        return {c: b() for c, b in builders.items() if c in need}
    if table == "part":
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        idx = keys.astype(np.uint64)

        def _p_name():
            w1 = np.asarray(_stream(401, idx) % np.uint64(92), dtype=np.int64)
            w2 = np.asarray(_stream(402, idx) % np.uint64(92), dtype=np.int64)
            # p_name: two color words (dbgen uses five; bounded-vocab
            # deviation)
            vocab = [f"{a} {b}" for a in PART_COLORS for b in PART_COLORS]
            return _vocab_col(vocab, (w1 * 92 + w2).astype(np.int32))

        # shared between p_mfgr and p_brand: one draw, not two
        _m = _memo1(lambda: _randint(403, idx, 1, 5))

        def _p_mfgr():
            vocab = [f"Manufacturer#{i}" for i in range(1, 6)]
            return _vocab_col(vocab, (_m() - 1).astype(np.int32))

        def _p_brand():
            n = _randint(404, idx, 1, 5)
            vocab = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
            return _vocab_col(vocab, ((_m() - 1) * 5 + (n - 1)).astype(np.int32))

        def _p_type():
            t1 = np.asarray(_stream(405, idx) % np.uint64(6), dtype=np.int64)
            t2 = np.asarray(_stream(406, idx) % np.uint64(5), dtype=np.int64)
            t3 = np.asarray(_stream(407, idx) % np.uint64(5), dtype=np.int64)
            vocab = [
                f"{a} {b} {c}" for a in TYPE_SYLLABLE1 for b in TYPE_SYLLABLE2 for c in TYPE_SYLLABLE3
            ]
            return _vocab_col(vocab, (t1 * 25 + t2 * 5 + t3).astype(np.int32))

        def _p_container():
            c1 = np.asarray(_stream(408, idx) % np.uint64(5), dtype=np.int64)
            c2 = np.asarray(_stream(409, idx) % np.uint64(8), dtype=np.int64)
            vocab = [f"{a} {b}" for a in CONTAINER_SYLLABLE1 for b in CONTAINER_SYLLABLE2]
            return _vocab_col(vocab, (c1 * 8 + c2).astype(np.int32))

        builders = {
            "p_partkey": lambda: ColumnData(T.BIGINT, keys),
            "p_name": _p_name,
            "p_mfgr": _p_mfgr,
            "p_brand": _p_brand,
            "p_type": _p_type,
            "p_size": lambda: ColumnData(
                T.INTEGER, _randint(410, idx, 1, 50).astype(np.int32)),
            "p_container": _p_container,
            "p_retailprice": lambda: _dec(_retail_price_scaled(keys)),
            "p_comment": lambda: _pool_comment_col(_generic_comment_pool(), 411, idx),
        }
        return {c: b() for c, b in builders.items() if c in need}
    if table == "partsupp":
        rows = np.arange(lo, hi, dtype=np.int64)
        part = rows // 4 + 1
        idx = rows.astype(np.uint64)

        def _ps_suppkey():
            scount = table_row_count("supplier", sf)
            i = rows % 4
            # spec 4.2.3: ps_suppkey spread so joins distribute evenly
            supp = (part + i * (scount // 4 + (part - 1) // scount)) % scount + 1
            return ColumnData(T.BIGINT, supp.astype(np.int64))

        builders = {
            "ps_partkey": lambda: ColumnData(T.BIGINT, part),
            "ps_suppkey": _ps_suppkey,
            "ps_availqty": lambda: ColumnData(
                T.INTEGER, _randint(501, idx, 1, 9999).astype(np.int32)),
            "ps_supplycost": lambda: _dec(_randint(502, idx, 100, 100000)),
            "ps_comment": lambda: _pool_comment_col(_generic_comment_pool(), 503, idx),
        }
        return {c: b() for c, b in builders.items() if c in need}
    raise KeyError(table)


# Order/line shared deterministic draws (both tables derive the same values
# from (orderkey, linenumber) — this is what keeps o_orderstatus consistent
# with lineitem linestatus without cross-table generation order).


def _order_keys(lo: int, hi: int) -> np.ndarray:
    return np.arange(lo + 1, hi + 1, dtype=np.int64)


def _line_count(okey: np.ndarray) -> np.ndarray:
    return 1 + np.asarray(_stream(601, okey.astype(np.uint64)) % np.uint64(7), dtype=np.int64)


def _order_date(okey: np.ndarray) -> np.ndarray:
    return _randint(602, okey.astype(np.uint64), START_DATE, END_DATE - 151)


def _line_key(okey: np.ndarray, lnum: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        return (okey.astype(np.uint64) * np.uint64(8) + lnum.astype(np.uint64)).astype(np.uint64)


def _line_ship_date(okey, lnum):
    return _order_date(okey) + _randint(603, _line_key(okey, lnum), 1, 121)


def _generate_orders(sf: float, lo: int, hi: int, need) -> Dict[str, ColumnData]:
    okey = _order_keys(lo, hi)
    idx = okey.astype(np.uint64)
    out: Dict[str, ColumnData] = {}
    if "o_orderkey" in need:
        out["o_orderkey"] = ColumnData(T.BIGINT, okey)
    if "o_custkey" in need:
        ccount = table_row_count("customer", sf)
        # spec: only 2/3 of customers have orders (custkey not divisible by 3)
        raw = _randint(604, idx, 1, max(ccount - 1, 1))
        cust = np.minimum(raw + (raw % 3 == 0), ccount)
        out["o_custkey"] = ColumnData(T.BIGINT, cust.astype(np.int64))
    if "o_orderdate" in need:
        out["o_orderdate"] = ColumnData(T.DATE, _order_date(okey).astype(np.int32))
    if "o_orderstatus" in need or "o_totalprice" in need:
        # order status/total derived from the order's line draws: O if all
        # lines ship after CURRENT_DATE, F if all before, else P
        nlines = _line_count(okey)
        all_f = np.ones(len(okey), dtype=bool)
        all_o = np.ones(len(okey), dtype=bool)
        total = np.zeros(len(okey), dtype=np.int64)
        pcount = table_row_count("part", sf)
        for ln in range(1, 8):
            mask = nlines >= ln
            lnum = np.full(len(okey), ln, dtype=np.int64)
            ship = _line_ship_date(okey, lnum)
            is_f = ship <= CURRENT_DATE
            all_f &= ~mask | is_f
            all_o &= ~mask | ~is_f
            if "o_totalprice" in need:
                lk = _line_key(okey, lnum)
                qty = _randint(605, lk, 1, 50)
                part = _randint(606, lk, 1, pcount)
                eprice = qty * _retail_price_scaled(part)
                disc = _randint(607, lk, 0, 10)
                tax = _randint(608, lk, 0, 8)
                line_total = (eprice * (100 - disc) * (100 + tax)) // 10000
                total += np.where(mask, line_total, 0)
        if "o_orderstatus" in need:
            status_codes = np.where(all_f, 0, np.where(all_o, 1, 2)).astype(np.int32)
            out["o_orderstatus"] = _vocab_col(["F", "O", "P"], status_codes)
        if "o_totalprice" in need:
            out["o_totalprice"] = _dec(total)
    if "o_orderpriority" in need:
        prio = np.asarray(_stream(610, idx) % np.uint64(5), dtype=np.int64)
        out["o_orderpriority"] = _vocab_col(ORDER_PRIORITIES, prio.astype(np.int32))
    if "o_clerk" in need:
        nclerks = max(1, int(1000 * max(sf, 0.001)))
        clerks = _randint(609, idx, 1, nclerks)
        clerk_vocab = [f"Clerk#{k:09d}" for k in range(1, nclerks + 1)]
        out["o_clerk"] = ColumnData(
            T.varchar(), (clerks - 1).astype(np.int32), dictionary=Dictionary(clerk_vocab)
        )
    if "o_shippriority" in need:
        out["o_shippriority"] = ColumnData(T.INTEGER, np.zeros(len(okey), dtype=np.int32))
    if "o_comment" in need:
        out["o_comment"] = _pool_comment_col(_order_comment_pool(), 611, idx)
    return out


def _generate_lineitem(sf: float, order_lo: int, order_hi: int, need) -> Dict[str, ColumnData]:
    okey_per_order = _order_keys(order_lo, order_hi)
    nlines = _line_count(okey_per_order)
    okey = np.repeat(okey_per_order, nlines)
    # linenumber: 1.. within each order (exclusive prefix sum — stays
    # shape-correct for an empty order range, e.g. a no-split device's scan)
    offsets = np.cumsum(nlines) - nlines
    lnum = (np.arange(len(okey)) - np.repeat(offsets, nlines) + 1).astype(np.int64)
    lk = _line_key(okey, lnum)
    out: Dict[str, ColumnData] = {}
    part = None
    if {"l_partkey", "l_suppkey", "l_extendedprice"} & need:
        part = _randint(606, lk, 1, table_row_count("part", sf))
    ship = None
    if {"l_shipdate", "l_receiptdate", "l_linestatus", "l_returnflag"} & need:
        ship = _order_date(okey) + _randint(603, lk, 1, 121)
    if "l_orderkey" in need:
        out["l_orderkey"] = ColumnData(T.BIGINT, okey)
    if "l_partkey" in need:
        out["l_partkey"] = ColumnData(T.BIGINT, part)
    if "l_suppkey" in need:
        # supplier must be one of the part's 4 partsupp suppliers (spec)
        scount = table_row_count("supplier", sf)
        j = _randint(612, lk, 0, 3)
        supp = (part + j * (scount // 4 + (part - 1) // scount)) % scount + 1
        out["l_suppkey"] = ColumnData(T.BIGINT, supp.astype(np.int64))
    if "l_linenumber" in need:
        out["l_linenumber"] = ColumnData(T.INTEGER, lnum.astype(np.int32))
    if {"l_quantity", "l_extendedprice"} & need:
        qty = _randint(605, lk, 1, 50)
        if "l_quantity" in need:
            out["l_quantity"] = _dec(qty * 100)
        if "l_extendedprice" in need:
            out["l_extendedprice"] = _dec(qty * _retail_price_scaled(part))
    if "l_discount" in need:
        out["l_discount"] = _dec(_randint(607, lk, 0, 10))
    if "l_tax" in need:
        out["l_tax"] = _dec(_randint(608, lk, 0, 8))
    if "l_shipdate" in need:
        out["l_shipdate"] = ColumnData(T.DATE, ship.astype(np.int32))
    if "l_commitdate" in need:
        commit = _order_date(okey) + _randint(613, lk, 30, 90)
        out["l_commitdate"] = ColumnData(T.DATE, commit.astype(np.int32))
    if {"l_receiptdate", "l_returnflag"} & need:
        receipt = ship + _randint(614, lk, 1, 30)
        if "l_receiptdate" in need:
            out["l_receiptdate"] = ColumnData(T.DATE, receipt.astype(np.int32))
        if "l_returnflag" in need:
            # returnflag: R or A if receipt <= current date else N
            returned = receipt <= CURRENT_DATE
            ra = np.asarray(_stream(615, lk) % np.uint64(2), dtype=np.int64)  # 0=A 1=R
            codes = np.where(returned, np.where(ra == 1, 2, 0), 1).astype(np.int32)
            out["l_returnflag"] = _vocab_col(["A", "N", "R"], codes)
    if "l_linestatus" in need:
        out["l_linestatus"] = _vocab_col(
            ["F", "O"], np.where(ship <= CURRENT_DATE, 0, 1).astype(np.int32)
        )
    if "l_shipinstruct" in need:
        instr = np.asarray(_stream(616, lk) % np.uint64(4), dtype=np.int64)
        out["l_shipinstruct"] = _vocab_col(SHIP_INSTRUCTIONS, instr.astype(np.int32))
    if "l_shipmode" in need:
        mode = np.asarray(_stream(617, lk) % np.uint64(7), dtype=np.int64)
        out["l_shipmode"] = _vocab_col(SHIP_MODES, mode.astype(np.int32))
    if "l_comment" in need:
        out["l_comment"] = _pool_comment_col(_generic_comment_pool(), 618, lk)
    return out


# --- column statistics (CBO + physical narrowing) ---------------------------
# Storage-repr (min, max) bounds derived from the generation formulas above.
# Table-wide (not per-split), so every split narrows to the same physical
# dtype. Reference: spi/statistics/ColumnStatistics low/high + NDV.

_EPRICE_MAX = 50 * 209900  # max qty * max retailprice (scaled)
_LINE_TOTAL_MAX = (_EPRICE_MAX * 100 * 108) // 10000
_ACCTBAL = (-99999, 999999)


def column_vrange(table: str, column: str, sf: float):
    """Static (min, max) of the column's storage values, or None."""
    n_supp = table_row_count("supplier", sf)
    n_cust = table_row_count("customer", sf)
    n_part = table_row_count("part", sf)
    n_ord = table_row_count("orders", sf)
    ranges = {
        ("region", "r_regionkey"): (0, 4),
        ("nation", "n_nationkey"): (0, 24),
        ("nation", "n_regionkey"): (0, 4),
        ("supplier", "s_suppkey"): (1, n_supp),
        ("supplier", "s_nationkey"): (0, 24),
        ("supplier", "s_acctbal"): _ACCTBAL,
        ("customer", "c_custkey"): (1, n_cust),
        ("customer", "c_nationkey"): (0, 24),
        ("customer", "c_acctbal"): _ACCTBAL,
        ("part", "p_partkey"): (1, n_part),
        ("part", "p_size"): (1, 50),
        ("part", "p_retailprice"): (90000, 209900),
        ("partsupp", "ps_partkey"): (1, n_part),
        ("partsupp", "ps_suppkey"): (1, n_supp),
        ("partsupp", "ps_availqty"): (1, 9999),
        ("partsupp", "ps_supplycost"): (100, 100000),
        ("orders", "o_orderkey"): (1, n_ord),
        ("orders", "o_custkey"): (1, n_cust),
        ("orders", "o_totalprice"): (81000, 7 * _LINE_TOTAL_MAX),
        ("orders", "o_orderdate"): (START_DATE, END_DATE - 151),
        ("orders", "o_shippriority"): (0, 0),
        ("lineitem", "l_orderkey"): (1, n_ord),
        ("lineitem", "l_partkey"): (1, n_part),
        ("lineitem", "l_suppkey"): (1, n_supp),
        ("lineitem", "l_linenumber"): (1, 7),
        ("lineitem", "l_quantity"): (100, 5000),
        ("lineitem", "l_extendedprice"): (90000, _EPRICE_MAX),
        ("lineitem", "l_discount"): (0, 10),
        ("lineitem", "l_tax"): (0, 8),
        ("lineitem", "l_shipdate"): (START_DATE + 1, END_DATE - 151 + 121),
        ("lineitem", "l_commitdate"): (START_DATE + 30, END_DATE - 151 + 90),
        ("lineitem", "l_receiptdate"): (START_DATE + 2, END_DATE - 151 + 151),
    }
    return ranges.get((table, column))


def column_ndv(table: str, column: str, sf: float):
    """Distinct-value estimate, or None when unknown."""
    vr = column_vrange(table, column, sf)
    rows = table_row_count(table, sf)
    # unique keys
    unique = {
        ("region", "r_regionkey"), ("nation", "n_nationkey"),
        ("supplier", "s_suppkey"), ("customer", "c_custkey"),
        ("part", "p_partkey"), ("orders", "o_orderkey"),
    }
    if (table, column) in unique:
        return rows
    if column == "l_orderkey":
        return table_row_count("orders", sf)
    if column == "o_custkey":
        return max(1, (table_row_count("customer", sf) * 2) // 3)
    # bounded-domain columns: min(span, rows)
    if vr is not None:
        return min(vr[1] - vr[0] + 1, rows)
    vocab_sizes = {
        "c_mktsegment": 5, "o_orderpriority": 5, "o_orderstatus": 3,
        "l_returnflag": 3, "l_linestatus": 2, "l_shipinstruct": 4,
        "l_shipmode": 7, "p_brand": 25, "p_mfgr": 5, "p_type": 150,
        "p_container": 40, "n_name": 25, "r_name": 5,
    }
    if column in vocab_sizes:
        return vocab_sizes[column]
    return None
