"""TPC-H connector: schemas tiny/sf1/sf10/... over the stateless generator.

Reference: ``plugin/trino-tpch`` (TpchMetadata.java:99 exposes schemas
tiny/sf1/sf100/... whose scale factor is parsed from the schema name;
TpchSplitManager splits by part ranges). Splits here are row ranges (order
ranges for orders/lineitem), each generated independently.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from trino_tpu import types as T
from trino_tpu.connector import spi
from trino_tpu.connector.tpch import generator as gen

_SCHEMA_SF = {
    "tiny": 0.01,
    "sf1": 1.0,
    "sf10": 10.0,
    "sf100": 100.0,
    "sf300": 300.0,
    "sf1000": 1000.0,
}


def schema_scale_factor(schema: str) -> float:
    if schema in _SCHEMA_SF:
        return _SCHEMA_SF[schema]
    if schema.startswith("sf"):
        return float(schema[2:].replace("_", "."))
    raise KeyError(f"unknown tpch schema: {schema}")


class TpchConnector(spi.Connector):
    name = "tpch"

    def list_schemas(self) -> List[str]:
        return list(_SCHEMA_SF)

    def list_tables(self, schema: str) -> List[str]:
        schema_scale_factor(schema)
        return list(gen.SCHEMAS)

    def get_table(self, schema: str, table: str) -> Optional[spi.TableMetadata]:
        try:
            schema_scale_factor(schema)
        except KeyError:
            return None
        if table not in gen.SCHEMAS:
            return None
        cols = [spi.ColumnMetadata(n, T.parse_type(t)) for n, t in gen.SCHEMAS[table]]
        return spi.TableMetadata(schema, table, cols)

    def table_row_count(self, schema: str, table: str) -> Optional[int]:
        return gen.table_row_count(table, schema_scale_factor(schema))

    _PRIMARY_KEYS = {
        "region": ["r_regionkey"],
        "nation": ["n_nationkey"],
        "supplier": ["s_suppkey"],
        "customer": ["c_custkey"],
        "part": ["p_partkey"],
        "partsupp": ["ps_partkey", "ps_suppkey"],
        "orders": ["o_orderkey"],
        "lineitem": ["l_orderkey", "l_linenumber"],
    }

    def primary_key(self, schema: str, table: str):
        return self._PRIMARY_KEYS.get(table)

    def get_splits(self, schema: str, table: str, target_splits: int) -> List[spi.Split]:
        sf = schema_scale_factor(schema)
        if table == "lineitem":
            n = gen.table_row_count("orders", sf)  # order-range splits
        else:
            n = gen.table_row_count(table, sf)
        target_splits = max(1, min(target_splits, n))
        bounds = [n * i // target_splits for i in range(target_splits + 1)]
        return [
            spi.Split(table, schema, bounds[i], bounds[i + 1])
            for i in range(target_splits)
            if bounds[i] < bounds[i + 1]
        ]

    def scan(self, split: spi.Split, columns: List[str]) -> Dict[str, spi.ColumnData]:
        sf = schema_scale_factor(split.schema)
        data = gen.generate(split.table, sf, split.lo, split.hi, columns)
        return {c: data[c] for c in columns}
