"""TPC-H connector: schemas tiny/sf1/sf10/... over the stateless generator.

Reference: ``plugin/trino-tpch`` (TpchMetadata.java:99 exposes schemas
tiny/sf1/sf100/... whose scale factor is parsed from the schema name;
TpchSplitManager splits by part ranges). Splits here are row ranges (order
ranges for orders/lineitem), each generated independently.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from trino_tpu import types as T
from trino_tpu.connector import spi
from trino_tpu.connector.tpch import generator as gen

_SCHEMA_SF = {
    "tiny": 0.01,
    "sf1": 1.0,
    "sf10": 10.0,
    "sf100": 100.0,
    "sf300": 300.0,
    "sf1000": 1000.0,
}


def schema_scale_factor(schema: str) -> float:
    if schema in _SCHEMA_SF:
        return _SCHEMA_SF[schema]
    if schema.startswith("sf"):
        return float(schema[2:].replace("_", "."))
    raise KeyError(f"unknown tpch schema: {schema}")


class TpchConnector(spi.Connector):
    name = "tpch"

    def list_schemas(self) -> List[str]:
        return list(_SCHEMA_SF)

    def list_tables(self, schema: str) -> List[str]:
        schema_scale_factor(schema)
        return list(gen.SCHEMAS)

    def get_table(self, schema: str, table: str) -> Optional[spi.TableMetadata]:
        try:
            schema_scale_factor(schema)
        except KeyError:
            return None
        if table not in gen.SCHEMAS:
            return None
        cols = [spi.ColumnMetadata(n, T.parse_type(t)) for n, t in gen.SCHEMAS[table]]
        return spi.TableMetadata(schema, table, cols)

    def table_row_count(self, schema: str, table: str) -> Optional[int]:
        return gen.table_row_count(table, schema_scale_factor(schema))

    def column_stats(self, schema: str, table: str, column: str) -> Optional[spi.ColumnStats]:
        sf = schema_scale_factor(schema)
        vr = gen.column_vrange(table, column, sf)
        ndv = gen.column_ndv(table, column, sf)
        if vr is None and ndv is None:
            return None
        low, high = vr if vr is not None else (None, None)
        return spi.ColumnStats(low=low, high=high, ndv=ndv)

    _PRIMARY_KEYS = {
        "region": ["r_regionkey"],
        "nation": ["n_nationkey"],
        "supplier": ["s_suppkey"],
        "customer": ["c_custkey"],
        "part": ["p_partkey"],
        "partsupp": ["ps_partkey", "ps_suppkey"],
        "orders": ["o_orderkey"],
        "lineitem": ["l_orderkey", "l_linenumber"],
    }

    def primary_key(self, schema: str, table: str):
        return self._PRIMARY_KEYS.get(table)

    def data_version(self, schema: str, table: str) -> str:
        # generated data is a pure function of (table, scale factor):
        # immutable per schema, so cached results never go stale
        return "immutable"

    def table_partitioning(self, schema: str, table: str):
        """orders and lineitem are both generated in ORDER-index ranges
        with identical split-boundary arithmetic (get_splits), so they
        co-partition on the order key: split i of one holds exactly the
        orders whose lines are in split i of the other — a join on
        o_orderkey = l_orderkey needs no exchange (reference:
        ConnectorTablePartitioning + ConnectorNodePartitioningProvider,
        the bucketed-table co-located join contract)."""
        family = f"tpch:{schema}:order-range"
        if table == "orders":
            return spi.TablePartitioning(("o_orderkey",), family)
        if table == "lineitem":
            return spi.TablePartitioning(("l_orderkey",), family)
        return None

    # Columns monotone in the generator's row index (key = row + 1; lineitem
    # rows are indexed by ORDER row; partsupp rows are 4 per part). A range
    # or in-set constraint on these maps directly to row-range narrowing —
    # the generator analog of Parquet row-group pruning by min/max stats.
    _MONOTONE = {
        "region": ("r_regionkey", 0, 1),  # (column, key_offset, rows_per_key)
        "nation": ("n_nationkey", 0, 1),
        "supplier": ("s_suppkey", 1, 1),
        "customer": ("c_custkey", 1, 1),
        "part": ("p_partkey", 1, 1),
        "partsupp": ("ps_partkey", 1, 4),
        "orders": ("o_orderkey", 1, 1),
        "lineitem": ("l_orderkey", 1, 1),  # row index = order row
    }

    # in-set domains split into at most this many range runs (split overhead
    # cap, like max-splits-per-request in the reference split managers)
    MAX_PUSHDOWN_RUNS = 256

    def _key_ranges(self, table: str, n: int, constraint) -> List:
        """[(lo, hi)) generator row ranges covered by the constraint's domain
        on the monotone key column; [(0, n)] when nothing applies."""
        if constraint is None or table not in self._MONOTONE:
            return [(0, n)]
        column, off, per_key = self._MONOTONE[table]
        dom = constraint.domain(column)
        if dom.is_all():
            return [(0, n)]

        def key_to_rows(k):
            base = (int(k) - off) * per_key
            return base, base + per_key

        if dom.values is not None:
            import numpy as np

            if dom.values_sorted is not None:
                keys = np.unique(dom.values_sorted).astype(np.int64)
            else:
                keys = np.unique(np.fromiter(
                    (int(v) for v in dom.values
                     if isinstance(v, int) or (isinstance(v, float) and v == int(v))),
                    dtype=np.int64, count=-1))
            if keys.size == 0:
                return []
            # vectorized run building: consecutive keys merge into one run;
            # when runs outnumber the budget, keep only the widest gaps as
            # separators (coalescing the closest neighbors) — all numpy, no
            # per-key python (in-set domains reach millions of keys under
            # phase-1 dynamic filtering)
            brk = np.nonzero(np.diff(keys) > 1)[0]
            run_first = keys[np.concatenate(([0], brk + 1))]
            run_last = keys[np.concatenate((brk, [keys.size - 1]))]
            cap = self.MAX_PUSHDOWN_RUNS
            if run_first.size > cap:
                gaps = run_first[1:] - run_last[:-1]
                sep = np.sort(np.argpartition(gaps, -(cap - 1))[-(cap - 1):])
                run_first = np.concatenate(([run_first[0]], run_first[sep + 1]))
                run_last = np.concatenate((run_last[sep], [run_last[-1]]))
            runs = [
                (key_to_rows(f)[0], key_to_rows(l)[1])
                for f, l in zip(run_first.tolist(), run_last.tolist())
            ]
            return [(max(0, lo), min(n, hi)) for lo, hi in runs if lo < n and hi > 0]
        low, high = dom.value_bounds()
        lo = 0 if low is None else max(0, key_to_rows(low)[0])
        hi = n if high is None else min(n, key_to_rows(high)[1])
        return [(lo, hi)] if lo < hi else []

    def get_splits(
        self, schema: str, table: str, target_splits: int, constraint=None,
        handle=None,
    ) -> List[spi.Split]:
        """Never returns more than ``target_splits`` splits (callers shard
        them 1:1 onto devices/workers). When the constraint's key runs
        outnumber the budget, runs are grouped into contiguous covers and
        ``scan`` re-narrows each cover to the exact runs."""
        sf = schema_scale_factor(schema)
        if table == "lineitem":
            n = gen.table_row_count("orders", sf)  # order-range splits
        else:
            n = gen.table_row_count(table, sf)
        target_splits = max(target_splits, 1)
        ranges = self._key_ranges(table, n, constraint)
        if not ranges:
            return []
        if len(ranges) == 1:
            lo0, hi0 = ranges[0]
            rows = hi0 - lo0
            k = max(1, min(target_splits, rows))
            bounds = [lo0 + rows * i // k for i in range(k + 1)]
            return [
                spi.Split(table, schema, bounds[i], bounds[i + 1])
                for i in range(k)
                if bounds[i] < bounds[i + 1]
            ]
        if len(ranges) > target_splits:
            # group into target_splits covers, balanced by run count
            grouped: List = []
            per = (len(ranges) + target_splits - 1) // target_splits
            for i in range(0, len(ranges), per):
                chunk = ranges[i : i + per]
                grouped.append((chunk[0][0], chunk[-1][1]))
            ranges = grouped
        return [spi.Split(table, schema, lo, hi) for lo, hi in ranges]

    def scan(self, split: spi.Split, columns: List[str], constraint=None) -> Dict[str, spi.ColumnData]:
        sf = schema_scale_factor(split.schema)
        ranges = [
            (max(split.lo, lo), min(split.hi, hi))
            for lo, hi in self._key_ranges(split.table, split.hi, constraint)
        ]
        ranges = [(lo, hi) for lo, hi in ranges if lo < hi]
        parts = [gen.generate(split.table, sf, lo, hi, columns) for lo, hi in ranges]
        # the monotone key column is non-decreasing within every generated
        # range and ranges are enumerated ascending: declare its sort order
        # (reference: ConnectorTableProperties local properties)
        mono = self._MONOTONE.get(split.table)
        if mono and mono[0] in columns:
            for p in parts:
                p[mono[0]].sorted = True
        if len(parts) == 1:
            return {c: parts[0][c] for c in columns}
        if not parts:
            empty = gen.generate(split.table, sf, 0, 0, columns)
            return {c: empty[c] for c in columns}
        # merge part dictionaries where they differ (nation/region name
        # vocabs are range-dependent) — shared helper with the engine
        return {c: spi.concat_column_data([p[c] for p in parts]) for c in columns}
