from trino_tpu.connector.tpch.connector import TpchConnector

__all__ = ["TpchConnector"]
