"""Scan-range generation cache for the stateless table generators.

Reference role: the buffer-pool / page-cache layer under a scan (the
reference reads ORC/Parquet through OS page cache + connector caches, so
re-scanning a table costs IO once). Our generators ARE the storage tier;
without a cache every scan of the same table re-synthesizes it — Q18 reads
lineitem twice (HAVING subquery + main join), TPC-DS q95 reads web_sales
three times. Entries key on (table, sf, lo, hi) and accumulate columns on
demand; the whole cache clears when it exceeds its byte budget (generation
is always correct, the cache is purely a cost optimization).
"""
from __future__ import annotations

from typing import Callable, Dict

import numpy as np

MAX_CACHE_BYTES = 4 << 30
MAX_ENTRY_BYTES = 2 << 30


class GenCache:
    def __init__(self, generate_fn: Callable):
        self._generate = generate_fn
        self._entries: Dict[tuple, dict] = {}
        self._entry_bytes: Dict[tuple, int] = {}
        self._bytes = 0

    @staticmethod
    def _cd_bytes(cd) -> int:
        total = 0
        for a in (cd.values, cd.nulls):
            arr = np.asarray(a) if a is not None else None
            if arr is not None and arr.ndim:
                total += arr.nbytes
        return total

    def generate(self, table: str, sf: float, lo: int, hi: int, columns):
        need = set(columns)
        key = (table, float(sf), int(lo), int(hi))
        ent = self._entries.get(key)
        missing = need - set(ent or ())
        if ent is None or missing:
            fresh = self._generate(table, sf, lo, hi, need if ent is None else missing)
            size = sum(self._cd_bytes(cd) for cd in fresh.values())
            if size > MAX_ENTRY_BYTES:
                out = dict(ent or {})
                out.update(fresh)
                return {c: out[c] for c in columns}
            if self._bytes + size > MAX_CACHE_BYTES:
                # evict everything EXCEPT the entry being filled: its
                # already-cached columns are part of this very result
                keep = self._entries.pop(key, None)
                keep_bytes = self._entry_bytes.pop(key, 0)
                self._entries.clear()
                self._entry_bytes.clear()
                self._bytes = 0
                if keep is not None:
                    self._entries[key] = keep
                    self._entry_bytes[key] = keep_bytes
                    self._bytes = keep_bytes
                ent = keep
            if ent is None:
                ent = {}
                self._entries[key] = ent
            ent.update(fresh)
            self._entry_bytes[key] = self._entry_bytes.get(key, 0) + size
            self._bytes += size
        return {c: ent[c] for c in columns}
