"""Scan-range generation cache for the stateless table generators.

Reference role: the buffer-pool / page-cache layer under a scan (the
reference reads ORC/Parquet through OS page cache + connector caches, so
re-scanning a table costs IO once). Our generators ARE the storage tier;
without a cache every scan of the same table re-synthesizes it — Q18 reads
lineitem twice (HAVING subquery + main join), TPC-DS q95 reads web_sales
three times. Entries key on (table, sf, lo, hi) and accumulate columns on
demand; the cache holds a byte-budgeted LRU — least-recently-scanned
ranges evict individually when the budget is exceeded (generation is
always correct, the cache is purely a cost optimization). Hit/miss/
eviction counters land in the typed metrics registry
(``trino_tpu_gencache_*``, obs/metrics.py).
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict

import numpy as np

MAX_CACHE_BYTES = 4 << 30
MAX_ENTRY_BYTES = 2 << 30


class GenCache:
    def __init__(self, generate_fn: Callable,
                 max_bytes: int = MAX_CACHE_BYTES,
                 max_entry_bytes: int = MAX_ENTRY_BYTES):
        self._generate = generate_fn
        self.max_bytes = max_bytes
        self.max_entry_bytes = max_entry_bytes
        self._entries: "OrderedDict[tuple, dict]" = OrderedDict()
        self._entry_bytes: Dict[tuple, int] = {}
        self._bytes = 0
        # workers scan concurrently; generation runs OUTSIDE the lock (it
        # can take seconds at scale), only map surgery is serialized
        self._lock = threading.Lock()

    @staticmethod
    def _cd_bytes(cd) -> int:
        total = 0
        for a in (cd.values, cd.nulls):
            arr = np.asarray(a) if a is not None else None
            if arr is not None and arr.ndim:
                total += arr.nbytes
        return total

    def cached_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def clear(self) -> None:
        """Drop every cached range (benchmark isolation: a cold-staging
        arm must not be served a previous arm's generated columns)."""
        with self._lock:
            self._entries.clear()
            self._entry_bytes.clear()
            self._bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def _evict_over_budget(self, keep: tuple) -> None:
        """LRU eviction down to the byte budget, never evicting ``keep``
        (its already-cached columns are part of the result being built).
        Caller holds the lock."""
        from trino_tpu.obs import metrics as M

        while self._bytes > self.max_bytes and len(self._entries) > 1:
            key = next(iter(self._entries))
            if key == keep:
                # keep is oldest: rotate it to MRU and evict the next-oldest
                self._entries.move_to_end(key)
                key = next(iter(self._entries))
                if key == keep:
                    break
            self._entries.pop(key)
            self._bytes -= self._entry_bytes.pop(key, 0)
            M.GENCACHE_EVICTIONS.inc()

    def generate(self, table: str, sf: float, lo: int, hi: int, columns):
        from trino_tpu.obs import metrics as M

        need = set(columns)
        key = (table, float(sf), int(lo), int(hi))
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
            missing = need - set(ent or ())
            if ent is not None and not missing:
                M.GENCACHE_HITS.inc()
                return {c: ent[c] for c in columns}
            # snapshot the columns already present: generation happens
            # outside the lock and a concurrent eviction must not lose them
            have = dict(ent or {})
        M.GENCACHE_MISSES.inc()
        fresh = self._generate(table, sf, lo, hi,
                               need if not have else missing)
        size = sum(self._cd_bytes(cd) for cd in fresh.values())
        if size > self.max_entry_bytes:
            # a range bigger than the per-entry cap is served uncached
            out = dict(have)
            out.update(fresh)
            return {c: out[c] for c in columns}
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                ent = dict(have)
                self._entries[key] = ent
                self._entry_bytes[key] = sum(
                    self._cd_bytes(cd) for cd in ent.values())
                self._bytes += self._entry_bytes[key]
            added = {c: cd for c, cd in fresh.items() if c not in ent}
            ent.update(added)
            grow = sum(self._cd_bytes(cd) for cd in added.values())
            self._entry_bytes[key] = self._entry_bytes.get(key, 0) + grow
            self._bytes += grow
            self._entries.move_to_end(key)
            self._evict_over_budget(keep=key)
            out = dict(ent)
        # the pre-lock snapshot + fresh columns always cover the request,
        # even if a concurrent thread evicted and rebuilt the entry
        out = {**have, **out, **fresh}
        return {c: out[c] for c in columns}
