"""TPC-DS connector: schemas tiny/sf1/... over the stateless generator.

Reference: ``plugin/trino-tpcds`` (TpcdsMetadata exposes tiny/sf1/sf100/...
schemas; TpcdsSplitManager splits tables into row ranges). Splits here are
row ranges (order/ticket ranges for the sales/returns fact tables), each
generated independently — the same coordination-free split design as the
tpch connector.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from trino_tpu import types as T
from trino_tpu.connector import spi
from trino_tpu.connector.tpcds import generator as gen
from trino_tpu.connector.tpch.connector import schema_scale_factor


class TpcdsConnector(spi.Connector):
    name = "tpcds"

    def list_schemas(self) -> List[str]:
        return ["tiny", "sf1", "sf10", "sf100"]

    def list_tables(self, schema: str) -> List[str]:
        schema_scale_factor(schema)
        return list(gen.SCHEMAS)

    def get_table(self, schema: str, table: str) -> Optional[spi.TableMetadata]:
        try:
            schema_scale_factor(schema)
        except KeyError:
            return None
        if table not in gen.SCHEMAS:
            return None
        cols = [spi.ColumnMetadata(n, T.parse_type(t)) for n, t in gen.SCHEMAS[table]]
        return spi.TableMetadata(schema, table, cols)

    def table_row_count(self, schema: str, table: str) -> Optional[int]:
        return gen.table_row_count(table, schema_scale_factor(schema))

    def column_stats(self, schema: str, table: str, column: str):
        sf = schema_scale_factor(schema)
        probe = gen.generate(table, sf, 0, 1, [column])
        vr = probe[column].vrange
        if vr is None:
            return None
        return spi.ColumnStats(low=vr[0], high=vr[1])

    _PRIMARY_KEYS = {
        "date_dim": ["d_date_sk"],
        "income_band": ["ib_income_band_sk"],
        "household_demographics": ["hd_demo_sk"],
        "customer_demographics": ["cd_demo_sk"],
        "customer_address": ["ca_address_sk"],
        "customer": ["c_customer_sk"],
        "item": ["i_item_sk"],
        "store": ["s_store_sk"],
        "warehouse": ["w_warehouse_sk"],
        "web_site": ["web_site_sk"],
        "promotion": ["p_promo_sk"],
    }

    def primary_key(self, schema: str, table: str):
        return self._PRIMARY_KEYS.get(table)

    def data_version(self, schema: str, table: str) -> str:
        # generated data is a pure function of (table, scale factor)
        return "immutable"

    def get_splits(
        self, schema: str, table: str, target_splits: int, constraint=None,
        handle=None,
    ) -> List[spi.Split]:
        sf = schema_scale_factor(schema)
        n = gen.order_range_count(table, sf)
        k = max(1, min(max(target_splits, 1), n))
        bounds = [n * i // k for i in range(k + 1)]
        return [
            spi.Split(table, schema, bounds[i], bounds[i + 1])
            for i in range(k)
            if bounds[i] < bounds[i + 1]
        ]

    def scan(self, split: spi.Split, columns: List[str], constraint=None) -> Dict[str, spi.ColumnData]:
        sf = schema_scale_factor(split.schema)
        out = gen.generate(split.table, sf, split.lo, split.hi, columns)
        return {c: out[c] for c in columns}
