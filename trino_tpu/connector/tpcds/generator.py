"""TPC-DS data generator: stateless, vectorized, split-parallel.

Reference: ``plugin/trino-tpcds`` (TpcdsMetadata/TpcdsRecordSetProvider over
the dsdgen-port library) generating TPC-DS data on the fly. Like the tpch
generator, this reproduces the *schema, key relationships, and the value
distributions the benchmark queries select on* with a counter-based PRNG
(splitmix64 over row indices) so any row range generates independently —
coordination-free distributed scans.

Documented deviations from dsdgen (the correctness oracle runs on OUR data,
so tests stay exact): text columns draw from bounded pools;
customer_demographics scales with sf instead of being fixed at 1,920,800
rows (keeps small-scale tests tractable); fact row counts approximate the
spec's sf1 cardinalities via orders x 1..L lines.
"""
from __future__ import annotations

import datetime
from typing import Dict, List, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector.spi import ColumnData
from trino_tpu.connector.tpch.generator import _randint, _stream
from trino_tpu.data.dictionary import Dictionary

_EPOCH = datetime.date(1970, 1, 1)
# d_date_sk is the astronomical Julian day number (the dsdgen convention)
_JULIAN_EPOCH = 2440588  # julian day of 1970-01-01

DATE_LO = (datetime.date(1900, 1, 1) - _EPOCH).days
DATE_HI = (datetime.date(2100, 1, 1) - _EPOCH).days
SALES_DATE_LO = (datetime.date(1998, 1, 2) - _EPOCH).days
SALES_DATE_HI = (datetime.date(2002, 12, 30) - _EPOCH).days

_DEC2 = T.decimal(7, 2)

GENDERS = ["F", "M"]
MARITAL = ["D", "M", "S", "U", "W"]
EDUCATION = [
    "2 yr Degree", "4 yr Degree", "Advanced Degree", "College",
    "Primary", "Secondary", "Unknown",
]
STATES = [
    "AL", "CA", "FL", "GA", "IA", "IL", "IN", "KS", "KY", "LA", "MI",
    "MN", "MO", "NC", "NE", "NY", "OH", "OK", "PA", "SC", "TN", "TX",
    "VA", "WA", "WI",
]
CITIES = [
    "Antioch", "Bethel", "Centerville", "Clifton", "Concord", "Edgewood",
    "Fairview", "Five Points", "Georgetown", "Glendale", "Greenfield",
    "Greenwood", "Hamilton", "Highland", "Jackson", "Lakeside", "Lakeview",
    "Lebanon", "Liberty", "Marion", "Midway", "Mount Olive", "Mount Zion",
    "Newport", "Oak Grove", "Oak Hill", "Oakdale", "Oakland", "Pine Grove",
    "Pleasant Grove", "Pleasant Hill", "Providence", "Riverdale",
    "Riverside", "Salem", "Shady Grove", "Shiloh", "Springdale",
    "Spring Hill", "Sulphur Springs", "Summit", "Sunnyside", "Union",
    "Union Hill", "Walnut Grove", "Waterloo", "Wildwood", "Wilson",
    "Woodland", "Woodville",
]
STREET_NAMES = [
    "1st", "2nd", "3rd", "4th", "5th", "6th", "7th", "8th", "9th", "10th",
    "Adams", "Birch", "Broadway", "Cedar", "Center", "Cherry", "Chestnut",
    "Church", "College", "Davis", "Dogwood", "East", "Elm", "Forest",
    "Fourth", "Franklin", "Green", "Highland", "Hickory", "Hill", "Hillcrest",
    "Jackson", "Jefferson", "Johnson", "Lake", "Laurel", "Lee", "Lincoln",
    "Locust", "Madison", "Main", "Maple", "Meadow", "Mill", "North", "Oak",
    "Park", "Pine", "Poplar", "Railroad", "Ridge", "River", "Second",
    "Smith", "South", "Spring", "Spruce", "Sunset", "Sycamore", "Valley",
    "View", "Walnut", "Washington", "West", "Williams", "Willow", "Wilson",
    "Woodland",
]
COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished",
    "chartreuse", "chiffon", "chocolate", "coral", "cornflower", "cornsilk",
    "cream", "cyan", "dark", "deep", "dim", "dodger", "drab", "firebrick",
    "floral", "forest", "frosted", "gainsboro", "ghost", "goldenrod",
    "green", "grey", "honeydew", "hot", "indian", "ivory", "khaki",
    "lavender", "lawn", "lemon", "light", "lime", "linen", "magenta",
    "maroon", "medium", "metallic", "midnight", "mint", "misty", "moccasin",
    "navajo", "navy", "olive", "orange", "orchid", "pale", "papaya",
    "peach", "peru", "pink", "plum", "powder", "puff", "purple", "red",
    "rose", "rosy", "royal", "saddle", "salmon", "sandy", "seashell",
    "sienna", "sky", "slate", "smoke", "snow", "spring", "steel", "tan",
    "thistle", "tomato", "turquoise", "violet", "wheat", "white", "yellow",
]
CATEGORIES = [
    "Books", "Children", "Electronics", "Home", "Jewelry", "Men", "Music",
    "Shoes", "Sports", "Women",
]
COMPANIES = ["able", "ation", "bar", "cally", "eing", "ese", "ought", "pri"]
BUY_POTENTIAL = ["0-500", "501-1000", "1001-5000", "5001-10000", ">10000", "Unknown"]
PROMO_NAMES = ["able", "anti", "bar", "cally", "ese", "n st", "ought", "pri"]

SCHEMAS: Dict[str, List[Tuple[str, str]]] = {
    "date_dim": [
        ("d_date_sk", "bigint"), ("d_date", "date"), ("d_year", "integer"),
        ("d_moy", "integer"), ("d_dom", "integer"), ("d_qoy", "integer"),
        ("d_dow", "integer"),
    ],
    "income_band": [
        ("ib_income_band_sk", "bigint"), ("ib_lower_bound", "integer"),
        ("ib_upper_bound", "integer"),
    ],
    "household_demographics": [
        ("hd_demo_sk", "bigint"), ("hd_income_band_sk", "bigint"),
        ("hd_buy_potential", "varchar(15)"), ("hd_dep_count", "integer"),
        ("hd_vehicle_count", "integer"),
    ],
    "customer_demographics": [
        ("cd_demo_sk", "bigint"), ("cd_gender", "varchar(1)"),
        ("cd_marital_status", "varchar(1)"),
        ("cd_education_status", "varchar(20)"),
        ("cd_dep_count", "integer"),
    ],
    "customer_address": [
        ("ca_address_sk", "bigint"), ("ca_street_number", "varchar(10)"),
        ("ca_street_name", "varchar(60)"), ("ca_city", "varchar(60)"),
        ("ca_zip", "varchar(10)"), ("ca_state", "varchar(2)"),
    ],
    "customer": [
        ("c_customer_sk", "bigint"), ("c_customer_id", "varchar(16)"),
        ("c_current_cdemo_sk", "bigint"), ("c_current_hdemo_sk", "bigint"),
        ("c_current_addr_sk", "bigint"), ("c_first_sales_date_sk", "bigint"),
        ("c_first_shipto_date_sk", "bigint"), ("c_first_name", "varchar(20)"),
        ("c_last_name", "varchar(30)"),
    ],
    "item": [
        ("i_item_sk", "bigint"), ("i_item_id", "varchar(16)"),
        ("i_product_name", "varchar(50)"), ("i_color", "varchar(20)"),
        ("i_current_price", "decimal(7,2)"), ("i_category", "varchar(50)"),
        ("i_brand_id", "integer"),
    ],
    "store": [
        ("s_store_sk", "bigint"), ("s_store_id", "varchar(16)"),
        ("s_store_name", "varchar(50)"), ("s_zip", "varchar(10)"),
        ("s_state", "varchar(2)"),
    ],
    "warehouse": [
        ("w_warehouse_sk", "bigint"), ("w_warehouse_name", "varchar(20)"),
        ("w_state", "varchar(2)"),
    ],
    "web_site": [
        ("web_site_sk", "bigint"), ("web_site_id", "varchar(16)"),
        ("web_company_name", "varchar(50)"),
    ],
    "promotion": [
        ("p_promo_sk", "bigint"), ("p_promo_id", "varchar(16)"),
        ("p_promo_name", "varchar(50)"), ("p_channel_email", "varchar(1)"),
    ],
    "store_sales": [
        ("ss_sold_date_sk", "bigint"), ("ss_item_sk", "bigint"),
        ("ss_customer_sk", "bigint"), ("ss_cdemo_sk", "bigint"),
        ("ss_hdemo_sk", "bigint"), ("ss_addr_sk", "bigint"),
        ("ss_store_sk", "bigint"), ("ss_promo_sk", "bigint"),
        ("ss_ticket_number", "bigint"), ("ss_quantity", "integer"),
        ("ss_wholesale_cost", "decimal(7,2)"), ("ss_list_price", "decimal(7,2)"),
        ("ss_coupon_amt", "decimal(7,2)"), ("ss_net_profit", "decimal(7,2)"),
    ],
    "store_returns": [
        ("sr_returned_date_sk", "bigint"), ("sr_item_sk", "bigint"),
        ("sr_ticket_number", "bigint"), ("sr_return_amt", "decimal(7,2)"),
    ],
    "catalog_sales": [
        ("cs_sold_date_sk", "bigint"), ("cs_item_sk", "bigint"),
        ("cs_order_number", "bigint"), ("cs_quantity", "integer"),
        ("cs_ext_list_price", "decimal(7,2)"),
    ],
    "catalog_returns": [
        ("cr_returned_date_sk", "bigint"), ("cr_item_sk", "bigint"),
        ("cr_order_number", "bigint"), ("cr_refunded_cash", "decimal(7,2)"),
        ("cr_reversed_charge", "decimal(7,2)"), ("cr_store_credit", "decimal(7,2)"),
    ],
    "web_sales": [
        ("ws_sold_date_sk", "bigint"), ("ws_ship_date_sk", "bigint"),
        ("ws_item_sk", "bigint"), ("ws_order_number", "bigint"),
        ("ws_warehouse_sk", "bigint"), ("ws_ship_addr_sk", "bigint"),
        ("ws_web_site_sk", "bigint"), ("ws_ext_ship_cost", "decimal(7,2)"),
        ("ws_net_profit", "decimal(7,2)"),
    ],
    "web_returns": [
        ("wr_returned_date_sk", "bigint"), ("wr_item_sk", "bigint"),
        ("wr_order_number", "bigint"), ("wr_return_amt", "decimal(7,2)"),
    ],
}

# sf1 cardinalities (facts via orders x lines; spec counts in comments)
_SF1 = {
    "customer": 100_000,
    "customer_address": 50_000,
    "customer_demographics": 192_080,  # deviation: spec fixes 1,920,800
    "item": 18_000,
    "store": 12,
    "warehouse": 5,
    "web_site": 30,
    "promotion": 300,
    "store_sales_tickets": 240_000,   # x ~12 lines = 2.88M (spec 2,880,404)
    "catalog_sales_orders": 160_000,  # x ~9 lines = 1.44M (spec 1,441,548)
    "web_sales_orders": 60_000,       # x ~12 lines = 720K (spec 719,384)
}

_FIXED = {"date_dim": DATE_HI - DATE_LO, "income_band": 20,
          "household_demographics": 7_200}


def _dim_rows(name: str, sf: float) -> int:
    if name in _FIXED:
        return _FIXED[name]
    return max(10, round(_SF1[name] * sf))


def table_row_count(table: str, sf: float) -> int:
    """Row-count estimate (facts report the expected line count)."""
    if table in _FIXED:
        return _FIXED[table]
    if table in ("store_sales", "store_returns"):
        n = _dim_rows("store_sales_tickets", sf) * 12
        return n if table == "store_sales" else n // 10
    if table in ("catalog_sales", "catalog_returns"):
        n = _dim_rows("catalog_sales_orders", sf) * 9
        return n if table == "catalog_sales" else n // 10
    if table in ("web_sales", "web_returns"):
        n = _dim_rows("web_sales_orders", sf) * 12
        return n if table == "web_sales" else n // 4
    return _dim_rows(table, sf)


def order_range_count(table: str, sf: float) -> int:
    """Split-unit count: the generator row index for fact tables is the
    ORDER/TICKET index (lines expand per order), dimension tables the row."""
    if table in ("store_sales", "store_returns"):
        return _dim_rows("store_sales_tickets", sf)
    if table in ("catalog_sales", "catalog_returns"):
        return _dim_rows("catalog_sales_orders", sf)
    if table in ("web_sales", "web_returns"):
        return _dim_rows("web_sales_orders", sf)
    return table_row_count(table, sf)


def _vocab_col(vocab: List[str], codes: np.ndarray) -> ColumnData:
    order = np.argsort(np.asarray(vocab))
    sorted_vocab = [vocab[i] for i in order]
    inverse = np.empty(len(vocab), dtype=np.int32)
    inverse[order] = np.arange(len(vocab), dtype=np.int32)
    return ColumnData(
        T.varchar(), values=inverse[codes.astype(np.int64)],
        dictionary=Dictionary(sorted_vocab),
    )


def _pool(tag: int, idx: np.ndarray, vocab: List[str]) -> ColumnData:
    codes = np.asarray(_stream(tag, idx) % np.uint64(len(vocab)), dtype=np.int32)
    return _vocab_col(vocab, codes)


def _keyed_id(prefix: str, keys: np.ndarray, lo: int, hi: int) -> ColumnData:
    vocab = [f"{prefix}{k:011d}" for k in range(lo, hi)]
    return ColumnData(
        T.varchar(), values=(keys - lo).astype(np.int32),
        dictionary=Dictionary(vocab),
    )


def _dec(values_scaled: np.ndarray) -> ColumnData:
    return ColumnData(_DEC2, values=values_scaled.astype(np.int64),
                      vrange=(0, 100_000_000))


def _key_col(keys: np.ndarray, hi: int) -> ColumnData:
    return ColumnData(T.BIGINT, keys.astype(np.int64), vrange=(1, hi))


def _julian(epoch_days: np.ndarray) -> np.ndarray:
    return epoch_days + _JULIAN_EPOCH


_J_RANGE = (_julian(np.array([DATE_LO]))[0].item(),
            _julian(np.array([DATE_HI]))[0].item())


def generate(table: str, sf: float, lo: int, hi: int, columns=None) -> Dict[str, ColumnData]:
    """Generate rows of ``table`` for order/row range [lo, hi); cached per
    scan range (connector/gencache.py — q95 reads web_sales three times)."""
    need = set(columns) if columns is not None else {n for n, _ in SCHEMAS[table]}
    return _gen_cache.generate(table, sf, lo, hi, need)


def _generate(table: str, sf: float, lo: int, hi: int, need) -> Dict[str, ColumnData]:
    fn = {
        "date_dim": _gen_date_dim, "income_band": _gen_income_band,
        "household_demographics": _gen_hd, "customer_demographics": _gen_cd,
        "customer_address": _gen_ca, "customer": _gen_customer,
        "item": _gen_item, "store": _gen_store, "warehouse": _gen_warehouse,
        "web_site": _gen_web_site, "promotion": _gen_promotion,
        "store_sales": _gen_store_sales, "store_returns": _gen_store_returns,
        "catalog_sales": _gen_catalog_sales, "catalog_returns": _gen_catalog_returns,
        "web_sales": _gen_web_sales, "web_returns": _gen_web_returns,
    }[table]
    out = fn(sf, lo, hi, need)
    return {c: out[c] for c in out if c in need}


from trino_tpu.connector.gencache import GenCache  # noqa: E402

_gen_cache = GenCache(_generate)


def _gen_date_dim(sf, lo, hi, need):
    days = np.arange(DATE_LO + lo, DATE_LO + hi, dtype=np.int64)
    # vectorized calendar decomposition via numpy datetime64
    d64 = days.astype("datetime64[D]")
    y = d64.astype("datetime64[Y]").astype(int) + 1970
    m = d64.astype("datetime64[M]").astype(int) % 12 + 1
    dom = (d64 - d64.astype("datetime64[M]")).astype(int) + 1
    return {
        "d_date_sk": ColumnData(T.BIGINT, _julian(days), vrange=_J_RANGE),
        "d_date": ColumnData(T.DATE, days.astype(np.int32),
                             vrange=(DATE_LO, DATE_HI)),
        "d_year": ColumnData(T.INTEGER, y.astype(np.int32), vrange=(1900, 2100)),
        "d_moy": ColumnData(T.INTEGER, m.astype(np.int32), vrange=(1, 12)),
        "d_dom": ColumnData(T.INTEGER, dom.astype(np.int32), vrange=(1, 31)),
        "d_qoy": ColumnData(T.INTEGER, ((m - 1) // 3 + 1).astype(np.int32),
                            vrange=(1, 4)),
        "d_dow": ColumnData(T.INTEGER, ((days + 4) % 7).astype(np.int32),
                            vrange=(0, 6)),
    }


def _gen_income_band(sf, lo, hi, need):
    k = np.arange(lo + 1, hi + 1, dtype=np.int64)
    return {
        "ib_income_band_sk": _key_col(k, 20),
        "ib_lower_bound": ColumnData(T.INTEGER, ((k - 1) * 10000).astype(np.int32),
                                     vrange=(0, 190000)),
        "ib_upper_bound": ColumnData(T.INTEGER, (k * 10000).astype(np.int32),
                                     vrange=(10000, 200000)),
    }


def _gen_hd(sf, lo, hi, need):
    k = np.arange(lo + 1, hi + 1, dtype=np.int64)
    idx = k.astype(np.uint64)
    return {
        "hd_demo_sk": _key_col(k, _FIXED["household_demographics"]),
        "hd_income_band_sk": ColumnData(
            T.BIGINT, ((k - 1) % 20 + 1).astype(np.int64), vrange=(1, 20)),
        "hd_buy_potential": _pool(3001, idx, BUY_POTENTIAL),
        "hd_dep_count": ColumnData(T.INTEGER, _randint(3002, idx, 0, 9).astype(np.int32),
                                   vrange=(0, 9)),
        "hd_vehicle_count": ColumnData(T.INTEGER, _randint(3003, idx, 0, 4).astype(np.int32),
                                       vrange=(0, 4)),
    }


def _gen_cd(sf, lo, hi, need):
    k = np.arange(lo + 1, hi + 1, dtype=np.int64)
    n = _dim_rows("customer_demographics", sf)
    return {
        "cd_demo_sk": _key_col(k, n),
        "cd_gender": _vocab_col(GENDERS, ((k - 1) % 2).astype(np.int32)),
        "cd_marital_status": _vocab_col(MARITAL, ((k - 1) // 2 % 5).astype(np.int32)),
        "cd_education_status": _vocab_col(
            EDUCATION, ((k - 1) // 10 % 7).astype(np.int32)),
        "cd_dep_count": ColumnData(
            T.INTEGER, ((k - 1) // 70 % 7).astype(np.int32), vrange=(0, 6)),
    }


def _gen_ca(sf, lo, hi, need):
    k = np.arange(lo + 1, hi + 1, dtype=np.int64)
    idx = k.astype(np.uint64)
    nums = _randint(3101, idx, 1, 1000)
    num_vocab = [str(i) for i in range(1, 1001)]
    return {
        "ca_address_sk": _key_col(k, _dim_rows("customer_address", sf)),
        "ca_street_number": _vocab_col(num_vocab, (nums - 1).astype(np.int32)),
        "ca_street_name": _pool(3102, idx, STREET_NAMES),
        "ca_city": _pool(3103, idx, CITIES),
        "ca_zip": _vocab_col(
            [f"{z:05d}" for z in range(10000, 10100)],
            np.asarray(_stream(3104, idx) % np.uint64(100), dtype=np.int32)),
        "ca_state": _pool(3105, idx, STATES),
    }


def _gen_customer(sf, lo, hi, need):
    k = np.arange(lo + 1, hi + 1, dtype=np.int64)
    idx = k.astype(np.uint64)
    n_cd = _dim_rows("customer_demographics", sf)
    n_hd = _FIXED["household_demographics"]
    n_ca = _dim_rows("customer_address", sf)
    first_sales = _randint(3201, idx, SALES_DATE_LO - 2920, SALES_DATE_LO)
    return {
        "c_customer_sk": _key_col(k, _dim_rows("customer", sf)),
        "c_customer_id": _keyed_id("AAAAAAAA", k, lo + 1, hi + 1),
        "c_current_cdemo_sk": ColumnData(
            T.BIGINT, _randint(3202, idx, 1, n_cd), vrange=(1, n_cd)),
        "c_current_hdemo_sk": ColumnData(
            T.BIGINT, _randint(3203, idx, 1, n_hd), vrange=(1, n_hd)),
        "c_current_addr_sk": ColumnData(
            T.BIGINT, _randint(3204, idx, 1, n_ca), vrange=(1, n_ca)),
        "c_first_sales_date_sk": ColumnData(
            T.BIGINT, _julian(first_sales), vrange=_J_RANGE),
        "c_first_shipto_date_sk": ColumnData(
            T.BIGINT, _julian(first_sales + _randint(3205, idx, 1, 60)),
            vrange=_J_RANGE),
        "c_first_name": _pool(3206, idx, STREET_NAMES),
        "c_last_name": _pool(3207, idx, CITIES),
    }


def _gen_item(sf, lo, hi, need):
    k = np.arange(lo + 1, hi + 1, dtype=np.int64)
    idx = k.astype(np.uint64)
    return {
        "i_item_sk": _key_col(k, _dim_rows("item", sf)),
        "i_item_id": _keyed_id("AAAAAAAA", k, lo + 1, hi + 1),
        "i_product_name": _keyed_id("product", k, lo + 1, hi + 1),
        "i_color": _pool(3301, idx, COLORS),
        "i_current_price": _dec(_randint(3302, idx, 100, 10000)),
        "i_category": _pool(3303, idx, CATEGORIES),
        "i_brand_id": ColumnData(
            T.INTEGER, _randint(3304, idx, 1001001, 10016017).astype(np.int32),
            vrange=(1001001, 10016017)),
    }


def _gen_store(sf, lo, hi, need):
    k = np.arange(lo + 1, hi + 1, dtype=np.int64)
    idx = k.astype(np.uint64)
    return {
        "s_store_sk": _key_col(k, _dim_rows("store", sf)),
        "s_store_id": _keyed_id("AAAAAAAA", k, lo + 1, hi + 1),
        "s_store_name": _pool(3401, idx, PROMO_NAMES),
        "s_zip": _vocab_col(
            [f"{z:05d}" for z in range(10000, 10100)],
            np.asarray(_stream(3402, idx) % np.uint64(100), dtype=np.int32)),
        "s_state": _pool(3403, idx, STATES),
    }


def _gen_warehouse(sf, lo, hi, need):
    k = np.arange(lo + 1, hi + 1, dtype=np.int64)
    idx = k.astype(np.uint64)
    return {
        "w_warehouse_sk": _key_col(k, _dim_rows("warehouse", sf)),
        "w_warehouse_name": _pool(3501, idx, CITIES),
        "w_state": _pool(3502, idx, STATES),
    }


def _gen_web_site(sf, lo, hi, need):
    k = np.arange(lo + 1, hi + 1, dtype=np.int64)
    return {
        "web_site_sk": _key_col(k, _dim_rows("web_site", sf)),
        "web_site_id": _keyed_id("AAAAAAAA", k, lo + 1, hi + 1),
        "web_company_name": _vocab_col(
            COMPANIES, ((k - 1) % len(COMPANIES)).astype(np.int32)),
    }


def _gen_promotion(sf, lo, hi, need):
    k = np.arange(lo + 1, hi + 1, dtype=np.int64)
    idx = k.astype(np.uint64)
    return {
        "p_promo_sk": _key_col(k, _dim_rows("promotion", sf)),
        "p_promo_id": _keyed_id("AAAAAAAA", k, lo + 1, hi + 1),
        "p_promo_name": _pool(3601, idx, PROMO_NAMES),
        "p_channel_email": _vocab_col(["N", "Y"], ((k - 1) % 2).astype(np.int32)),
    }


# --- fact tables: order/ticket index -> 1..L lines -------------------------


def _cols(need, **makers) -> Dict[str, ColumnData]:
    """Evaluate only the requested columns (connector projection pushdown:
    the lambda per column defers its PRNG draws — the tpch generator's
    `if col in need:` pattern, in combinator form)."""
    return {k: f() for k, f in makers.items() if k in need}


def _lines(tag: int, order: np.ndarray, max_lines: int) -> np.ndarray:
    return 1 + np.asarray(
        _stream(tag, order.astype(np.uint64)) % np.uint64(max_lines),
        dtype=np.int64,
    )


def _expand_orders(tag: int, lo: int, hi: int, max_lines: int):
    """(order_key[n_lines], line_number[n_lines]) for order range [lo, hi)."""
    okey = np.arange(lo + 1, hi + 1, dtype=np.int64)
    nlines = _lines(tag, okey, max_lines)
    orders = np.repeat(okey, nlines)
    offsets = np.concatenate([[0], np.cumsum(nlines)[:-1]])
    lnum = (np.arange(len(orders)) - np.repeat(offsets, nlines) + 1).astype(np.int64)
    return orders, lnum


def _line_key(order: np.ndarray, lnum: np.ndarray, salt: int) -> np.ndarray:
    with np.errstate(over="ignore"):
        return (order.astype(np.uint64) * np.uint64(32)
                + lnum.astype(np.uint64) + np.uint64(salt))


def _gen_store_sales(sf, lo, hi, need):
    ticket, lnum = _expand_orders(4001, lo, hi, 23)
    lk = _line_key(ticket, lnum, 0)
    tidx = ticket.astype(np.uint64)
    n_item = _dim_rows("item", sf)
    return _cols(
        need,
        ss_sold_date_sk=lambda: ColumnData(
            T.BIGINT, _julian(_randint(4002, tidx, SALES_DATE_LO, SALES_DATE_HI)),
            vrange=_J_RANGE),
        ss_item_sk=lambda: ColumnData(
            T.BIGINT, _randint(4003, lk, 1, n_item), vrange=(1, n_item)),
        ss_customer_sk=lambda: ColumnData(
            T.BIGINT, _randint(4004, tidx, 1, _dim_rows("customer", sf)),
            vrange=(1, _dim_rows("customer", sf))),
        ss_cdemo_sk=lambda: ColumnData(
            T.BIGINT, _randint(4007, tidx, 1, _dim_rows("customer_demographics", sf)),
            vrange=(1, _dim_rows("customer_demographics", sf))),
        ss_hdemo_sk=lambda: ColumnData(
            T.BIGINT, _randint(4008, tidx, 1, _FIXED["household_demographics"]),
            vrange=(1, _FIXED["household_demographics"])),
        ss_addr_sk=lambda: ColumnData(
            T.BIGINT, _randint(4009, tidx, 1, _dim_rows("customer_address", sf)),
            vrange=(1, _dim_rows("customer_address", sf))),
        ss_store_sk=lambda: ColumnData(
            T.BIGINT, _randint(4010, tidx, 1, _dim_rows("store", sf)),
            vrange=(1, _dim_rows("store", sf))),
        ss_promo_sk=lambda: ColumnData(
            T.BIGINT, _randint(4011, lk, 1, _dim_rows("promotion", sf)),
            vrange=(1, _dim_rows("promotion", sf))),
        ss_ticket_number=lambda: ColumnData(
            T.BIGINT, ticket, vrange=(1, order_range_count("store_sales", sf))),
        ss_quantity=lambda: ColumnData(
            T.INTEGER, _randint(4006, lk, 1, 100).astype(np.int32), vrange=(1, 100)),
        ss_wholesale_cost=lambda: _dec(_randint(4005, lk, 100, 10000)),
        ss_list_price=lambda: _dec(
            _randint(4005, lk, 100, 10000) + _randint(4012, lk, 10, 5000)),
        ss_coupon_amt=lambda: _dec(np.where(
            _stream(4013, lk) % np.uint64(5) == 0,
            _randint(4014, lk, 10, 2000), 0)),
        ss_net_profit=lambda: _dec(_randint(4015, lk, 0, 3000)),
    )


_RETURN_MOD = 10  # ~1 in 10 sales lines is returned


def _gen_store_returns(sf, lo, hi, need):
    ticket, lnum = _expand_orders(4001, lo, hi, 23)  # same draws as sales
    lk = _line_key(ticket, lnum, 0)
    returned = _stream(4101, lk) % np.uint64(_RETURN_MOD) == 0
    ticket, lnum, lk = ticket[returned], lnum[returned], lk[returned]
    n_item = _dim_rows("item", sf)
    return _cols(
        need,
        sr_returned_date_sk=lambda: ColumnData(
            T.BIGINT,
            _julian(_randint(4002, ticket.astype(np.uint64),
                             SALES_DATE_LO, SALES_DATE_HI)
                    + _randint(4102, lk, 1, 90)),
            vrange=_J_RANGE),
        sr_item_sk=lambda: ColumnData(
            T.BIGINT, _randint(4003, lk, 1, n_item), vrange=(1, n_item)),
        sr_ticket_number=lambda: ColumnData(
            T.BIGINT, ticket, vrange=(1, order_range_count("store_returns", sf))),
        sr_return_amt=lambda: _dec(_randint(4103, lk, 100, 10000)),
    )


def _gen_catalog_sales(sf, lo, hi, need):
    order, lnum = _expand_orders(4201, lo, hi, 17)
    lk = _line_key(order, lnum, 1)
    n_item = _dim_rows("item", sf)
    return _cols(
        need,
        cs_sold_date_sk=lambda: ColumnData(
            T.BIGINT,
            _julian(_randint(4202, order.astype(np.uint64),
                             SALES_DATE_LO, SALES_DATE_HI)),
            vrange=_J_RANGE),
        cs_item_sk=lambda: ColumnData(
            T.BIGINT, _randint(4203, lk, 1, n_item), vrange=(1, n_item)),
        cs_order_number=lambda: ColumnData(
            T.BIGINT, order, vrange=(1, order_range_count("catalog_sales", sf))),
        cs_quantity=lambda: ColumnData(
            T.INTEGER, _randint(4204, lk, 1, 100).astype(np.int32), vrange=(1, 100)),
        cs_ext_list_price=lambda: _dec(_randint(4205, lk, 100, 30000)),
    )


def _gen_catalog_returns(sf, lo, hi, need):
    order, lnum = _expand_orders(4201, lo, hi, 17)
    lk = _line_key(order, lnum, 1)
    returned = _stream(4301, lk) % np.uint64(_RETURN_MOD) == 0
    order, lnum, lk = order[returned], lnum[returned], lk[returned]
    n_item = _dim_rows("item", sf)
    return _cols(
        need,
        cr_returned_date_sk=lambda: ColumnData(
            T.BIGINT,
            _julian(_randint(4202, order.astype(np.uint64),
                             SALES_DATE_LO, SALES_DATE_HI)
                    + _randint(4302, lk, 1, 90)),
            vrange=_J_RANGE),
        cr_item_sk=lambda: ColumnData(
            T.BIGINT, _randint(4203, lk, 1, n_item), vrange=(1, n_item)),
        cr_order_number=lambda: ColumnData(
            T.BIGINT, order, vrange=(1, order_range_count("catalog_returns", sf))),
        cr_refunded_cash=lambda: _dec(_randint(4303, lk, 0, 8000)),
        cr_reversed_charge=lambda: _dec(_randint(4304, lk, 0, 4000)),
        cr_store_credit=lambda: _dec(_randint(4305, lk, 0, 4000)),
    )


def _gen_web_sales(sf, lo, hi, need):
    order, lnum = _expand_orders(4401, lo, hi, 23)
    lk = _line_key(order, lnum, 2)
    oidx = order.astype(np.uint64)
    n_item = _dim_rows("item", sf)
    n_wh = _dim_rows("warehouse", sf)

    def _sold():
        return _randint(4402, oidx, SALES_DATE_LO, SALES_DATE_HI)

    return _cols(
        need,
        ws_sold_date_sk=lambda: ColumnData(
            T.BIGINT, _julian(_sold()), vrange=_J_RANGE),
        ws_ship_date_sk=lambda: ColumnData(
            T.BIGINT, _julian(_sold() + _randint(4403, lk, 1, 120)),
            vrange=_J_RANGE),
        ws_item_sk=lambda: ColumnData(
            T.BIGINT, _randint(4404, lk, 1, n_item), vrange=(1, n_item)),
        ws_order_number=lambda: ColumnData(
            T.BIGINT, order, vrange=(1, order_range_count("web_sales", sf))),
        # per-LINE warehouse: orders spanning warehouses feed q95's ws_wh
        ws_warehouse_sk=lambda: ColumnData(
            T.BIGINT, _randint(4405, lk, 1, n_wh), vrange=(1, n_wh)),
        ws_ship_addr_sk=lambda: ColumnData(
            T.BIGINT, _randint(4406, oidx, 1, _dim_rows("customer_address", sf)),
            vrange=(1, _dim_rows("customer_address", sf))),
        ws_web_site_sk=lambda: ColumnData(
            T.BIGINT, _randint(4407, oidx, 1, _dim_rows("web_site", sf)),
            vrange=(1, _dim_rows("web_site", sf))),
        ws_ext_ship_cost=lambda: _dec(_randint(4408, lk, 0, 10000)),
        ws_net_profit=lambda: _dec(_randint(4409, lk, 0, 20000)),
    )


def _gen_web_returns(sf, lo, hi, need):
    order, lnum = _expand_orders(4401, lo, hi, 23)
    lk = _line_key(order, lnum, 2)
    returned = _stream(4501, lk) % np.uint64(4) == 0  # ~25%
    order, lnum, lk = order[returned], lnum[returned], lk[returned]
    n_item = _dim_rows("item", sf)
    return _cols(
        need,
        wr_returned_date_sk=lambda: ColumnData(
            T.BIGINT,
            _julian(_randint(4402, order.astype(np.uint64),
                             SALES_DATE_LO, SALES_DATE_HI)
                    + _randint(4502, lk, 1, 120)),
            vrange=_J_RANGE),
        wr_item_sk=lambda: ColumnData(
            T.BIGINT, _randint(4404, lk, 1, n_item), vrange=(1, n_item)),
        wr_order_number=lambda: ColumnData(
            T.BIGINT, order, vrange=(1, order_range_count("web_returns", sf))),
        wr_return_amt=lambda: _dec(_randint(4503, lk, 100, 10000)),
    )
