from trino_tpu.connector.tpcds.connector import TpcdsConnector  # noqa: F401
