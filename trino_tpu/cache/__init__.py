"""Query caching subsystem.

Reference role: the coordinator-side caching stack Trino itself lacks
in-core (SURVEY §1 — every repeated dashboard query pays parse/plan/
schedule/execute again) but that fronting systems bolt on. Three layers,
all keyed off the same canonical-plan machinery:

- ``plan_key``     — deterministic fingerprints of optimized plan trees
  (node kinds, channels, literals, table identities, connector data
  versions), with plan-node ids canonicalized so two plantings of the
  same SQL fingerprint identically;
- ``determinism``  — the analysis pass that marks a statement uncachable
  (non-deterministic functions, table functions, non-SELECT statements);
- ``result_cache`` — the coordinator's byte-budgeted LRU of final result
  pages with TTL + single-flight de-duplication, the logical-plan cache,
  and the ``QueryCache`` facade the coordinator wires in.

Invalidation is version-based, never notification-based: connectors
expose a cheap per-table ``data_version()`` token (connector/spi.py) that
is captured into the cache key at plan time, so any mutation changes the
key and stale entries miss naturally (then age out via TTL/LRU).
"""
from trino_tpu.cache.determinism import uncachable_reason
from trino_tpu.cache.plan_key import canonicalize_plan, plan_fingerprint
from trino_tpu.cache.result_cache import (
    PlanCache, QueryCache, ResultCache)

__all__ = [
    "canonicalize_plan",
    "plan_fingerprint",
    "uncachable_reason",
    "PlanCache",
    "QueryCache",
    "ResultCache",
]
