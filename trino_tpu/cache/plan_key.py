"""Canonical plan keys: deterministic fingerprints of optimized plans.

Reference role: the cache keys of plan-/result-caching front-ends
(canonical SQL is too weak — ``select 1+1`` and ``SELECT 2`` should
collide, session catalog/schema must distinguish ``orders`` from
``tpch.sf1.orders`` — and too strong — comments and whitespace should
not split entries). Fingerprinting the OPTIMIZED plan tree solves both:
names are resolved, constants folded, and pushed-down handles and
constraints participate in the key.

Plan-node ids are process-global counters (sql/planner/plan.py
``_next_plan_id``), so two plantings of identical SQL produce structurally
identical trees with different ids. Canonicalization maps every id to its
pre-order ordinal before serialization — including the join-node ids that
``TableScanNode.dynamic_filters`` references — so the fingerprint depends
only on plan STRUCTURE.

Connector data versions ride into the fingerprint (``plan_fingerprint``'s
``versions``), which is the whole invalidation story: a table mutation
bumps its version, the next identical query fingerprints differently, and
the stale entry is never consulted again (TTL/LRU reclaims it).
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Iterable, List, Optional, Sequence, Tuple

from trino_tpu.sql import ir
from trino_tpu.sql.planner import plan as P


def canonicalize_plan(root: P.PlanNode) -> str:
    """Deterministic text form of a plan tree, independent of plan-node
    id allocation. Same SQL planned twice -> same string."""
    ordinal = {}
    for i, node in enumerate(P.walk_plan(root)):
        # a DAG-shaped plan (shared subtree) keeps the FIRST ordinal, so
        # repeated visits serialize consistently
        ordinal.setdefault(node.id, i)
    out: List[str] = []
    _serialize_node(root, ordinal, out)
    return "".join(out)


def _serialize_node(node: P.PlanNode, ordinal: dict, out: List[str]) -> None:
    out.append(f"{type(node).__name__}#{ordinal[node.id]}(")
    for f in dataclasses.fields(node):
        if f.name == "id":
            continue
        v = getattr(node, f.name)
        if f.name == "dynamic_filters" and v:
            # entries are (join_node_id, key_index, column_name): the join
            # id is a raw plan-node id and must canonicalize like the rest
            v = [(ordinal.get(jid, -1), ki, col) for jid, ki, col in v]
        out.append(f"{f.name}=")
        _serialize_value(v, ordinal, out)
        out.append(",")
    out.append(")")


def _serialize_value(v, ordinal: dict, out: List[str]) -> None:
    if isinstance(v, P.PlanNode):
        _serialize_node(v, ordinal, out)
    elif isinstance(v, ir.Expr):
        # ir reprs are deterministic (channel indices + literal values)
        out.append(repr(v))
    elif isinstance(v, (list, tuple)):
        out.append("[")
        for x in v:
            _serialize_value(x, ordinal, out)
            out.append(",")
        out.append("]")
    elif isinstance(v, (str, int, float, bool)) or v is None:
        out.append(repr(v))
    else:
        # types, TupleDomain constraints, pushdown handles, AST fragments
        # (MATCH_RECOGNIZE defines/measures): dataclass reprs, determined
        # by construction, not by identity
        out.append(repr(v))


def plan_fingerprint(
    root: P.PlanNode,
    versions: Optional[Iterable[Tuple[Tuple[str, str, str], str]]] = None,
    extra: Sequence[str] = (),
) -> str:
    """sha256 over the canonical plan + captured connector data versions
    (+ any extra discriminators, e.g. result-affecting session values)."""
    return fingerprint_from_canonical(canonicalize_plan(root), versions,
                                      extra)


def fingerprint_from_canonical(
    canonical: str,
    versions: Optional[Iterable[Tuple[Tuple[str, str, str], str]]] = None,
    extra: Sequence[str] = (),
) -> str:
    """``plan_fingerprint`` over an already-canonicalized plan string.
    The prepared-EXECUTE hot path canonicalizes its parameterized plan
    ONCE (the bindings ride in ``extra``) instead of re-serializing the
    bound plan on every request."""
    h = hashlib.sha256()
    h.update(canonical.encode())
    for (catalog, schema, table), version in sorted(versions or ()):
        h.update(f"|{catalog}.{schema}.{table}@{version}".encode())
    for x in extra:
        h.update(f"|{x}".encode())
    return h.hexdigest()


def scanned_tables(root: P.PlanNode) -> List[Tuple[str, str, str]]:
    """Distinct (catalog, schema, table) identities the plan scans."""
    seen = []
    for node in P.walk_plan(root):
        if isinstance(node, P.TableScanNode):
            key = (node.catalog, node.schema, node.table)
            if key not in seen:
                seen.append(key)
    return seen


def capture_versions(session, root: P.PlanNode):
    """Current connector data version per scanned table, or None when any
    scanned table is unversioned (its connector returned None) — an
    unversioned table cannot be invalidated, so its queries must bypass."""
    versions = []
    for catalog, schema, table in scanned_tables(root):
        conn = session.catalogs.get(catalog)
        v = conn.data_version(schema, table) if conn is not None else None
        if v is None:
            return None
        versions.append(((catalog, schema, table), str(v)))
    return versions
