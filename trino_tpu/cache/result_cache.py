"""Coordinator result cache + logical-plan cache.

Reference role: the result/plan caches fronting systems put before Trino
(and the reference's own ``CachingTableStatsProvider`` /
``NonEvictableCache`` idioms for plan-time metadata). Two stores:

- ``ResultCache`` — final result pages (column names + Python rows) in a
  byte-budgeted LRU with per-entry TTL and SINGLE-FLIGHT de-duplication:
  the first query on a key executes (the leader), concurrent identical
  queries park on the flight and are served the leader's result as HITs —
  one execution, N answers (the role of request coalescing in any serving
  cache; reference analog: QueuedStatementResource de-duplicates nothing,
  which is exactly the tax this removes).
- ``PlanCache`` — optimized logical plans keyed by canonical SQL +
  session-property signature, validated against connector data versions
  at lookup (a stale plan may bake dropped tables or dead statistics).

Admission: entries above ``max_bytes / 4`` are never admitted (one giant
result must not wipe the working set); DML/DDL and uncachable plans never
reach ``begin`` at all (coordinator bypasses first).

Both stores are process-wide and thread-safe: every query thread on the
coordinator races through them.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, List, Optional, Tuple

from trino_tpu.obs import metrics as M

DEFAULT_RESULT_CACHE_BYTES = 64 << 20
DEFAULT_TTL_MS = 60_000


def estimate_result_bytes(columns: List[str], rows: List[tuple]) -> int:
    """Cheap size estimate of a materialized result (sampled: results can
    be millions of rows and admission must not cost a full scan)."""
    base = 256 + sum(len(c) + 49 for c in columns)
    n = len(rows)
    if n == 0:
        return base
    sample = rows[:: max(1, n // 200)][:200]
    per_row = sum(
        64 + sum(_value_bytes(v) for v in row) for row in sample
    ) / len(sample)
    return base + int(per_row * n)


def _value_bytes(v) -> int:
    if v is None:
        return 16
    if isinstance(v, bool):
        return 24
    if isinstance(v, int):
        return 28
    if isinstance(v, float):
        return 24
    if isinstance(v, (str, bytes)):
        return 49 + len(v)
    return 64  # dates, decimals, nested values


def session_user(session) -> str:
    """The session's authenticated principal (cache-key partition: plan
    and result reuse across users would bypass per-table access control,
    which is enforced at plan time)."""
    return getattr(getattr(session, "identity", None), "user", "") or ""


def _current_group() -> Optional[str]:
    """Resource group of the query on THIS thread (dispatcher lane sets
    it around execution), or None. Lazy + fail-open so the cache stays
    importable and functional without the server package."""
    try:
        from trino_tpu.server.resource_groups import current_group

        return current_group()
    except Exception:  # noqa: BLE001 — attribution never fails caching
        return None


class _Flight:
    """One in-progress computation of a cache key (single-flight)."""

    def __init__(self):
        self._event = threading.Event()
        self.value: Optional[Tuple[List[str], List[tuple]]] = None
        self.ok = False

    def wait(self, timeout: Optional[float]) -> bool:
        return self._event.wait(timeout)

    def _resolve(self, value, ok: bool) -> None:
        self.value = value
        self.ok = ok
        self._event.set()


class ResultCache:
    """Byte-budgeted LRU of final result pages with TTL + single-flight."""

    def __init__(self, max_bytes: int = DEFAULT_RESULT_CACHE_BYTES):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        # key -> (columns, rows, bytes, expires_at monotonic, group)
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        self._bytes = 0
        self._flights: dict = {}
        # resident bytes per resource group (None = ungrouped) — the
        # carve-out ground truth for over-share eviction preference
        self._group_bytes: dict = {}

    # ------------------------------------------------------------ inspection
    def cached_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def group_bytes(self) -> dict:
        """Resident bytes per owning resource group (None = ungrouped)."""
        with self._lock:
            return dict(self._group_bytes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def _group_sub_locked(self, group, nbytes: int) -> None:
        remaining = self._group_bytes.get(group, 0) - nbytes
        if remaining > 0:
            self._group_bytes[group] = remaining
        else:
            self._group_bytes.pop(group, None)

    def _victim_key_locked(self, exclude=None):
        """Eviction victim: the oldest entry of a group over its
        configured cache share first (one tenant's burst reclaims its own
        over-share bytes before touching another's warm results), else
        the LRU head."""
        try:
            from trino_tpu.server.resource_groups import CACHE_SHARES

            for k, ent in self._entries.items():  # LRU order
                if k == exclude:
                    continue
                group = ent[4]
                if CACHE_SHARES.over_share(
                        group, self._group_bytes.get(group, 0),
                        self.max_bytes):
                    return k
        except Exception:  # noqa: BLE001 — carve-outs never wedge eviction
            pass
        return next(iter(self._entries))

    # ------------------------------------------------------------- lifecycle
    def begin(self, key: str):
        """One atomic admission step. Returns
        ``("hit", (columns, rows))`` — a live entry was found;
        ``("wait", flight)``        — another query is computing this key;
        ``("lead", None)``          — caller must execute, then call
        ``complete`` (success) or ``abandon`` (failure)."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                columns, rows, nbytes, expires_at, group = ent
                if time.monotonic() < expires_at:
                    self._entries.move_to_end(key)
                    return "hit", (columns, rows)
                del self._entries[key]
                self._bytes -= nbytes
                self._group_sub_locked(group, nbytes)
                M.RESULT_CACHE_BYTES.set(self._bytes)
            flight = self._flights.get(key)
            if flight is not None:
                return "wait", flight
            self._flights[key] = _Flight()
            return "lead", None

    def complete(self, key: str, columns: List[str], rows: List[tuple],
                 ttl_ms: int, max_bytes: Optional[int] = None) -> None:
        """Leader publishes its result: waiters wake with the value, and
        the entry is admitted (budget and per-entry cap permitting).
        ``max_bytes`` is the session's admission budget for THIS entry —
        it tightens the per-entry cap but never resizes the shared
        server-wide cache (one tenant must not flush the others)."""
        value = (columns, rows)
        nbytes = estimate_result_bytes(columns, rows)
        group = _current_group()
        with self._lock:
            flight = self._flights.pop(key, None)
            budget = (self.max_bytes if max_bytes is None
                      else min(self.max_bytes, max_bytes))
            if nbytes <= budget // 4:
                old = self._entries.pop(key, None)
                if old is not None:
                    self._bytes -= old[2]
                    self._group_sub_locked(old[4], old[2])
                self._entries[key] = (
                    columns, rows, nbytes,
                    time.monotonic() + ttl_ms / 1e3, group)
                self._bytes += nbytes
                self._group_bytes[group] = (
                    self._group_bytes.get(group, 0) + nbytes)
                while self._bytes > self.max_bytes and len(self._entries) > 1:
                    vk = self._victim_key_locked(exclude=key)
                    _c, _r, b, _e, g = self._entries.pop(vk)
                    self._bytes -= b
                    self._group_sub_locked(g, b)
                    M.RESULT_CACHE_EVICTIONS.inc()
                M.RESULT_CACHE_BYTES.set(self._bytes)
        if flight is not None:
            flight._resolve(value, ok=True)

    def peek(self, key: str):
        """Read-only lookup: ``(columns, rows)`` for a live entry, else
        None. Never starts a flight — the dispatch plane's serving index
        (server/dispatch.py) consults this on the HTTP thread, where
        leading (and later having to abandon) a flight would be wrong."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return None
            columns, rows, nbytes, expires_at, group = ent
            if time.monotonic() >= expires_at:
                del self._entries[key]
                self._bytes -= nbytes
                self._group_sub_locked(group, nbytes)
                M.RESULT_CACHE_BYTES.set(self._bytes)
                return None
            self._entries.move_to_end(key)
            return columns, rows

    def abandon(self, key: str) -> None:
        """Leader failed: wake waiters empty-handed (they re-execute)."""
        with self._lock:
            flight = self._flights.pop(key, None)
        if flight is not None:
            flight._resolve(None, ok=False)

    def invalidate_all(self) -> None:
        with self._lock:
            self._entries.clear()
            self._group_bytes.clear()
            self._bytes = 0
            M.RESULT_CACHE_BYTES.set(0)


class PlanCache:
    """Optimized-plan LRU keyed by canonical SQL + session-property
    signature, revalidated against connector data versions per lookup."""

    def __init__(self, max_entries: int = 256):
        self.max_entries = max_entries
        self._lock = threading.Lock()
        # key -> (root, [((catalog, schema, table), version), ...] | None)
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()

    @staticmethod
    def key_for(session, sql: str) -> tuple:
        props = tuple(sorted(
            (k, str(v)) for k, v in session.properties.items()))
        # SQL routines inline at plan time (sql/routines.py expand_udfs):
        # a CREATE OR REPLACE FUNCTION must not resurrect a plan holding
        # the old body, so the routine store participates in the key
        udfs = getattr(session, "udfs", None) or {}
        udf_sig = tuple(sorted((name, repr(d)) for name, d in udfs.items()))
        # access control fires inside Planner.plan (check_can_select):
        # reusing another principal's plan would skip it, so the cache is
        # partitioned per user (reference: per-identity cache keying)
        return (sql.strip(), session_user(session), props, udf_sig)

    def get(self, session, sql: str):
        """``(root, current_versions)`` for a still-valid entry, or None.
        A version mismatch (or an unversioned scanned table) invalidates
        the entry in place. The freshly captured versions are returned so
        the caller's result-cache lookup doesn't re-stat every table."""
        from trino_tpu.cache.plan_key import capture_versions

        key = self.key_for(session, sql)
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return None
            self._entries.move_to_end(key)
        root, versions = ent
        current = capture_versions(session, root)
        if current != versions:
            with self._lock:
                self._entries.pop(key, None)
            return None
        return root, current

    def put(self, session, sql: str, root, versions=None) -> None:
        """``versions``: the capture the caller already did at plan time
        (avoids a duplicate per-table data_version pass); computed here
        when omitted."""
        from trino_tpu.cache.plan_key import capture_versions

        if versions is None:
            versions = capture_versions(session, root)
        if versions is None:
            return  # unversioned tables can't be revalidated: never cache
        key = self.key_for(session, sql)
        with self._lock:
            self._entries[key] = (root, versions)
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def invalidate_all(self) -> None:
        with self._lock:
            self._entries.clear()


class QueryCache:
    """The coordinator's cache facade: one logical-plan cache + one result
    cache, shared by every query the server runs."""

    def __init__(self, result_max_bytes: int = DEFAULT_RESULT_CACHE_BYTES,
                 plan_max_entries: int = 256):
        self.plans = PlanCache(plan_max_entries)
        self.results = ResultCache(result_max_bytes)
