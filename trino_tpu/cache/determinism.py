"""Determinism analysis: is a statement's result a pure function of its
inputs' data versions?

Reference: the reference engine tags every scalar function with
``@ScalarFunction(deterministic = ...)`` and plans consult
``isDeterministic`` before reusing expressions; here the same judgment
gates the result cache. A query is UNCACHABLE when it references:

- non-deterministic scalar functions (``random()``, ``now()``,
  ``current_timestamp``, ...) — their value varies per evaluation or per
  query, so a cached result would freeze them;
- table functions — they materialize rows AT PLAN TIME
  (planner._plan_table_function folds them into a ValuesNode), so the
  plan fingerprint cannot distinguish a re-invocation;
- anything that is not a plain SELECT (DML/DDL/session control bypass
  long before this pass runs).

The walk covers BOTH representations: the parsed AST (catches calls that
constant-fold away before the optimized plan — and table-function
invocations, which leave no plan node behind) and the optimized IR plan
(catches calls introduced by expansion, e.g. SQL routines whose bodies
mention ``random()``).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from trino_tpu.sql import ir
from trino_tpu.sql.parser import ast
from trino_tpu.sql.planner import plan as P

# canonical IR names AND surface spellings (the analyzer maps surface ->
# canonical, e.g. rand -> random; both sides appear here so the AST walk
# and the IR walk share one set)
NONDETERMINISTIC_FUNCTIONS = frozenset({
    "random", "rand", "now", "current_timestamp", "current_date",
    "current_time", "localtimestamp", "localtime", "uuid", "shuffle",
})

# the system catalog's tables materialize from LIVE coordinator state at
# scan time (connector/system/): two evaluations of the same plan see
# different rows by design, so any scan over it is non-deterministic —
# caught here IN ADDITION to the connector's None data_version (belt and
# braces: both independently keep these plans out of the result and plan
# caches)
LIVE_SYSTEM_CATALOG = "system"


def scans_live_table_reason(root: P.PlanNode) -> Optional[str]:
    """A reason string when the plan scans a live system table, else
    None."""
    for node in P.walk_plan(root):
        if isinstance(node, P.TableScanNode) \
                and node.catalog == LIVE_SYSTEM_CATALOG:
            return (f"live system table "
                    f"{node.catalog}.{node.schema}.{node.table}")
    return None


def _ast_reason(node) -> Optional[str]:
    """Generic dataclass-tree walk over the parser AST."""
    if isinstance(node, ast.FunctionCall) and \
            node.name in NONDETERMINISTIC_FUNCTIONS:
        return f"non-deterministic function {node.name}()"
    if isinstance(node, ast.TableFunctionCall):
        return f"table function {node.name}(...)"
    if isinstance(node, (tuple, list)):
        for x in node:
            r = _ast_reason(x)
            if r:
                return r
        return None
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        for f in dataclasses.fields(node):
            r = _ast_reason(getattr(node, f.name))
            if r:
                return r
    return None


def _expr_reason(e: ir.Expr) -> Optional[str]:
    for x in ir.walk(e):
        if isinstance(x, ir.Call) and x.name in NONDETERMINISTIC_FUNCTIONS:
            return f"non-deterministic function {x.name}()"
    return None


def _plan_reason(root: P.PlanNode) -> Optional[str]:
    """Walk every expression position of every plan node generically: any
    dataclass field holding ir.Expr values (directly, or inside
    lists/tuples like Case whens or window calls) is scanned."""
    for node in P.walk_plan(root):
        for f in dataclasses.fields(node):
            r = _value_reason(getattr(node, f.name))
            if r:
                return r
    return None


def _value_reason(v) -> Optional[str]:
    if isinstance(v, ir.Expr):
        return _expr_reason(v)
    if isinstance(v, (list, tuple)):
        for x in v:
            r = _value_reason(x)
            if r:
                return r
    return None


def contains_table_function(stmt) -> bool:
    """True when the statement invokes a table function. Distinct from
    full non-determinism: a plan holding ``random()`` re-draws on every
    EXECUTION (safe to reuse the plan, unsafe to reuse results), but a
    table function's rows freeze into a ValuesNode AT PLAN TIME — so the
    logical-plan cache must also refuse these."""

    def walk(node) -> bool:
        if isinstance(node, ast.TableFunctionCall):
            return True
        if isinstance(node, (tuple, list)):
            return any(walk(x) for x in node)
        if dataclasses.is_dataclass(node) and not isinstance(node, type):
            return any(walk(getattr(node, f.name))
                       for f in dataclasses.fields(node))
        return False

    return walk(stmt)


def uncachable_reason(stmt, root: Optional[P.PlanNode] = None) -> Optional[str]:
    """None when the statement is cacheable; otherwise a human-readable
    reason (surfaced as a span attribute on the cache/lookup span)."""
    if not isinstance(stmt, ast.Query):
        return f"not a SELECT ({type(stmt).__name__})"
    r = _ast_reason(stmt)
    if r:
        return r
    if root is not None:
        return scans_live_table_reason(root) or _plan_reason(root)
    return None
