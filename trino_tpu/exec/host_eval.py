"""Phase-1 host evaluation: two-phase compiled execution for dynamic filtering.

Reference role: ``DynamicFilterService.java:105`` (collect build-side key
domains at runtime, narrow probe scans) + ``sql/planner/AdaptivePlanner.java``
(replan from runtime facts). The traced tiers (exec/compiled.py,
parallel/spmd.py) stage every scan BEFORE tracing, so the eager tier's
execute-build-side-first dynamic filtering cannot run there. Instead the
coordinator runs a **phase 1** on the host: evaluate each DF-producing join's
build subplan with numpy (dynamic shapes are free on the host), extract the
key domains, and only then stage the probe scans — physically narrowed — for
the compiled program. Phase 2 is the normal single compiled program over the
narrowed inputs.

Exactness contract: a dynamic-filter domain must be a SUPERSET of the build
side's true key set (a too-narrow domain silently drops rows). Host numpy
arithmetic on ints/decimals(scaled ints)/dates/dictionary codes is exact;
float REDUCTIONS (sum/avg) and decimal division are order/rounding sensitive
and may differ from the device, so any filter consuming such a column makes
the subplan ``Unsupported`` and the DF is skipped (conservative = correct).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector.predicate import Domain, TupleDomain
from trino_tpu.sql import ir
from trino_tpu.sql.planner import plan as P

# In-set domain cap for phase-1 collected filters. Much larger than the eager
# tier's 1024: these sets are applied host-side with sorted np.isin (cheap)
# and physically shrink the staged probe pages, which is the whole point.
PHASE1_MAX_SET = 1 << 21


class Unsupported(Exception):
    """The subplan uses a node/expression/exactness the host evaluator does
    not handle; the caller skips that dynamic filter (never an error)."""


@dataclasses.dataclass
class HCol:
    """One host column: numpy values (+nulls mask, True = NULL). Varchar
    rides decoded numpy unicode arrays (vocabularies are host-side anyway).
    ``exact`` is False for order/rounding-sensitive results (float sums,
    decimal division) — see the module exactness contract."""

    type: T.Type
    values: np.ndarray
    nulls: Optional[np.ndarray] = None
    exact: bool = True

    def take(self, idx) -> "HCol":
        return HCol(
            self.type,
            self.values[idx],
            None if self.nulls is None else self.nulls[idx],
            self.exact,
        )

    def live_values(self) -> np.ndarray:
        if self.nulls is None:
            return self.values
        return self.values[~self.nulls]


@dataclasses.dataclass
class HPage:
    cols: List[HCol]

    @property
    def num_rows(self) -> int:
        return len(self.cols[0].values) if self.cols else 0

    def take(self, idx) -> "HPage":
        return HPage([c.take(idx) for c in self.cols])


def domain_mask(dom: Domain, values: np.ndarray, nulls=None) -> np.ndarray:
    """Vectorized Domain.contains over a host column (the engine-side
    application of a dynamic filter at scan time — reference:
    FilterAndProjectOperator applying DynamicFilter.getCurrentPredicate)."""
    if dom.values is not None:
        from trino_tpu.connector.predicate import sorted_values_array

        if len(dom.values) == 0:
            m = np.zeros(len(values), dtype=bool)
        else:
            sa = sorted_values_array(dom)
            values = np.asarray(values)
            lo, hi = int(sa[0]), int(sa[-1])
            span = hi - lo + 1
            if sa.dtype.kind in "iu" and values.dtype.kind in "iu" \
                    and span <= max(8 * len(values), 1 << 22):
                # dense-span set: a boolean lookup table turns membership
                # into ONE bounded gather (binary search over millions of
                # needles is ~20x slower host-side). The LUT is cached on
                # the Domain like values_sorted: per-SPLIT pruning (the
                # pipelined staging engine) applies the same domain many
                # times, and rebuilding a multi-MB table per split would
                # dominate the mask itself
                cached = getattr(dom, "values_lut", None)
                if cached is not None and cached[0] == lo:
                    lut = cached[1]
                else:
                    lut = np.zeros(span, dtype=bool)
                    lut[sa.astype(np.int64) - lo] = True
                    object.__setattr__(dom, "values_lut", (lo, lut))
                inb = (values >= lo) & (values <= hi)
                idx = np.where(inb, values.astype(np.int64) - lo, 0)
                m = inb & lut[idx]
            else:
                idx = np.clip(np.searchsorted(sa, values), 0, len(sa) - 1)
                m = sa[idx] == values
    else:
        m = np.ones(len(values), dtype=bool)
        if dom.low is not None:
            m &= values >= dom.low if dom.low_inclusive else values > dom.low
        if dom.high is not None:
            m &= values <= dom.high if dom.high_inclusive else values < dom.high
    if nulls is not None:
        m = np.where(np.asarray(nulls), dom.null_allowed, m)
    return m


def _decode_varchar(cd) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Dictionary codes -> numpy unicode ('<U') array + null mask. Unicode
    dtype (not object) so lexsort/isin/unique all work vectorized."""
    codes = np.asarray(cd.values)
    vocab = np.asarray(cd.dictionary.values if cd.dictionary else [], dtype=str)
    null = codes < 0
    if cd.nulls is not None:
        null = null | np.asarray(cd.nulls)
    if len(vocab) == 0:
        return np.full(len(codes), "", dtype=str), null
    vals = vocab[np.clip(codes, 0, None)]
    return vals, null if null.any() else None


class HostEvaluator:
    """Numpy interpreter for build subplans. Shares the channel-positional
    plan contract with the device executor but compacts rows freely (hosts
    have dynamic shapes). Raises ``Unsupported`` on anything outside its
    subset — callers degrade to no-DF, never to wrong answers."""

    def __init__(self, session, dyn_domains: Dict[Tuple[int, int], Domain]):
        self.session = session
        self.dyn_domains = dyn_domains
        # node.id -> HPage. Safe across collects: by resolve_dynamic_filters'
        # visit order, every domain that will ever target a scan inside a
        # subtree is resolved before any eval first touches that subtree, so
        # a subtree's result never changes between evaluations.
        self._memo: Dict[int, HPage] = {}

    # ------------------------------------------------------------- plan
    def eval(self, node: P.PlanNode) -> HPage:
        hit = self._memo.get(node.id)
        if hit is not None:
            return hit
        method = getattr(self, f"_eval_{type(node).__name__}", None)
        if method is None:
            raise Unsupported(type(node).__name__)
        out = method(node)
        self._memo[node.id] = out
        return out

    def _eval_TableScanNode(self, node: P.TableScanNode) -> HPage:
        from trino_tpu.exec.executor import dynamic_domain_map

        conn = self.session.catalogs[node.catalog]
        td = node.constraint
        dyn = dynamic_domain_map(node, self.dyn_domains)
        if dyn:
            td = TupleDomain(dict(dyn)) if td is None else td.intersect(TupleDomain(dict(dyn)))
        # enumerate with the SAME adaptive target the staging tier will
        # use (exec/staging.target_split_count): phase-1 evaluation and
        # the staging loop then request identical split ranges, so the
        # generator-range cache (connector/gencache.py) fills here and
        # HITS there — mismatched boundaries would regenerate every
        # build-side table a second time at staging
        from trino_tpu.exec import staging as _staging

        target = _staging.target_split_count(
            self.session, conn, node.schema, node.table,
            handle=node.table_handle)
        splits = conn.get_splits(node.schema, node.table, target,
                                 constraint=td, handle=node.table_handle)
        datas = [conn.scan(s, node.column_names, constraint=td) for s in splits]
        from trino_tpu.connector.spi import concat_column_data

        cols: List[HCol] = []
        n_rows = None
        for name, typ in zip(node.column_names, node.column_types):
            parts = [d[name] for d in datas]
            cd = concat_column_data(parts) if parts else None
            if cd is None:
                cols.append(HCol(typ, np.empty(0, dtype=np.int64)))
                continue
            if typ.is_varchar:
                vals, nulls = _decode_varchar(cd)
            else:
                vals = np.asarray(cd.values)
                nulls = np.asarray(cd.nulls) if cd.nulls is not None else None
            n_rows = len(vals)
            cols.append(HCol(typ, vals, nulls))
        # engine-side enforcement of the dynamic part (connectors treat
        # constraints as advisory; monotone-key pushdown may have already
        # pruned most rows, this makes the narrowing exact)
        if dyn and n_rows:
            keep = np.ones(n_rows, dtype=bool)
            for name, dom in dyn.items():
                i = node.column_names.index(name)
                c = cols[i]
                if c.type.is_varchar:
                    continue
                keep &= domain_mask(dom, c.values, c.nulls)
            if not keep.all():
                cols = [c.take(keep) for c in cols]
        return HPage(cols)

    def _eval_FilterNode(self, node: P.FilterNode) -> HPage:
        page = self.eval(node.source)
        vals, valid, exact = self._expr(node.predicate, page)
        if not exact:
            raise Unsupported("filter over inexact input")
        mask = vals.astype(bool)
        if valid is not None:
            mask &= valid
        return page.take(mask)

    def _eval_ProjectNode(self, node: P.ProjectNode) -> HPage:
        page = self.eval(node.source)
        out: List[HCol] = []
        for e in node.expressions:
            if isinstance(e, ir.ColumnRef):
                out.append(page.cols[e.index])
                continue
            vals, valid, exact = self._expr(e, page)
            out.append(
                HCol(e.type, vals, None if valid is None else ~valid, exact)
            )
        return HPage(out)

    def _eval_CompactNode(self, node: P.CompactNode) -> HPage:
        return self.eval(node.source)  # host pages are always compact

    def _eval_ValuesNode(self, node: P.ValuesNode) -> HPage:
        cols = []
        for i, t in enumerate(node.types):
            pyvals = [r[i] for r in node.rows]
            if t.is_varchar:
                vals = np.asarray(
                    [v if v is not None else "" for v in pyvals], dtype=object)
            else:
                vals = np.asarray([v if v is not None else 0 for v in pyvals])
            nulls = np.asarray([v is None for v in pyvals])
            cols.append(HCol(t, vals, nulls if nulls.any() else None))
        return HPage(cols)

    def _eval_UnionNode(self, node: P.UnionNode) -> HPage:
        pages = [self.eval(s) for s in node.sources_]
        out: List[HCol] = []
        for ci in range(len(pages[0].cols)):
            parts = [p.cols[ci] for p in pages]
            vals = np.concatenate([np.asarray(c.values) for c in parts])
            if any(c.nulls is not None for c in parts):
                nulls = np.concatenate(
                    [c.nulls if c.nulls is not None
                     else np.zeros(len(c.values), bool) for c in parts])
            else:
                nulls = None
            out.append(HCol(parts[0].type, vals, nulls,
                            all(c.exact for c in parts)))
        return HPage(out)

    def _eval_SortNode(self, node: P.SortNode) -> HPage:
        return self.eval(node.source)  # row order is irrelevant to domains

    # ---------------------------------------------------------- aggregation
    def _eval_AggregationNode(self, node: P.AggregationNode) -> HPage:
        if node.step != "single":
            raise Unsupported("partial/final aggregation")
        page = self.eval(node.source)
        if not node.group_channels:
            return self._global_agg(node, page)
        dense = self._dense_group_agg(node, page)
        if dense is not None:
            return dense
        gid, uniq_idx, n_groups = self._group_ids(page, node.group_channels)
        out = [page.cols[c].take(uniq_idx) for c in node.group_channels]
        for a in node.aggregates:
            out.append(self._agg_call(a, page, gid, n_groups))
        return HPage(out)

    def _dense_group_agg(self, node: P.AggregationNode, page: HPage):
        """Single-int-key grouping via direct binning over the key RANGE —
        no sort, no gather. This is the per-run hot loop of phase 1 (Q18's
        HAVING subquery groups all of lineitem by orderkey every execution);
        dense ids = key - min make every aggregate one bincount/ufunc.at.
        Returns None when the shape doesn't fit (multi-key, nulls, sparse
        range, exotic aggregate) — the generic sort path handles those."""
        if len(node.group_channels) != 1:
            return None
        col = page.cols[node.group_channels[0]]
        k = np.asarray(col.values)
        if k.dtype.kind not in "iu" or k.size == 0:
            return None
        if col.nulls is not None and col.nulls.any():
            return None
        for a in node.aggregates:
            if a.distinct or a.function not in (
                    "count", "count_star", "sum", "min", "max", "avg"):
                return None
            if a.arg_channel is not None:
                ac = page.cols[a.arg_channel]
                if np.asarray(ac.values).dtype.kind not in "iuf":
                    return None
                if ac.nulls is not None and ac.nulls.any():
                    return None
        lo, hi = int(k.min()), int(k.max())
        span = hi - lo + 1
        if span > max(4 * k.size, 1 << 20):
            return None
        ids = k - lo
        counts = np.bincount(ids, minlength=span)
        present = counts > 0
        out = [HCol(col.type, (np.nonzero(present)[0] + lo).astype(k.dtype),
                    exact=col.exact)]
        for a in node.aggregates:
            fn = a.function
            if fn in ("count", "count_star"):
                out.append(HCol(a.output_type, counts[present].astype(np.int64)))
                continue
            ac = page.cols[a.arg_channel]
            vals = np.asarray(ac.values)
            if fn == "sum" and vals.dtype.kind in "iu":
                acc = np.zeros(span, dtype=np.int64)
                np.add.at(acc, ids, vals)
                out.append(HCol(a.output_type, acc[present], exact=ac.exact))
            elif fn in ("sum", "avg"):
                acc = np.zeros(span, dtype=np.float64)
                np.add.at(acc, ids, vals.astype(np.float64))
                v = acc[present] / counts[present] if fn == "avg" else acc[present]
                exact = False if fn == "avg" or vals.dtype.kind == "f" else ac.exact
                out.append(HCol(a.output_type, v, exact=exact))
            else:  # min / max
                op = np.minimum if fn == "min" else np.maximum
                if vals.dtype.kind == "f":
                    init = np.inf if fn == "min" else -np.inf
                    acc = np.full(span, init, dtype=np.float64)
                else:
                    ii = np.iinfo(vals.dtype)
                    init = ii.max if fn == "min" else ii.min
                    acc = np.full(span, init, dtype=vals.dtype)
                op.at(acc, ids, vals)
                out.append(HCol(a.output_type, acc[present], exact=ac.exact))
        return HPage(out)

    def _group_ids(self, page: HPage, channels):
        """(group id per row, representative row index per group, n_groups)."""
        keys = []
        for c in channels:
            col = page.cols[c]
            v = np.asarray(col.values)
            if col.nulls is not None:
                # NULL is its own group: (is_null, zeroed value) — the value
                # under a null slot is garbage and must not split the group
                keys.append(np.asarray(col.nulls))
                v = np.where(col.nulls, v.dtype.type(0) if v.dtype.kind != "U" else "", v)
            keys.append(v)
        if len(keys) == 1:
            uniq, uniq_idx, inv = np.unique(
                keys[0], return_index=True, return_inverse=True)
            return inv, uniq_idx, len(uniq)
        order = np.lexsort(keys[::-1])
        n = page.num_rows
        if n == 0:
            return np.empty(0, np.int64), np.empty(0, np.int64), 0
        sorted_keys = [k[order] for k in keys]
        new_group = np.zeros(n, dtype=bool)
        new_group[0] = True
        for k in sorted_keys:
            new_group[1:] |= k[1:] != k[:-1]
        gid_sorted = np.cumsum(new_group) - 1
        gid = np.empty(n, dtype=np.int64)
        gid[order] = gid_sorted
        uniq_idx = order[new_group]
        return gid, uniq_idx, int(gid_sorted[-1]) + 1

    def _agg_call(self, a, page: HPage, gid, n_groups) -> HCol:
        if a.distinct or a.function not in ("count", "count_star", "sum",
                                            "min", "max", "avg"):
            raise Unsupported(f"aggregate {a.function}")
        if a.function == "count_star" or (a.function == "count" and a.arg_channel is None):
            cnt = np.bincount(gid, minlength=n_groups).astype(np.int64)
            return HCol(a.output_type, cnt)
        col = page.cols[a.arg_channel]
        live = np.ones(page.num_rows, bool) if col.nulls is None else ~col.nulls
        if a.function == "count":
            cnt = np.bincount(gid[live], minlength=n_groups).astype(np.int64)
            return HCol(a.output_type, cnt, exact=col.exact)
        vals, g = np.asarray(col.values)[live], gid[live]
        if vals.dtype.kind not in "iuf":
            raise Unsupported(f"{a.function} over {vals.dtype} column")
        present = np.bincount(g, minlength=n_groups) > 0
        nulls = None if present.all() else ~present
        if a.function == "sum":
            if np.issubdtype(vals.dtype, np.integer):
                acc = np.zeros(n_groups, dtype=np.int64)
                np.add.at(acc, g, vals.astype(np.int64))
                return HCol(a.output_type, acc, nulls, exact=col.exact)
            acc = np.zeros(n_groups, dtype=np.float64)
            np.add.at(acc, g, vals)
            return HCol(a.output_type, acc, nulls, exact=False)
        if a.function == "avg":
            cnt = np.bincount(g, minlength=n_groups)
            acc = np.zeros(n_groups, dtype=np.float64)
            np.add.at(acc, g, vals.astype(np.float64))
            return HCol(a.output_type, acc / np.maximum(cnt, 1), nulls, exact=False)
        # min / max via sorted reduceat-free extremes
        op = np.minimum if a.function == "min" else np.maximum
        init = vals.dtype.type(np.iinfo(vals.dtype).max if np.issubdtype(vals.dtype, np.integer) else np.inf)
        if a.function == "max":
            init = vals.dtype.type(np.iinfo(vals.dtype).min if np.issubdtype(vals.dtype, np.integer) else -np.inf)
        acc = np.full(n_groups, init)
        op.at(acc, g, vals)
        return HCol(a.output_type, acc, nulls, exact=col.exact)

    def _global_agg(self, node: P.AggregationNode, page: HPage) -> HPage:
        gid = np.zeros(page.num_rows, dtype=np.int64)
        out = [self._agg_call(a, page, gid, 1) for a in node.aggregates]
        return HPage(out)

    def eval_key_column(self, node: P.PlanNode, channel: int) -> HCol:
        """Values of one output channel of ``node``, join multiplicity
        IGNORED — exact for domain extraction (a domain is a value SET).
        Inner equi-joins reduce to a semi filter on the side carrying the
        channel, skipping the M:N expansion and the other side's gathers —
        the dominant phase-1 cost for large build sides."""
        if (isinstance(node, P.JoinNode) and node.join_type == "inner"
                and node.left_keys and node.filter is None
                and not node.singleton):
            nl = len(node.left.output_types)
            left = self.eval(node.left)
            right = self.eval(node.right)
            lkey, rkey = self._combined_key(
                left, node.left_keys, right, node.right_keys)
            if channel < nl:
                page, own, other, ch = left, lkey, rkey, channel
            else:
                page, own, other, ch = right, rkey, lkey, channel - nl
            keep = np.isin(np.asarray(own.values), other.live_values())
            if own.nulls is not None:
                keep &= ~own.nulls
            return page.cols[ch].take(keep)
        if isinstance(node, P.ProjectNode):
            e = node.expressions[channel]
            if isinstance(e, ir.ColumnRef):
                return self.eval_key_column(node.source, e.index)
        return self.eval(node).cols[channel]

    # --------------------------------------------------------------- joins
    def _eval_JoinNode(self, node: P.JoinNode) -> HPage:
        if node.singleton or not node.left_keys:
            raise Unsupported("cross/singleton join")
        if node.join_type not in ("inner", "semi", "anti", "left"):
            raise Unsupported(f"{node.join_type} join")
        left = self.eval(node.left)
        right = self.eval(node.right)
        lk = self._combined_key(left, node.left_keys, right, node.right_keys)
        lkey, rkey = lk
        if node.join_type in ("semi", "anti"):
            if node.filter is not None:
                raise Unsupported("filtered semi join")
            hit = np.isin(lkey.values, rkey.live_values())
            if lkey.nulls is not None:
                hit &= ~lkey.nulls
            return left.take(hit if node.join_type == "semi" else ~hit)
        # inner/left M:N sort-merge expansion
        l_idx, r_idx = _inner_match(lkey, rkey)
        joined = HPage(
            [c.take(l_idx) for c in left.cols] + [c.take(r_idx) for c in right.cols]
        )
        if node.filter is not None:
            vals, valid, exact = self._expr(node.filter, joined)
            if not exact:
                raise Unsupported("join filter over inexact input")
            mask = vals.astype(bool)
            if valid is not None:
                mask &= valid
            joined = joined.take(mask)
            l_idx = l_idx[mask]
        if node.join_type != "left":
            return joined
        # left outer: probe rows with no (filter-passing) match emit once
        # with NULL build columns
        matched = np.zeros(left.num_rows, bool)
        matched[l_idx] = True
        tail_idx = np.flatnonzero(~matched)
        tail_cols = [c.take(tail_idx) for c in left.cols] + [
            HCol(c.type, np.zeros(len(tail_idx), dtype=np.asarray(c.values).dtype),
                 np.ones(len(tail_idx), bool), c.exact)
            for c in right.cols
        ]
        out = []
        for jc, tc in zip(joined.cols, tail_cols):
            nulls = None
            if jc.nulls is not None or tc.nulls is not None:
                nulls = np.concatenate([
                    jc.nulls if jc.nulls is not None
                    else np.zeros(len(jc.values), bool),
                    tc.nulls if tc.nulls is not None
                    else np.zeros(len(tc.values), bool),
                ])
            out.append(HCol(jc.type, np.concatenate([jc.values, tc.values]),
                            nulls, jc.exact and tc.exact))
        return HPage(out)

    def _combined_key(self, left: HPage, lchs, right: HPage, rchs):
        """Reduce (possibly multi-column) join keys to one comparable array
        per side: single keys ride as-is; multi-keys densify each column over
        the union of both sides' values, then mix into one int64."""
        if len(lchs) == 1:
            return left.cols[lchs[0]], right.cols[rchs[0]]
        lcols = [left.cols[c] for c in lchs]
        rcols = [right.cols[c] for c in rchs]
        lmix = np.zeros(left.num_rows, dtype=np.int64)
        rmix = np.zeros(right.num_rows, dtype=np.int64)
        for lc, rc in zip(lcols, rcols):
            both = np.concatenate([np.asarray(lc.values), np.asarray(rc.values)])
            uniq, inv = np.unique(both, return_inverse=True)
            stride = len(uniq) + 1
            lmix = lmix * stride + inv[: left.num_rows]
            rmix = rmix * stride + inv[left.num_rows:]
        lnull = None
        for c in lcols:
            if c.nulls is not None:
                lnull = c.nulls if lnull is None else (lnull | c.nulls)
        rnull = None
        for c in rcols:
            if c.nulls is not None:
                rnull = c.nulls if rnull is None else (rnull | c.nulls)
        return (
            HCol(T.BIGINT, lmix, lnull),
            HCol(T.BIGINT, rmix, rnull),
        )

    # --------------------------------------------------------- expressions
    def _expr(self, e: ir.Expr, page: HPage):
        """-> (values ndarray, valid ndarray|None, exact bool). ``valid``
        True = non-null (matching expr_lower's LoweredVal convention)."""
        if isinstance(e, ir.Constant):
            n = page.num_rows
            if e.value is None:
                return np.zeros(n, np.int64), np.zeros(n, bool), True
            v = np.full(n, e.value)  # str constants infer '<U' dtype
            return v, None, True
        if isinstance(e, ir.ColumnRef):
            c = page.cols[e.index]
            valid = None if c.nulls is None else ~c.nulls
            return c.values, valid, c.exact
        if isinstance(e, ir.Cast):
            vals, valid, exact = self._expr(e.value, page)
            if e.type.is_floating:
                return vals.astype(np.float64), valid, exact
            if e.type.name in ("bigint", "integer", "date"):
                if np.issubdtype(np.asarray(vals).dtype, np.floating):
                    raise Unsupported("float->int cast (rounding semantics)")
                return vals.astype(np.int64), valid, exact
            raise Unsupported(f"cast to {e.type}")
        if isinstance(e, ir.Call):
            return self._call(e, page)
        raise Unsupported(type(e).__name__)

    _CMP = {
        "eq": np.equal, "ne": np.not_equal, "lt": np.less,
        "le": np.less_equal, "gt": np.greater, "ge": np.greater_equal,
    }

    def _call(self, e: ir.Call, page: HPage):
        name = e.name
        if name in self._CMP:
            a, av, ax = self._expr(e.args[0], page)
            b, bv, bx = self._expr(e.args[1], page)
            a, b = _align_numeric(a, e.args[0].type, b, e.args[1].type)
            return self._CMP[name](a, b), _and_valid(av, bv), ax and bx
        if name in ("and", "or"):
            a, av, ax = self._expr(e.args[0], page)
            b, bv, bx = self._expr(e.args[1], page)
            # domain-collection filters only need Kleene-false = drop row:
            # treating NULL as false is exact for top-level conjunctions
            a = a.astype(bool) & (av if av is not None else True)
            b = b.astype(bool) & (bv if bv is not None else True)
            out = (a | b) if name == "or" else (a & b)
            return out, None, ax and bx
        if name == "not":
            a, av, ax = self._expr(e.args[0], page)
            return ~a.astype(bool), av, ax
        if name == "is_null":
            a, av, ax = self._expr(e.args[0], page)
            out = np.zeros(len(a), bool) if av is None else ~av
            return out, None, True
        if name == "between":
            v, lo, hi = (self._expr(a, page) for a in e.args)
            v1, lo1 = _align_numeric(v[0], e.args[0].type, lo[0], e.args[1].type)
            v2, hi2 = _align_numeric(v[0], e.args[0].type, hi[0], e.args[2].type)
            out = (v1 >= lo1) & (v2 <= hi2)
            return out, _and_valid(_and_valid(v[1], lo[1]), hi[1]), v[2] and lo[2] and hi[2]
        if name == "in_list":
            v, vv, vx = self._expr(e.args[0], page)
            consts = []
            for a in e.args[1:]:
                if not isinstance(a, ir.Constant) or a.value is None:
                    raise Unsupported("non-literal IN list")
                cv, _, _ = self._expr(a, page)
                v2, cv = _align_numeric(v, e.args[0].type, cv, a.type)
                consts.append(cv[:1])
            return np.isin(v2, np.concatenate(consts)), vv, vx
        if name in ("add", "sub", "mul"):
            a, av, ax = self._expr(e.args[0], page)
            b, bv, bx = self._expr(e.args[1], page)
            op = {"add": np.add, "sub": np.subtract, "mul": np.multiply}[name]
            # decimal arithmetic has result-scale/rounding semantics
            # (expr_lower._rescale_decimal) not reproduced here — inexact
            exact = ax and bx and not e.type.is_decimal
            return op(a, b), _and_valid(av, bv), exact
        if name == "negate":
            a, av, ax = self._expr(e.args[0], page)
            return -a, av, ax
        if name == "div":
            a, av, ax = self._expr(e.args[0], page)
            b, bv, bx = self._expr(e.args[1], page)
            with np.errstate(divide="ignore", invalid="ignore"):
                out = np.asarray(a, dtype=np.float64) / np.asarray(b, np.float64)
            # float elementwise div is IEEE-exact; decimal/int division has
            # engine rounding semantics we do not reproduce here
            exact = ax and bx and e.type.is_floating
            return out, _and_valid(av, bv), exact
        if name == "extract_year":
            a, av, ax = self._expr(e.args[0], page)
            y = a.astype("datetime64[D]").astype("datetime64[Y]").astype(np.int64) + 1970
            return y, av, ax
        if name == "extract_month":
            a, av, ax = self._expr(e.args[0], page)
            d = a.astype("datetime64[D]")
            m = (d.astype("datetime64[M]").astype(np.int64) % 12) + 1
            return m, av, ax
        if name == "coalesce":
            out_v, out_valid, exact = None, None, True
            for arg in e.args:
                v, valid, ax = self._expr(arg, page)
                exact = exact and ax
                if out_v is None:
                    out_v = np.array(v)
                    out_valid = np.ones(len(v), bool) if valid is None else valid.copy()
                else:
                    fill = ~out_valid
                    out_v[fill] = v[fill]
                    out_valid[fill] = True if valid is None else valid[fill]
            return out_v, out_valid, exact
        raise Unsupported(f"call {name}")


def _align_numeric(av, at: T.Type, bv, bt: T.Type):
    """Mirror of ops/expr_lower._numeric_align (the device comparison
    semantics) in numpy: decimals compare at the max scale, mixed
    float/decimal at float64 — host and device must agree bit-for-bit."""
    if at.is_varchar or bt.is_varchar:
        return av, bv
    if at.is_decimal or bt.is_decimal:
        sa = at.scale if getattr(at, "scale", None) is not None and at.is_decimal else 0
        sb = bt.scale if getattr(bt, "scale", None) is not None and bt.is_decimal else 0
        if at.is_floating or bt.is_floating:
            fa = av / (10.0 ** sa) if at.is_decimal else av
            fb = bv / (10.0 ** sb) if bt.is_decimal else bv
            return np.asarray(fa, np.float64), np.asarray(fb, np.float64)
        s = max(sa, sb)
        return (
            np.asarray(av, np.int64) * (10 ** (s - sa)),
            np.asarray(bv, np.int64) * (10 ** (s - sb)),
        )
    if at.is_floating != bt.is_floating:
        return np.asarray(av, np.float64), np.asarray(bv, np.float64)
    return av, bv


def _and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _inner_match(lkey: HCol, rkey: HCol):
    """Sort-merge M:N inner-join row indices (null keys never match)."""
    lv, rv = np.asarray(lkey.values), np.asarray(rkey.values)
    l_live = np.arange(len(lv)) if lkey.nulls is None else np.nonzero(~lkey.nulls)[0]
    r_live = np.arange(len(rv)) if rkey.nulls is None else np.nonzero(~rkey.nulls)[0]
    lv, rv = lv[l_live], rv[r_live]
    order = np.argsort(rv, kind="stable")
    rs = rv[order]
    lo = np.searchsorted(rs, lv, "left")
    hi = np.searchsorted(rs, lv, "right")
    counts = hi - lo
    total = int(counts.sum())
    l_idx = np.repeat(np.arange(len(lv)), counts)
    starts = np.cumsum(counts) - counts  # exclusive prefix, empty-safe
    r_pos = np.arange(total) - np.repeat(starts, counts) + np.repeat(lo, counts)
    return l_live[l_idx], r_live[order[r_pos]]


def resolve_dynamic_filters(session, root: P.PlanNode) -> Dict[Tuple[int, int], Domain]:
    """Phase 1: host-evaluate every DF-producing join's build side and return
    {(join_id, key_index): Domain} for the staged-scan narrowing of phase 2.
    Joins whose build subplan the host evaluator cannot reproduce exactly are
    skipped (their probe scans simply stay unnarrowed).

    Ordering mirrors the eager executor's build-before-probe recursion: at
    each join the BUILD subtree resolves (and this join's domain is
    collected) before the PROBE subtree is visited, so scans inside the
    probe subtree — including build sides of nested joins there, e.g. the
    orders side of Q3's (lineitem ⨝ orders) under the customer join — see
    every enclosing join's domain before they are evaluated."""
    props = getattr(session, "properties", None) or {}
    if not props.get("dynamic_filtering_enabled", True):
        return {}
    domains: Dict[Tuple[int, int], Domain] = {}
    ev = HostEvaluator(session, domains)

    def collect(join: P.JoinNode) -> None:
        for i in join.dyn_filter_keys:
            try:
                col = ev.eval_key_column(join.right, join.right_keys[i])
            except Unsupported:
                continue
            if col.type.is_varchar or not col.exact:
                continue
            lv = col.live_values()
            if len(lv) == 0:
                dom = Domain(values=frozenset())
            elif len(lv) <= PHASE1_MAX_SET:
                dom = Domain.from_values(np.unique(lv))  # caches sorted array
                # an exact in-set domain means every surviving probe row has
                # >= 1 build match: the join's match-fraction estimate is 1
                join.df_exact = True
            else:
                dom = Domain.range(low=lv.min().item(), high=lv.max().item())
            domains[(join.id, i)] = dom

    def visit(node: P.PlanNode) -> None:
        if isinstance(node, P.JoinNode):
            visit(node.right)  # nested DF joins inside the build side first
            if node.dyn_filter_keys:
                collect(node)
            visit(node.left)  # probe subtree sees this join's domain
            return
        for s in node.sources:
            visit(s)

    visit(root)
    return domains
