"""Transactions: explicit START TRANSACTION / COMMIT / ROLLBACK plus
atomic auto-commit DML.

Reference: ``core/trino-main/.../transaction/InMemoryTransactionManager.java``
— a transaction owns per-catalog connector transaction handles; metadata
reads inside it see the transaction's isolated view; commit publishes
atomically, abort discards. Here the same shape with the engine's one
transactional connector (memory): a transaction wraps the catalog in a
copy-on-write OVERLAY — reads hit the overlay first, writes mutate only
the overlay — and COMMIT swaps the staged tables into the base connector
under its lock in one step. Non-transactional connectors inside an explicit
transaction raise, matching the reference's "Catalog only supports writes
using autocommit" error.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from trino_tpu.connector import spi


class TransactionError(RuntimeError):
    pass


class TransactionalOverlay(spi.Connector):
    """The memory connector's transaction view: staged creates/drops/appends
    living only in this object until commit (reference: each connector's
    ConnectorTransactionHandle-scoped metadata)."""

    coordinator_only = True

    def __init__(self, base):
        self.base = base
        self.name = base.name
        self._staged: Dict[Tuple[str, str], Optional[tuple]] = {}
        # (schema, table) -> (meta, cols) staged state, or None = dropped

    # --- reads: overlay first -------------------------------------------
    def list_schemas(self):
        return self.base.list_schemas()

    def list_tables(self, schema):
        names = {
            n for n in self.base.list_tables(schema)
            if self._staged.get((schema, n), ()) is not None
        }
        names |= {
            t for (s, t), v in self._staged.items() if s == schema and v is not None
        }
        return sorted(names)

    def get_table(self, schema, table):
        key = (schema, table)
        if key in self._staged:
            st = self._staged[key]
            return None if st is None else st[0]
        return self.base.get_table(schema, table)

    def table_row_count(self, schema, table):
        key = (schema, table)
        if key in self._staged:
            st = self._staged[key]
            if st is None:
                return None
            _, cols = st
            first = next(iter(cols.values()), None)
            return 0 if first is None else len(first.values)
        return self.base.table_row_count(schema, table)

    def column_stats(self, schema, table, column):
        if (schema, table) in self._staged:
            return None  # staged data: no stats (conservative)
        return self.base.column_stats(schema, table, column)

    def primary_key(self, schema, table):
        if (schema, table) in self._staged:
            return None
        return self.base.primary_key(schema, table)

    def get_splits(self, schema, table, target_splits, constraint=None,
                   handle=None):
        if (schema, table) in self._staged:
            st = self._staged[(schema, table)]
            if st is None:
                raise KeyError(f"{self.name}.{schema}.{table} does not exist")
            n = self.table_row_count(schema, table) or 0
            return [spi.Split(table, schema, 0, n)]
        return self.base.get_splits(schema, table, target_splits, constraint,
                                    handle=handle)

    def scan(self, split, columns, constraint=None):
        key = (split.schema, split.table)
        if key in self._staged:
            st = self._staged[key]
            assert st is not None
            _, cols = st
            return {c: spi.column_data_slice(cols[c], split.lo, split.hi) for c in columns}
        return self.base.scan(split, columns, constraint)

    # --- writes: stage only ---------------------------------------------
    def _snapshot(self, schema, table):
        """Copy the base table into the overlay (copy-on-write)."""
        key = (schema, table)
        if key in self._staged:
            if self._staged[key] is None:
                raise KeyError(
                    f"{self.name}.{schema}.{table} does not exist "
                    "(dropped in this transaction)"
                )
            return
        entry = self.base._tables.get(key)
        if entry is None:
            raise KeyError(f"{self.name}.{schema}.{table} does not exist")
        meta, cols = entry
        self._staged[key] = (meta, dict(cols))

    def create_table(self, schema, name, schema_def, rows):
        if self.get_table(schema, name) is not None:
            raise ValueError(f"table already exists: {schema}.{name}")
        from trino_tpu.data.page import Column

        cols = {
            cname: spi.column_data_from_column(
                Column.from_python(ctype, [r[i] for r in rows])
            )
            for i, (cname, ctype) in enumerate(schema_def)
        }
        meta = spi.TableMetadata(
            schema, name, [spi.ColumnMetadata(n, t) for n, t in schema_def]
        )
        self._staged[(schema, name)] = (meta, cols)

    def insert_rows(self, schema, table, rows):
        self._snapshot(schema, table)
        meta, cols = self._staged[(schema, table)]
        if not rows:
            return 0
        from trino_tpu.data.page import Column

        new_cols = {}
        for i, cm in enumerate(meta.columns):
            col = Column.from_python(cm.type, [r[i] for r in rows])
            new_cols[cm.name] = spi.concat_column_data(
                [cols[cm.name], spi.column_data_from_column(col)]
            )
        self._staged[(schema, table)] = (meta, new_cols)
        return len(rows)

    def overwrite_rows(self, schema, table, rows):
        self._snapshot(schema, table)
        meta, _cols = self._staged[(schema, table)]
        from trino_tpu.data.page import Column

        new_cols = {
            cm.name: spi.column_data_from_column(
                Column.from_python(cm.type, [r[i] for r in rows]))
            for i, cm in enumerate(meta.columns)
        }
        self._staged[(schema, table)] = (meta, new_cols)

    def drop_table(self, schema, table):
        if self.get_table(schema, table) is None:
            return
        self._staged[(schema, table)] = None

    # --- lifecycle -------------------------------------------------------
    def publish(self):
        """Apply every staged change to the base connector atomically."""
        with _BASE_LOCK:
            for (schema, table), st in self._staged.items():
                if st is None:
                    self.base._tables.pop((schema, table), None)
                else:
                    self.base._tables[(schema, table)] = st
                # commit is a data mutation like any other: advance the
                # base table's cache-invalidation version
                self.base._bump(schema, table)


_BASE_LOCK = threading.Lock()


class Transaction:
    """One explicit transaction: catalog name -> overlay."""

    def __init__(self, session):
        self.session = session
        self.overlays: Dict[str, TransactionalOverlay] = {}
        self.saved: Dict[str, spi.Connector] = {}

    def enlist(self, catalog: str):
        """Wrap ``catalog`` in an overlay on first touch (reference:
        TransactionManager.getConnectorTransaction creating the handle)."""
        if catalog in self.overlays:
            return
        conn = self.session.catalogs[catalog]
        if not getattr(conn, "supports_transactions", False):
            raise TransactionError(
                f"catalog '{catalog}' only supports writes using autocommit"
            )
        ov = TransactionalOverlay(conn)
        self.saved[catalog] = conn
        self.overlays[catalog] = ov
        self.session.catalogs[catalog] = ov

    def commit(self):
        for ov in self.overlays.values():
            ov.publish()
        self._restore()

    def rollback(self):
        self._restore()

    def _restore(self):
        for catalog, conn in self.saved.items():
            self.session.catalogs[catalog] = conn
        self.session.transaction = None


def begin(session) -> Transaction:
    if getattr(session, "transaction", None) is not None:
        raise TransactionError("a transaction is already in progress")
    txn = Transaction(session)
    session.transaction = txn
    return txn
