"""Whole-query compilation: trace the executor once, jit, reuse.

Reference role: this is the moral equivalent of the reference's query-time
bytecode generation pipeline (``sql/gen/ExpressionCompiler`` + operator
factories baked per query by ``LocalExecutionPlanner``) — except the unit of
compilation is the *entire query body* (scan outputs -> final page), so XLA
fuses across operator boundaries (filter into scan into partial-agg, etc.),
which no per-operator engine can do.

The compiled artifact is reusable across runs with same-shaped inputs
(same splits) — the bench harness measures steady-state throughput on it.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import jax

from trino_tpu.data.page import Page
from trino_tpu.exec.executor import Executor, QueryError
from trino_tpu.exec.page_tree import PageSpec, flatten_page, unflatten_page
from trino_tpu.sql.planner import plan as P


class PreloadedExecutor(Executor):
    """Executor that reads table scans from pre-staged pages (the traced
    inputs) instead of calling the connector."""

    def __init__(self, session, staged: Dict[int, Page], capacity_hints=None):
        super().__init__(session, capacity_hints)
        self.staged = staged

    def _exec_TableScanNode(self, node: P.TableScanNode) -> Page:
        return self.staged[node.id]


@dataclasses.dataclass
class CompiledQuery:
    session: object
    root: P.OutputNode
    input_arrays: List
    input_specs: Dict[int, PageSpec]
    fn: object  # jitted
    out_spec_cell: List
    error_codes_cell: List

    @classmethod
    def build(cls, session, root: P.OutputNode) -> "CompiledQuery":
        base = Executor(session)
        scans = [n for n in P.walk_plan(root) if isinstance(n, P.TableScanNode)]
        staged_pages = {n.id: base._exec_TableScanNode(n) for n in scans}
        # shape-hint collection: one eager pass discovers the M:N join output
        # capacities that the traced program needs as static constants
        # (SURVEY.md §7.3 "two-pass kernels + bucketed recompiles")
        capacity_hints: Dict[int, int] = {}
        if P.needs_capacity_hints(root):
            hint_ex = PreloadedExecutor(session, staged_pages)
            hint_ex.execute(root)
            capacity_hints = dict(hint_ex.capacity_hints)
        flat_inputs: List = []
        specs: Dict[int, PageSpec] = {}
        layout: List[Tuple[int, int]] = []  # (node_id, num_arrays)
        for nid, page in staged_pages.items():
            arrays, spec = flatten_page(page)
            specs[nid] = spec
            layout.append((nid, len(arrays)))
            flat_inputs.extend(arrays)
        out_spec_cell: List = [None]
        error_codes_cell: List = [None]

        def run(flat):
            pages: Dict[int, Page] = {}
            i = 0
            for nid, count in layout:
                pages[nid] = unflatten_page(specs[nid], flat[i : i + count])
                i += count
            ex = PreloadedExecutor(session, pages, dict(capacity_hints))
            out_page = ex.execute(root)
            out_arrays, out_spec = flatten_page(out_page)
            out_spec_cell[0] = out_spec
            error_codes_cell[0] = [c for c, _ in ex.errors]
            return out_arrays, [f for _, f in ex.errors]

        fn = jax.jit(run)
        cq = cls(session, root, flat_inputs, specs, fn, out_spec_cell, error_codes_cell)
        cq.raw_fn = run  # unjitted closure (for AOT/compile-check harnesses)
        return cq

    def run(self) -> Page:
        from trino_tpu.exec.executor import raise_query_errors

        out_arrays, error_flags = self.fn(self.input_arrays)
        raise_query_errors(self.error_codes_cell[0], error_flags)
        return unflatten_page(self.out_spec_cell[0], out_arrays)
