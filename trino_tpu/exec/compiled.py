"""Whole-query compilation: trace the executor once, jit, reuse.

Reference role: this is the moral equivalent of the reference's query-time
bytecode generation pipeline (``sql/gen/ExpressionCompiler`` + operator
factories baked per query by ``LocalExecutionPlanner``) — except the unit of
compilation is the *entire query body* (scan outputs -> final page), so XLA
fuses across operator boundaries (filter into scan into partial-agg, etc.),
which no per-operator engine can do.

The compiled artifact is reusable across runs with same-shaped inputs
(same splits) — the bench harness measures steady-state throughput on it.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.data.page import Page
from trino_tpu.exec.executor import Executor, QueryError
from trino_tpu.exec.page_tree import PageSpec, flatten_page, unflatten_page
from trino_tpu.obs import metrics as M
from trino_tpu.obs import trace as tracing
from trino_tpu.sql.planner import plan as P


# strong domains (|set|/NDV at or below this) prune rows HOST-SIDE at
# staging — a cheap numpy LUT pass that cuts the host->device transfer,
# the staging bottleneck at scale; weaker domains are enforced on device
HOST_APPLY_MAX_SEL = 0.25
# max probe-column value span for an in-program boolean LUT (bytes on the
# device = span); wider spans degrade to min/max range narrowing. 1<<28 =
# 256 MB worst case — big enough for sf100 orderkeys (150M span)
LUT_MAX_SPAN = 1 << 28


class StagingExecutor(Executor):
    """Stages scans for the compiled tier: constraint pushdown (including
    resolved dynamic domains — the connector can prune clustered key runs
    at the generator level) plus SELECTIVE host row filtering: strongly
    narrowing domains prune rows before the device transfer (through the
    tunnel the transfer is the staging bottleneck at scale), while weak
    domains are left for PreloadedExecutor to enforce on device. The split
    is decided per domain by ``df_host_allow`` (set in
    CompiledQuery.build from NDV selectivity estimates)."""

    df_host_allow = None  # callable(node, column, domain) -> bool


class PreloadedExecutor(Executor):
    """Executor that reads table scans from pre-staged pages (the traced
    inputs) instead of calling the connector, with IN-PROGRAM dynamic
    filtering: when a join executes its build side, the traced key values
    ride into a boolean lookup table (one scatter, statically sized from
    the probe column's vrange) or a min/max range; probe scans deeper in
    the recursion mask against it and compact to a stats-sized capacity.
    The whole collect->apply dataflow lives inside the single compiled
    program — ZERO host work repeats per run (reference:
    DynamicFilterService.java:105 + DynamicFiltersCollector, redesigned as
    a pure dataflow instead of a coordinator round-trip)."""

    eager_tier = False  # runs under jax tracing: no host-side syncs
    enable_dynamic_filtering = True  # traced collection (see below)
    collect_stats = False  # tracing once; per-call timing is meaningless

    def __init__(self, session, staged: Dict[int, Page], capacity_hints=None,
                 device_df=None):
        super().__init__(session, capacity_hints)
        self.staged = staged
        # scan node_id -> [(channel, join_id, key_idx, spec)] where spec is
        # ("lut", lo, span) with STATIC bounds from the probe column's
        # vrange, or ("range",) for min/max-only narrowing
        self.device_df = device_df or {}
        # (join_id, key_idx) -> (traced key values, traced live mask),
        # registered by _collect_dynamic_filters during the build-side
        # visit, consumed by probe scans later in the same trace
        self.traced_domains: Dict[Tuple[int, int], tuple] = {}

    def _collect_dynamic_filters(self, node: P.JoinNode, build: Page) -> None:
        """Traced collection: no host syncs, just remember the build-side
        key column (+liveness) for probe scans to mask against."""
        for i in node.dyn_filter_keys:
            ch = node.right_keys[i]
            col = build.columns[ch]
            if col.type.is_varchar or col.hi is not None:
                continue  # dictionary codes are page-local; two-limb later
            live = (build.sel if build.sel is not None
                    else jnp.ones(build.num_rows, bool))
            if col.nulls is not None:
                live = live & ~col.nulls
            self.traced_domains[(node.id, i)] = (col.values, live)

    def _exec_TableScanNode(self, node: P.TableScanNode) -> Page:
        page = self.staged[node.id]
        entries = self.device_df.get(node.id)
        if not entries:
            return page
        sel = page.sel if page.sel is not None else jnp.ones(page.num_rows, bool)
        applied = False
        for ch, join_id, key_idx, spec in entries:
            dom = self.traced_domains.get((join_id, key_idx))
            if dom is None:
                continue  # build side could not register (exotic key type)
            col = page.columns[ch]
            m = _traced_domain_mask(col.values, dom, spec)
            if col.nulls is not None:
                m = m & ~col.nulls
            sel = sel & m
            applied = True
        if not applied:
            return page
        page = Page(list(page.columns), sel, page.replicated)
        cap = self.capacity_hints.get(f"dfc:{node.id}")
        if cap is not None:
            page = self.compact_to(page, cap, f"dfc:{node.id}")
        return page


def _traced_domain_mask(values, dom, spec):
    """Membership of probe ``values`` in a traced build-side key set.
    LUT path: the dense boolean-table membership kernel shared with semi
    joins (ops/join.py dense_membership — one scatter, one bounded gather;
    NEVER jnp.searchsorted, whose log2(n) dependent random-gather passes
    cost ~2.5 s for 6M probes on v5e). Range path: masked min/max
    reductions — empty build sides yield an all-false mask (inner/semi
    join with an empty build emits nothing)."""
    from trino_tpu.ops import join as join_ops

    bvals, blive = dom
    if spec[0] == "lut":
        _, lo, span = spec
        return join_ops.dense_membership(
            (bvals, None), blive, (values, None), lo, span)
    bv = bvals.astype(jnp.int64)
    big = jnp.int64(1) << 62
    lo = jnp.min(jnp.where(blive, bv, big))
    hi = jnp.max(jnp.where(blive, bv, -big))
    v = values.astype(jnp.int64)
    return (v >= lo) & (v <= hi)


@dataclasses.dataclass
class CompiledQuery:
    session: object
    root: P.OutputNode
    input_arrays: List
    input_specs: Dict[int, PageSpec]
    fn: object  # jitted
    out_spec_cell: List
    error_codes_cell: List
    capacity_hints: Dict[str, int] = dataclasses.field(default_factory=dict)
    # two-phase execution profile: host phase-1 wall (dynamic-filter build
    # evaluation, exec/host_eval.py), host domain-application wall at the
    # scans, and per-scan staged row counts. Benchmarks charge
    # phase1_s + df_apply_s to every run: it is query work done off-device.
    phase1_s: float = 0.0
    df_apply_s: float = 0.0
    scan_rows: Dict[int, int] = dataclasses.field(default_factory=dict)
    # staging profile of the build: wall seconds of the staging loop, how
    # many scans the device cache served warm, and the rows that actually
    # crossed host->device (0 on a fully warm build — the warm-run proof)
    staging_s: float = 0.0
    cache_hits: int = 0
    fresh_staged_rows: int = 0
    # capacity-overflow regrowth recompiles this query has paid (the
    # double-and-recompile loop; 0 when hints were right the first time —
    # e.g. under adaptive_capacity_reseed)
    recompiles: int = 0
    # kernel-ledger rollup (obs/devprofiler.py): one "CompiledBody" row
    # accumulating this query's jitted-body dispatches
    kernel_stats: Dict[tuple, dict] = dataclasses.field(default_factory=dict)
    # compile-ledger identity, computed lazily once per instance
    _fingerprint: str = ""

    MAX_RECOMPILES = 16  # doubling buckets: 2^16x headroom over the estimate

    @classmethod
    def build(
        cls, session, root: P.OutputNode, capacity_hints: Dict[str, int] = None
    ) -> "CompiledQuery":
        """Two-phase compile (reference: DynamicFilterService +
        AdaptivePlanner): phase 1 host-evaluates DF build sides and narrows
        probe scans BEFORE staging; actual staged cardinalities then right-
        size capacities (stats start from truth). Phase 2 traces the query
        body once over the narrowed inputs. If a run still overflows a
        bucket, ``run()`` doubles it and recompiles."""
        from trino_tpu.exec import host_eval
        from trino_tpu.sql.planner import stats

        t0 = time.perf_counter()
        with tracing.span("staging/dynamic-filters"):
            dyn = host_eval.resolve_dynamic_filters(session, root)
        phase1_s = time.perf_counter() - t0
        scans = [n for n in P.walk_plan(root) if isinstance(n, P.TableScanNode)]

        def _dom_sel(node, col_name, dom):
            """|domain| / column NDV — the narrowing strength estimate."""
            if dom.values is None:
                return 1.0
            conn = session.catalogs[node.catalog]
            cs = conn.column_stats(node.schema, node.table, col_name)
            if cs is not None and cs.ndv:
                return min(1.0, len(dom.values) / cs.ndv)
            return 1.0

        def host_allow(node, col_name, dom):
            return dom.values is not None and \
                _dom_sel(node, col_name, dom) <= HOST_APPLY_MAX_SEL

        base = StagingExecutor(session)
        base.df_host_allow = host_allow
        base.dyn_domains.update(dyn)
        with tracing.span("device/staging") as stage_sp:
            t_stage = time.perf_counter()
            staged_pages = {n.id: base._exec_TableScanNode(n) for n in scans}
            staging_s = time.perf_counter() - t_stage
            # a device-cache HIT staged zero host->device bytes: the span's
            # staged_rows (the warm-run proof signal) and STAGED_ROWS count
            # only freshly transferred scans; cached rows report separately
            cache_hits = sum(
                1 for n in scans if base.scan_cache.get(n.id) == "hit")
            fresh_staged = sum(
                base.scan_stats.get(n.id, staged_pages[n.id].num_rows)
                for n in scans if base.scan_cache.get(n.id) != "hit")
            total_staged = sum(
                base.scan_stats.get(n.id, staged_pages[n.id].num_rows)
                for n in scans)
            stage_sp.set("staged_rows", int(fresh_staged))
            stage_sp.set("cached_rows", int(total_staged - fresh_staged))
            stage_sp.set("cache_hits", cache_hits)
            stage_sp.set("scans", len(scans))
        # staging_df_s (bench) = phase1_s + df_apply_s: DF resolution plus
        # host domain application — the counter charges exactly that, so
        # the metric and bench's per-query field can never drift (asserted
        # by tests/test_device_cache.py::test_staging_seconds_accounting)
        M.STAGED_ROWS.inc(int(fresh_staged))
        M.STAGING_SECONDS.inc(phase1_s + base.df_apply_s)
        # in-program dynamic-filter specs + stats-sized compaction per scan.
        # Every (join, key) the optimizer annotated is applied ON DEVICE by
        # the traced collect->mask dataflow — including builds the host
        # evaluator cannot reproduce (host_eval's Unsupported shapes); the
        # host-resolved domains are used here only to (a) prune STAGING for
        # strong domains and (b) right-size the compaction capacities.
        df_hints: Dict[str, int] = {}
        device_df: Dict[int, List] = {}  # nid -> [(ch, join_id, key_idx, spec)]
        joins_by_id = {
            n.id: n for n in P.walk_plan(root) if isinstance(n, P.JoinNode)
        }
        for n in scans:
            staged_rows = base.scan_stats.get(n.id, staged_pages[n.id].num_rows)
            if not n.dynamic_filters:
                n.runtime_rows = staged_rows
                continue
            page = staged_pages[n.id]
            sel_frac = 1.0
            entries: List = []
            for join_id, key_idx, col_name in n.dynamic_filters:
                ch = n.column_names.index(col_name)
                col = page.columns[ch]
                join = joins_by_id.get(join_id)
                if col.type.is_varchar or col.hi is not None or join is None:
                    continue
                bcol_t = join.right.output_types[join.right_keys[key_idx]]
                if bcol_t.is_varchar:
                    continue  # build side cannot register this key
                dom_known = dyn.get((join_id, key_idx))
                if dom_known is not None and host_allow(n, col_name, dom_known):
                    # already physically applied at staging: an in-program
                    # mask would be provably all-true — skip the hot-path
                    # scatter+gather entirely
                    continue
                vr = col.vrange
                lut = vr is not None and (vr[1] - vr[0] + 1) <= LUT_MAX_SPAN
                if lut:
                    entries.append(
                        (ch, join_id, key_idx,
                         ("lut", int(vr[0]), int(vr[1] - vr[0] + 1))))
                else:
                    entries.append((ch, join_id, key_idx, ("range",)))
                if dom_known is not None and lut:
                    # discount only set domains the device enforces EXACTLY
                    # (the LUT); a range-degraded spec keeps far more rows
                    # than |set|/NDV, so it must not shrink the estimate,
                    # and host-applied domains already shrank staged_rows
                    sel_frac *= _dom_sel(n, col_name, dom_known)
            if not entries:
                n.runtime_rows = staged_rows
                continue
            device_df[n.id] = entries
            # base the estimate on the rows actually staged (host pruning
            # already happened); discount only the device-side narrowing
            est = max(int(staged_rows * sel_frac), 1)
            n.runtime_rows = est
            cap = 1 << max(int(est * 1.3), 1024).bit_length()
            if cap < staged_rows:
                df_hints[f"dfc:{n.id}"] = cap
        if capacity_hints is None:
            capacity_hints = stats.estimate_capacity_hints(session, root)
        from trino_tpu.adaptive.reseed import apply_reseed, reseed_enabled

        if reseed_enabled(session):
            # adaptive capacity reseeding (trino_tpu/adaptive/reseed.py):
            # the staged pages ARE the actual upstream rows — price
            # expansion-join capacities from their key histograms instead
            # of the static fudge-factor guesses, replacing over-allocation
            # AND the double-and-recompile loop in one move
            apply_reseed(session, root, staged_pages, 1, capacity_hints)
        capacity_hints.update(df_hints)
        flat_inputs: List = []
        specs: Dict[int, PageSpec] = {}
        layout: List[Tuple[int, int]] = []  # (node_id, num_arrays)
        for nid, page in staged_pages.items():
            arrays, spec = flatten_page(page)
            specs[nid] = spec
            layout.append((nid, len(arrays)))
            flat_inputs.extend(arrays)
        cq = cls(session, root, flat_inputs, specs, None, [None], [None], dict(capacity_hints))
        cq.phase1_s = phase1_s
        cq.df_apply_s = base.df_apply_s
        cq.scan_rows = dict(base.scan_stats)
        # device-cache disposition of this build's staging (warm-serving
        # telemetry: bench's warm_seconds and the microbench read these)
        cq.staging_s = staging_s
        cq.cache_hits = cache_hits
        cq.fresh_staged_rows = int(fresh_staged)
        cq._layout = layout
        cq._device_df = device_df
        cq._jit()
        return cq

    def _jit(self):
        session, root, specs = self.session, self.root, self.input_specs
        layout, hints = self._layout, self.capacity_hints
        device_df = getattr(self, "_device_df", {})
        out_spec_cell, error_codes_cell = self.out_spec_cell, self.error_codes_cell

        def run(flat):
            pages: Dict[int, Page] = {}
            i = 0
            for nid, count in layout:
                pages[nid] = unflatten_page(specs[nid], flat[i : i + count])
                i += count
            ex = PreloadedExecutor(session, pages, dict(hints), device_df)
            out_page = ex.execute(root)
            out_arrays, out_spec = flatten_page(out_page)
            out_spec_cell[0] = out_spec
            error_codes_cell[0] = [c for c, _ in ex.errors]
            return out_arrays, [f for _, f in ex.errors]

        self.raw_fn = run  # unjitted closure (for AOT/compile-check harnesses)
        self.fn = jax.jit(run)
        # compile-cache state: the jitted callable IS the cache (reused
        # executable across runs); a fresh _jit means the next call traces
        # + compiles (a miss), later calls reuse the executable (hits)
        self._executable_fresh = True

    def _profile_run(self, fresh: bool, dispatch_wall_s: float,
                     body_device_s: float, estimated: bool) -> None:
        """Feed the device profiler: one compile-ledger event per run
        (miss on fresh executables, hit on reuse) + a ``CompiledBody``
        kernel row. Best-effort — accounting never fails work."""
        try:
            from trino_tpu.cache.plan_key import plan_fingerprint
            from trino_tpu.obs.devprofiler import (
                DEVICE_PROFILER, shape_signature)

            if not self._fingerprint:
                self._fingerprint = plan_fingerprint(self.root)
            DEVICE_PROFILER.record_compile(
                "compiled", self._fingerprint,
                shape_signature(self.input_arrays),
                dispatch_wall_s if fresh else 0.0,
                "miss" if fresh else "hit", started=fresh)
            # a fresh run's dispatch wall is dominated by trace+compile —
            # charged to the compile ledger above, NOT to the kernel row,
            # so dispatch overhead stays a steady-state signal
            wall = (body_device_s if fresh
                    else dispatch_wall_s + (0.0 if estimated
                                            else body_device_s))
            key = (str(self.root.id), "CompiledBody", "compiled")
            ks = self.kernel_stats.get(key)
            if ks is None:
                ks = self.kernel_stats[key] = {
                    "planNodeId": key[0], "operator": key[1],
                    "tier": "compiled", "launches": 0, "wallS": 0.0,
                    "deviceS": 0.0, "inputBytes": 0, "outputBytes": 0,
                    "estimated": estimated}
            ks["launches"] += 1
            ks["wallS"] += wall
            ks["deviceS"] += body_device_s
            ks["estimated"] = bool(ks["estimated"] or estimated)
            DEVICE_PROFILER.count_launch(wall, body_device_s
                                         if not estimated else 0.0)
        except Exception:  # noqa: BLE001 — accounting never fails work
            pass

    def run(self) -> Page:
        """Execute; on a capacity overflow, double the offending join's
        bucket and recompile (reference analog: the spill/partition FSM of
        HashBuilderOperator — growth instead of spill)."""
        from trino_tpu.exec.executor import QueryError, raise_query_errors
        from trino_tpu.sql.planner import stats

        for _ in range(self.MAX_RECOMPILES):
            # first call on a fresh executable traces + compiles (a compile-
            # cache miss); subsequent calls reuse the jitted executable
            fresh = self._executable_fresh
            if fresh:
                try:
                    from trino_tpu.obs.devprofiler import DEVICE_PROFILER

                    DEVICE_PROFILER.compile_started()
                except Exception:  # noqa: BLE001 — accounting only
                    pass
            with tracing.span(
                    "device/compile" if fresh else "device/execute") as sp:
                t0 = time.perf_counter()
                out_arrays, error_flags = self.fn(self.input_arrays)
                device_s = time.perf_counter() - t0
                sp.set("device_seconds", round(device_s, 6))
                sp.set("staged_rows", int(sum(self.scan_rows.values())))
            # kernel/compile ledger (obs/devprofiler.py): with
            # device_profiling on, bracket the post-dispatch wait so
            # device seconds are measured, not dispatch wall
            props = getattr(self.session, "properties", None) or {}
            sync = bool(props.get("device_profiling", False))
            # estimated (no-sync) mode: a fresh run's wall is compile, not
            # kernel time — estimate the body's device share as 0 there
            body_device_s = 0.0 if fresh else device_s
            estimated = True
            if sync:
                t_sync = time.perf_counter()
                try:
                    jax.block_until_ready(out_arrays)
                except Exception:  # noqa: BLE001 — profiling never fails
                    pass
                body_device_s = time.perf_counter() - t_sync
                estimated = False
            self._profile_run(fresh, device_s, body_device_s, estimated)
            (M.COMPILE_CACHE_MISSES if fresh else M.COMPILE_CACHE_HITS).inc()
            self._executable_fresh = False
            # a fresh run's wall is dominated by trace+XLA-compile; charge
            # it to compile seconds so device_seconds stays a steady-state
            # throughput signal (mirrors the device/compile span split)
            (M.COMPILE_SECONDS if fresh else M.DEVICE_SECONDS).inc(device_s)
            codes = self.error_codes_cell[0]
            # capacity overflows first: any other flag fired on the same run
            # may be an artifact of the truncated join output
            grown = stats.grow_overflowed_hints(self.capacity_hints, codes, error_flags)
            if grown is not None:
                self.capacity_hints = grown
                self.recompiles += 1
                self._jit()
                continue
            raise_query_errors(codes, error_flags)
            return unflatten_page(self.out_spec_cell[0], out_arrays)
        raise QueryError("capacity still exceeded after recompiles (join or exchange bucket)")
