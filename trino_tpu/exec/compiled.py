"""Whole-query compilation: trace the executor once, jit, reuse.

Reference role: this is the moral equivalent of the reference's query-time
bytecode generation pipeline (``sql/gen/ExpressionCompiler`` + operator
factories baked per query by ``LocalExecutionPlanner``) — except the unit of
compilation is the *entire query body* (scan outputs -> final page), so XLA
fuses across operator boundaries (filter into scan into partial-agg, etc.),
which no per-operator engine can do.

The compiled artifact is reusable across runs with same-shaped inputs
(same splits) — the bench harness measures steady-state throughput on it.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.data.page import Page
from trino_tpu.exec.executor import Executor, QueryError
from trino_tpu.exec.page_tree import PageSpec, flatten_page, unflatten_page
from trino_tpu.sql.planner import plan as P


# strong domains (|set|/NDV at or below this) prune rows HOST-SIDE at
# staging — a cheap numpy LUT pass that cuts the host->device transfer,
# the staging bottleneck at scale; weaker domains are enforced on device
HOST_APPLY_MAX_SEL = 0.25


class StagingExecutor(Executor):
    """Stages scans for the compiled tier: constraint pushdown (including
    resolved dynamic domains — the connector can prune clustered key runs
    at the generator level) plus SELECTIVE host row filtering: strongly
    narrowing domains prune rows before the device transfer (through the
    tunnel the transfer is the staging bottleneck at scale), while weak
    domains are left for PreloadedExecutor to enforce on device. The split
    is decided per domain by ``df_host_allow`` (set in
    CompiledQuery.build from NDV selectivity estimates)."""

    df_host_allow = None  # callable(node, column, domain) -> bool


class PreloadedExecutor(Executor):
    """Executor that reads table scans from pre-staged pages (the traced
    inputs) instead of calling the connector. Scans listed in
    ``scan_filters`` apply their phase-1 dynamic-filter domains on device:
    sel &= sorted-set membership (jnp.searchsorted) or range compares, then
    compact to a stats-sized capacity — the traced-tier half of two-phase
    dynamic filtering (reference: DynamicFilterService; the compaction is
    the AdaptivePlanner-style runtime right-sizing)."""

    eager_tier = False  # runs under jax tracing: no host-side syncs
    enable_dynamic_filtering = False  # scans pre-staged before tracing
    collect_stats = False  # tracing once; per-call timing is meaningless

    def __init__(self, session, staged: Dict[int, Page], capacity_hints=None,
                 scan_filters=None):
        super().__init__(session, capacity_hints)
        self.staged = staged
        # node_id -> [(channel, spec)]; spec = ("set", jnp sorted array)
        # or ("range", lo, hi, lo_inc, hi_inc) with static bounds
        self.scan_filters = scan_filters or {}

    def _exec_TableScanNode(self, node: P.TableScanNode) -> Page:
        page = self.staged[node.id]
        filters = self.scan_filters.get(node.id)
        if not filters:
            return page
        sel = page.sel if page.sel is not None else jnp.ones(page.num_rows, bool)
        for ch, spec in filters:
            col = page.columns[ch]
            m = _device_domain_mask(col.values, spec)
            if col.nulls is not None:
                m = m & ~col.nulls
            sel = sel & m
        page = Page(list(page.columns), sel, page.replicated)
        cap = self.capacity_hints.get(f"dfc:{node.id}")
        if cap is not None:
            page = self.compact_to(page, cap, f"dfc:{node.id}")
        return page


def _device_domain_mask(values, spec):
    """Membership of ``values`` in a dynamic-filter domain, on device.
    NEVER jnp.searchsorted (log2(n) dependent random-gather passes — 2.5 s
    for 6M probes on v5e): dense-span int domains ride a staged boolean
    lookup table (ONE bounded gather); wide-span sets use the combined-sort
    merge ranks of ops/ranks.py; ranges are pure compares."""
    kind = spec[0]
    if kind == "empty":
        return jnp.zeros(values.shape[0], bool)
    if kind == "lut":
        _, lut, lo = spec
        idx = jnp.clip(values - lo, 0, lut.shape[0] - 1)
        return (values >= lo) & (values <= lo + (lut.shape[0] - 1)) & lut[idx]
    if kind == "sorted":
        from trino_tpu.ops import ranks

        arr = spec[1]
        _, counts = ranks.sorted_ranks([arr], [values])
        return counts > 0
    _, lo, hi, lo_inc, hi_inc = spec
    m = jnp.ones(values.shape[0], bool)
    if lo is not None:
        m = m & (values >= lo if lo_inc else values > lo)
    if hi is not None:
        m = m & (values <= hi if hi_inc else values < hi)
    return m


@dataclasses.dataclass
class CompiledQuery:
    session: object
    root: P.OutputNode
    input_arrays: List
    input_specs: Dict[int, PageSpec]
    fn: object  # jitted
    out_spec_cell: List
    error_codes_cell: List
    capacity_hints: Dict[str, int] = dataclasses.field(default_factory=dict)
    # two-phase execution profile: host phase-1 wall (dynamic-filter build
    # evaluation, exec/host_eval.py), host domain-application wall at the
    # scans, and per-scan staged row counts. Benchmarks charge
    # phase1_s + df_apply_s to every run: it is query work done off-device.
    phase1_s: float = 0.0
    df_apply_s: float = 0.0
    scan_rows: Dict[int, int] = dataclasses.field(default_factory=dict)

    MAX_RECOMPILES = 16  # doubling buckets: 2^16x headroom over the estimate

    @classmethod
    def build(
        cls, session, root: P.OutputNode, capacity_hints: Dict[str, int] = None
    ) -> "CompiledQuery":
        """Two-phase compile (reference: DynamicFilterService +
        AdaptivePlanner): phase 1 host-evaluates DF build sides and narrows
        probe scans BEFORE staging; actual staged cardinalities then right-
        size capacities (stats start from truth). Phase 2 traces the query
        body once over the narrowed inputs. If a run still overflows a
        bucket, ``run()`` doubles it and recompiles."""
        import time

        from trino_tpu.exec import host_eval
        from trino_tpu.sql.planner import stats

        from trino_tpu.exec.executor import dynamic_domain_map

        t0 = time.perf_counter()
        dyn = host_eval.resolve_dynamic_filters(session, root)
        phase1_s = time.perf_counter() - t0
        scans = [n for n in P.walk_plan(root) if isinstance(n, P.TableScanNode)]

        def _dom_sel(node, col_name, dom):
            """|domain| / column NDV — the narrowing strength estimate."""
            if dom.values is None:
                return 1.0
            conn = session.catalogs[node.catalog]
            cs = conn.column_stats(node.schema, node.table, col_name)
            if cs is not None and cs.ndv:
                return min(1.0, len(dom.values) / cs.ndv)
            return 1.0

        def host_allow(node, col_name, dom):
            return dom.values is not None and \
                _dom_sel(node, col_name, dom) <= HOST_APPLY_MAX_SEL

        base = StagingExecutor(session)
        base.df_host_allow = host_allow
        base.dyn_domains.update(dyn)
        staged_pages = {n.id: base._exec_TableScanNode(n) for n in scans}
        # device-side dynamic-filter specs + stats-sized compaction per scan
        df_hints: Dict[str, int] = {}
        filter_specs: Dict[int, List] = {}  # nid -> [(ch, spec)]
        filter_arrays: List[Tuple[int, int, object]] = []  # (nid, ch, np array)
        for n in scans:
            doms = dynamic_domain_map(n, dyn)
            if not doms:
                n.runtime_rows = base.scan_stats.get(n.id)
                continue
            page = staged_pages[n.id]
            staged_rows = base.scan_stats.get(n.id, page.num_rows)
            sel_frac = 1.0
            specs_for_scan: List = []
            for col_name, dom in doms.items():
                ch = n.column_names.index(col_name)
                col = page.columns[ch]
                if col.type.is_varchar or host_allow(n, col_name, dom):
                    continue  # host-applied (or inapplicable) at staging
                if dom.values is not None:
                    from trino_tpu.connector.predicate import sorted_values_array

                    dtype = np.asarray(col.values).dtype
                    sa = sorted_values_array(dom)
                    if sa.size == 0:
                        specs_for_scan.append((ch, ("empty",)))
                    else:
                        lo_v, hi_v = int(sa[0]), int(sa[-1])
                        span = hi_v - lo_v + 1
                        if sa.dtype.kind in "iu" and span <= 1 << 24:
                            lut = np.zeros(span, dtype=bool)
                            lut[(sa - lo_v).astype(np.int64)] = True
                            filter_arrays.append((n.id, ch, lut))
                            specs_for_scan.append((ch, ("lut", None, lo_v)))
                        else:
                            filter_arrays.append((n.id, ch, sa.astype(dtype)))
                            specs_for_scan.append((ch, ("sorted", None)))
                    sel_frac *= _dom_sel(n, col_name, dom)
                else:
                    specs_for_scan.append(
                        (ch, ("range", dom.low, dom.high,
                              dom.low_inclusive, dom.high_inclusive)))
            if not specs_for_scan:
                n.runtime_rows = staged_rows
                continue
            filter_specs[n.id] = specs_for_scan
            # base the estimate on the rows actually staged (host pruning
            # already happened); discount only the DEVICE-side domains
            est = max(int(staged_rows * sel_frac), 1)
            n.runtime_rows = est
            cap = 1 << max(int(est * 1.3), 1024).bit_length()
            if cap < staged_rows:
                df_hints[f"dfc:{n.id}"] = cap
        if capacity_hints is None:
            capacity_hints = stats.estimate_capacity_hints(session, root)
        capacity_hints.update(df_hints)
        flat_inputs: List = []
        specs: Dict[int, PageSpec] = {}
        layout: List[Tuple[int, int]] = []  # (node_id, num_arrays)
        for nid, page in staged_pages.items():
            arrays, spec = flatten_page(page)
            specs[nid] = spec
            layout.append((nid, len(arrays)))
            flat_inputs.extend(arrays)
        # domain set arrays ride as trailing traced inputs (values change
        # with data; sizes force a recompile anyway, so no need to bake)
        filter_layout: List[Tuple[int, int]] = [(nid, ch) for nid, ch, _ in filter_arrays]
        flat_inputs.extend(jnp.asarray(a) for _, _, a in filter_arrays)
        cq = cls(session, root, flat_inputs, specs, None, [None], [None], dict(capacity_hints))
        cq.phase1_s = phase1_s
        cq.df_apply_s = base.df_apply_s
        cq.scan_rows = dict(base.scan_stats)
        cq._layout = layout
        cq._filter_specs = filter_specs
        cq._filter_layout = filter_layout
        cq._jit()
        return cq

    def _jit(self):
        session, root, specs = self.session, self.root, self.input_specs
        layout, hints = self._layout, self.capacity_hints
        filter_specs = getattr(self, "_filter_specs", {})
        filter_layout = getattr(self, "_filter_layout", [])
        out_spec_cell, error_codes_cell = self.out_spec_cell, self.error_codes_cell

        def run(flat):
            pages: Dict[int, Page] = {}
            i = 0
            for nid, count in layout:
                pages[nid] = unflatten_page(specs[nid], flat[i : i + count])
                i += count
            # trailing inputs: sorted dynamic-filter domain arrays, slotted
            # into their ("set", arr) specs in layout order
            sf: Dict[int, List] = {}
            arr_by_slot = {}
            for (nid, ch), a in zip(filter_layout, flat[i:]):
                arr_by_slot[(nid, ch)] = a
            for nid, entries in filter_specs.items():
                out_entries = []
                for ch, spec in entries:
                    if spec[0] in ("lut", "sorted"):
                        out_entries.append(
                            (ch, (spec[0], arr_by_slot[(nid, ch)]) + spec[2:]))
                    else:
                        out_entries.append((ch, spec))
                sf[nid] = out_entries
            ex = PreloadedExecutor(session, pages, dict(hints), sf)
            out_page = ex.execute(root)
            out_arrays, out_spec = flatten_page(out_page)
            out_spec_cell[0] = out_spec
            error_codes_cell[0] = [c for c, _ in ex.errors]
            return out_arrays, [f for _, f in ex.errors]

        self.raw_fn = run  # unjitted closure (for AOT/compile-check harnesses)
        self.fn = jax.jit(run)

    def run(self) -> Page:
        """Execute; on a capacity overflow, double the offending join's
        bucket and recompile (reference analog: the spill/partition FSM of
        HashBuilderOperator — growth instead of spill)."""
        from trino_tpu.exec.executor import QueryError, raise_query_errors
        from trino_tpu.sql.planner import stats

        for _ in range(self.MAX_RECOMPILES):
            out_arrays, error_flags = self.fn(self.input_arrays)
            codes = self.error_codes_cell[0]
            # capacity overflows first: any other flag fired on the same run
            # may be an artifact of the truncated join output
            grown = stats.grow_overflowed_hints(self.capacity_hints, codes, error_flags)
            if grown is not None:
                self.capacity_hints = grown
                self._jit()
                continue
            raise_query_errors(codes, error_flags)
            return unflatten_page(self.out_spec_cell[0], out_arrays)
        raise QueryError("capacity still exceeded after recompiles (join or exchange bucket)")
