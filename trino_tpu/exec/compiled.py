"""Whole-query compilation: trace the executor once, jit, reuse.

Reference role: this is the moral equivalent of the reference's query-time
bytecode generation pipeline (``sql/gen/ExpressionCompiler`` + operator
factories baked per query by ``LocalExecutionPlanner``) — except the unit of
compilation is the *entire query body* (scan outputs -> final page), so XLA
fuses across operator boundaries (filter into scan into partial-agg, etc.),
which no per-operator engine can do.

The compiled artifact is reusable across runs with same-shaped inputs
(same splits) — the bench harness measures steady-state throughput on it.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import jax

from trino_tpu.data.page import Page
from trino_tpu.exec.executor import Executor, QueryError
from trino_tpu.exec.page_tree import PageSpec, flatten_page, unflatten_page
from trino_tpu.sql.planner import plan as P


class PreloadedExecutor(Executor):
    """Executor that reads table scans from pre-staged pages (the traced
    inputs) instead of calling the connector."""

    eager_tier = False  # runs under jax tracing: no host-side syncs
    enable_dynamic_filtering = False  # scans pre-staged before tracing
    collect_stats = False  # tracing once; per-call timing is meaningless

    def __init__(self, session, staged: Dict[int, Page], capacity_hints=None):
        super().__init__(session, capacity_hints)
        self.staged = staged

    def _exec_TableScanNode(self, node: P.TableScanNode) -> Page:
        return self.staged[node.id]


@dataclasses.dataclass
class CompiledQuery:
    session: object
    root: P.OutputNode
    input_arrays: List
    input_specs: Dict[int, PageSpec]
    fn: object  # jitted
    out_spec_cell: List
    error_codes_cell: List
    capacity_hints: Dict[str, int] = dataclasses.field(default_factory=dict)
    # two-phase execution profile: host phase-1 wall (dynamic-filter build
    # evaluation, exec/host_eval.py), host domain-application wall at the
    # scans, and per-scan staged row counts. Benchmarks charge
    # phase1_s + df_apply_s to every run: it is query work done off-device.
    phase1_s: float = 0.0
    df_apply_s: float = 0.0
    scan_rows: Dict[int, int] = dataclasses.field(default_factory=dict)

    MAX_RECOMPILES = 16  # doubling buckets: 2^16x headroom over the estimate

    @classmethod
    def build(
        cls, session, root: P.OutputNode, capacity_hints: Dict[str, int] = None
    ) -> "CompiledQuery":
        """Two-phase compile (reference: DynamicFilterService +
        AdaptivePlanner): phase 1 host-evaluates DF build sides and narrows
        probe scans BEFORE staging; actual staged cardinalities then right-
        size capacities (stats start from truth). Phase 2 traces the query
        body once over the narrowed inputs. If a run still overflows a
        bucket, ``run()`` doubles it and recompiles."""
        import time

        from trino_tpu.exec import host_eval
        from trino_tpu.sql.planner import stats

        t0 = time.perf_counter()
        dyn = host_eval.resolve_dynamic_filters(session, root)
        phase1_s = time.perf_counter() - t0
        base = Executor(session)
        base.dyn_domains.update(dyn)
        scans = [n for n in P.walk_plan(root) if isinstance(n, P.TableScanNode)]
        staged_pages = {n.id: base._exec_TableScanNode(n) for n in scans}
        for n in scans:
            n.runtime_rows = base.scan_stats.get(n.id)
        if capacity_hints is None:
            capacity_hints = stats.estimate_capacity_hints(session, root)
        flat_inputs: List = []
        specs: Dict[int, PageSpec] = {}
        layout: List[Tuple[int, int]] = []  # (node_id, num_arrays)
        for nid, page in staged_pages.items():
            arrays, spec = flatten_page(page)
            specs[nid] = spec
            layout.append((nid, len(arrays)))
            flat_inputs.extend(arrays)
        cq = cls(session, root, flat_inputs, specs, None, [None], [None], dict(capacity_hints))
        cq.phase1_s = phase1_s
        cq.df_apply_s = base.df_apply_s
        cq.scan_rows = dict(base.scan_stats)
        cq._layout = layout
        cq._jit()
        return cq

    def _jit(self):
        session, root, specs = self.session, self.root, self.input_specs
        layout, hints = self._layout, self.capacity_hints
        out_spec_cell, error_codes_cell = self.out_spec_cell, self.error_codes_cell

        def run(flat):
            pages: Dict[int, Page] = {}
            i = 0
            for nid, count in layout:
                pages[nid] = unflatten_page(specs[nid], flat[i : i + count])
                i += count
            ex = PreloadedExecutor(session, pages, dict(hints))
            out_page = ex.execute(root)
            out_arrays, out_spec = flatten_page(out_page)
            out_spec_cell[0] = out_spec
            error_codes_cell[0] = [c for c, _ in ex.errors]
            return out_arrays, [f for _, f in ex.errors]

        self.raw_fn = run  # unjitted closure (for AOT/compile-check harnesses)
        self.fn = jax.jit(run)

    def run(self) -> Page:
        """Execute; on a capacity overflow, double the offending join's
        bucket and recompile (reference analog: the spill/partition FSM of
        HashBuilderOperator — growth instead of spill)."""
        from trino_tpu.exec.executor import QueryError, raise_query_errors
        from trino_tpu.sql.planner import stats

        for _ in range(self.MAX_RECOMPILES):
            out_arrays, error_flags = self.fn(self.input_arrays)
            codes = self.error_codes_cell[0]
            # capacity overflows first: any other flag fired on the same run
            # may be an artifact of the truncated join output
            grown = stats.grow_overflowed_hints(self.capacity_hints, codes, error_flags)
            if grown is not None:
                self.capacity_hints = grown
                self._jit()
                continue
            raise_query_errors(codes, error_flags)
            return unflatten_page(self.out_spec_cell[0], out_arrays)
        raise QueryError("capacity still exceeded after recompiles (join or exchange bucket)")
