"""Pipelined cold staging: the engine every staging tier runs.

BENCH_r05's dominant remaining cost is the COLD run (q3_sf10: 22.7 s
staging vs 1.17 s device execute) — the warm-HBM device cache (PR 7)
only fixes the second run. In the reference this work is inherently
parallel: the connector SPI hands out *splits* and tasks run concurrent
page-source drivers over them. This module is that split-driver plane for
the staged-execution model, used by all three staging tiers (eager /
compiled phase-1 in ``exec/executor.py``, worker fragments in
``server/task.py``, SPMD shards in ``parallel/spmd.py``):

- **parallel split reads** — ``stage_splits`` fans ``connector.scan`` +
  host-applied domain pruning out over a shared process-wide IO pool (the
  PR 12 ``io_pool`` pattern), so scan+decode of split k+2 overlaps the
  decode/transfer of split k; results assemble in split order, so the
  staged arrays are BIT-IDENTICAL to the serial path;
- **a host-RAM columnar cache consult per split** — misses fill
  :data:`~trino_tpu.devcache.hostcache.HOST_CACHE` (single-flight), hits
  skip the connector entirely, so an HBM eviction or a re-sharding pays
  transfer only (``staging/host-cache`` span);
- **double-buffered host->device transfer** — ``blocked_transfer`` chunks
  the assembled columns into byte-bounded row blocks and issues the async
  ``jax.device_put`` for block k+1 before block k is consumed by the
  device-side assembly, bounding pinned-host pressure and overlapping
  PCIe/ICI DMA with host work on real accelerators (CPU meshes degrade to
  a plain copy); the pre-transfer projection (scan's column list) and the
  host-applied constraint pruning mean only needed columns/rows cross;
- **adaptive split sizing** — ``target_split_count`` derives the
  ``get_splits`` target from estimated table bytes / the
  ``staging_split_bytes`` session property, so tiny tables don't pay
  fan-out overhead and huge tables don't underparallelize.

Observability: the ``device/staging`` wall decomposes into the
``staging/scan`` / ``staging/decode`` / ``staging/transfer`` /
``staging/host-cache`` sub-spans (all mapped into the phase ledger's
``device-staging`` bucket) and the
``trino_tpu_staging_phase_seconds_total{phase}`` counter;
``trino_tpu_staging_seconds_total`` keeps its exact per-tier charging
semantics (bench's ``staging_df_s`` identity is drift-tested).
"""
from __future__ import annotations

import dataclasses
import os
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from trino_tpu.obs import metrics as M
from trino_tpu.obs import trace as tracing

# default target bytes per split when the session does not override
# staging_split_bytes — sized so a handful of splits cover a warm L3-sized
# table and a TPC-H sf10 lineitem fans out to tens of splits
DEFAULT_SPLIT_BYTES = 64 << 20
# fan-out ceiling: beyond this, per-split constant costs (gencache entry
# churn, dictionary merges) dominate any remaining overlap win
MAX_TARGET_SPLITS = 64
# target bytes per double-buffered transfer block
TRANSFER_BLOCK_BYTES = 32 << 20
# above this, a column transfers single-shot instead of blocked: the
# blocked path's device-side concat transiently holds blocks + output
# (~2x the column) — a peak the eviction machinery cannot see — so giant
# columns keep the 1x-peak path until the hardware round sizes a real
# bound (env TRINO_TPU_STAGING_BLOCKED_MAX_BYTES)
BLOCKED_MAX_BYTES = int(os.environ.get(
    "TRINO_TPU_STAGING_BLOCKED_MAX_BYTES") or 256 << 20)
# double-buffer depth: un-materialized device_puts allowed in flight
# before the next block issues (bounds pinned-host/DMA-staging memory)
_INFLIGHT_PUTS = 2
# shared scan pool capacity (all sessions of this process; per-staging
# concurrency is bounded separately by staging_parallelism)
POOL_WORKERS = max(4, int(os.environ.get("TRINO_TPU_STAGING_POOL") or 16))

_pool_cell: List = []
_pool_lock = threading.Lock()


def staging_pool():
    """The process-wide staging IO pool, created on first use (the PR 12
    ``CoordinatorServer.io_pool`` pattern: one long-lived pool instead of
    per-staging thread churn)."""
    if _pool_cell:
        return _pool_cell[0]
    from concurrent.futures import ThreadPoolExecutor

    with _pool_lock:
        if not _pool_cell:
            _pool_cell.append(ThreadPoolExecutor(
                max_workers=POOL_WORKERS, thread_name_prefix="staging-io"))
    return _pool_cell[0]


def staging_parallelism(session) -> int:
    """Per-staging fan-out width: the ``staging_parallelism`` session
    property, or (0 = auto) min(8, cpu count). 1 = the serial path."""
    props = getattr(session, "properties", None) or {}
    v = int(props.get("staging_parallelism") or 0)
    if v > 0:
        return v
    return min(8, os.cpu_count() or 1)


def split_bytes_target(session) -> int:
    props = getattr(session, "properties", None) or {}
    return int(props.get("staging_split_bytes") or DEFAULT_SPLIT_BYTES)


# (connector -> {(schema, table): (estimate, monotonic stamp)}): the
# estimate is consulted on three paths per query (coordinator split
# assignment, phase-1 host evaluation, the staging loaders) and some
# connectors' table_row_count is a real query (sqlite: COUNT(*)) —
# memoized briefly since split sizing only needs the order of magnitude
# (correctness always comes from data_version keys, never split counts)
_estimate_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_estimate_lock = threading.Lock()
_ESTIMATE_TTL_S = 10.0


def estimated_table_bytes(conn, schema: str, table: str) -> Optional[int]:
    """Row-count × FULL-table-width estimate (8 bytes/column —
    dictionary codes and narrowed ints are smaller, limbed decimals
    bigger; split sizing only needs the order of magnitude). Width comes
    from the table metadata, NOT the scan's projection: split boundaries
    must be projection-INVARIANT so two scans of the same table (Q18's
    double lineitem read) request identical ranges and the generator
    range cache (connector/gencache.py) accumulates their columns in one
    entry instead of re-synthesizing per projection."""
    now = time.monotonic()
    try:
        with _estimate_lock:
            per = _estimate_cache.get(conn)
            hit = per.get((schema, table)) if per else None
    except TypeError:  # non-weakrefable connector: probe uncached
        per, hit = None, None
    if hit is not None and now - hit[1] <= _ESTIMATE_TTL_S:
        return hit[0]
    try:
        rows = conn.table_row_count(schema, table)
    except Exception:  # noqa: BLE001 — stats are best-effort
        rows = None
    if not rows:
        est = None
    else:
        try:
            meta = conn.get_table(schema, table)
            width = len(meta.columns) if meta is not None else None
        except Exception:  # noqa: BLE001
            width = None
        est = int(rows) * 8 * max(int(width or 4), 1)
    try:
        with _estimate_lock:
            _estimate_cache.setdefault(conn, {})[(schema, table)] = (est, now)
    except TypeError:
        pass
    return est


def target_split_count(session, conn, schema: str, table: str,
                       floor: int = 1, handle=None) -> int:
    """Adaptive ``get_splits`` target: ceil(estimated bytes /
    staging_split_bytes), clamped to [floor, MAX_TARGET_SPLITS]. Unknown
    row counts keep the caller's floor (no fan-out gamble on tables the
    connector cannot size). A pushdown ``handle`` disables the
    adaptation entirely (the caller's floor stands): a pushed
    aggregation/TopN/limit is a GLOBAL statement whose guarantee would
    become per-split — the guard lives HERE so no call site can forget
    it."""
    if handle is not None:
        return max(1, floor)
    est = estimated_table_bytes(conn, schema, table)
    if est is None:
        return max(1, floor)
    per = max(1, split_bytes_target(session))
    target = (est + per - 1) // per
    return max(max(1, floor), min(MAX_TARGET_SPLITS, int(target)))


# ------------------------------------------------------------- fan-out
# scan_one marker: this split is mid-flight in ANOTHER staging; the
# calling thread joins that flight after the fan-out drains
_INFLIGHT = object()


@dataclasses.dataclass
class StageProfile:
    """Per-staging timing/disposition record. ``scan_s``/``prune_s`` are
    CUMULATIVE thread seconds (the host work done, however overlapped);
    the ``*_wall_s`` fields are calling-thread wall. overlap =
    (scan_s + prune_s) / fanout_wall_s > 1 means the fan-out genuinely
    ran split reads concurrently."""

    splits: int = 0
    parallelism: int = 1
    host_hits: int = 0
    scan_s: float = 0.0
    prune_s: float = 0.0
    hostcache_wall_s: float = 0.0
    fanout_wall_s: float = 0.0
    decode_wall_s: float = 0.0
    transfer_wall_s: float = 0.0
    transfer_blocks: int = 0

    def overlap(self) -> float:
        if self.fanout_wall_s <= 0:
            return 0.0
        return (self.scan_s + self.prune_s) / self.fanout_wall_s


def _map_ordered(fn: Callable[[int], object], n: int, width: int) -> List:
    """Run ``fn(0..n-1)`` with at most ``width`` in flight on the shared
    pool, returning results in index order (completion order never leaks
    into the output — the bit-identity contract). width<=1 degrades to
    the plain serial loop."""
    if width <= 1 or n <= 1:
        return [fn(i) for i in range(n)]
    from concurrent.futures import FIRST_COMPLETED, wait

    pool = staging_pool()
    results: List = [None] * n
    pending = {}
    nxt = 0
    try:
        while nxt < n and len(pending) < width:
            pending[pool.submit(fn, nxt)] = nxt
            nxt += 1
        while pending:
            done, _ = wait(list(pending), return_when=FIRST_COMPLETED)
            for fut in done:
                i = pending.pop(fut)
                results[i] = fut.result()  # re-raises the worker error
                if nxt < n:
                    pending[pool.submit(fn, nxt)] = nxt
                    nxt += 1
    finally:
        for fut in pending:
            fut.cancel()
    return results


def stage_splits(session, node, conn, splits, constraint,
                 prune: Optional[Callable] = None,
                 applied_domains: Optional[Dict] = None,
                 ) -> Tuple[List[Dict], StageProfile]:
    """Scan + decode every split, pipelined: host-tier probe first (hits
    skip the connector), then the missing splits fan out over the shared
    pool — each running ``conn.scan`` + ``prune`` (the tier's host-applied
    domain subset, which is also baked into the host-cache key) and
    filling the host tier single-flighted. Returns the per-split decoded
    column dicts IN SPLIT ORDER plus the profile."""
    from trino_tpu import devcache

    prof = StageProfile(splits=len(splits),
                        parallelism=staging_parallelism(session))
    if not splits:
        return [], prof
    datas: List = [None] * len(splits)
    keys = devcache.host_split_keys(session, node, constraint,
                                    applied_domains or {}, splits)
    if any(k is not None for k in keys):
        t0 = time.perf_counter()
        with tracing.span("staging/host-cache", table=node.table) as sp:
            for i, k in enumerate(keys):
                if k is None:
                    continue
                ent = devcache.HOST_CACHE.peek(k)
                if ent is not None:
                    datas[i] = ent.value
                    prof.host_hits += 1
            sp.set("hits", prof.host_hits)
            sp.set("splits", len(splits))
        prof.hostcache_wall_s = time.perf_counter() - t0
        M.STAGING_PHASE_SECONDS.inc(prof.hostcache_wall_s, "host-cache")
    missing = [i for i in range(len(splits)) if datas[i] is None]
    if not missing:
        return datas, prof
    acc_lock = threading.Lock()
    columns = list(node.column_names)

    def make_loader(i: int):
        def loader():
            t0 = time.perf_counter()
            data = conn.scan(splits[i], columns, constraint=constraint)
            t1 = time.perf_counter()
            if prune is not None:
                (data,) = prune([data])
            t2 = time.perf_counter()
            with acc_lock:
                prof.scan_s += t1 - t0
                prof.prune_s += t2 - t1
            rows = len(next(iter(data.values())).values) if data else 0
            return data, rows, devcache.split_data_bytes(data), 1

        return loader

    def scan_one(i: int):
        loader = make_loader(i)
        if keys[i] is not None:
            # wait=False: a split another staging is already loading must
            # not park this shared-pool thread behind that flight (one
            # slow cold staging would otherwise pin every pool slot and
            # freeze the process's whole staging plane) — in-flight
            # splits resolve on the calling thread below
            ent, _disposition = devcache.HOST_CACHE.lookup_or_stage(
                keys[i], loader, wait=False,
                admit_bytes=devcache.host_admit_budget(session))
            return ent.value if ent is not None else _INFLIGHT
        return loader()[0]

    t0 = time.perf_counter()
    with tracing.span("staging/scan", table=node.table) as sp:
        for j, data in zip(missing,
                           _map_ordered(lambda k: scan_one(missing[k]),
                                        len(missing), prof.parallelism)):
            datas[j] = data
        for j in missing:
            if datas[j] is _INFLIGHT:
                # follower wait happens HERE, on the staging's own calling
                # thread — bounded by FLIGHT_WAIT_S with the stuck-leader
                # bypass, and never occupying a shared pool slot
                ent, _disposition = devcache.HOST_CACHE.lookup_or_stage(
                    keys[j], make_loader(j),
                    admit_bytes=devcache.host_admit_budget(session))
                datas[j] = ent.value
        prof.fanout_wall_s = time.perf_counter() - t0
        sp.set("splits", len(missing))
        sp.set("parallelism", prof.parallelism)
        sp.set("scan_s", round(prof.scan_s, 6))
        sp.set("prune_s", round(prof.prune_s, 6))
        sp.set("overlap", round(prof.overlap(), 3))
    M.STAGING_PHASE_SECONDS.inc(prof.fanout_wall_s, "scan")
    return datas, prof


# ----------------------------------------------------------- assembly
def assemble_host_columns(column_names, column_types, datas):
    """Concat the per-split decoded columns host-side (merging varchar
    dictionaries via spi.concat_column_data — split order is preserved,
    so sortedness survives and the result is bit-identical to a serial
    single-shot scan). Returns the ColumnData list, or None for the
    empty/all-dead case."""
    from trino_tpu.connector.spi import concat_column_data

    if not datas:
        return None
    cols = []
    for name in column_names:
        cols.append(concat_column_data([d[name] for d in datas]))
    if cols and len(np.asarray(cols[0].values)) == 0:
        return None
    return cols


def blocked_transfer(profile: Optional[StageProfile] = None,
                     block_bytes: int = TRANSFER_BLOCK_BYTES):
    """A ``transfer(np.ndarray) -> device array`` that double-buffers:
    rows chunk into ~``block_bytes`` blocks, every block's async
    ``jax.device_put`` is issued before the first is consumed, and the
    device-side concat assembles them — so DMA of block k+1 overlaps the
    consumption of block k, and the result is bitwise identical to a
    single-shot put. Arrays at/below two blocks take the single-shot fast
    path (no device-side copy for the small-table common case), and
    arrays over BLOCKED_MAX_BYTES do too: the blocked path's device-side
    concat transiently holds blocks + output (~2x the column) regardless
    of the put window — see the constant. The in-flight PUT window is
    what is double-buffered: at most _INFLIGHT_PUTS un-materialized
    host->device copies exist at once, bounding pinned-host/DMA-staging
    pressure while the transfer engine runs ahead of the consumer. The
    rows axis is the LAST axis (flat columns are 1-D; SPMD stacked
    shards are [ndev, rows])."""
    import jax
    import jax.numpy as jnp

    from trino_tpu.obs.flowledger import FLOW_LEDGER
    from trino_tpu.obs.memledger import MEMORY_LEDGER, POOL_DEVICE

    def transfer(arr: np.ndarray):
        arr = np.asarray(arr)
        n = arr.shape[-1] if arr.ndim else 0
        row_bytes = (arr.nbytes // n) if n else 0
        block_rows = max(1, block_bytes // max(1, row_bytes)) if n else 0
        t0 = time.perf_counter()
        if not n or n <= 2 * block_rows or arr.nbytes > BLOCKED_MAX_BYTES:
            out = jnp.asarray(arr)
            FLOW_LEDGER.record_transfer(
                "staging-transfer", "staging", int(arr.nbytes),
                time.perf_counter() - t0, pages=1, src="host", dst="device",
                direction="send", status="single-shot")
            return out
        axis = arr.ndim - 1
        # the blocked path's transient scratch (blocks + concat output,
        # ~2x the column — the BLOCKED_MAX_BYTES comment) is attributed
        # to the ledger's staging owner for its lifetime: this is
        # device-pool pressure the eviction machinery cannot see
        MEMORY_LEDGER.record_event(
            "reserve", POOL_DEVICE, "staging", int(arr.nbytes))
        try:
            blocks = []
            for bi, i in enumerate(range(0, n, block_rows)):
                idx = (slice(None),) * axis + (slice(i, i + block_rows),)
                # force block bi - _INFLIGHT_PUTS resident BEFORE issuing
                # block bi, so at most _INFLIGHT_PUTS un-materialized puts
                # ever exist at once (forcing after the issue would briefly
                # hold one extra)
                if bi >= _INFLIGHT_PUTS:
                    blocks[bi - _INFLIGHT_PUTS].block_until_ready()
                blocks.append(jax.device_put(arr[idx]))
            if profile is not None:
                profile.transfer_blocks += len(blocks)
            out = jnp.concatenate(blocks, axis=axis)
            FLOW_LEDGER.record_transfer(
                "staging-transfer", "staging", int(arr.nbytes),
                time.perf_counter() - t0, pages=len(blocks), src="host",
                dst="device", direction="send", status="blocked")
            return out
        finally:
            MEMORY_LEDGER.record_event(
                "release", POOL_DEVICE, "staging", int(arr.nbytes))

    return transfer


def page_from_host_columns(column_types, host_cols, transfer):
    """Host ColumnData list -> device Page: physical int32 narrowing for
    provably-fitting int64 columns (table-wide vrange, the
    data/page.py rule: table-wide ranges keep every split and shard
    dtype-uniform), then the injected transfer per array.
    Nested and two-limb columns take the single-shot path (their
    children/limb layout is recursive)."""
    from trino_tpu.data.page import Column, Page, fits_int32
    from trino_tpu.exec.executor import _column_from_data

    if host_cols is None:
        return Page.all_dead(column_types)
    cols = []
    for typ, cd in zip(column_types, host_cols):
        if typ.is_nested or cd.hi is not None:
            cols.append(_column_from_data(cd))
            continue
        vals = np.asarray(cd.values)
        if vals.dtype == np.int64 and fits_int32(cd.vrange):
            vals = vals.astype(np.int32)
        cols.append(Column(
            typ,
            transfer(vals),
            transfer(np.asarray(cd.nulls)) if cd.nulls is not None else None,
            cd.dictionary,
            cd.vrange,
            ascending=bool(getattr(cd, "sorted", False)),
        ))
    return Page(cols)


def staged_scan_page(session, node, conn, splits, constraint,
                     prune: Optional[Callable] = None,
                     applied_domains: Optional[Dict] = None,
                     ) -> Tuple[object, int, StageProfile]:
    """The whole pipeline for one scan: parallel split reads (host tier
    consulted per split) -> host assembly -> double-buffered transfer.
    Returns ``(Page, scanned_rows, StageProfile)``. This is the loader
    body behind every device-cache miss in the eager/compiled and worker
    tiers (the SPMD tier shares stage_splits + blocked_transfer but owns
    its shard stacking)."""
    datas, prof = stage_splits(session, node, conn, splits, constraint,
                               prune=prune, applied_domains=applied_domains)
    scanned = sum(
        len(next(iter(d.values())).values) if d else 0 for d in datas)
    t0 = time.perf_counter()
    with tracing.span("staging/decode", table=node.table) as sp:
        host_cols = assemble_host_columns(
            node.column_names, node.column_types, datas)
        prof.decode_wall_s = time.perf_counter() - t0
        sp.set("rows", scanned)
    M.STAGING_PHASE_SECONDS.inc(prof.decode_wall_s, "decode")
    t0 = time.perf_counter()
    with tracing.span("staging/transfer", table=node.table) as sp:
        page = page_from_host_columns(
            node.column_types, host_cols, blocked_transfer(prof))
        prof.transfer_wall_s = time.perf_counter() - t0
        sp.set("blocks", prof.transfer_blocks)
    M.STAGING_PHASE_SECONDS.inc(prof.transfer_wall_s, "transfer")
    return page, scanned, prof
