"""Device-memory accounting and the spill decision.

Reference: ``lib/trino-memory-context`` (``AggregatedMemoryContext.java:30``,
``LocalMemoryContext.java:31``) + ``memory/QueryContext.java:58`` — operator
reservations roll up to a per-query pool; exceeding revocable memory
triggers spill (``HashBuilderOperator.java:162-177`` FSM,
``SpillableHashAggregationBuilder``).

TPU-first redesign (SURVEY.md §7.2 step 9): page shapes are static, so
"reservation" is exact arithmetic on array bytes — no JVM-style object
walking. The spill tier is HOST RAM, not disk: an over-budget join or
aggregation hash-partitions its inputs host-side into P passes and runs
each pass on device (the partitioned-spill design of
``GenericPartitioningSpiller`` collapsed into a loop over compiled kernels).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from trino_tpu.obs import trace as tracing
from trino_tpu.obs.memledger import MEMORY_LEDGER, POOL_DEVICE


def page_bytes(page) -> int:
    """Exact device bytes of a Page (static shapes make this precise)."""
    total = 0
    for c in page.columns:
        total += c.values.size * c.values.dtype.itemsize
        if c.nulls is not None:
            total += c.nulls.size  # bool = 1 byte
    if page.sel is not None:
        total += page.sel.size
    return total


@dataclasses.dataclass
class SpillEvent:
    node_id: int
    kind: str  # 'join' | 'aggregation'
    partitions: int
    projected_bytes: int


class MemoryContext:
    """Per-query device-memory budget + peak tracking + spill log.

    ``owner`` is the memory-ledger attribution tag (``query:<id>``):
    when set, every peak INCREASE lands in the process
    :data:`~trino_tpu.obs.memledger.MEMORY_LEDGER` as a ``reserve``
    event for that owner (deltas, so the owner's live bytes track the
    peak), and the spill decision's cache yield is charged to the query
    (``shed_bytes`` / ``yields`` feed queryStats.memory through the
    stats spine)."""

    MAX_SPILL_PARTITIONS = 64

    def __init__(self, budget_bytes: Optional[int] = None,
                 owner: Optional[str] = None):
        self.budget = int(budget_bytes) if budget_bytes else None
        self.owner = owner
        self.peak = 0
        self.spills: List[SpillEvent] = []
        # revocable bytes shed on THIS query's behalf + yield-event count
        self.shed_bytes = 0
        self.yields = 0

    @property
    def enabled(self) -> bool:
        return self.budget is not None

    def observe(self, nbytes: int) -> None:
        if nbytes > self.peak:
            delta = nbytes - self.peak
            self.peak = nbytes
            if self.owner:
                MEMORY_LEDGER.record_event(
                    "reserve", POOL_DEVICE, self.owner, delta)

    def release(self) -> None:
        """Query done: the owner's live bytes drop to zero (its peak and
        event history stay in the ledger for attribution)."""
        if self.owner and self.peak:
            MEMORY_LEDGER.record_event(
                "release", POOL_DEVICE, self.owner, self.peak, reason="done")

    def spill_partitions(self, projected_bytes: int) -> int:
        """1 = fits in budget; else the number of hash partitions (power of
        two) whose per-pass working set fits."""
        self.observe(projected_bytes)
        if self.budget is None or projected_bytes <= self.budget:
            with tracing.span("memory/reserve") as sp:
                sp.set("bytes", int(projected_bytes))
                if self.owner:
                    sp.set("owner", self.owner)
            return 1
        parts = 1
        while parts < self.MAX_SPILL_PARTITIONS and projected_bytes // parts > self.budget:
            parts *= 2
        # the device table cache is the REVOCABLE tier: a query about to
        # pay a spill reclaims warm-table HBM first, so cached tables
        # yield to running work instead of competing with it. The yield is
        # sized to the PER-PASS working set — what will actually be
        # resident once the join runs partitioned — never the raw
        # projection (a 64 GB projection over an 8 GB budget must not
        # flush a whole warm cache its passes will never displace).
        from trino_tpu.devcache import DEVICE_CACHE

        with tracing.span("memory/shed") as sp:
            freed = DEVICE_CACHE.yield_bytes(
                projected_bytes // parts, reason="spill")
            sp.set("requested", int(projected_bytes // parts))
            sp.set("freed", int(freed))
            sp.set("partitions", parts)
            if self.owner:
                sp.set("owner", self.owner)
        self.shed_bytes += freed
        self.yields += 1
        return parts

    def record_spill(self, node_id: int, kind: str, partitions: int, projected: int) -> None:
        self.spills.append(SpillEvent(node_id, kind, partitions, projected))


# ------------------------------------------------- host-side partitioning

_M1 = 0xBF58476D1CE4E5B9
_M2 = 0x94D049BB133111EB
_NULL_HASH = 0x9E3779B97F4A7C15
_MASK = (1 << 64) - 1


def _mix64_np(x):
    import numpy as np

    x = x.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(_M1)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(_M2)
        return x ^ (x >> np.uint64(31))


def partition_page_host(page, key_channels, parts: int, pid=None):
    """Split a page into ``parts`` hash partitions by key columns, host-side
    (numpy) — the spill write path. Equal keys co-locate (same splitmix64
    combine as the device exchange, parallel/exchange.py, so a spilled join
    and an exchanged join agree on placement); dead rows are dropped.

    Returns a list of ``parts`` compacted Pages (1-row all-dead when empty).
    """
    import jax.numpy as jnp
    import numpy as np

    from trino_tpu.data.page import Column, Page

    n = page.num_rows
    live = np.ones(n, bool) if page.sel is None else np.asarray(page.sel)
    if pid is None:
        h = np.zeros(n, np.uint64)
        for ch in key_channels:
            col = page.columns[ch]
            # hash the LOW limb only: equal values always share it, and a
            # column's hi-limb PRESENCE is data-dependent (one join side may
            # carry it while the other doesn't) — mixing hi in would place
            # equal keys in different partitions across sides/producers
            k = _mix64_np(np.asarray(col.values).astype(np.int64))
            if col.nulls is not None:
                k = np.where(np.asarray(col.nulls), np.uint64(_NULL_HASH), k)
            h = _mix64_np(h ^ k)
        pid = (h % np.uint64(parts)).astype(np.int64)
    else:
        pid = np.asarray(pid)
    from trino_tpu.data.page import host_take

    out = []
    for p in range(parts):
        idx = np.nonzero(live & (pid == p))[0]
        if len(idx) == 0:
            out.append(_pad_like(page))
            continue
        # host_take handles two-limb and nested columns uniformly
        out.append(Page([host_take(c, idx) for c in page.columns], None, page.replicated))
    return out


def _pad_like(page):
    """1-row all-dead page with the same column dtypes/dictionaries."""
    import jax.numpy as jnp

    from trino_tpu.data.page import Column, Page

    cols = [
        Column(
            c.type,
            jnp.zeros((1,) + c.values.shape[1:], c.values.dtype),
            None,
            c.dictionary,
            c.vrange,
        )
        for c in page.columns
    ]
    return Page(cols, jnp.zeros((1,), bool), page.replicated)
