"""Page <-> flat array-list conversion (pytree-style) for jit boundaries.

The dynamic parts of a Page (values, null masks, selection, nested child
columns) flatten to a list of arrays; the static parts (types,
dictionaries, vranges) go into a PageSpec captured in the compiled
closure. Nested (array/map/row) columns flatten RECURSIVELY: the parent's
lengths/placeholder array first, then each child column — static shapes
throughout, so a traced program can ship nested results across the jit
boundary (the Block-tree serialization role of the reference's
``spi/block`` serde, re-targeted at XLA buffers).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax.numpy as jnp

from trino_tpu import types as T
from trino_tpu.data.dictionary import Dictionary
from trino_tpu.data.page import Column, Page


@dataclasses.dataclass
class ColSpec:
    """Static description of one column's flat layout."""

    type: T.Type
    dictionary: Optional[Dictionary]
    has_nulls: bool
    vrange: Optional[tuple] = None
    ascending: bool = False
    has_hi: bool = False
    children: Optional[List["ColSpec"]] = None

    def count(self) -> int:
        return (1 + (1 if self.has_nulls else 0) + (1 if self.has_hi else 0)
                + sum(k.count() for k in (self.children or ())))


@dataclasses.dataclass
class PageSpec:
    col_specs: List[ColSpec]
    has_sel: bool
    live_prefix: bool = False

    # legacy accessors (older callers address columns by parallel lists)
    @property
    def types(self) -> List[T.Type]:
        return [c.type for c in self.col_specs]

    @property
    def dictionaries(self):
        return [c.dictionary for c in self.col_specs]

    @property
    def has_nulls(self):
        return [c.has_nulls for c in self.col_specs]

    @property
    def vranges(self):
        return [c.vrange for c in self.col_specs]

    def array_count(self) -> int:
        """How many flat arrays a page with this spec occupies."""
        return sum(c.count() for c in self.col_specs) + (1 if self.has_sel else 0)


def _flatten_col(c: Column, arrays: List[jnp.ndarray]) -> ColSpec:
    arrays.append(c.values)
    if c.nulls is not None:
        arrays.append(c.nulls)
    if c.hi is not None:
        arrays.append(c.hi)
    children = None
    if c.children is not None:
        children = [_flatten_col(k, arrays) for k in c.children]
    return ColSpec(
        c.type, c.dictionary, c.nulls is not None, c.vrange,
        bool(c.ascending), c.hi is not None, children,
    )


def _unflatten_col(spec: ColSpec, arrays: List[jnp.ndarray], i: int
                   ) -> Tuple[Column, int]:
    vals = arrays[i]
    i += 1
    nulls = None
    if spec.has_nulls:
        nulls = arrays[i]
        i += 1
    hi = None
    if spec.has_hi:
        hi = arrays[i]
        i += 1
    children = None
    if spec.children is not None:
        children = []
        for ks in spec.children:
            k, i = _unflatten_col(ks, arrays, i)
            children.append(k)
    return Column(spec.type, vals, nulls, spec.dictionary, spec.vrange,
                  spec.ascending, hi=hi, children=children), i


def flatten_page(page: Page) -> Tuple[List[jnp.ndarray], PageSpec]:
    arrays: List[jnp.ndarray] = []
    col_specs = [_flatten_col(c, arrays) for c in page.columns]
    if page.sel is not None:
        arrays.append(page.sel)
    return arrays, PageSpec(col_specs, page.sel is not None, page.live_prefix)


def unflatten_page(spec: PageSpec, arrays: List[jnp.ndarray]) -> Page:
    cols: List[Column] = []
    i = 0
    for cs in spec.col_specs:
        c, i = _unflatten_col(cs, arrays, i)
        cols.append(c)
    sel = arrays[i] if spec.has_sel else None
    return Page(cols, sel, live_prefix=spec.live_prefix)
