"""Page <-> flat array-list conversion (pytree-style) for jit boundaries.

The dynamic parts of a Page (values, null masks, selection) flatten to a
list of arrays; the static parts (types, dictionaries) go into a PageSpec
captured in the compiled closure.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax.numpy as jnp

from trino_tpu import types as T
from trino_tpu.data.dictionary import Dictionary
from trino_tpu.data.page import Column, Page


@dataclasses.dataclass
class PageSpec:
    types: List[T.Type]
    dictionaries: List[Optional[Dictionary]]
    has_nulls: List[bool]
    has_sel: bool
    # static (min, max) bounds per column (data/page.py Column.vrange) —
    # static metadata, so it crosses the jit boundary in the spec
    vranges: Optional[List[Optional[tuple]]] = None
    # per-column sort-order flags + the page's live-prefix property
    # (data/page.py) — static metadata licensing sort-free fast paths
    ascending: Optional[List[bool]] = None
    live_prefix: bool = False
    # per-column long-decimal high-limb presence (data/page.py Column.hi)
    has_hi: Optional[List[bool]] = None

    def array_count(self) -> int:
        """How many flat arrays a page with this spec occupies."""
        return (
            len(self.types) + sum(self.has_nulls) + (1 if self.has_sel else 0)
            + sum(self.has_hi or ())
        )


def flatten_page(page: Page) -> Tuple[List[jnp.ndarray], PageSpec]:
    arrays: List[jnp.ndarray] = []
    has_nulls = []
    has_hi = []
    for c in page.columns:
        if c.type.is_nested:
            raise NotImplementedError(
                "array/map columns across the jit page boundary")
        arrays.append(c.values)
        if c.nulls is not None:
            arrays.append(c.nulls)
            has_nulls.append(True)
        else:
            has_nulls.append(False)
        if c.hi is not None:
            arrays.append(c.hi)
            has_hi.append(True)
        else:
            has_hi.append(False)
    if page.sel is not None:
        arrays.append(page.sel)
    spec = PageSpec(
        [c.type for c in page.columns],
        [c.dictionary for c in page.columns],
        has_nulls,
        page.sel is not None,
        [c.vrange for c in page.columns],
        [c.ascending for c in page.columns],
        page.live_prefix,
        has_hi,
    )
    return arrays, spec


def unflatten_page(spec: PageSpec, arrays: List[jnp.ndarray]) -> Page:
    cols: List[Column] = []
    i = 0
    vranges = spec.vranges or [None] * len(spec.types)
    asc = spec.ascending or [False] * len(spec.types)
    has_hi = spec.has_hi or [False] * len(spec.types)
    for t, d, hn, vr, a, hh in zip(
            spec.types, spec.dictionaries, spec.has_nulls, vranges, asc, has_hi):
        vals = arrays[i]
        i += 1
        nulls = None
        if hn:
            nulls = arrays[i]
            i += 1
        hi = None
        if hh:
            hi = arrays[i]
            i += 1
        cols.append(Column(t, vals, nulls, d, vr, a, hi=hi))
    sel = arrays[i] if spec.has_sel else None
    return Page(cols, sel, live_prefix=spec.live_prefix)
