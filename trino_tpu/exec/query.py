"""Query lifecycle: parse -> analyze/plan -> optimize -> execute.

Reference: ``execution/SqlQueryExecution.java:393`` (start -> analyze ->
planQuery -> planDistribution -> schedule); collapsed here to the local path.
EXPLAIN mirrors sql/planner/planprinter/PlanPrinter.
"""
from __future__ import annotations

from trino_tpu.exec.executor import Executor, QueryResult
from trino_tpu.sql.parser import ast
from trino_tpu.sql.parser.parser import parse_statement
from trino_tpu.sql.planner.optimizer import optimize
from trino_tpu.sql.planner.plan import format_plan
from trino_tpu.sql.planner.planner import Planner


def plan_sql(session, sql: str):
    stmt = parse_statement(sql)
    if isinstance(stmt, ast.Explain):
        raise ValueError("use explain_query")
    if not isinstance(stmt, ast.Query):
        return stmt  # SHOW et al, handled by run_query
    root = Planner(session).plan(stmt)
    return optimize(root, session)


def run_query(session, sql: str) -> QueryResult:
    stmt = parse_statement(sql)
    if isinstance(stmt, ast.Explain):
        text = explain_query(session, None, stmt.mode, stmt=stmt.statement)
        return QueryResult(["Query Plan"], [], [(line,) for line in text.split("\n")])
    if isinstance(stmt, ast.ShowTables):
        return _show_tables(session, stmt)
    if isinstance(stmt, ast.ShowSchemas):
        return _show_schemas(session, stmt)
    if isinstance(stmt, ast.ShowColumns):
        return _show_columns(session, stmt)
    if not isinstance(stmt, ast.Query):
        raise ValueError(f"unsupported statement {type(stmt).__name__}")
    root = Planner(session).plan(stmt)
    root = optimize(root, session)
    page = Executor(session).execute_checked(root)
    return QueryResult(root.column_names, page.columns, page.to_pylist())


def explain_query(session, sql, mode: str = "logical", stmt=None) -> str:
    if stmt is None:
        stmt = parse_statement(sql)
        if isinstance(stmt, ast.Explain):
            mode = stmt.mode
            stmt = stmt.statement
    root = Planner(session).plan(stmt)
    root = optimize(root, session)
    if mode == "distributed":
        from trino_tpu.sql.planner.fragmenter import fragment_plan, format_fragments

        return format_fragments(fragment_plan(root, session))
    return format_plan(root)


def _show_tables(session, stmt):
    if stmt.schema:
        parts = stmt.schema
        catalog = parts[0] if len(parts) == 2 else session.properties.get("catalog", "tpch")
        schema = parts[-1]
    else:
        catalog = session.properties.get("catalog", "tpch")
        schema = session.properties.get("schema", "tiny")
    conn = session.catalogs[catalog]
    rows = [(t,) for t in conn.list_tables(schema)]
    return QueryResult(["Table"], [], rows)


def _show_schemas(session, stmt):
    catalog = stmt.catalog or session.properties.get("catalog", "tpch")
    conn = session.catalogs[catalog]
    return QueryResult(["Schema"], [], [(s,) for s in conn.list_schemas()])


def _show_columns(session, stmt):
    parts = [p.lower() for p in stmt.table]
    catalog = session.properties.get("catalog", "tpch")
    schema = session.properties.get("schema", "tiny")
    if len(parts) == 3:
        catalog, schema, table = parts
    elif len(parts) == 2:
        schema, table = parts
    else:
        (table,) = parts
    meta = session.catalogs[catalog].get_table(schema, table)
    if meta is None:
        raise ValueError(f"table not found: {table}")
    return QueryResult(
        ["Column", "Type"], [], [(c.name, str(c.type)) for c in meta.columns]
    )
