"""Query lifecycle: parse -> analyze/plan -> optimize -> execute.

Reference: ``execution/SqlQueryExecution.java:393`` (start -> analyze ->
planQuery -> planDistribution -> schedule); collapsed here to the local path.
EXPLAIN mirrors sql/planner/planprinter/PlanPrinter.
"""
from __future__ import annotations

from trino_tpu.exec.executor import Executor, QueryResult
from trino_tpu.sql.parser import ast
from trino_tpu.sql.parser.parser import parse_statement
from trino_tpu.sql.planner.optimizer import optimize
from trino_tpu.sql.planner.plan import format_plan
from trino_tpu.sql.planner.planner import Planner


def plan_sql(session, sql: str):
    from trino_tpu.obs import trace as tracing

    with tracing.span("parse"):
        stmt = parse_statement(sql)
    if isinstance(stmt, ast.Explain):
        raise ValueError("use explain_query")
    if not isinstance(stmt, ast.Query):
        return stmt  # SHOW et al, handled by run_query
    udfs = getattr(session, "udfs", None)
    if udfs:
        from trino_tpu.sql.routines import expand_udfs

        stmt = expand_udfs(stmt, udfs)
    with tracing.span("analyze/plan"):
        root = Planner(session).plan(stmt)
    with tracing.span("optimize"):
        return optimize(root, session)


def run_query(session, sql: str) -> QueryResult:
    return _dispatch_statement(session, parse_statement(sql), sql=sql)


def dispatch_statement(session, stmt) -> QueryResult:
    """Run an already-parsed statement (the coordinator's EXECUTE path
    dispatches the stored prepared AST without re-parsing)."""
    return _dispatch_statement(session, stmt)


def bind_parameters(stmt, params):
    """Substitute ``?`` placeholders with the EXECUTE ... USING expressions
    (reference: planner/ParameterRewriter): a generic rewrite over the
    frozen AST. Arity must match exactly — too many bindings is as much a
    caller bug as too few."""
    from trino_tpu.server.prepared import count_parameters

    need = count_parameters(stmt)
    if len(params) != need:
        raise ValueError(
            f"prepared statement expects {need} parameters, "
            f"got {len(params)}")
    return _bind_parameters(stmt, params)


def _bind_parameters(stmt, params):
    import dataclasses as _dc

    def rewrite(node):
        if isinstance(node, ast.Parameter):
            if node.index >= len(params):
                raise ValueError(
                    f"prepared statement needs {node.index + 1} parameters, "
                    f"got {len(params)}")
            return params[node.index]
        if isinstance(node, tuple):
            return tuple(rewrite(x) for x in node)
        if _dc.is_dataclass(node) and not isinstance(node, type):
            changes = {}
            for f in _dc.fields(node):
                v = getattr(node, f.name)
                nv = rewrite(v)
                if nv is not v:
                    changes[f.name] = nv
            return _dc.replace(node, **changes) if changes else node
        return node

    return rewrite(stmt)


def _dispatch_statement(session, stmt, sql=None) -> QueryResult:
    if isinstance(stmt, ast.Explain):
        if stmt.analyze:
            text = explain_analyze(session, stmt.statement,
                                   verbose=stmt.verbose)
        else:
            text = explain_query(session, None, stmt.mode, stmt=stmt.statement)
        return QueryResult(["Query Plan"], [], [(line,) for line in text.split("\n")])
    if isinstance(stmt, ast.CreateTable):
        return _create_table(session, stmt)
    if isinstance(stmt, ast.CreateTableAs):
        return _create_table_as(session, stmt)
    if isinstance(stmt, ast.Insert):
        return _insert(session, stmt)
    if isinstance(stmt, ast.DropTable):
        return _drop_table(session, stmt)
    if isinstance(stmt, (ast.CreateMaterializedView,
                         ast.RefreshMaterializedView,
                         ast.DropMaterializedView)):
        # materialized views (trino_tpu/matview/): the embedded path runs
        # the REFRESH's defining query on the local executor; the
        # coordinator intercepts these statements earlier to execute the
        # refresh through its distributed path
        from trino_tpu.matview import lifecycle as mv_lifecycle

        columns, rows = mv_lifecycle.dispatch_mv_statement(
            session, stmt, sql=sql)
        return QueryResult(columns, [], rows)
    if isinstance(stmt, ast.Delete):
        return _delete(session, stmt)
    if isinstance(stmt, ast.Update):
        return _update(session, stmt)
    if isinstance(stmt, ast.CreateFunction):
        from trino_tpu.sql.routines import (
            RoutineError, UdfDef, expand_udfs, validate)

        name = stmt.name[-1].lower()
        if name in session.udfs and not stmt.or_replace:
            raise RoutineError(f"function already exists: {name}")
        # early binding: routine calls INSIDE the body expand at creation
        # (so validation sees a closed expression and later redefinitions
        # of inner routines don't change this one)
        body = expand_udfs(stmt.body, session.udfs)
        udf = UdfDef(name, tuple(stmt.params), stmt.returns, body)
        validate(udf)
        session.udfs[name] = udf
        return QueryResult(["result"], [], [("CREATE FUNCTION",)])
    if isinstance(stmt, ast.DropFunction):
        name = stmt.name[-1].lower()
        if name not in session.udfs:
            if stmt.if_exists:
                return QueryResult(["result"], [], [("DROP FUNCTION",)])
            raise ValueError(f"function not found: {name}")
        del session.udfs[name]
        return QueryResult(["result"], [], [("DROP FUNCTION",)])
    if isinstance(stmt, ast.Prepare):
        # reference: execution/PrepareTask — the statement is stored parsed;
        # parameters bind at EXECUTE time (sql/tree/Parameter)
        if not hasattr(session, "prepared_statements"):
            session.prepared_statements = {}
        session.prepared_statements[stmt.name] = stmt.statement
        return QueryResult(["result"], [], [("PREPARE",)])
    if isinstance(stmt, ast.ExecutePrepared):
        prepared = getattr(session, "prepared_statements", {}).get(stmt.name)
        if prepared is None:
            raise ValueError(f"prepared statement not found: {stmt.name}")
        bound = bind_parameters(prepared, stmt.params)
        return _dispatch_statement(session, bound)
    if isinstance(stmt, ast.Deallocate):
        store = getattr(session, "prepared_statements", {})
        if stmt.name not in store:
            raise ValueError(f"prepared statement not found: {stmt.name}")
        del store[stmt.name]
        return QueryResult(["result"], [], [("DEALLOCATE",)])
    if isinstance(stmt, ast.Call):
        return _call_procedure(session, stmt)
    if isinstance(stmt, ast.StartTransaction):
        from trino_tpu.exec import transaction as txn_mod

        txn_mod.begin(session)
        return QueryResult(["result"], [], [("START TRANSACTION",)])
    if isinstance(stmt, ast.Commit):
        txn = getattr(session, "transaction", None)
        if txn is None:
            raise ValueError("no transaction in progress")
        txn.commit()
        return QueryResult(["result"], [], [("COMMIT",)])
    if isinstance(stmt, ast.Rollback):
        txn = getattr(session, "transaction", None)
        if txn is None:
            raise ValueError("no transaction in progress")
        txn.rollback()
        return QueryResult(["result"], [], [("ROLLBACK",)])
    if isinstance(stmt, ast.SetSession):
        session.set_property(stmt.name, stmt.value)
        return QueryResult(["result"], [], [("SET SESSION",)])
    if isinstance(stmt, ast.ResetSession):
        from trino_tpu.client.properties import SYSTEM_SESSION_PROPERTIES

        meta = SYSTEM_SESSION_PROPERTIES.get(stmt.name)
        if meta is None:
            raise ValueError(f"session property '{stmt.name}' does not exist")
        if meta.default is None:
            session.properties.pop(stmt.name, None)
        else:
            session.properties[stmt.name] = meta.default
        return QueryResult(["result"], [], [("RESET SESSION",)])
    if isinstance(stmt, ast.ShowSession):
        from trino_tpu.client.properties import SYSTEM_SESSION_PROPERTIES

        rows = [
            (name, str(session.properties.get(name, meta.default)),
             str(meta.default), meta.py_type.__name__, meta.description)
            for name, meta in sorted(SYSTEM_SESSION_PROPERTIES.items())
        ]
        return QueryResult(["Name", "Value", "Default", "Type", "Description"], [], rows)
    if isinstance(stmt, ast.ShowTables):
        return _show_tables(session, stmt)
    if isinstance(stmt, ast.ShowSchemas):
        return _show_schemas(session, stmt)
    if isinstance(stmt, ast.ShowColumns):
        return _show_columns(session, stmt)
    if not isinstance(stmt, ast.Query):
        raise ValueError(f"unsupported statement {type(stmt).__name__}")
    udfs = getattr(session, "udfs", None)
    if udfs:
        from trino_tpu.sql.routines import expand_udfs

        stmt = expand_udfs(stmt, udfs)
    root = Planner(session).plan(stmt)
    root = optimize(root, session)
    # materialized-view substitution (trino_tpu/matview/): a fresh MV
    # whose definition matches a plan subtree serves as a storage scan
    from trino_tpu.matview.substitute import substitute_plan

    root, _mv_notes = substitute_plan(session, root)
    page = Executor(session).execute_checked(root)
    return QueryResult(root.column_names, page.columns, page.to_pylist())


def mv_notes_header(notes) -> str:
    """EXPLAIN header lines for the materialized-view substitution
    decisions: the scan annotation shows WHERE a view substituted; these
    lines show the freshness verdict (including fallbacks, which leave
    no mark on the plan)."""
    lines = []
    for n in notes or ():
        if n["result"] == "substituted":
            extra = (f" (prefix {n['prefix']} columns)"
                     if n.get("prefix") else "")
            lines.append(f"Materialized view {n['view']}: substituted"
                         f"{extra}")
        else:
            lines.append(f"Materialized view {n['view']}: fallback "
                         f"({n['result']}: {n['reason']})")
    return "\n".join(lines) + "\n" if lines else ""


def explain_query(session, sql, mode: str = "logical", stmt=None) -> str:
    if stmt is None:
        stmt = parse_statement(sql)
        if isinstance(stmt, ast.Explain):
            mode = stmt.mode
            stmt = stmt.statement
    root = Planner(session).plan(stmt)
    root = optimize(root, session)
    from trino_tpu.matview.substitute import substitute_plan

    root, mv_notes = substitute_plan(session, root)
    header = mv_notes_header(mv_notes)
    if mode == "distributed":
        from trino_tpu.sql.planner.fragmenter import fragment_plan, format_fragments

        return header + format_fragments(fragment_plan(root, session))
    return header + format_plan(root)


def _resolve_table_name(session, parts, write: bool = False):
    parts = [p.lower() for p in parts]
    catalog = session.properties.get("catalog", "tpch")
    schema = session.properties.get("schema", "tiny")
    if len(parts) == 3:
        catalog, schema, table = parts
    elif len(parts) == 2:
        schema, table = parts
    else:
        (table,) = parts
    if catalog not in session.catalogs:
        raise ValueError(f"catalog not found: {catalog}")
    if write:
        ac = getattr(session, "access_control", None)
        if ac is not None:
            ac.check_can_write(session.identity, catalog, schema, table)
        txn = getattr(session, "transaction", None)
        if txn is not None:
            # writes inside an explicit transaction go to its overlay
            # (exec/transaction.py; reference: TransactionManager handles)
            txn.enlist(catalog)
    return session.catalogs[catalog], schema, table


def _resolve_table_named(session, parts, write: bool = False):
    """Like _resolve_table_name but also returns the resolved CATALOG NAME
    (DML rewrites re-plan against the table and must name the same
    catalog, never re-derive it by connector identity)."""
    parts_l = [p.lower() for p in parts]
    catalog = session.properties.get("catalog", "tpch")
    if len(parts_l) == 3:
        catalog = parts_l[0]
    conn, schema, table = _resolve_table_name(session, parts, write=write)
    return conn, catalog, schema, table


def _call_procedure(session, stmt):
    """CALL catalog.schema.procedure(args...) (reference:
    execution/CallTask: resolve the procedure through connector metadata,
    evaluate constant arguments, invoke). Arguments analyze against an
    empty scope and must constant-fold — a procedure is a control-plane
    action, not a row pipeline."""
    from trino_tpu.sql.analyzer.expr_analyzer import ExprAnalyzer
    from trino_tpu.sql.analyzer.scope import Scope
    from trino_tpu.sql.planner.planner import _fold_constant

    parts = [p.lower() for p in stmt.name]
    catalog = session.properties.get("catalog", "tpch")
    schema = session.properties.get("schema", "tiny")
    if len(parts) == 3:
        catalog, schema, proc = parts
    elif len(parts) == 2:
        schema, proc = parts
    else:
        (proc,) = parts
    conn = session.catalogs.get(catalog)
    if conn is None:
        raise ValueError(f"catalog not found: {catalog}")
    fn = conn.procedure(schema, proc)
    if fn is None:
        raise ValueError(
            f"procedure not registered: {catalog}.{schema}.{proc}")
    analyzer = ExprAnalyzer(Scope([], None))
    values = []
    for e in stmt.args:
        c = _fold_constant(analyzer.analyze(e))
        if c is None:
            raise ValueError(
                f"CALL {catalog}.{schema}.{proc}: arguments must be "
                "constants")
        v = c.value
        if v is not None and c.type.is_decimal:
            v = float(v) / (10 ** c.type.scale)
        values.append(v)
    message = fn(session, *values)
    return QueryResult(["result"], [], [(message or "CALL",)])


def _create_table(session, stmt):
    """CREATE TABLE (reference: execution/CreateTableTask.java)."""
    from trino_tpu import types as T

    conn, schema, table = _resolve_table_name(session, stmt.name, write=True)
    if conn.get_table(schema, table) is not None:
        if stmt.not_exists:
            return QueryResult(["result"], [], [("CREATE TABLE",)])
        raise ValueError(f"table already exists: {schema}.{table}")
    schema_def = [(n.lower(), T.parse_type(t)) for n, t in stmt.columns]
    conn.create_table(schema, table, schema_def, [])
    return QueryResult(["result"], [], [("CREATE TABLE",)])


def _create_table_as(session, stmt):
    """CTAS (reference: the TableWriterOperator/TableFinishOperator pair,
    collapsed: the source query runs eagerly, rows sink via the connector
    write SPI — distributed scaled writers are the SPMD tier's upgrade)."""
    conn, schema, table = _resolve_table_name(session, stmt.name, write=True)
    if conn.get_table(schema, table) is not None:
        if stmt.not_exists:
            return QueryResult(["rows"], [], [(0,)])
        raise ValueError(f"table already exists: {schema}.{table}")
    root = Planner(session).plan(stmt.query)
    root = optimize(root, session)
    page = Executor(session).execute_checked(root)
    rows = page.to_pylist()
    schema_def = list(zip([n.lower() for n in root.column_names], root.source.output_types))
    conn.create_table(schema, table, schema_def, rows)
    return QueryResult(["rows"], [], [(len(rows),)])


def _insert(session, stmt):
    """INSERT INTO (reference: execution/InsertTask + page sink)."""
    conn, schema, table = _resolve_table_name(session, stmt.name, write=True)
    meta = conn.get_table(schema, table)
    if meta is None:
        raise ValueError(f"table not found: {schema}.{table}")
    root = Planner(session).plan(stmt.query)
    root = optimize(root, session)
    page = Executor(session).execute_checked(root)
    rows = page.to_pylist()
    table_cols = [c.name for c in meta.columns]
    src_width = len(root.column_names)
    if stmt.columns:
        named = [c.lower() for c in stmt.columns]
        if len(named) != src_width:
            raise ValueError("INSERT column list does not match query width")
        if len(set(named)) != len(named):
            raise ValueError("INSERT column list contains duplicates")
        for c in named:
            if c not in table_cols:
                raise ValueError(f"insert column does not exist: {c}")
        pos = {c: i for i, c in enumerate(named)}
        # unmentioned columns get NULL (reference Insert semantics)
        rows = [
            tuple(r[pos[c]] if c in pos else None for c in table_cols)
            for r in rows
        ]
    elif src_width != len(table_cols):
        raise ValueError(
            f"INSERT has {src_width} expressions but table has {len(table_cols)} columns")
    _check_insert_types(meta, stmt.columns, root.source.output_types)
    n = conn.insert_rows(schema, table, rows)
    return QueryResult(["rows"], [], [(n,)])


def _check_insert_types(meta, named_columns, src_types):
    """Reject sources that cannot widen into the target column type
    (reference: Trino's 'Insert query has mismatched column types'). A
    source type is accepted when it IS the target or implicitly coerces to
    it (common super type == target): bigint -> decimal is fine, decimal ->
    bigint is a silent-truncation hazard and is rejected."""
    from trino_tpu import types as T

    if named_columns:
        targets = [
            meta.columns[meta.column_index(c.lower())].type for c in named_columns
        ]
    else:
        targets = [c.type for c in meta.columns]
    for i, (src, tgt) in enumerate(zip(src_types, targets)):
        if src == tgt or src == T.UNKNOWN:
            continue
        # the reference's implicit-coercion rule (TypeCoercion.canCoerce):
        # src must widen EXACTLY into tgt — common super type IS the target,
        # or an integer fits the decimal's integral digits
        if T.common_super_type(src, tgt) == tgt:
            continue
        int_digits = {T.INTEGER: 10, T.BIGINT: 19}.get(src)
        if (int_digits is not None and tgt.is_decimal
                and tgt.precision - tgt.scale >= int_digits):
            continue
        raise ValueError(
            f"insert column {i}: mismatched types — query produces {src}, "
            f"table expects {tgt}")


def _delete(session, stmt):
    """DELETE FROM t [WHERE p]: rows where p IS TRUE are removed; the KEPT
    set (NOT p OR p IS NULL) is computed by the engine and the table
    overwritten (reference: sql/tree/Delete; the whole-table rewrite is
    the simple-connector analog of the row-change/merge machinery)."""
    conn, catalog, schema, table = _resolve_table_named(
        session, stmt.name, write=True)
    meta = conn.get_table(schema, table)
    if meta is None:
        raise ValueError(f"table not found: {schema}.{table}")
    total = conn.table_row_count(schema, table)
    if total is None:  # stats are optional SPI surface: count via the engine
        total = _dml_select_rows(session, catalog, schema, table, meta,
                                 count_only=True)
    if stmt.where is None:
        kept = []
    else:
        keep_pred = ast.LogicalBinary(
            "or", ast.Not(stmt.where), ast.IsNull(stmt.where))
        kept = _dml_select_rows(session, catalog, schema, table, meta,
                                where=keep_pred)
    conn.overwrite_rows(schema, table, kept)
    return QueryResult(["rows"], [], [(total - len(kept),)])


def _update(session, stmt):
    """UPDATE t SET c = e [WHERE p]: every row rewrites as
    CASE WHEN p THEN e ELSE c END per assigned column (reference:
    sql/tree/Update). Assignment types must COERCE to the column type
    (widening only), matching INSERT's check."""
    from trino_tpu import types as T
    from trino_tpu.sql.analyzer.expr_analyzer import ExprAnalyzer
    from trino_tpu.sql.analyzer.scope import Field, Scope

    conn, catalog, schema, table = _resolve_table_named(
        session, stmt.name, write=True)
    meta = conn.get_table(schema, table)
    if meta is None:
        raise ValueError(f"table not found: {schema}.{table}")
    assigns = {c.lower(): e for c, e in stmt.assignments}
    col_types = {m.name: m.type for m in meta.columns}
    scope = Scope([Field(m.name, m.type, table) for m in meta.columns], None)
    analyzer = ExprAnalyzer(scope)
    for c, e in assigns.items():
        if c not in col_types:
            raise ValueError(f"update column does not exist: {c}")
        et = analyzer.analyze(e).type
        target = col_types[c]
        if et == T.UNKNOWN or T.common_super_type(et, target) == target:
            continue
        if et.is_decimal and target.is_decimal:
            # store-assignment (SQL): decimal precision may NARROW — the
            # cast's runtime DECIMAL_OVERFLOW check protects values that
            # do not fit (amt = amt * 2 grows the static precision even
            # though the values usually still fit)
            continue
        raise ValueError(
            f"UPDATE assignment to {c}: {et} does not coerce to {target}")
    # ONE scan computes the rewritten rows AND the match count (an extra
    # boolean column, stripped before the overwrite)
    rows = _dml_select_rows(session, catalog, schema, table, meta,
                            assigns=assigns, assign_where=stmt.where,
                            with_match_flag=stmt.where is not None)
    if stmt.where is None:
        updated = len(rows)
    else:
        updated = sum(1 for r in rows if r[-1])
        rows = [r[:-1] for r in rows]
    conn.overwrite_rows(schema, table, rows)
    return QueryResult(["rows"], [], [(updated,)])


def _dml_select_rows(session, catalog, schema, table, meta, where=None,
                     assigns=None, assign_where=None, count_only=False,
                     with_match_flag=False):
    """Evaluate a rewrite SELECT built at the AST level over the target
    table with the engine's full expression machinery: the kept rows of a
    DELETE, the updated projection of an UPDATE (plus an optional
    predicate-match flag column), or a row count."""
    table_rel = ast.Table((catalog, schema, table))
    if count_only:
        items = (ast.SelectItem(
            ast.FunctionCall("count", (), is_star=True), "c"),)
    else:
        items = []
        for cm in meta.columns:
            col = ast.Identifier((cm.name,))
            e = col
            if assigns and cm.name in assigns:
                e = (assigns[cm.name] if assign_where is None
                     else ast.SearchedCase(((assign_where, assigns[cm.name]),), col))
                e = ast.Cast(e, str(cm.type))  # keep the column's type
            items.append(ast.SelectItem(e, cm.name))
        if with_match_flag and assign_where is not None:
            items.append(ast.SelectItem(
                ast.SearchedCase(
                    ((assign_where, ast.Literal("boolean", True)),),
                    ast.Literal("boolean", False)), "__match"))
        items = tuple(items)
    q = ast.Query(body=ast.QuerySpec(
        select_items=items, distinct=False, from_=table_rel, where=where,
        group_by=(), having=None))
    root = Planner(session).plan(q)
    root = optimize(root, session)
    page = Executor(session).execute_checked(root)
    rows = page.to_pylist()
    return rows[0][0] if count_only else rows


def _drop_table(session, stmt):
    conn, schema, table = _resolve_table_name(session, stmt.name, write=True)
    if conn.get_table(schema, table) is None:
        if stmt.if_exists:
            return QueryResult(["result"], [], [("DROP TABLE",)])
        raise ValueError(f"table not found: {schema}.{table}")
    conn.drop_table(schema, table)
    return QueryResult(["result"], [], [("DROP TABLE",)])


def explain_analyze(session, stmt, verbose: bool = False) -> str:
    """EXPLAIN ANALYZE: execute, then print the plan annotated with the
    executor's per-operator stats (reference: ExplainAnalyzeOperator +
    PlanPrinter.java:183 with OperatorStats injected). The header's wall
    time covers planning AND execution, broken down so it agrees with the
    query-level span totals (plan/optimize time used to be silently
    dropped). ``verbose`` adds device detail: per-node bytes/peaks plus the
    compiled tier's compile-cache disposition over this run."""
    import time as _time

    from trino_tpu.obs import metrics as M

    t_plan = _time.perf_counter()
    root = Planner(session).plan(stmt)
    root = optimize(root, session)
    from trino_tpu.matview.substitute import substitute_plan

    root, mv_notes = substitute_plan(session, root)
    plan_s = _time.perf_counter() - t_plan
    ex = Executor(session)
    hits0, misses0 = (M.COMPILE_CACHE_HITS.value(),
                      M.COMPILE_CACHE_MISSES.value())
    t0 = _time.perf_counter()
    ex.execute_checked(root)
    exec_s = _time.perf_counter() - t0
    from trino_tpu.exec.operator_stats import wall_time_header

    header = [wall_time_header(plan_s, exec_s)]
    if ex.memory.budget is not None:
        header.append(
            f"Device memory budget: {ex.memory.budget // 1024}KiB,"
            f" peak working set: {ex.memory.peak // 1024}KiB,"
            f" spills: {len(ex.memory.spills)}"
        )
    else:
        header.append(f"Peak working set: {ex.memory.peak // 1024}KiB (no budget)")
    if verbose:
        # the compile-cache delta is PROCESS-WIDE over this run's window
        # (the registry has no per-query partitions): labeled as such so
        # concurrent compiled-tier activity is never misread as this query
        staged = sum(ex.scan_stats.values())
        header.append(
            f"Device detail: staged rows={staged},"
            f" compile cache hits/misses (process-wide during run)="
            f"{int(M.COMPILE_CACHE_HITS.value() - hits0)}/"
            f"{int(M.COMPILE_CACHE_MISSES.value() - misses0)},"
            f" dynamic-filter host seconds={ex.df_apply_s * 1e3:.1f}ms")
    mv_header = mv_notes_header(mv_notes)
    return mv_header + "\n".join(header) + "\n" + format_plan(
        root, executor=ex, verbose=verbose)


def _show_tables(session, stmt):
    if stmt.schema:
        parts = stmt.schema
        catalog = parts[0] if len(parts) == 2 else session.properties.get("catalog", "tpch")
        schema = parts[-1]
    else:
        catalog = session.properties.get("catalog", "tpch")
        schema = session.properties.get("schema", "tiny")
    conn = session.catalogs[catalog]
    rows = [(t,) for t in conn.list_tables(schema)]
    return QueryResult(["Table"], [], rows)


def _show_schemas(session, stmt):
    catalog = stmt.catalog or session.properties.get("catalog", "tpch")
    conn = session.catalogs[catalog]
    return QueryResult(["Schema"], [], [(s,) for s in conn.list_schemas()])


def _show_columns(session, stmt):
    parts = [p.lower() for p in stmt.table]
    catalog = session.properties.get("catalog", "tpch")
    schema = session.properties.get("schema", "tiny")
    if len(parts) == 3:
        catalog, schema, table = parts
    elif len(parts) == 2:
        schema, table = parts
    else:
        (table,) = parts
    meta = session.catalogs[catalog].get_table(schema, table)
    if meta is None:
        raise ValueError(f"table not found: {table}")
    return QueryResult(
        ["Column", "Type"], [], [(c.name, str(c.type)) for c in meta.columns]
    )
