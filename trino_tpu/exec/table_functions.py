"""Table functions (polymorphic table function invocation).

Reference: ``spi/function/table/`` (ConnectorTableFunction,
TableFunctionProcessorProvider) + the built-in ``sequence`` /
``exclude_columns`` functions under ``operator/table/``. Resolution order:
the session's current catalog connector first (the SPI hook
``Connector.table_function``), then the engine built-ins — mirroring the
reference's catalog-scoped function resolution.

A table function here returns (column names, column types, rows); the
planner materializes it as a constant relation. Functions over TABLE
arguments (exclude_columns' input => TABLE(...)) are not yet modeled —
the argument grammar accepts scalar positional/named arguments.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from trino_tpu import types as T

MAX_ROWS = 10_000_000  # generation guard for sequence()


class TableFunctionError(ValueError):
    pass


def _sequence(args: List, named: Dict) -> Tuple[List[str], List[T.Type], List[tuple]]:
    """sequence(start, stop[, step]) -> one bigint column
    ``sequential_number``, inclusive bounds (reference:
    operator/table/Sequence.java semantics). Positional and named
    arguments MERGE by parameter position (mixing is fine; providing the
    same parameter both ways is an error)."""
    slots = {"start": None, "stop": None, "step": None}
    order = ("start", "stop", "step")
    if len(args) > 3:
        raise TableFunctionError("sequence(start, stop[, step])")
    for pos, v in enumerate(args):
        slots[order[pos]] = v
    for k, v in named.items():
        if k not in slots:
            raise TableFunctionError(f"sequence() has no parameter {k!r}")
        if slots[k] is not None:
            raise TableFunctionError(
                f"sequence() parameter {k!r} given both positionally and by name")
        slots[k] = v
    if slots["stop"] is None:
        raise TableFunctionError("sequence() needs stop")
    start = slots["start"] if slots["start"] is not None else 0
    stop = slots["stop"]
    step = slots["step"] if slots["step"] is not None else 1
    start, stop, step = int(start), int(stop), int(step)
    if step == 0:
        raise TableFunctionError("sequence() step must not be zero")
    n = max(0, (stop - start) // step + 1)
    if n > MAX_ROWS:
        raise TableFunctionError(
            f"sequence() would produce {n} rows (limit {MAX_ROWS})")
    rows = [(start + i * step,) for i in range(n)]
    return ["sequential_number"], [T.BIGINT], rows


_BUILTINS = {
    "sequence": _sequence,
}


def resolve(session, name: str, args: List, named: Dict
            ) -> Tuple[List[str], List[T.Type], List[tuple]]:
    """Evaluate table function ``name`` with constant arguments."""
    catalog = (session.properties or {}).get("catalog")
    conn = session.catalogs.get(catalog) if catalog else None
    if conn is not None:
        fn = conn.table_function(name)
        if fn is not None:
            return fn(args, named)
    builtin = _BUILTINS.get(name)
    if builtin is None:
        raise TableFunctionError(f"unknown table function: {name}")
    return builtin(args, named)
