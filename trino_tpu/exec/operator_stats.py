"""Typed per-operator execution statistics + the task→stage→query rollup.

Reference: ``operator/OperatorStats.java`` (one record per operator
instance: input/output positions+bytes, wall/CPU nanos, peak memory)
aggregated by ``TaskStats`` → ``StageStats`` → ``QueryStats``
(``execution/QueryStats.java``), which feed the Web UI query page and
``EXPLAIN ANALYZE``'s plan annotations (PlanPrinter stats injection).

Here one ``OperatorStats`` accumulates across *repeated* executions of the
same plan node (a node re-executed per probe batch or per split ADDS, never
overwrites), so every rollup below is a plain sum/max and the math is
additive by construction:

- worker: ``Executor.node_stats`` (node id → OperatorStats), snapshot into
  the task's status payload (``server/task.py``);
- coordinator: task snapshots merge per stage (``rollup_tasks_to_stage``)
  and stages merge per query (``rollup_stages_to_query``) inside the
  status-polling loop (``server/coordinator.py``);
- printers: ``format_plan`` / ``format_fragments`` annotate plan nodes from
  a ``Dict[int, OperatorStats]`` regardless of which process produced it.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List


@dataclasses.dataclass
class OperatorStats:
    """Cumulative stats for one plan node (identified by plan-node id)."""

    node_id: int
    operator: str  # operator kind: "TableScan", "Join", "Aggregation", ...
    input_rows: int = 0
    output_rows: int = 0
    output_bytes: int = 0
    wall_s: float = 0.0
    device_s: float = 0.0  # device-execute seconds attributed to this node
    peak_bytes: int = 0  # largest single output reservation observed
    splits: int = 0  # splits completed (scans only)
    invocations: int = 0

    def add(self, other: "OperatorStats") -> None:
        """Fold another record for the SAME node into this one (additive
        fields sum, peaks max) — used across tasks and across workers."""
        self.input_rows += other.input_rows
        self.output_rows += other.output_rows
        self.output_bytes += other.output_bytes
        self.wall_s += other.wall_s
        self.device_s += other.device_s
        self.peak_bytes = max(self.peak_bytes, other.peak_bytes)
        self.splits += other.splits
        self.invocations += other.invocations

    def to_dict(self) -> dict:
        return {
            "nodeId": self.node_id,
            "operator": self.operator,
            "inputRows": self.input_rows,
            "outputRows": self.output_rows,
            "outputBytes": self.output_bytes,
            "wallS": round(self.wall_s, 6),
            "deviceS": round(self.device_s, 6),
            "peakBytes": self.peak_bytes,
            "splits": self.splits,
            "invocations": self.invocations,
        }

    @staticmethod
    def from_dict(d: dict) -> "OperatorStats":
        return OperatorStats(
            node_id=int(d["nodeId"]),
            operator=str(d.get("operator", "?")),
            input_rows=int(d.get("inputRows", 0)),
            output_rows=int(d.get("outputRows", 0)),
            output_bytes=int(d.get("outputBytes", 0)),
            wall_s=float(d.get("wallS", 0.0)),
            device_s=float(d.get("deviceS", 0.0)),
            peak_bytes=int(d.get("peakBytes", 0)),
            splits=int(d.get("splits", 0)),
            invocations=int(d.get("invocations", 0)),
        )


def merge_operator_dicts(
        dict_lists: Iterable[Iterable[dict]]) -> Dict[int, OperatorStats]:
    """Merge per-task ``operatorStats`` payload lists by plan-node id —
    tasks of one stage run the same fragment subtree, so equal node ids
    across tasks (and across workers) are the same operator."""
    merged: Dict[int, OperatorStats] = {}
    for ops in dict_lists:
        for d in ops or ():
            st = OperatorStats.from_dict(d)
            have = merged.get(st.node_id)
            if have is None:
                merged[st.node_id] = st
            else:
                have.add(st)
    return merged


def merge_kernel_lists(dict_lists: Iterable[Iterable[dict]]) -> List[dict]:
    """Merge per-task ``kernelStats`` payload lists (kernel-ledger rows,
    obs/devprofiler.py wire shape) — additive by construction, keyed by
    (plan node, operator, tier, node) so per-worker attribution survives
    the stage rollup."""
    from trino_tpu.obs.devprofiler import merge_kernel_rows

    merged: Dict[tuple, dict] = {}
    for rows in dict_lists:
        merge_kernel_rows(merged, list(rows or ()))
    return [merged[k] for k in sorted(merged)]


def _stage_state(task_entries: List[dict]) -> str:
    """A stage is FINISHED only when every task finished; any failed or
    canceled task marks the whole stage (a FAILED stage must never read as
    successfully completed)."""
    states = [e.get("state") for e in task_entries]
    if any(s == "FAILED" for s in states):
        return "FAILED"
    if any(s == "CANCELED" for s in states):
        return "CANCELED"
    if states and all(s == "FINISHED" for s in states):
        return "FINISHED"
    return "RUNNING"


def rollup_tasks_to_stage(fragment_id: int, task_entries: List[dict],
                          include_operators: bool = True) -> dict:
    """One stage's rollup from its tasks' status records.

    ``task_entries`` are coordinator-side records: ``{"state": str,
    "stats": <task stats snapshot>}`` — one per task SLOT (retried or
    speculative attempts replace the slot's record, so nothing double
    counts). ``include_operators=False`` skips the per-node merge for
    callers that only need the scalar summary (protocol polls, UI)."""
    ops = merge_operator_dicts(
        e.get("stats", {}).get("operatorStats")
        for e in task_entries) if include_operators else {}
    stage = {
        "stageId": fragment_id,
        "tasks": len(task_entries),
        "completedTasks": sum(
            1 for e in task_entries if e.get("state") == "FINISHED"),
        "state": _stage_state(task_entries),
        "completedSplits": 0,
        "totalSplits": 0,
        "inputRows": 0,
        "outputRows": 0,
        "outputBytes": 0,
        "wallS": 0.0,
        "deviceS": 0.0,
        "peakBytes": 0,
        "spills": 0,
        "shedBytes": 0,
        "yieldEvents": 0,
        "deviceCacheHits": 0,
        "deviceCacheMisses": 0,
        "operatorStats": [ops[k].to_dict() for k in sorted(ops)],
        "kernelStats": merge_kernel_lists(
            e.get("stats", {}).get("kernelStats")
            for e in task_entries) if include_operators else [],
    }
    part_bytes = None
    part_rows = None
    for e in task_entries:
        s = e.get("stats") or {}
        stage["completedSplits"] += int(s.get("completedSplits", 0))
        stage["totalSplits"] += int(s.get("totalSplits", 0))
        stage["inputRows"] += int(s.get("inputRows", 0))
        stage["outputRows"] += int(s.get("outputRows", 0))
        stage["outputBytes"] += int(s.get("outputBytes", 0))
        stage["wallS"] += float(s.get("elapsedS", 0.0))
        stage["deviceS"] += float(s.get("deviceS", 0.0))
        stage["peakBytes"] = max(stage["peakBytes"],
                                 int(s.get("peakBytes", 0)))
        stage["spills"] += int(s.get("spills", 0))
        # memory-ledger ride-along: bytes shed from the revocable caches
        # on this task's behalf + yield events — SUMS (each task's sheds
        # are distinct reclamations, unlike the shared-pool peak)
        stage["shedBytes"] += int(s.get("shedBytes", 0))
        stage["yieldEvents"] += int(s.get("yieldEvents", 0))
        stage["deviceCacheHits"] += int(s.get("deviceCacheHits", 0))
        stage["deviceCacheMisses"] += int(s.get("deviceCacheMisses", 0))
        # per-partition output bytes sum ELEMENTWISE across tasks: every
        # producer task contributes rows to every partition, so the stage
        # view is the skew signal (adaptive re-planner / UI)
        pb = s.get("partitionBytes")
        if pb is not None:
            if part_bytes is None:
                part_bytes = [0] * len(pb)
            for i, b in enumerate(pb[: len(part_bytes)]):
                part_bytes[i] += int(b)
        pr = s.get("partitionRows")
        if pr is not None:
            if part_rows is None:
                part_rows = [0] * len(pr)
            for i, r in enumerate(pr[: len(part_rows)]):
                part_rows[i] += int(r)
    if part_bytes is not None:
        stage["partitionBytes"] = part_bytes
    if part_rows is not None:
        stage["partitionRows"] = part_rows
    stage["wallS"] = round(stage["wallS"], 6)
    stage["deviceS"] = round(stage["deviceS"], 6)
    return stage


def rollup_stages_to_query(stages: List[dict]) -> dict:
    """Query-level totals from stage rollups (reference: QueryStats).

    ``totalRows``/``totalBytes`` count work PROCESSED (stage input rows /
    stage output bytes), the progress numbers a client renders; peaks max
    across stages because stages share each worker's memory pool."""
    q = {
        "stages": len(stages),
        "completedStages": sum(
            1 for s in stages if s.get("state") == "FINISHED"),
        "completedSplits": sum(int(s.get("completedSplits", 0)) for s in stages),
        "totalSplits": sum(int(s.get("totalSplits", 0)) for s in stages),
        "totalRows": sum(int(s.get("inputRows", 0)) for s in stages),
        "totalBytes": sum(int(s.get("outputBytes", 0)) for s in stages),
        "wallS": round(sum(float(s.get("wallS", 0.0)) for s in stages), 6),
        "deviceS": round(sum(float(s.get("deviceS", 0.0)) for s in stages), 6),
        "peakBytes": max(
            [int(s.get("peakBytes", 0)) for s in stages], default=0),
        "spills": sum(int(s.get("spills", 0)) for s in stages),
        "shedBytes": sum(int(s.get("shedBytes", 0)) for s in stages),
        "yieldEvents": sum(int(s.get("yieldEvents", 0)) for s in stages),
        # warm-HBM serving signal: scans served from the device table
        # cache vs scans that paid a host->device transfer
        "deviceCacheHits": sum(
            int(s.get("deviceCacheHits", 0)) for s in stages),
        "deviceCacheMisses": sum(
            int(s.get("deviceCacheMisses", 0)) for s in stages),
    }
    return q


def wall_time_header(plan_s: float, exec_s: float) -> str:
    """The EXPLAIN ANALYZE header line, shared by the local and distributed
    paths: total wall includes planning so it agrees with the query-level
    span totals."""
    return (f"Query wall time: {(plan_s + exec_s) * 1e3:.1f}ms"
            f" (planning {plan_s * 1e3:.1f}ms,"
            f" execution {exec_s * 1e3:.1f}ms)")
