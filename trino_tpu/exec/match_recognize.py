"""MATCH_RECOGNIZE: row pattern matching (host tier).

Reference: ``operator/window/pattern/`` (the IrRowPattern machine +
PatternRecognitionPartition) and ``sql/tree/PatternRecognitionRelation``.
Subset implemented: ONE ROW PER MATCH output (partition keys + measures),
AFTER MATCH SKIP PAST LAST ROW / SKIP TO NEXT ROW, concatenation patterns
with ?/*/+ quantifiers (greedy with backtracking), DEFINE predicates over
current-row columns, pattern-variable-qualified columns (LAST-row
semantics), PREV/NEXT(col[, n]) physical navigation, FIRST/LAST(var.col),
CLASSIFIER() and MATCH_NUMBER().

Execution is HOST-side over concrete rows (the eager tier): pattern
matching is inherently sequential/backtracking — the one operator family
whose inner loop does not vectorize onto the device. Partitions at this
operator are post-aggregation-scale; the distributed tier gathers into the
coordinator-local fragment first (fragmenter routes it like a SortNode).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from trino_tpu.sql.parser import ast

MAX_BACKTRACK_STEPS = 1_000_000  # per-partition guard


class MatchError(ValueError):
    pass


@dataclasses.dataclass
class Ctx:
    rows: List[dict]  # partition rows (ordered), name -> python value
    i: int  # current row index under evaluation
    var: str  # variable being tested (classifier of the current row)
    assigns: List[Tuple[int, str]]  # rows matched so far (row_idx, var)
    match_number: int
    final: bool = False  # measures evaluate FINAL (whole match known)

    def rows_of(self, var: str) -> List[int]:
        return [r for r, v in self.assigns if v == var]


def _evaluate(e: ast.Expression, ctx: Ctx):
    """AST -> python value under pattern-matching semantics. NULL = None
    with SQL three-valued comparisons (None propagates)."""
    if isinstance(e, ast.Literal):
        from trino_tpu.data.page import _from_repr
        from trino_tpu.sql.analyzer.expr_analyzer import analyze_literal

        c = analyze_literal(e)
        if c.value is None:
            return None
        if c.type.is_varchar:
            return c.value
        return _from_repr(c.type, c.value)
    if isinstance(e, ast.Identifier):
        if len(e.parts) == 2:
            # var-qualified: value of the LAST row assigned to that
            # variable so far (reference: pattern navigation defaults)
            var, col = e.parts[0].lower(), e.parts[1].lower()
            rows = [r for r, v in ctx.assigns if v == var]
            if ctx.var == var and not ctx.final:
                rows = rows + [ctx.i]  # the row under test counts as var
            if not rows:
                return None
            return ctx.rows[rows[-1]].get(col)
        name = e.name.lower()
        return ctx.rows[ctx.i].get(name) if not ctx.final else (
            ctx.rows[ctx.assigns[-1][0]].get(name))
    if isinstance(e, ast.FunctionCall):
        name = e.name.lower()
        if name in ("prev", "next"):
            n = 1
            if len(e.args) == 2:
                n = int(_evaluate(e.args[1], ctx))
            base = ctx.i if not ctx.final else ctx.assigns[-1][0]
            j = base - n if name == "prev" else base + n
            if not 0 <= j < len(ctx.rows):
                return None
            inner = e.args[0]
            if isinstance(inner, ast.Identifier):
                return ctx.rows[j].get(inner.parts[-1].lower())
            sub = dataclasses.replace(ctx, i=j, final=False)
            return _evaluate(inner, sub)
        if name in ("first", "last"):
            inner = e.args[0]
            if not isinstance(inner, ast.Identifier):
                raise MatchError(f"{name}() expects a column reference")
            if len(inner.parts) == 2:
                var, col = inner.parts[0].lower(), inner.parts[1].lower()
                rows = ctx.rows_of(var)
                if ctx.var == var and not ctx.final:
                    rows = rows + [ctx.i]
            else:
                col = inner.name.lower()
                rows = [r for r, _ in ctx.assigns]
                if not ctx.final:
                    rows = rows + [ctx.i]
            if not rows:
                return None
            return ctx.rows[rows[0] if name == "first" else rows[-1]].get(col)
        if name == "classifier":
            if ctx.final:
                return ctx.assigns[-1][1].upper()
            return ctx.var.upper()
        if name == "match_number":
            return ctx.match_number
        if name == "abs":
            v = _evaluate(e.args[0], ctx)
            return None if v is None else abs(v)
        if name == "coalesce":
            for a in e.args:
                v = _evaluate(a, ctx)
                if v is not None:
                    return v
            return None
        raise MatchError(f"MATCH_RECOGNIZE: unsupported function {name}")
    if isinstance(e, ast.Arithmetic):
        a = _evaluate(e.left, ctx)
        b = _evaluate(e.right, ctx)
        if a is None or b is None:
            return None
        return {"+": lambda: a + b, "-": lambda: a - b, "*": lambda: a * b,
                "/": lambda: a / b, "%": lambda: a % b}[e.op]()
    if isinstance(e, ast.Negative):
        v = _evaluate(e.value, ctx)
        return None if v is None else -v
    if isinstance(e, ast.Comparison):
        a = _evaluate(e.left, ctx)
        b = _evaluate(e.right, ctx)
        if a is None or b is None:
            return None
        return {"=": a == b, "<>": a != b, "!=": a != b, "<": a < b,
                "<=": a <= b, ">": a > b, ">=": a >= b}[e.op]
    if isinstance(e, ast.LogicalBinary):
        a = _evaluate(e.left, ctx)
        b = _evaluate(e.right, ctx)
        if e.op == "and":
            if a is False or b is False:
                return False
            return None if a is None or b is None else True
        if a is True or b is True:
            return True
        return None if a is None or b is None else False
    if isinstance(e, ast.Not):
        v = _evaluate(e.value, ctx)
        return None if v is None else not v
    if isinstance(e, ast.IsNull):
        v = _evaluate(e.value, ctx)
        out = v is None
        return (not out) if e.negated else out
    if isinstance(e, ast.Between):
        v = _evaluate(e.value, ctx)
        lo = _evaluate(e.low, ctx)
        hi = _evaluate(e.high, ctx)
        if v is None or lo is None or hi is None:
            return None
        out = lo <= v <= hi
        return (not out) if e.negated else out
    raise MatchError(
        f"MATCH_RECOGNIZE: unsupported expression {type(e).__name__}")


def _pred_holds(defines: Dict[str, ast.Expression], var: str, ctx: Ctx) -> bool:
    pred = defines.get(var)
    if pred is None:
        return True  # undefined variable matches any row (spec)
    return _evaluate(pred, dataclasses.replace(ctx, var=var)) is True


def _match_at(rows, start: int, pattern, defines, match_number: int,
              budget: List[int]) -> Optional[List[Tuple[int, str]]]:
    """Greedy backtracking match of the quantified concatenation pattern
    anchored at ``start``; returns the row->variable assignment or None."""

    def rec(e_idx: int, row: int, assigns):
        budget[0] -= 1
        if budget[0] <= 0:
            raise MatchError("MATCH_RECOGNIZE backtracking budget exceeded")
        if e_idx == len(pattern):
            return assigns
        var, quant = pattern[e_idx]

        def holds(r):
            return r < len(rows) and _pred_holds(
                defines, var,
                Ctx(rows, r, var, assigns, match_number))

        if quant == "1":
            if holds(row):
                return rec(e_idx + 1, row + 1, assigns + [(row, var)])
            return None
        if quant == "?":
            if holds(row):
                out = rec(e_idx + 1, row + 1, assigns + [(row, var)])
                if out is not None:
                    return out
            return rec(e_idx + 1, row, assigns)
        # greedy * / +: consume as many as the predicate admits, then
        # backtrack down to the minimum count
        taken = []
        r = row
        while holds(r):
            taken.append((r, var))
            r += 1
        min_take = 1 if quant == "+" else 0
        for k in range(len(taken), min_take - 1, -1):
            out = rec(e_idx + 1, row + k, assigns + taken[:k])
            if out is not None:
                return out
        return None

    return rec(0, start, [])


def run_match_recognize(rows: List[dict], order_key, pattern, defines,
                        measures, after_match: str):
    """-> list of (assigns, measure python values) per match, over ONE
    partition (rows already restricted to it). ``order_key(row) -> tuple``
    orders the partition; measures evaluate FINAL."""
    rows = sorted(rows, key=order_key)
    defines = dict(defines)
    out = []
    budget = [MAX_BACKTRACK_STEPS]
    i = 0
    match_number = 1
    while i < len(rows):
        assigns = _match_at(rows, i, pattern, defines, match_number, budget)
        if assigns:
            ctx = Ctx(rows, assigns[-1][0], assigns[-1][1], assigns,
                      match_number, final=True)
            out.append(tuple(_evaluate(m, ctx) for m, _ in measures))
            match_number += 1
            if after_match == "past_last":
                i = assigns[-1][0] + 1
            else:  # next_row
                i = i + 1
        else:
            # no match anchored here (or an empty match): advance
            i += 1
    return out
