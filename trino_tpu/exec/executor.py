"""Plan executor: fully traceable array program over device Pages.

Reference: the worker execution engine — ``LocalExecutionPlanner.java:532``
turning plan nodes into operator pipelines + ``Driver.java:372``'s page loop.
TPU-first difference (SURVEY.md §7.1): no page-at-a-time pull loop — each
plan node is a whole-column array transformation with *static shapes*:
filters keep selection masks instead of compacting, aggregations emit
padded outputs with a live-group prefix, sorts move dead rows last. Because
every step is shape-static and host-sync-free, the entire query body can be
traced once and compiled by XLA (``exec.compiled``), and the same recursion
runs under ``shard_map`` for multi-chip SPMD (``parallel.spmd``).

Data-dependent runtime errors (division by zero, multi-row scalar subquery)
are collected as boolean flags and checked once after execution — the
deferred-error contract of ops/expr_lower.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.data.page import Column, Page
from trino_tpu.ops import aggregate as agg_ops
from trino_tpu.ops import expr_lower as L
from trino_tpu.ops import groupby as gb
from trino_tpu.ops import join as join_ops
from trino_tpu.ops import ranks as ranks_ops
from trino_tpu.ops import segments as seg
from trino_tpu.ops import sort as sort_ops
from trino_tpu.sql import ir
from trino_tpu.sql.planner import plan as P


class QueryError(RuntimeError):
    def __init__(self, message: str, code: str = ""):
        super().__init__(message)
        self.code = code


def raise_query_errors(codes, flags):
    """Raise the first deferred runtime error whose flag fired. Shared by
    the eager, compiled, and SPMD paths."""
    import numpy as _np

    for code, flag in zip(codes, flags):
        if bool(_np.asarray(flag).any()):
            raise QueryError(code.replace("_", " ").capitalize(), code=code)


def _col_from_lowered(t: T.Type, lv: L.LoweredVal) -> Column:
    nulls = None if lv.valid is None else ~lv.valid
    children = None
    if lv.children is not None:
        children = [
            _col_from_lowered(ct, k) for ct, k in zip(T.type_children(t), lv.children)
        ]
        return Column(t, lv.vals, nulls, None, children=children)
    return Column(t, lv.vals, nulls, lv.dictionary)


def _col_to_lowered(c: Column) -> join_ops.Lowered:
    return (c.values, None if c.nulls is None else ~c.nulls)


def assemble_scan_page(column_names, column_types, datas) -> Page:
    """Build a device Page from per-split connector scan results: concat
    parts per column (merging varchar dictionaries via
    spi.concat_column_data), pad empty scans to the canonical one-dead-row
    page. Shared by the eager executor and the worker fragment executor."""
    from trino_tpu.connector.spi import concat_column_data
    from trino_tpu.data.page import fits_int32

    if not datas:
        return Page.all_dead(column_types)
    cols: List[Column] = []
    for name, typ in zip(column_names, column_types):
        cd = concat_column_data([d[name] for d in datas])
        if typ.is_nested:
            cols.append(_column_from_data(cd))
            continue
        vals = np.asarray(cd.values)
        # Physical narrowing: int64-stored columns whose table-wide value
        # range provably fits int32 ride int32 on device — int64 is emulated
        # 2x int32 on TPU, so narrow keys sort/join/group ~2x faster (see
        # data/page.py Column). Table-wide ranges keep splits dtype-uniform.
        if vals.dtype == np.int64 and fits_int32(cd.vrange):
            vals = vals.astype(np.int32)
        cols.append(
            Column(
                typ,
                jnp.asarray(vals),
                jnp.asarray(cd.nulls) if cd.nulls is not None else None,
                cd.dictionary,
                cd.vrange,
                ascending=bool(getattr(cd, "sorted", False)),
            )
        )
    if cols and cols[0].values.shape[0] == 0:
        return Page.all_dead(column_types)
    return Page(cols)


def _column_from_data(cd) -> Column:
    """ColumnData -> device Column, recursing into nested children."""
    return Column(
        cd.type,
        jnp.asarray(np.asarray(cd.values)),
        jnp.asarray(cd.nulls) if cd.nulls is not None else None,
        cd.dictionary,
        cd.vrange,
        ascending=bool(getattr(cd, "sorted", False)),
        children=(
            [_column_from_data(k) for k in cd.children]
            if cd.children is not None
            else None
        ),
    )


def scan_constraint_with(node: "P.TableScanNode", dyn_domains):
    """Effective TupleDomain for a scan: static pushdown ∩ available
    dynamic-filter domains (reference: DynamicFilter.getCurrentPredicate).
    Shared by the eager executor and the staged tiers (compiled/SPMD)."""
    from trino_tpu.connector.predicate import TupleDomain

    td = node.constraint
    for join_id, key_idx, column in node.dynamic_filters or ():
        dom = dyn_domains.get((join_id, key_idx))
        if dom is None:
            continue
        extra = TupleDomain({column: dom})
        td = extra if td is None else td.intersect(extra)
    return td


def dynamic_domain_map(node, dyn_domains):
    """column -> available dynamic-filter Domain for a scan (intersecting
    when several joins filter the same column). Shared by the phase-1 host
    evaluator and the scan-time enforcer so both always agree on which rows
    survive."""
    dyn = {}
    for join_id, key_idx, column in node.dynamic_filters or ():
        dom = dyn_domains.get((join_id, key_idx))
        if dom is None or dom.is_all():
            continue
        dyn[column] = dom.intersect(dyn[column]) if column in dyn else dom
    return dyn


def apply_dynamic_domains(node, dyn_domains, datas, allow=None):
    """Engine-side enforcement of a scan's available dynamic-filter domains
    on host-side scanned data: connectors treat constraints as ADVISORY (the
    tpch generator prunes only via its monotone key), so the scan operator
    itself drops rows outside the domain before device transfer — the
    reference's ScanFilterAndProjectOperator applying
    DynamicFilter.getCurrentPredicate. Varchar domains are skipped
    (dictionary codes are page-local). ``allow(column, domain)`` restricts
    which domains apply here (the compiled tier splits strong domains —
    host row pruning cuts the device transfer — from weak ones it enforces
    on device)."""
    import dataclasses as _dc

    from trino_tpu.exec.host_eval import domain_mask

    dyn = dynamic_domain_map(node, dyn_domains)
    if allow is not None:
        dyn = {c: d for c, d in dyn.items() if allow(node, c, d)}
    if not dyn:
        return datas
    out = []
    for d in datas:
        if not d:
            out.append(d)
            continue
        n = len(next(iter(d.values())).values)
        keep = np.ones(n, dtype=bool)
        for column, dom in dyn.items():
            cd = d.get(column)
            if cd is None or cd.dictionary is not None:
                continue
            keep &= domain_mask(
                dom,
                np.asarray(cd.values),
                np.asarray(cd.nulls) if cd.nulls is not None else None,
            )
        if keep.all():
            out.append(d)
            continue
        out.append({
            name: _dc.replace(
                cd,
                values=np.asarray(cd.values)[keep],
                nulls=np.asarray(cd.nulls)[keep] if cd.nulls is not None else None,
            )
            for name, cd in d.items()
        })
    return out


class Executor:
    """Traceable plan interpreter. ``execute_checked`` runs eagerly and
    raises deferred errors; the recursion itself (``execute``) is pure and
    jit-safe."""

    # Eager tier: host-side recursion over concrete arrays (the local path
    # and worker fragments). Traced subclasses (PreloadedExecutor,
    # SpmdExecutor) run under jax tracing where host-side syncs (stats,
    # dynamic-filter domains, spill partitioning) are impossible.
    eager_tier = True
    enable_dynamic_filtering = True  # AND-ed with the session property
    collect_stats = True  # per-operator wall/rows (traced subclasses: False)
    # Row-level dynamic-domain enforcement at the scan: host-side numpy here
    # (concrete arrays); the compiled tier stages full pages and enforces ON
    # DEVICE instead (searchsorted membership + compact ride HBM bandwidth,
    # ~40x the host's — exec/compiled.py StagingExecutor)
    apply_df_host = True

    def __init__(self, session, capacity_hints: Optional[Dict[str, int]] = None):
        self.session = session
        self.errors: List[Tuple[str, jnp.ndarray]] = []
        # M:N join output capacities by plan-node id. Eager runs compute the
        # exact total (one device sync) and record a padded power-of-two here;
        # traced runs (compiled/SPMD) require the hint to pre-exist — the
        # bucketed-recompile strategy of SURVEY.md §7.3 (dynamic shapes).
        self.capacity_hints: Dict[str, int] = capacity_hints if capacity_hints is not None else {}
        # Dynamic filtering (reference: DynamicFilterService): build-side key
        # domains by (join_id, key_index), produced when joins execute their
        # build side, consumed by probe-side scans. Eager execution only —
        # traced subclasses (PreloadedExecutor/SpmdExecutor) stage scans
        # before tracing and override the class flag (Tracers have no
        # concrete min/max).
        self.dyn_domains: Dict[Tuple[int, int], object] = {}
        # host seconds spent applying dynamic domains at scans (benchmarks
        # charge this to the query: it is join work moved off-device)
        self.df_apply_s = 0.0
        # rows materialized per scan plan-node id (EXPLAIN/pushdown tests)
        self.scan_stats: Dict[int, int] = {}
        # per-operator stats by plan-node id (EXPLAIN ANALYZE)
        self.node_stats: Dict[int, dict] = {}
        # device-memory budget + spill decisions (exec/memory.py; reference:
        # lib/trino-memory-context + the spill FSMs). Property name mirrors
        # the reference's query_max_memory_per_node.
        from trino_tpu.exec.memory import MemoryContext

        props = (
            session.properties
            if session is not None and hasattr(session, "properties")
            else {}
        ) or {}
        self.memory = MemoryContext(props.get("query_max_device_memory"))
        if not props.get("dynamic_filtering_enabled", True):
            self.enable_dynamic_filtering = False
        self.spill_enabled = bool(props.get("spill_enabled", True))

    # ------------------------------------------------------------------ api
    def execute_checked(self, node: P.PlanNode) -> Page:
        page = self.execute(node)
        self.raise_errors()
        return page

    def raise_errors(self):
        raise_query_errors([c for c, _ in self.errors], [f for _, f in self.errors])

    def execute(self, node: P.PlanNode) -> Page:
        method = getattr(self, f"_exec_{type(node).__name__}", None)
        if method is None:
            raise NotImplementedError(f"executor: {type(node).__name__}")
        if not self.collect_stats:
            return method(node)
        # per-operator profiling, always on in the eager tier (reference:
        # OperatorContext/OperatorStats via OperationTimer — SURVEY.md §5.1)
        t0 = time.perf_counter()
        page = method(node)
        wall = time.perf_counter() - t0
        st = self.node_stats.setdefault(
            node.id, {"name": type(node).__name__.replace("Node", ""), "wall_s": 0.0}
        )
        st["wall_s"] += wall
        st["output_rows"] = page.live_count()  # live rows, not padded slots
        return page

    def _lower(self, e: ir.Expr, page: Page) -> L.LoweredVal:
        ctx = L.LowerCtx(page.columns, page.num_rows, page.sel)
        out = L.lower(e, ctx)
        for code, flag in ctx.errors:
            self.errors.append((code, flag))
        return out

    # ----------------------------------------------------------------- scan
    def scan_constraint(self, node: P.TableScanNode):
        return scan_constraint_with(node, self.dyn_domains)

    def _exec_TableScanNode(self, node: P.TableScanNode) -> Page:
        conn = self.session.catalogs[node.catalog]
        constraint = self.scan_constraint(node)
        splits = conn.get_splits(node.schema, node.table, 1, constraint=constraint)
        datas = [conn.scan(s, node.column_names, constraint=constraint) for s in splits]
        if self.apply_df_host:
            t0 = time.perf_counter()
            datas = apply_dynamic_domains(
                node, self.dyn_domains, datas,
                allow=getattr(self, "df_host_allow", None))
            self.df_apply_s += time.perf_counter() - t0
        self.scan_stats[node.id] = sum(
            len(next(iter(d.values())).values) if d else 0 for d in datas
        )
        return assemble_scan_page(node.column_names, node.column_types, datas)

    def _exec_ValuesNode(self, node: P.ValuesNode) -> Page:
        cols = [
            Column.from_python(t, [r[i] for r in node.rows])
            for i, t in enumerate(node.types)
        ]
        # identical on every device under SPMD -> replicated
        if not cols:
            # zero-column single row (SELECT without FROM)
            return Page(
                [Column(T.BIGINT, jnp.zeros(len(node.rows), dtype=jnp.int64))],
                replicated=True,
            )
        return Page(cols, replicated=True)

    # -------------------------------------------------------------- set ops
    def _exec_UnionNode(self, node: P.UnionNode) -> Page:
        """UNION ALL: row-wise page concatenation (static shapes: total =
        sum of branch capacities; dead rows stay dead)."""
        pages = [self.execute(s) for s in node.sources_]
        out = pages[0]
        for p in pages[1:]:
            out = Page.concat_pages(out, p)
        return out

    def _exec_SetOpNode(self, node: P.SetOpNode) -> Page:
        left = self.execute(node.left)
        right = self.execute(node.right)
        return self.set_op_pages(node, left, right)

    def set_op_pages(self, node: P.SetOpNode, left: Page, right: Page) -> Page:
        """INTERSECT/EXCEPT DISTINCT via the grouping machinery: concat both
        sides with a side tag, group by ALL columns (grouping equality makes
        NULLs compare equal — the set-operation semantics), then keep groups
        by per-side presence counts. Reference: SetOperationNodeTranslator's
        aggregation-based lowering."""
        both = Page.concat_pages(left, right)
        n_l = left.num_rows
        side_right = jnp.arange(both.num_rows) >= n_l
        return self._set_op_grouped(node, both, side_right)

    def _set_op_grouped(self, node: P.SetOpNode, both: Page, side_right) -> Page:
        """The grouping half of a set operation over a combined page with an
        explicit per-row side tag — reused by the SPMD tier after a
        whole-row hash exchange (where positional tagging is impossible)."""
        n = both.num_rows
        layout, out_sel, (side_right_l,), sel_l = self.group_structure(
            list(range(both.channel_count)), both, [side_right]
        )
        l_cnt = seg.seg_sum(layout, (~side_right_l).astype(jnp.int64), sel_l, jnp.int64)
        r_cnt = seg.seg_sum(layout, side_right_l.astype(jnp.int64), sel_l, jnp.int64)
        if node.op == "intersect":
            keep = (l_cnt > 0) & (r_cnt > 0)
        else:  # except
            keep = (l_cnt > 0) & (r_cnt == 0)
        keys = [_col_to_lowered(both.columns[c]) for c in range(both.channel_count)]
        key_cols = gb.gather_group_keys(keys, layout.rep)
        out_cols = [
            Column(both.columns[i].type, v,
                   None if valid is None else ~valid,
                   both.columns[i].dictionary)
            for i, (v, valid) in enumerate(key_cols)
        ]
        return Page(out_cols, out_sel & keep, both.replicated)

    # --------------------------------------------------------------- filter
    def _exec_FilterNode(self, node: P.FilterNode) -> Page:
        page = self.execute(node.source)
        lv = self._lower(node.predicate, page)
        passed = lv.vals if lv.valid is None else (lv.vals & lv.valid)
        sel = passed if page.sel is None else (page.sel & passed)
        return Page(page.columns, sel, page.replicated)

    def _exec_CompactNode(self, node: P.CompactNode) -> Page:
        """Squeeze live rows into a smaller static-capacity page: ONE stable
        payload-carrying sort by the dead flag (live rows first, original
        order kept), then a static truncation to the capacity hint. Skipped
        when it cannot help (no selection mask, or capacity >= the page's
        rows — e.g. an SPMD shard already smaller than the global
        estimate). Overflow raises CAPACITY_EXCEEDED:cmp:<id> for the
        recompile-growth loop."""
        page = self.execute(node.source)
        if page.sel is None:
            return page
        capacity = self.hint_capacity(f"cmp:{node.id}", page.sel.astype(jnp.int32))
        return self.compact_to(page, capacity, f"cmp:{node.id}")

    def compact_to(self, page: Page, capacity: int, key: str) -> Page:
        """Squeeze live rows into a ``capacity``-slot page: ONE stable
        key-only sort of (dead flag, iota) for the live-first permutation,
        then ONE batched row-gather per dtype group at the first
        ``capacity`` indices — gathering only the KEPT rows (capacity), not
        all n, and never carrying the payload columns through the sort
        network (a 6M-row multi-payload lax.sort costs ~5x the flag sort).
        Original row order is kept (stable). Overflow raises
        CAPACITY_EXCEEDED:<key> for the recompile-growth loop. Shared by
        CompactNode and the device-side dynamic-filter scans."""
        from trino_tpu.ops import ranks as ranks_ops

        n = page.num_rows
        if page.sel is None or capacity >= n:
            return page
        if any(c.type.is_nested for c in page.columns):
            # device row-gathers cannot re-flatten variable-length children
            # (data-dependent shapes); keep the selection mask instead —
            # semantically identical, just uncompacted
            return page
        live = page.sel
        total = jnp.sum(live.astype(jnp.int32))
        self.errors.append((f"CAPACITY_EXCEEDED:{key}", total > capacity))
        _, order = jax.lax.sort(
            (~live, jnp.arange(n, dtype=jnp.int32)), num_keys=1, is_stable=True
        )
        idx = order[:capacity]
        arrays = []
        for c in page.columns:
            arrays.append(c.values)
            if c.nulls is not None:
                arrays.append(c.nulls)
        gathered = ranks_ops.batched_gather(arrays, idx)
        cols = []
        i = 0
        for c in page.columns:
            v = gathered[i]
            i += 1
            nulls = None
            if c.nulls is not None:
                nulls = gathered[i]
                i += 1
            # stable: live rows keep their relative order -> ascending holds
            cols.append(Column(c.type, v, nulls, c.dictionary, c.vrange,
                               ascending=c.ascending))
        sel = jnp.arange(capacity, dtype=jnp.int32) < jnp.minimum(total, capacity)
        return Page(cols, sel, page.replicated, live_prefix=True)

    def _exec_ProjectNode(self, node: P.ProjectNode) -> Page:
        page = self.execute(node.source)
        cols = []
        for e in node.expressions:
            if isinstance(e, ir.ColumnRef):
                # pass-through: reuse the column wholesale (keeps vrange,
                # dictionary, and sort-order metadata; skips re-lowering)
                cols.append(page.columns[e.index])
                continue
            lv = self._lower(e, page)
            cols.append(_col_from_lowered(e.type, lv))
        return Page(cols, page.sel, page.replicated,
                    live_prefix=page.live_prefix)

    # -------------------------------------------------------------- unnest
    def _exec_UnnestNode(self, node: P.UnnestNode) -> Page:
        page = self.execute(node.source)
        return self.unnest_page(node, page)

    def unnest_page(self, node: P.UnnestNode, page: Page) -> Page:
        """Static-shape UNNEST expansion (plan.py UnnestNode docstring).

        Output capacity = total flat element count across the unnested
        expressions (the exact row count for the single-array case; an upper
        bound when zipping several). Per-output-slot parent rows come from
        one searchsorted over the output offsets; every produced column is
        either a parent-row gather (replicated channels) or a flat-child
        gather at ``child_offset[parent] + position`` (unnested channels)."""
        from trino_tpu.ops import array_ops as A

        n = page.num_rows
        lows = [self._lower(e, page) for e in node.unnest_exprs]
        for lv in lows:
            if lv.children is None:
                raise NotImplementedError("UNNEST argument must be array/map-typed")
        for c in node.replicate_channels:
            if page.columns[c].type.is_nested:
                raise NotImplementedError(
                    "replicating an array/map column through UNNEST "
                    "(project it before/after instead)"
                )
        raw_lens = [lv.vals.astype(jnp.int32) for lv in lows]
        eff_lens = [
            jnp.where(lv.valid, ln, 0) if lv.valid is not None else ln
            for lv, ln in zip(lows, raw_lens)
        ]
        out_len = eff_lens[0]
        for ln in eff_lens[1:]:
            out_len = jnp.maximum(out_len, ln)
        if page.sel is not None:
            out_len = jnp.where(page.sel, out_len, 0)
        out_offsets = A.offsets_from_lengths(out_len)
        capacity = max(
            1, sum(int(lv.children[0].vals.shape[0]) for lv in lows)
        )
        slot = jnp.arange(capacity, dtype=jnp.int32)
        rowid_raw = jnp.searchsorted(out_offsets, slot, side="right").astype(jnp.int32) - 1
        rowid = jnp.clip(rowid_raw, 0, n - 1)
        pos = slot - out_offsets[rowid]  # 0-based position within the parent row
        sel = slot < out_offsets[-1]
        cols: List[Column] = []
        for ci in node.replicate_channels:
            c = page.columns[ci]
            cols.append(
                Column(
                    c.type,
                    c.values[rowid],
                    c.nulls[rowid] if c.nulls is not None else None,
                    c.dictionary,
                    c.vrange,
                )
            )
        child_types = iter(node.output_types[len(node.replicate_channels):])
        for lv, raw_ln in zip(lows, raw_lens):
            child_off = A.offsets_from_lengths(raw_ln)
            in_range = pos < raw_ln[rowid]
            if lv.valid is not None:
                in_range = in_range & lv.valid[rowid]
            for child in lv.children:
                flat = child.vals
                flat_n = int(flat.shape[0])
                safe = flat if flat_n else jnp.zeros((1,), flat.dtype)
                idx = jnp.clip(child_off[rowid] + pos, 0, max(flat_n - 1, 0))
                vals = safe[idx]
                valid = in_range
                if child.valid is not None:
                    cvalid = child.valid if flat_n else jnp.zeros((1,), bool)
                    valid = valid & cvalid[idx]
                cols.append(Column(next(child_types), vals, ~valid, child.dictionary))
        if node.ordinality:
            cols.append(Column(T.BIGINT, (pos + 1).astype(jnp.int64)))
        return Page(cols, sel)

    # ---------------------------------------------------------- aggregation
    def _exec_AggregationNode(self, node: P.AggregationNode) -> Page:
        page = self.execute(node.source)
        if node.step == "partial":
            return self.aggregate_partial(node, page)
        if node.step == "final":
            return self.aggregate_final(node, page)
        return self.aggregate_page(node, page)

    def aggregate_partial(self, node: P.AggregationNode, page: Page) -> Page:
        """Partial aggregation: emit group keys + accumulator-state columns
        (reference: HashAggregationOperator(PARTIAL) shipping
        AccumulatorCompiler intermediate states through an exchange).
        State column types follow plan._acc_types so the page can cross the
        wire (serde needs faithful dtypes)."""
        keys = [_col_to_lowered(page.columns[c]) for c in node.group_channels]
        payload_arrays, slots = self._agg_payloads(node.aggregates, page.columns)
        layout, part_sel, payloads_l, sel_l = self.group_structure(
            node.group_channels, page, payload_arrays
        )
        out_cols: List[Column] = []
        if node.group_channels:
            key_cols = gb.gather_group_keys(keys, layout.rep)
            for i, c in enumerate(node.group_channels):
                src = page.columns[c]
                v, valid = key_cols[i]
                out_cols.append(
                    Column(src.type, v, None if valid is None else ~valid,
                           src.dictionary, src.vrange)
                )
        src_types = node.source.output_types
        for call, slot in zip(node.aggregates, slots):
            states = self._partial_states(
                call, page, layout, self._slot_arg(payloads_l, slot), sel_l
            )
            state_types = P._acc_types(call, src_types)
            for (sv, valid), st in zip(states, state_types):
                out_cols.append(
                    Column(st, sv, None if valid is None else ~valid, None)
                )
        return Page(out_cols, part_sel, page.replicated)

    def aggregate_final(self, node: P.AggregationNode, page: Page) -> Page:
        """Final aggregation over gathered partial-state pages."""
        k = len(node.group_channels)
        keys = [_col_to_lowered(page.columns[c]) for c in range(k)]
        # state columns ride the grouping sort as payloads (layout space)
        payload_arrays: List = []
        state_slots: List = []
        for c in page.columns[k:]:
            vi = len(payload_arrays)
            payload_arrays.append(c.values)
            hv = c.nulls is not None
            if hv:
                payload_arrays.append(~c.nulls)
            state_slots.append((vi, hv))
        layout, out_sel, payloads_l, sel_l = self.group_structure(
            list(range(k)), page, payload_arrays
        )
        out_cols: List[Column] = []
        if k:
            key_cols = gb.gather_group_keys(keys, layout.rep)
            for i in range(k):
                src = page.columns[i]
                v, valid = key_cols[i]
                out_cols.append(
                    Column(src.type, v, None if valid is None else ~valid,
                           src.dictionary, src.vrange)
                )
        ci = 0
        for call in node.aggregates:
            # state layout must match what aggregate_partial emitted
            n_states = P._acc_state_count(call)
            states = [
                self._slot_arg(payloads_l, state_slots[ci + j]) for j in range(n_states)
            ]
            ci += n_states
            out_cols.append(self._combine_state(call, states, sel_l, layout))
        return Page(out_cols, out_sel, page.replicated)

    def _partial_states(self, call: P.AggregateCall, page, layout, arg_l, sel_l):
        """State arrays per aggregate: [(values, valid)], layout matching
        plan._acc_types. ``arg_l``/``sel_l`` are in layout space
        (group_structure payloads)."""
        if call.distinct:
            raise NotImplementedError(
                "DISTINCT aggregates cannot be split partial/final (the "
                "planner routes them through a gather exchange instead)"
            )
        sel = sel_l
        if call.function == "count" and call.arg_channel is None:
            v, _ = agg_ops.agg_count_star(layout, sel)
            return [(v, None)]
        arg = arg_l
        if call.function == "count":
            v, _ = agg_ops.agg_count(layout, arg, sel)
            return [(v, None)]
        if call.function == "sum":
            return [agg_ops.agg_sum(layout, arg, sel, call.output_type.np_dtype)]
        if call.function == "avg":
            base = (
                call.output_type.np_dtype
                if call.output_type.is_decimal
                else np.dtype(np.float64)
            )
            s, s_valid = agg_ops.agg_sum(layout, arg, sel, base)
            cnt, _ = agg_ops.agg_count(layout, arg, sel)
            return [(s, s_valid), (cnt, None)]
        if call.function == "min":
            return [agg_ops.agg_min(layout, arg, sel)]
        if call.function == "max":
            return [agg_ops.agg_max(layout, arg, sel)]
        if call.function in P._VAR_FAMILY:
            t = page.columns[call.arg_channel].type
            cnt, mean, m2 = agg_ops.var_states(
                layout, arg, sel, t.scale if t.is_decimal else 0
            )
            return [(cnt, None), (mean, None), (m2, None)]
        if call.function == "approx_percentile":
            from trino_tpu.ops import hll

            vals_l, valid_l = arg
            m_l = valid_l if sel is None else (
                sel if valid_l is None else (valid_l & sel))
            return hll.percentile_states(layout, vals_l, m_l)
        raise NotImplementedError(call.function)

    def _combine_state(self, call: P.AggregateCall, states, sel, layout) -> Column:
        """``states``: per-state (values, valid) pairs in layout space; sel
        likewise (see group_structure)."""
        if call.function == "count":
            v, _ = agg_ops.agg_sum(layout, states[0], sel, np.dtype(np.int64))
            return Column(T.BIGINT, v, None, None)
        if call.function == "sum":
            v, valid = agg_ops.agg_sum(
                layout, states[0], sel, call.output_type.np_dtype
            )
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        if call.function == "avg":
            base = (
                call.output_type.np_dtype
                if call.output_type.is_decimal
                else np.dtype(np.float64)
            )
            s, _sv = agg_ops.agg_sum(layout, states[0], sel, base)
            cnt, _ = agg_ops.agg_sum(layout, states[1], sel, np.dtype(np.int64))
            v, valid = agg_ops.finish_avg(s, cnt, call.output_type)
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        if call.function == "min":
            v, valid = agg_ops.agg_min(layout, states[0], sel)
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        if call.function == "max":
            v, valid = agg_ops.agg_max(layout, states[0], sel)
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        if call.function in P._VAR_FAMILY:
            cnt_i, m = states[0]
            if sel is not None:
                m = sel if m is None else (m & sel)
            cnt, mean, m2 = agg_ops.combine_var_states(
                layout, cnt_i, states[1][0], states[2][0], m
            )
            v, valid = agg_ops.finish_var(cnt, mean, m2, call.function)
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        if call.function == "approx_percentile":
            from trino_tpu.ops import hll

            cnt_state = states[-1]
            if sel is not None:
                cv, cm = cnt_state
                cnt_state = (jnp.where(sel, cv, jnp.zeros((), cv.dtype)), cm)
            v, valid = hll.percentile_merge(
                layout, states[:-1], cnt_state, call.param)
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        raise NotImplementedError(call.function)

    def group_structure(
        self, group_channels: List[int], page: Page, payloads=(), force_sort=False
    ):
        """(GroupLayout, out_sel, payloads_l, sel_l): group assignment.

        Two strategies (the FlatHash vs BigintGroupByHash specialization
        split in the reference, re-chosen for TPU — see ops/segments.py):
        - direct-mapped: all keys are null-free dictionary codes (or
          booleans) with a small cardinality product -> gid is a perfect
          index, NO sort, aggregation via unrolled masked reductions
          (the Q1-shape fast path; out_sel is the occupancy mask, in key
          order).
        - sort-based: exact comparison grouping for arbitrary keys
          (ops/groupby.py); capacity == input length, out_sel a prefix.

        ``payloads`` (e.g. aggregate argument columns) come back in LAYOUT
        SPACE: permuted group-contiguous by the sort for the sorted
        strategy (free payload operands of the one fused lax.sort),
        unchanged for direct layouts. ``sel_l`` is the page's selection in
        that same space (a live-prefix mask after sorting dead rows last).
        """
        n = page.num_rows
        keys = [_col_to_lowered(page.columns[c]) for c in group_channels]
        sel = page.sel
        if not group_channels:
            gids = jnp.zeros((n,), dtype=jnp.int32)
            layout = seg.direct_layout(gids, 1, sel)
            return layout, jnp.arange(1) < 1, list(payloads), sel
        direct = None if force_sort else self._direct_strides(group_channels, page)
        if direct is not None:
            strides, capacity = direct
            gids = jnp.zeros((n,), dtype=jnp.int32)
            for (vals, _), stride in zip(keys, strides):
                gids = gids + vals.astype(jnp.int32) * stride
            layout = seg.direct_layout(gids, capacity, sel)
            return layout, seg.occupancy(layout, sel), list(payloads), sel
        presorted = self._presorted_group(group_channels, page)
        if presorted is not None:
            # input already group-contiguous (single ascending key, dead
            # rows a tail): boundaries are one elementwise compare — the
            # n·log²n lax.sort, the engine's dominant cost at scale, never
            # runs. Layout space == original row order, so payloads and
            # sel pass through unchanged.
            vals = presorted
            dead = jnp.zeros((n,), bool) if sel is None else ~sel
            neq = vals[1:] != vals[:-1]
            boundary = jnp.concatenate(
                [jnp.ones((1,), bool), neq | (dead[1:] != dead[:-1])])
            gid_sorted = (jnp.cumsum(boundary.astype(jnp.int32)) - 1).astype(jnp.int32)
            num_groups = jnp.sum(boundary & ~dead)
            layout = seg.sorted_layout(
                jnp.arange(n, dtype=jnp.int32), gid_sorted, num_groups)
            return layout, jnp.arange(n) < num_groups, list(payloads), sel
        order, gid_sorted, num_groups, payloads_l = gb.group_plan(keys, sel, payloads)
        layout = seg.sorted_layout(order, gid_sorted, num_groups)
        if sel is None:
            sel_l = None
        else:
            n_live = jnp.sum(sel).astype(jnp.int32)
            sel_l = jnp.arange(n, dtype=jnp.int32) < n_live
        return layout, jnp.arange(n) < num_groups, payloads_l, sel_l

    @staticmethod
    def _agg_payloads(aggregates, columns):
        """(payload_arrays, slots): flatten every non-distinct aggregate
        argument (values + validity) into sort-payload operands; slots maps
        each call to its (index, has_valid) or None (count(*)/DISTINCT)."""
        payload_arrays: List = []
        slots: List = []
        for call in aggregates:
            if call.arg_channel is None or call.distinct:
                slots.append(None)
                continue
            col = columns[call.arg_channel]
            vi = len(payload_arrays)
            payload_arrays.append(col.values)
            hv = col.nulls is not None
            if hv:
                payload_arrays.append(~col.nulls)
            slots.append((vi, hv))
        return payload_arrays, slots

    @staticmethod
    def _slot_arg(payloads_l, slot):
        if slot is None:
            return None
        vi, hv = slot
        return (payloads_l[vi], payloads_l[vi + 1] if hv else None)

    @staticmethod
    def _presorted_group(group_channels: List[int], page: Page):
        """The single group-key column when the page is already
        group-contiguous: key ascending, null-free, dead rows a tail
        (sel None or live-prefix). Returns its values array or None."""
        if len(group_channels) != 1:
            return None
        col = page.columns[group_channels[0]]
        if not col.ascending or col.nulls is not None:
            return None
        if page.sel is not None and not page.live_prefix:
            return None
        return col.values

    @staticmethod
    def _direct_strides(group_channels: List[int], page: Page):
        sizes = []
        for c in group_channels:
            col = page.columns[c]
            if col.nulls is not None:
                return None
            if col.type.is_varchar and col.dictionary is not None:
                sizes.append(max(len(col.dictionary), 1))
            elif col.type == T.BOOLEAN:
                sizes.append(2)
            else:
                return None
        capacity = 1
        for s in sizes:
            capacity *= s
        if not 1 <= capacity <= seg.DIRECT_CAPACITY_MAX:
            return None
        strides = []
        acc = 1
        for s in reversed(sizes):
            strides.append(acc)
            acc *= s
        return list(reversed(strides)), capacity

    def aggregate_page(self, node: P.AggregationNode, page: Page) -> Page:
        """Group and aggregate; output has `capacity` rows, sel marking live
        groups (prefix for the sort path, occupancy mask for the direct
        path — both in group-key order)."""
        if node.group_channels and self.eager_tier:
            spilled = self._maybe_spill_aggregation(node, page)
            if spilled is not None:
                return spilled
        n = page.num_rows
        sel = page.sel
        if n == 0:
            page = Page(
                [
                    Column(c.type, jnp.zeros((1,), dtype=c.values.dtype), None, c.dictionary)
                    for c in page.columns
                ],
                jnp.zeros((1,), dtype=bool),
            )
            n = 1
            sel = page.sel
        keys = [_col_to_lowered(page.columns[c]) for c in node.group_channels]
        payload_arrays, slots = self._agg_payloads(node.aggregates, page.columns)
        # array_agg needs group-contiguous rows in layout space (its output
        # IS the per-group row runs); the direct masked-loop layout never
        # permutes, so force the sort strategy
        force_sort = any(c.function == "array_agg" for c in node.aggregates)
        layout, out_sel, payloads_l, sel_l = self.group_structure(
            node.group_channels, page, payload_arrays, force_sort=force_sort
        )
        out_cols: List[Column] = []
        if node.group_channels:
            key_cols = gb.gather_group_keys(keys, layout.rep)
            for i, c in enumerate(node.group_channels):
                src = page.columns[c]
                v, valid = key_cols[i]
                nulls = None if valid is None else ~valid
                out_cols.append(Column(src.type, v, nulls, src.dictionary, src.vrange))
        for call, slot in zip(node.aggregates, slots):
            if call.function == "array_agg":
                if call.distinct:
                    raise NotImplementedError("array_agg(DISTINCT): not yet supported")
                out_cols.append(
                    self._array_agg_column(
                        call, page, layout, self._slot_arg(payloads_l, slot), sel_l
                    )
                )
                continue
            vals, valid = self._exec_aggregate(
                call, page, sel, layout, self._slot_arg(payloads_l, slot), sel_l
            )
            out_cols.append(
                Column(
                    call.output_type,
                    vals,
                    (~valid) if valid is not None else None,
                    None,
                )
            )
        return Page(out_cols, out_sel, page.replicated)

    def _array_agg_column(self, call, page, layout, arg_l, sel_l) -> Column:
        """array_agg: the output array column IS the group-contiguous row
        runs of the grouping sort — per-slot lengths are the group ranges,
        the flat child is the (layout-space) argument column itself. NULL
        inputs are kept as NULL elements (reference: ArrayAggregation-
        Function has them by default).

        Sorted layouts put live rows first, group-contiguous from position
        0, so cumsum(lengths) == starts for every live slot and the flat
        child aligns with no extra gather. The global (no GROUP BY) case
        rides the direct single-slot layout: live rows compact to a prefix
        with one stable flag sort."""
        vals_l, valid_l = arg_l
        src = page.columns[call.arg_channel]
        elem_t = call.output_type.element
        if layout.is_direct:
            assert layout.capacity == 1, "grouped array_agg must use a sorted layout"
            n = layout.n
            if sel_l is None:
                flat, flat_valid = vals_l, valid_l
                count = jnp.int32(n)
            else:
                order = jax.lax.sort(
                    (~sel_l, jnp.arange(n, dtype=jnp.int32)), num_keys=1,
                    is_stable=True,
                )[1]
                flat = vals_l[order]
                flat_valid = valid_l[order] if valid_l is not None else None
                count = jnp.sum(sel_l.astype(jnp.int32))
            lengths = count[None].astype(jnp.int32)
        else:
            lengths = (layout.ends - layout.starts).astype(jnp.int32)
            flat, flat_valid = vals_l, valid_l
        child = Column(
            elem_t, flat, None if flat_valid is None else ~flat_valid, src.dictionary
        )
        return Column(call.output_type, lengths, None, children=[child])

    _in_spill_pass = False  # reentrancy guard for partitioned passes

    def _maybe_spill_aggregation(self, node: P.AggregationNode, page: Page):
        """Over-budget group-by: hash-partition rows by group key host-side,
        aggregate each partition fully on device, concatenate. Partitions
        hold disjoint group-key sets, so per-partition results are exact
        (reference: SpillableHashAggregationBuilder, host RAM as the tier)."""
        from trino_tpu.exec import memory as mem

        if self._in_spill_pass or not self.spill_enabled:
            return None
        projected = mem.page_bytes(page)
        parts = self.memory.spill_partitions(projected)
        if parts <= 1:
            return None
        self.memory.record_spill(node.id, "aggregation", parts, projected)
        out = None
        self._in_spill_pass = True
        try:
            for part in mem.partition_page_host(page, node.group_channels, parts):
                res = self.aggregate_page(node, part).compact()
                out = res if out is None else Page.concat_pages(out, res)
        finally:
            self._in_spill_pass = False
        return out

    def _exec_aggregate(self, call: P.AggregateCall, page, sel, layout, arg_l, sel_l):
        """``arg_l``/``sel_l`` are in layout space (group_structure
        payloads); the DISTINCT path re-groups and takes the original-order
        page column instead."""
        if call.function == "approx_percentile":
            if call.distinct:
                raise NotImplementedError(
                    "approx_percentile(DISTINCT): not yet supported")
            from trino_tpu.ops import hll

            vals_l, valid_l = arg_l
            m_l = valid_l if sel_l is None else (
                sel_l if valid_l is None else (sel_l & valid_l))
            return hll.approx_percentile(layout, vals_l, m_l, call.param)
        if call.distinct:
            if call.function not in ("count", "approx_distinct"):
                raise NotImplementedError(f"{call.function}(DISTINCT): not yet supported")
            arg = _col_to_lowered(page.columns[call.arg_channel])
            if call.function == "approx_distinct":
                # real HyperLogLog sketch (reference: airlift HLL via
                # ApproximateCountDistinctAggregation) — m=2048, ~2.3%
                # standard error, at sorted-segment cost (ops/hll.py)
                from trino_tpu.ops import hll

                return hll.approx_distinct(layout, arg, sel)
            return agg_ops.agg_count_distinct(layout, arg, sel)
        sel = sel_l
        if call.function == "count" and call.arg_channel is None:
            return agg_ops.agg_count_star(layout, sel)
        arg = arg_l
        if call.function == "count":
            return agg_ops.agg_count(layout, arg, sel)
        if call.function == "sum":
            return agg_ops.agg_sum(layout, arg, sel, call.output_type.np_dtype)
        if call.function == "avg":
            base = (
                call.output_type.np_dtype
                if call.output_type.is_decimal
                else np.dtype(np.float64)
            )
            s, _ = agg_ops.agg_sum(layout, arg, sel, base)
            cnt, _ = agg_ops.agg_count(layout, arg, sel)
            return agg_ops.finish_avg(s, cnt, call.output_type)
        if call.function == "min":
            return agg_ops.agg_min(layout, arg, sel)
        if call.function == "max":
            return agg_ops.agg_max(layout, arg, sel)
        if call.function in P._VAR_FAMILY:
            t = page.columns[call.arg_channel].type
            return agg_ops.agg_var(
                layout, arg, sel, call.function, t.scale if t.is_decimal else 0
            )
        raise NotImplementedError(call.function)

    # -------------------------------------------------------------- window
    def _exec_WindowNode(self, node: P.WindowNode) -> Page:
        return self.window_over_page(node, self.execute(node.source))

    def window_over_page(self, node: P.WindowNode, page: Page) -> Page:
        from trino_tpu.ops import window as win_ops

        n = page.num_rows
        pkeys = [_col_to_lowered(page.columns[c]) for c in node.partition_channels]
        okeys = [
            (_col_to_lowered(page.columns[c]), asc, nf)
            for c, asc, nf in node.order_channels
        ]
        layout = win_ops.build_layout(pkeys, okeys, page.sel, n)
        out_cols = list(page.columns)
        for call, name in zip(node.calls, node.names):
            arg = (
                _col_to_lowered(page.columns[call.arg_channel])
                if call.arg_channel is not None
                else None
            )
            fn = call.function
            flo, fhi = call.frame_lo, call.frame_hi
            if fn == "row_number":
                v, valid = win_ops.row_number(layout)
            elif fn == "rank":
                v, valid = win_ops.rank(layout)
            elif fn == "dense_rank":
                v, valid = win_ops.dense_rank(layout)
            elif fn == "ntile":
                v, valid = win_ops.ntile(layout, call.offset)
            elif fn == "percent_rank":
                v, valid = win_ops.percent_rank(layout)
            elif fn == "cume_dist":
                v, valid = win_ops.cume_dist(layout)
            elif fn == "sum":
                v, valid = win_ops.agg_sum(
                    layout, arg, call.frame, call.output_type.np_dtype, flo, fhi)
            elif fn == "avg":
                s, s_valid = win_ops.agg_sum(
                    layout, arg, call.frame,
                    call.output_type.np_dtype if call.output_type.is_decimal
                    else np.dtype(np.float64),
                    flo, fhi,
                )
                cnt, _ = win_ops.agg_count(layout, arg, call.frame, flo, fhi)
                v, dvalid = agg_ops.finish_avg(s, cnt, call.output_type)
                valid = s_valid if dvalid is None else (
                    dvalid if s_valid is None else (s_valid & dvalid)
                )
            elif fn in ("count", "count_star"):
                v, valid = win_ops.agg_count(layout, arg, call.frame, flo, fhi)
            elif fn in ("min", "max"):
                v, valid = win_ops.agg_minmax(layout, arg, call.frame, fn == "min")
            elif fn in ("lag", "lead"):
                v, valid = win_ops.shifted_value(layout, arg, call.offset, fn == "lead")
            elif fn == "nth_value":
                v, valid = win_ops.nth_value(
                    layout, arg, call.offset, call.frame, flo, fhi)
            elif fn in ("first_value", "last_value"):
                v, valid = win_ops.edge_value(
                    layout, arg, call.frame, fn == "first_value", flo, fhi)
            else:
                raise NotImplementedError(f"window function {fn}")
            # value-carrying functions keep the source column's dictionary
            dictionary = None
            if fn in ("min", "max", "lag", "lead", "first_value", "last_value",
                      "nth_value"):
                dictionary = page.columns[call.arg_channel].dictionary
            out_cols.append(
                Column(call.output_type, v, None if valid is None else ~valid, dictionary)
            )
        return Page(out_cols, page.sel, page.replicated)

    # -------------------------------------------------------------- joins
    def _exec_JoinNode(self, node: P.JoinNode) -> Page:
        # Build side FIRST (the reference's phased build-before-probe
        # ordering) so its key domains can dynamically narrow probe scans.
        right = self.execute(node.right)
        if self.enable_dynamic_filtering and node.dyn_filter_keys:
            self._collect_dynamic_filters(node, right)
        left = self.execute(node.left)
        return self._dispatch_join(node, left, right)

    def _dispatch_join(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        if node.left_keys and self.eager_tier:
            # eager tier: spill-partition when the working set exceeds the
            # device budget (traced tiers bound memory via capacity hints)
            spilled = self._maybe_spill_join(node, left, right)
            if spilled is not None:
                return spilled
        return self._run_join_kernel(node, left, right)

    def _maybe_spill_join(self, node: P.JoinNode, left: Page, right: Page):
        """Host-offload spill (exec/memory.py): when probe+build exceed the
        device budget, hash-partition BOTH sides by join key host-side and
        run the join as P independent on-device passes (equal keys
        co-locate, so the union of pass outputs is the exact join). The
        reference's partitioned-spill design (HashBuilderOperator FSM +
        GenericPartitioningSpiller) with host RAM as the spill tier."""
        from trino_tpu.exec import memory as mem

        if not self.spill_enabled:
            return None
        projected = mem.page_bytes(left) + mem.page_bytes(right)
        parts = self.memory.spill_partitions(projected)
        if parts <= 1:
            return None
        self.memory.record_spill(node.id, "join", parts, projected)
        lparts = mem.partition_page_host(left, node.left_keys, parts)
        rparts = mem.partition_page_host(right, node.right_keys, parts)
        out = None
        hint_key = f"join:{node.id}"
        for lp, rp in zip(lparts, rparts):
            # per-pass expansion capacity: each pass sizes its own bucket
            self.capacity_hints.pop(hint_key, None)
            res = self._run_join_kernel(node, lp, rp)
            res = res.compact()  # spill the pass result to host-sized rows
            out = res if out is None else Page.concat_pages(out, res)
        self.capacity_hints.pop(hint_key, None)
        return out

    def _run_join_kernel(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        """The single join-kernel dispatch, shared by the direct path and
        the spilled per-partition passes."""
        if node.join_type in ("semi", "anti"):
            if node.filter is not None:
                return self.semi_join_filtered(node, left, right)
            return self.semi_join(node, left, right)
        if not node.left_keys:
            if node.singleton:
                return self.singleton_cross(node, left, right)
            return self.expand_join(node, left, right)  # true cross join
        if node.right_unique:
            return self.lookup_join(node, left, right)
        return self.expand_join(node, left, right)

    DYNAMIC_FILTER_MAX_SET = 1024  # in-set domain cap (reference: the
    # small/large domain-compaction thresholds of DynamicFilterConfig)

    def _collect_dynamic_filters(self, node: P.JoinNode, build: Page) -> None:
        """Extract build-side key domains host-side (one device sync per
        key) for probe scans annotated by the optimizer."""
        from trino_tpu.connector.predicate import Domain

        for i in node.dyn_filter_keys:
            ch = node.right_keys[i]
            col = build.columns[ch]
            if col.type.is_varchar:
                continue  # dictionary codes are page-local, not portable
            vals = np.asarray(col.values)
            live = (
                np.ones(len(vals), bool)
                if build.sel is None
                else np.asarray(build.sel).copy()
            )
            if col.nulls is not None:
                live &= ~np.asarray(col.nulls)
            lv = vals[live]
            if len(lv) == 0:
                dom = Domain(values=frozenset())  # provably empty probe
            elif len(lv) <= self.DYNAMIC_FILTER_MAX_SET:
                dom = Domain.from_values(np.unique(lv).tolist())
            else:
                dom = Domain.range(low=lv.min().item(), high=lv.max().item())
            self.dyn_domains[(node.id, i)] = dom

    def hint_capacity(self, key: str, emit_counts) -> int:
        """Static output capacity for an expansion join or exchange, by hint
        key ("join:<id>" / "xchg*:<id>", see sql/planner/stats.py)."""
        cap = self.capacity_hints.get(key)
        if cap is not None:
            return cap
        if emit_counts is None:  # exchanges have no eager fallback
            raise RuntimeError(
                f"{key} has no capacity hint — estimate_exchange_hints and "
                "the executor's dispatch disagree (sql/planner/stats.py)"
            )
        try:
            total = int(jnp.sum(emit_counts))
        except jax.errors.ConcretizationTypeError:
            raise RuntimeError(
                f"{key} traced without a capacity hint — compiled paths "
                "estimate hints from stats (sql/planner/stats.py)"
            )
        cap = max(16, 1 << (max(total, 1) - 1).bit_length())
        self.capacity_hints[key] = cap
        return cap

    def _expansion_keys(self, node: P.JoinNode, left: Page, right: Page):
        if node.left_keys:
            build_keys = [_col_to_lowered(right.columns[c]) for c in node.right_keys]
            probe_keys = [_col_to_lowered(left.columns[c]) for c in node.left_keys]
            return join_ops.align_join_keys(
                build_keys, probe_keys,
                [right.columns[c].vrange for c in node.right_keys],
                [left.columns[c].vrange for c in node.left_keys],
            )
        # cross join: everything matches everything (constant key)
        build_keys = [(jnp.zeros((right.num_rows,), jnp.int32), None)]
        probe_keys = [(jnp.zeros((left.num_rows,), jnp.int32), None)]
        return build_keys, probe_keys


    @staticmethod
    def _build_presorted(page: Page, key_channels) -> bool:
        """True when the build page's single join key is ascending,
        null-free, and dead rows form a tail — build_side skips its sort."""
        if len(key_channels) != 1:
            return False
        col = page.columns[key_channels[0]]
        if not col.ascending or col.nulls is not None:
            return False
        return page.sel is None or page.live_prefix

    def expand_join(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        """General M:N inner/left join: count matches per probe row, then
        gather into a static-capacity probe-major output (ops/join.py
        probe_counts + expand; reference JoinHash position-links chains)."""
        build_keys, probe_keys = self._expansion_keys(node, left, right)
        build = join_ops.build_side(
            build_keys, right.sel,
            presorted=node.left_keys and self._build_presorted(right, node.right_keys))
        lo, counts = join_ops.probe_counts(build, probe_keys, left.sel)
        n = left.num_rows
        outer = node.join_type == "left"
        probe_live = (
            left.sel if left.sel is not None else jnp.ones((n,), dtype=bool)
        )
        plain_outer = outer and node.filter is None
        emit = jnp.where(probe_live, jnp.maximum(counts, 1), 0) if plain_outer else counts
        capacity = self.hint_capacity(f"join:{node.id}", emit)
        p, k, live, total = join_ops.expand(emit, capacity)
        self.errors.append((f"CAPACITY_EXCEEDED:join:{node.id}", total > capacity))
        # ONE batched random gather at p for lo/counts and every left column
        # (separate computed-index gathers don't fuse: ~40 ms each per 6M
        # rows on v5e — see ranks.batched_gather)
        left_arrays = [lo, counts]
        for c in left.columns:
            left_arrays.append(c.values)
            if c.nulls is not None:
                left_arrays.append(c.nulls)
        g = ranks_ops.batched_gather(left_arrays, p)
        lo_p, counts_p = g[0], g[1]
        matched = live & (k < counts_p)
        b_idx = jnp.clip(lo_p + k, 0, build.n - 1)
        rows = build.rows[b_idx]
        out_cols = []
        gi = 2
        for c in left.columns:
            v = g[gi]
            gi += 1
            nulls = None
            if c.nulls is not None:
                nulls = g[gi]
                gi += 1
            out_cols.append(Column(c.type, v, nulls, c.dictionary, c.vrange))
        right_lowered = join_ops.gather_columns(
            [_col_to_lowered(rc) for rc in right.columns], rows, matched
        )
        for rc, (v, valid) in zip(right.columns, right_lowered):
            out_cols.append(
                Column(rc.type, v, ~valid if valid is not None else None, rc.dictionary, rc.vrange)
            )
        page = Page(out_cols, live, left.replicated and right.replicated)
        if node.filter is None:
            return page
        lv = self._lower(node.filter, page)
        passed = lv.vals if lv.valid is None else (lv.vals & lv.valid)
        if not outer:
            return Page(out_cols, live & passed, page.replicated)
        # left join with filter: expanded rows that pass, plus one null-build
        # row for each probe row with no passing match
        passing = live & matched & passed
        # p is probe-major (non-decreasing) — monotonic segment sum, no scatter
        any_pass = (
            seg.monotonic_segment_sum(passing.astype(jnp.int32), p, n) > 0
        )
        tail_sel = probe_live & ~any_pass
        tail_cols = []
        for c in left.columns:
            tail_cols.append(c)
        for rc in right.columns:
            tail_cols.append(
                Column(
                    rc.type,
                    jnp.zeros((n,), dtype=rc.values.dtype),
                    jnp.ones((n,), dtype=bool),
                    rc.dictionary,
                )
            )
        head = Page(out_cols, passing, page.replicated)
        tail = Page(tail_cols, tail_sel, page.replicated)
        return Page.concat_pages(head, tail)

    def semi_join_filtered(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        """Semi/anti join with a residual filter (correlated EXISTS with
        non-equality predicates): expand the matches, evaluate the filter,
        then reduce any-passing back to the probe rows."""
        build_keys, probe_keys = self._expansion_keys(node, left, right)
        build = join_ops.build_side(
            build_keys, right.sel,
            presorted=node.left_keys and self._build_presorted(right, node.right_keys))
        lo, counts = join_ops.probe_counts(build, probe_keys, left.sel)
        n = left.num_rows
        capacity = self.hint_capacity(f"join:{node.id}", counts)
        p, k, live, total = join_ops.expand(counts, capacity)
        self.errors.append((f"CAPACITY_EXCEEDED:join:{node.id}", total > capacity))
        left_arrays = [lo]
        for c in left.columns:
            left_arrays.append(c.values)
            if c.nulls is not None:
                left_arrays.append(c.nulls)
        g = ranks_ops.batched_gather(left_arrays, p)
        b_idx = jnp.clip(g[0] + k, 0, build.n - 1)
        rows = build.rows[b_idx]
        exp_cols = []
        gi = 1
        for c in left.columns:
            v = g[gi]
            gi += 1
            nulls = None
            if c.nulls is not None:
                nulls = g[gi]
                gi += 1
            exp_cols.append(Column(c.type, v, nulls, c.dictionary, c.vrange))
        right_lowered = join_ops.gather_columns(
            [_col_to_lowered(rc) for rc in right.columns], rows, live
        )
        for rc, (v, valid) in zip(right.columns, right_lowered):
            exp_cols.append(
                Column(rc.type, v, ~valid if valid is not None else None, rc.dictionary, rc.vrange)
            )
        exp_page = Page(exp_cols, live, left.replicated and right.replicated)
        lv = self._lower(node.filter, exp_page)
        passed = lv.vals if lv.valid is None else (lv.vals & lv.valid)
        hit = (
            seg.monotonic_segment_sum((live & passed).astype(jnp.int32), p, n) > 0
        )
        keep = hit if node.join_type == "semi" else ~hit
        sel = keep if left.sel is None else left.sel & keep
        return Page(left.columns, sel, left.replicated)

    def lookup_join(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        build_keys = [_col_to_lowered(right.columns[c]) for c in node.right_keys]
        probe_keys = [_col_to_lowered(left.columns[c]) for c in node.left_keys]
        build_keys, probe_keys = join_ops.align_join_keys(
            build_keys, probe_keys,
            [right.columns[c].vrange for c in node.right_keys],
            [left.columns[c].vrange for c in node.left_keys],
        )
        build = join_ops.build_side(
            build_keys, right.sel,
            presorted=self._build_presorted(right, node.right_keys))
        rows, matched = join_ops.probe_unique(build, probe_keys)
        out_cols = list(left.columns)
        right_lowered = join_ops.gather_columns(
            [_col_to_lowered(rc) for rc in right.columns], rows, matched
        )
        for rc, (v, valid) in zip(right.columns, right_lowered):
            out_cols.append(
                Column(rc.type, v, ~valid if valid is not None else None, rc.dictionary, rc.vrange)
            )
        if node.join_type == "inner":
            sel = matched if left.sel is None else (left.sel & matched)
        else:  # left outer: probe rows always survive; build cols null when unmatched
            sel = left.sel
        page = Page(out_cols, sel, left.replicated)
        if node.filter is not None:
            lv = self._lower(node.filter, page)
            passed = lv.vals if lv.valid is None else (lv.vals & lv.valid)
            if node.join_type == "left":
                # probe rows survive; a failing filter just voids the match
                keep_match = matched & passed
                new_cols = list(left.columns)
                for rc, oc in zip(right.columns, out_cols[len(left.columns):]):
                    nulls = ~keep_match if oc.nulls is None else (oc.nulls | ~keep_match)
                    new_cols.append(Column(oc.type, oc.values, nulls, oc.dictionary))
                return Page(new_cols, left.sel, left.replicated)
            page = Page(out_cols, passed if page.sel is None else page.sel & passed, left.replicated)
        return page

    def semi_join(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        build_keys = [_col_to_lowered(right.columns[c]) for c in node.right_keys]
        probe_keys = [_col_to_lowered(left.columns[c]) for c in node.left_keys]
        build_keys, probe_keys = join_ops.align_join_keys(
            build_keys, probe_keys,
            [right.columns[c].vrange for c in node.right_keys],
            [left.columns[c].vrange for c in node.left_keys],
        )
        hit = join_ops.membership(
            build_keys, right.sel, probe_keys,
            presorted=self._build_presorted(right, node.right_keys))
        keep = hit if node.join_type == "semi" else ~hit
        sel = keep if left.sel is None else left.sel & keep
        return Page(left.columns, sel, left.replicated)

    def singleton_cross(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        """Cross join against a single-row relation (scalar subquery)."""
        r_sel = right.sel
        nr = right.num_rows
        if r_sel is None:
            live = jnp.asarray(nr, dtype=jnp.int64)
            idx = 0
        else:
            live = jnp.sum(r_sel)
            idx = jnp.argmax(r_sel)
        self.errors.append(("SCALAR_SUBQUERY_MULTIPLE_ROWS", live > 1))
        self.errors.append(("SCALAR_SUBQUERY_NO_ROWS", live < 1))
        n = left.num_rows
        out_cols = list(left.columns)
        for rc in right.columns:
            v = jnp.broadcast_to(rc.values[idx], (n,))
            nulls = (
                jnp.broadcast_to(rc.nulls[idx], (n,)) if rc.nulls is not None else None
            )
            out_cols.append(Column(rc.type, v, nulls, rc.dictionary, rc.vrange))
        page = Page(out_cols, left.sel, left.replicated)
        if node.filter is not None:
            lv = self._lower(node.filter, page)
            passed = lv.vals if lv.valid is None else lv.vals & lv.valid
            page = Page(out_cols, passed if page.sel is None else page.sel & passed, left.replicated)
        return page

    # ------------------------------------------------------------- ordering
    def _exec_SortNode(self, node: P.SortNode) -> Page:
        page = self.execute(node.source)
        return self.sorted_page(page, node.sort_channels)

    def sorted_page(self, page: Page, sort_channels, limit: Optional[int] = None) -> Page:
        """Move rows into sort order (dead rows last); sel becomes a prefix
        mask of the live (and limit-capped) rows. All columns ride the ONE
        payload-carrying sort (sort_ops.sort_payloads) — never a computed-
        permutation gather per column."""
        n = page.num_rows
        if any(c.type.is_nested for c in page.columns):
            # nested columns cannot ride a device payload sort (children
            # re-flatten with data-dependent shapes); sort host-side — this
            # path serves root-level ORDER BY over array_agg/unnest results
            return self._sorted_page_host(page, sort_channels, limit)
        keys = [
            (_col_to_lowered(page.columns[c]), asc, nf) for c, asc, nf in sort_channels
        ]
        payloads = []
        for c in page.columns:
            payloads.append(c.values)
            if c.nulls is not None:
                payloads.append(c.nulls)
        sorted_arrays = sort_ops.sort_payloads(keys, page.sel, payloads)
        live = (
            jnp.asarray(n, dtype=jnp.int64) if page.sel is None else jnp.sum(page.sel)
        )
        if limit is not None:
            live = jnp.minimum(live, limit)
        sel = jnp.arange(n) < live
        cols = []
        i = 0
        for c in page.columns:
            v = sorted_arrays[i]
            i += 1
            nulls = None
            if c.nulls is not None:
                nulls = sorted_arrays[i]
                i += 1
            cols.append(Column(c.type, v, nulls, c.dictionary, c.vrange))
        return Page(cols, sel, page.replicated)

    def _sorted_page_host(self, page: Page, sort_channels, limit=None) -> Page:
        """Host (numpy) ORDER BY for pages carrying nested columns: compact,
        lexsort with SQL null placement (ops/sort.py _sort_key semantics),
        host_take the permutation (which re-flattens children correctly)."""
        from trino_tpu.data.page import host_take

        compacted = page.compact()
        n = compacted.num_rows
        lex_keys = []  # least-significant first for np.lexsort
        for c, asc, nf in reversed(list(sort_channels)):
            col = compacted.columns[c]
            if col.type.is_nested:
                raise NotImplementedError("ORDER BY an array/map column")
            v = np.asarray(col.values)
            if v.dtype == np.bool_:
                v = v.astype(np.int8)
            if not asc:
                v = -v if np.issubdtype(v.dtype, np.floating) else ~v
            nulls_first = (not asc) if nf is None else nf
            if col.nulls is not None:
                isnull = np.asarray(col.nulls)
                rank = (~isnull).astype(np.int8) if nulls_first else isnull.astype(np.int8)
                lex_keys.append(np.where(isnull, np.zeros((), v.dtype), v))
                lex_keys.append(rank)
            else:
                lex_keys.append(v)
        order = (
            np.lexsort(lex_keys) if lex_keys else np.arange(n)
        )
        if limit is not None:
            order = order[:limit]
        return Page([host_take(c, order) for c in compacted.columns], None,
                    page.replicated)

    def _exec_TopNNode(self, node: P.TopNNode) -> Page:
        page = self.execute(node.source)
        return self.sorted_page(page, node.sort_channels, limit=node.count)

    def _exec_LimitNode(self, node: P.LimitNode) -> Page:
        page = self.execute(node.source)
        return self.sorted_page(page, [], limit=node.count)

    def _exec_OutputNode(self, node: P.OutputNode) -> Page:
        return self.execute(node.source)


@dataclasses.dataclass
class QueryResult:
    column_names: List[str]
    columns: List[Column]
    rows: List[tuple]

    def __repr__(self):
        return f"QueryResult({self.column_names}, {len(self.rows)} rows)"
