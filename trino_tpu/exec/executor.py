"""Local (single-device) plan executor.

Reference: the worker execution engine — ``LocalExecutionPlanner.java:532``
turning plan nodes into operator pipelines + ``Driver.java:372``'s page loop.
TPU-first difference (SURVEY.md §7.1): no page-at-a-time pull loop — each
plan node is a whole-column array transformation; XLA traces/fuses the
per-node work, and data-dependent result sizes (group counts, sort/limit
compaction) surface as one host-read scalar per materialization point.

This eager executor is the correctness path; ``exec.compiled`` (bench path)
jits whole fragments.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.data.page import Column, Page
from trino_tpu.ops import aggregate as agg_ops
from trino_tpu.ops import expr_lower as L
from trino_tpu.ops import groupby as gb
from trino_tpu.ops import join as join_ops
from trino_tpu.ops import sort as sort_ops
from trino_tpu.sql import ir
from trino_tpu.sql.planner import plan as P


class QueryError(RuntimeError):
    pass


def _check_errors(ctx: L.LowerCtx):
    for code, flag in ctx.errors:
        if bool(flag):
            raise QueryError(code.replace("_", " ").capitalize())


def _lower_expr(e: ir.Expr, page: Page) -> Tuple[L.LoweredVal, L.LowerCtx]:
    ctx = L.LowerCtx(page.columns, page.num_rows)
    out = L.lower(e, ctx)
    # errors only matter on live rows
    if ctx.errors and page.sel is not None:
        ctx.errors = [(c, f) for c, f in ctx.errors]
    _check_errors(ctx)
    return out, ctx


def _col_from_lowered(t: T.Type, lv: L.LoweredVal) -> Column:
    nulls = None if lv.valid is None else ~lv.valid
    return Column(t, lv.vals, nulls, lv.dictionary)


def _col_to_lowered(c: Column) -> join_ops.Lowered:
    return (c.values, None if c.nulls is None else ~c.nulls)


class Executor:
    def __init__(self, session):
        self.session = session

    def execute(self, node: P.PlanNode) -> Page:
        method = getattr(self, f"_exec_{type(node).__name__}", None)
        if method is None:
            raise NotImplementedError(f"executor: {type(node).__name__}")
        return method(node)

    # ----------------------------------------------------------------- scan
    def _exec_TableScanNode(self, node: P.TableScanNode) -> Page:
        conn = self.session.catalogs[node.catalog]
        splits = conn.get_splits(node.schema, node.table, 1)
        datas = [conn.scan(s, node.column_names) for s in splits]
        cols: List[Column] = []
        for name, typ in zip(node.column_names, node.column_types):
            parts = [d[name] for d in datas]
            vals = np.concatenate([p.values for p in parts]) if len(parts) > 1 else parts[0].values
            nulls = None
            if any(p.nulls is not None for p in parts):
                nulls = np.concatenate(
                    [
                        p.nulls if p.nulls is not None else np.zeros(len(p.values), bool)
                        for p in parts
                    ]
                )
            dictionary = parts[0].dictionary
            cols.append(
                Column(
                    typ,
                    jnp.asarray(vals),
                    jnp.asarray(nulls) if nulls is not None else None,
                    dictionary,
                )
            )
        return Page(cols)

    def _exec_ValuesNode(self, node: P.ValuesNode) -> Page:
        cols = [
            Column.from_python(t, [r[i] for r in node.rows])
            for i, t in enumerate(node.types)
        ]
        if not cols:
            # zero-column single row (SELECT without FROM)
            return Page([Column(T.BIGINT, jnp.zeros(len(node.rows), dtype=jnp.int64))])
        return Page(cols)

    # --------------------------------------------------------------- filter
    def _exec_FilterNode(self, node: P.FilterNode) -> Page:
        page = self.execute(node.source)
        lv, _ = _lower_expr(node.predicate, page)
        passed = lv.vals if lv.valid is None else (lv.vals & lv.valid)
        sel = passed if page.sel is None else (page.sel & passed)
        return Page(page.columns, sel)

    def _exec_ProjectNode(self, node: P.ProjectNode) -> Page:
        page = self.execute(node.source)
        cols = []
        for e in node.expressions:
            lv, _ = _lower_expr(e, page)
            cols.append(_col_from_lowered(e.type, lv))
        return Page(cols, page.sel)

    # ---------------------------------------------------------- aggregation
    def _exec_AggregationNode(self, node: P.AggregationNode) -> Page:
        page = self.execute(node.source)
        n = page.num_rows
        keys = [_col_to_lowered(page.columns[c]) for c in node.group_channels]
        if node.group_channels:
            gids, rep, num_groups_dev = gb.group_ids(keys, page.sel)
            num_groups = int(num_groups_dev)
            key_cols = gb.gather_group_keys(keys, rep)
        else:
            gids = jnp.zeros((max(n, 1),), dtype=jnp.int32)
            num_groups = 1
            key_cols = []
        cap = max(n, 1)
        out_cols: List[Column] = []
        for i, c in enumerate(node.group_channels):
            src = page.columns[c]
            v, valid = key_cols[i]
            nulls = None if valid is None else ~valid
            out_cols.append(
                Column(
                    src.type,
                    v[:num_groups],
                    nulls[:num_groups] if nulls is not None else None,
                    src.dictionary,
                )
            )
        sel_for_agg = page.sel
        if n == 0:
            # pad a zero-row page so segment ops have shape (1,)
            sel_for_agg = jnp.zeros((1,), dtype=bool)
        for call in node.aggregates:
            col = self._exec_aggregate(call, page, sel_for_agg, gids, cap, n)
            out_cols.append(
                Column(
                    call.output_type,
                    col[0][:num_groups],
                    (~col[1][:num_groups]) if col[1] is not None else None,
                    None,
                )
            )
        return Page(out_cols)

    def _exec_aggregate(self, call: P.AggregateCall, page, sel, gids, cap, n):
        if call.distinct:
            raise NotImplementedError("DISTINCT aggregates: round 2")
        if call.function == "count" and call.arg_channel is None:
            return agg_ops.agg_count_star(sel, gids, cap, max(n, 1))
        arg_col = page.columns[call.arg_channel]
        arg = _col_to_lowered(arg_col)
        if n == 0:
            arg = (jnp.zeros((1,), dtype=arg_col.values.dtype), jnp.zeros((1,), bool))
        if call.function == "count":
            return agg_ops.agg_count(arg, sel, gids, cap)
        if call.function == "sum":
            dt = call.output_type.np_dtype
            return agg_ops.agg_sum(arg, sel, gids, cap, dt)
        if call.function == "avg":
            base = (
                call.output_type.np_dtype
                if call.output_type.is_decimal
                else np.dtype(np.float64)
            )
            s, s_valid = agg_ops.agg_sum(arg, sel, gids, cap, base)
            cnt, _ = agg_ops.agg_count(arg, sel, gids, cap)
            return agg_ops.finish_avg(s, cnt, call.output_type)
        if call.function == "min":
            return agg_ops.agg_min(arg, sel, gids, cap)
        if call.function == "max":
            return agg_ops.agg_max(arg, sel, gids, cap)
        raise NotImplementedError(call.function)

    # -------------------------------------------------------------- joins
    def _exec_JoinNode(self, node: P.JoinNode) -> Page:
        left = self.execute(node.left)
        right = self.execute(node.right)
        if node.join_type in ("semi", "anti"):
            return self._exec_semi(node, left, right)
        if not node.left_keys:
            return self._exec_singleton_cross(node, left, right)
        build_key = join_ops.pack_keys(
            [_col_to_lowered(right.columns[c]) for c in node.right_keys]
        )
        probe_key = join_ops.pack_keys(
            [_col_to_lowered(left.columns[c]) for c in node.left_keys]
        )
        bk_sorted, b_rows, b_live = join_ops.build_side(build_key, right.sel)
        rows, matched = join_ops.probe_unique(bk_sorted, b_rows, b_live, probe_key)
        out_cols = list(left.columns)
        for rc in right.columns:
            v, valid = join_ops.gather_column(_col_to_lowered(rc), rows, matched)
            out_cols.append(Column(rc.type, v, ~valid if valid is not None else None, rc.dictionary))
        if node.join_type == "inner":
            sel = matched if left.sel is None else (left.sel & matched)
        else:  # left outer: probe rows always survive; build cols null when unmatched
            sel = left.sel
        page = Page(out_cols, sel)
        if node.filter is not None:
            lv, _ = _lower_expr(node.filter, page)
            passed = lv.vals if lv.valid is None else (lv.vals & lv.valid)
            if node.join_type == "left":
                raise NotImplementedError("filtered left join: round 2")
            page = Page(out_cols, passed if page.sel is None else page.sel & passed)
        return page

    def _exec_semi(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        build = join_ops.pack_keys(
            [_col_to_lowered(right.columns[c]) for c in node.right_keys]
        )
        probe = join_ops.pack_keys(
            [_col_to_lowered(left.columns[c]) for c in node.left_keys]
        )
        hit = join_ops.membership(build, right.sel, probe)
        keep = hit if node.join_type == "semi" else ~hit
        sel = keep if left.sel is None else left.sel & keep
        return Page(left.columns, sel)

    def _exec_singleton_cross(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        """Cross join against a single-row relation (scalar subquery)."""
        r_live = right.live_count()
        if r_live != 1:
            raise QueryError(
                "Scalar sub-query has returned multiple rows"
                if r_live > 1
                else "Scalar sub-query returned no rows"  # SQL says NULL; round 2
            )
        n = left.num_rows
        # find live row index host-side
        if right.sel is None:
            idx = 0
        else:
            idx = int(np.argmax(np.asarray(right.sel)))
        out_cols = list(left.columns)
        for rc in right.columns:
            v = jnp.broadcast_to(rc.values[idx], (n,))
            nulls = (
                jnp.broadcast_to(rc.nulls[idx], (n,)) if rc.nulls is not None else None
            )
            out_cols.append(Column(rc.type, v, nulls, rc.dictionary))
        page = Page(out_cols, left.sel)
        if node.filter is not None:
            lv, _ = _lower_expr(node.filter, page)
            passed = lv.vals if lv.valid is None else lv.vals & lv.valid
            page = Page(out_cols, passed if page.sel is None else page.sel & passed)
        return page

    # ------------------------------------------------------------- ordering
    def _exec_SortNode(self, node: P.SortNode) -> Page:
        page = self.execute(node.source)
        return self._sorted_page(page, node.sort_channels)

    def _sorted_page(self, page: Page, sort_channels, limit: Optional[int] = None) -> Page:
        n = page.num_rows
        keys = [
            (_col_to_lowered(page.columns[c]), asc, nf) for c, asc, nf in sort_channels
        ]
        order = sort_ops.sort_order(keys, page.sel, n)
        live = page.live_count()
        if limit is not None:
            live = min(live, limit)
        order = order[:live]
        cols = [
            Column(
                c.type,
                c.values[order],
                c.nulls[order] if c.nulls is not None else None,
                c.dictionary,
            )
            for c in page.columns
        ]
        return Page(cols)

    def _exec_TopNNode(self, node: P.TopNNode) -> Page:
        page = self.execute(node.source)
        return self._sorted_page(page, node.sort_channels, limit=node.count)

    def _exec_LimitNode(self, node: P.LimitNode) -> Page:
        page = self.execute(node.source)
        return self._sorted_page(page, [], limit=node.count)

    def _exec_OutputNode(self, node: P.OutputNode) -> Page:
        return self.execute(node.source)


@dataclasses.dataclass
class QueryResult:
    column_names: List[str]
    columns: List[Column]
    rows: List[tuple]

    def __repr__(self):
        return f"QueryResult({self.column_names}, {len(self.rows)} rows)"
