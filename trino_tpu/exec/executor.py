"""Plan executor: fully traceable array program over device Pages.

Reference: the worker execution engine — ``LocalExecutionPlanner.java:532``
turning plan nodes into operator pipelines + ``Driver.java:372``'s page loop.
TPU-first difference (SURVEY.md §7.1): no page-at-a-time pull loop — each
plan node is a whole-column array transformation with *static shapes*:
filters keep selection masks instead of compacting, aggregations emit
padded outputs with a live-group prefix, sorts move dead rows last. Because
every step is shape-static and host-sync-free, the entire query body can be
traced once and compiled by XLA (``exec.compiled``), and the same recursion
runs under ``shard_map`` for multi-chip SPMD (``parallel.spmd``).

Data-dependent runtime errors (division by zero, multi-row scalar subquery)
are collected as boolean flags and checked once after execution — the
deferred-error contract of ops/expr_lower.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.data.page import Column, Page
from trino_tpu.exec import memory as _mem
from trino_tpu.exec.operator_stats import OperatorStats
from trino_tpu.obs import metrics as M
from trino_tpu.ops import aggregate as agg_ops
from trino_tpu.ops import expr_lower as L
from trino_tpu.ops import fused_join as fused_ops
from trino_tpu.ops import groupby as gb
from trino_tpu.ops import join as join_ops
from trino_tpu.ops import ranks as ranks_ops
from trino_tpu.ops import segments as seg
from trino_tpu.ops import sort as sort_ops
from trino_tpu.sql import ir
from trino_tpu.sql.planner import plan as P


class QueryError(RuntimeError):
    def __init__(self, message: str, code: str = ""):
        super().__init__(message)
        self.code = code


def raise_query_errors(codes, flags):
    """Raise the first deferred runtime error whose flag fired. Shared by
    the eager, compiled, and SPMD paths."""
    import numpy as _np

    for code, flag in zip(codes, flags):
        if bool(_np.asarray(flag).any()):
            raise QueryError(code.replace("_", " ").capitalize(), code=code)


def _col_from_lowered(t: T.Type, lv: L.LoweredVal) -> Column:
    nulls = None if lv.valid is None else ~lv.valid
    children = None
    if lv.children is not None:
        children = [
            _col_from_lowered(ct, k) for ct, k in zip(T.type_children(t), lv.children)
        ]
        return Column(t, lv.vals, nulls, None, children=children)
    # a static |value| bound proven by the lowering becomes the column's
    # vrange, so downstream consumers (sum's int64-vs-limb choice, physical
    # narrowing) keep their fast paths for projected expressions
    vrange = (-lv.bound, lv.bound) if lv.bound is not None and lv.hi is None else None
    return Column(t, lv.vals, nulls, lv.dictionary, vrange, hi=lv.hi)


def _col_to_lowered(c: Column) -> join_ops.Lowered:
    return (c.values, None if c.nulls is None else ~c.nulls)


def _key_lowereds(c: Column, force_two_limb: bool = False) -> List[join_ops.Lowered]:
    """Key operands for grouping/joining/sorting one column. Two-limb long
    decimals (Column.hi) contribute TWO lexicographic key operands:
    (hi, lo-with-flipped-sign-bit) — the flip makes the unsigned low word
    order correctly as a signed int64, and equality is flip-invariant, so
    the same pair serves hash, merge, and order comparisons (reference:
    Int128.compareTo = compare hi, then unsigned lo). ``force_two_limb``
    expands a single-limb column the same way (sign-extended hi) so the
    two sides of a join stay symmetric."""
    if c.hi is None and not force_two_limb:
        return [_col_to_lowered(c)]
    valid = None if c.nulls is None else ~c.nulls
    lo = c.values.astype(jnp.int64)
    hi = c.hi if c.hi is not None else (lo >> 63)
    return [(hi, valid), (lo ^ jnp.int64(-(2**63)), valid)]


def _column_from_data(cd) -> Column:
    """ColumnData -> device Column, recursing into nested children."""
    return Column(
        cd.type,
        jnp.asarray(np.asarray(cd.values)),
        jnp.asarray(cd.nulls) if cd.nulls is not None else None,
        cd.dictionary,
        cd.vrange,
        ascending=bool(getattr(cd, "sorted", False)),
        children=(
            [_column_from_data(k) for k in cd.children]
            if cd.children is not None
            else None
        ),
        hi=jnp.asarray(cd.hi) if cd.hi is not None else None,
    )


def scan_constraint_with(node: "P.TableScanNode", dyn_domains):
    """Effective TupleDomain for a scan: static pushdown ∩ available
    dynamic-filter domains (reference: DynamicFilter.getCurrentPredicate).
    Shared by the eager executor and the staged tiers (compiled/SPMD)."""
    from trino_tpu.connector.predicate import TupleDomain

    td = node.constraint
    for join_id, key_idx, column in node.dynamic_filters or ():
        dom = dyn_domains.get((join_id, key_idx))
        if dom is None:
            continue
        extra = TupleDomain({column: dom})
        td = extra if td is None else td.intersect(extra)
    return td


def dynamic_domain_map(node, dyn_domains):
    """column -> available dynamic-filter Domain for a scan (intersecting
    when several joins filter the same column). Shared by the phase-1 host
    evaluator and the scan-time enforcer so both always agree on which rows
    survive."""
    dyn = {}
    for join_id, key_idx, column in node.dynamic_filters or ():
        dom = dyn_domains.get((join_id, key_idx))
        if dom is None or dom.is_all():
            continue
        dyn[column] = dom.intersect(dyn[column]) if column in dyn else dom
    return dyn


def apply_dynamic_domains(node, dyn_domains, datas, allow=None):
    """Engine-side enforcement of a scan's available dynamic-filter domains
    on host-side scanned data: connectors treat constraints as ADVISORY (the
    tpch generator prunes only via its monotone key), so the scan operator
    itself drops rows outside the domain before device transfer — the
    reference's ScanFilterAndProjectOperator applying
    DynamicFilter.getCurrentPredicate. Varchar domains are skipped
    (dictionary codes are page-local). ``allow(column, domain)`` restricts
    which domains apply here (the compiled tier splits strong domains —
    host row pruning cuts the device transfer — from weak ones it enforces
    on device)."""
    import dataclasses as _dc

    from trino_tpu.exec.host_eval import domain_mask

    dyn = dynamic_domain_map(node, dyn_domains)
    if allow is not None:
        dyn = {c: d for c, d in dyn.items() if allow(node, c, d)}
    if not dyn:
        return datas
    out = []
    for d in datas:
        if not d:
            out.append(d)
            continue
        n = len(next(iter(d.values())).values)
        keep = np.ones(n, dtype=bool)
        for column, dom in dyn.items():
            cd = d.get(column)
            if cd is None or cd.dictionary is not None:
                continue
            keep &= domain_mask(
                dom,
                np.asarray(cd.values),
                np.asarray(cd.nulls) if cd.nulls is not None else None,
            )
        if keep.all():
            out.append(d)
            continue
        from trino_tpu.connector.spi import column_data_take

        out.append({name: column_data_take(cd, keep) for name, cd in d.items()})
    return out


class Executor:
    """Traceable plan interpreter. ``execute_checked`` runs eagerly and
    raises deferred errors; the recursion itself (``execute``) is pure and
    jit-safe."""

    # Eager tier: host-side recursion over concrete arrays (the local path
    # and worker fragments). Traced subclasses (PreloadedExecutor,
    # SpmdExecutor) run under jax tracing where host-side syncs (stats,
    # dynamic-filter domains, spill partitioning) are impossible.
    eager_tier = True
    enable_dynamic_filtering = True  # AND-ed with the session property
    collect_stats = True  # per-operator wall/rows (traced subclasses: False)
    # Row-level dynamic-domain enforcement at the scan: host-side numpy here
    # (concrete arrays); the compiled tier stages full pages and enforces ON
    # DEVICE instead (searchsorted membership + compact ride HBM bandwidth,
    # ~40x the host's — exec/compiled.py StagingExecutor)
    apply_df_host = True

    def __init__(self, session, capacity_hints: Optional[Dict[str, int]] = None):
        self.session = session
        self.errors: List[Tuple[str, jnp.ndarray]] = []
        # M:N join output capacities by plan-node id. Eager runs compute the
        # exact total (one device sync) and record a padded power-of-two here;
        # traced runs (compiled/SPMD) require the hint to pre-exist — the
        # bucketed-recompile strategy of SURVEY.md §7.3 (dynamic shapes).
        self.capacity_hints: Dict[str, int] = capacity_hints if capacity_hints is not None else {}
        # Dynamic filtering (reference: DynamicFilterService): build-side key
        # domains by (join_id, key_index), produced when joins execute their
        # build side, consumed by probe-side scans. Eager execution only —
        # traced subclasses (PreloadedExecutor/SpmdExecutor) stage scans
        # before tracing and override the class flag (Tracers have no
        # concrete min/max).
        self.dyn_domains: Dict[Tuple[int, int], object] = {}
        # host seconds spent applying dynamic domains at scans (benchmarks
        # charge this to the query: it is join work moved off-device)
        self.df_apply_s = 0.0
        # rows materialized per scan plan-node id (EXPLAIN/pushdown tests)
        self.scan_stats: Dict[int, int] = {}
        # device-cache disposition per scan plan-node id ("hit" | "miss" |
        # "bypass"): a "hit" staged ZERO host->device bytes — callers use
        # this to keep staged-rows accounting honest (trino_tpu/devcache/)
        self.scan_cache: Dict[int, str] = {}
        # per-operator stats by plan-node id (EXPLAIN ANALYZE, task status):
        # typed OperatorStats ACCUMULATED across repeated node executions
        # (reference: OperatorContext/OperatorStats — SURVEY.md §5.1)
        self.node_stats: Dict[int, OperatorStats] = {}
        # rows a node produced on its LATEST execution — parents read their
        # children's entries to charge input_rows per invocation
        self._last_output_rows: Dict[int, int] = {}
        # stack of accumulated child wall time: operators recursively
        # execute their sources inside method(node), so per-operator wall
        # must subtract the subtree's time to be EXCLUSIVE (the reference's
        # OperatorStats semantics — summing operators then equals the query)
        self._child_wall: List[float] = [0.0]
        # (splits, scanned_rows) staged by the scan method that just ran,
        # consumed by the execute() wrapper into the scan's OperatorStats
        self._pending_scan: Dict[int, Tuple[int, int]] = {}
        # bytes a node produced on its LATEST execution — parents charge
        # input bytes per invocation (kernel-ledger input side)
        self._last_output_bytes: Dict[int, int] = {}
        # device profiler (obs/devprofiler.py): per-(node, operator)
        # kernel rollups — launches, wall vs device seconds, bytes.
        # Accumulated here, folded ONCE at task/query completion.
        self.kernel_stats: Dict[Tuple[int, str], dict] = {}
        # device-memory budget + spill decisions (exec/memory.py; reference:
        # lib/trino-memory-context + the spill FSMs). Property name mirrors
        # the reference's query_max_memory_per_node.
        from trino_tpu.exec.memory import MemoryContext

        props = (
            session.properties
            if session is not None and hasattr(session, "properties")
            else {}
        ) or {}
        self.memory = MemoryContext(props.get("query_max_device_memory"))
        if not props.get("dynamic_filtering_enabled", True):
            self.enable_dynamic_filtering = False
        self.spill_enabled = bool(props.get("spill_enabled", True))
        # device_profiling session property: when on, each dispatch is
        # block_until_ready-bracketed so device seconds are measured;
        # when off (default) NO sync is added — device seconds are
        # estimated from wall and only zero-sync counting happens
        self.profile_sync = bool(props.get("device_profiling", False))

    # ------------------------------------------------------------------ api
    def execute_checked(self, node: P.PlanNode) -> Page:
        page = self.execute(node)
        self.raise_errors()
        return page

    def raise_errors(self):
        raise_query_errors([c for c, _ in self.errors], [f for _, f in self.errors])

    def execute(self, node: P.PlanNode) -> Page:
        method = getattr(self, f"_exec_{type(node).__name__}", None)
        if method is None:
            raise NotImplementedError(f"executor: {type(node).__name__}")
        if not self.collect_stats:
            return method(node)
        # per-operator profiling, always on in the eager tier (reference:
        # OperatorContext/OperatorStats via OperationTimer — SURVEY.md §5.1)
        self._child_wall.append(0.0)
        t0 = time.perf_counter()
        try:
            page = method(node)
        finally:
            # keep the stack balanced on error paths: the parent is still
            # charged the subtree's time
            wall = time.perf_counter() - t0
            child_wall = self._child_wall.pop()
            self._child_wall[-1] += wall
        excl_wall = max(0.0, wall - child_wall)
        # kernel ledger (obs/devprofiler.py): device seconds per dispatch.
        # profile_sync ON: eager jax dispatch returns before the math
        # finishes — the block_until_ready wait IS the device time, and
        # excl_wall (dispatch + host glue) minus it is the overhead.
        # OFF: zero-sync estimate — device ≈ exclusive wall, flagged.
        device_s = excl_wall
        estimated = True
        if self.profile_sync:
            t_sync = time.perf_counter()
            try:
                jax.block_until_ready([c.values for c in page.columns])
            except Exception:  # noqa: BLE001 — profiling never fails work
                pass
            device_s = time.perf_counter() - t_sync
            estimated = False
            # the sync wait is elapsed time inside THIS node's subtree:
            # charge it to the parent's child ledger so the parent's
            # exclusive wall stays exclusive of it
            self._child_wall[-1] += device_s
        live = page.live_count()  # live rows, not padded slots
        nbytes = _mem.page_bytes(page)
        st = self.node_stats.get(node.id)
        if st is None:
            st = self.node_stats[node.id] = OperatorStats(
                node.id, type(node).__name__.replace("Node", ""))
        # accumulate, never overwrite: a node re-executed (per probe batch,
        # per split) ADDS its rows/bytes/time, so rollups stay additive.
        # Wall is EXCLUSIVE (children's recursive time subtracted), so the
        # per-operator-kind metrics and rollups sum to the fragment body.
        st.wall_s += max(0.0, wall - child_wall)
        st.output_rows += live
        st.output_bytes += nbytes
        st.invocations += 1
        st.peak_bytes = max(st.peak_bytes, nbytes)
        st.input_rows += sum(
            self._last_output_rows.get(s.id, 0) for s in node.sources)
        splits, scanned = self._pending_scan.pop(node.id, (0, 0))
        st.splits += splits
        st.input_rows += scanned  # scans: connector rows are the input side
        # kernel-ledger rollup: one "launch" per node execution in the
        # eager tier (each _exec_ dispatches this node's device ops).
        # Wall here is EXCLUSIVE (matches st.wall_s), with the measured
        # sync wait added back when profiling — wall − device = the
        # per-operator dispatch overhead megakernels must beat.
        in_bytes = sum(
            self._last_output_bytes.get(s.id, 0) for s in node.sources)
        kwall = excl_wall + (device_s if not estimated else 0.0)
        kkey = (node.id, st.operator)
        ks = self.kernel_stats.get(kkey)
        if ks is None:
            ks = self.kernel_stats[kkey] = {
                "planNodeId": str(node.id), "operator": st.operator,
                "tier": "eager", "launches": 0, "wallS": 0.0,
                "deviceS": 0.0, "inputBytes": 0, "outputBytes": 0,
                "estimated": estimated}
        ks["launches"] += 1
        ks["wallS"] += kwall
        ks["deviceS"] += device_s
        ks["inputBytes"] += in_bytes
        ks["outputBytes"] += nbytes
        ks["estimated"] = bool(ks["estimated"] or estimated)
        try:
            from trino_tpu.obs.devprofiler import DEVICE_PROFILER

            DEVICE_PROFILER.count_launch(kwall, device_s
                                         if not estimated else 0.0)
        except Exception:  # noqa: BLE001 — accounting never fails work
            pass
        self._last_output_bytes[node.id] = nbytes
        self._last_output_rows[node.id] = live
        # operator-output reservation rolls into the query's peak (the
        # LocalMemoryContext -> query-pool rollup, exact from static shapes)
        self.memory.observe(nbytes)
        return page

    def _narrowed_or_flag(self, col: Column, sel=None) -> Column:
        """Degrade a two-limb long-decimal column to its low word for
        consumers without limb support (window args, map-building aggregate
        keys, ...): LIVE rows whose value does not fit int64 raise the
        deferred DECIMAL_OVERFLOW error — exactly the pre-limb-storage
        contract, so in-range data keeps working and out-of-range data
        fails loudly instead of silently truncating."""
        if col.hi is None:
            return col
        fits = col.hi == (col.values.astype(jnp.int64) >> 63)
        if col.nulls is not None:
            fits = fits | col.nulls
        if sel is not None:
            fits = fits | ~sel
        self.errors.append((L.DECIMAL_OVERFLOW, jnp.any(~fits)))
        return Column(col.type, col.values, col.nulls, col.dictionary)

    def _narrow_lowered_or_flag(self, arg, hi_l, sel_l=None):
        """The layout-space analog of _narrowed_or_flag for payload pairs."""
        if hi_l is None:
            return arg
        vals_l, valid_l = arg
        fits = hi_l == (vals_l.astype(jnp.int64) >> 63)
        if valid_l is not None:
            fits = fits | ~valid_l
        if sel_l is not None:
            fits = fits | ~sel_l
        self.errors.append((L.DECIMAL_OVERFLOW, jnp.any(~fits)))
        return arg

    def _lower(self, e: ir.Expr, page: Page) -> L.LoweredVal:
        ctx = L.LowerCtx(page.columns, page.num_rows, page.sel)
        out = L.lower(e, ctx)
        for code, flag in ctx.errors:
            self.errors.append((code, flag))
        return out

    # ----------------------------------------------------------------- scan
    def scan_constraint(self, node: P.TableScanNode):
        return scan_constraint_with(node, self.dyn_domains)

    def _host_applied_domains(self, node: P.TableScanNode) -> Dict:
        """The dynamic domains this executor will physically apply at the
        scan (the host-pruning subset) — part of the cache signature: two
        executors with the same constraint but different applied sets
        stage DIFFERENT pages (trino_tpu/devcache/keys.py)."""
        if not self.apply_df_host:
            return {}
        dyn = dynamic_domain_map(node, self.dyn_domains)
        allow = getattr(self, "df_host_allow", None)
        if allow is not None:
            dyn = {c: d for c, d in dyn.items() if allow(node, c, d)}
        return dyn

    def _exec_TableScanNode(self, node: P.TableScanNode) -> Page:
        from trino_tpu import devcache
        from trino_tpu.exec import staging

        conn = self.session.catalogs[node.catalog]
        constraint = self.scan_constraint(node)
        applied = self._host_applied_domains(node)

        def load():
            # adaptive split sizing: fan big tables out over the staging
            # pool (pushdown handles stay single-split — the guard is
            # inside target_split_count)
            target = staging.target_split_count(
                self.session, conn, node.schema, node.table,
                handle=node.table_handle)
            splits = conn.get_splits(
                node.schema, node.table, target, constraint=constraint,
                handle=node.table_handle)
            prune = None
            if self.apply_df_host:
                allow = getattr(self, "df_host_allow", None)

                def prune(datas):
                    return apply_dynamic_domains(
                        node, self.dyn_domains, datas, allow=allow)

            page, scanned, prof = staging.staged_scan_page(
                self.session, node, conn, splits, constraint,
                prune=prune, applied_domains=applied)
            if self.apply_df_host:
                # CUMULATIVE host domain-application seconds across the
                # scan threads (StageProfile.prune_s): under a parallel
                # fan-out this is CPU-seconds of host work, which can
                # exceed the staging wall — the honest measure of "work
                # a run repeats", but not a wall clock. The PR 7
                # accounting identity (STAGING_SECONDS charges exactly
                # phase1_s + df_apply_s) holds by construction either
                # way; at parallelism 1 it equals the old serial wall.
                self.df_apply_s += prof.prune_s
            return page, scanned, _mem.page_bytes(page), len(splits)

        ent, disposition = devcache.cached_stage(
            self.session, node, constraint, applied, "table", load)
        self.scan_cache[node.id] = disposition
        self.scan_stats[node.id] = ent.rows
        self._pending_scan[node.id] = (ent.splits, ent.rows)
        return ent.value

    def _exec_ValuesNode(self, node: P.ValuesNode) -> Page:
        cols = [
            Column.from_python(t, [r[i] for r in node.rows])
            for i, t in enumerate(node.types)
        ]
        # identical on every device under SPMD -> replicated
        if not cols:
            # zero-column single row (SELECT without FROM)
            return Page(
                [Column(T.BIGINT, jnp.zeros(len(node.rows), dtype=jnp.int64))],
                replicated=True,
            )
        return Page(cols, replicated=True)

    # -------------------------------------------------------------- set ops
    def _exec_UnionNode(self, node: P.UnionNode) -> Page:
        """UNION ALL: row-wise page concatenation (static shapes: total =
        sum of branch capacities; dead rows stay dead)."""
        pages = [self.execute(s) for s in node.sources_]
        out = pages[0]
        for p in pages[1:]:
            out = Page.concat_pages(out, p)
        return out

    def _exec_SetOpNode(self, node: P.SetOpNode) -> Page:
        left = self.execute(node.left)
        right = self.execute(node.right)
        return self.set_op_pages(node, left, right)

    def set_op_pages(self, node: P.SetOpNode, left: Page, right: Page) -> Page:
        """INTERSECT/EXCEPT DISTINCT via the grouping machinery: concat both
        sides with a side tag, group by ALL columns (grouping equality makes
        NULLs compare equal — the set-operation semantics), then keep groups
        by per-side presence counts. Reference: SetOperationNodeTranslator's
        aggregation-based lowering."""
        both = Page.concat_pages(left, right)
        n_l = left.num_rows
        side_right = jnp.arange(both.num_rows) >= n_l
        return self._set_op_grouped(node, both, side_right)

    def _set_op_grouped(self, node: P.SetOpNode, both: Page, side_right) -> Page:
        """The grouping half of a set operation over a combined page with an
        explicit per-row side tag — reused by the SPMD tier after a
        whole-row hash exchange (where positional tagging is impossible)."""
        n = both.num_rows
        layout, out_sel, (side_right_l,), sel_l = self.group_structure(
            list(range(both.channel_count)), both, [side_right]
        )
        l_cnt = seg.seg_sum(layout, (~side_right_l).astype(jnp.int64), sel_l, jnp.int64)
        r_cnt = seg.seg_sum(layout, side_right_l.astype(jnp.int64), sel_l, jnp.int64)
        if node.op == "intersect":
            keep = (l_cnt > 0) & (r_cnt > 0)
        else:  # except
            keep = (l_cnt > 0) & (r_cnt == 0)
        out_cols = self._gathered_key_cols(
            both, list(range(both.channel_count)), layout
        )
        return Page(out_cols, out_sel & keep, both.replicated)

    # --------------------------------------------------------------- filter
    def _exec_FilterNode(self, node: P.FilterNode) -> Page:
        page = self.execute(node.source)
        lv = self._lower(node.predicate, page)
        passed = lv.vals if lv.valid is None else (lv.vals & lv.valid)
        sel = passed if page.sel is None else (page.sel & passed)
        return Page(page.columns, sel, page.replicated)

    def _exec_CompactNode(self, node: P.CompactNode) -> Page:
        """Squeeze live rows into a smaller static-capacity page: ONE stable
        payload-carrying sort by the dead flag (live rows first, original
        order kept), then a static truncation to the capacity hint. Skipped
        when it cannot help (no selection mask, or capacity >= the page's
        rows — e.g. an SPMD shard already smaller than the global
        estimate). Overflow raises CAPACITY_EXCEEDED:cmp:<id> for the
        recompile-growth loop."""
        page = self.execute(node.source)
        if page.sel is None:
            return page
        capacity = self.hint_capacity(f"cmp:{node.id}", page.sel.astype(jnp.int32))
        return self.compact_to(page, capacity, f"cmp:{node.id}")

    def compact_to(self, page: Page, capacity: int, key: str) -> Page:
        """Squeeze live rows into a ``capacity``-slot page: ONE stable
        key-only sort of (dead flag, iota) for the live-first permutation,
        then ONE batched row-gather per dtype group at the first
        ``capacity`` indices — gathering only the KEPT rows (capacity), not
        all n, and never carrying the payload columns through the sort
        network (a 6M-row multi-payload lax.sort costs ~5x the flag sort).
        Original row order is kept (stable). Overflow raises
        CAPACITY_EXCEEDED:<key> for the recompile-growth loop. Shared by
        CompactNode and the device-side dynamic-filter scans."""
        from trino_tpu.ops import ranks as ranks_ops

        n = page.num_rows
        if page.sel is None or capacity >= n:
            return page
        if any(c.type.is_nested for c in page.columns):
            # device row-gathers cannot re-flatten variable-length children
            # (data-dependent shapes); keep the selection mask instead —
            # semantically identical, just uncompacted
            return page
        live = page.sel
        total = jnp.sum(live.astype(jnp.int32))
        self.errors.append((f"CAPACITY_EXCEEDED:{key}", total > capacity))
        _, order = jax.lax.sort(
            (~live, jnp.arange(n, dtype=jnp.int32)), num_keys=1, is_stable=True
        )
        idx = order[:capacity]
        arrays = []
        for c in page.columns:
            arrays.append(c.values)
            if c.nulls is not None:
                arrays.append(c.nulls)
            if c.hi is not None:
                arrays.append(c.hi)
        gathered = ranks_ops.batched_gather(arrays, idx)
        cols = []
        i = 0
        for c in page.columns:
            v = gathered[i]
            i += 1
            nulls = None
            if c.nulls is not None:
                nulls = gathered[i]
                i += 1
            chi = None
            if c.hi is not None:
                chi = gathered[i]
                i += 1
            # stable: live rows keep their relative order -> ascending holds
            cols.append(Column(c.type, v, nulls, c.dictionary, c.vrange,
                               ascending=c.ascending, hi=chi))
        sel = jnp.arange(capacity, dtype=jnp.int32) < jnp.minimum(total, capacity)
        return Page(cols, sel, page.replicated, live_prefix=True)

    def _exec_ProjectNode(self, node: P.ProjectNode) -> Page:
        page = self.execute(node.source)
        cols = []
        for e in node.expressions:
            if isinstance(e, ir.ColumnRef):
                # pass-through: reuse the column wholesale (keeps vrange,
                # dictionary, and sort-order metadata; skips re-lowering)
                cols.append(page.columns[e.index])
                continue
            lv = self._lower(e, page)
            cols.append(_col_from_lowered(e.type, lv))
        return Page(cols, page.sel, page.replicated,
                    live_prefix=page.live_prefix)

    # -------------------------------------------------------------- unnest
    def _exec_UnnestNode(self, node: P.UnnestNode) -> Page:
        page = self.execute(node.source)
        return self.unnest_page(node, page)

    def unnest_page(self, node: P.UnnestNode, page: Page) -> Page:
        """Static-shape UNNEST expansion (plan.py UnnestNode docstring).

        Output capacity = total flat element count across the unnested
        expressions (the exact row count for the single-array case; an upper
        bound when zipping several). Per-output-slot parent rows come from
        one searchsorted over the output offsets; every produced column is
        either a parent-row gather (replicated channels) or a flat-child
        gather at ``child_offset[parent] + position`` (unnested channels)."""
        from trino_tpu.ops import array_ops as A

        n = page.num_rows
        lows = [self._lower(e, page) for e in node.unnest_exprs]
        for lv in lows:
            if lv.children is None:
                raise NotImplementedError("UNNEST argument must be array/map-typed")
        for c in node.replicate_channels:
            if page.columns[c].type.is_nested:
                raise NotImplementedError(
                    "replicating an array/map column through UNNEST "
                    "(project it before/after instead)"
                )
        raw_lens = [lv.vals.astype(jnp.int32) for lv in lows]
        eff_lens = [
            jnp.where(lv.valid, ln, 0) if lv.valid is not None else ln
            for lv, ln in zip(lows, raw_lens)
        ]
        out_len = eff_lens[0]
        for ln in eff_lens[1:]:
            out_len = jnp.maximum(out_len, ln)
        if page.sel is not None:
            out_len = jnp.where(page.sel, out_len, 0)
        out_offsets = A.offsets_from_lengths(out_len)
        capacity = max(
            1, sum(int(lv.children[0].vals.shape[0]) for lv in lows)
        )
        slot = jnp.arange(capacity, dtype=jnp.int32)
        rowid_raw = jnp.searchsorted(out_offsets, slot, side="right").astype(jnp.int32) - 1
        rowid = jnp.clip(rowid_raw, 0, n - 1)
        pos = slot - out_offsets[rowid]  # 0-based position within the parent row
        sel = slot < out_offsets[-1]
        cols: List[Column] = []
        for ci in node.replicate_channels:
            c = page.columns[ci]
            cols.append(
                Column(
                    c.type,
                    c.values[rowid],
                    c.nulls[rowid] if c.nulls is not None else None,
                    c.dictionary,
                    c.vrange,
                )
            )
        child_types = iter(node.output_types[len(node.replicate_channels):])
        for lv, raw_ln in zip(lows, raw_lens):
            child_off = A.offsets_from_lengths(raw_ln)
            in_range = pos < raw_ln[rowid]
            if lv.valid is not None:
                in_range = in_range & lv.valid[rowid]
            for child in lv.children:
                flat = child.vals
                flat_n = int(flat.shape[0])
                safe = flat if flat_n else jnp.zeros((1,), flat.dtype)
                idx = jnp.clip(child_off[rowid] + pos, 0, max(flat_n - 1, 0))
                vals = safe[idx]
                valid = in_range
                if child.valid is not None:
                    cvalid = child.valid if flat_n else jnp.zeros((1,), bool)
                    valid = valid & cvalid[idx]
                cols.append(Column(next(child_types), vals, ~valid, child.dictionary))
        if node.ordinality:
            cols.append(Column(T.BIGINT, (pos + 1).astype(jnp.int64)))
        return Page(cols, sel)

    # ---------------------------------------------------------- aggregation
    def _exec_AggregationNode(self, node: P.AggregationNode) -> Page:
        page = self.execute(node.source)
        if node.step == "partial":
            return self.aggregate_partial(node, page)
        if node.step == "final":
            return self.aggregate_final(node, page)
        return self.aggregate_page(node, page)

    def aggregate_partial(self, node: P.AggregationNode, page: Page) -> Page:
        """Partial aggregation: emit group keys + accumulator-state columns
        (reference: HashAggregationOperator(PARTIAL) shipping
        AccumulatorCompiler intermediate states through an exchange).
        State column types follow plan._acc_types so the page can cross the
        wire (serde needs faithful dtypes)."""
        payload_arrays, slots = self._agg_payloads(node.aggregates, page.columns)
        layout, part_sel, payloads_l, sel_l = self.group_structure(
            node.group_channels, page, payload_arrays
        )
        out_cols: List[Column] = []
        if node.group_channels:
            out_cols.extend(
                self._gathered_key_cols(page, node.group_channels, layout)
            )
        src_types = node.source.output_types
        for call, slot in zip(node.aggregates, slots):
            s1 = slot[0] if slot is not None else None
            hi_l = self._slot_hi(payloads_l, s1)
            arg1 = self._slot_arg(payloads_l, s1)
            if hi_l is not None and call.function not in ("sum", "count"):
                arg1 = self._narrow_lowered_or_flag(arg1, hi_l, sel_l)
                hi_l = None
            states = self._partial_states(
                call, page, layout, arg1, sel_l, hi_l=hi_l,
            )
            state_types = P._acc_types(call, src_types)
            for (sv, valid), st in zip(states, state_types):
                out_cols.append(
                    Column(st, sv, None if valid is None else ~valid, None)
                )
        return Page(out_cols, part_sel, page.replicated)

    def aggregate_final(self, node: P.AggregationNode, page: Page) -> Page:
        """Final aggregation over gathered partial-state pages."""
        k = len(node.group_channels)
        # state columns ride the grouping sort as payloads (layout space)
        payload_arrays: List = []
        state_slots: List = []
        for c in page.columns[k:]:
            if c.hi is not None:
                raise NotImplementedError(
                    "distributed final aggregation over long-decimal states "
                    "beyond int64 (single-process paths support them)"
                )
            vi = len(payload_arrays)
            payload_arrays.append(c.values)
            hv = c.nulls is not None
            if hv:
                payload_arrays.append(~c.nulls)
            state_slots.append((vi, hv, None))
        layout, out_sel, payloads_l, sel_l = self.group_structure(
            list(range(k)), page, payload_arrays
        )
        out_cols: List[Column] = []
        if k:
            out_cols.extend(
                self._gathered_key_cols(page, list(range(k)), layout)
            )
        ci = 0
        for call in node.aggregates:
            # state layout must match what aggregate_partial emitted
            n_states = P._acc_state_count(call)
            states = [
                self._slot_arg(payloads_l, state_slots[ci + j]) for j in range(n_states)
            ]
            ci += n_states
            out_cols.append(self._combine_state(call, states, sel_l, layout))
        return Page(out_cols, out_sel, page.replicated)

    # aggregate functions whose partial STATES merge into states of the
    # same dtypes with plain sum/min/max reductions — the set the streaming
    # consumer's intermediate fold supports (reference:
    # AggregationNode.Step.INTERMEDIATE)
    MERGEABLE_STATE_FNS = {"count", "sum", "avg", "min", "max", "count_if"}

    def aggregate_intermediate(self, node: P.AggregationNode, page: Page) -> Page:
        """Merge partial-state pages into a COMBINED partial-state page of
        the same schema (reference: AggregationNode.Step.INTERMEDIATE —
        the reference inserts these between partial and final exchanges;
        here they are the fold step of the streaming consumer loop: state
        pages accumulate per arriving micro-batch, memory stays
        O(groups + batch) no matter how much the producer emits)."""
        k = len(node.group_channels)
        payload_arrays: List = []
        state_slots: List = []
        for c in page.columns[k:]:
            if c.hi is not None:
                raise NotImplementedError(
                    "intermediate merge over long-decimal two-limb states")
            vi = len(payload_arrays)
            payload_arrays.append(c.values)
            hv = c.nulls is not None
            if hv:
                payload_arrays.append(~c.nulls)
            state_slots.append((vi, hv, None))
        layout, out_sel, payloads_l, sel_l = self.group_structure(
            list(range(k)), page, payload_arrays
        )
        out_cols: List[Column] = []
        if k:
            out_cols.extend(
                self._gathered_key_cols(page, list(range(k)), layout)
            )
        ci = 0
        for call in node.aggregates:
            n_states = P._acc_state_count(call)
            states = [
                self._slot_arg(payloads_l, state_slots[ci + j])
                for j in range(n_states)
            ]
            types = [page.columns[k + ci + j].type for j in range(n_states)]
            ci += n_states
            fn = call.function
            if fn not in self.MERGEABLE_STATE_FNS or call.distinct:
                raise NotImplementedError(f"intermediate merge of {fn}")
            if fn in ("count", "count_if"):
                merged = [agg_ops.agg_sum(layout, states[0], sel_l,
                                          np.dtype(np.int64))]
            elif fn == "sum" and n_states == 2:
                # long-decimal running sum: (lo, hi) limb-pair states merge
                # through the same exact int128 grouped sum the partial used
                lo_vals, lo_valid = states[0]
                hi_vals, _ = states[1]
                (m_hi, m_lo), nonempty = agg_ops.agg_sum_128(
                    layout, lo_vals, hi_vals, lo_valid, sel_l)
                merged = [(m_lo, nonempty), (m_hi, None)]
            elif fn == "sum":
                merged = [agg_ops.agg_sum(layout, states[0], sel_l,
                                          types[0].np_dtype)]
            elif fn == "avg":
                merged = [
                    agg_ops.agg_sum(layout, states[0], sel_l, types[0].np_dtype),
                    agg_ops.agg_sum(layout, states[1], sel_l, np.dtype(np.int64)),
                ]
            elif fn == "min":
                merged = [agg_ops.agg_min(layout, states[0], sel_l)]
            else:  # max
                merged = [agg_ops.agg_max(layout, states[0], sel_l)]
            for (sv, valid), st in zip(merged, types):
                out_cols.append(
                    Column(st, sv, None if valid is None else ~valid, None)
                )
        return Page(out_cols, out_sel, page.replicated)

    def _partial_states(self, call: P.AggregateCall, page, layout, arg_l, sel_l,
                        hi_l=None):
        """State arrays per aggregate: [(values, valid)], layout matching
        plan._acc_types. ``arg_l``/``sel_l`` are in layout space
        (group_structure payloads)."""
        if call.distinct:
            raise NotImplementedError(
                "DISTINCT aggregates cannot be split partial/final (the "
                "planner routes them through a gather exchange instead)"
            )
        sel = sel_l
        if call.function == "count" and call.arg_channel is None:
            v, _ = agg_ops.agg_count_star(layout, sel)
            return [(v, None)]
        arg = arg_l
        if call.function == "count":
            v, _ = agg_ops.agg_count(layout, arg, sel)
            return [(v, None)]
        if call.function == "sum":
            if P._is_long_decimal(call.output_type):
                # two-limb running state (plan._acc_types): exact across the
                # partial/final split for the full p38 range
                vals_l, valid_l = arg
                (s_hi, s_lo), nonempty = agg_ops.agg_sum_128(
                    layout, vals_l, hi_l, valid_l, sel
                )
                return [(s_lo, nonempty), (s_hi, None)]
            return [agg_ops.agg_sum(layout, arg, sel, call.output_type.np_dtype)]
        if call.function == "avg":
            base = (
                call.output_type.np_dtype
                if call.output_type.is_decimal
                else np.dtype(np.float64)
            )
            s, s_valid = agg_ops.agg_sum(layout, arg, sel, base)
            cnt, _ = agg_ops.agg_count(layout, arg, sel)
            return [(s, s_valid), (cnt, None)]
        if call.function == "min":
            return [agg_ops.agg_min(layout, arg, sel)]
        if call.function == "max":
            return [agg_ops.agg_max(layout, arg, sel)]
        if call.function in P._VAR_FAMILY:
            t = page.columns[call.arg_channel].type
            cnt, mean, m2 = agg_ops.var_states(
                layout, arg, sel, t.scale if t.is_decimal else 0
            )
            return [(cnt, None), (mean, None), (m2, None)]
        if call.function == "approx_percentile":
            from trino_tpu.ops import hll

            vals_l, valid_l = arg
            m_l = valid_l if sel is None else (
                sel if valid_l is None else (valid_l & sel))
            return hll.percentile_states(layout, vals_l, m_l)
        if call.function in ("bool_and", "bool_or"):
            fn = agg_ops.agg_min if call.function == "bool_and" else agg_ops.agg_max
            v, valid = fn(layout, arg, sel)
            return [(v.astype(bool), valid)]
        if call.function == "count_if":
            vals_l, valid_l = arg
            m = vals_l if valid_l is None else (vals_l & valid_l)
            v, _ = agg_ops.agg_count_star(layout, m if sel is None else m & sel)
            return [(v, None)]
        raise NotImplementedError(call.function)

    def _combine_state(self, call: P.AggregateCall, states, sel, layout) -> Column:
        """``states``: per-state (values, valid) pairs in layout space; sel
        likewise (see group_structure)."""
        if call.function == "count":
            v, _ = agg_ops.agg_sum(layout, states[0], sel, np.dtype(np.int64))
            return Column(T.BIGINT, v, None, None)
        if call.function == "sum":
            if P._is_long_decimal(call.output_type):
                lo_v, lo_valid = states[0]
                hi_v, _ = states[1]
                (s_hi, s_lo), nonempty = agg_ops.agg_sum_128(
                    layout, lo_v, hi_v, lo_valid, sel
                )
                return Column(call.output_type, s_lo, ~nonempty, None, hi=s_hi)
            v, valid = agg_ops.agg_sum(
                layout, states[0], sel, call.output_type.np_dtype
            )
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        if call.function == "avg":
            base = (
                call.output_type.np_dtype
                if call.output_type.is_decimal
                else np.dtype(np.float64)
            )
            s, _sv = agg_ops.agg_sum(layout, states[0], sel, base)
            cnt, _ = agg_ops.agg_sum(layout, states[1], sel, np.dtype(np.int64))
            v, valid = agg_ops.finish_avg(s, cnt, call.output_type)
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        if call.function == "min":
            v, valid = agg_ops.agg_min(layout, states[0], sel)
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        if call.function == "max":
            v, valid = agg_ops.agg_max(layout, states[0], sel)
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        if call.function in P._VAR_FAMILY:
            cnt_i, m = states[0]
            if sel is not None:
                m = sel if m is None else (m & sel)
            cnt, mean, m2 = agg_ops.combine_var_states(
                layout, cnt_i, states[1][0], states[2][0], m
            )
            v, valid = agg_ops.finish_var(cnt, mean, m2, call.function)
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        if call.function == "approx_percentile":
            from trino_tpu.ops import hll

            cnt_state = states[-1]
            if sel is not None:
                cv, cm = cnt_state
                cnt_state = (jnp.where(sel, cv, jnp.zeros((), cv.dtype)), cm)
            v, valid = hll.percentile_merge(
                layout, states[:-1], cnt_state, call.param)
            return Column(call.output_type, v, None if valid is None else ~valid, None)
        if call.function in ("bool_and", "bool_or"):
            fn = agg_ops.agg_min if call.function == "bool_and" else agg_ops.agg_max
            v, valid = fn(layout, states[0], sel)
            return Column(T.BOOLEAN, v.astype(bool),
                          None if valid is None else ~valid, None)
        if call.function == "count_if":
            v, _ = agg_ops.agg_sum(layout, states[0], sel, np.dtype(np.int64))
            return Column(T.BIGINT, v, None, None)
        raise NotImplementedError(call.function)

    def group_structure(
        self, group_channels: List[int], page: Page, payloads=(), force_sort=False
    ):
        """(GroupLayout, out_sel, payloads_l, sel_l): group assignment.

        Two strategies (the FlatHash vs BigintGroupByHash specialization
        split in the reference, re-chosen for TPU — see ops/segments.py):
        - direct-mapped: all keys are null-free dictionary codes (or
          booleans) with a small cardinality product -> gid is a perfect
          index, NO sort, aggregation via unrolled masked reductions
          (the Q1-shape fast path; out_sel is the occupancy mask, in key
          order).
        - sort-based: exact comparison grouping for arbitrary keys
          (ops/groupby.py); capacity == input length, out_sel a prefix.

        ``payloads`` (e.g. aggregate argument columns) come back in LAYOUT
        SPACE: permuted group-contiguous by the sort for the sorted
        strategy (free payload operands of the one fused lax.sort),
        unchanged for direct layouts. ``sel_l`` is the page's selection in
        that same space (a live-prefix mask after sorting dead rows last).
        """
        n = page.num_rows
        keys = [kl for c in group_channels for kl in _key_lowereds(page.columns[c])]
        sel = page.sel
        if not group_channels:
            gids = jnp.zeros((n,), dtype=jnp.int32)
            layout = seg.direct_layout(gids, 1, sel)
            return layout, jnp.arange(1) < 1, list(payloads), sel
        direct = None if force_sort else self._direct_strides(group_channels, page)
        if direct is not None:
            strides, capacity = direct
            gids = jnp.zeros((n,), dtype=jnp.int32)
            for (vals, _), stride in zip(keys, strides):
                gids = gids + vals.astype(jnp.int32) * stride
            layout = seg.direct_layout(gids, capacity, sel)
            return layout, seg.occupancy(layout, sel), list(payloads), sel
        presorted = self._presorted_group(group_channels, page)
        if presorted is not None:
            # input already group-contiguous (single ascending key, dead
            # rows a tail): boundaries are one elementwise compare — the
            # n·log²n lax.sort, the engine's dominant cost at scale, never
            # runs. Layout space == original row order, so payloads and
            # sel pass through unchanged.
            vals = presorted
            dead = jnp.zeros((n,), bool) if sel is None else ~sel
            neq = vals[1:] != vals[:-1]
            boundary = jnp.concatenate(
                [jnp.ones((1,), bool), neq | (dead[1:] != dead[:-1])])
            gid_sorted = (jnp.cumsum(boundary.astype(jnp.int32)) - 1).astype(jnp.int32)
            num_groups = jnp.sum(boundary & ~dead)
            layout = seg.sorted_layout(
                jnp.arange(n, dtype=jnp.int32), gid_sorted, num_groups)
            return layout, jnp.arange(n) < num_groups, list(payloads), sel
        order, gid_sorted, num_groups, payloads_l = gb.group_plan(keys, sel, payloads)
        layout = seg.sorted_layout(order, gid_sorted, num_groups)
        if sel is None:
            sel_l = None
        else:
            n_live = jnp.sum(sel).astype(jnp.int32)
            sel_l = jnp.arange(n, dtype=jnp.int32) < n_live
        return layout, jnp.arange(n) < num_groups, payloads_l, sel_l

    @staticmethod
    def _agg_payloads(aggregates, columns):
        """(payload_arrays, slots): flatten every non-distinct aggregate
        argument (values + validity) into sort-payload operands; slots maps
        each call to its (index, has_valid) or None (count(*)/DISTINCT)."""
        payload_arrays: List = []
        slots: List = []
        for call in aggregates:
            if call.arg_channel is None or call.distinct:
                slots.append(None)
                continue
            def add(col):
                vi = len(payload_arrays)
                payload_arrays.append(col.values)
                hv = col.nulls is not None
                if hv:
                    payload_arrays.append(~col.nulls)
                hii = None
                if col.hi is not None:  # long-decimal high limb rides along
                    hii = len(payload_arrays)
                    payload_arrays.append(col.hi)
                return (vi, hv, hii)

            s1 = add(columns[call.arg_channel])
            s2 = (
                add(columns[call.arg2_channel])
                if call.arg2_channel is not None
                else None
            )
            slots.append((s1, s2))
        return payload_arrays, slots

    @staticmethod
    def _slot_arg(payloads_l, slot):
        if slot is None:
            return None
        vi, hv, _ = slot
        return (payloads_l[vi], payloads_l[vi + 1] if hv else None)

    @staticmethod
    def _slot_hi(payloads_l, slot):
        """Layout-space high-limb array of the aggregate argument, if any."""
        if slot is None or slot[2] is None:
            return None
        return payloads_l[slot[2]]

    @staticmethod
    def _presorted_group(group_channels: List[int], page: Page):
        """The single group-key column when the page is already
        group-contiguous: key ascending, null-free, dead rows a tail
        (sel None or live-prefix). Returns its values array or None."""
        if len(group_channels) != 1:
            return None
        col = page.columns[group_channels[0]]
        if not col.ascending or col.nulls is not None:
            return None
        if page.sel is not None and not page.live_prefix:
            return None
        return col.values

    @staticmethod
    def _direct_strides(group_channels: List[int], page: Page):
        sizes = []
        for c in group_channels:
            col = page.columns[c]
            if col.nulls is not None:
                return None
            if col.type.is_varchar and col.dictionary is not None:
                sizes.append(max(len(col.dictionary), 1))
            elif col.type == T.BOOLEAN:
                sizes.append(2)
            else:
                return None
        capacity = 1
        for s in sizes:
            capacity *= s
        if not 1 <= capacity <= seg.DIRECT_CAPACITY_MAX:
            return None
        strides = []
        acc = 1
        for s in reversed(sizes):
            strides.append(acc)
            acc *= s
        return list(reversed(strides)), capacity

    def aggregate_page(self, node: P.AggregationNode, page: Page) -> Page:
        """Group and aggregate; output has `capacity` rows, sel marking live
        groups (prefix for the sort path, occupancy mask for the direct
        path — both in group-key order)."""
        if node.group_channels and self.eager_tier:
            spilled = self._maybe_spill_aggregation(node, page)
            if spilled is not None:
                return spilled
        n = page.num_rows
        sel = page.sel
        if n == 0:
            page = Page(
                [
                    Column(c.type, jnp.zeros((1,), dtype=c.values.dtype), None, c.dictionary)
                    for c in page.columns
                ],
                jnp.zeros((1,), dtype=bool),
            )
            n = 1
            sel = page.sel
        payload_arrays, slots = self._agg_payloads(node.aggregates, page.columns)
        # array_agg/histogram/map_agg need group-contiguous rows in layout
        # space (their outputs ARE the per-group row runs); the direct
        # masked-loop layout never permutes, so force the sort strategy
        force_sort = any(
            c.function in ("array_agg", "histogram", "map_agg")
            for c in node.aggregates
        )
        layout, out_sel, payloads_l, sel_l = self.group_structure(
            node.group_channels, page, payload_arrays, force_sort=force_sort
        )
        out_cols: List[Column] = []
        if node.group_channels:
            out_cols.extend(
                self._gathered_key_cols(page, node.group_channels, layout)
            )
        for call, slot in zip(node.aggregates, slots):
            s1, s2 = slot if slot is not None else (None, None)
            if call.function in ("array_agg", "histogram", "map_agg"):
                if call.distinct:
                    raise NotImplementedError(
                        f"{call.function}(DISTINCT): not yet supported")
                out_cols.append(
                    self._nested_agg_column(
                        call, page, layout,
                        self._slot_arg(payloads_l, s1),
                        self._slot_arg(payloads_l, s2) if s2 is not None else None,
                        sel_l,
                        hi_l=self._slot_hi(payloads_l, s1),
                    )
                )
                continue
            res = self._exec_aggregate(
                call, page, sel, layout, self._slot_arg(payloads_l, s1), sel_l,
                hi_l=self._slot_hi(payloads_l, s1),
                arg2_l=self._slot_arg(payloads_l, s2) if s2 is not None else None,
                hi2_l=self._slot_hi(payloads_l, s2) if s2 is not None else None,
            )
            vals, valid = res[0], res[1]
            hi_out = res[2] if len(res) > 2 else None
            # value-carrying aggregates keep the argument's dictionary
            dictionary = None
            if call.function in ("min", "max", "arbitrary", "any_value",
                                 "min_by", "max_by") and call.arg_channel is not None:
                dictionary = page.columns[call.arg_channel].dictionary
            out_cols.append(
                Column(
                    call.output_type,
                    vals,
                    (~valid) if valid is not None else None,
                    dictionary,
                    hi=hi_out,
                )
            )
        return Page(out_cols, out_sel, page.replicated)

    def _gathered_key_cols(self, page: Page, channels, layout) -> List[Column]:
        """Output group-key columns gathered at each slot's representative
        row, rebuilding two-limb long decimals from their (hi, lo-flipped)
        key operand pairs (_key_lowereds)."""
        keys, spans = [], []
        for c in channels:
            parts = _key_lowereds(page.columns[c])
            spans.append((len(keys), len(parts)))
            keys.extend(parts)
        key_cols = gb.gather_group_keys(keys, layout.rep)
        out = []
        for (start, cnt), c in zip(spans, channels):
            src = page.columns[c]
            if cnt == 2:
                hi_v, valid = key_cols[start]
                lo_flip, _ = key_cols[start + 1]
                lo = lo_flip ^ jnp.int64(-(2**63))
                out.append(
                    Column(src.type, lo, None if valid is None else ~valid,
                           None, hi=hi_v)
                )
            else:
                v, valid = key_cols[start]
                out.append(
                    Column(src.type, v, None if valid is None else ~valid,
                           src.dictionary, src.vrange)
                )
        return out

    def _nested_agg_column(self, call, page, layout, arg_l, arg2_l, sel_l,
                           hi_l=None) -> Column:
        """Aggregates with nested (array/map) outputs.

        array_agg: the output array column IS the group-contiguous row runs
        of the grouping sort — per-slot lengths are the group ranges, the
        flat child is the (layout-space) argument column itself. NULL inputs
        are kept as NULL elements (reference: ArrayAggregationFunction).
        Sorted layouts put live rows first, group-contiguous from position
        0, so cumsum(lengths) == starts for every live slot and the flat
        child aligns with no extra gather. The global (no GROUP BY) case
        rides the direct single-slot layout: live rows compact to a prefix
        with one stable flag sort.

        histogram / map_agg re-group on (group, key) pairs (ops/aggregate.py
        grouped_pairs): each distinct pair is one map entry; histogram's
        values are the run counts, map_agg's the representative row's value
        (duplicate keys keep an arbitrary one, matching the reference)."""
        if call.function in ("histogram", "map_agg"):
            return self._map_agg_column(call, page, layout, sel_l)
        vals_l, valid_l = arg_l
        src = page.columns[call.arg_channel]
        elem_t = call.output_type.element
        if layout.is_direct:
            assert layout.capacity == 1, "grouped array_agg must use a sorted layout"
            n = layout.n
            if sel_l is None:
                flat, flat_valid, flat_hi = vals_l, valid_l, hi_l
                count = jnp.int32(n)
            else:
                order = jax.lax.sort(
                    (~sel_l, jnp.arange(n, dtype=jnp.int32)), num_keys=1,
                    is_stable=True,
                )[1]
                flat = vals_l[order]
                flat_valid = valid_l[order] if valid_l is not None else None
                flat_hi = hi_l[order] if hi_l is not None else None
                count = jnp.sum(sel_l.astype(jnp.int32))
            lengths = count[None].astype(jnp.int32)
        else:
            lengths = (layout.ends - layout.starts).astype(jnp.int32)
            flat, flat_valid, flat_hi = vals_l, valid_l, hi_l
        child = Column(
            elem_t, flat, None if flat_valid is None else ~flat_valid, src.dictionary,
            hi=flat_hi,
        )
        # SQL: an aggregate over zero rows is NULL (a zero-length group can
        # only arise from an empty input set)
        return Column(call.output_type, lengths, lengths == 0, children=[child])

    def _map_agg_column(self, call, page, layout, sel_l) -> Column:
        """histogram(x) / map_agg(k, v) over original-order page columns
        (grouped_pairs re-sorts internally; null keys drop per SQL)."""
        # keys/values without limb kernels degrade to the low word with a
        # deferred overflow check (see _narrowed_or_flag)
        key_col = self._narrowed_or_flag(page.columns[call.arg_channel], page.sel)
        key = _col_to_lowered(key_col)
        # sel must be in ORIGINAL row order here (grouped_pairs resorts)
        entry_counts, rep, run_counts, entry_live = agg_ops.grouped_pairs(
            layout, key, page.sel
        )
        keys_flat = Column(
            call.output_type.key, key_col.values[rep], None, key_col.dictionary
        )
        if call.function == "histogram":
            vals_flat = Column(T.BIGINT, run_counts)
        else:
            vcol = page.columns[call.arg2_channel]
            vvals = vcol.values[rep]
            vnulls = vcol.nulls[rep] if vcol.nulls is not None else None
            vhi = vcol.hi[rep] if vcol.hi is not None else None
            vals_flat = Column(call.output_type.value, vvals, vnulls,
                               vcol.dictionary, hi=vhi)
        # SQL: null for groups whose input set is empty after null-key drops
        return Column(
            call.output_type, entry_counts, entry_counts == 0,
            children=[keys_flat, vals_flat],
        )

    _in_spill_pass = False  # reentrancy guard for partitioned passes

    def _maybe_spill_aggregation(self, node: P.AggregationNode, page: Page):
        """Over-budget group-by: hash-partition rows by group key host-side,
        aggregate each partition fully on device, concatenate. Partitions
        hold disjoint group-key sets, so per-partition results are exact
        (reference: SpillableHashAggregationBuilder, host RAM as the tier)."""
        from trino_tpu.exec import memory as mem

        if self._in_spill_pass or not self.spill_enabled:
            return None
        projected = mem.page_bytes(page)
        parts = self.memory.spill_partitions(projected)
        if parts <= 1:
            return None
        self.memory.record_spill(node.id, "aggregation", parts, projected)
        out = None
        self._in_spill_pass = True
        try:
            for part in mem.partition_page_host(page, node.group_channels, parts):
                res = self.aggregate_page(node, part).compact()
                out = res if out is None else Page.concat_pages(out, res)
        finally:
            self._in_spill_pass = False
        return out

    def _exec_aggregate(
        self, call: P.AggregateCall, page, sel, layout, arg_l, sel_l,
        hi_l=None, arg2_l=None, hi2_l=None,
    ):
        """``arg_l``/``sel_l``/``hi_l`` are in layout space (group_structure
        payloads); the DISTINCT path re-groups and takes the original-order
        page column instead. Returns (vals, valid) — or (lo, valid, hi) for
        two-limb long-decimal results."""
        if hi_l is not None and call.function not in ("sum", "count"):
            # no limb kernel for this aggregate: degrade to the low word
            # with a deferred overflow check (the pre-limb contract)
            arg_l = self._narrow_lowered_or_flag(arg_l, hi_l, sel_l)
            hi_l = None
        if hi2_l is not None:
            arg2_l = self._narrow_lowered_or_flag(arg2_l, hi2_l, sel_l)
        if call.function == "approx_percentile":
            if call.distinct:
                raise NotImplementedError(
                    "approx_percentile(DISTINCT): not yet supported")
            from trino_tpu.ops import hll

            vals_l, valid_l = arg_l
            m_l = valid_l if sel_l is None else (
                sel_l if valid_l is None else (sel_l & valid_l))
            return hll.approx_percentile(layout, vals_l, m_l, call.param)
        if call.distinct:
            if call.function not in ("count", "approx_distinct"):
                raise NotImplementedError(f"{call.function}(DISTINCT): not yet supported")
            arg = _col_to_lowered(page.columns[call.arg_channel])
            if call.function == "approx_distinct":
                # real HyperLogLog sketch (reference: airlift HLL via
                # ApproximateCountDistinctAggregation) — m=2048, ~2.3%
                # standard error, at sorted-segment cost (ops/hll.py)
                from trino_tpu.ops import hll

                return hll.approx_distinct(layout, arg, sel)
            return agg_ops.agg_count_distinct(layout, arg, sel)
        sel = sel_l
        if call.function == "count" and call.arg_channel is None:
            return agg_ops.agg_count_star(layout, sel)
        arg = arg_l
        if call.function == "count":
            return agg_ops.agg_count(layout, arg, sel)
        if call.function == "sum":
            vals_l, valid_l = arg
            out_t = call.output_type
            need128 = hi_l is not None
            if (not need128 and isinstance(out_t, T.DecimalType)
                    and out_t.precision > 18):
                # int64 accumulation is exact only when stats bound the
                # total; otherwise take the limb path (correct for the full
                # p38 range instead of silently wrapping)
                src = page.columns[call.arg_channel]
                bound_ok = False
                if src.vrange is not None:
                    b = max(abs(int(src.vrange[0])), abs(int(src.vrange[1])))
                    bound_ok = b * max(layout.n, 1) < 2**62
                need128 = not bound_ok
            if need128:
                (s_hi, s_lo), nonempty = agg_ops.agg_sum_128(
                    layout, vals_l, hi_l, valid_l, sel
                )
                return s_lo, nonempty, s_hi
            return agg_ops.agg_sum(layout, arg, sel, call.output_type.np_dtype)
        if call.function == "avg":
            base = (
                call.output_type.np_dtype
                if call.output_type.is_decimal
                else np.dtype(np.float64)
            )
            s, _ = agg_ops.agg_sum(layout, arg, sel, base)
            cnt, _ = agg_ops.agg_count(layout, arg, sel)
            return agg_ops.finish_avg(s, cnt, call.output_type)
        if call.function == "min":
            return agg_ops.agg_min(layout, arg, sel)
        if call.function == "max":
            return agg_ops.agg_max(layout, arg, sel)
        if call.function in P._VAR_FAMILY:
            t = page.columns[call.arg_channel].type
            return agg_ops.agg_var(
                layout, arg, sel, call.function, t.scale if t.is_decimal else 0
            )
        if call.function in ("bool_and", "bool_or"):
            # boolean min/max (reference: BooleanAndAggregation/BooleanOr)
            vals_l, valid_l = arg
            fn = agg_ops.agg_min if call.function == "bool_and" else agg_ops.agg_max
            v, valid = fn(layout, (vals_l, valid_l), sel)
            return v.astype(bool), valid
        if call.function == "count_if":
            vals_l, valid_l = arg
            m = vals_l if valid_l is None else (vals_l & valid_l)
            return agg_ops.agg_count_star(layout, m if sel is None else m & sel)
        if call.function in ("arbitrary", "any_value"):
            return agg_ops.agg_first(layout, arg, sel)
        if call.function == "geometric_mean":
            vals_l, valid_l = arg
            t = page.columns[call.arg_channel].type
            x = vals_l.astype(jnp.float64)
            if t.is_decimal:
                x = x / (10.0 ** t.scale)
            ln = jnp.log(jnp.maximum(x, 1e-300))  # non-positive -> NaN domain
            ln = jnp.where(x > 0, ln, jnp.nan)
            s, nonempty = agg_ops.agg_sum(layout, (ln, valid_l), sel, np.dtype(np.float64))
            cnt, _ = agg_ops.agg_count(layout, arg, sel)
            v = jnp.exp(s / jnp.maximum(cnt, 1))
            return v, nonempty
        if call.function == "checksum":
            # order-independent 64-bit checksum: sum (mod 2^64) of per-row
            # CONTENT hashes (reference ChecksumAggregation is xor-of-hash;
            # same properties, engine-specific constant). Varchar hashes the
            # UTF-8 string per vocab entry (dictionary codes are ranks and
            # would collide across datasets); floats hash their bit pattern.
            from trino_tpu.parallel.exchange import _mix64 as mix64

            vals_l, valid_l = arg
            src = page.columns[call.arg_channel]
            if src.dictionary is not None:
                import hashlib

                lut = np.array(
                    [
                        int.from_bytes(
                            hashlib.blake2b(v.encode(), digest_size=8).digest(),
                            "little", signed=True)
                        for v in src.dictionary.values
                    ] or [0],
                    dtype=np.int64,
                )
                h = jnp.asarray(lut)[jnp.clip(vals_l, 0, len(lut) - 1)]
            else:
                x = vals_l
                if jnp.issubdtype(x.dtype, jnp.floating):
                    x = jax.lax.bitcast_convert_type(
                        x.astype(jnp.float64), jnp.int64)
                h = mix64(x.astype(jnp.int64).astype(jnp.uint64)).astype(jnp.int64)
            if valid_l is not None:
                h = jnp.where(valid_l, h, jnp.int64(-7046029254386353131))
            v, _ = agg_ops.agg_sum(layout, (h, None), sel, np.dtype(np.int64))
            return v, None
        if call.function in ("min_by", "max_by"):
            return agg_ops.agg_minmax_by(
                layout, arg, arg2_l, sel, call.function == "min_by"
            )
        if call.function in ("corr", "covar_samp", "covar_pop",
                             "regr_slope", "regr_intercept"):
            tx = page.columns[call.arg_channel].type
            ty = page.columns[call.arg2_channel].type
            return agg_ops.agg_bivariate(
                layout, arg, arg2_l, sel, call.function,
                tx.scale if tx.is_decimal else 0,
                ty.scale if ty.is_decimal else 0,
            )
        raise NotImplementedError(call.function)

    # -------------------------------------------------------------- window
    def _exec_WindowNode(self, node: P.WindowNode) -> Page:
        return self.window_over_page(node, self.execute(node.source))

    def window_over_page(self, node: P.WindowNode, page: Page) -> Page:
        from trino_tpu.ops import window as win_ops

        n = page.num_rows
        pkeys = [
            kl for c in node.partition_channels
            for kl in _key_lowereds(page.columns[c])
        ]
        okeys = [
            (kl, asc, nf)
            for c, asc, nf in node.order_channels
            for kl in _key_lowereds(page.columns[c])
        ]
        layout = win_ops.build_layout(pkeys, okeys, page.sel, n)
        out_cols = list(page.columns)
        for call, name in zip(node.calls, node.names):
            arg = (
                _col_to_lowered(
                    self._narrowed_or_flag(page.columns[call.arg_channel],
                                           page.sel))
                if call.arg_channel is not None
                else None
            )
            fn = call.function
            flo, fhi = call.frame_lo, call.frame_hi
            if fn == "row_number":
                v, valid = win_ops.row_number(layout)
            elif fn == "rank":
                v, valid = win_ops.rank(layout)
            elif fn == "dense_rank":
                v, valid = win_ops.dense_rank(layout)
            elif fn == "ntile":
                v, valid = win_ops.ntile(layout, call.offset)
            elif fn == "percent_rank":
                v, valid = win_ops.percent_rank(layout)
            elif fn == "cume_dist":
                v, valid = win_ops.cume_dist(layout)
            elif fn == "sum":
                v, valid = win_ops.agg_sum(
                    layout, arg, call.frame, call.output_type.np_dtype, flo, fhi)
            elif fn == "avg":
                s, s_valid = win_ops.agg_sum(
                    layout, arg, call.frame,
                    call.output_type.np_dtype if call.output_type.is_decimal
                    else np.dtype(np.float64),
                    flo, fhi,
                )
                cnt, _ = win_ops.agg_count(layout, arg, call.frame, flo, fhi)
                v, dvalid = agg_ops.finish_avg(s, cnt, call.output_type)
                valid = s_valid if dvalid is None else (
                    dvalid if s_valid is None else (s_valid & dvalid)
                )
            elif fn in ("count", "count_star"):
                v, valid = win_ops.agg_count(layout, arg, call.frame, flo, fhi)
            elif fn in ("min", "max"):
                v, valid = win_ops.agg_minmax(layout, arg, call.frame, fn == "min")
            elif fn in ("lag", "lead"):
                v, valid = win_ops.shifted_value(layout, arg, call.offset, fn == "lead")
            elif fn == "nth_value":
                v, valid = win_ops.nth_value(
                    layout, arg, call.offset, call.frame, flo, fhi)
            elif fn in ("first_value", "last_value"):
                v, valid = win_ops.edge_value(
                    layout, arg, call.frame, fn == "first_value", flo, fhi)
            else:
                raise NotImplementedError(f"window function {fn}")
            # value-carrying functions keep the source column's dictionary
            dictionary = None
            if fn in ("min", "max", "lag", "lead", "first_value", "last_value",
                      "nth_value"):
                dictionary = page.columns[call.arg_channel].dictionary
            out_cols.append(
                Column(call.output_type, v, None if valid is None else ~valid, dictionary)
            )
        return Page(out_cols, page.sel, page.replicated)

    # -------------------------------------------------------------- joins
    def _exec_JoinNode(self, node: P.JoinNode) -> Page:
        # Build side FIRST (the reference's phased build-before-probe
        # ordering) so its key domains can dynamically narrow probe scans.
        right = self.execute(node.right)
        if self.enable_dynamic_filtering and node.dyn_filter_keys:
            self._collect_dynamic_filters(node, right)
        left = self.execute(node.left)
        return self._dispatch_join(node, left, right)

    def _dispatch_join(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        if node.left_keys and self.eager_tier:
            # eager tier: spill-partition when the working set exceeds the
            # device budget (traced tiers bound memory via capacity hints)
            spilled = self._maybe_spill_join(node, left, right)
            if spilled is not None:
                return spilled
        return self._run_join_kernel(node, left, right)

    def _maybe_spill_join(self, node: P.JoinNode, left: Page, right: Page):
        """Host-offload spill (exec/memory.py): when probe+build exceed the
        device budget, hash-partition BOTH sides by join key host-side and
        run the join as P independent on-device passes (equal keys
        co-locate, so the union of pass outputs is the exact join). The
        reference's partitioned-spill design (HashBuilderOperator FSM +
        GenericPartitioningSpiller) with host RAM as the spill tier."""
        from trino_tpu.exec import memory as mem

        if not self.spill_enabled:
            return None
        projected = mem.page_bytes(left) + mem.page_bytes(right)
        parts = self.memory.spill_partitions(projected)
        if parts <= 1:
            return None
        self.memory.record_spill(node.id, "join", parts, projected)
        lparts = mem.partition_page_host(left, node.left_keys, parts)
        rparts = mem.partition_page_host(right, node.right_keys, parts)
        out = None
        hint_key = f"join:{node.id}"
        for lp, rp in zip(lparts, rparts):
            # per-pass expansion capacity: each pass sizes its own bucket
            self.capacity_hints.pop(hint_key, None)
            res = self._run_join_kernel(node, lp, rp)
            res = res.compact()  # spill the pass result to host-sized rows
            out = res if out is None else Page.concat_pages(out, res)
        self.capacity_hints.pop(hint_key, None)
        return out

    def _run_join_kernel(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        """The single join-kernel dispatch, shared by the direct path and
        the spilled per-partition passes."""
        if node.join_type in ("semi", "anti"):
            if node.filter is not None:
                return self.semi_join_filtered(node, left, right)
            return self.semi_join(node, left, right)
        if not node.left_keys:
            if node.singleton:
                return self.singleton_cross(node, left, right)
            return self.expand_join(node, left, right)  # true cross join
        if node.right_unique:
            return self.lookup_join(node, left, right)
        return self.expand_join(node, left, right)

    DYNAMIC_FILTER_MAX_SET = 1024  # in-set domain cap (reference: the
    # small/large domain-compaction thresholds of DynamicFilterConfig)

    def _collect_dynamic_filters(self, node: P.JoinNode, build: Page) -> None:
        """Extract build-side key domains host-side (one device sync per
        key) for probe scans annotated by the optimizer."""
        from trino_tpu.connector.predicate import Domain

        for i in node.dyn_filter_keys:
            ch = node.right_keys[i]
            col = build.columns[ch]
            if col.type.is_varchar:
                continue  # dictionary codes are page-local, not portable
            vals = np.asarray(col.values)
            live = (
                np.ones(len(vals), bool)
                if build.sel is None
                else np.asarray(build.sel).copy()
            )
            if col.nulls is not None:
                live &= ~np.asarray(col.nulls)
            lv = vals[live]
            if len(lv) == 0:
                dom = Domain(values=frozenset())  # provably empty probe
            elif len(lv) <= self.DYNAMIC_FILTER_MAX_SET:
                dom = Domain.from_values(np.unique(lv).tolist())
            else:
                dom = Domain.range(low=lv.min().item(), high=lv.max().item())
            self.dyn_domains[(node.id, i)] = dom

    def hint_capacity(self, key: str, emit_counts) -> int:
        """Static output capacity for an expansion join or exchange, by hint
        key ("join:<id>" / "xchg*:<id>", see sql/planner/stats.py)."""
        cap = self.capacity_hints.get(key)
        if cap is not None:
            return cap
        if emit_counts is None:  # exchanges have no eager fallback
            raise RuntimeError(
                f"{key} has no capacity hint — estimate_exchange_hints and "
                "the executor's dispatch disagree (sql/planner/stats.py)"
            )
        try:
            total = int(jnp.sum(emit_counts))
        except jax.errors.ConcretizationTypeError:
            raise RuntimeError(
                f"{key} traced without a capacity hint — compiled paths "
                "estimate hints from stats (sql/planner/stats.py)"
            )
        cap = max(16, 1 << (max(total, 1) - 1).bit_length())
        self.capacity_hints[key] = cap
        return cap

    @staticmethod
    def _join_keys_aligned(left: Page, right: Page, left_keys, right_keys):
        """(build_keys, probe_keys) aligned for the join kernels, expanding
        two-limb long-decimal key columns into (hi, lo-flipped) pairs on
        BOTH sides symmetrically (_key_lowereds)."""
        build_keys, probe_keys, bvr, pvr = [], [], [], []
        for lc, rc in zip(left_keys, right_keys):
            bc, pc = right.columns[rc], left.columns[lc]
            if bc.hi is not None or pc.hi is not None:
                # symmetric two-limb expansion on BOTH sides (_key_lowereds)
                build_keys.extend(_key_lowereds(bc, force_two_limb=True))
                probe_keys.extend(_key_lowereds(pc, force_two_limb=True))
                bvr.extend([None, None])
                pvr.extend([None, None])
            else:
                build_keys.append(_col_to_lowered(bc))
                probe_keys.append(_col_to_lowered(pc))
                bvr.append(bc.vrange)
                pvr.append(pc.vrange)
        return join_ops.align_join_keys(build_keys, probe_keys, bvr, pvr)

    def _expansion_keys(self, node: P.JoinNode, left: Page, right: Page):
        if node.left_keys:
            return self._join_keys_aligned(
                left, right, node.left_keys, node.right_keys
            )
        # cross join: everything matches everything (constant key)
        build_keys = [(jnp.zeros((right.num_rows,), jnp.int32), None)]
        probe_keys = [(jnp.zeros((left.num_rows,), jnp.int32), None)]
        return build_keys, probe_keys


    @staticmethod
    def _gather_right_cols(right_cols, rows, mask) -> List[Column]:
        """Gather build-side payload columns by matched row ids, carrying
        two-limb hi limbs as extra gather operands."""
        lows = []
        for rc in right_cols:
            if rc.type.is_nested:
                raise NotImplementedError("array/map columns through join payloads")
            lows.append(_col_to_lowered(rc))
        hi_map = {}
        for i, rc in enumerate(right_cols):
            if rc.hi is not None:
                hi_map[i] = len(lows)
                lows.append((rc.hi, None))
        g = join_ops.gather_columns(lows, rows, mask)
        out = []
        for i, rc in enumerate(right_cols):
            v, valid = g[i]
            hi = g[hi_map[i]][0] if i in hi_map else None
            out.append(
                Column(
                    rc.type, v, ~valid if valid is not None else None,
                    rc.dictionary, rc.vrange if hi is None else None, hi=hi,
                )
            )
        return out

    @staticmethod
    def _build_presorted(page: Page, key_channels) -> bool:
        """True when the build page's single join key is ascending,
        null-free, and dead rows form a tail — build_side skips its sort."""
        if len(key_channels) != 1:
            return False
        col = page.columns[key_channels[0]]
        if not col.ascending or col.nulls is not None:
            return False
        return page.sel is None or page.live_prefix

    def expand_join(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        """General M:N inner/left join: count matches per probe row, then
        gather into a static-capacity probe-major output (ops/join.py
        probe_counts + expand; reference JoinHash position-links chains)."""
        build_keys, probe_keys = self._expansion_keys(node, left, right)
        build = join_ops.build_side(
            build_keys, right.sel,
            presorted=node.left_keys and self._build_presorted(right, node.right_keys))
        lo, counts = join_ops.probe_counts(build, probe_keys, left.sel)
        n = left.num_rows
        outer = node.join_type == "left"
        probe_live = (
            left.sel if left.sel is not None else jnp.ones((n,), dtype=bool)
        )
        plain_outer = outer and node.filter is None
        emit = jnp.where(probe_live, jnp.maximum(counts, 1), 0) if plain_outer else counts
        capacity = self.hint_capacity(f"join:{node.id}", emit)
        p, k, live, total = join_ops.expand(emit, capacity)
        self.errors.append((f"CAPACITY_EXCEEDED:join:{node.id}", total > capacity))
        # ONE batched random gather at p for lo/counts and every left column
        # (separate computed-index gathers don't fuse: ~40 ms each per 6M
        # rows on v5e — see ranks.batched_gather)
        left_arrays = [lo, counts]
        for c in left.columns:
            if c.type.is_nested:
                raise NotImplementedError("array/map columns through join payloads")
            left_arrays.append(c.values)
            if c.nulls is not None:
                left_arrays.append(c.nulls)
            if c.hi is not None:
                left_arrays.append(c.hi)
        g = ranks_ops.batched_gather(left_arrays, p)
        lo_p, counts_p = g[0], g[1]
        matched = live & (k < counts_p)
        b_idx = jnp.clip(lo_p + k, 0, build.n - 1)
        rows = build.rows[b_idx]
        out_cols = []
        gi = 2
        for c in left.columns:
            v = g[gi]
            gi += 1
            nulls = None
            if c.nulls is not None:
                nulls = g[gi]
                gi += 1
            chi = None
            if c.hi is not None:
                chi = g[gi]
                gi += 1
            out_cols.append(
                Column(c.type, v, nulls, c.dictionary,
                       c.vrange if chi is None else None, hi=chi))
        out_cols.extend(self._gather_right_cols(right.columns, rows, matched))
        page = Page(out_cols, live, left.replicated and right.replicated)
        if node.filter is None:
            return page
        lv = self._lower(node.filter, page)
        passed = lv.vals if lv.valid is None else (lv.vals & lv.valid)
        if not outer:
            return Page(out_cols, live & passed, page.replicated)
        # left join with filter: expanded rows that pass, plus one null-build
        # row for each probe row with no passing match
        passing = live & matched & passed
        # p is probe-major (non-decreasing) — monotonic segment sum, no scatter
        any_pass = (
            seg.monotonic_segment_sum(passing.astype(jnp.int32), p, n) > 0
        )
        tail_sel = probe_live & ~any_pass
        tail_cols = []
        for c in left.columns:
            tail_cols.append(c)
        for rc in right.columns:
            tail_cols.append(
                Column(
                    rc.type,
                    jnp.zeros((n,), dtype=rc.values.dtype),
                    jnp.ones((n,), dtype=bool),
                    rc.dictionary,
                )
            )
        head = Page(out_cols, passing, page.replicated)
        tail = Page(tail_cols, tail_sel, page.replicated)
        return Page.concat_pages(head, tail)

    def semi_join_filtered(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        """Semi/anti join with a residual filter (correlated EXISTS with
        non-equality predicates): expand the matches, evaluate the filter,
        then reduce any-passing back to the probe rows."""
        build_keys, probe_keys = self._expansion_keys(node, left, right)
        build = join_ops.build_side(
            build_keys, right.sel,
            presorted=node.left_keys and self._build_presorted(right, node.right_keys))
        lo, counts = join_ops.probe_counts(build, probe_keys, left.sel)
        n = left.num_rows
        capacity = self.hint_capacity(f"join:{node.id}", counts)
        p, k, live, total = join_ops.expand(counts, capacity)
        self.errors.append((f"CAPACITY_EXCEEDED:join:{node.id}", total > capacity))
        left_arrays = [lo]
        for c in left.columns:
            if c.type.is_nested:
                raise NotImplementedError("array/map columns through join payloads")
            left_arrays.append(c.values)
            if c.nulls is not None:
                left_arrays.append(c.nulls)
            if c.hi is not None:
                left_arrays.append(c.hi)
        g = ranks_ops.batched_gather(left_arrays, p)
        b_idx = jnp.clip(g[0] + k, 0, build.n - 1)
        rows = build.rows[b_idx]
        exp_cols = []
        gi = 1
        for c in left.columns:
            v = g[gi]
            gi += 1
            nulls = None
            if c.nulls is not None:
                nulls = g[gi]
                gi += 1
            chi = None
            if c.hi is not None:
                chi = g[gi]
                gi += 1
            exp_cols.append(
                Column(c.type, v, nulls, c.dictionary,
                       c.vrange if chi is None else None, hi=chi))
        exp_cols.extend(self._gather_right_cols(right.columns, rows, live))
        exp_page = Page(exp_cols, live, left.replicated and right.replicated)
        lv = self._lower(node.filter, exp_page)
        passed = lv.vals if lv.valid is None else (lv.vals & lv.valid)
        hit = (
            seg.monotonic_segment_sum((live & passed).astype(jnp.int32), p, n) > 0
        )
        keep = hit if node.join_type == "semi" else ~hit
        sel = keep if left.sel is None else left.sel & keep
        return Page(left.columns, sel, left.replicated)

    def _dense_join_cols(self, node: P.JoinNode, left: Page, right: Page):
        """(build_col, probe_col, lo, span) when the single-int-key dense
        direct-address kernel applies (ops/join.py dense_span), else None.
        Varchar (page-local dictionary codes) and two-limb decimals stay on
        the sort path."""
        if len(node.right_keys) != 1:
            return None
        bc = right.columns[node.right_keys[0]]
        pc = left.columns[node.left_keys[0]]
        if bc.hi is not None or pc.hi is not None:
            return None
        if bc.type.is_varchar or pc.type.is_varchar:
            return None
        if not (jnp.issubdtype(bc.values.dtype, jnp.integer)
                and jnp.issubdtype(pc.values.dtype, jnp.integer)):
            return None
        ds = join_ops.dense_span(bc.vrange, right.num_rows)
        if ds is None:
            return None
        return bc, pc, ds[0], ds[1]

    # ------------------------------------------------------ fused join tier
    def _fused_join_enabled(self) -> bool:
        props = getattr(self.session, "properties", None) or {}
        return bool(props.get("fused_join_enabled", True))

    def _pallas_merge_mode(self) -> Optional[bool]:
        """None = don't use the Pallas merge kernel; False = compiled mode
        (real TPU); True = interpret mode (CPU test meshes). The kernel is
        OPT-IN (property explicitly true): unset keeps the XLA rank merge
        until a hardware bench round validates the Mosaic compile —
        microbench/join_kernels.py carries the kernel case on TPU."""
        props = getattr(self.session, "properties", None) or {}
        v = props.get("fused_join_pallas")
        if not v:
            return None
        from trino_tpu.ops import merge_pallas

        if not merge_pallas.pallas_available():
            return None  # no pallas on this jax install: XLA fallback
        try:
            return jax.default_backend() != "tpu"
        except Exception:  # noqa: BLE001 — no backend yet
            return True

    def _merge_sentinel_safe(self, node: P.JoinNode, left: Page, right: Page,
                             build_keys) -> bool:
        """The FULL Pallas merge contract: a single int32 key (the
        kernel's only lane dtype) whose PROVEN value range keeps the
        dtype's max (the dead-row sentinel and the kernel's pad value)
        unreachable by any live key. Checking the whole contract here
        keeps the ``merge-pallas`` selection metric truthful — the
        kernel's own guard would otherwise degrade silently to XLA after
        the tier was already counted."""
        if len(node.right_keys) != 1 or len(build_keys) != 1:
            return False
        bc = right.columns[node.right_keys[0]]
        pc = left.columns[node.left_keys[0]]
        if bc.hi is not None or pc.hi is not None:
            return False
        if bc.type.is_varchar or pc.type.is_varchar:
            return False
        dt = build_keys[0][0].dtype
        if dt != jnp.int32:
            return False
        return (bc.vrange is not None and pc.vrange is not None
                and max(int(bc.vrange[1]), int(pc.vrange[1]))
                < jnp.iinfo(dt).max)

    def _cached_sorted_build(self, node: P.JoinNode, right: Page, build_keys):
        """SortedBuild served by the device build cache, or None. Eager
        tier only (traced tiers sort in-program — their artifact is the
        compiled executable itself); the build side must be a bare
        versioned TableScanNode so the artifact's identity is provable
        from the scan signature + join-key signature."""
        if not self.eager_tier:
            return None
        scan = node.right
        if not isinstance(scan, P.TableScanNode):
            return None
        from trino_tpu import devcache

        constraint = scan_constraint_with(scan, self.dyn_domains)
        dtypes = ",".join(str(v.dtype) for v, _ in build_keys)

        def load():
            build = join_ops.build_side(build_keys, right.sel)
            arrays = list(build.cols) + [build.rows, build.live]
            nbytes = sum(int(a.size) * a.dtype.itemsize for a in arrays)
            return build, int(build.n), nbytes, 0

        built, _disposition = devcache.cached_build(
            self.session, scan, constraint,
            self._host_applied_domains(scan), tuple(node.right_keys),
            dtypes, load)
        return built

    def _merge_sorted_tier(self, node: P.JoinNode, left: Page, right: Page,
                           build, build_keys, probe_keys, record: bool = True):
        """(rows, matched) by merging probes against an already-sorted
        build — the Pallas tiled merge when its contract holds, the XLA
        rank merge otherwise. ``record=False`` skips the selection metric
        (the overlapped exchange calls this once per send block but the
        selection is one join)."""
        pallas_interp = self._pallas_merge_mode()
        use_pallas = (pallas_interp is not None
                      and self._merge_sentinel_safe(node, left, right,
                                                    build_keys))
        if record:
            M.FUSED_JOIN_SELECTIONS.inc(
                1, "merge-pallas" if use_pallas else "merge-sorted")
        return fused_ops.merge_sorted_build(
            build, probe_keys,
            use_pallas=use_pallas,
            pallas_block_build=self.capacity_hints.get(
                f"jtile:{node.id}", 2048),
            pallas_interpret=bool(pallas_interp),
        )

    def _sortmerge_probe(self, node: P.JoinNode, left: Page, right: Page):
        """(build_row_idx, matched) for the N:1 lookup join when the dense
        direct-address table does not apply: the fused sort-merge tier
        (ops/fused_join.py — one combined sort, no SortedBuild
        intermediate) behind the cost gate, with two special build-side
        shapes routed to the merge tier instead (a presorted key skips all
        build work; a device-cached sorted build skips the build sort on
        every warm join); legacy build_side + probe_unique when the tier
        is disabled."""
        build_keys, probe_keys = self._join_keys_aligned(
            left, right, node.left_keys, node.right_keys
        )
        presorted = self._build_presorted(right, node.right_keys)
        if self._fused_join_enabled():
            cached = None if presorted else self._cached_sorted_build(
                node, right, build_keys)
            if presorted or cached is not None:
                build = cached if cached is not None else join_ops.build_side(
                    build_keys, right.sel, presorted=True)
                return self._merge_sorted_tier(
                    node, left, right, build, build_keys, probe_keys)
            M.FUSED_JOIN_SELECTIONS.inc(1, "fused")
            return fused_ops.fused_probe_unique(
                build_keys, right.sel, probe_keys)
        M.FUSED_JOIN_SELECTIONS.inc(1, "legacy")
        build = join_ops.build_side(build_keys, right.sel, presorted=presorted)
        return join_ops.probe_unique(build, probe_keys)

    def lookup_join(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        dense = self._dense_join_cols(node, left, right)
        if dense is not None:
            # cost gate: dense-keyed builds keep the direct-address fast
            # path (KERNELS_r05: one scatter + one bounded gather beats
            # any sort formulation when the key range is dense)
            M.FUSED_JOIN_SELECTIONS.inc(1, "dense")
            bc, pc, lo, span = dense
            table = join_ops.dense_unique_table(
                _col_to_lowered(bc), right.sel, lo, span)
            rows, matched = join_ops.dense_probe_unique(
                table, _col_to_lowered(pc), lo)
        else:
            rows, matched = self._sortmerge_probe(node, left, right)
        return self._assemble_lookup_output(node, left, right, rows, matched)

    def _assemble_lookup_output(self, node: P.JoinNode, left: Page,
                                right: Page, rows, matched) -> Page:
        """Projection half of the lookup join: gather build payloads at the
        matched rows and apply join-type/filter semantics. ROW-LOCAL in the
        probe (each output row depends only on its probe row and the whole
        build) — the property the overlapped SPMD exchange relies on to
        consume probe blocks independently (parallel/spmd.py)."""
        out_cols = list(left.columns)
        out_cols.extend(self._gather_right_cols(right.columns, rows, matched))
        if node.join_type == "inner":
            sel = matched if left.sel is None else (left.sel & matched)
        else:  # left outer: probe rows always survive; build cols null when unmatched
            sel = left.sel
        page = Page(out_cols, sel, left.replicated)
        if node.filter is not None:
            lv = self._lower(node.filter, page)
            passed = lv.vals if lv.valid is None else (lv.vals & lv.valid)
            if node.join_type == "left":
                # probe rows survive; a failing filter just voids the match
                keep_match = matched & passed
                new_cols = list(left.columns)
                for rc, oc in zip(right.columns, out_cols[len(left.columns):]):
                    nulls = ~keep_match if oc.nulls is None else (oc.nulls | ~keep_match)
                    new_cols.append(Column(oc.type, oc.values, nulls, oc.dictionary))
                return Page(new_cols, left.sel, left.replicated)
            page = Page(out_cols, passed if page.sel is None else page.sel & passed, left.replicated)
        return page

    def semi_join(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        dense = self._dense_join_cols(node, left, right)
        if dense is not None:
            M.FUSED_JOIN_SELECTIONS.inc(1, "dense")
            bc, pc, lo, span = dense
            hit = join_ops.dense_membership(
                _col_to_lowered(bc), right.sel, _col_to_lowered(pc), lo, span)
            keep = hit if node.join_type == "semi" else ~hit
            sel = keep if left.sel is None else left.sel & keep
            return Page(left.columns, sel, left.replicated)
        build_keys, probe_keys = self._join_keys_aligned(
            left, right, node.left_keys, node.right_keys
        )
        presorted = self._build_presorted(right, node.right_keys)
        if self._fused_join_enabled():
            # same tier gate as the lookup join: presorted/device-cached
            # sorted builds take the merge tier, everything else fuses
            # build+probe into one combined sort (duplicates on the build
            # side are fine for membership — any live equal row flags)
            cached = None if presorted else self._cached_sorted_build(
                node, right, build_keys)
            if presorted or cached is not None:
                build = cached if cached is not None else join_ops.build_side(
                    build_keys, right.sel, presorted=True)
                _rows, hit = self._merge_sorted_tier(
                    node, left, right, build, build_keys, probe_keys)
            else:
                M.FUSED_JOIN_SELECTIONS.inc(1, "fused")
                hit = fused_ops.fused_membership(
                    build_keys, right.sel, probe_keys)
        else:
            M.FUSED_JOIN_SELECTIONS.inc(1, "legacy")
            hit = join_ops.membership(
                build_keys, right.sel, probe_keys, presorted=presorted)
        keep = hit if node.join_type == "semi" else ~hit
        sel = keep if left.sel is None else left.sel & keep
        return Page(left.columns, sel, left.replicated)

    def singleton_cross(self, node: P.JoinNode, left: Page, right: Page) -> Page:
        """Cross join against a single-row relation (scalar subquery)."""
        r_sel = right.sel
        nr = right.num_rows
        if r_sel is None:
            live = jnp.asarray(nr, dtype=jnp.int64)
            idx = 0
        else:
            live = jnp.sum(r_sel)
            idx = jnp.argmax(r_sel)
        self.errors.append(("SCALAR_SUBQUERY_MULTIPLE_ROWS", live > 1))
        self.errors.append(("SCALAR_SUBQUERY_NO_ROWS", live < 1))
        n = left.num_rows
        out_cols = list(left.columns)
        for rc in right.columns:
            v = jnp.broadcast_to(rc.values[idx], (n,))
            nulls = (
                jnp.broadcast_to(rc.nulls[idx], (n,)) if rc.nulls is not None else None
            )
            out_cols.append(Column(rc.type, v, nulls, rc.dictionary, rc.vrange))
        page = Page(out_cols, left.sel, left.replicated)
        if node.filter is not None:
            lv = self._lower(node.filter, page)
            passed = lv.vals if lv.valid is None else lv.vals & lv.valid
            page = Page(out_cols, passed if page.sel is None else page.sel & passed, left.replicated)
        return page

    # ----------------------------------------------------- pattern matching
    def _exec_MatchRecognizeNode(self, node: "P.MatchRecognizeNode") -> Page:
        """MATCH_RECOGNIZE (reference: PatternRecognitionOperator): host
        tier only — the backtracking matcher is sequential by nature (see
        exec/match_recognize.py). Traced tiers route queries containing it
        through the gathered coordinator fragment."""
        if not self.eager_tier:
            raise NotImplementedError(
                "MATCH_RECOGNIZE executes on the host tier")
        from trino_tpu.exec.match_recognize import run_match_recognize

        page = self.execute(node.source)
        names = node.input_names or node.source.output_names
        # case-insensitive resolution, matching the analyzer's (plan-time
        # validation lowercases identifiers)
        lnames = [n.lower() for n in names]
        pyrows = [dict(zip(lnames, r)) for r in page.to_pylist()]
        part_names = [lnames[c] for c in node.partition_channels]
        parts: Dict[tuple, List[dict]] = {}
        for r in pyrows:
            parts.setdefault(tuple(r[n] for n in part_names), []).append(r)

        class _K:
            """Total-order sort key with SQL null placement (nulls last
            ascending, first descending — the engine's default)."""

            __slots__ = ("v", "asc")

            def __init__(self, v, asc):
                self.v, self.asc = v, asc

            def __lt__(self, other):
                a, b = self.v, other.v
                if a is None or b is None:
                    if a is None and b is None:
                        return False
                    return (a is None) != self.asc  # None last when asc
                return (a < b) if self.asc else (b < a)

            def __eq__(self, other):
                # tuple comparison consults secondary keys only when
                # earlier keys compare EQUAL — identity-based equality
                # would freeze ties in input order
                return self.v == other.v

        sort_cols = [(lnames[c], asc) for c, asc, _n in node.sort_channels]

        def order_key(row):
            return tuple(_K(row[n], asc) for n, asc in sort_cols)

        out_rows: List[tuple] = []
        for key in sorted(parts, key=lambda k: tuple(map(repr, k))):
            for mvals in run_match_recognize(
                    parts[key], order_key, list(node.pattern),
                    list(node.defines), list(node.measures),
                    node.after_match):
                out_rows.append(key + mvals)
        if not out_rows:
            # zero-length arrays break downstream gathers: the no-match
            # result is the canonical 1-slot all-dead page
            return Page.all_dead(node.output_types)
        cols = []
        for i, (t, _n) in enumerate(zip(node.output_types, node.output_names)):
            cols.append(Column.from_python(t, [r[i] for r in out_rows]))
        return Page(cols)

    # ------------------------------------------------------------- ordering
    def _exec_SortNode(self, node: P.SortNode) -> Page:
        page = self.execute(node.source)
        return self.sorted_page(page, node.sort_channels)

    def sorted_page(self, page: Page, sort_channels, limit: Optional[int] = None) -> Page:
        """Move rows into sort order (dead rows last); sel becomes a prefix
        mask of the live (and limit-capped) rows. All columns ride the ONE
        payload-carrying sort (sort_ops.sort_payloads) — never a computed-
        permutation gather per column."""
        n = page.num_rows
        if any(c.type.is_nested for c in page.columns):
            # nested columns cannot ride a device payload sort (children
            # re-flatten with data-dependent shapes); sort host-side — this
            # path serves root-level ORDER BY over array_agg/unnest results
            return self._sorted_page_host(page, sort_channels, limit)
        keys = [
            (kl, asc, nf)
            for c, asc, nf in sort_channels
            for kl in _key_lowereds(page.columns[c])
        ]
        payloads = []
        for c in page.columns:
            payloads.append(c.values)
            if c.nulls is not None:
                payloads.append(c.nulls)
            if c.hi is not None:
                payloads.append(c.hi)
        sorted_arrays = sort_ops.sort_payloads(keys, page.sel, payloads)
        live = (
            jnp.asarray(n, dtype=jnp.int64) if page.sel is None else jnp.sum(page.sel)
        )
        if limit is not None:
            live = jnp.minimum(live, limit)
        sel = jnp.arange(n) < live
        cols = []
        i = 0
        for c in page.columns:
            v = sorted_arrays[i]
            i += 1
            nulls = None
            if c.nulls is not None:
                nulls = sorted_arrays[i]
                i += 1
            chi = None
            if c.hi is not None:
                chi = sorted_arrays[i]
                i += 1
            cols.append(Column(c.type, v, nulls, c.dictionary,
                               c.vrange if chi is None else None, hi=chi))
        return Page(cols, sel, page.replicated)

    def _sorted_page_host(self, page: Page, sort_channels, limit=None) -> Page:
        """Host (numpy) ORDER BY for pages carrying nested columns: compact,
        lexsort with SQL null placement (ops/sort.py _sort_key semantics),
        host_take the permutation (which re-flattens children correctly)."""
        from trino_tpu.data.page import host_take

        compacted = page.compact()
        n = compacted.num_rows
        lex_keys = []  # least-significant first for np.lexsort
        for c, asc, nf in reversed(list(sort_channels)):
            col = compacted.columns[c]
            if col.type.is_nested:
                raise NotImplementedError("ORDER BY an array/map column")
            v = np.asarray(col.values)
            if v.dtype == np.bool_:
                v = v.astype(np.int8)
            if not asc:
                v = -v if np.issubdtype(v.dtype, np.floating) else ~v
            nulls_first = (not asc) if nf is None else nf
            if col.nulls is not None:
                isnull = np.asarray(col.nulls)
                rank = (~isnull).astype(np.int8) if nulls_first else isnull.astype(np.int8)
                lex_keys.append(np.where(isnull, np.zeros((), v.dtype), v))
                lex_keys.append(rank)
            else:
                lex_keys.append(v)
        order = (
            np.lexsort(lex_keys) if lex_keys else np.arange(n)
        )
        if limit is not None:
            order = order[:limit]
        return Page([host_take(c, order) for c in compacted.columns], None,
                    page.replicated)

    def _exec_TopNNode(self, node: P.TopNNode) -> Page:
        page = self.execute(node.source)
        return self.sorted_page(page, node.sort_channels, limit=node.count)

    def _exec_LimitNode(self, node: P.LimitNode) -> Page:
        page = self.execute(node.source)
        return self.sorted_page(page, [], limit=node.count)

    def _exec_OutputNode(self, node: P.OutputNode) -> Page:
        return self.execute(node.source)


@dataclasses.dataclass
class QueryResult:
    column_names: List[str]
    columns: List[Column]
    rows: List[tuple]

    def __repr__(self):
        return f"QueryResult({self.column_names}, {len(self.rows)} rows)"
