"""Fused sort–merge join: one compiled region from keys to match spans.

The legacy pipeline (ops/join.py) materializes a ``SortedBuild`` between
phases: sort the build side (nb rows), THEN rank the probes against it
with a combined sort of build+probe (N = nb + np rows, ops/ranks.py), THEN
return ranks to probe order through a second N-row payload sort, THEN
gather ``build.rows`` at the matched rank (one more np-row random pass).
KERNELS_r05 measured the result: 0.156 GB/s on the probe=16M/build=4M
lookup — every phase re-touches the full working set.

The fused formulation here sorts build and probe keys TOGETHER and emits
the matched build row directly into the projection gather:

1. ONE combined stable sort of the raw aligned key columns over N rows,
   builds concatenated first (equal keys keep builds before probes — no
   tag operand), payload = combined row index. Dead/null build rows ride
   along UNMASKED and inert: they are simply never encoded as candidates
   in step 2, so the sentinel masking, dtype widening, and dead-flag
   column of ``build_side`` all disappear.
2. In sorted space, the matching build row propagates to every probe slot
   of its equal-key run by ONE streaming pass: encode
   ``run_id * (nb + 1) + (build_row + 1)`` at live-build slots (0
   elsewhere) and take a running max (``lax.cummax``). A probe slot
   decodes a match iff the running max carries its own run_id — the
   within-run reset costs no segmented scan.
3. Matched build rows return to probe order by ONE np-row scatter through
   the sort permutation (the permutation's probe slots are unique, so the
   scatter is ``unique_indices`` at the measured ~7 ns/element
   random-access floor) — cheaper than the legacy second N-row sort
   whenever np is not much larger than the sort's row budget, and N never
   re-enters the pipeline after step 2.

Total: one N-row sort + two streaming prefixes + one np scatter, versus
sort(nb) + sort(N) + sort(N) + gather(np). The build-side sort is gone
and N is touched once — on the 16M/4M case that is the measured >=2x.

When the build side is ALREADY sorted (ops/join.py ``SortedBuild`` from
the device build cache or a presorted column), the combined sort shrinks
to the probe side and the rank step runs as a tiled two-pointer merge —
optionally the Pallas kernel in ops/merge_pallas.py (see
``merge_sorted_build``), where XLA has no fusion story at all.

Scope: the fused tier serves the N:1 lookup join and semi/anti
membership — the kernels under TPC-H q3/q18's 300x gap. M:N expansion
joins keep the legacy two-pass count+emit (their output capacity
machinery needs probe-order counts anyway; see the tier table in
README "Join kernels").
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]


# liveness/null-match semantics are SHARED with the legacy kernels — one
# definition, so the fused tier can never silently diverge from the
# pipeline it must stay bit-compatible with
from trino_tpu.ops.join import _live_mask as _build_live  # noqa: E402
from trino_tpu.ops.join import probe_valid as _probe_valid  # noqa: E402


def _as_key(v: jnp.ndarray) -> jnp.ndarray:
    return v.astype(jnp.int8) if v.dtype == jnp.bool_ else v


def fused_match_rows(
    build_keys: List[Lowered],
    build_sel: Optional[jnp.ndarray],
    probe_keys: List[Lowered],
) -> jnp.ndarray:
    """Per probe row (original order): the ORIGINAL build row index of a
    live equal-key build row, or -1 when none exists. Duplicate build keys
    resolve to the last live duplicate in sorted order (the caller proves
    uniqueness for N:1 joins; membership only needs "any").

    This is the whole fused region: callers derive ``(rows, matched)``
    as ``(clip(m, 0), m >= 0)`` and feed ``rows`` straight into the
    projection gather.
    """
    nb = build_keys[0][0].shape[0]
    np_ = probe_keys[0][0].shape[0]
    if np_ == 0:
        return jnp.zeros((0,), jnp.int32)
    if nb == 0:
        return jnp.full((np_,), -1, jnp.int32)
    n = nb + np_
    operands = []
    for (bv, _), (pv, _) in zip(build_keys, probe_keys):
        bv, pv = _as_key(bv), _as_key(pv)
        dt = jnp.promote_types(bv.dtype, pv.dtype)
        operands.append(jnp.concatenate([bv.astype(dt), pv.astype(dt)]))
    idx = jnp.arange(n, dtype=jnp.int32)
    # liveness rides the sort as a payload operand (streaming bytes) — a
    # post-sort live_b[idx_s] gather would re-touch N rows at the ~7 ns
    # random-access floor, the exact cost this kernel exists to avoid
    live_b = _build_live(build_keys, build_sel)
    live_concat = jnp.concatenate([live_b, jnp.ones((np_,), bool)])
    out = jax.lax.sort(
        tuple(operands) + (idx, live_concat),
        num_keys=len(operands), is_stable=True,
    )
    sorted_cols, idx_s, live_s = out[:-2], out[-2], out[-1]
    is_build = idx_s < nb
    # equal-key run boundaries (any key column differs from the previous)
    neq = jnp.zeros((n - 1,), bool)
    for c in sorted_cols:
        neq = neq | (c[1:] != c[:-1])
    run_start = jnp.concatenate([jnp.ones((1,), bool), neq])
    run_id = jnp.cumsum(run_start.astype(jnp.int32))
    # candidate encoding at LIVE build slots only: dead/null builds never
    # match, so they need no masking anywhere upstream
    cand_live = is_build & live_s
    stride = jnp.int64(nb + 1)
    enc = run_id.astype(jnp.int64) * stride + jnp.where(
        cand_live, idx_s.astype(jnp.int64) + 1, jnp.int64(0)
    )
    m = jax.lax.cummax(enc)
    has_build = (m // stride) == run_id.astype(jnp.int64)
    brow_sorted = jnp.where(
        has_build & (m % stride > 0), (m % stride - 1).astype(jnp.int32),
        jnp.int32(-1),
    )
    # back to probe order: scatter through the sort permutation's probe
    # slots (unique by construction); build slots map to DISTINCT
    # out-of-bounds slots (np_ + idx_s) and drop, so ``unique_indices``
    # stays truthful — duplicated OOB indices are documented UB (same
    # convention as dense_unique_table's span + iota)
    probe_pos = jnp.where(is_build, jnp.int32(np_) + idx_s,
                          idx_s - jnp.int32(nb))
    return (
        jnp.full((np_,), -1, jnp.int32)
        .at[probe_pos]
        .set(brow_sorted, mode="drop", unique_indices=True)
    )


def fused_probe_unique(
    build_keys: List[Lowered],
    build_sel: Optional[jnp.ndarray],
    probe_keys: List[Lowered],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused analog of ``build_side`` + ``probe_unique``: (build_row_idx,
    matched) in probe order, no SortedBuild ever materialized."""
    m = fused_match_rows(build_keys, build_sel, probe_keys)
    matched = m >= 0
    pvalid = _probe_valid(probe_keys)
    if pvalid is not None:
        matched = matched & pvalid
    return jnp.maximum(m, 0), matched


def fused_membership(
    build_keys: List[Lowered],
    build_sel: Optional[jnp.ndarray],
    probe_keys: List[Lowered],
) -> jnp.ndarray:
    """Fused analog of ``membership`` (semi/anti join): build duplicates
    are fine — any live equal-key build row flags the probe."""
    _, matched = fused_probe_unique(build_keys, build_sel, probe_keys)
    return matched


# ------------------------------------------------- pre-sorted build merge
def merge_sorted_build(
    build,  # ops/join.py SortedBuild
    probe_keys: List[Lowered],
    *,
    use_pallas: bool = False,
    pallas_block_build: int = 2048,
    pallas_interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(build_row_idx, matched) against an ALREADY-SORTED build (an
    ops/join.py ``SortedBuild`` — e.g. served warm by the device build
    cache, or a presorted key column whose sort was skipped).

    Only the probe side is unsorted work; the rank step is the tiled
    two-pointer merge. With ``use_pallas`` the merge runs as the Pallas
    kernel in ops/merge_pallas.py: sorted probe blocks stream against
    DMA'd build windows entirely in VMEM, an access pattern XLA cannot
    recover from a searchsorted-style lowering. PRECONDITION for
    ``use_pallas``: the caller has PROVEN the dead-row sentinel
    unreachable from the key's value range (executor
    ``_merge_sentinel_safe``) — the kernel cannot tell a sentinel-masked
    dead row from a live key equal to it. A hard shape/dtype guard
    (single int32 key) still degrades silently to the XLA fallback: the
    same merge expressed as ranks over the combined sort (ops/ranks.py).
    """
    from trino_tpu.ops import join as join_ops
    from trino_tpu.ops import ranks

    nb = build.n
    np_ = probe_keys[0][0].shape[0]
    if np_ == 0 or nb == 0:
        z = jnp.zeros((np_,), jnp.int32)
        return z, jnp.zeros((np_,), bool)
    pcols = join_ops._probe_cols(build, probe_keys)
    # one np-row gather serves both the row id and the live guard: dead
    # build slots pre-encode as -1 (streaming elementwise pass over nb)
    rows_live = jnp.where(build.live, build.rows.astype(jnp.int32),
                          jnp.int32(-1))
    if (
        use_pallas
        and build.single
        and len(pcols) == 1
        and pcols[0].dtype == jnp.int32
        and build.cols[0].dtype == jnp.int32
    ):
        from trino_tpu.ops import merge_pallas

        # NULL probe slots carry RAW physical values the vrange proof does
        # not bound — mask them in-range (0) so no slot can equal the
        # kernel's INT32_MAX pad (an equal slot would drag its block's
        # covering window into the pad tail); their matches are voided by
        # the pvalid mask below either way
        pv = _probe_valid(probe_keys)
        pkey = pcols[0] if pv is None else jnp.where(pv, pcols[0], 0)
        perm = ranks.argsort32(pkey)
        p_sorted = pkey[perm]
        pos = merge_pallas.merge_unique_sorted(
            build.cols[0], p_sorted, block_build=pallas_block_build,
            interpret=pallas_interpret,
        )
        # back to probe order through the probe permutation (np scatter)
        pos_o = (
            jnp.zeros((np_,), jnp.int32)
            .at[perm]
            .set(pos, mode="drop", unique_indices=True)
        )
        rl = rows_live[jnp.clip(pos_o, 0, nb - 1)]
        matched = (pos_o >= 0) & (rl >= 0)
        rows = jnp.maximum(rl, 0)
    else:
        lo, counts = ranks.sorted_ranks(build.cols, pcols)
        rl = rows_live[jnp.clip(lo, 0, nb - 1)]
        matched = (counts > 0) & (rl >= 0)
        rows = jnp.maximum(rl, 0)
    pvalid = _probe_valid(probe_keys)
    if pvalid is not None:
        matched = matched & pvalid
    return rows, matched
