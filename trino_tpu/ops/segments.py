"""Scatter-free segment reductions: the TPU group-by/aggregate substrate.

Reference role: ``operator/FlatHash.java`` + ``AccumulatorCompiler`` — the
grouped-accumulation inner loop. On TPU, scatter (``jax.ops.segment_*``)
compiles to serialized HBM read-modify-write and is ~50x slower than the
streaming alternatives (measured on v5e: 6M-row int64 segment_sum = 513 ms vs
9.5 ms for masked reductions). So grouped aggregation here never scatters
integers; it uses one of two layouts:

- **direct** (the BigintGroupByHash analog): group keys are small perfect
  indices (dictionary codes / booleans); per-group values come from an
  unrolled masked-reduction loop over the (small, static) capacity — each
  reduction is a streaming VPU pass, XLA fuses the whole unrolled set into
  few passes.
- **sorted** (the FlatHash analog): rows are permuted group-contiguous
  (stable multi-key argsort, dead rows last); per-group sums are
  cumsum-then-boundary-difference (exact in int64), min/max are a segmented
  associative scan — all streaming ops, no scatter.

Float sums still use ``jax.ops.segment_sum`` (f32 scatter is fast on TPU and
per-slot accumulation order is deterministic).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from trino_tpu.ops import ranks

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]

# Above this capacity the unrolled masked loop stops making sense and the
# sort-based layout wins (threshold: capacity reads of the column).
DIRECT_CAPACITY_MAX = 128


@dataclasses.dataclass
class GroupLayout:
    """Grouping structure shared by every aggregate of one aggregation node.

    Exactly one of (``gids``,) / (``order``, ``gid_sorted``) is populated:
    direct layouts keep per-row perfect-index group ids in original row
    order; sorted layouts keep the permutation to group-contiguous order
    plus per-slot [start, end) ranges in that sorted space.
    """

    n: int  # input rows
    capacity: int  # static output slots
    # direct layout
    gids: Optional[jnp.ndarray] = None  # int32[n] perfect index
    # sorted layout
    order: Optional[jnp.ndarray] = None  # int32[n] permutation
    gid_sorted: Optional[jnp.ndarray] = None  # int32[n] non-decreasing
    starts: Optional[jnp.ndarray] = None  # int32[capacity]
    ends: Optional[jnp.ndarray] = None  # int32[capacity]
    num_groups: Optional[jnp.ndarray] = None  # scalar (sorted only)
    rep: Optional[jnp.ndarray] = None  # int[capacity] representative row (orig order)

    @property
    def is_direct(self) -> bool:
        return self.gids is not None

    def gids_layout(self) -> jnp.ndarray:
        """Per-row group ids in LAYOUT SPACE (original order for direct
        layouts, sorted order for sorted ones)."""
        return self.gids if self.gids is not None else self.gid_sorted

    def gids_orig(self) -> jnp.ndarray:
        """Per-row group ids in original row order (rarely needed: only
        nested regroupings like count(DISTINCT) ask for it)."""
        if self.gids is not None:
            return self.gids
        inverse = ranks.inverse_permutation(self.order)
        return self.gid_sorted[inverse]


def direct_layout(gids: jnp.ndarray, capacity: int, live: Optional[jnp.ndarray]) -> GroupLayout:
    """Layout for perfect-index group ids (capacity <= DIRECT_CAPACITY_MAX)."""
    n = gids.shape[0]
    assert capacity <= DIRECT_CAPACITY_MAX
    idx = jnp.arange(n, dtype=jnp.int32)
    dead_idx = jnp.int32(n)
    reps = []
    for g in range(capacity):
        m = gids == g
        if live is not None:
            m = m & live
        reps.append(jnp.min(jnp.where(m, idx, dead_idx)))
    rep = jnp.stack(reps)
    return GroupLayout(n=n, capacity=capacity, gids=gids, rep=rep)


def sorted_layout(
    order: jnp.ndarray, gid_sorted: jnp.ndarray, num_groups: jnp.ndarray
) -> GroupLayout:
    """Layout from a group-contiguous permutation (ops/groupby.py).

    ``gid_sorted`` is DENSE and non-decreasing (run k has gid k), so slot
    ranges need no rank search: compacting the run-boundary positions to
    the front with one bool-key sort yields ``starts`` directly, and each
    run ends where the next begins. One n-row 2-operand sort replaces the
    2n-row combined rank sort plus its inverse-permutation sort."""
    n = order.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), gid_sorted[1:] != gid_sorted[:-1]]
    )
    nb = jnp.sum(boundary.astype(jnp.int32))
    _, starts_seq = jax.lax.sort((~boundary, pos), num_keys=1, is_stable=True)
    nn = jnp.int32(n)
    starts = jnp.where(pos < nb, starts_seq, nn)
    next_start = jnp.concatenate([starts_seq[1:], jnp.full((1,), nn, jnp.int32)])
    ends = jnp.where(pos < nb, jnp.where(pos + 1 < nb, next_start, nn), nn)
    rep = order[jnp.clip(starts, 0, n - 1)]
    return GroupLayout(
        n=n,
        capacity=n,
        order=order,
        gid_sorted=gid_sorted,
        starts=starts,
        ends=ends,
        num_groups=num_groups,
        rep=rep,
    )


def occupancy(layout: GroupLayout, live: Optional[jnp.ndarray]) -> jnp.ndarray:
    """bool[capacity]: slots holding at least one live row (the live mask is
    already baked into ``rep`` by direct_layout)."""
    if layout.is_direct:
        return layout.rep < layout.n
    return jnp.arange(layout.capacity) < layout.num_groups


def _cumsum_diff_ranges(
    starts: jnp.ndarray, ends: jnp.ndarray, x_sorted: jnp.ndarray
) -> jnp.ndarray:
    """Per-range sums of a segment-contiguous array via cumsum + boundary
    difference (exact for ints: wraparound cancels mod 2^64)."""
    c = jnp.cumsum(x_sorted)
    c0 = jnp.concatenate([jnp.zeros((1,), c.dtype), c])
    return c0[ends] - c0[starts]


def _cumsum_diff(layout: GroupLayout, x_sorted: jnp.ndarray) -> jnp.ndarray:
    return _cumsum_diff_ranges(layout.starts, layout.ends, x_sorted)


def seg_sum(
    layout: GroupLayout, vals: jnp.ndarray, m: Optional[jnp.ndarray], out_dtype
) -> jnp.ndarray:
    """Per-slot sum of ``vals`` over rows where mask ``m`` holds.

    ``vals``/``m`` are in LAYOUT SPACE: original row order for direct
    layouts, group-contiguous sorted order for sorted layouts. Callers get
    sorted-space arrays for free as payload operands of the grouping sort
    (Executor.group_structure) — a per-aggregate random re-gather by the
    permutation would cost ~40 ms per 6M rows on v5e."""
    x = vals.astype(out_dtype)
    if m is not None:
        x = jnp.where(m, x, jnp.zeros((), out_dtype))
    if layout.is_direct:
        return jnp.stack([jnp.sum(jnp.where(layout.gids == g, x, 0)) for g in range(layout.capacity)])
    if jnp.issubdtype(jnp.dtype(out_dtype), jnp.floating):
        # f32/f64 scatter-add is fast on TPU and avoids cumsum error growth
        return jax.ops.segment_sum(
            x, layout.gid_sorted, num_segments=layout.capacity
        )
    return _cumsum_diff(layout, x)


def seg_count(layout: GroupLayout, m: Optional[jnp.ndarray]) -> jnp.ndarray:
    """Per-slot count of rows where mask ``m`` holds (int64). ``m`` is in
    layout space (see seg_sum)."""
    ones = (
        jnp.ones((layout.n,), jnp.int64)
        if m is None
        else m.astype(jnp.int64)
    )
    if layout.is_direct:
        return jnp.stack(
            [jnp.sum(jnp.where(layout.gids == g, ones, 0)) for g in range(layout.capacity)]
        )
    if m is None:
        return (layout.ends - layout.starts).astype(jnp.int64)
    return _cumsum_diff(layout, ones)


def seg_minmax(
    layout: GroupLayout, vals: jnp.ndarray, m: Optional[jnp.ndarray], is_min: bool
) -> jnp.ndarray:
    """Per-slot min/max of vals over rows where ``m`` holds (sentinel-filled
    for empty slots — pair with seg_count to derive validity).

    ``vals``/``m`` are in layout space (see seg_sum).

    Sorted path: one fused sort by (gid, value) puts each group's min at its
    start and max at its end — two gathers finish the job. (A segmented
    associative_scan would be the textbook formulation, but its unrolled
    log-depth graph does not compile at multi-million rows on v5e.)"""
    if jnp.issubdtype(vals.dtype, jnp.floating):
        sentinel = jnp.inf if is_min else -jnp.inf
    elif vals.dtype == jnp.bool_:
        vals = vals.astype(jnp.int32)
        sentinel = 1 if is_min else 0
    else:
        info = jnp.iinfo(vals.dtype)
        sentinel = info.max if is_min else info.min
    x = vals if m is None else jnp.where(m, vals, sentinel)
    if layout.is_direct:
        red = jnp.min if is_min else jnp.max
        return jnp.stack(
            [red(jnp.where(layout.gids == g, x, sentinel)) for g in range(layout.capacity)]
        )
    _, x_by_group = jax.lax.sort((layout.gid_sorted, x), num_keys=2)
    n = layout.n
    pos = layout.starts if is_min else jnp.clip(layout.ends - 1, 0, n - 1)
    out = x_by_group[jnp.clip(pos, 0, n - 1)]
    return jnp.where(layout.ends > layout.starts, out, sentinel)


def monotonic_segment_sum(
    x: jnp.ndarray, seg: jnp.ndarray, n_segments: int
) -> jnp.ndarray:
    """Segment sums when ``seg`` is already non-decreasing (e.g. the
    probe-major output of a join expansion) — cumsum + boundary diff,
    no scatter."""
    slots = jnp.arange(n_segments, dtype=seg.dtype)
    starts, cnt = ranks.sorted_ranks([seg], [slots])
    return _cumsum_diff_ranges(starts, starts + cnt, x)
