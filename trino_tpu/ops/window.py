"""Window-function kernels: one fused sort + streaming prefix passes.

Reference: ``operator/WindowOperator.java:69`` + ``window/`` (36 files) —
which iterates partitions row-by-row with per-frame state. TPU redesign:
sort ALL rows once by (dead, partition keys, order keys); in sorted space
every quantity is a streaming prefix computation:

- partition / peer-run starts: ``lax.cummax`` over boundary-masked indices;
- row_number / rank / dense_rank: index arithmetic on those starts;
- running and whole-partition sums/counts: cumsum + gathered boundary
  differences (peer-run ends from merge ranks, ops/ranks.py);
- whole-partition min/max: one extra sort by (partition, value), gather at
  partition starts/ends (same trick as ops/segments.seg_minmax);
- lag/lead/first_value/last_value: bounds-checked gathers in sorted space.

Results return to original row order through the sort's inverse permutation.
Everything is O(n log n) with static shapes — no per-partition loop exists.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from trino_tpu.ops import ranks
from trino_tpu.ops import sort as sort_ops

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]


@dataclasses.dataclass
class WindowLayout:
    """Shared sorted-space structure for all window calls of one node."""

    n: int
    order: jnp.ndarray  # int32[n]: sorted slot -> original row
    inv: jnp.ndarray  # int32[n]: original row -> sorted slot
    part_start: jnp.ndarray  # int32[n] per sorted slot
    part_end: jnp.ndarray  # int32[n] per sorted slot (exclusive)
    peer_start: jnp.ndarray  # int32[n]
    peer_end: jnp.ndarray  # int32[n] (exclusive)
    part_id: jnp.ndarray  # int32[n] dense, non-decreasing
    dense_peer: jnp.ndarray  # int32[n] peer-run ordinal within all rows


def _null_split(col: Lowered) -> List[jnp.ndarray]:
    """(null_flag, masked_value) arrays so NULL groups/compares as its own
    value (IS NOT DISTINCT semantics for PARTITION BY / peer detection)."""
    vals, valid = col
    if valid is None:
        return [vals]
    return [~valid, jnp.where(valid, vals, jnp.zeros((), vals.dtype))]


def build_layout(
    partition_keys: List[Lowered],
    order_keys: List[Tuple[Lowered, bool, Optional[bool]]],
    sel: Optional[jnp.ndarray],
    n: int,
) -> WindowLayout:
    sort_keys: List[jnp.ndarray] = []
    if sel is not None:
        sort_keys.append(~sel)  # dead rows last, outside every partition
    part_cols: List[jnp.ndarray] = []
    for pk in partition_keys:
        part_cols.extend(_null_split(pk))
    sort_keys.extend(part_cols)
    peer_cols: List[jnp.ndarray] = []
    for (col, asc, nf) in order_keys:
        peer_cols.extend(sort_ops._sort_key(col[0], col[1], asc, nf))
    sort_keys.extend(peer_cols)
    if not sort_keys:
        sort_keys = [jnp.zeros((n,), jnp.int8)]
    order = ranks.lex_argsort32(sort_keys)
    inv = ranks.inverse_permutation(order)

    def boundary(cols: List[jnp.ndarray]) -> jnp.ndarray:
        neq = jnp.zeros((max(n - 1, 0),), bool)
        for c in cols:
            cs = c[order]
            neq = neq | (cs[1:] != cs[:-1])
        return jnp.concatenate([jnp.ones((1,), bool), neq])

    dead_cols = [~sel] if sel is not None else []
    pb = boundary(dead_cols + part_cols)
    peerb = pb | boundary(peer_cols) if peer_cols else pb
    idx = jnp.arange(n, dtype=jnp.int32)
    part_start = jax.lax.cummax(jnp.where(pb, idx, jnp.int32(-1)))
    peer_start = jax.lax.cummax(jnp.where(peerb, idx, jnp.int32(-1)))
    part_id = jnp.cumsum(pb.astype(jnp.int32)) - 1
    dense_peer = jnp.cumsum(peerb.astype(jnp.int32)) - 1
    # ends via merge ranks over the dense non-decreasing ids
    ps, pc = ranks.sorted_ranks([part_id], [part_id])
    part_end = ps + pc
    es, ec = ranks.sorted_ranks([dense_peer], [dense_peer])
    peer_end = es + ec
    return WindowLayout(
        n=n, order=order, inv=inv,
        part_start=part_start, part_end=part_end,
        peer_start=peer_start, peer_end=peer_end,
        part_id=part_id, dense_peer=dense_peer,
    )


def _to_orig(layout: WindowLayout, sorted_vals, sorted_valid=None) -> Lowered:
    v = sorted_vals[layout.inv]
    return v, (sorted_valid[layout.inv] if sorted_valid is not None else None)


def row_number(layout: WindowLayout) -> Lowered:
    idx = jnp.arange(layout.n, dtype=jnp.int64)
    return _to_orig(layout, idx - layout.part_start + 1)


def rank(layout: WindowLayout) -> Lowered:
    v = (layout.peer_start - layout.part_start + 1).astype(jnp.int64)
    return _to_orig(layout, v)


def dense_rank(layout: WindowLayout) -> Lowered:
    base = layout.dense_peer[jnp.clip(layout.part_start, 0, layout.n - 1)]
    v = (layout.dense_peer - base + 1).astype(jnp.int64)
    return _to_orig(layout, v)


def _frame_bounds(layout: WindowLayout, frame: str,
                  frame_lo=None, frame_hi=None):
    """[lo, hi) sorted-slot range per row for the supported frames.
    ``rows_offset``: numeric ROWS bounds relative to the current row
    (reference: window/FrameInfo), clamped to the partition."""
    idx = jnp.arange(layout.n, dtype=jnp.int32)
    if frame == "partition":
        return layout.part_start, layout.part_end
    if frame == "rows_running":
        return layout.part_start, idx + 1
    if frame == "rows_offset":
        lo = layout.part_start if frame_lo is None else jnp.maximum(
            layout.part_start, idx + jnp.int32(frame_lo))
        hi = layout.part_end if frame_hi is None else jnp.minimum(
            layout.part_end, idx + jnp.int32(frame_hi) + 1)
        return lo, jnp.maximum(hi, lo)  # empty frame -> hi == lo
    # default 'running': RANGE UNBOUNDED PRECEDING..CURRENT ROW = peers incl.
    return layout.part_start, layout.peer_end


def agg_sum(layout: WindowLayout, arg: Lowered, frame: str, out_dtype,
            frame_lo=None, frame_hi=None) -> Lowered:
    vals, valid = arg
    x = vals[layout.order].astype(out_dtype)
    m = valid[layout.order] if valid is not None else None
    if m is not None:
        x = jnp.where(m, x, jnp.zeros((), out_dtype))
    c = jnp.cumsum(x)
    c0 = jnp.concatenate([jnp.zeros((1,), c.dtype), c])
    lo, hi = _frame_bounds(layout, frame, frame_lo, frame_hi)
    s = c0[hi] - c0[lo]
    cnt = _count_in_frame(layout, m, lo, hi)
    return _to_orig(layout, s, cnt > 0)


def agg_count(layout: WindowLayout, arg: Optional[Lowered], frame: str,
              frame_lo=None, frame_hi=None) -> Lowered:
    lo, hi = _frame_bounds(layout, frame, frame_lo, frame_hi)
    if arg is None or arg[1] is None:
        return _to_orig(layout, (hi - lo).astype(jnp.int64))
    m = arg[1][layout.order]
    return _to_orig(layout, _count_in_frame(layout, m, lo, hi))


def _count_in_frame(layout, m, lo, hi) -> jnp.ndarray:
    if m is None:
        return (hi - lo).astype(jnp.int64)
    c = jnp.cumsum(m.astype(jnp.int64))
    c0 = jnp.concatenate([jnp.zeros((1,), c.dtype), c])
    return c0[hi] - c0[lo]


def agg_minmax(layout: WindowLayout, arg: Lowered, frame: str, is_min: bool) -> Lowered:
    """Whole-partition min/max via one sort by (partition, value)."""
    if frame != "partition":
        raise NotImplementedError("running min/max window frames")
    vals, valid = arg
    if jnp.issubdtype(vals.dtype, jnp.floating):
        sentinel = jnp.inf if is_min else -jnp.inf
    else:
        info = jnp.iinfo(vals.dtype if vals.dtype != jnp.bool_ else jnp.int32)
        vals = vals.astype(jnp.int32) if vals.dtype == jnp.bool_ else vals
        sentinel = info.max if is_min else info.min
    x = vals if valid is None else jnp.where(valid, vals, sentinel)
    xs = x[layout.order]
    _, x_by = jax.lax.sort((layout.part_id, xs), num_keys=2)
    pos = layout.part_start if is_min else jnp.clip(layout.part_end - 1, 0, layout.n - 1)
    out = x_by[pos]
    m = valid[layout.order] if valid is not None else None
    lo, hi = _frame_bounds(layout, "partition")
    cnt = _count_in_frame(layout, m, lo, hi)
    return _to_orig(layout, out, cnt > 0)


def shifted_value(layout: WindowLayout, arg: Lowered, offset: int, lead: bool) -> Lowered:
    """lag/lead: the value ``offset`` rows before/after within the partition
    (NULL outside)."""
    vals, valid = arg
    xs = vals[layout.order]
    vs = valid[layout.order] if valid is not None else None
    idx = jnp.arange(layout.n, dtype=jnp.int32)
    tgt = idx + offset if lead else idx - offset
    inside = (tgt >= layout.part_start) & (tgt < layout.part_end)
    tgt = jnp.clip(tgt, 0, layout.n - 1)
    v = xs[tgt]
    ok = inside if vs is None else (inside & vs[tgt])
    return _to_orig(layout, v, ok)


def edge_value(layout: WindowLayout, arg: Lowered, frame: str, first: bool,
               frame_lo=None, frame_hi=None) -> Lowered:
    """first_value / last_value over the frame (default frame: last_value is
    the current peer run's end — the SQL footgun, faithfully)."""
    vals, valid = arg
    xs = vals[layout.order]
    vs = valid[layout.order] if valid is not None else None
    lo, hi = _frame_bounds(layout, frame, frame_lo, frame_hi)
    pos = lo if first else jnp.clip(hi - 1, 0, layout.n - 1)
    v = xs[pos]
    ok = None if vs is None else vs[pos]
    nonempty = hi > lo
    ok = nonempty if ok is None else (ok & nonempty)
    return _to_orig(layout, v, ok)


def nth_value(layout: WindowLayout, arg: Lowered, nth: int, frame: str,
              frame_lo=None, frame_hi=None) -> Lowered:
    """nth_value(x, n): the frame's n-th row's value (NULL past the end)."""
    vals, valid = arg
    xs = vals[layout.order]
    vs = valid[layout.order] if valid is not None else None
    lo, hi = _frame_bounds(layout, frame, frame_lo, frame_hi)
    pos = lo + jnp.int32(nth - 1)
    inside = pos < hi
    pos = jnp.clip(pos, 0, layout.n - 1)
    v = xs[pos]
    ok = inside if vs is None else (inside & vs[pos])
    return _to_orig(layout, v, ok)


def ntile(layout: WindowLayout, buckets: int) -> Lowered:
    """ntile(k): partition rows into k buckets, earlier buckets one larger
    when sizes don't divide (reference: window/NTileFunction)."""
    idx = jnp.arange(layout.n, dtype=jnp.int64)
    rn0 = idx - layout.part_start  # 0-based row number
    size = (layout.part_end - layout.part_start).astype(jnp.int64)
    k = jnp.int64(buckets)
    q = size // k
    r = size % k
    big_rows = r * (q + 1)  # rows covered by the (q+1)-sized buckets
    tile = jnp.where(
        rn0 < big_rows,
        rn0 // jnp.maximum(q + 1, 1),
        r + (rn0 - big_rows) // jnp.maximum(q, 1),
    )
    return _to_orig(layout, tile + 1)


def percent_rank(layout: WindowLayout) -> Lowered:
    """(rank - 1) / (partition size - 1); 0 for single-row partitions."""
    rk = (layout.peer_start - layout.part_start).astype(jnp.float64)
    size = (layout.part_end - layout.part_start).astype(jnp.float64)
    v = jnp.where(size > 1, rk / jnp.maximum(size - 1.0, 1.0), 0.0)
    return _to_orig(layout, v)


def cume_dist(layout: WindowLayout) -> Lowered:
    """rows at-or-before the current peer group / partition size."""
    covered = (layout.peer_end - layout.part_start).astype(jnp.float64)
    size = (layout.part_end - layout.part_start).astype(jnp.float64)
    return _to_orig(layout, covered / jnp.maximum(size, 1.0))
