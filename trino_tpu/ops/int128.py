"""Int128 limb arithmetic for long decimals (p > 18).

Reference: ``core/trino-spi/.../spi/type/Int128Math.java`` (+ Int128.java) —
the reference's long-decimal substrate. Device representation here: a pair
of int64 arrays ``(hi, lo)``; ``lo`` carries the low 64 bits as a raw bit
pattern (interpreted unsigned), ``hi`` the high 64 bits including sign.

Engaged by the expression lowering (ops/expr_lower.py) for decimal
arithmetic whose intermediates or results exceed int64. Long-decimal
(p > 18) values AT REST are adaptive two-limb: columns carry an optional
``hi`` int64 limb (data/page.py Column.hi) exactly when the data needs it,
so the full ±(10^38 - 1) range round-trips, joins, groups, and sums;
results past the p=38 cap raise the deferred DECIMAL_OVERFLOW error
(matching the reference's Int128Math overflow throws).

All ops are elementwise on uint64 words (TPU-native 32-bit pairs under the
hood; no Python bigints inside jit).
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

I128 = Tuple[jnp.ndarray, jnp.ndarray]  # (hi int64, lo int64 bit pattern)

# numpy scalar to stay concrete if first imported under a trace
_MASK32 = np.uint64(0xFFFFFFFF)


def _u(x: jnp.ndarray) -> jnp.ndarray:
    return x.astype(jnp.uint64)


def _s(x: jnp.ndarray) -> jnp.ndarray:
    return x.astype(jnp.int64)


def from_int64(x: jnp.ndarray) -> I128:
    x = x.astype(jnp.int64)
    return x >> 63, x


def is_negative(a: I128) -> jnp.ndarray:
    return a[0] < 0


def neg(a: I128) -> I128:
    hi, lo = a
    nlo = _u(~lo) + jnp.uint64(1)
    # ~lo + 1 == 0 only when lo == 0 (then the +1 carries into hi)
    nhi = _u(~hi) + (nlo == 0).astype(jnp.uint64)
    return _s(nhi), _s(nlo)


def add(a: I128, b: I128) -> I128:
    hi1, lo1 = a
    hi2, lo2 = b
    lo = _u(lo1) + _u(lo2)
    carry = (lo < _u(lo1)).astype(jnp.uint64)
    hi = _u(hi1) + _u(hi2) + carry
    return _s(hi), _s(lo)


def sub(a: I128, b: I128) -> I128:
    return add(a, neg(b))


def abs128(a: I128) -> Tuple[I128, jnp.ndarray]:
    """(|a|, was_negative)."""
    n = is_negative(a)
    na = neg(a)
    return (jnp.where(n, na[0], a[0]), jnp.where(n, na[1], a[1])), n


def _mul_u64(x: jnp.ndarray, y: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Full 128-bit product of two uint64 arrays -> (hi u64, lo u64)."""
    x0, x1 = x & _MASK32, x >> 32
    y0, y1 = y & _MASK32, y >> 32
    ll = x0 * y0
    m1 = x1 * y0
    m2 = x0 * y1
    hh = x1 * y1
    t = (ll >> 32) + (m1 & _MASK32) + (m2 & _MASK32)
    lo = (ll & _MASK32) | (t << 32)
    hi = hh + (m1 >> 32) + (m2 >> 32) + (t >> 32)
    return hi, lo


def mul_int64(x: jnp.ndarray, y: jnp.ndarray) -> I128:
    """Exact signed product of two int64 arrays."""
    sx = x < 0
    sy = y < 0
    ax = _u(jnp.where(sx, -x, x))
    ay = _u(jnp.where(sy, -y, y))
    hi, lo = _mul_u64(ax, ay)
    res = (_s(hi), _s(lo))
    nres = neg(res)
    flip = sx ^ sy
    return jnp.where(flip, nres[0], res[0]), jnp.where(flip, nres[1], res[1])


def mul_small(a: I128, m: int) -> I128:
    """a * m for a small non-negative Python int m (< 2^63); caller must
    bound the magnitude (see mul_small_checked for the flagged variant)."""
    out, _ = mul_small_checked(a, m)
    return out


def mul_small_checked(a: I128, m: int) -> Tuple[I128, jnp.ndarray]:
    """(a * m, overflowed): flags rows whose |a|*m exceeds 2^127 - 1
    (reference: Int128Math overflow checks on rescale)."""
    (ahi, alo), n = abs128(a)
    mm = jnp.uint64(m)
    phi, plo = _mul_u64(_u(alo), mm)
    hh_hi, hh_lo = _mul_u64(_u(ahi), mm)  # high-limb product, 128-bit
    hi2 = phi + hh_lo
    overflow = (hh_hi != 0) | (hi2 < phi) | (_s(hi2) < 0)  # >= 2^127
    res = (_s(hi2), _s(plo))
    nres = neg(res)
    return (jnp.where(n, nres[0], res[0]), jnp.where(n, nres[1], res[1])), overflow


def mul_checked(a: I128, b: I128) -> Tuple[I128, jnp.ndarray]:
    """(a * b, overflowed) for two int128 operands — the low 128 bits of the
    signed product, flagging rows whose |a|*|b| exceeds 2^127 - 1
    (reference: Int128Math.multiply)."""
    (ahi, alo), na = abs128(a)
    (bhi, blo), nb = abs128(b)
    p_hi, p_lo = _mul_u64(_u(alo), _u(blo))  # |a|.lo * |b|.lo, 128-bit
    c1_hi, c1_lo = _mul_u64(_u(alo), _u(bhi))  # contributes << 64
    c2_hi, c2_lo = _mul_u64(_u(ahi), _u(blo))  # contributes << 64
    hh = (_u(ahi) != 0) & (_u(bhi) != 0)  # |a|.hi * |b|.hi -> always >= 2^128
    hi1 = p_hi + c1_lo
    hi2 = hi1 + c2_lo
    overflow = (
        hh
        | (c1_hi != 0)
        | (c2_hi != 0)
        | (hi1 < p_hi)
        | (hi2 < hi1)
        | (_s(hi2) < 0)  # >= 2^127
    )
    res = (_s(hi2), _s(p_lo))
    nres = neg(res)
    flip = na ^ nb
    return (
        jnp.where(flip, nres[0], res[0]),
        jnp.where(flip, nres[1], res[1]),
    ), overflow


def _divmod_core(hi: jnp.ndarray, lo: jnp.ndarray, dd: jnp.ndarray):
    """Unsigned (hi,lo) u64 pair divided by u64 ``dd`` (< 2^63): shift-
    subtract over the low word after dividing the high word (64 unrolled
    vector steps)."""
    q_hi = hi // dd
    r = hi % dd  # < d <= 2^63: doubling stays below 2^64
    q_lo = jnp.zeros_like(lo)
    for i in range(63, -1, -1):
        bit = (lo >> jnp.uint64(i)) & jnp.uint64(1)
        r = (r << jnp.uint64(1)) | bit
        ge = r >= dd
        r = jnp.where(ge, r - dd, r)
        q_lo = q_lo | (ge.astype(jnp.uint64) << jnp.uint64(i))
    return (_s(q_hi), _s(q_lo)), r


def divmod_u128(a: I128, b: I128) -> Tuple[I128, I128]:
    """Unsigned 128/128 division of NON-NEGATIVE operands (b > 0): classic
    shift-subtract long division, 128 unrolled vector steps (reference:
    Int128Math.divide's unsigned core). Returns (quotient, remainder)."""
    n_hi, n_lo = _u(a[0]), _u(a[1])
    d_hi, d_lo = _u(b[0]), _u(b[1])
    r_hi = jnp.zeros_like(n_hi)
    r_lo = jnp.zeros_like(n_lo)
    q_hi = jnp.zeros_like(n_hi)
    q_lo = jnp.zeros_like(n_lo)
    one = jnp.uint64(1)
    for i in range(127, -1, -1):
        bit = (
            (n_hi >> jnp.uint64(i - 64)) & one
            if i >= 64
            else (n_lo >> jnp.uint64(i)) & one
        )
        # r = (r << 1) | bit
        r_hi = (r_hi << one) | (r_lo >> jnp.uint64(63))
        r_lo = (r_lo << one) | bit
        ge = (r_hi > d_hi) | ((r_hi == d_hi) & (r_lo >= d_lo))
        # r -= d where ge
        borrow = (r_lo < d_lo).astype(jnp.uint64)
        r_lo = jnp.where(ge, r_lo - d_lo, r_lo)
        r_hi = jnp.where(ge, r_hi - d_hi - borrow, r_hi)
        if i >= 64:
            q_hi = q_hi | jnp.where(ge, one << jnp.uint64(i - 64), jnp.uint64(0))
        else:
            q_lo = q_lo | jnp.where(ge, one << jnp.uint64(i), jnp.uint64(0))
    return (_s(q_hi), _s(q_lo)), (_s(r_hi), _s(r_lo))


def divmod_u64(a: I128, d: int) -> Tuple[I128, jnp.ndarray]:
    """Unsigned division of a NON-NEGATIVE int128 by a Python int d < 2^63.
    Returns (quotient int128, remainder u64)."""
    return _divmod_core(_u(a[0]), _u(a[1]), jnp.uint64(d))


def divmod_u64_arr(a: I128, d: jnp.ndarray) -> Tuple[I128, jnp.ndarray]:
    """Unsigned division of a NON-NEGATIVE int128 by a positive u64 array."""
    return _divmod_core(_u(a[0]), _u(a[1]), d.astype(jnp.uint64))


def div_round_small(a: I128, d: int) -> I128:
    """a / d with HALF-UP rounding away from zero (Trino decimal rescale
    semantics, Int128Math.rescale), d a positive Python int < 2^63."""
    (ahi, alo), n = abs128(a)
    q, r = divmod_u64((ahi, alo), d)
    round_up = r >= jnp.uint64((d + 1) // 2)
    q = add(q, (jnp.zeros_like(q[0]), round_up.astype(jnp.int64)))
    nq = neg(q)
    return jnp.where(n, nq[0], q[0]), jnp.where(n, nq[1], q[1])


def compare(a: I128, b: I128) -> jnp.ndarray:
    """-1 / 0 / 1 signed comparison."""
    hi1, lo1 = a
    hi2, lo2 = b
    lt = (hi1 < hi2) | ((hi1 == hi2) & (_u(lo1) < _u(lo2)))
    gt = (hi1 > hi2) | ((hi1 == hi2) & (_u(lo1) > _u(lo2)))
    return jnp.where(lt, -1, jnp.where(gt, 1, 0)).astype(jnp.int8)


def fits_int64(a: I128) -> jnp.ndarray:
    """True where the value is exactly representable as int64."""
    hi, lo = a
    return hi == (lo >> 63)


def to_int64(a: I128) -> jnp.ndarray:
    """Low 64 bits as signed (caller checks fits_int64)."""
    return a[1]


def rescale(a: I128, from_scale: int, to_scale: int) -> I128:
    """Multiply/divide by powers of ten (half-up on scale-down)."""
    out, _ = rescale_checked(a, from_scale, to_scale)
    return out


def rescale_checked(a: I128, from_scale: int, to_scale: int) -> Tuple[I128, jnp.ndarray]:
    """rescale + a per-row overflow flag for the scale-up direction
    (scale-up by 10^40+ happily wraps 128 bits otherwise)."""
    if to_scale == from_scale:
        return a, jnp.zeros(a[0].shape, bool)
    if to_scale > from_scale:
        out = a
        overflow = jnp.zeros(a[0].shape, bool)
        k = to_scale - from_scale
        while k > 0:  # 10^18 fits the small-multiplier bound
            step = min(k, 18)
            out, ovf = mul_small_checked(out, 10 ** step)
            overflow = overflow | ovf
            k -= step
        return out, overflow
    out = a
    k = from_scale - to_scale
    while k > 18:
        out, _ = divmod_u64_signed_trunc(out, 10 ** 18)
        k -= 18
    return div_round_small(out, 10 ** k), jnp.zeros(a[0].shape, bool)


def divmod_u64_signed_trunc(a: I128, d: int) -> Tuple[I128, jnp.ndarray]:
    """Truncating signed division by positive d (no rounding)."""
    (ahi, alo), n = abs128(a)
    q, r = divmod_u64((ahi, alo), d)
    nq = neg(q)
    return (jnp.where(n, nq[0], q[0]), jnp.where(n, nq[1], q[1])), r
