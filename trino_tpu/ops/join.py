"""Join kernels: lookup (N:1), M:N expansion, semi/anti — sort-merge based.

Reference: ``operator/join/`` — PagesHash open addressing + PositionLinks
chains (JoinHash.java:28-69). TPU formulation: the build side is sorted by
key once (one fused multi-operand ``lax.sort``); probe ranges come from
merge ranks (ops/ranks.py: one combined stable sort + streaming prefixes —
binary search and its log2(n) random-gather passes never appear):

- unique-key build (PK-FK joins, N:1): probe -> at most one match -> output
  size == probe size (static shapes, no two-pass emit). The planner proves
  uniqueness (primary keys / group-by outputs) before choosing this kernel.
- general M:N join: two-pass count+emit (``probe_counts`` + ``expand``) —
  the role of PositionLinks chain-following (JoinHash.java:28-69), done as
  one vectorized gather into a *static-capacity* output (capacity from the
  executor's shape-hint mechanism; exceeding it raises a deferred error and
  triggers a bucketed recompile).
- semi/anti joins: membership only (duplicates on build side are fine).

Composite keys of any column count and full int64 range are supported (the
lex sort and merge ranks compare all columns; no bit packing). The reference
hashes arbitrary-width keys the same way (InterpretedHashGenerator.java:85).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax.numpy as jnp

from trino_tpu.ops import ranks

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]


def _sentinel_max(dtype):
    """Largest value of the key dtype — dead rows sort last under it. A live
    key equal to the sentinel is re-guarded by the live mask at probe time
    (probe_counts checks build.live at the range start)."""
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)


_INT_WIDEN = {jnp.dtype(jnp.int8): jnp.int16, jnp.dtype(jnp.int16): jnp.int32,
              jnp.dtype(jnp.int32): jnp.int64}


def align_join_keys(
    build_keys: List[Lowered],
    probe_keys: List[Lowered],
    build_vranges=None,
    probe_vranges=None,
) -> Tuple[List[Lowered], List[Lowered]]:
    """Cast each (build, probe) key pair to its common PHYSICAL dtype so the
    kernels below sort/compare at the narrowest width the data rides
    (data/page.py Column: int32-narrowed keys sort ~2x faster than emulated
    int64 on TPU). Bool keys promote to int8.

    Single-key builds mask dead rows with the dtype's max value (sentinel),
    so a live key equal to that max could collide with dead rows. When the
    pair's value ranges don't PROVE the max is unreachable, integer keys
    widen one step (int8->int16->...->int64; int64 keeps the legacy
    2^63-1 edge). Multi-key builds use a dead-flag column instead of a
    sentinel and never need this."""
    n = len(build_keys)
    single = n == 1
    if build_vranges is None:
        build_vranges = [None] * n
    if probe_vranges is None:
        probe_vranges = [None] * n
    out_b, out_p = [], []
    for (bv, bva), (pv, pva), bvr, pvr in zip(
        build_keys, probe_keys, build_vranges, probe_vranges
    ):
        dt = jnp.promote_types(bv.dtype, pv.dtype)
        if dt == jnp.bool_:
            dt = jnp.int8
        if single and jnp.issubdtype(dt, jnp.integer):
            proven = (
                bvr is not None and pvr is not None
                and max(bvr[1], pvr[1]) < jnp.iinfo(dt).max
            )
            if not proven and jnp.dtype(dt) in _INT_WIDEN:
                dt = _INT_WIDEN[jnp.dtype(dt)]
        out_b.append((bv.astype(dt), bva))
        out_p.append((pv.astype(dt), pva))
    return out_b, out_p


@dataclasses.dataclass
class SortedBuild:
    """Build side sorted lexicographically by key, dead rows last.

    ``cols`` are the search columns in sorted order, most significant first.
    Single-key builds carry one sentinel-masked column (fast path); multi-key
    builds carry a leading dead-flag column (0 live / 1 dead) so dead rows
    can never equal a probe (whose flag is implicitly 0).
    """

    cols: List[jnp.ndarray]
    rows: jnp.ndarray  # original row index per sorted slot
    live: jnp.ndarray  # bool per sorted slot
    single: bool  # True -> cols == [sentinel-masked key], no flag column

    @property
    def n(self) -> int:
        return self.rows.shape[0]


def _live_mask(keys: List[Lowered], sel: Optional[jnp.ndarray]) -> jnp.ndarray:
    n = keys[0][0].shape[0]
    live = jnp.ones((n,), dtype=bool)
    if sel is not None:
        live = live & sel
    for _, valid in keys:
        if valid is not None:
            live = live & valid
    return live


def build_side(keys: List[Lowered], sel: Optional[jnp.ndarray],
               presorted: bool = False) -> SortedBuild:
    """Sort the build side by composite key; dead/null rows sort last and can
    never match (single-key: sentinel; multi-key: leading dead-flag column).

    ``presorted``: the caller proves a SINGLE null-free key already
    ascending with dead rows forming a TAIL (Column.ascending +
    Page.live_prefix) — the build sort is skipped entirely (sentinel-masked
    dead tail keeps the array sorted: the sentinel is the dtype max)."""
    import jax

    live = _live_mask(keys, sel)
    n = live.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    if presorted and len(keys) == 1 and keys[0][1] is None:
        vals = keys[0][0]
        if vals.dtype == jnp.bool_:
            vals = vals.astype(jnp.int8)
        k = jnp.where(live, vals, _sentinel_max(vals.dtype))
        return SortedBuild([k], iota, live, True)
    # sorted key columns and the permuted live flags come out of the ONE
    # fused lax.sort (payload operands) — never re-gathered by the
    # permutation (random gathers cost ~40 ms per 6M rows on v5e)
    if len(keys) == 1:
        vals = keys[0][0]
        if vals.dtype == jnp.bool_:
            vals = vals.astype(jnp.int8)
        k = jnp.where(live, vals, _sentinel_max(vals.dtype))
        k_s, live_s, order = jax.lax.sort(
            (k, live, iota), num_keys=1, is_stable=True
        )
        return SortedBuild([k_s], order, live_s, True)
    dead = (~live).astype(jnp.int8)
    masked = [
        jnp.where(live, v.astype(jnp.int8) if v.dtype == jnp.bool_ else v,
                  jnp.zeros((), jnp.int8 if v.dtype == jnp.bool_ else v.dtype))
        for v, _ in keys
    ]
    sort_keys = [dead] + masked
    out = jax.lax.sort(
        tuple(sort_keys) + (live, iota), num_keys=len(sort_keys), is_stable=True
    )
    return SortedBuild(list(out[:-2]), out[-1], out[-2], False)


def _probe_cols(build: SortedBuild, probe_keys: List[Lowered]) -> List[jnp.ndarray]:
    """Probe-side search columns aligned with ``build.cols`` (callers align
    physical dtypes up front via align_join_keys)."""
    def as_key(v):
        return v.astype(jnp.int8) if v.dtype == jnp.bool_ else v

    if build.single:
        return [as_key(probe_keys[0][0])]
    m = probe_keys[0][0].shape[0]
    return [jnp.zeros((m,), jnp.int8)] + [as_key(v) for v, _ in probe_keys]


def probe_valid(probe_keys: List[Lowered]) -> Optional[jnp.ndarray]:
    """AND of per-column probe validity (NULL keys never match)."""
    valid = None
    for _, v in probe_keys:
        if v is not None:
            valid = v if valid is None else (valid & v)
    return valid


def probe_unique(
    build: SortedBuild, probe_keys: List[Lowered]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Probe against a unique-key build. Returns (build_row_idx, matched)."""
    lo, counts = probe_counts(build, probe_keys, None)
    pos = jnp.clip(lo, 0, build.n - 1)
    return build.rows[pos], counts > 0


def membership(
    build_keys: List[Lowered],
    build_sel: Optional[jnp.ndarray],
    probe_keys: List[Lowered],
    presorted: bool = False,
) -> jnp.ndarray:
    """Semi-join membership test (build side may have duplicates)."""
    build = build_side(build_keys, build_sel, presorted=presorted)
    _, counts = probe_counts(build, probe_keys, None)
    return counts > 0


def probe_counts(
    build: SortedBuild,
    probe_keys: List[Lowered],
    probe_sel: Optional[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pass 1 of the M:N join: per probe row, the sorted-build range start
    and match count (merge ranks, ops/ranks.py — no binary search). Dead
    probe rows (sel/NULL key) count 0."""
    probe = _probe_cols(build, probe_keys)
    lo, counts = ranks.sorted_ranks(build.cols, probe)
    # ranges of a real key contain only live rows (dead rows sort last with
    # unmatchable key) but guard the all-dead-build edge anyway
    counts = jnp.where(build.live[jnp.clip(lo, 0, build.n - 1)], counts, 0)
    pvalid = probe_valid(probe_keys)
    if pvalid is not None:
        counts = jnp.where(pvalid, counts, 0)
    if probe_sel is not None:
        counts = jnp.where(probe_sel, counts, 0)
    return lo, counts


def expand(
    counts: jnp.ndarray, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pass 2: map output slot j -> (probe_row, within-range offset).

    Returns (probe_row[cap], offset_in_range[cap], live[cap], total).
    Output is probe-major (all matches of probe row 0, then row 1, ...).
    """
    n = counts.shape[0]
    c64 = counts.astype(jnp.int64)  # cumsum in int64: totals can exceed 2^31
    if n == 0:  # zero-row probe page: all output slots dead
        z = jnp.zeros((capacity,), jnp.int64)
        return z, z, jnp.zeros((capacity,), bool), jnp.zeros((), jnp.int64)
    offsets = jnp.cumsum(c64)  # inclusive
    total = offsets[n - 1]
    starts = offsets - c64
    # search in int32 when capacity fits: offsets past 2^31 only occur when
    # total overflowed the capacity anyway (flagged, run discarded), so
    # clipping them cannot change any slot j < capacity's result
    if capacity < 2**31:
        offs = jnp.clip(offsets, 0, 2**31 - 1).astype(jnp.int32)
        j = jnp.arange(capacity, dtype=jnp.int32)
    else:
        offs = offsets
        j = jnp.arange(capacity, dtype=jnp.int64)
    # both sides sorted -> merge ranks, not binary search
    p = jnp.clip(ranks.ranks_sorted_queries(offs, j, side="right"), 0, n - 1)
    k = j.astype(jnp.int64) - starts[p]
    live = j < jnp.minimum(total, capacity).astype(j.dtype)
    return p, k, live, total


# ---------------------------------------------------------------- dense path
# Direct-address join: when the single integer build key rides a known value
# range (Column.vrange) whose span fits a device table, the build side
# scatters row ids into a span-sized table and the probe side does ONE
# bounded gather — no sort of either side ever happens. This is the TPU
# answer to the reference's array-based lookup sources
# (``operator/join/ArrayBasedLookupSource``): TPC-H/DS keys are dense
# integer sequences, so the "hash" is the identity map onto the vrange.
DENSE_SPAN_MAX = 1 << 27  # int32 table slots (512 MiB worst case)


def dense_span(build_vrange, n_build: int) -> Optional[Tuple[int, int]]:
    """(lo, span) when a direct-address table is worth it, else None.
    Worth it = span bounded AND not absurdly sparse relative to the build
    (a 128x-over-provisioned table still beats a sort at these sizes)."""
    if build_vrange is None:
        return None
    lo, hi = int(build_vrange[0]), int(build_vrange[1])
    span = hi - lo + 1
    if span <= 0 or span > DENSE_SPAN_MAX:
        return None
    if span > 128 * max(n_build, 1024):
        return None
    return lo, span


def dense_unique_table(
    key: Lowered, sel: Optional[jnp.ndarray], lo: int, span: int
) -> jnp.ndarray:
    """Scatter build row ids (+1; 0 = empty) into the span table. Dead rows
    scatter to DISTINCT out-of-bounds slots (span + iota) and are dropped,
    so ``unique_indices`` stays truthful — the planner proved live-key
    uniqueness (right_unique) before choosing this kernel."""
    vals, valid = key
    n = vals.shape[0]
    iota = jnp.arange(n, dtype=jnp.int64)
    live = jnp.ones((n,), bool) if sel is None else sel
    if valid is not None:
        live = live & valid
    idx = jnp.where(live, vals.astype(jnp.int64) - lo, span + iota)
    return jnp.zeros((span,), jnp.int32).at[idx].set(
        iota.astype(jnp.int32) + 1, mode="drop", unique_indices=True)


def dense_probe_unique(
    table: jnp.ndarray, key: Lowered, lo: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(build_row_idx, matched) — the dense analog of probe_unique."""
    vals, valid = key
    span = table.shape[0]
    v = vals.astype(jnp.int64)
    slot = table[jnp.clip(v - lo, 0, span - 1)]
    matched = (v >= lo) & (v < lo + span) & (slot > 0)
    if valid is not None:
        matched = matched & valid
    return jnp.maximum(slot - 1, 0), matched


def dense_membership_table(
    build_key: Lowered, build_sel: Optional[jnp.ndarray], lo: int, span: int,
) -> jnp.ndarray:
    """Build half of the dense membership test: the boolean LUT (build
    duplicates are fine: True is idempotent, so the non-unique scatter-set
    is deterministic). Split out so callers probing many pages against ONE
    build (the overlapped per-block exchange) scatter the table once."""
    bvals, bvalid = build_key
    live = (jnp.ones((bvals.shape[0],), bool) if build_sel is None
            else build_sel)
    if bvalid is not None:
        live = live & bvalid
    idx = jnp.where(live, bvals.astype(jnp.int64) - lo, span)
    return jnp.zeros((span,), bool).at[idx].set(True, mode="drop")


def dense_membership_probe(
    lut: jnp.ndarray, probe_key: Lowered, lo: int,
) -> jnp.ndarray:
    """Probe half of the dense membership test: one bounded gather."""
    span = lut.shape[0]
    pvals, pvalid = probe_key
    v = pvals.astype(jnp.int64)
    hit = (v >= lo) & (v < lo + span) & lut[jnp.clip(v - lo, 0, span - 1)]
    if pvalid is not None:
        hit = hit & pvalid
    return hit


def dense_membership(
    build_key: Lowered, build_sel: Optional[jnp.ndarray],
    probe_key: Lowered, lo: int, span: int,
) -> jnp.ndarray:
    """Semi-join membership via a boolean LUT (one scatter, one bounded
    gather)."""
    lut = dense_membership_table(build_key, build_sel, lo, span)
    return dense_membership_probe(lut, probe_key, lo)


def gather_columns(
    cols: List[Lowered], rows: jnp.ndarray, matched: jnp.ndarray
) -> List[Lowered]:
    """Gather build columns to probe positions in ONE random-HBM pass per
    dtype (ranks.batched_gather) — separate computed-index gathers don't
    fuse and cost ~40 ms per 6M rows each on v5e. Unmatched rows become
    NULL (consumed by inner-join sel or left-join null masks)."""
    if not cols:
        return []
    n = cols[0][0].shape[0]
    safe = jnp.clip(rows, 0, n - 1)
    arrays = [vals for vals, _ in cols] + [
        valid for _, valid in cols if valid is not None
    ]
    gathered = ranks.batched_gather(arrays, safe)
    out: List[Lowered] = []
    vi = len(cols)
    for i, (_, valid) in enumerate(cols):
        if valid is None:
            out.append((gathered[i], matched))
        else:
            out.append((gathered[i], gathered[vi] & matched))
            vi += 1
    return out
