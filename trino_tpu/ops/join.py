"""Join kernels: lookup (N:1), M:N expansion, semi/anti — searchsorted-based.

Reference: ``operator/join/`` — PagesHash open addressing + PositionLinks
chains (JoinHash.java:28-69). TPU formulation: the build side is sorted by
key once; probes binary-search (``jnp.searchsorted``, log2(n) vectorized
steps, no scatter):

- unique-key build (PK-FK joins, N:1): probe -> at most one match -> output
  size == probe size (static shapes, no two-pass emit). The planner proves
  uniqueness (primary keys / group-by outputs) before choosing this kernel.
- general M:N join: two-pass count+emit (``probe_counts`` + ``expand``) —
  the role of PositionLinks chain-following (JoinHash.java:28-69), done as
  one vectorized gather into a *static-capacity* output (capacity from the
  executor's shape-hint mechanism; exceeding it raises a deferred error and
  triggers a bucketed recompile).
- semi/anti joins: membership only (duplicates on build side are fine).
- composite keys pack into one int64 (32/32 bits) — planner guarantees range.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]

_DEAD_KEY = jnp.int64(2**63 - 1)  # sorts last; equality re-checked via sel gather


def pack_keys(keys: List[Lowered]) -> Lowered:
    """Combine multiple int key columns into one int64 (32 bits each for 2
    keys). Valid only when the planner has proven the ranges fit."""
    if len(keys) == 1:
        return keys[0]
    if len(keys) == 2:
        (a, av), (b, bv) = keys
        vals = (a.astype(jnp.int64) << 32) | (b.astype(jnp.int64) & 0xFFFFFFFF)
        valid = None
        if av is not None or bv is not None:
            valid = (av if av is not None else True) & (bv if bv is not None else True)
        return vals, valid
    raise NotImplementedError(">2 join key columns")


def build_side(key: Lowered, sel: Optional[jnp.ndarray]):
    """Sort the build side by key; dead/null rows get a sentinel that sorts
    last and can never match (their liveness is re-checked on gather)."""
    vals, valid = key
    n = vals.shape[0]
    live = jnp.ones((n,), dtype=bool)
    if sel is not None:
        live = live & sel
    if valid is not None:
        live = live & valid
    k = jnp.where(live, vals.astype(jnp.int64), _DEAD_KEY)
    order = jnp.argsort(k, stable=True)
    return k[order], order, live[order]


def probe_unique(
    build_keys_sorted: jnp.ndarray,
    build_rows: jnp.ndarray,
    build_live: jnp.ndarray,
    probe_key: Lowered,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Probe against a unique-key build. Returns (build_row_idx, matched)."""
    pvals, pvalid = probe_key
    n = build_keys_sorted.shape[0]
    pos = jnp.searchsorted(build_keys_sorted, pvals.astype(jnp.int64))
    pos = jnp.clip(pos, 0, n - 1)
    hit = (build_keys_sorted[pos] == pvals.astype(jnp.int64)) & build_live[pos]
    if pvalid is not None:
        hit = hit & pvalid
    return build_rows[pos], hit


def membership(
    build_key: Lowered, build_sel: Optional[jnp.ndarray], probe_key: Lowered
) -> jnp.ndarray:
    """Semi-join membership test (build side may have duplicates)."""
    bk_sorted, _, live = build_side(build_key, build_sel)
    pvals, pvalid = probe_key
    n = bk_sorted.shape[0]
    pos = jnp.clip(jnp.searchsorted(bk_sorted, pvals.astype(jnp.int64)), 0, n - 1)
    hit = (bk_sorted[pos] == pvals.astype(jnp.int64)) & live[pos]
    if pvalid is not None:
        hit = hit & pvalid
    return hit


def probe_counts(
    build_keys_sorted: jnp.ndarray,
    build_live: jnp.ndarray,
    probe_key: Lowered,
    probe_sel: Optional[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pass 1 of the M:N join: per probe row, the sorted-build range start
    and match count. Dead probe rows (sel/NULL key) count 0."""
    pvals, pvalid = probe_key
    pv = pvals.astype(jnp.int64)
    lo = jnp.searchsorted(build_keys_sorted, pv, side="left")
    hi = jnp.searchsorted(build_keys_sorted, pv, side="right")
    counts = hi - lo
    # ranges of a real key contain only live rows (dead keys got the sentinel)
    # but guard the all-dead-build edge anyway
    counts = jnp.where(
        build_live[jnp.clip(lo, 0, build_live.shape[0] - 1)], counts, 0
    )
    if pvalid is not None:
        counts = jnp.where(pvalid, counts, 0)
    if probe_sel is not None:
        counts = jnp.where(probe_sel, counts, 0)
    return lo, counts


def expand(
    counts: jnp.ndarray, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pass 2: map output slot j -> (probe_row, within-range offset).

    Returns (probe_row[cap], offset_in_range[cap], live[cap], total).
    Output is probe-major (all matches of probe row 0, then row 1, ...).
    """
    n = counts.shape[0]
    offsets = jnp.cumsum(counts)  # inclusive
    total = offsets[n - 1]
    starts = offsets - counts
    j = jnp.arange(capacity, dtype=counts.dtype)
    p = jnp.clip(jnp.searchsorted(offsets, j, side="right"), 0, n - 1)
    k = j - starts[p]
    live = j < total
    return p, k, live, total


def gather_column(col: Lowered, rows: jnp.ndarray, matched: jnp.ndarray) -> Lowered:
    """Gather a build column to probe positions; unmatched rows become NULL
    (consumed by inner-join sel or left-join null masks)."""
    vals, valid = col
    n = vals.shape[0]
    safe = jnp.clip(rows, 0, n - 1)
    v = vals[safe]
    va = matched if valid is None else (valid[safe] & matched)
    return v, va
