"""Join kernels: lookup (N:1), semi/anti membership — searchsorted-based.

Reference: ``operator/join/`` — PagesHash open addressing + PositionLinks
chains (JoinHash.java:28-69). TPU formulation: the build side is sorted by
key once; probes binary-search (``jnp.searchsorted``, log2(n) vectorized
steps, no scatter). Round-1 scope:

- unique-key build (PK-FK joins, N:1): probe -> at most one match -> output
  size == probe size (static shapes, no two-pass emit). The planner proves
  uniqueness (primary keys / group-by outputs) before choosing this kernel.
- semi/anti joins: membership only (duplicates on build side are fine).
- composite keys pack into one int64 (32/32 bits) — planner guarantees range.

General M:N inner join (two-pass count+emit) is a round-2 kernel.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]

_DEAD_KEY = jnp.int64(2**63 - 1)  # sorts last; equality re-checked via sel gather


def pack_keys(keys: List[Lowered]) -> Lowered:
    """Combine multiple int key columns into one int64 (32 bits each for 2
    keys). Valid only when the planner has proven the ranges fit."""
    if len(keys) == 1:
        return keys[0]
    if len(keys) == 2:
        (a, av), (b, bv) = keys
        vals = (a.astype(jnp.int64) << 32) | (b.astype(jnp.int64) & 0xFFFFFFFF)
        valid = None
        if av is not None or bv is not None:
            valid = (av if av is not None else True) & (bv if bv is not None else True)
        return vals, valid
    raise NotImplementedError(">2 join key columns")


def build_side(key: Lowered, sel: Optional[jnp.ndarray]):
    """Sort the build side by key; dead/null rows get a sentinel that sorts
    last and can never match (their liveness is re-checked on gather)."""
    vals, valid = key
    n = vals.shape[0]
    live = jnp.ones((n,), dtype=bool)
    if sel is not None:
        live = live & sel
    if valid is not None:
        live = live & valid
    k = jnp.where(live, vals.astype(jnp.int64), _DEAD_KEY)
    order = jnp.argsort(k, stable=True)
    return k[order], order, live[order]


def probe_unique(
    build_keys_sorted: jnp.ndarray,
    build_rows: jnp.ndarray,
    build_live: jnp.ndarray,
    probe_key: Lowered,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Probe against a unique-key build. Returns (build_row_idx, matched)."""
    pvals, pvalid = probe_key
    n = build_keys_sorted.shape[0]
    pos = jnp.searchsorted(build_keys_sorted, pvals.astype(jnp.int64))
    pos = jnp.clip(pos, 0, n - 1)
    hit = (build_keys_sorted[pos] == pvals.astype(jnp.int64)) & build_live[pos]
    if pvalid is not None:
        hit = hit & pvalid
    return build_rows[pos], hit


def membership(
    build_key: Lowered, build_sel: Optional[jnp.ndarray], probe_key: Lowered
) -> jnp.ndarray:
    """Semi-join membership test (build side may have duplicates)."""
    bk_sorted, _, live = build_side(build_key, build_sel)
    pvals, pvalid = probe_key
    n = bk_sorted.shape[0]
    pos = jnp.clip(jnp.searchsorted(bk_sorted, pvals.astype(jnp.int64)), 0, n - 1)
    hit = (bk_sorted[pos] == pvals.astype(jnp.int64)) & live[pos]
    if pvalid is not None:
        hit = hit & pvalid
    return hit


def gather_column(col: Lowered, rows: jnp.ndarray, matched: jnp.ndarray) -> Lowered:
    """Gather a build column to probe positions; unmatched rows become NULL
    (consumed by inner-join sel or left-join null masks)."""
    vals, valid = col
    n = vals.shape[0]
    safe = jnp.clip(rows, 0, n - 1)
    v = vals[safe]
    va = matched if valid is None else (valid[safe] & matched)
    return v, va
