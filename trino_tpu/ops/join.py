"""Join kernels: lookup (N:1), M:N expansion, semi/anti — sort-merge based.

Reference: ``operator/join/`` — PagesHash open addressing + PositionLinks
chains (JoinHash.java:28-69). TPU formulation: the build side is sorted by
key once (one fused multi-operand ``lax.sort``); probe ranges come from
merge ranks (ops/ranks.py: one combined stable sort + streaming prefixes —
binary search and its log2(n) random-gather passes never appear):

- unique-key build (PK-FK joins, N:1): probe -> at most one match -> output
  size == probe size (static shapes, no two-pass emit). The planner proves
  uniqueness (primary keys / group-by outputs) before choosing this kernel.
- general M:N join: two-pass count+emit (``probe_counts`` + ``expand``) —
  the role of PositionLinks chain-following (JoinHash.java:28-69), done as
  one vectorized gather into a *static-capacity* output (capacity from the
  executor's shape-hint mechanism; exceeding it raises a deferred error and
  triggers a bucketed recompile).
- semi/anti joins: membership only (duplicates on build side are fine).

Composite keys of any column count and full int64 range are supported (the
lex sort and merge ranks compare all columns; no bit packing). The reference
hashes arbitrary-width keys the same way (InterpretedHashGenerator.java:85).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax.numpy as jnp

from trino_tpu.ops import ranks

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]

_DEAD_KEY = jnp.int64(2**63 - 1)  # sorts last; equality re-checked via live mask


@dataclasses.dataclass
class SortedBuild:
    """Build side sorted lexicographically by key, dead rows last.

    ``cols`` are the search columns in sorted order, most significant first.
    Single-key builds carry one sentinel-masked column (fast path); multi-key
    builds carry a leading dead-flag column (0 live / 1 dead) so dead rows
    can never equal a probe (whose flag is implicitly 0).
    """

    cols: List[jnp.ndarray]
    rows: jnp.ndarray  # original row index per sorted slot
    live: jnp.ndarray  # bool per sorted slot
    single: bool  # True -> cols == [sentinel-masked key], no flag column

    @property
    def n(self) -> int:
        return self.rows.shape[0]


def _live_mask(keys: List[Lowered], sel: Optional[jnp.ndarray]) -> jnp.ndarray:
    n = keys[0][0].shape[0]
    live = jnp.ones((n,), dtype=bool)
    if sel is not None:
        live = live & sel
    for _, valid in keys:
        if valid is not None:
            live = live & valid
    return live


def build_side(keys: List[Lowered], sel: Optional[jnp.ndarray]) -> SortedBuild:
    """Sort the build side by composite key; dead/null rows sort last and can
    never match (single-key: sentinel; multi-key: leading dead-flag column)."""
    live = _live_mask(keys, sel)
    if len(keys) == 1:
        vals = keys[0][0].astype(jnp.int64)
        k = jnp.where(live, vals, _DEAD_KEY)
        order = ranks.argsort32(k)
        return SortedBuild([k[order]], order, live[order], True)
    dead = (~live).astype(jnp.int8)
    masked = [jnp.where(live, v.astype(jnp.int64), 0) for v, _ in keys]
    sort_keys = [dead] + masked
    order = ranks.lex_argsort32(sort_keys)
    return SortedBuild(
        [k[order] for k in sort_keys], order, live[order], False
    )


def _probe_cols(build: SortedBuild, probe_keys: List[Lowered]) -> List[jnp.ndarray]:
    """Probe-side search columns aligned with ``build.cols``."""
    if build.single:
        return [probe_keys[0][0].astype(jnp.int64)]
    m = probe_keys[0][0].shape[0]
    return [jnp.zeros((m,), jnp.int8)] + [v.astype(jnp.int64) for v, _ in probe_keys]


def probe_valid(probe_keys: List[Lowered]) -> Optional[jnp.ndarray]:
    """AND of per-column probe validity (NULL keys never match)."""
    valid = None
    for _, v in probe_keys:
        if v is not None:
            valid = v if valid is None else (valid & v)
    return valid


def probe_unique(
    build: SortedBuild, probe_keys: List[Lowered]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Probe against a unique-key build. Returns (build_row_idx, matched)."""
    lo, counts = probe_counts(build, probe_keys, None)
    pos = jnp.clip(lo, 0, build.n - 1)
    return build.rows[pos], counts > 0


def membership(
    build_keys: List[Lowered],
    build_sel: Optional[jnp.ndarray],
    probe_keys: List[Lowered],
) -> jnp.ndarray:
    """Semi-join membership test (build side may have duplicates)."""
    build = build_side(build_keys, build_sel)
    _, counts = probe_counts(build, probe_keys, None)
    return counts > 0


def probe_counts(
    build: SortedBuild,
    probe_keys: List[Lowered],
    probe_sel: Optional[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pass 1 of the M:N join: per probe row, the sorted-build range start
    and match count (merge ranks, ops/ranks.py — no binary search). Dead
    probe rows (sel/NULL key) count 0."""
    probe = _probe_cols(build, probe_keys)
    lo, counts = ranks.sorted_ranks(build.cols, probe)
    # ranges of a real key contain only live rows (dead rows sort last with
    # unmatchable key) but guard the all-dead-build edge anyway
    counts = jnp.where(build.live[jnp.clip(lo, 0, build.n - 1)], counts, 0)
    pvalid = probe_valid(probe_keys)
    if pvalid is not None:
        counts = jnp.where(pvalid, counts, 0)
    if probe_sel is not None:
        counts = jnp.where(probe_sel, counts, 0)
    return lo, counts


def expand(
    counts: jnp.ndarray, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pass 2: map output slot j -> (probe_row, within-range offset).

    Returns (probe_row[cap], offset_in_range[cap], live[cap], total).
    Output is probe-major (all matches of probe row 0, then row 1, ...).
    """
    n = counts.shape[0]
    c64 = counts.astype(jnp.int64)  # cumsum in int64: totals can exceed 2^31
    if n == 0:  # zero-row probe page: all output slots dead
        z = jnp.zeros((capacity,), jnp.int64)
        return z, z, jnp.zeros((capacity,), bool), jnp.zeros((), jnp.int64)
    offsets = jnp.cumsum(c64)  # inclusive
    total = offsets[n - 1]
    starts = offsets - c64
    j = jnp.arange(capacity, dtype=jnp.int64)
    # both sides sorted -> merge ranks, not binary search
    p = jnp.clip(ranks.ranks_sorted_queries(offsets, j, side="right"), 0, n - 1)
    k = j - starts[p]
    live = j < total
    return p, k, live, total


def gather_column(col: Lowered, rows: jnp.ndarray, matched: jnp.ndarray) -> Lowered:
    """Gather a build column to probe positions; unmatched rows become NULL
    (consumed by inner-join sel or left-join null masks)."""
    vals, valid = col
    n = vals.shape[0]
    safe = jnp.clip(rows, 0, n - 1)
    v = vals[safe]
    va = matched if valid is None else (valid[safe] & matched)
    return v, va
