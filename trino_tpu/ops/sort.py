"""Sort kernel: stable multi-key argsort with SQL null placement.

Reference: ``operator/OrderByOperator.java`` + ``sql/gen/OrderingCompiler``
(type-specialized comparators). Here: per-key transform to a sortable int64/
float array (descending = negation, NULLs = rank-prefix keys per
nulls_first), then ONE fused multi-operand stable ``lax.sort`` with an int32
payload (ops/ranks.lex_argsort32). Dead rows (selection mask false) always
sort last so LIMIT/host slicing sees live rows first.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

from trino_tpu.ops import ranks

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]


def _sort_key(vals, valid, ascending: bool, nulls_first: Optional[bool]):
    """Produce (null_rank_key, value_key) so NULLs land per SQL defaults:
    NULLS LAST for ASC, NULLS FIRST for DESC, unless specified.

    Keys keep their PHYSICAL dtype (data/page.py Column): int32-narrowed
    keys sort ~2x faster than emulated int64 on TPU. Descending integers
    reverse via bitwise NOT (~v = -v-1: order-reversing for the full dtype
    range, no INT_MIN negation overflow)."""
    if nulls_first is None:
        nulls_first = not ascending
    v = vals
    if v.dtype == jnp.bool_:
        v = v.astype(jnp.int8)
    if not ascending:
        v = -v if jnp.issubdtype(v.dtype, jnp.floating) else ~v
    if valid is None:
        return [v]
    null_rank = valid.astype(jnp.int8) if nulls_first else (~valid).astype(jnp.int8)
    return [null_rank, jnp.where(valid, v, jnp.zeros((), v.dtype))]


def _sort_operands(
    keys: List[Tuple[Lowered, bool, Optional[bool]]],
    sel: Optional[jnp.ndarray],
) -> List[jnp.ndarray]:
    sort_keys: List[jnp.ndarray] = []
    if sel is not None:
        sort_keys.append(~sel)  # dead rows last
    for (vals, valid), asc, nf in keys:
        sort_keys.extend(_sort_key(vals, valid, asc, nf))
    return sort_keys


def sort_payloads(
    keys: List[Tuple[Lowered, bool, Optional[bool]]],
    sel: Optional[jnp.ndarray],
    payloads: List[jnp.ndarray],
) -> List[jnp.ndarray]:
    """Every payload array permuted into sort order (dead rows last) by ONE
    payload-carrying ``lax.sort`` — computed-permutation gathers don't fuse
    and cost ~40 ms per 6M-row column on v5e, ~10x a sort operand's
    marginal cost."""
    import jax

    sort_keys = _sort_operands(keys, sel)
    if not sort_keys:
        return list(payloads)
    out = jax.lax.sort(
        tuple(sort_keys) + tuple(payloads), num_keys=len(sort_keys), is_stable=True
    )
    return list(out[len(sort_keys):])
