"""HyperLogLog approx_distinct — scatter-free grouped sketch estimation.

Reference: ``operator/aggregation/ApproximateCountDistinctAggregation`` over
airlift's HyperLogLog (m = 2048 registers, ~2.3% standard error — the
reference's default). TPU redesign: instead of materializing per-group
register arrays (a [groups, 2048] scatter-max), rows regroup by
(group, bucket) with the same sorted machinery the engine uses everywhere:

1. per row: h = mix64(x); bucket = low 11 bits; rho = 1 + clz of the
   remaining 53 bits (capped);
2. group rows by (outer group id, bucket) — one fused sort;
3. register value = max(rho) per (group, bucket) pair (segmented max);
4. per outer group, two monotonic segment sums over the pair rows give
   sum(2^-register) and the count of PRESENT buckets; absent buckets
   contribute 2^0 each, so the harmonic denominator completes as
   sum_present + (m - present);
5. alpha_m * m^2 / denominator, with the standard small-range linear
   counting correction (E <= 2.5m -> m * ln(m / V)).

No scatter appears; the cost profile is one extra (gid, bucket) sort —
the sketch semantics of the reference at sorted-segment prices.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.ops import segments as seg

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]

LOG2_M = 11
M = 1 << LOG2_M  # 2048 registers -> ~1.04/sqrt(m) = 2.3% standard error
_ALPHA = 0.7213 / (1.0 + 1.079 / M)  # alpha_m for m >= 128

# numpy scalars to stay concrete if first imported under a trace
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = (x ^ (x >> 30)) * _M1
    x = (x ^ (x >> 27)) * _M2
    return x ^ (x >> 31)


def _rho(w: jnp.ndarray, width: int) -> jnp.ndarray:
    """1 + count of leading zeros of ``w`` within ``width`` bits (capped at
    width + 1 when w == 0) — the HLL register value."""
    # clz via bit-length: floor(log2(w)) through float conversion is unsafe
    # for 53-bit ints; use a shift cascade (6 steps for 64-bit)
    n = jnp.zeros_like(w, dtype=jnp.int32)
    x = w
    for shift in (32, 16, 8, 4, 2, 1):
        mask = x >= (jnp.uint64(1) << shift)
        n = jnp.where(mask, n + shift, n)
        x = jnp.where(mask, x >> shift, x)
    bit_length = jnp.where(w == 0, 0, n + 1)
    return (width - bit_length + 1).astype(jnp.int32)


def approx_distinct(layout: seg.GroupLayout, arg: Lowered, sel) -> Tuple[jnp.ndarray, None]:
    """Per-group HLL estimate (int64). ``arg``/``sel`` are in ORIGINAL row
    order (this re-groups, like agg_count_distinct)."""
    from trino_tpu.ops import groupby as gb

    vals, valid = arg
    n = vals.shape[0]
    live = sel if sel is not None else jnp.ones((n,), bool)
    if valid is not None:
        live = live & valid
    if jnp.issubdtype(vals.dtype, jnp.floating):
        # BIT-cast floats (a value cast to int64 would collapse distinct
        # fractional values onto the same integer)
        f64 = vals.astype(jnp.float64)
        key_bits = jax.lax.bitcast_convert_type(f64, jnp.int64)
    else:
        key_bits = vals.astype(jnp.int64)
    h = _mix64(key_bits.astype(jnp.uint64))
    bucket = (h & jnp.uint64(M - 1)).astype(jnp.int32)
    w = h >> LOG2_M
    rho = _rho(w, 64 - LOG2_M)

    outer = layout.gids_orig()
    order, gid_sorted, num_pairs, (rho_l,) = gb.group_plan(
        [(outer, None), (bucket, None)], live, payloads=[rho]
    )
    pairs = seg.sorted_layout(order, gid_sorted, num_pairs)
    # two DIFFERENT prefixes: live ROWS (dead rows sort last) vs live pair
    # SLOTS (distinct (group, bucket) pairs)
    n_live = jnp.sum(live).astype(jnp.int32)
    row_live = jnp.arange(n, dtype=jnp.int32) < n_live
    slot_live = jnp.arange(n, dtype=jnp.int32) < num_pairs.astype(jnp.int32)
    register = seg.seg_minmax(pairs, rho_l, row_live, is_min=False)
    register = jnp.where(slot_live, register, 0)
    # outer group id per pair slot (dead pairs past every real group)
    outer_of_pair = jnp.where(
        slot_live,
        outer[jnp.clip(pairs.rep, 0, n - 1)].astype(jnp.int32),
        jnp.int32(layout.capacity),
    )
    inv_pow = jnp.where(slot_live, jnp.exp2(-register.astype(jnp.float64)), 0.0)
    sum_present = seg.monotonic_segment_sum(inv_pow, outer_of_pair, layout.capacity)
    present = seg.monotonic_segment_sum(
        slot_live.astype(jnp.int64), outer_of_pair, layout.capacity
    )
    denom = sum_present + (M - present).astype(jnp.float64)
    raw = _ALPHA * M * M / jnp.maximum(denom, 1e-9)
    v_zero = (M - present).astype(jnp.float64)
    linear = M * jnp.log(jnp.maximum(M / jnp.maximum(v_zero, 1e-9), 1.0))
    est = jnp.where((raw <= 2.5 * M) & (v_zero > 0), linear, raw)
    out = jnp.round(est).astype(jnp.int64)
    return jnp.where(present > 0, out, 0), None


def approx_percentile(
    layout: seg.GroupLayout,
    vals_l: jnp.ndarray,
    m_l,
    p: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-group percentile by nearest rank over the grouped sort.

    Design note vs the reference (``ApproximateDoublePercentileAggregations``
    over tdigest): a streaming sketch exists to bound memory on
    row-at-a-time execution; under sorted-segment execution the engine can
    sort (group, value) outright — one fused lax.sort — and read the exact
    percentile, which is both cheaper here and strictly more accurate.
    ``vals_l``/``m_l`` are in layout space (group_structure payloads).
    """
    if jnp.issubdtype(vals_l.dtype, jnp.floating):
        sentinel = jnp.asarray(jnp.inf, vals_l.dtype)
    else:
        sentinel = jnp.asarray(jnp.iinfo(vals_l.dtype).max, vals_l.dtype)
    x = vals_l if m_l is None else jnp.where(m_l, vals_l, sentinel)
    if layout.is_direct:
        # direct layouts are tiny-capacity: sort by (gid, value) too
        gids = layout.gids
        _, x_by_group = jax.lax.sort((gids, x), num_keys=2)
        starts, cnt = _direct_ranges(layout, m_l)
    else:
        _, x_by_group = jax.lax.sort((layout.gid_sorted, x), num_keys=2)
        starts = layout.starts
        cnt = seg.seg_count(layout, m_l)
    nn = x_by_group.shape[0]
    rank = jnp.clip(
        jnp.ceil(p * cnt.astype(jnp.float64)).astype(jnp.int64) - 1, 0, None
    )
    pos = jnp.clip(starts.astype(jnp.int64) + rank, 0, nn - 1)
    out = x_by_group[pos]
    return out, cnt > 0


# Mergeable quantile-summary width: 64 rank intervals -> worst-case rank
# error ~1/(2*64) < 1% after merging (reference role: the mergeable
# t-digest/qdigest of ApproximatePercentileAggregations — here an
# equal-rank sample summary, the natural fixed-shape formulation).
QUANTILE_SAMPLES = 65


def percentile_states(layout: seg.GroupLayout, vals_l, m_l):
    """Partial approx_percentile state: per group, QUANTILE_SAMPLES local
    values at evenly spaced ranks + the live count. All static shapes: one
    (gid, value) sort + one [capacity, SAMPLES] bounded gather."""
    if jnp.issubdtype(vals_l.dtype, jnp.floating):
        sentinel = jnp.asarray(jnp.inf, vals_l.dtype)
    else:
        sentinel = jnp.asarray(jnp.iinfo(vals_l.dtype).max, vals_l.dtype)
    x = vals_l if m_l is None else jnp.where(m_l, vals_l, sentinel)
    if layout.is_direct:
        _, x_by_group = jax.lax.sort((layout.gids, x), num_keys=2)
        starts, cnt = _direct_ranges(layout, m_l)
    else:
        _, x_by_group = jax.lax.sort((layout.gid_sorted, x), num_keys=2)
        starts = layout.starts
        cnt = seg.seg_count(layout, m_l)
    nn = x_by_group.shape[0]
    j = jnp.arange(QUANTILE_SAMPLES, dtype=jnp.float64) / (QUANTILE_SAMPLES - 1)
    ranks = jnp.round(
        j[None, :] * jnp.maximum(cnt - 1, 0).astype(jnp.float64)[:, None]
    ).astype(jnp.int64)
    pos = jnp.clip(starts.astype(jnp.int64)[:, None] + ranks, 0, max(nn - 1, 0))
    samples = x_by_group[pos]  # [capacity, SAMPLES]
    live = cnt > 0
    out = [(samples[:, k], live) for k in range(QUANTILE_SAMPLES)]
    out.append((cnt, None))
    return out


def percentile_merge(layout: seg.GroupLayout, samples, cnt_state, p: float):
    """Final approx_percentile: weighted quantile over every shard's
    summary. Each partial row expands to its SAMPLES values weighted
    count/SAMPLES; one (gid, value) sort + a cumulative-weight rank pick
    per group slot. ``samples``/``cnt_state`` are layout-space payloads of
    the final grouping (small arrays: shards x groups rows)."""
    S = len(samples)
    cnt_l, _ = cnt_state
    n_l = cnt_l.shape[0]
    vals = jnp.stack([v for v, _ in samples], axis=1)  # [n_l, S]
    valid0 = samples[0][1]
    live_row = cnt_l > 0
    if valid0 is not None:
        live_row = live_row & valid0
    w_row = jnp.where(live_row, cnt_l.astype(jnp.float64) / S, 0.0)
    if layout.is_direct:
        gid_l = layout.gids
        starts_l, _cnt = _direct_ranges(layout, None)
        ends_l = starts_l.astype(jnp.int64) + seg.seg_count(layout, None)
    else:
        gid_l = layout.gid_sorted
        starts_l = layout.starts
        ends_l = layout.ends
    gid2 = jnp.repeat(gid_l, S)
    x2 = vals.reshape(-1)
    w2 = jnp.repeat(w_row, S)
    _, x_s, w_s = jax.lax.sort((gid2, x2, w2), num_keys=2, is_stable=True)
    c = jnp.cumsum(w_s)
    c0 = jnp.concatenate([jnp.zeros((1,), c.dtype), c])
    e_start = starts_l.astype(jnp.int64) * S
    e_end = ends_l.astype(jnp.int64) * S
    w_group = c0[e_end] - c0[e_start]
    # lower weighted percentile: first sample whose cumulative weight
    # reaches p * W (reduces to the nearest-rank pick for equal weights)
    target = c0[e_start] + p * w_group
    pos = jnp.searchsorted(c, target, side="left")
    pos = jnp.clip(pos, e_start, jnp.maximum(e_end - 1, e_start))
    out = x_s[jnp.clip(pos, 0, max(x_s.shape[0] - 1, 0))]
    return out, w_group > 0


def _direct_ranges(layout: seg.GroupLayout, m_l):
    """(starts, live counts) per slot for a direct layout, derived from the
    per-slot counts (rows sort group-contiguous by gid)."""
    cnt_all = seg.seg_count(layout, None)  # rows per slot including masked
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64), jnp.cumsum(cnt_all)[:-1]]
    ).astype(jnp.int32)
    cnt = seg.seg_count(layout, m_l)
    return starts, cnt
