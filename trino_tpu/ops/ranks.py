"""Merge-based rank computation: the join-probe / segment-boundary substrate.

Reference role: the probe half of ``operator/join/`` (JoinProbe over
PagesHash) and the group-boundary lookups of FlatHash. The natural TPU
formulation of "find each query key's range in a sorted build" is NOT a
per-query binary search: ``jnp.searchsorted`` lowers to ~log2(n) dependent
random-gather passes over the whole query vector (measured 2.5 s for 6M
int64 probes into 1.5M keys on v5e — the round-1 engine's dominant cost).

Instead, ranks are computed by ONE combined stable sort (lax.sort is a fast
TPU radix/merge network: 6M int64 keys ≈ 27 ms) of build keys and query keys
tagged 0/1, followed by streaming prefix ops:

- at a query slot, every build key <= it sorts before it (builds win ties),
  so the inclusive build-count prefix IS the query's right rank
  (searchsorted side='right');
- the left rank is the build-count prefix at the start of the equal-key run,
  propagated across the run by a running max (prefixes are non-decreasing);
- results return to query order through the sort's inverted permutation
  (one int32 argsort + gather).

Everything index-typed is int32 (int64 gathers cost 3.7x on v5e).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp


def _iota32(n: int) -> jnp.ndarray:
    return jnp.arange(n, dtype=jnp.int32)


def argsort32(vals: jnp.ndarray) -> jnp.ndarray:
    """Stable argsort returning int32 indices. Under x64, jnp.argsort carries
    int64 iota through the sort and produces int64 indices — int64 payloads
    slow the sort and every downstream gather runs 3.7x slower on v5e."""
    n = vals.shape[0]
    _, perm = jax.lax.sort((vals, _iota32(n)), num_keys=1, is_stable=True)
    return perm


def lex_argsort32(sort_keys: List[jnp.ndarray]) -> jnp.ndarray:
    """Stable lexicographic argsort (most significant first), int32 indices,
    one fused multi-operand sort (no per-key argsort chain)."""
    n = sort_keys[0].shape[0]
    out = jax.lax.sort(
        tuple(sort_keys) + (_iota32(n),), num_keys=len(sort_keys), is_stable=True
    )
    return out[-1]


def batched_gather(arrays: List[jnp.ndarray], idx: jnp.ndarray) -> List[jnp.ndarray]:
    """Gather many same-length arrays at the same indices in ONE random-HBM
    pass per dtype group. Separate gathers do not fuse when the index is
    computed (each costs ~40 ms per 6M rows on v5e); a [n, k] row-gather
    moves k columns for about the price of one."""
    if len(arrays) <= 1:
        return [a[idx] for a in arrays]
    groups: dict = {}
    for i, a in enumerate(arrays):
        groups.setdefault(a.dtype, []).append(i)
    out: List = [None] * len(arrays)
    for _, idxs in groups.items():
        if len(idxs) == 1:
            i = idxs[0]
            out[i] = arrays[i][idx]
        else:
            m = jnp.stack([arrays[i] for i in idxs], axis=1)
            g = m[idx]
            for j, i in enumerate(idxs):
                out[i] = g[:, j]
    return out


def apply_inverse(perm: jnp.ndarray, payloads: List[jnp.ndarray]) -> List[jnp.ndarray]:
    """Return each payload re-ordered so slot perm[i] moves to slot i —
    i.e. payload[inverse_permutation(perm)] — via ONE payload-carrying sort
    (sort by perm). Replaces an inverse-permutation sort plus one random
    gather per payload."""
    out = jax.lax.sort(
        (perm.astype(jnp.int32),) + tuple(payloads), num_keys=1, is_stable=True
    )
    return list(out[1:])


def inverse_permutation(perm: jnp.ndarray) -> jnp.ndarray:
    """inv[perm[i]] = i, scatter-free (one int32 sort)."""
    n = perm.shape[0]
    _, inv = jax.lax.sort(
        (perm.astype(jnp.int32), _iota32(n)), num_keys=1, is_stable=True
    )
    return inv


def sorted_ranks(
    build_cols_sorted: List[jnp.ndarray],
    query_cols: List[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per query row: (left_rank, match_count) against the lex-sorted build.

    ``left_rank`` = number of build tuples strictly less than the query
    (== searchsorted side='left'); ``match_count`` = number equal. Both
    int32, in original query order. Build columns must already be sorted
    lexicographically (most significant first); query columns are unordered.
    """
    nb = build_cols_sorted[0].shape[0]
    nq = query_cols[0].shape[0]
    n = nb + nq
    # combined STABLE sort with builds concatenated first: equal keys keep
    # builds before queries (no tag operand needed), payload = combined index
    operands = [
        jnp.concatenate([b, q]) if b.dtype == q.dtype
        else jnp.concatenate([
            b.astype(jnp.promote_types(b.dtype, q.dtype)),
            q.astype(jnp.promote_types(b.dtype, q.dtype)),
        ])
        for b, q in zip(build_cols_sorted, query_cols)
    ]
    out = jax.lax.sort(
        tuple(operands) + (_iota32(n),), num_keys=len(operands), is_stable=True
    )
    sorted_cols = out[: len(operands)]
    idx_s = out[-1]
    is_build = (idx_s < nb).astype(jnp.int32)
    prefix_incl = jnp.cumsum(is_build, dtype=jnp.int32)
    prefix_excl = prefix_incl - is_build
    # equal-key run starts
    neq = jnp.zeros((max(n - 1, 0),), bool)
    for c in sorted_cols:
        neq = neq | (c[1:] != c[:-1])
    run_start = jnp.concatenate([jnp.ones((1,), bool), neq])
    # left rank for every slot of a run = build prefix at run start;
    # propagate by running max (prefixes are non-decreasing across runs)
    left_at_start = jnp.where(run_start, prefix_excl, jnp.int32(-1))
    # lax.cummax, NOT associative_scan: the latter's unrolled log-depth graph
    # does not compile at multi-million rows on v5e
    left_all = jax.lax.cummax(left_at_start)
    right_all = prefix_incl  # at query slots: builds <= query
    # back to query order (query i sits at combined index nb + i): ONE
    # payload-carrying sort by idx_s, instead of inverse_permutation plus
    # two random gathers (~40 ms each per 6M rows on v5e)
    left_o, right_o = apply_inverse(idx_s, [left_all, right_all])
    lo = left_o[nb:]
    counts = right_o[nb:] - lo
    return lo, counts


def ranks_sorted_queries(
    sorted_vals: jnp.ndarray, queries_sorted: jnp.ndarray, side: str
) -> jnp.ndarray:
    """searchsorted(sorted_vals, queries_sorted, side) when BOTH arrays are
    sorted — same combined-sort machinery, one call."""
    lo, counts = sorted_ranks([sorted_vals], [queries_sorted])
    return lo if side == "left" else lo + counts
