"""Group-by kernel: sort/segment based, static shapes, scatter-free.

Reference algorithm being replaced: ``operator/FlatHash.java:42`` (SWAR
control-byte open addressing) + ``FlatHashStrategyCompiler``. On TPU, a
sort + segment formulation maps better onto the VPU than scatter-heavy
hashing (SURVEY.md §7.1): stable multi-key argsort, boundary detection,
dense group ids via cumsum. Exact (comparison-based, no hash collisions),
null-safe (NULL is its own group), and selection-mask aware (dead rows sort
last, into trailing groups past ``num_groups``).

All downstream consumption happens in *sorted space* through
ops/segments.GroupLayout — integer scatters never appear (measured ~50x
slower than streaming ops on v5e; see ops/segments.py).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]  # (vals, valid|None)


def group_plan(
    keys: List[Lowered], sel: Optional[jnp.ndarray], payloads=()
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, List[jnp.ndarray]]:
    """Permute rows group-contiguous and assign dense group ids.

    Returns (order[n] int32, gid_sorted[n] int32 non-decreasing,
    num_groups scalar, sorted_payloads). Dead rows (sel false) sort last
    and receive group ids >= num_groups; NULL keys group together (their
    own group). ``payloads`` ride the same fused sort as extra operands
    and come back permuted into sorted (layout) space — the free way to
    get aggregate arguments group-contiguous (see segments.seg_sum).

    The sorted key columns come straight out of the one fused ``lax.sort``
    (operands sort together) — re-gathering them by the permutation would
    cost ~40 ms per 6M-row column of random HBM access on v5e, ~10x the
    marginal cost of a sort operand."""
    n = keys[0][0].shape[0]
    dead = jnp.zeros((n,), dtype=bool) if sel is None else ~sel
    sort_keys: List[jnp.ndarray] = [dead]
    for vals, valid in keys:
        if valid is not None:
            sort_keys.append(~valid)
            sort_keys.append(jnp.where(valid, vals, jnp.zeros((), vals.dtype)))
        else:
            sort_keys.append(vals)
    iota = jnp.arange(n, dtype=jnp.int32)
    nk = len(sort_keys)
    out = jax.lax.sort(
        tuple(sort_keys) + (iota,) + tuple(payloads), num_keys=nk, is_stable=True
    )
    gathered = out[:nk]
    order = out[nk]
    sorted_payloads = list(out[nk + 1:])
    boundary = jnp.zeros((n,), dtype=bool)
    for g in gathered:
        boundary = boundary | jnp.concatenate([jnp.ones((1,), bool), g[1:] != g[:-1]])
    gid_sorted = (jnp.cumsum(boundary.astype(jnp.int32)) - 1).astype(jnp.int32)
    dead_sorted = gathered[0]
    num_groups = jnp.sum(boundary & ~dead_sorted)
    return order, gid_sorted, num_groups, sorted_payloads


def gather_group_keys(keys: List[Lowered], rep: jnp.ndarray) -> List[Lowered]:
    """Group-key output columns: gather each key at the representative row
    (rep indexes original row order; empty slots carry rep == n, clipped).
    One batched HBM pass for all keys (ranks.batched_gather)."""
    from trino_tpu.ops import ranks

    n = keys[0][0].shape[0]
    safe = jnp.clip(rep, 0, n - 1)
    arrays = [vals for vals, _ in keys] + [
        valid for _, valid in keys if valid is not None
    ]
    gathered = ranks.batched_gather(arrays, safe)
    out = []
    vi = len(keys)
    for i, (_, valid) in enumerate(keys):
        if valid is None:
            out.append((gathered[i], None))
        else:
            out.append((gathered[i], gathered[vi]))
            vi += 1
    return out
