"""Group-by kernel: sort/segment based, static shapes.

Reference algorithm being replaced: ``operator/FlatHash.java:42`` (SWAR
control-byte open addressing) + ``FlatHashStrategyCompiler``. On TPU, a
sort + segment-reduce formulation maps better onto the VPU than scatter-heavy
hashing (SURVEY.md §7.1): stable multi-key argsort, boundary detection,
dense group ids via cumsum, then ``jax.ops.segment_*`` reductions. Exact
(comparison-based, no hash collisions), null-safe (NULL is its own group),
and selection-mask aware (dead rows sort last, into discarded groups).

All shapes are static; the true group count comes back as a scalar the host
reads once per aggregation to slice the padded outputs.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

Lowered = Tuple[jnp.ndarray, Optional[jnp.ndarray]]  # (vals, valid|None)


def _sort_order(sort_keys: List[jnp.ndarray]) -> jnp.ndarray:
    """Stable lexicographic argsort over multiple key arrays (most significant
    first): chain stable argsorts from least to most significant."""
    n = sort_keys[0].shape[0]
    order = jnp.arange(n)
    for k in reversed(sort_keys):
        order = order[jnp.argsort(k[order], stable=True)]
    return order


def group_ids(
    keys: List[Lowered], sel: Optional[jnp.ndarray]
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Assign dense group ids per row.

    Returns (gids[n] int32, rep[n] int64 — representative row per group id
    (padded with n beyond the live groups), num_groups scalar).
    Dead rows (sel false) get group ids >= num_groups.
    """
    n = keys[0][0].shape[0]
    dead = (
        jnp.zeros((n,), dtype=bool) if sel is None else ~sel
    )
    sort_keys: List[jnp.ndarray] = [dead]
    for vals, valid in keys:
        if valid is not None:
            sort_keys.append(~valid)  # NULLs group together (their own group)
            sort_keys.append(jnp.where(valid, vals, 0))
        else:
            sort_keys.append(vals)
    order = _sort_order(sort_keys)
    gathered = [k[order] for k in sort_keys]
    boundary = jnp.zeros((n,), dtype=bool).at[0].set(True)
    for g in gathered:
        boundary = boundary | jnp.concatenate([jnp.ones((1,), bool), g[1:] != g[:-1]])
    gid_sorted = jnp.cumsum(boundary) - 1
    dead_sorted = gathered[0]
    num_groups = jnp.sum(boundary & ~dead_sorted)
    gids = jnp.zeros((n,), dtype=jnp.int64).at[order].set(gid_sorted)
    rep = jnp.full((n,), n, dtype=jnp.int64).at[gid_sorted].min(order)
    return gids.astype(jnp.int32), rep, num_groups


def gather_group_keys(
    keys: List[Lowered], rep: jnp.ndarray
) -> List[Lowered]:
    """Group-key output columns: gather each key at the representative row."""
    n = keys[0][0].shape[0]
    safe = jnp.clip(rep, 0, n - 1)
    out = []
    for vals, valid in keys:
        v = vals[safe]
        va = valid[safe] if valid is not None else None
        out.append((v, va))
    return out
