"""Vectorized civil-calendar math on epoch-day arrays.

Reference: Trino's date/time scalar functions
(``core/trino-main/.../operator/scalar/DateTimeFunctions.java``) delegate to
java.time; on TPU we need branch-free integer arithmetic. Uses the
days<->civil algorithms from Howard Hinnant's public-domain date algorithms
(the same math java.time uses), fully vectorizable on the VPU.
"""
from __future__ import annotations

import jax.numpy as jnp


def civil_from_days(days):
    """epoch days -> (year, month, day), elementwise (int32 arrays)."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365  # [0, 399]
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)  # [0, 365]
    mp = (5 * doy + 2) // 153  # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1  # [1, 31]
    m = mp + jnp.where(mp < 10, 3, -9)  # [1, 12]
    y = y + (m <= 2)
    return y.astype(jnp.int64), m.astype(jnp.int64), d.astype(jnp.int64)


def days_from_civil(y, m, d):
    """(year, month, day) -> epoch days, elementwise."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400  # [0, 399]
    mp = m + jnp.where(m > 2, -3, 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def extract_year(days):
    return civil_from_days(days)[0]


def extract_month(days):
    return civil_from_days(days)[1]


def extract_day(days):
    return civil_from_days(days)[2]


def extract_quarter(days):
    return (civil_from_days(days)[1] - 1) // 3 + 1


def add_months(days, n):
    """date + INTERVAL n MONTH with end-of-month clamping (SQL semantics)."""
    y, m, d = civil_from_days(days)
    m0 = m - 1 + n
    y2 = y + jnp.floor_divide(m0, 12)
    m2 = jnp.mod(m0, 12) + 1
    d2 = jnp.minimum(d, days_in_month(y2, m2))
    return days_from_civil(y2, m2, d2)


def extract_dow(days):
    """ISO day-of-week, Monday=1..Sunday=7 (reference:
    DateTimeFunctions.dayOfWeekFromDate). 1970-01-01 was a Thursday."""
    return jnp.mod(days.astype(jnp.int64) + 3, 7) + 1


def extract_doy(days):
    """Day of year, 1-based."""
    y, _, _ = civil_from_days(days)
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return days.astype(jnp.int64) - jan1 + 1


def extract_week(days):
    """ISO-8601 week number (reference: DateTimeFunctions.weekFromDate):
    week 1 contains the year's first Thursday."""
    d = days.astype(jnp.int64)
    thursday = d - extract_dow(d) + 4  # Thursday of this ISO week
    y, _, _ = civil_from_days(thursday)
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return (thursday - jan1) // 7 + 1


def trunc_date(days, unit: str):
    """date_trunc(unit, date) -> epoch days (reference:
    DateTimeFunctions.truncateDate)."""
    d = days.astype(jnp.int64)
    if unit == "day":
        return d
    if unit == "week":  # ISO week start (Monday)
        return d - (extract_dow(d) - 1)
    y, m, _dd = civil_from_days(d)
    one = jnp.ones_like(y)
    if unit == "month":
        return days_from_civil(y, m, one)
    if unit == "quarter":
        return days_from_civil(y, ((m - 1) // 3) * 3 + 1, one)
    if unit == "year":
        return days_from_civil(y, one, one)
    raise NotImplementedError(f"date_trunc unit: {unit}")


def days_in_month(y, m):
    lengths = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31], dtype=jnp.int64)
    base = lengths[m - 1]
    leap = ((jnp.mod(y, 4) == 0) & (jnp.mod(y, 100) != 0)) | (jnp.mod(y, 400) == 0)
    return base + ((m == 2) & leap)
